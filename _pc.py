import sys, time
import numpy as np, jax, jax.numpy as jnp
from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.engine.state import EngineConfig, init_engine
from grapevine_tpu.engine import vphases
from grapevine_tpu.engine.state import mb_bucket_hash
from grapevine_tpu.oram.round import oram_round
from grapevine_tpu.oblivious.primitives import is_zero_words
from bench import make_batches
U32 = jnp.uint32

bs = int(__import__("os").environ.get("BS","64"))
cfg = GrapevineConfig(max_messages=1 << 16, max_recipients=1 << 12, batch_size=bs, stash_size=224)
ecfg = EngineConfig.from_config(cfg)
state = init_engine(ecfg, seed=0)
batch = make_batches(1, bs)[0]
rt = jnp.asarray(batch["req_type"], U32)
is_create = rt == 1; is_read = rt == 2; is_update = rt == 3; is_delete = rt == 4
is_real = is_create | is_read | is_update | is_delete
msg_id = jnp.asarray(batch["msg_id"]); recipient = jnp.asarray(batch["recipient"])
auth = jnp.asarray(batch["auth"]); payload = jnp.asarray(batch["payload"])
id_zero = is_zero_words(msg_id); zero_recip = is_zero_words(recipient)
ka = jnp.where((is_create | ~id_zero)[:, None], recipient, auth)
bucket = jax.vmap(lambda k: mb_bucket_hash(state.hash_key, k, ecfg.mb_table_buckets))(ka)
idxs_mb = jnp.where(is_real, bucket, U32(ecfg.mb.dummy_index))
ks = jnp.arange(bs, dtype=U32)
cand_idx = state.freelist[jnp.where(ks < state.free_top, state.free_top - 1 - ks, 0)]
ctx = dict(is_real=is_real, is_create=is_create, is_read=is_read, is_update=is_update,
           is_delete=is_delete, id_zero=id_zero, zero_recip=zero_recip, ka=ka,
           idxs_mb=idxs_mb, cand_idx=cand_idx,
           id_rand=jnp.zeros((bs, 3), U32), free_top0=state.free_top,
           recipients0=state.recipients, seq0=state.seq, now=jnp.uint32(1),
           auth=auth, recipient=recipient, msg_id=msg_id, payload=payload)

which = sys.argv[1]
nl = jnp.zeros((bs,), U32); dl = jnp.ones((bs,), U32)

if which == "a":
    f = jax.jit(lambda st: oram_round(ecfg.mb, st, idxs_mb, nl, dl, vphases.phase_a_batch(ecfg, ctx)))
    t0 = time.perf_counter(); f.lower(state.mb).compile(); print("A compiled", time.perf_counter()-t0)
elif which == "b":
    ctx_b = {**ctx, "idx_b": jnp.where(is_real, ks % U32(ecfg.rec.leaves), U32(ecfg.rec.dummy_index)),
             "real_b": is_real, "create_ok": is_create, "new_id": jnp.zeros((bs,4),U32),
             "sel_blk": jnp.zeros((bs,),U32), "sel_idw": jnp.zeros((bs,),U32)}
    nlb = jnp.zeros((bs,), U32)
    f = jax.jit(lambda st: oram_round(ecfg.rec, st, ctx_b["idx_b"], nlb, nlb+1, vphases.phase_b_batch(ecfg, ctx_b)))
    t0 = time.perf_counter(); f.lower(state.rec).compile(); print("B compiled", time.perf_counter()-t0)
elif which == "c":
    ctx_c = {**ctx, "del_ok": is_delete, "upd_ok": is_update, "rm_a": jnp.zeros((bs,), bool)}
    f = jax.jit(lambda st: oram_round(ecfg.mb, st, idxs_mb, nl, dl, vphases.phase_c_batch(ecfg, ctx_c)))
    t0 = time.perf_counter(); f.lower(state.mb).compile(); print("C compiled", time.perf_counter()-t0)
