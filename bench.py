#!/usr/bin/env python3
"""The BASELINE benchmark configs, with p99 round latency.

Configs (BASELINE.md / BASELINE.json, plus two extensions):
  1. crd_loop            single-client create→read→delete loop, 2^16 bus
  2. batched_read        2048 concurrent explicit-id reads, 2^20 bus
  3. zipf_mixed          mixed CRUD, Zipf recipient keys, 62-cap stress
  3b. zipf_pallas_cipher the same workload through the fused Pallas
                         cipher kernel (TPU backends only)
  3c. zipf_pallas_fused  …plus the path fetch and write-back fused
                         into the cipher passes (Mosaic backends only)
  4. expiry_sweep        timestamped eviction scan, 2^22 at density 4
  4b. vphases_ab         dense vs scan slot-order machinery A/B —
                         B-sweep (64/256/1024) of per-op round cost,
                         interleaved (PR3; PERF.md Round 6)
  4c. sort_ab            xla vs radix bounded-key sort engine A/B —
                         eviction/dedup machinery + whole-round
                         B-sweep, interleaved (PR5; PERF.md Round 7)
  4d. posmap_ab          flat vs recursive position map A/B — lookup
                         machinery (B × capacity grid, with the
                         private/HBM memory split) + whole-round
                         B-sweep, interleaved (PR7; PERF.md Round 9)
  4e. tree_cache_ab      tree-top cache A/B — isolated ORAM-round
                         machinery (cap × B × k grid) + whole-round
                         k ∈ {0,2,4,auto} B-sweep, interleaved
                         (PR8; PERF.md Round 10)
  5. sharded             bucket-tree sharded over a device mesh (CPU
                         mesh subprocess when one chip is visible)
  6. server_loopback     full-stack gRPC: session crypto + batched
                         verification + pipelined scheduler + engine
                         (skipped, not errored, without `cryptography`)
  7. slo_loopback        scheduler loopback with the observability
                         stack on (round tracer + commit-latency SLO,
                         PR6): enqueue→settle latency, burn rates, and
                         the host/device bubble ratio — runs everywhere
                         (no session crypto in the loop)
  7b. pipeline_ab        round-pipeline depth A/B (PR10): depth 1
                         (serial) vs depth 2 (collection window +
                         journal fsync overlap the in-flight device
                         rounds) through the scheduler with fsync ON —
                         sustained throughput + commit p99 per depth,
                         min-of-N interleaved; runs everywhere
  8. load_scenarios      the workload observatory (PR9): open-loop
                         scenario suite (steady/bursty/diurnal/
                         pop-heavy/adversarial/ramp) through the
                         scheduler with workload telemetry + leakmon
                         on — per-scenario commit p50/p99/fill/depth,
                         adversarial-vs-honest /leakaudit verdicts,
                         and the ramp's measured saturation knee (the
                         banked capacity number) — runs everywhere
  9. fleet_loopback      the fleet observatory (PR16): TWO engines
                         behind a recipient-partitioned ramp replayed
                         concurrently (ShardedScenarioRunner) with a
                         live in-process FleetAggregator scraping both
                         registries on its fixed cadence — per-shard
                         knees, the folded fleet knee (geometry key
                         shard_count=2), merged-view liveness, and the
                         cross-shard uniformity verdict (must PASS:
                         the production scheduler is uniform) — runs
                         everywhere

stdout is ONE JSON line: the headline mixed-CRUD throughput at the
largest batched config, with every config's (ops/s, p99 round ms)
embedded under "configs". Per-config progress lines go to stderr.

``--smoke`` runs every config at toy sizes on whatever backend JAX
selects (CI uses the CPU backend) to assert the harness itself works.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

NOW = 1_700_000_000


def _p99(times_s: list[float]) -> float:
    return float(np.percentile(np.asarray(times_s) * 1e3, 99))


def _mk_engine(cap, recips, batch, stash=None, seed=0, density=2, cipher_impl="jnp",
               vphases_impl=None, cipher_rounds=8, mailbox_cap=None,
               sort_impl=None, posmap_impl=None, tree_top_cache=None,
               evict_every=None):
    import jax

    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.engine.round_step import engine_round_step
    from grapevine_tpu.engine.state import EngineConfig, init_engine

    extra = {} if mailbox_cap is None else {"mailbox_cap": mailbox_cap}
    cfg = GrapevineConfig(
        max_messages=cap,
        max_recipients=recips,
        batch_size=batch,
        stash_size=stash or max(128, batch // 2 + 96),
        tree_density=density,
        bucket_cipher_impl=cipher_impl,
        bucket_cipher_rounds=cipher_rounds,
        vphases_impl=vphases_impl,
        sort_impl=sort_impl,
        posmap_impl=posmap_impl,
        tree_top_cache_levels=tree_top_cache,
        evict_every=evict_every,
        **extra,
    )
    ecfg = EngineConfig.from_config(cfg)
    state = init_engine(ecfg, seed=seed)
    step = jax.jit(engine_round_step, static_argnums=(0,), donate_argnums=(1,))
    return cfg, ecfg, state, step


def _run_rounds(ecfg, state, step, batches, n_rounds):
    """Two measurements over the same round stream:

    - **throughput** from scan-fused rounds: the batch stream is staged
      on device once and ``lax.scan`` chains the rounds inside one jit,
      so the metric is the engine's own round rate — not the host's
      dispatch/transfer path, which the production scheduler overlaps
      with compute anyway (scheduler.py collects while the device runs);
    - **p99 latency** from individually dispatched, blocking rounds —
      the latency a serving round actually pays, host boundary included.
    """
    import jax
    import jax.numpy as jnp

    state, resp, _ = step(ecfg, state, batches[0])
    jax.block_until_ready(resp)  # warmup: compile + settle

    # p99 from per-dispatch rounds
    times = []
    for i in range(min(n_rounds, 32)):
        t0 = time.perf_counter()
        state, resp, _ = step(ecfg, state, batches[i % len(batches)])
        jax.block_until_ready(resp)
        times.append(time.perf_counter() - t0)

    # throughput from fused rounds: stack the batch stream, scan it
    from grapevine_tpu.engine.round_step import engine_round_step

    # rounds per dispatch: scan compiles its body once regardless of
    # length, so a longer chain costs no compile time and amortizes the
    # per-dispatch overhead further
    n_fused = max(16, len(batches))
    order = [i % len(batches) for i in range(n_fused)]
    stacked = {
        k: (jnp.stack([jnp.asarray(batches[i][k]) for i in order]) if k != "now"
            else jnp.asarray([batches[i]["now"] for i in order]))
        for k in batches[0]
    }
    stacked = jax.device_put(stacked)  # staged once, outside the timing

    def scan_rounds(state, xs):
        def body(st, batch):
            st2, resp, _ = engine_round_step(ecfg, st, batch)
            # responses stay on device; carry a cheap digest out so XLA
            # cannot elide any round's work
            return st2, resp["status"]
        return jax.lax.scan(body, state, xs)

    fused = jax.jit(scan_rounds, donate_argnums=(0,))
    state, statuses = fused(state, stacked)
    jax.block_until_ready(statuses)  # fused compile + settle
    n_loops = max(1, n_rounds // n_fused)
    t_all = time.perf_counter()
    for _ in range(n_loops):
        state, statuses = fused(state, stacked)
    jax.block_until_ready(statuses)
    total = time.perf_counter() - t_all
    rounds_run = n_loops * n_fused
    overflow = int(np.asarray(state.rec.overflow)) + int(np.asarray(state.mb.overflow))
    assert overflow == 0, f"stash overflow during bench: {overflow}"
    # scale `total` to what n_rounds rounds take, keeping callers' ops math
    return state, times, total * (n_rounds / rounds_run)


def _batch_arrays(reqs, ecfg):
    from grapevine_tpu.engine.state import ID_WORDS, KEY_WORDS, PAYLOAD_WORDS

    b = ecfg.batch_size
    out = {
        "req_type": np.zeros((b,), np.uint32),
        "auth": np.zeros((b, KEY_WORDS), np.uint32),
        "msg_id": np.zeros((b, ID_WORDS), np.uint32),
        "recipient": np.zeros((b, KEY_WORDS), np.uint32),
        "payload": np.zeros((b, PAYLOAD_WORDS), np.uint32),
        "now": np.uint32(NOW),
    }
    for i, (rt, auth, mid, rcp, pl) in enumerate(reqs):
        out["req_type"][i] = rt
        out["auth"][i] = auth
        out["msg_id"][i] = mid
        out["recipient"][i] = rcp
        out["payload"][i] = pl
    return out


def make_batches(n_batches: int, batch_size: int, seed: int = 7):
    """Create-heavy mixed CRUD batches (legacy helper, used by tests)."""
    from grapevine_tpu.engine.state import ID_WORDS, KEY_WORDS, PAYLOAD_WORDS

    rng = np.random.default_rng(seed)
    idents = rng.integers(1, 2**31, (64, KEY_WORDS)).astype(np.uint32)
    batches = []
    for _ in range(n_batches):
        b = batch_size
        rt = rng.choice(np.array([1, 1, 2, 2, 3, 4], np.uint32), size=b)
        auth = idents[rng.integers(0, len(idents), b)]
        recipient = idents[rng.integers(0, len(idents), b)]
        msg_id = np.zeros((b, ID_WORDS), np.uint32)
        explicit = rt == 3  # UPDATE needs nonzero id (grapevine.proto:95)
        msg_id[explicit] = rng.integers(1, 2**31, (int(explicit.sum()), ID_WORDS))
        batches.append(
            {
                "req_type": rt,
                "auth": auth,
                "msg_id": msg_id,
                "recipient": recipient,
                "payload": rng.integers(0, 2**31, (b, PAYLOAD_WORDS)).astype(np.uint32),
                "now": np.uint32(NOW),
            }
        )
    return batches


# ----------------------------------------------------------------------
# the five configs
# ----------------------------------------------------------------------


def bench_crd_loop(smoke):
    """Config 1: one client, create → zero-id read → zero-id delete."""
    # batch 64 (lane-aligned): 21 C-R-D triples + one padding dummy slot
    cap, batch, n_rounds = (1 << 10, 4, 4) if smoke else (1 << 16, 64, 32)
    cfg, ecfg, state, step = _mk_engine(cap, 1 << 8, batch)
    rng = np.random.default_rng(3)
    me = rng.integers(1, 2**31, (8,)).astype(np.uint32)
    pl = rng.integers(0, 2**31, (234,)).astype(np.uint32)
    zid = np.zeros((4,), np.uint32)
    # C,R,D triples in slot order — the per-batch form of the CRD loop
    reqs = []
    for _ in range(batch // 3):
        reqs += [(1, me, zid, me, pl), (2, me, zid, np.zeros(8, np.uint32), pl),
                 (4, me, zid, np.zeros(8, np.uint32), pl)]
    batches = [_batch_arrays(reqs, ecfg)]
    _, times, total = _run_rounds(ecfg, state, step, batches, n_rounds)
    ops = len(reqs) * n_rounds
    return {"ops_per_sec": round(ops / total, 1), "p99_round_ms": round(_p99(times), 2),
            "batch": batch, "capacity_log2": cap.bit_length() - 1}


def bench_batched_read(smoke):
    """Config 2: B concurrent explicit-id reads at 2^20."""
    cap, batch, n_rounds = (1 << 10, 8, 4) if smoke else (1 << 20, 2048, 12)
    cfg, ecfg, state, step = _mk_engine(cap, 1 << 12, batch)
    rng = np.random.default_rng(5)
    n_live = batch
    idents = rng.integers(1, 2**31, (64, 8)).astype(np.uint32)
    # populate with creates, keeping ids from the responses
    creates = [(1, idents[i % 64], np.zeros(4, np.uint32), idents[(i + 1) % 64],
                rng.integers(0, 2**31, (234,)).astype(np.uint32)) for i in range(n_live)]
    import jax
    ids = []
    for i in range(0, n_live, batch):
        b = _batch_arrays(creates[i : i + batch], ecfg)
        state, resp, _ = step(ecfg, state, b)
        ids.append(np.asarray(resp["msg_id"]))
    jax.block_until_ready(state)
    all_ids = np.concatenate(ids)[:n_live]
    reads = [(2, creates[i][3], all_ids[i], np.zeros(8, np.uint32),
              np.zeros(234, np.uint32)) for i in range(n_live)]
    batches = [_batch_arrays(reads[:batch], ecfg)]
    _, times, total = _run_rounds(ecfg, state, step, batches, n_rounds)
    ops = batch * n_rounds
    return {"ops_per_sec": round(ops / total, 1), "p99_round_ms": round(_p99(times), 2),
            "batch": batch, "capacity_log2": cap.bit_length() - 1}


def bench_zipf_mixed(smoke, cipher_impl="jnp"):
    """Config 3: mixed CRUD, Zipf(1.1) recipients — hammers hot
    mailboxes into the 62-message cap. ``cipher_impl="pallas"`` runs
    the same workload through the fused VMEM keystream kernel
    (oblivious/pallas_cipher.py) — reported as its own config line so
    a Mosaic compile issue cannot sink the headline.

    ``GRAPEVINE_BENCH_BATCH`` overrides the full-size batch (default
    2048 to bound driver compile time on one weak core; B=4096 runs
    overflow-free with the batch-scaled stash — PERF.md lever 5 — and
    halves the per-op share of fixed round cost on a healthy TPU)."""
    import os

    full_batch = int(os.environ.get("GRAPEVINE_BENCH_BATCH", "2048"))
    cap, batch, n_rounds = (1 << 10, 8, 4) if smoke else (1 << 20, full_batch, 12)
    cfg, ecfg, state, step = _mk_engine(cap, 1 << 12, batch, cipher_impl=cipher_impl)
    rng = np.random.default_rng(11)
    n_id = 512
    idents = rng.integers(1, 2**31, (n_id, 8)).astype(np.uint32)
    zipf = np.minimum(rng.zipf(1.1, size=8 * batch), n_id) - 1
    batches = []
    for k in range(4):
        reqs = []
        for j in range(batch):
            r = rng.random()
            rcp = idents[zipf[(k * batch + j) % len(zipf)]]
            me = idents[rng.integers(0, n_id)]
            pl = rng.integers(0, 2**31, (234,)).astype(np.uint32)
            zid = np.zeros((4,), np.uint32)
            if r < 0.5:
                reqs.append((1, me, zid, rcp, pl))  # CREATE → hot recipient
            elif r < 0.8:
                reqs.append((2, rcp, zid, np.zeros(8, np.uint32), pl))  # pop-read
            else:
                reqs.append((4, rcp, zid, np.zeros(8, np.uint32), pl))  # pop-del
        batches.append(_batch_arrays(reqs, ecfg))
    _, times, total = _run_rounds(ecfg, state, step, batches, n_rounds)
    ops = batch * n_rounds
    return {"ops_per_sec": round(ops / total, 1), "p99_round_ms": round(_p99(times), 2),
            "batch": batch, "capacity_log2": cap.bit_length() - 1}


def bench_zipf_pallas(smoke, impl="pallas"):
    """zipf_mixed through a Pallas cipher kernel (``impl="pallas"`` =
    fused VMEM keystream+XOR; ``"pallas_fused"`` = that plus the path
    gather fused into the decrypt, one HBM pass per fetched row).
    Full-size runs require a backend that compiles Mosaic ("tpu", or
    "axon" — the relay tunnel's name for its one real chip); elsewhere
    the kernel would fall back to interpret mode,
    which at B=2048 means thousands of per-tile dispatches — skipped
    rather than timed. Smoke mode runs interpret at toy shapes to keep
    the path exercised."""
    import jax

    from grapevine_tpu.config import TPU_BACKENDS

    backend = jax.default_backend()
    if impl in ("pallas_fused", "pallas_fused_tiled") and backend not in TPU_BACKENDS:
        # The fused gather's grid is one step per fetched row, and
        # interpret mode traces every grid step into the jit — ~60 s of
        # tracing at B=2048, so real shapes are Mosaic-only. But the
        # e2e plumbing (engine round through the fused fetch+decrypt /
        # encrypt+scatter path) must produce an executed number every
        # round, not only when a TPU shows up: run ONE toy-shape round
        # and report it under a key that cannot be mistaken for perf.
        return _fused_plumbing_proof(impl)
    if not smoke and backend not in TPU_BACKENDS:
        return {"skipped": f"needs a TPU backend for Mosaic (have {backend!r})"}
    return bench_zipf_mixed(smoke, cipher_impl=impl)


def _fused_plumbing_proof(impl="pallas_fused"):
    """Tiny interpret-mode engine rounds through the given fused cipher
    impl (cap 2^6, B=2): proves the bench→engine→fused-kernel plumbing
    executes end to end on this backend. The time is dominated by
    interpret-mode tracing at compile; the steady-state round time is
    reported separately and is NOT a perf claim (Mosaic numbers come
    from a TPU backend run of this same config)."""
    import jax

    from grapevine_tpu.engine.state import ID_WORDS, KEY_WORDS, PAYLOAD_WORDS

    cfg, ecfg, state, step = _mk_engine(1 << 6, 1 << 3, 2, cipher_impl=impl)
    rng = np.random.default_rng(5)
    me = rng.integers(1, 2**31, (KEY_WORDS,)).astype(np.uint32)
    pl = rng.integers(0, 2**31, (PAYLOAD_WORDS,)).astype(np.uint32)
    zid = np.zeros((ID_WORDS,), np.uint32)
    zkey = np.zeros((KEY_WORDS,), np.uint32)
    reqs = [(1, me, zid, me, pl), (2, me, zid, zkey, pl)]
    b = _batch_arrays(reqs, ecfg)
    t0 = time.perf_counter()
    state, resp, _ = step(ecfg, state, b)
    jax.block_until_ready(resp)
    t_compile = time.perf_counter() - t0
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        state, resp, _ = step(ecfg, state, b)
        jax.block_until_ready(resp)
        times.append(time.perf_counter() - t0)
    return {
        "plumbing_round_ms": round(float(np.mean(times)) * 1e3, 2),
        "interpret_trace_s": round(t_compile, 1),
        "note": "toy-shape interpret-mode plumbing proof, not a perf number",
        "batch": 2, "capacity_log2": 6,
    }


def bench_vphases_ab(smoke):
    """Config 7: dense vs scan slot-order machinery A/B (PR3 tentpole).

    B-sweep of whole-round per-op cost with ``vphases_impl`` as the only
    difference (bit-identical semantics, tests/test_vphases_scan.py).
    Geometry choices, deliberately:

    - cipher rounds 0: ChaCha8 on a scalar backend is ~90% of round
      time and identical under both impls — it would bury the A/B;
    - small trees (2^12) + mailbox_cap 8: bounds the gather/scatter
      share and compile time so three B points fit the per-config cap;
    - rounds interleaved dense/scan, compared by MINIMUM round time:
      the round is oblivious (shape-static, data-independent), so its
      true cost is a constant and the min is the unbiased estimator
      under this sandbox's 2-vCPU scheduler noise (back-to-back
      identical runs were measured 2× apart on wall-clock medians).

    Override the sweep with GRAPEVINE_VPHASES_AB_BS="64,256,..." — the
    dense quadratic term grows as B² against the round's ~linear rest,
    so the ratio rises with B (PERF.md Round 6 has the measured curve
    and the B=4096 memory math)."""
    import os
    import time as _time

    import jax

    sweep = [
        int(x)
        for x in os.environ.get(
            "GRAPEVINE_VPHASES_AB_BS", "64,256,1024"
        ).split(",")
    ]
    n_timed = 5 if smoke else 9
    out = {"sweep": {}}
    for B in sweep:
        ctxs = {}
        for impl in ("dense", "scan"):
            cfg, ecfg, state, step = _mk_engine(
                1 << 12, 1 << 9, B, vphases_impl=impl, cipher_rounds=0,
                mailbox_cap=8,
            )
            batches = make_batches(3, B, seed=13)
            state, resp, _ = step(ecfg, state, batches[0])
            jax.block_until_ready(resp)  # compile + warm
            ctxs[impl] = [ecfg, state, step, batches]

        def one_round(ctx, i):
            ecfg, state, step, batches = ctx
            t0 = _time.perf_counter()
            state, resp, _ = step(ecfg, state, batches[i % 3])
            jax.block_until_ready(resp)
            ctx[1] = state
            return _time.perf_counter() - t0

        times = {"dense": [], "scan": []}
        for i in range(n_timed):  # interleaved A/B
            times["dense"].append(one_round(ctxs["dense"], i))
            times["scan"].append(one_round(ctxs["scan"], i))
        md = float(np.min(times["dense"]))
        ms = float(np.min(times["scan"]))
        out["sweep"][str(B)] = {
            "dense_ms_per_op": round(md / B * 1e3, 4),
            "scan_ms_per_op": round(ms / B * 1e3, 4),
            "dense_round_ms": round(md * 1e3, 2),
            "scan_round_ms": round(ms * 1e3, 2),
            "median_dense_round_ms": round(
                float(np.median(times["dense"])) * 1e3, 2
            ),
            "median_scan_round_ms": round(
                float(np.median(times["scan"])) * 1e3, 2
            ),
            "speedup": round(md / ms, 3),
        }
        if B == 256:
            out["b256_dense_ms_per_op"] = out["sweep"]["256"]["dense_ms_per_op"]
            out["b256_scan_ms_per_op"] = out["sweep"]["256"]["scan_ms_per_op"]
            out["b256_speedup"] = out["sweep"]["256"]["speedup"]
    out["machinery"] = _vphases_machinery_sweep(smoke)
    return out


def _vphases_machinery_sweep(smoke):
    """Isolated group-aggregation machinery A/B (the exact term the
    vphases_impl knob swaps): one jit per (B, impl) exercising every
    group method at representative shapes. Unlike the whole-round A/B
    this is stable under the sandbox scheduler (sub-ms to ~100 ms ops,
    min-of-9) and shows the clean O(B²) vs O(B log B) separation the
    whole round dilutes with tree gather/scatter traffic."""
    import time as _time

    import jax
    import jax.numpy as jnp

    from grapevine_tpu.engine import vphases as V

    def one(B, impl, reps):
        rng = np.random.default_rng(0)
        ka = jnp.asarray(
            rng.integers(0, max(2, B // 8), (B, 8)).astype(np.uint32)
        )
        is_real = jnp.asarray(rng.random(B) < 0.9)
        flags = jnp.asarray(rng.random(B) < 0.3)
        u = jnp.asarray(rng.random((B, 248)) < 0.1)
        q = jnp.asarray(rng.integers(-2, 5, B).astype(np.int32))
        vals = jnp.asarray(rng.integers(0, 1 << 30, (B, 2)).astype(np.uint32))

        class E:
            vphases_impl = impl

        def work(ka, is_real, flags, u, q, vals):
            g = V._recipient_groups(E, ka, is_real)
            return [
                g.counts_before(flags), g.any_before(flags),
                g.total_sum(flags), g.total_or(flags), g.total_or_rows(u),
                g.total_sum_rows(u), g.group_first(), g.group_last(),
                g.first_flag_index(flags)[0],
                g.last_flag_index_upto(flags), g.last_flag_index(flags),
                g.select_by_rank(flags, vals, q),
            ]

        f = jax.jit(work)
        o = f(ka, is_real, flags, u, q, vals)
        jax.block_until_ready(o)
        ts = []
        for _ in range(reps):
            t0 = _time.perf_counter()
            o = f(ka, is_real, flags, u, q, vals)
            jax.block_until_ready(o)
            ts.append(_time.perf_counter() - t0)
        return float(np.min(ts))

    sweep = (256, 1024) if smoke else (256, 1024, 2048, 4096)
    reps = 5 if smoke else 9
    res = {}
    for B in sweep:
        d = one(B, "dense", reps)
        s = one(B, "scan", reps)
        res[str(B)] = {
            "dense_ms": round(d * 1e3, 2),
            "scan_ms": round(s * 1e3, 2),
            "speedup": round(d / s, 2),
        }
    return res


def _model_ab(kind, measured, **kw):
    """Modeled-vs-measured winner line for one A/B config group.

    Every ``_ab`` bench reports the static cost model's pick
    (analysis/costmodel.ab_verdict — amortized HBM bytes at the exact
    bench geometry, tie-band preferring least machinery) next to the
    measured winner, so a model/machine divergence is visible in the
    bench output itself, not only in the post-hoc
    ``check_cost_model --grade`` replay of the banked trajectory."""
    from grapevine_tpu.analysis.costmodel import ab_verdict

    v = ab_verdict(kind, **kw)
    return {
        "modeled_winner": v["winner"],
        "measured_winner": measured,
        "agree": v["winner"] == measured,
        "basis": v["basis"],
    }


def _min_of(fn, args, reps):
    """Interleaved-A/B timing primitive shared by the `_ab` configs:
    min of ``reps`` timed calls after one compile+warm call — the min
    is the unbiased cost of a shape-static oblivious program under this
    sandbox's 2-vCPU scheduler noise (PERF.md Round 6 methodology)."""
    import time as _time

    import jax

    jax.block_until_ready(fn(*args))  # compile + warm
    ts = []
    for _ in range(reps):
        t0 = _time.perf_counter()
        jax.block_until_ready(fn(*args))
        ts.append(_time.perf_counter() - t0)
    return float(np.min(ts))


def bench_sort_ab(smoke):
    """Config 4c: xla vs radix bounded-key sort engine A/B (PR5).

    Two scopes, both interleaved min-of-N (the min is the unbiased cost
    of a shape-static oblivious program under this sandbox's 2-vCPU
    scheduler noise — the vphases_ab methodology):

    - **machinery**: the exact sort the knob swaps, isolated — stable
      leaf-rank (``radix_rank`` vs ``jnp.argsort(stable=True)``) at
      eviction-shaped working-set sizes W with h-bit keys, plus the
      dedup group sort (``radix_group_sort`` vs
      ``multiword_group_sort``) at round batch sizes. Radix is timed at
      its best ``bits_per_pass`` per size so the comparison can't be
      rigged against it.
    - **whole round**: B-sweep with ``sort_impl`` as the only knob
      (vphases pinned "scan" so the bounded group sorts are actually in
      the round under both impls).

    Honest-reporting note (the PR-3 lesson, PERF.md Round 7): on
    XLA:CPU each radix pass pays a serial ~80 ns/elem scatter, so the
    native comparison sort wins here at every size — these numbers are
    the *CPU floor record* that justifies keeping ``sort_impl`` auto =
    "xla" off-TPU; the TPU decision belongs to the capture's
    ``sort_perf`` stage. Override sweeps with
    GRAPEVINE_SORT_AB_BS / GRAPEVINE_SORT_AB_WS."""
    import os
    import time as _time

    import jax
    import jax.numpy as jnp

    from grapevine_tpu.oblivious.radix import radix_group_sort, radix_rank
    from grapevine_tpu.oblivious.segmented import multiword_group_sort

    reps = 3 if smoke else 7
    out = {"machinery": {}, "sweep": {}}

    # --- machinery: eviction leaf rank at working-set sizes ------------
    h = 16 if smoke else 20  # leaf bits of a 2^16 / 2^20-capacity tree
    ws = [
        int(x)
        for x in os.environ.get(
            "GRAPEVINE_SORT_AB_WS",
            "4096,16384" if smoke else "16384,65536,262144",
        ).split(",")
    ]
    rng = np.random.default_rng(5)
    for w in ws:
        keys = jnp.asarray(
            rng.integers(0, 1 << h, w).astype(np.uint32)
        )
        tx = _min_of(
            jax.jit(lambda k: jnp.argsort(k, stable=True)), (keys,), reps
        )
        # radix at its best pass width for this size (1-bit passes have
        # no [W,R] bin table; wider passes amortize the per-pass
        # gather+scatter) — report the winner so the A/B is fair to it
        tr, bpp_best = None, None
        for bpp in (1, 4, 8):
            t = _min_of(
                jax.jit(lambda k, b=bpp: radix_rank(k, h + 1, b)),
                (keys,), reps,
            )
            if tr is None or t < tr:
                tr, bpp_best = t, bpp
        out["machinery"][f"evict_rank_w{w}"] = {
            "key_bits": h + 1,
            "xla_ms": round(tx * 1e3, 3),
            "radix_ms": round(tr * 1e3, 3),
            "radix_bits_per_pass": bpp_best,
            "speedup_radix_over_xla": round(tx / tr, 3),
        }
    # --- machinery: dedup group sort at batch sizes --------------------
    for b in (256, 1024) if smoke else (1024, 4096):
        kb = max(1, (b * 4).bit_length())
        idxs = jnp.asarray(
            rng.integers(0, b * 4, b).astype(np.uint32)
        )
        tx = _min_of(jax.jit(lambda i: multiword_group_sort([i])), (idxs,), reps)
        tr = _min_of(
            jax.jit(lambda i: radix_group_sort([i], kb)), (idxs,), reps
        )
        out["machinery"][f"dedup_group_b{b}"] = {
            "key_bits": kb,
            "xla_ms": round(tx * 1e3, 3),
            "radix_ms": round(tr * 1e3, 3),
            "speedup_radix_over_xla": round(tx / tr, 3),
        }

    # --- whole round: sort_impl the only knob --------------------------
    sweep = [
        int(x)
        for x in os.environ.get(
            "GRAPEVINE_SORT_AB_BS", "64,256" if smoke else "64,256,1024"
        ).split(",")
    ]
    n_timed = 3 if smoke else 9
    for B in sweep:
        ctxs = {}
        for impl in ("xla", "radix"):
            cfg, ecfg, state, step = _mk_engine(
                1 << 12, 1 << 9, B, vphases_impl="scan", sort_impl=impl,
                cipher_rounds=0, mailbox_cap=8,
            )
            batches = make_batches(3, B, seed=13)
            state, resp, _ = step(ecfg, state, batches[0])
            jax.block_until_ready(resp)
            ctxs[impl] = [ecfg, state, step, batches]

        def one_round(ctx, i):
            ecfg, state, step, batches = ctx
            t0 = _time.perf_counter()
            state, resp, _ = step(ecfg, state, batches[i % 3])
            jax.block_until_ready(resp)
            ctx[1] = state
            return _time.perf_counter() - t0

        times = {"xla": [], "radix": []}
        for i in range(n_timed):  # interleaved A/B
            times["xla"].append(one_round(ctxs["xla"], i))
            times["radix"].append(one_round(ctxs["radix"], i))
        mx = float(np.min(times["xla"]))
        mr = float(np.min(times["radix"]))
        out["sweep"][str(B)] = {
            "xla_round_ms": round(mx * 1e3, 2),
            "radix_round_ms": round(mr * 1e3, 2),
            "median_xla_round_ms": round(
                float(np.median(times["xla"])) * 1e3, 2
            ),
            "median_radix_round_ms": round(
                float(np.median(times["radix"])) * 1e3, 2
            ),
            "speedup_radix_over_xla": round(mx / mr, 3),
        }

    # modeled-vs-measured winner per config group (ISSUE 17): sort is
    # a structural verdict — backend decides (serial scatter floor on
    # XLA:CPU), not a byte count — so one verdict covers every group
    backend = jax.default_backend()
    for scope in ("machinery", "sweep"):
        for g in out[scope].values():
            g["model"] = _model_ab(
                "sort",
                "radix" if g["speedup_radix_over_xla"] > 1.0 else "xla",
                scope=scope, backend=backend,
            )
    return out


def bench_posmap_ab(smoke):
    """Config 4d: flat vs recursive position map A/B (PR7).

    Two scopes, both interleaved min-of-N (the vphases/sort_ab
    methodology):

    - **machinery**: ``lookup_remap_round`` isolated — the exact code
      the knob swaps — over a (batch B × capacity) grid: flat is one
      private gather + scatter, recursive is a full internal-ORAM round
      over blocks/k blocks of k entries. This is the *cost of position
      resolution itself*, the number OPERATIONS.md §13's "when to flip"
      guidance prices against the capacity win.
    - **whole round**: B-sweep with ``posmap_impl`` as the only knob —
      what a serving round actually pays, since the recursive map adds
      its internal path fetch/evict to every ORAM round.

    Honest-reporting note (the PR-3/PR-5 lesson): the recursive map is
    NOT a speed optimization and is not expected to win wall-clock
    anywhere — it buys ~sqrt(capacity)× less *resident* position memory
    (the ≥2^30 capacity enabler) for extra HBM traffic. The A/B exists
    to price that overhead honestly; auto stays "flat" until capacity
    forces the flip or the capture's ``posmap_perf`` stage (real chip)
    shows the overhead is hidden under the round's existing
    gather/scatter wall. Override sweeps with GRAPEVINE_POSMAP_AB_BS /
    GRAPEVINE_POSMAP_AB_CAPS."""
    import os
    import time as _time

    import jax
    import jax.numpy as jnp

    from grapevine_tpu.oram.path_oram import OramConfig, init_oram
    from grapevine_tpu.oram.posmap import (
        derive_posmap_spec,
        lookup_remap_round,
        posmap_hbm_bytes,
        posmap_private_bytes,
    )
    from grapevine_tpu.oram.round import occurrence_masks

    reps = 3 if smoke else 7
    out = {"machinery": {}, "sweep": {}}

    # --- machinery: the lookup round isolated, B × capacity grid -------
    caps = [
        int(x)
        for x in os.environ.get(
            "GRAPEVINE_POSMAP_AB_CAPS",
            "4096,65536" if smoke else "65536,1048576",
        ).split(",")
    ]
    bs_m = (64, 256) if smoke else (256, 1024)
    rng = np.random.default_rng(5)
    for cap_n in caps:
        height = max(1, cap_n.bit_length() - 2)  # density-2 payload shape
        flat_cfg = OramConfig(height=height, value_words=4, n_blocks=cap_n)
        spec = derive_posmap_spec(cap_n)
        rec_cfg = OramConfig(
            height=height, value_words=4, n_blocks=cap_n, posmap=spec
        )
        pm_f = init_oram(flat_cfg, jax.random.PRNGKey(1)).posmap
        pm_r = init_oram(rec_cfg, jax.random.PRNGKey(1)).posmap
        for b in bs_m:
            idxs = jnp.asarray(
                rng.integers(0, cap_n + 1, b).astype(np.uint32)
            )
            nl = jnp.asarray(rng.integers(0, flat_cfg.leaves, b).astype(np.uint32))
            dl = jnp.asarray(rng.integers(0, flat_cfg.leaves, b).astype(np.uint32))
            pnl = jnp.asarray(
                rng.integers(0, spec.inner_leaves, b).astype(np.uint32)
            )
            pdl = jnp.asarray(
                rng.integers(0, spec.inner_leaves, b).astype(np.uint32)
            )

            def lookup(cfg, pm, pm_nl, pm_dl):
                fo, lo, _ = occurrence_masks(idxs, cfg.dummy_index)
                pm2, leaves, inner = lookup_remap_round(
                    cfg, pm, idxs, nl, dl, fo, lo,
                    pm_new_leaves=pm_nl, pm_dummy_leaves=pm_dl,
                )
                # pm2 must be a live output: dropping it lets XLA
                # dead-code-eliminate flat's remap scatter and the
                # internal round's whole eviction write-back (the
                # sort_ab full-output rule)
                return (pm2, leaves) if inner is None else (pm2, leaves, inner)

            tf = _min_of(
                jax.jit(lambda pm: lookup(flat_cfg, pm, None, None)),
                (pm_f,), reps,
            )
            tr = _min_of(
                jax.jit(lambda pm: lookup(rec_cfg, pm, pnl, pdl)),
                (pm_r,), reps,
            )
            out["machinery"][f"lookup_cap{cap_n}_b{b}"] = {
                "k": spec.entries_per_block,
                "flat_ms": round(tf * 1e3, 3),
                "recursive_ms": round(tr * 1e3, 3),
                "overhead_recursive_over_flat": round(tr / tf, 2),
                "flat_private_mib": round(
                    posmap_private_bytes(flat_cfg) / 2**20, 3
                ),
                "recursive_private_mib": round(
                    posmap_private_bytes(rec_cfg) / 2**20, 3
                ),
                "recursive_hbm_mib": round(
                    posmap_hbm_bytes(rec_cfg) / 2**20, 3
                ),
            }

    # --- whole round: posmap_impl the only knob ------------------------
    sweep = [
        int(x)
        for x in os.environ.get(
            "GRAPEVINE_POSMAP_AB_BS", "16,64" if smoke else "64,256,1024"
        ).split(",")
    ]
    n_timed = 3 if smoke else 9
    for B in sweep:
        ctxs = {}
        for impl in ("flat", "recursive"):
            cfg, ecfg, state, step = _mk_engine(
                1 << 12, 1 << 9, B, posmap_impl=impl,
                cipher_rounds=0, mailbox_cap=8,
            )
            batches = make_batches(3, B, seed=13)
            state, resp, _ = step(ecfg, state, batches[0])
            jax.block_until_ready(resp)
            ctxs[impl] = [ecfg, state, step, batches]

        def one_round(ctx, i):
            ecfg, state, step, batches = ctx
            t0 = _time.perf_counter()
            state, resp, _ = step(ecfg, state, batches[i % 3])
            jax.block_until_ready(resp)
            ctx[1] = state
            return _time.perf_counter() - t0

        times = {"flat": [], "recursive": []}
        for i in range(n_timed):  # interleaved A/B
            times["flat"].append(one_round(ctxs["flat"], i))
            times["recursive"].append(one_round(ctxs["recursive"], i))
        mf = float(np.min(times["flat"]))
        mr = float(np.min(times["recursive"]))
        out["sweep"][str(B)] = {
            "flat_round_ms": round(mf * 1e3, 2),
            "recursive_round_ms": round(mr * 1e3, 2),
            "median_flat_round_ms": round(
                float(np.median(times["flat"])) * 1e3, 2
            ),
            "median_recursive_round_ms": round(
                float(np.median(times["recursive"])) * 1e3, 2
            ),
            "overhead_recursive_over_flat": round(mr / mf, 3),
        }
    return out


def bench_tree_cache_ab(smoke):
    """Config 4e: tree-top cache A/B (PR8; ROADMAP item 1).

    Two scopes, both interleaved min-of-N (the vphases/sort/posmap_ab
    methodology):

    - **machinery**: one records-shaped ``oram_round`` isolated (trivial
      apply callback) with ``top_cache_levels`` the only knob — the
      exact path gather/decrypt/evict/encrypt/scatter the cache cuts,
      without the engine's vphases/response machinery diluting it.
      Cap × B grid, cipher on (the cipher-row cut is part of the
      claim), with the per-k resident cache bytes reported.
    - **whole round**: engine B-sweep over k ∈ {0, 2, 4, auto} — what a
      serving round actually pays.

    Honest-reporting note (the PR-3/5 lesson): caching strictly removes
    HBM gather/scatter rows and cipher work — there is no algorithmic
    trade — but on this 2-vCPU sandbox the absolute win rides on how
    much of the round the path traffic is at the swept geometry;
    PERF.md Round 10 carries the analysis either way, and the on-chip
    number lands via tools/tpu_capture.py ``tree_cache_perf``.
    Override sweeps with GRAPEVINE_TREE_CACHE_AB_BS /
    GRAPEVINE_TREE_CACHE_AB_CAPS."""
    import os
    import time as _time

    import jax
    import jax.numpy as jnp

    from grapevine_tpu.oram.path_oram import (
        OramConfig,
        init_oram,
        tree_cache_private_bytes,
    )
    from grapevine_tpu.oram.round import oram_round

    reps = 3 if smoke else 7
    out = {"machinery": {}, "sweep": {}}

    # --- machinery: one ORAM round isolated, cap × B grid --------------
    caps = [
        int(x)
        for x in os.environ.get(
            "GRAPEVINE_TREE_CACHE_AB_CAPS",
            "4096" if smoke else "65536,1048576",
        ).split(",")
    ]
    bs_m = (64,) if smoke else (256, 1024)
    ks_m = (0, 2) if smoke else (0, 2, 4, 8)
    rng = np.random.default_rng(5)
    for cap_n in caps:
        height = max(1, cap_n.bit_length() - 2)  # density-2 payload shape
        for b in bs_m:
            idxs = jnp.asarray(
                rng.integers(0, cap_n + 1, b).astype(np.uint32)
            )
            # one leaf schedule shared by every k arm (the posmap_ab
            # rule: the knob is the ONLY difference between arms —
            # round cost is leaf-independent by obliviousness, but the
            # A/B should not have to lean on that)
            nl = jnp.asarray(
                rng.integers(0, 1 << height, b).astype(np.uint32)
            )
            dl = jnp.asarray(
                rng.integers(0, 1 << height, b).astype(np.uint32)
            )
            grid = {}
            for k in ks_m:
                cfg = OramConfig(
                    height=height, value_words=64, n_blocks=cap_n,
                    cipher_rounds=8, stash_size=max(96, b // 2 + 96),
                    top_cache_levels=min(k, height),
                )
                state = init_oram(cfg, jax.random.PRNGKey(1))

                def one(st, cfg=cfg):
                    def apply_batch(vals0, present0):
                        return jnp.sum(vals0, axis=1), vals0, present0

                    st2, outs, leaves = oram_round(
                        cfg, st, idxs, nl, dl, apply_batch
                    )
                    # full-output rule: the new state must be live or
                    # XLA DCEs the write-back half of the round
                    return st2, outs, leaves

                t = _min_of(jax.jit(one), (state,), reps)
                grid[f"k{k}"] = {
                    "round_ms": round(t * 1e3, 3),
                    "cache_kib": round(
                        tree_cache_private_bytes(cfg) / 1024, 1
                    ),
                }
            base = grid["k0"]["round_ms"]
            for k in ks_m[1:]:
                grid[f"k{k}"]["speedup_over_k0"] = round(
                    base / grid[f"k{k}"]["round_ms"], 3
                )
            grid["model"] = _model_ab(
                "tree_cache",
                min((f"k{k}" for k in ks_m),
                    key=lambda a: grid[a]["round_ms"]),
                scope="machinery", cap_n=cap_n, batch=b,
                arms=list(ks_m),
            )
            out["machinery"][f"round_cap{cap_n}_b{b}"] = grid

    # --- whole round: tree_top_cache_levels the only knob --------------
    sweep = [
        int(x)
        for x in os.environ.get(
            "GRAPEVINE_TREE_CACHE_AB_BS", "64" if smoke else "256,1024"
        ).split(",")
    ]
    ks = (0, 2) if smoke else (0, 2, 4, "auto")
    n_timed = 3 if smoke else 9
    for B in sweep:
        ctxs = {}
        for k in ks:
            cfg, ecfg, state, step = _mk_engine(
                1 << 12, 1 << 9, B, mailbox_cap=8,
                tree_top_cache=None if k == "auto" else k,
            )
            batches = make_batches(3, B, seed=13)
            state, resp, _ = step(ecfg, state, batches[0])
            jax.block_until_ready(resp)
            ctxs[k] = [ecfg, state, step, batches]

        def one_round(ctx, i):
            ecfg, state, step, batches = ctx
            t0 = _time.perf_counter()
            state, resp, _ = step(ecfg, state, batches[i % 3])
            jax.block_until_ready(resp)
            ctx[1] = state
            return _time.perf_counter() - t0

        times = {k: [] for k in ks}
        for i in range(n_timed):  # interleaved A/B
            for k in ks:
                times[k].append(one_round(ctxs[k], i))
        m0 = float(np.min(times[0]))
        entry = {}
        for k in ks:
            mk = float(np.min(times[k]))
            entry[f"k{k}"] = {
                "round_ms": round(mk * 1e3, 2),
                "median_round_ms": round(
                    float(np.median(times[k])) * 1e3, 2
                ),
                "speedup_over_k0": round(m0 / mk, 3),
            }
            if k == "auto":
                entry["kauto"]["resolved_k"] = ctxs[k][0].tree_top_cache_levels
        numeric = [k for k in ks if k != "auto"]
        entry["model"] = _model_ab(
            "tree_cache",
            min((f"k{k}" for k in numeric),
                key=lambda a: entry[a]["round_ms"]),
            scope="sweep", batch=B, arms=numeric,
        )
        out["sweep"][str(B)] = entry
    return out


def bench_evict_ab(smoke):
    """Config 4f: delayed batched eviction A/B (PR 15; ROADMAP item 1).

    Two scopes, both interleaved min-of-N (the vphases/sort/posmap/
    tree_cache_ab methodology), cipher ON in both — the amortized
    encrypt work is half the claim:

    - **machinery**: one records-shaped ORAM isolated (trivial apply
      callback). Per E arm the component programs are timed separately
      — the fetch-only round and the flush, each its own jit (an
      unrolled E-round window in ONE jit would pay an O(E·B) compile
      that blows the bench cap at E=8/B=1024 without changing what is
      measured) — and the honest amortized per-round cost is
      fetch + flush/E. The fetch/e1 ratio is the measured fetch-only
      fraction, the floor the amortized cost approaches as E grows
      (the ISSUE-15 acceptance comparator).
    - **whole round**: engine-level sweep over E × B — what a serving
      round actually pays with vphases/posmap/response machinery in the
      loop, same window-averaged timing through the jitted
      engine_round_step + engine_flush_step pair.

    Honest-reporting note: on this 2-vCPU sandbox the scatter+encrypt
    half is large (cipher rows + XLA scatter on the host), so the CPU
    win is real but the flush cannot overlap a device window here —
    the on-chip number (flush riding the bubble-ratio idle window)
    lands via tools/tpu_capture.py ``evict_perf``. Override sweeps
    with GRAPEVINE_EVICT_AB_BS / GRAPEVINE_EVICT_AB_ES /
    GRAPEVINE_EVICT_AB_CAPS."""
    import os
    import time as _time

    import jax
    import jax.numpy as jnp

    from grapevine_tpu.engine.round_step import engine_flush_step
    from grapevine_tpu.oram.path_oram import (
        OramConfig,
        derive_evict_buffer_slots,
        evict_buffer_private_bytes,
        init_oram,
    )
    from grapevine_tpu.oram.round import oram_flush, oram_round

    reps = 3 if smoke else 7
    out = {"machinery": {}, "sweep": {}}

    # --- machinery: one ORAM isolated, cap × B × E grid ----------------
    caps = [
        int(x)
        for x in os.environ.get(
            "GRAPEVINE_EVICT_AB_CAPS", "4096" if smoke else "65536"
        ).split(",")
    ]
    bs_m = (64,) if smoke else (256, 1024)
    es_m = (1, 2) if smoke else (1, 2, 4, 8)
    rng = np.random.default_rng(6)
    for cap_n in caps:
        height = max(1, cap_n.bit_length() - 2)  # density-2 payload shape
        for b in bs_m:
            idxs = jnp.asarray(
                rng.integers(0, cap_n + 1, b).astype(np.uint32)
            )
            nl = jnp.asarray(
                rng.integers(0, 1 << height, b).astype(np.uint32)
            )
            dl = jnp.asarray(
                rng.integers(0, 1 << height, b).astype(np.uint32)
            )
            grid = {}
            for e in es_m:
                cfg = OramConfig(
                    height=height, value_words=64, n_blocks=cap_n,
                    cipher_rounds=8, stash_size=max(96, b // 2 + 96),
                    evict_window=e,
                    evict_fetch_count=b if e > 1 else 0,
                    evict_buffer_slots=(
                        derive_evict_buffer_slots(cap_n, e, b, 4)
                        if e > 1 else 0
                    ),
                )
                state = init_oram(cfg, jax.random.PRNGKey(1))

                def apply_batch(vals0, present0):
                    return jnp.sum(vals0, axis=1), vals0, present0

                def one_round(st, cfg=cfg):
                    # full-output rule: the new state must be live or
                    # XLA DCEs the write half of the round
                    return oram_round(cfg, st, idxs, nl, dl, apply_batch)

                jit_round = jax.jit(one_round)
                t_round = _min_of(jit_round, (state,), reps)
                entry = {
                    "buffer_kib": round(
                        evict_buffer_private_bytes(cfg) / 1024, 1
                    ),
                }
                if e > 1:
                    entry["fetch_round_ms"] = round(t_round * 1e3, 3)
                    # flush timed at a 1-round fill: every flush shape
                    # (target slots, cipher rows, working set) is a
                    # static function of the geometry — obliviousness
                    # means fill level cannot change the cost
                    st1, _, _ = jit_round(state)
                    t_flush = _min_of(
                        jax.jit(lambda s, cfg=cfg: oram_flush(cfg, s)),
                        (st1,), reps,
                    )
                    entry["flush_ms"] = round(t_flush * 1e3, 3)
                    entry["amortized_round_ms"] = round(
                        (t_round + t_flush / e) * 1e3, 3
                    )
                else:
                    entry["amortized_round_ms"] = round(t_round * 1e3, 3)
                grid[f"e{e}"] = entry
            base = grid["e1"]["amortized_round_ms"]
            for e in es_m[1:]:
                g = grid[f"e{e}"]
                g["speedup_over_e1"] = round(
                    base / g["amortized_round_ms"], 3
                )
                g["fetch_fraction_of_e1"] = round(
                    g["fetch_round_ms"] / base, 3
                )
            grid["model"] = _model_ab(
                "evict",
                min((f"e{e}" for e in es_m),
                    key=lambda a: grid[a]["amortized_round_ms"]),
                scope="machinery", cap_n=cap_n, batch=b,
                arms=list(es_m),
            )
            out["machinery"][f"round_cap{cap_n}_b{b}"] = grid

    # --- whole round: evict_every the only knob ------------------------
    sweep = [
        int(x)
        for x in os.environ.get(
            "GRAPEVINE_EVICT_AB_BS", "64" if smoke else "256,1024"
        ).split(",")
    ]
    es = [
        int(x)
        for x in os.environ.get(
            "GRAPEVINE_EVICT_AB_ES", "1,2" if smoke else "1,2,4,8"
        ).split(",")
    ]
    n_windows = 2 if smoke else 5
    for B in sweep:
        ctxs = {}
        for e in es:
            cfg, ecfg, state, step = _mk_engine(
                1 << 12, 1 << 9, B, mailbox_cap=8, evict_every=e,
            )
            flush = jax.jit(
                engine_flush_step, static_argnums=(0,),
                donate_argnums=(1,),
            )
            batches = make_batches(3, B, seed=13)
            state, resp, _ = step(ecfg, state, batches[0])
            jax.block_until_ready(resp)
            if e > 1:
                for _ in range(e - 1):  # finish the first window + warm
                    state, resp, _ = step(ecfg, state, batches[1])
                state = flush(ecfg, state)
                jax.block_until_ready(
                    jax.tree_util.tree_leaves(state)[0]
                )
            ctxs[e] = [ecfg, state, step, flush, batches]

        def one_window(ctx, i, e):
            ecfg, state, step, flush, batches = ctx
            t0 = _time.perf_counter()
            for j in range(e):
                state, resp, _ = step(
                    ecfg, state, batches[(i * e + j) % 3]
                )
            if e > 1:
                state = flush(ecfg, state)
            # block on the WHOLE window output — state included, not
            # just the last responses: the flush (and the final round's
            # write half) must finish inside its own arm's timer, or
            # its device time leaks into the next interleaved arm's
            # window and the E arms under-report their own flush cost
            jax.block_until_ready((state, resp))
            ctx[1] = state
            return (_time.perf_counter() - t0) / e

        times = {e: [] for e in es}
        for i in range(n_windows):  # interleaved A/B
            for e in es:
                times[e].append(one_window(ctxs[e], i, e))
        m1 = float(np.min(times[es[0]]))
        entry = {}
        for e in es:
            me = float(np.min(times[e]))
            entry[f"e{e}"] = {
                "amortized_round_ms": round(me * 1e3, 2),
                "median_round_ms": round(
                    float(np.median(times[e])) * 1e3, 2
                ),
                "speedup_over_e1": round(m1 / me, 3),
            }
        for e in es:
            ov = sum(
                int(np.asarray(getattr(ctxs[e][1], t).overflow))
                for t in ("rec", "mb")
            )
            assert ov == 0, f"overflow at E={e}: {ov}"
        entry["model"] = _model_ab(
            "evict",
            min((f"e{e}" for e in es),
                key=lambda a: entry[a]["amortized_round_ms"]),
            scope="sweep", batch=B, arms=list(es),
        )
        out["sweep"][str(B)] = entry
    return out


def bench_expiry_sweep(smoke):
    """Config 4: full-bus timestamped eviction scan (reference
    README.md:86-98) at the largest capacity that fits one chip:
    2^22 messages at tree density 4 — an 8 GB records tree on a 16 GB
    v5e, twice the 2^24 pod's 4 GB-per-chip shard (tests/
    test_capacity.py pins that shard to the 2^20-density-2 tree)."""
    import jax

    from grapevine_tpu.engine.expiry import expiry_sweep

    cap, density = ((1 << 10), 2) if smoke else ((1 << 22), 4)
    cfg, ecfg, state, step = _mk_engine(cap, 1 << 12, 64, density=density)
    # populate some traffic first so the sweep has work
    batches = make_batches(4, 64)
    for b in batches:
        state, resp, _ = step(ecfg, state, b)
    jax.block_until_ready(resp)
    sweep = jax.jit(expiry_sweep, static_argnums=(0,))
    s2 = sweep(ecfg, state, np.uint32(NOW + 10), np.uint32(5))
    jax.block_until_ready(s2)
    times = []
    for i in range(3 if smoke else 8):
        t0 = time.perf_counter()
        s2 = sweep(ecfg, s2, np.uint32(NOW + 10 + i), np.uint32(5))
        jax.block_until_ready(s2)
        times.append(time.perf_counter() - t0)
    # records scanned per second over the full bus
    per = float(np.mean(times))
    return {"records_per_sec": round(cap / per, 1), "p99_sweep_ms": round(_p99(times), 2),
            "capacity_log2": cap.bit_length() - 1, "tree_density": density}


def bench_sharded(smoke):
    """Config 5: the sharded engine on whatever mesh exists. The 8-way
    ICI path runs in-process whenever ≥2 devices are visible (a pod, or
    CI's virtual CPU mesh). With ONE real chip visible, the sharded
    program is instead executed on a virtual 8-device CPU mesh in a
    subprocess (the backend cannot be switched after TPU init) — the
    result is labeled ``backend: cpu-mesh-sim`` because its ops/s
    measures host simulation, not ICI; it exists so the sharded path is
    exercised under bench conditions, not skipped."""
    import jax
    import os

    n_dev = len(jax.devices())
    if n_dev < 2:
        if os.environ.get("GRAPEVINE_SHARDED_SUBPROC"):
            # we ARE the fallback child yet still see <2 devices —
            # report instead of recursing into another subprocess
            return {"skipped": f"cpu-mesh child saw {n_dev} device(s)"}
        return _sharded_subprocess(smoke)
    from grapevine_tpu.parallel.mesh import (
        make_mesh,
        make_sharded_step,
        shard_engine_state,
    )

    cap, batch, n_rounds = (1 << 10, 8, 3) if smoke else (1 << 20, 256, 8)
    cfg, ecfg, state, _ = _mk_engine(cap, 1 << 10, batch)
    mesh = make_mesh()
    state = shard_engine_state(state, mesh)
    step = make_sharded_step(ecfg, mesh)
    batches = make_batches(4, batch)
    state, resp, _ = step(state, batches[0])
    jax.block_until_ready(resp)
    times = []
    t_all = time.perf_counter()
    for i in range(n_rounds):
        t0 = time.perf_counter()
        state, resp, _ = step(state, batches[i % 4])
        jax.block_until_ready(resp)
        times.append(time.perf_counter() - t0)
    total = time.perf_counter() - t_all
    overflow = int(np.asarray(state.rec.overflow)) + int(np.asarray(state.mb.overflow))
    assert overflow == 0, f"stash overflow during sharded bench: {overflow}"
    ops = batch * n_rounds
    return {"ops_per_sec": round(ops / total, 1), "p99_round_ms": round(_p99(times), 2),
            "batch": batch, "capacity_log2": cap.bit_length() - 1, "mesh": n_dev}


def bench_sharded_evict_ab(smoke):
    """Config 5b: owner-masked sharded flush A/B (ISSUE 18; ROADMAP
    item 1) — delayed batched eviction composed with the bucket-axis
    mesh. One records-shaped ORAM (the evict_ab machinery geometry,
    cipher ON, built via costmodel.machinery_oram_cfg so the model
    prices exactly what is timed) runs sharded per arm over
    E∈{1,2,4} × shards∈{1,2,4}. Per (s, E>1) arm the fetch-only round
    and the owner-masked flush are timed as separate jitted shard_map
    programs (the evict_ab component methodology — an unrolled window
    in one jit pays an O(E·B) compile without changing what is
    measured) and amortized as fetch + flush/E.
    ``fetch_fraction_of_e1`` is the ISSUE-18 acceptance comparator:
    the steady non-flush sharded round vs the SAME-mesh E=1 round.

    With <2 devices the whole config runs on a virtual 8-device CPU
    mesh in a subprocess, labeled ``backend: cpu-mesh-sim`` — host
    simulation, not ICI, so cross-shard wall-clock ratios would
    measure vCPU timeslicing and every reported ratio stays WITHIN one
    mesh width. The on-chip number lands via tools/tpu_capture.py
    ``sharded_perf``."""
    import os

    import jax
    import jax.numpy as jnp

    n_dev = len(jax.devices())
    if n_dev < 2:
        if os.environ.get("GRAPEVINE_SHARDED_SUBPROC"):
            return {"skipped": f"cpu-mesh child saw {n_dev} device(s)"}
        return _sharded_subprocess(smoke, "sharded_evict_ab")

    from jax.sharding import NamedSharding, PartitionSpec as P

    from grapevine_tpu.analysis.costmodel import machinery_oram_cfg
    from grapevine_tpu.oram.path_oram import init_oram
    from grapevine_tpu.oram.round import oram_flush, oram_round
    from grapevine_tpu.parallel.mesh import (
        _SHARD_MAP_NOCHECK,
        TREE_AXIS,
        _oram_specs,
        _shard_map,
        make_mesh,
    )

    reps = 3 if smoke else 7
    cap_n, b = (4096, 64) if smoke else (65536, 256)
    es = (1, 2, 4)
    shard_arms = [s for s in (1, 2, 4) if s <= n_dev]
    rng = np.random.default_rng(18)
    height = max(1, cap_n.bit_length() - 2)
    idxs = jnp.asarray(rng.integers(0, cap_n + 1, b).astype(np.uint32))
    nl = jnp.asarray(rng.integers(0, 1 << height, b).astype(np.uint32))
    dl = jnp.asarray(rng.integers(0, 1 << height, b).astype(np.uint32))
    specs = _oram_specs()
    out = {
        "machinery": {},
        # geometry keys (tools/check_perf_regression.py): a re-swept
        # arm grid is a different line, never a regression comparison
        "shard_count": ",".join(str(s) for s in shard_arms),
        "evict_every": ",".join(str(e) for e in es),
    }
    for s in shard_arms:
        mesh = make_mesh(jax.devices()[:s])
        grid = {}
        for e in es:
            cfg = machinery_oram_cfg(cap_n, b, e=e)
            assert cfg.n_buckets_padded % s == 0
            state = jax.tree.map(
                lambda sp, x: jax.device_put(x, NamedSharding(mesh, sp)),
                specs, init_oram(cfg, jax.random.PRNGKey(1)),
                is_leaf=lambda sp: isinstance(sp, P),
            )

            def apply_batch(vals0, present0):
                return jnp.sum(vals0, axis=1), vals0, present0

            def one_round(st, cfg=cfg):
                return oram_round(cfg, st, idxs, nl, dl, apply_batch,
                                  axis_name=TREE_AXIS)

            jit_round = jax.jit(_shard_map(
                one_round, mesh=mesh, in_specs=(specs,),
                out_specs=(specs, P(), P()), **_SHARD_MAP_NOCHECK,
            ))
            t_round = _min_of(jit_round, (state,), reps)
            entry = {}
            if e > 1:
                entry["fetch_round_ms"] = round(t_round * 1e3, 3)
                # flush timed at a 1-round fill: every flush shape is a
                # static function of the geometry (obliviousness means
                # fill level cannot change the cost)
                st1, _, _ = jit_round(state)
                jit_flush = jax.jit(_shard_map(
                    lambda st, cfg=cfg: oram_flush(cfg, st, TREE_AXIS),
                    mesh=mesh, in_specs=(specs,), out_specs=specs,
                    **_SHARD_MAP_NOCHECK,
                ))
                t_flush = _min_of(jit_flush, (st1,), reps)
                entry["flush_ms"] = round(t_flush * 1e3, 3)
                entry["amortized_round_ms"] = round(
                    (t_round + t_flush / e) * 1e3, 3
                )
            else:
                entry["amortized_round_ms"] = round(t_round * 1e3, 3)
            grid[f"e{e}"] = entry
        base = grid["e1"]["amortized_round_ms"]
        for e in es[1:]:
            g = grid[f"e{e}"]
            g["speedup_over_e1"] = round(
                base / g["amortized_round_ms"], 3
            )
            g["fetch_fraction_of_e1"] = round(
                g["fetch_round_ms"] / base, 3
            )
        grid["model"] = _model_ab(
            "sharded_evict",
            min((f"e{e}" for e in es),
                key=lambda a: grid[a]["amortized_round_ms"]),
            scope="machinery", cap_n=cap_n, batch=b, arms=list(es),
            shards=s,
        )
        out["machinery"][f"round_cap{cap_n}_b{b}_s{s}"] = grid
    return out


def _xla_flags_supported(flags: str) -> bool:
    """True iff this jaxlib parses ``flags`` (older ones abort on
    unknown XLA flags). Mirrors tests/conftest.py, incl. the per-jaxlib
    /tmp cache so the cold probe is paid once per environment."""
    import hashlib
    import os
    import subprocess
    import tempfile

    try:
        import jaxlib

        version = jaxlib.__version__
    except Exception:
        version = "unknown"
    tag = hashlib.sha256(f"{version}:{flags}".encode()).hexdigest()[:16]
    cache = os.path.join(
        tempfile.gettempdir(), f"grapevine_xla_flag_probe_{tag}"
    )
    try:
        with open(cache) as fh:
            return fh.read().strip() == "ok"
    except OSError:
        pass
    probe = (
        "import os; os.environ['JAX_PLATFORMS']='cpu'; "
        f"os.environ['XLA_FLAGS']={flags!r}; "
        "import jax; jax.devices()"
    )
    try:
        ok = (
            subprocess.run(
                [sys.executable, "-c", probe],
                capture_output=True,
                timeout=120,
            ).returncode
            == 0
        )
    except Exception:
        return False  # don't cache a flaky probe run
    try:
        with open(cache, "w") as fh:
            fh.write("ok" if ok else "unsupported")
    except OSError:
        pass
    return ok


def _sharded_subprocess(smoke, config="sharded"):
    """Run one of this file's sharded configs on a virtual CPU mesh,
    isolated in a subprocess (the backend cannot switch after init)."""
    import json as _json
    import os
    import subprocess
    import sys as _sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["GRAPEVINE_SHARDED_SUBPROC"] = "1"  # recursion guard
    _ours = (
        "xla_force_host_platform_device_count",
        "xla_cpu_collective_call_warn_stuck_timeout_seconds",
        "xla_cpu_collective_call_terminate_timeout_seconds",
    )
    flags = [
        f for f in env.get("XLA_FLAGS", "").split()
        if not any(name in f for name in _ours)
    ]
    # timesliced virtual devices rendezvous slowly on a loaded core;
    # the default terminate timeout SIGABRTs spuriously (BIGRUN_r5.md —
    # it is a flag, not a scale wall). But older jaxlibs CHECK-fail-
    # abort on *unknown* XLA flags (the PR-1 conftest lesson), so probe
    # support in a throwaway subprocess before adding them.
    _timeouts = [
        "--xla_cpu_collective_call_warn_stuck_timeout_seconds=120",
        "--xla_cpu_collective_call_terminate_timeout_seconds=600",
    ]
    env["XLA_FLAGS"] = " ".join(
        flags
        + ["--xla_force_host_platform_device_count=8"]
        + (_timeouts if _xla_flags_supported(" ".join(_timeouts)) else [])
    )
    # always smoke-sized shapes: the sim measures host CPU, so big
    # shapes only burn driver wall-clock without adding information
    code = (
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        "import json, bench\n"
        f"print('SHARDED_JSON ' + json.dumps(bench.bench_{config}(True)))\n"
    )
    # under --smoke a broken sharded path must FAIL the harness gate
    # (error), not silently pass as skipped
    fail_key = "error" if smoke else "skipped"
    try:
        out = subprocess.run(
            [_sys.executable, "-c", code],
            env=env, capture_output=True, text=True, timeout=900,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        for line in out.stdout.splitlines():
            if line.startswith("SHARDED_JSON "):
                r = _json.loads(line[len("SHARDED_JSON "):])
                r["backend"] = "cpu-mesh-sim"
                return r
        return {fail_key: f"subprocess produced no result: {out.stderr[-300:]}"}
    except Exception as e:
        return {fail_key: f"cpu-mesh subprocess failed: {type(e).__name__}: {e}"}


def bench_server_loopback(smoke):
    """End-to-end gRPC loopback: in-process server (session crypto +
    challenge lockstep + batched signature verification + engine),
    concurrent authenticated clients. Exposes the full-stack throughput
    the engine-only configs skip (VERDICT r2: the auth path capped the
    server at O(100) ops/s before batch verification).

    The session layer runs on the ``cryptography`` wheel when present
    and on the stdlib ChaCha20+HMAC port (session/stdcrypto.py) when
    not, so this config reports real numbers in every container — the
    historical wheel-less *skip* is gone (ISSUE 20). The active backend
    rides the result line so banked numbers are never compared across
    backends by accident."""
    import threading

    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.server.client import GrapevineClient
    from grapevine_tpu.server.service import GrapevineServer
    from grapevine_tpu.session.channel import CRYPTO_BACKEND
    from grapevine_tpu.wire import constants as C

    cap, n_clients, per_client = (1 << 10, 2, 4) if smoke else (1 << 16, 16, 24)
    cfg = GrapevineConfig(
        max_messages=cap,
        max_recipients=1 << 10,
        batch_size=16,
        bucket_cipher_rounds=0 if smoke else 8,
    )
    # the leak monitor rides every loopback round (ISSUE 2 acceptance:
    # p99 must hold within 3% with it on — the hand-off is one queue
    # put; detectors run on the monitor's own thread). Its verdict is
    # reported so the bench doubles as an honest-soak audit.
    from grapevine_tpu.obs.leakmon import LeakMonitorConfig

    server = GrapevineServer(config=cfg, leakmon=LeakMonitorConfig())
    port = server.start("insecure-grapevine://127.0.0.1:0")
    try:
        clients = [
            GrapevineClient(
                f"insecure-grapevine://127.0.0.1:{port}",
                identity_seed=bytes([i + 1]) * 32,
            )
            for i in range(n_clients)
        ]
        for c in clients:
            c.auth()
        errs = []
        lat: list[float] = []
        lock = threading.Lock()

        def run(c, peer):
            try:
                for i in range(per_client):
                    t0 = time.perf_counter()
                    r = c.create(recipient=peer.public_key,
                                 payload=bytes([i & 0xFF]) * C.PAYLOAD_SIZE)
                    assert r.status_code == C.STATUS_CODE_SUCCESS, r.status_code
                    r2 = c.read()  # zero-id pop of my own inbox (may be empty)
                    assert r2.status_code in (
                        C.STATUS_CODE_SUCCESS,
                        C.STATUS_CODE_NOT_FOUND,
                    )
                    with lock:
                        lat.append(time.perf_counter() - t0)
            except Exception as e:  # pragma: no cover
                errs.append(e)

        t0 = time.perf_counter()
        threads = [
            threading.Thread(target=run, args=(c, clients[(j + 1) % n_clients]))
            for j, c in enumerate(clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        total = time.perf_counter() - t0
        assert not errs, errs[0]
        ops = n_clients * per_client * 2  # create + read per iteration
        # per-phase p99s from the obs registry: where the full-stack
        # round budget actually went (assembly window vs verify vs
        # device vs demux) — the breakdown Palermo-style perf work needs
        phases = {
            k.split("phase=", 1)[1].split("}", 1)[0]: v
            for k, v in server.metrics_registry.snapshot().items()
            if k.startswith("grapevine_phase_seconds{") and k.endswith("_p99")
        }
        server.leakmon.flush(10)
        audit = server.leakmon.verdict()
        return {
            "ops_per_sec": round(ops / total, 1),
            "p99_pair_ms": round(_p99(lat), 2),
            "phase_p99_s": phases,
            "clients": n_clients,
            "capacity_log2": cap.bit_length() - 1,
            "crypto_backend": CRYPTO_BACKEND,
            "leakaudit": audit["verdict"],
            "leakaudit_rounds": audit["rounds_observed"],
        }
    finally:
        server.stop()


def bench_host_pipeline_ab(smoke):
    """Config 6b (ISSUE 20): worker-count scaling of the verify+codec
    machinery through the multiprocess hostpipe (server/hostpipe.py) —
    the off-GIL pool the scheduler fans batch verification across and
    the serving layer runs session codec (AEAD open/seal + unpack +
    validate + challenge lockstep) on.

    Three arms, interleaved rep by rep: in-process (the historical
    single-GIL path, verify only — there is no in-process pool to run
    codec tasks on), W=1, and W=2. Per arm: sr25519 batch-verify
    throughput over a round-sized item set, and codec throughput over
    pipelined `open` tasks across channels sticky-routed over the pool.

    Honesty: scaling is a property of the HOST, so ``host_cores`` (the
    scheduler-visible core count) rides the line as a perf-sentinel
    geometry key. On a single-core container W=2 physically serializes
    — the measured speedup is the serialized floor (~1.0x), and the
    ceiling analysis is the Amdahl projection from the measured
    dispatch-serial fraction (parent-side pickle + pipe send, the only
    part that cannot parallelize): what W=2 would deliver with two real
    cores. The ≥1.7x acceptance claim is gated on ``host_cores >= 2``;
    a single-core line reports the projection and says so in ``note``.
    """
    import os
    import pickle
    import threading

    from grapevine_tpu.obs import TelemetryRegistry
    from grapevine_tpu.server.hostpipe import HostPipeline
    from grapevine_tpu.session import schnorrkel
    from grapevine_tpu.session.chacha import ChallengeRng
    from grapevine_tpu.session.channel import (
        CRYPTO_BACKEND,
        client_finish,
        client_handshake,
        server_handshake,
    )
    from grapevine_tpu.wire import constants as C
    from grapevine_tpu.wire.records import QueryRequest, RequestRecord

    n_items, n_chan, opens_per_chan, reps = (
        (256, 4, 8, 2) if smoke else (2048, 8, 24, 3)
    )
    cores = len(os.sched_getaffinity(0))
    ctx = C.GRAPEVINE_CHALLENGE_SIGNING_CONTEXT

    # one signing key per 250 identities is plenty: verify cost is
    # per-item regardless of key reuse
    keys = []
    for i in range(250):
        sk, _ = schnorrkel.expand_mini_secret(bytes([i + 1]) * 32)
        keys.append((sk, schnorrkel.public_key(sk)))
    items = []
    for i in range(n_items):
        sk, pub = keys[i % len(keys)]
        msg = b"round-challenge-%06d" % i
        items.append((pub, ctx, msg, schnorrkel.sign(sk, ctx, msg)))

    def mk_sealed(chan, rng, n):
        """n sealed CREATE envelopes in lockstep order for one channel."""
        sk, pub = keys[0]
        out = []
        for i in range(n):
            ch = rng.next_challenge()
            req = QueryRequest(
                request_type=C.REQUEST_TYPE_CREATE,
                auth_identity=pub,
                auth_signature=schnorrkel.sign(sk, ctx, ch),
                record=RequestRecord(
                    recipient=pub,
                    payload=bytes([i & 0xFF]) * C.PAYLOAD_SIZE,
                ),
            )
            out.append(chan.encrypt(req.pack()))
        return out

    def setup_pool(w):
        pool = HostPipeline(w, registry=TelemetryRegistry())
        pool.verify_parallel(items[: 4 * w])  # warm every worker
        chans = []
        for j in range(n_chan):
            cid = b"host-ab-%08d" % j
            state, msg1 = client_handshake()
            reply, server_chan = server_handshake(msg1)
            cchan = client_finish(state, reply)
            seed = bytes([j + 1]) * 32
            pool.attach_session(cid, server_chan, seed)
            sealed = mk_sealed(cchan, ChallengeRng(seed),
                               opens_per_chan * reps)
            chans.append((cid, sealed))
        return pool, chans

    arms = {w: setup_pool(w) for w in (1, 2)}
    best = {
        "inproc": {"verify": 0.0},
        1: {"verify": 0.0, "codec": 0.0},
        2: {"verify": 0.0, "codec": 0.0},
    }
    schnorrkel.batch_verify(items[:8])  # warm the in-process tables
    try:
        for rep in range(reps):
            t0 = time.perf_counter()
            assert schnorrkel.batch_verify(items)
            best["inproc"]["verify"] = max(
                best["inproc"]["verify"],
                n_items / (time.perf_counter() - t0))
            for w, (pool, chans) in arms.items():
                t0 = time.perf_counter()
                assert pool.verify_parallel(items)
                best[w]["verify"] = max(
                    best[w]["verify"],
                    n_items / (time.perf_counter() - t0))
                # codec: pipeline this rep's slice of every channel's
                # sealed stream; per-channel FIFO order preserves the
                # AEAD/challenge lockstep, channels overlap across the
                # pool exactly as sticky routing spreads them
                lo, hi = rep * opens_per_chan, (rep + 1) * opens_per_chan
                t0 = time.perf_counter()
                futs = [
                    pool.submit("open", (cid, ct, b""), sticky=cid)
                    for cid, sealed in chans
                    for ct in sealed[lo:hi]
                ]
                for f in futs:
                    f.result(timeout=60.0)
                best[w]["codec"] = max(
                    best[w]["codec"],
                    len(futs) / (time.perf_counter() - t0))
        # the dispatch-side serial fraction: what the parent must do
        # alone before workers can run (chunk pickle + pipe write;
        # measured as the pickle, the pipe write rides the same bytes)
        t0 = time.perf_counter()
        pickle.dumps(("schnorrkel", items))
        t_serial = time.perf_counter() - t0
        t_w1 = n_items / best[1]["verify"]
        s_frac = min(1.0, t_serial / t_w1)
        projected = 1.0 / (s_frac + (1.0 - s_frac) / 2.0)
    finally:
        for pool, _ in arms.values():
            pool.close()

    out = {
        "host_cores": cores,
        "clients": n_chan,
        "crypto_backend": CRYPTO_BACKEND,
        "verify_items": n_items,
        "reps": reps,
        "inproc": {
            "verify_ops_per_sec": round(best["inproc"]["verify"], 1),
        },
    }
    for w in (1, 2):
        out[f"w{w}"] = {
            "verify_ops_per_sec": round(best[w]["verify"], 1),
            "codec_ops_per_sec": round(best[w]["codec"], 1),
        }
    out["speedup_verify_w2_over_w1"] = round(
        best[2]["verify"] / best[1]["verify"], 3)
    out["speedup_codec_w2_over_w1"] = round(
        best[2]["codec"] / best[1]["codec"], 3)
    out["fanout_tax_w1_over_inproc"] = round(
        best[1]["verify"] / best["inproc"]["verify"], 3)
    out["dispatch_serial_fraction"] = round(s_frac, 4)
    out["projected_w2_speedup_2cores"] = round(projected, 3)
    if cores >= 2:
        assert out["speedup_verify_w2_over_w1"] >= 1.7, (
            f"W=2 verify scaling {out['speedup_verify_w2_over_w1']}x "
            f"< 1.7x on a {cores}-core host"
        )
    else:
        out["note"] = (
            "single-core container: W=2 serializes by construction, so "
            "the measured speedup is the floor, not the machinery's "
            "ceiling; the Amdahl projection from the measured dispatch-"
            "serial fraction is the honest 2-core estimate"
        )
    return out


def bench_slo_loopback(smoke):
    """Config 7: concurrent submitters through the BatchScheduler into
    the engine with the PR-6 observability stack attached (round tracer
    + commit-latency SLO tracker) — the end-to-end *commit latency* a
    client observes (enqueue → round settle), which is what the SLO
    engine gates on, plus the derived bubble ratio that sizes the
    pipelined-round refactor (ROADMAP item 2). No session crypto in the
    loop, so unlike ``server_loopback`` this runs in every container;
    the observability overhead rides every round exactly as it does in
    production (`EngineServer` attaches the same stack)."""
    import threading

    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.engine.batcher import GrapevineEngine
    from grapevine_tpu.obs.slo import SloConfig, SloTracker
    from grapevine_tpu.obs.tracer import RoundTracer
    from grapevine_tpu.server.scheduler import BatchScheduler
    from grapevine_tpu.wire import constants as C
    from grapevine_tpu.wire.records import QueryRequest, RequestRecord

    cap, n_clients, per_client, batch = (
        (1 << 10, 2, 6, 4) if smoke else (1 << 16, 8, 48, 16)
    )
    cfg = GrapevineConfig(
        max_messages=cap, max_recipients=1 << 10, batch_size=batch,
        bucket_cipher_rounds=0 if smoke else 8,
    )
    engine = GrapevineEngine(cfg)
    tracer = RoundTracer(capacity=256, registry=engine.metrics.registry)
    engine.attach_tracer(tracer)
    slo = SloTracker(SloConfig(), registry=engine.metrics.registry)
    engine.attach_slo(slo)
    sched = BatchScheduler(engine, clock=lambda: NOW)
    try:
        rng = np.random.default_rng(17)
        idents = rng.integers(1, 256, (n_clients, 32)).astype(np.uint8)
        # recipients rotate through a pool wide enough that no mailbox
        # approaches the 62-message cap across warm-up + timed sends
        recips = rng.integers(1, 256, (64, 32)).astype(np.uint8)
        errs: list = []
        lat: list[float] = []
        lock = threading.Lock()

        def run(j):
            me = idents[j].tobytes()
            try:
                for i in range(per_client):
                    req = QueryRequest(
                        request_type=C.REQUEST_TYPE_CREATE,
                        auth_identity=me,
                        auth_signature=b"\x01" * C.SIGNATURE_SIZE,
                        record=RequestRecord(
                            msg_id=C.ZERO_MSG_ID,
                            recipient=recips[
                                (j * per_client + i) % len(recips)
                            ].tobytes(),
                            payload=bytes([i & 0xFF]) * C.PAYLOAD_SIZE,
                        ),
                    )
                    t0 = time.perf_counter()
                    r = sched.submit(req)
                    assert r.status_code == C.STATUS_CODE_SUCCESS, r.status_code
                    with lock:
                        lat.append(time.perf_counter() - t0)
            except Exception as e:  # pragma: no cover
                errs.append(e)

        # one warm-up op pays the compile outside the timed window (the
        # SLO tracker sees it too — exactly the cold-start breach the
        # min_rounds gate exists to not page on)
        warm = sched.submit(QueryRequest(
            request_type=C.REQUEST_TYPE_CREATE,
            auth_identity=idents[0].tobytes(),
            auth_signature=b"\x01" * C.SIGNATURE_SIZE,
            record=RequestRecord(
                msg_id=C.ZERO_MSG_ID, recipient=recips[0].tobytes(),
                payload=b"\x00" * C.PAYLOAD_SIZE,
            ),
        ))
        assert warm.status_code == C.STATUS_CODE_SUCCESS
        t0 = time.perf_counter()
        threads = [threading.Thread(target=run, args=(j,))
                   for j in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        total = time.perf_counter() - t0
        assert not errs, errs[0]
        verdict = slo.verdict()
        trace = tracer.chrome_trace()
        ops = n_clients * per_client
        return {
            "ops_per_sec": round(ops / total, 1),
            "p99_commit_ms": round(_p99(lat), 2),
            "median_commit_ms": round(float(np.median(lat)) * 1e3, 2),
            "bubble_ratio": trace["otherData"]["bubble_ratio"],
            "trace_rounds": trace["otherData"]["rounds_recorded_total"],
            "slo_target_ms": verdict["target_ms"],
            "slo_ok": verdict["ok"],
            "fast_burn_rate": verdict["fast_burn_rate"],
            "slow_burn_rate": verdict["slow_burn_rate"],
            "clients": n_clients, "batch": batch,
            "capacity_log2": cap.bit_length() - 1,
        }
    finally:
        sched.close()


def bench_pipeline_ab(smoke):
    """Config 7b: round-pipeline depth A/B (PR 10; ROADMAP item 2).

    Whole-round sustained throughput + enqueue→settle commit latency
    through the production BatchScheduler at ``pipeline_depth`` 1 (the
    serial pre-PR-10 program) vs 2 (round k+1's collection window,
    verification, and journal fsync overlap rounds k/k+1 on the
    device), **fsync on**: each arm journals every round to its own
    state dir with ``journal_fsync_every=1`` and checkpoints pushed out
    of the window, so the A/B prices exactly the claim — at depth 2 the
    fsync barrier overlaps device execution instead of serializing
    with it. Min-of-N interleaved at the whole-rep level (the
    vphases/sort/posmap playbook): arms alternate rep by rep so drift
    in the shared host hits both equally; per arm the best rep's
    throughput and the minimum p99 are reported. The tracer rides both
    arms and contributes the measured journal-span p99 and the bubble
    ratio. No session crypto in the loop — runs in every container."""
    import os
    import shutil
    import tempfile
    import threading

    from grapevine_tpu.config import DurabilityConfig, GrapevineConfig
    from grapevine_tpu.engine.batcher import GrapevineEngine
    from grapevine_tpu.obs.tracer import RoundTracer
    from grapevine_tpu.server.scheduler import BatchScheduler
    from grapevine_tpu.wire import constants as C
    from grapevine_tpu.wire.records import QueryRequest, RequestRecord

    cap, n_clients, per_client, batch, reps = (
        (1 << 10, 2, 6, 4, 2) if smoke else (1 << 16, 8, 48, 16, 3)
    )
    rng = np.random.default_rng(23)
    idents = rng.integers(1, 256, (n_clients, 32)).astype(np.uint8)
    recips = rng.integers(1, 256, (64, 32)).astype(np.uint8)

    def mk_req(j, i):
        return QueryRequest(
            request_type=C.REQUEST_TYPE_CREATE,
            auth_identity=idents[j].tobytes(),
            auth_signature=b"\x01" * C.SIGNATURE_SIZE,
            record=RequestRecord(
                msg_id=C.ZERO_MSG_ID,
                recipient=recips[(j * per_client + i) % len(recips)].tobytes(),
                payload=bytes([i & 0xFF]) * C.PAYLOAD_SIZE,
            ),
        )

    tmp = tempfile.mkdtemp(prefix="gv-pipeline-ab-")
    arms: dict = {}
    try:
        for depth in (1, 2):
            cfg = GrapevineConfig(
                max_messages=cap, max_recipients=1 << 10, batch_size=batch,
                bucket_cipher_rounds=0 if smoke else 8,
                pipeline_depth=depth,
            )
            dcfg = DurabilityConfig(
                state_dir=os.path.join(tmp, f"d{depth}"),
                # no checkpoint inside the timed window: the A/B prices
                # the per-round fsync, not the periodic state seal
                checkpoint_every_rounds=1 << 20,
                journal_fsync_every=1,
            )
            engine = GrapevineEngine(cfg, durability=dcfg)
            tracer = RoundTracer(capacity=2048,
                                 registry=engine.metrics.registry)
            engine.attach_tracer(tracer)
            sched = BatchScheduler(engine, clock=lambda: NOW)
            warm = sched.submit(mk_req(0, 0))  # compile outside the window
            assert warm.status_code == C.STATUS_CODE_SUCCESS
            arms[depth] = {"engine": engine, "tracer": tracer,
                           "sched": sched, "ops": 0.0, "p99": None,
                           "p50": None}

        def one_rep(arm):
            lat: list[float] = []
            errs: list = []
            lock = threading.Lock()

            def run(j):
                try:
                    for i in range(per_client):
                        req = mk_req(j, i)
                        t0 = time.perf_counter()
                        r = arm["sched"].submit(req)
                        assert r.status_code == C.STATUS_CODE_SUCCESS, (
                            r.status_code)
                        with lock:
                            lat.append(time.perf_counter() - t0)
                except Exception as e:  # pragma: no cover
                    errs.append(e)

            threads = [threading.Thread(target=run, args=(j,))
                       for j in range(n_clients)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            total = time.perf_counter() - t0
            assert not errs, errs[0]
            ops = n_clients * per_client / total
            arm["ops"] = max(arm["ops"], ops)
            p99, p50 = _p99(lat), float(np.median(lat)) * 1e3
            arm["p99"] = p99 if arm["p99"] is None else min(arm["p99"], p99)
            arm["p50"] = p50 if arm["p50"] is None else min(arm["p50"], p50)

        for _ in range(reps):  # interleaved: drift hits both arms
            for depth in (1, 2):
                one_rep(arms[depth])

        out: dict = {"batch": batch, "capacity_log2": cap.bit_length() - 1,
                     "clients": n_clients, "reps": reps, "fsync": True}
        for depth in (1, 2):
            arm = arms[depth]
            trace = arm["tracer"].chrome_trace()
            j_ms = arm["tracer"].span_durations_ms("journal")
            out[f"depth{depth}"] = {
                "ops_per_sec": round(arm["ops"], 1),
                "p99_commit_ms": round(arm["p99"], 2),
                "median_commit_ms": round(arm["p50"], 2),
                "journal_p99_ms": round(
                    float(np.percentile(j_ms, 99, method="higher")), 3)
                if j_ms else None,
                "journal_mean_ms": round(float(np.mean(j_ms)), 3)
                if j_ms else None,
                "bubble_ratio": trace["otherData"]["bubble_ratio"],
                "rounds": trace["otherData"]["rounds_recorded_total"],
            }
        d1, d2 = out["depth1"], out["depth2"]
        out["speedup_ops_d2_over_d1"] = round(
            d2["ops_per_sec"] / d1["ops_per_sec"], 3)
        out["p99_delta_ms_d1_minus_d2"] = round(
            d1["p99_commit_ms"] - d2["p99_commit_ms"], 2)
        out["model"] = _model_ab(
            "pipeline",
            "depth2" if d2["ops_per_sec"] > d1["ops_per_sec"]
            else "depth1",
        )
        return out
    finally:
        for arm in arms.values():
            arm["sched"].close()
            arm["engine"].close()
        shutil.rmtree(tmp, ignore_errors=True)


def bench_load_scenarios(smoke):
    """Config 8: the workload observatory (PR9; ROADMAP item 4's
    measurement half). Open-loop scenario suite through the production
    BatchScheduler (``submit_nowait`` — overload latency is measured,
    never self-throttled) with the workload telemetry + leak monitor
    attached, no session crypto in the loop (the ``slo_loopback``
    container-portability pattern).

    Rates are calibrated to THIS host: a warm timed round gives the
    engine's intrinsic capacity estimate, honest scenarios offer
    fractions of it, and the ramp staircases past it — so the same
    config saturates a 2-vCPU sandbox and a real TPU without hand
    tuning. The knee SLO target is ``max(250 ms, 8× the unloaded round
    time)``: the capacity question is where latency departs from the
    unloaded baseline (OPERATIONS.md §15 has the methodology).

    Hard acceptance rides inside the config (ISSUE 9): the adversarial
    probe campaign (+ the red-team leak injector — an honest engine's
    transcript cannot be flipped by traffic shape alone, which is the
    point of the FP gate) must end SUSPECT and every honest scenario
    PASS, else this config errors and ``--smoke`` fails rc!=0.

    Second pass (ISSUE 20): the same suite reruns through the
    multiprocess frontend — hostpipe pool + SLO-adaptive windows +
    flush-aware collection — against a fresh engine, with the same
    verdict acceptance plus a knee-no-worse gate vs the first pass."""
    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.engine.batcher import GrapevineEngine
    from grapevine_tpu.load import (
        ProbeCampaignInjector,
        ScenarioRunner,
        adversarial_probe,
        analyze_ramp,
        bursty_onoff,
        calibrate_unloaded_round,
        diurnal_sinusoid,
        pop_heavy_drain,
        ramp_to_saturation,
        steady_poisson,
    )
    from grapevine_tpu.obs.leakmon import EngineLeakMonitor
    from grapevine_tpu.obs.workload import WorkloadTelemetry
    from grapevine_tpu.server.scheduler import BatchScheduler

    cap, batch, dur = (1 << 10, 4, 1.5) if smoke else (1 << 14, 16, 3.0)
    pd = _pipeline_depth_arg()
    cfg = GrapevineConfig(
        max_messages=cap, max_recipients=1 << 10, batch_size=batch,
        bucket_cipher_rounds=0 if smoke else 8,
        pipeline_depth=pd,
    )
    engine = GrapevineEngine(cfg)
    wl = WorkloadTelemetry(engine.metrics.registry, batch_size=batch)
    engine.attach_workload(wl)
    # warm the jit + measure the unloaded round; est scales every
    # scenario to this host and target_ms is the knee SLO (the shared
    # formula — load/harness.py calibrate_unloaded_round)
    t_round, est, target_ms = calibrate_unloaded_round(engine, NOW)

    # --- the scenario suite, rates relative to the calibrated est -----
    pulse = max(2.0 * t_round, 0.02)
    n_steps = 4 if smoke else 5
    # ramp steps must dwarf the commit latency (itself a couple of
    # rounds): with steps shorter than the backlog's time constant, a
    # past-capacity step ends before its own arrivals' waits blow up
    # and the knee reads as "unsaturated" at an offered rate the
    # engine never sustained
    step_s = max(0.75, dur / 3.0, 12.0 * t_round)
    schedules = {
        "steady": steady_poisson(0.5 * est, dur, seed=11),
        "bursty": bursty_onoff(
            1.2 * est, duty=0.4, period_s=dur / 3.0, duration_s=dur,
            seed=12),
        "diurnal": diurnal_sinusoid(
            0.5 * est, rel_amplitude=0.8, period_s=dur / 2.0,
            duration_s=dur, seed=13),
        "pop_heavy": pop_heavy_drain(0.5 * est, dur, seed=14, n_hot=4),
        "adversarial": adversarial_probe(
            pulse, dur, seed=15, n_probe_keys=4, probes_per_pulse=2),
        "ramp": ramp_to_saturation(
            0.25 * est, factor=2.0, n_steps=n_steps, step_s=step_s,
            seed=16),
    }
    honest = ("steady", "bursty", "diurnal", "pop_heavy")
    out = {
        "scenarios": {},
        "calibrated_round_ms": round(t_round * 1e3, 2),
        # NOT named slo_target_ms: that is a GEOMETRY key for the perf
        # sentinel, and this value is perf_counter-calibrated — as
        # geometry it would make every run a fresh series and the
        # capacity numbers would never be gated at all
        "knee_target_ms": round(target_ms, 1),
        "batch": batch, "capacity_log2": cap.bit_length() - 1,
    }
    if pd is not None:
        # explicit depth reruns (the PR-10 knee-delta question) key
        # their own sentinel series; auto runs keep the PR-9 series
        # continuous by omitting the field entirely
        out["pipeline_depth"] = pd
    for name, schedule in schedules.items():
        # fresh monitor per scenario (registry=None: the engine registry
        # already carries the serving leakmon families; per-scenario
        # verdicts need fresh windows, not fresh gauges)
        mon = EngineLeakMonitor(
            mb_leaves=engine.ecfg.mb.leaves,
            rec_leaves=engine.ecfg.rec.leaves,
            mb_choices=engine.ecfg.mb_choices,
        )
        sink = (
            ProbeCampaignInjector(mon, engine.ecfg)
            if name == "adversarial" else mon
        )
        engine.attach_leakmon(sink)
        sched = BatchScheduler(engine, clock=lambda: NOW)
        try:
            runner = ScenarioRunner(sched, n_idents=64,
                                    settle_timeout_s=120.0)
            res = runner.run(schedule)
        finally:
            sched.close()
        mon.flush(30)
        v = mon.verdict()
        entry = res.summary()
        entry["leakaudit"] = v["verdict"]
        entry["leakaudit_rounds"] = v["rounds_observed"]
        rounds = mon.recorder.dump()["rounds"]
        if rounds:
            fills = [r["fill"] for r in rounds]
            depths = [r.get("queue_depth", 0) for r in rounds]
            entry["mean_fill"] = round(float(np.mean(fills)), 3)
            entry["queue_depth_p99"] = float(
                np.percentile(depths, 99, method="higher"))
        if name == "ramp":
            entry.update(analyze_ramp(schedule, res, target_ms))
            entry["knee_target_ms"] = entry.pop("target_ms")
        out["scenarios"][name] = entry
        mon.close()
        engine.attach_leakmon(None)
        print(f"[bench]   load_scenarios/{name}: "
              f"{entry.get('achieved_ops_per_sec')} ops/s, "
              f"p99 {entry.get('p99_commit_ms')} ms, "
              f"{entry['leakaudit']}", file=sys.stderr, flush=True)

    # ISSUE 9 acceptance, enforced in the config itself
    adv = out["scenarios"]["adversarial"]
    assert adv["leakaudit"] == "SUSPECT" and adv["leakaudit_rounds"] > 0, (
        f"probe campaign did not flip /leakaudit: {adv}"
    )
    for name in honest:
        h = out["scenarios"][name]
        assert h["leakaudit"] == "PASS" and h["leakaudit_rounds"] > 0, (
            f"honest scenario {name} not PASS: {h}"
        )
    assert out["scenarios"]["ramp"]["knee_ops_per_sec"] > 0, (
        f"ramp found no holding step: {out['scenarios']['ramp']}"
    )
    out["knee_ops_per_sec"] = out["scenarios"]["ramp"]["knee_ops_per_sec"]

    # --- second pass: the multiprocess frontend (ISSUE 20) ------------
    # Same engine, same calibrated schedules, but the scheduler now
    # carries the full host pipeline: a 2-worker hostpipe pool planted
    # for verify fan-out, the SLO-adaptive window policy fed by the
    # workload telemetry, and a flush-aware collection window. The
    # acceptance is behavioral, not throughput: every honest generator
    # must still PASS the leak audit (the adaptive window is driven by
    # public aggregates only — a contents-driven window would flip the
    # detectors), the probe campaign must still end SUSPECT, and the
    # knee must be no worse than the single-process same-session run.
    from grapevine_tpu.obs import TelemetryRegistry
    from grapevine_tpu.server.adaptive import AdaptiveBatchPolicy
    from grapevine_tpu.server.hostpipe import HostPipeline

    # fresh engine, same config + schedules: the first pass filled a
    # meaningful fraction of the (smoke-sized) capacity, and a knee
    # measured against a half-full tree is not comparable to one
    # against a fresh one
    engine.close()
    engine = GrapevineEngine(cfg)
    wl = WorkloadTelemetry(engine.metrics.registry, batch_size=batch)
    engine.attach_workload(wl)
    calibrate_unloaded_round(engine, NOW)  # warm the jit only; the
    # schedules keep the first pass's calibrated rates for an honest
    # same-session comparison

    pool = HostPipeline(2, registry=TelemetryRegistry())
    adaptive = AdaptiveBatchPolicy(batch, 0.008, 0.002, workload=wl)
    delayed = getattr(engine, "_flush_step", None) is not None
    hp: dict = {"scenarios": {}, "worker_count": 2, "adaptive_batch": True}
    try:
        for name, schedule in schedules.items():
            mon = EngineLeakMonitor(
                mb_leaves=engine.ecfg.mb.leaves,
                rec_leaves=engine.ecfg.rec.leaves,
                mb_choices=engine.ecfg.mb_choices,
                # the flush-cadence detector audits the soak whenever
                # delayed eviction is on: window stretches must never
                # move the flush itself
                flush_every=engine.evict_every if delayed else None,
            )
            sink = (
                ProbeCampaignInjector(mon, engine.ecfg)
                if name == "adversarial" else mon
            )
            engine.attach_leakmon(sink)
            sched = BatchScheduler(engine, clock=lambda: NOW,
                                   flush_window_ms=4.0)
            sched.hostpipe = pool
            sched.adaptive = adaptive
            try:
                runner = ScenarioRunner(sched, n_idents=64,
                                        settle_timeout_s=120.0)
                res = runner.run(schedule)
            finally:
                sched.close()
            mon.flush(30)
            v = mon.verdict()
            entry = res.summary()
            entry["leakaudit"] = v["verdict"]
            entry["leakaudit_rounds"] = v["rounds_observed"]
            if name == "ramp":
                entry.update(analyze_ramp(schedule, res, target_ms))
                entry["knee_target_ms"] = entry.pop("target_ms")
            hp["scenarios"][name] = entry
            mon.close()
            engine.attach_leakmon(None)
            print(f"[bench]   load_scenarios/hostpipe/{name}: "
                  f"{entry.get('achieved_ops_per_sec')} ops/s, "
                  f"p99 {entry.get('p99_commit_ms')} ms, "
                  f"{entry['leakaudit']}", file=sys.stderr, flush=True)
    finally:
        pool.close()

    adv = hp["scenarios"]["adversarial"]
    assert adv["leakaudit"] == "SUSPECT" and adv["leakaudit_rounds"] > 0, (
        f"probe campaign not SUSPECT through the frontend: {adv}"
    )
    for name in honest:
        h = hp["scenarios"][name]
        assert h["leakaudit"] == "PASS" and h["leakaudit_rounds"] > 0, (
            f"honest scenario {name} not PASS through the frontend: {h}"
        )
    hp["knee_ops_per_sec"] = hp["scenarios"]["ramp"]["knee_ops_per_sec"]
    assert hp["knee_ops_per_sec"] > 0, (
        f"frontend ramp found no holding step: {hp['scenarios']['ramp']}"
    )
    # "no worse" with single-core calibration noise: a real regression
    # halves the knee (a serialized window or a stalled pool); 0.7x is
    # outside rep-to-rep noise on the sandbox and inside any real break
    hp["knee_ratio_vs_inproc"] = round(
        hp["knee_ops_per_sec"] / out["knee_ops_per_sec"], 3)
    assert hp["knee_ratio_vs_inproc"] >= 0.7, (
        f"multiprocess frontend degraded the knee: {hp['knee_ratio_vs_inproc']}"
    )
    out["hostpipe_frontend"] = hp
    return out


def bench_fleet_loopback(smoke):
    """Config 9: the fleet observatory (PR16; ROADMAP items 1/4's
    measurement half). Two independent engines take a recipient-
    partitioned ramp concurrently while a real FleetAggregator —
    fetch wired straight to the two engine registries, no sockets —
    scrapes them on its fixed public cadence. Banks the per-shard
    knees and the folded fleet knee under the ``shard_count`` geometry
    key (tools/check_perf_regression.py never compares them against
    single-engine series), and asserts the fleet-grain acceptance
    inside the config: both members up in the merged view, and the
    cross-shard uniformity verdict PASS — the production scheduler
    dispatches uniformly, so a SUSPECT here is a harness or detector
    regression, not noise."""
    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.engine.batcher import GrapevineEngine
    from grapevine_tpu.load import (
        ShardedScenarioRunner,
        analyze_ramp,
        calibrate_unloaded_round,
        fleet_capacity,
        ramp_to_saturation,
    )
    from grapevine_tpu.obs.exporter import render_prometheus
    from grapevine_tpu.obs.fleet import (
        FleetAggregator,
        FleetConfig,
        _sample_value,
    )
    from grapevine_tpu.obs.workload import WorkloadTelemetry
    from grapevine_tpu.server.scheduler import BatchScheduler

    n_shards = 2
    cap, batch, dur = (1 << 10, 4, 1.5) if smoke else (1 << 13, 8, 3.0)
    cfg = GrapevineConfig(
        max_messages=cap, max_recipients=1 << 10, batch_size=batch,
        bucket_cipher_rounds=0 if smoke else 8,
    )
    engines = [GrapevineEngine(cfg) for _ in range(n_shards)]
    # workload telemetry per shard: the fill histogram is both the
    # uniformity monitor's fill series and the banked per-shard stat
    for e in engines:
        e.attach_workload(
            WorkloadTelemetry(e.metrics.registry, batch_size=batch))
    # solo calibration (warms every shard's jit), then a barrier-synced
    # CONTENDED round: all shards commit one round at the same instant,
    # which is what steady-state fleet replay looks like. On shared
    # silicon (this CPU sandbox) the contended round is ~n_shards x the
    # solo one and the knee target must be rated against it, or the
    # ramp's first step already misses; on a real fleet (one chip per
    # shard) contended == solo and this degenerates to the §15 formula
    import threading as _threading

    from grapevine_tpu.load.generators import CREATE
    from grapevine_tpu.load.harness import identity_pool
    from grapevine_tpu.wire import constants as C
    from grapevine_tpu.wire.records import QueryRequest, RequestRecord

    for e in engines:
        calibrate_unloaded_round(e, NOW)
    idents = identity_pool(8)
    calib_reqs = [
        QueryRequest(
            request_type=CREATE, auth_identity=idents[i % 8],
            auth_signature=b"\x01" * C.SIGNATURE_SIZE,
            record=RequestRecord(
                msg_id=C.ZERO_MSG_ID, recipient=idents[(i + 1) % 8],
                payload=bytes([i & 0xFF]) * C.PAYLOAD_SIZE))
        for i in range(batch)
    ]
    barrier = _threading.Barrier(n_shards)
    times: list = [[] for _ in range(n_shards)]

    def _contended(i):
        for _ in range(3):
            barrier.wait()
            t0 = time.perf_counter()
            engines[i].handle_queries(calib_reqs, NOW)
            times[i].append(time.perf_counter() - t0)

    threads = [
        _threading.Thread(target=_contended, args=(i,))
        for i in range(n_shards)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # min over reps of the slowest shard: the steady contended round
    t_round = min(max(ts[k] for ts in times) for k in range(3))
    est = batch / t_round  # per-shard contended capacity
    target_ms = max(250.0, 8.0 * t_round * 1e3)

    registries = [e.metrics.registry for e in engines]

    def loopback_fetch(url: str, timeout_s: float) -> bytes:
        addr, _, path = url.split("//")[1].partition("/")
        shard = int(addr.split(":")[0].removeprefix("shard"))
        if path == "metrics":
            return render_prometheus(registries[shard]).encode()
        return b""  # aux endpoints absent in-process: best-effort

    agg = FleetAggregator(
        FleetConfig(
            members=tuple(f"shard{i}:1" for i in range(n_shards)),
            scrape_interval_s=max(0.05, 2.0 * t_round),
        ),
        fetch=loopback_fetch,
    )
    n_steps = 4 if smoke else 5
    step_s = max(0.75, dur / 3.0, 12.0 * t_round)
    # each shard walks the single-engine staircase against its
    # CONTENDED capacity (fleet offered rate = n_shards x per-shard)
    schedule = ramp_to_saturation(
        0.25 * est * n_shards, factor=2.0, n_steps=n_steps,
        step_s=step_s, seed=17)
    scheds = [BatchScheduler(e, clock=lambda: NOW) for e in engines]
    agg.start()
    try:
        runner = ShardedScenarioRunner(scheds, n_idents=64,
                                       settle_timeout_s=120.0)
        results = runner.run(schedule)
    finally:
        agg.stop()
        for s in scheds:
            s.close()
    agg.scrape_once()  # final aligned sample after the drain
    analyses = [
        analyze_ramp(r.schedule, r, target_ms) for r in results
    ]
    fleet = fleet_capacity(analyses)
    uv = agg.uniformity.verdict()
    merged = agg.render_merged()
    # per-shard fill/cadence stats off the aggregator's final scrape —
    # the same public series the uniformity detectors consume
    for i, shard_out in enumerate(fleet["shards"]):
        fams = agg._members[i].families or {}
        rounds = _sample_value(
            fams, "grapevine_rounds_total", default=0.0)
        fill_sum = _sample_value(
            fams, "grapevine_load_batch_fill",
            "grapevine_load_batch_fill_sum", 0.0)
        fill_count = _sample_value(
            fams, "grapevine_load_batch_fill",
            "grapevine_load_batch_fill_count", 0.0)
        shard_out["rounds_total"] = int(rounds)
        shard_out["mean_fill"] = (
            round(fill_sum / fill_count, 3) if fill_count else None
        )
    out = {
        "shard_count": n_shards,
        "fleet_knee_ops_per_sec": fleet["fleet_knee_ops_per_sec"],
        "saturated": fleet["saturated"],
        "shards": fleet["shards"],
        "uniformity": uv["verdict"],
        "uniformity_window_ticks": uv["window_ticks"],
        "calibrated_round_ms": round(t_round * 1e3, 2),
        "knee_target_ms": round(target_ms, 1),
        "batch": batch, "capacity_log2": cap.bit_length() - 1,
    }
    # fleet-grain acceptance rides inside the config (ISSUE 16)
    assert all(st.up for st in agg._members), "member down in loopback"
    for i in range(n_shards):
        assert f'grapevine_rounds_total{{shard="{i}"}}' in merged, (
            f"shard {i} missing from merged view"
        )
    assert uv["verdict"] == "PASS", f"uniform fleet graded SUSPECT: {uv}"
    assert out["fleet_knee_ops_per_sec"] > 0, f"no fleet knee: {out}"
    print(f"[bench]   fleet_loopback: fleet knee "
          f"{out['fleet_knee_ops_per_sec']} ops/s over {n_shards} shards "
          f"(uniformity {uv['verdict']})", file=sys.stderr, flush=True)
    return out


def bench_failover_ab(smoke):
    """Config: hot-standby failover — measured RTO vs durable-tail
    length (ISSUE 19 acceptance: RTO ≤ tail-replay of one checkpoint
    interval, RPO 0 for durable frames).

    One primary engine ships its sealed journal to an in-process
    ``StandbyReplica`` over the real socket transport
    (engine/replication.py). The standby catches up live; then the
    link is cut, the primary appends a controlled durable tail of
    exactly ``tail_frames`` journal records past the standby's applied
    seq, and ``promote()`` is timed: fence plant + tail drain + pending
    flush completion + fsync. Three tails — empty (pure fencing floor),
    E·4 (a few flush windows), and one full checkpoint interval (the
    worst legal tail: any longer and the standby would bootstrap from
    the next checkpoint instead). RPO is asserted, not claimed: the
    promoted state must hash bit-identical to the dead primary's.

    ``tail_frames`` (the checkpoint interval) is the geometry key:
    trajectory lines at different intervals are different experiments,
    never graded against each other (tools/check_perf_regression.py)."""
    import hashlib as _hashlib
    import os
    import tempfile as _tempfile

    from grapevine_tpu.config import DurabilityConfig, GrapevineConfig
    from grapevine_tpu.engine.batcher import GrapevineEngine
    from grapevine_tpu.engine.checkpoint import state_to_bytes
    from grapevine_tpu.engine.replication import JournalShipper, StandbyReplica
    from grapevine_tpu.load.harness import identity_pool
    from grapevine_tpu.wire import constants as C
    from grapevine_tpu.wire.records import QueryRequest, RequestRecord

    batch = 4
    evict_every = 2
    ckpt_interval = 12 if smoke else 32
    cfg = GrapevineConfig(
        max_messages=64, max_recipients=8, mailbox_cap=4,
        batch_size=batch, stash_size=64, bucket_cipher_rounds=0,
        evict_every=evict_every,
    )
    idents = identity_pool(8)

    def _reqs(i):
        return [
            QueryRequest(
                request_type=C.REQUEST_TYPE_CREATE,
                auth_identity=idents[(i + j) % 8],
                auth_signature=b"\x01" * C.SIGNATURE_SIZE,
                record=RequestRecord(
                    msg_id=C.ZERO_MSG_ID,
                    recipient=idents[(i + j + 1) % 8],
                    payload=bytes([(i + j) & 0xFF]) * C.PAYLOAD_SIZE))
            for j in range(batch)
        ]

    tails = {
        "rto_empty_tail_ms": 0,
        "rto_e4_tail_ms": evict_every * 4,
        "rto_full_tail_ms": ckpt_interval,
    }
    out = {"tail_frames": ckpt_interval, "evict_every": evict_every,
           "rpo_frames": 0}
    for metric, tail in tails.items():
        with _tempfile.TemporaryDirectory(prefix="bench-failover-") as root:
            pdir = os.path.join(root, "primary")
            sdir = os.path.join(root, "standby")
            os.makedirs(pdir)
            os.makedirs(sdir)
            # replication's standing requirement: a shared root seal key
            key = bytes(range(32))
            for d in (pdir, sdir):
                with open(os.path.join(d, "root.key"), "wb") as fh:
                    fh.write(key)
                os.chmod(os.path.join(d, "root.key"), 0o600)
            # manual checkpoint control: the interval IS the experiment
            big = 1 << 20
            primary = GrapevineEngine(cfg, seed=7, durability=DurabilityConfig(
                state_dir=pdir, checkpoint_every_rounds=big,
                journal_fsync_every=1))
            replica = StandbyReplica(cfg, seed=7, durability=DurabilityConfig(
                state_dir=sdir, checkpoint_every_rounds=big,
                journal_fsync_every=1))
            port = replica.listen()
            shipper = JournalShipper(primary, f"127.0.0.1:{port}")
            shipper.start()
            # live catch-up phase: a few warm rounds through the wire
            now = NOW
            for i in range(4):
                primary.handle_queries(_reqs(i), now)
                now += 1
            deadline = time.monotonic() + 30.0
            while (replica.dm.applied_seq < primary.durability.seq
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            assert replica.dm.applied_seq == primary.durability.seq, (
                f"standby never caught up: {replica.dm.applied_seq} < "
                f"{primary.durability.seq}"
            )
            # cut the link, then append exactly ``tail`` durable frames
            shipper.close()
            i = 4
            while primary.durability.seq - replica.dm.applied_seq < tail:
                primary.handle_queries(_reqs(i), now)
                now += 1
                i += 1
            dead_seq = primary.durability.seq
            dead_hash = _hashlib.sha256(
                state_to_bytes(primary.ecfg, primary.state)
            ).hexdigest()
            primary.close()
            info = replica.promote(primary_state_dir=pdir)
            live_hash = _hashlib.sha256(
                state_to_bytes(replica.engine.ecfg, replica.engine.state)
            ).hexdigest()
            # RPO 0 for durable frames, bit for bit — asserted inside
            # the config so a regression fails the bench, not just a
            # number drifting
            assert replica.dm.applied_seq == dead_seq, (
                f"promoted replica at seq {replica.dm.applied_seq}, "
                f"primary died at {dead_seq}"
            )
            assert live_hash == dead_hash, (
                "promoted state is not bit-identical to the dead primary"
            )
            assert info["drained_frames"] >= tail - evict_every, (
                f"tail drain too short: {info['drained_frames']} < ~{tail}"
            )
            out[metric] = round(info["rto_seconds"] * 1e3, 2)
            replica.close()
    assert out["rto_full_tail_ms"] < 60_000, (
        f"full-interval tail replay blew the RTO budget: {out}"
    )
    print(f"[bench]   failover_ab: rto empty/{tails['rto_e4_tail_ms']}f/"
          f"{out['tail_frames']}f = {out['rto_empty_tail_ms']}/"
          f"{out['rto_e4_tail_ms']}/{out['rto_full_tail_ms']} ms "
          f"(rpo 0, bit-identical)", file=sys.stderr, flush=True)
    return out


# Headline config FIRST: if the run later hits a budget wall or the
# driver's own timeout, the metric that matters is already captured
# (VERDICT r3, next-round #1b).
CONFIGS = [
    ("zipf_mixed", bench_zipf_mixed),
    ("batched_read", bench_batched_read),
    ("zipf_pallas_cipher", bench_zipf_pallas),
    ("zipf_pallas_fused", lambda smoke: bench_zipf_pallas(smoke, "pallas_fused")),
    ("zipf_pallas_tiled",
     lambda smoke: bench_zipf_pallas(smoke, "pallas_fused_tiled")),
    ("crd_loop", bench_crd_loop),
    ("vphases_ab", bench_vphases_ab),
    ("sort_ab", bench_sort_ab),
    ("posmap_ab", bench_posmap_ab),
    ("tree_cache_ab", bench_tree_cache_ab),
    ("evict_ab", bench_evict_ab),
    ("expiry_sweep", bench_expiry_sweep),
    ("sharded", bench_sharded),
    ("sharded_evict_ab", bench_sharded_evict_ab),
    ("server_loopback", bench_server_loopback),
    ("host_pipeline_ab", bench_host_pipeline_ab),
    ("slo_loopback", bench_slo_loopback),
    ("pipeline_ab", bench_pipeline_ab),
    ("load_scenarios", bench_load_scenarios),
    ("fleet_loopback", bench_fleet_loopback),
    ("failover_ab", bench_failover_ab),
]


def _probe_backend(timeout_s: float):
    """Prove the default backend initializes AND runs a computation.

    In a subprocess, so a wedged backend init (r3: the axon relay
    burned 1,505 s inside ``crd_loop`` before erroring) can never hang
    the bench itself. Returns (backend_name, None) or (None, error).
    """
    import os
    import subprocess

    code = (
        "import jax, jax.numpy as jnp\n"
        "x = jnp.ones((256, 256), jnp.float32)\n"
        "(x @ x).block_until_ready()\n"
        "print('PROBE_OK', jax.default_backend(), jax.devices()[0].platform)\n"
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout_s, cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return None, f"backend probe timed out after {timeout_s:.0f}s"
    except Exception as e:  # pragma: no cover
        return None, f"backend probe failed to launch: {type(e).__name__}: {e}"
    for line in out.stdout.splitlines():
        if line.startswith("PROBE_OK"):
            return line.split()[1], None
    return None, f"backend probe rc={out.returncode}: {out.stderr[-300:]!r}"


class _ConfigTimeout(Exception):
    pass


def _run_capped(fn, smoke: bool, cap_s: float):
    """Run one config under a SIGALRM cap. The benches loop in Python
    between device dispatches, so the alarm lands between iterations;
    a truly wedged C call is instead covered by the probe (init) and by
    snapshot emission (the last stdout line stays parseable)."""
    import signal

    def _handler(signum, frame):
        raise _ConfigTimeout(f"config exceeded {cap_s:.0f}s cap")

    old = signal.signal(signal.SIGALRM, _handler)
    signal.alarm(max(1, int(cap_s)))
    try:
        return fn(smoke)
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def _emit(results, meta):
    """Print the full result JSON as one line. Called after EVERY
    config: if the driver kills the process mid-run, the last complete
    stdout line is still a parseable snapshot with the configs that
    finished — never again an empty ``parsed: null`` artifact."""
    headline = results.get("zipf_mixed", {}).get("ops_per_sec", 0.0)
    line = {
        "metric": "oblivious_crud_ops_per_sec",
        "value": headline,
        "unit": "ops/s",
        "vs_baseline": round(headline / 1_000_000, 6),
        "configs": results,
    }
    line.update(meta)
    sys.stdout.write(json.dumps(line) + "\n")
    sys.stdout.flush()
    return line


def _pr_tag() -> str:
    """The PR tag for the trajectory line: ``--pr TAG`` (or ``--pr=TAG``)
    on the command line, else $GRAPEVINE_PR, else empty."""
    import os

    val = _argv_flag_value("--pr")
    return val if val is not None else os.environ.get("GRAPEVINE_PR", "")


def _append_trajectory(line: dict, tag: str) -> None:
    """Append the final result line to BENCH_trajectory.jsonl next to
    this file, so the perf trajectory accumulates across PRs instead of
    living only in per-run artifacts. Best-effort: a read-only checkout
    must not fail the bench itself."""
    import os

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_trajectory.jsonl"
    )
    entry = {"ts": int(time.time()), "pr": tag, **line}
    try:
        with open(path, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(entry) + "\n")
    except OSError as e:
        print(f"[bench] trajectory append failed: {e}", file=sys.stderr)


def _argv_flag_value(name: str) -> str | None:
    """Last value of ``--name V`` / ``--name=V`` on the command line,
    or None — the one token scan shared by the bench's ad-hoc flags
    (``--pr``, ``--only``, ``--pipeline-depth``)."""
    argv = sys.argv[1:]
    val = None
    for i, tok in enumerate(argv):
        if tok == name and i + 1 < len(argv):
            val = argv[i + 1]
        elif tok.startswith(name + "="):
            val = tok[len(name) + 1:]
    return val


def _pipeline_depth_arg() -> int | None:
    """``--pipeline-depth N`` (or ``=N``): run the scheduler-driven
    configs (load_scenarios) at an explicit round-pipeline depth — the
    ISSUE-10 knee-delta rerun — instead of the engine auto."""
    val = _argv_flag_value("--pipeline-depth")
    if val is None:
        return None
    try:
        return int(val)
    except ValueError:
        raise SystemExit(
            f"--pipeline-depth: want an integer depth, got {val!r}"
        ) from None


def _only_filter() -> list | None:
    """``--only a,b`` (or ``--only=a,b``): run just those configs — for
    banking one config's line (e.g. a PR's A/B) without paying the full
    suite on a weak builder core. Unknown names fail fast."""
    val = _argv_flag_value("--only")
    if val is None:
        return None
    names = [n.strip() for n in val.split(",") if n.strip()]
    known = {n for n, _ in CONFIGS}
    bad = [n for n in names if n not in known]
    if bad:
        raise SystemExit(f"--only: unknown config(s) {bad}; known: {sorted(known)}")
    return names


def main():
    import os

    smoke = "--smoke" in sys.argv
    budget_s = float(os.environ.get("GRAPEVINE_BENCH_BUDGET_S", "1500"))
    per_cfg_env = os.environ.get("GRAPEVINE_BENCH_CONFIG_S")
    per_cfg_s = float(per_cfg_env) if per_cfg_env else 420.0
    # persistent XLA compilation cache, shared with tools/tpu_capture.py:
    # full-size TPU compiles cost minutes through the relay's one weak
    # core; if the probe loop's capture already compiled these programs
    # during the same session, the driver bench must not pay twice
    from grapevine_tpu.config import JAX_CACHE_DIR

    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", JAX_CACHE_DIR)
    t_start = time.perf_counter()
    results: dict = {}
    meta: dict = {"sizes": "smoke" if smoke else "full"}
    strict_smoke = smoke
    # a parseable line exists from t=0 — BEFORE anything imports jax in
    # this process: a wedged site hook can stall `import jax` itself for
    # minutes (the r3 empty-artifact failure mode), and the emit must
    # not be behind that risk
    _emit(results, meta)
    if smoke:
        # smoke mode must not grab (or wait on) TPU hardware; the env var
        # alone loses to platform-pinning plugin hooks, so pin via config
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        meta["backend"] = "cpu"
    else:
        backend, err = _probe_backend(float(os.environ.get(
            "GRAPEVINE_BENCH_PROBE_S", "300")))
        if backend is None:
            # Fail fast: do NOT let all seven configs rediscover the
            # outage serially (r3 rc=124). Pin CPU and run smoke sizes
            # so the artifact still carries data, flagged as fallback.
            meta.update(backend="cpu-fallback", probe_error=err,
                        sizes="smoke")
            smoke = True
            _emit(results, meta)  # fallback line lands pre-import too
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax

            jax.config.update("jax_platforms", "cpu")
            print(f"[bench] PROBE FAILED ({err}); cpu-fallback smoke run",
                  file=sys.stderr, flush=True)
        else:
            meta["backend"] = backend
            from grapevine_tpu.config import TPU_BACKENDS

            if backend in TPU_BACKENDS and not per_cfg_env:
                # cold full-size compiles through the relay's one weak
                # core can alone approach the CPU-tuned 420s cap; with a
                # real device the headline-first ordering makes a longer
                # leash the right trade (explicit env still wins)
                per_cfg_s = 900.0
    _emit(results, meta)
    only = _only_filter()
    configs = (
        CONFIGS if only is None
        else [(n, f) for n, f in CONFIGS if n in only]
    )
    for name, fn in configs:
        elapsed = time.perf_counter() - t_start
        if elapsed > budget_s:
            results[name] = {"skipped":
                             f"global budget {budget_s:.0f}s exhausted"}
            _emit(results, meta)
            continue
        cap = min(per_cfg_s, max(60.0, budget_s - elapsed))
        t0 = time.perf_counter()
        try:
            results[name] = _run_capped(fn, smoke, cap)
        except Exception as e:  # one config must not sink the others
            results[name] = {"error": f"{type(e).__name__}: {e}"}
        print(f"[bench] {name}: {results[name]} ({time.perf_counter()-t0:.1f}s)",
              file=sys.stderr, flush=True)
        _emit(results, meta)
    line = _emit(results, meta)
    # trajectory first, assert after: a failed config must still leave
    # its line in the cross-PR record (the artifact tells the story)
    _append_trajectory(line, _pr_tag())
    if strict_smoke:
        for name, r in results.items():
            assert "error" not in r, f"{name} failed in smoke mode: {r}"


if __name__ == "__main__":
    main()
