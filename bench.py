#!/usr/bin/env python3
"""Headline benchmark: oblivious CRUD throughput of the batched engine.

Mixed create/read/update/delete batches against a 2^16-message bus
(BASELINE configs 1-3 territory), run on whatever backend JAX selects
(the real TPU chip under the driver). Prints ONE JSON line:

    {"metric": "oblivious_crud_ops_per_sec", "value": N,
     "unit": "ops/s", "vs_baseline": N / 1e6}

``vs_baseline`` is measured against the BASELINE.json north-star target
of 1M oblivious CRUD ops/sec (v5e-8 at 2^24 buckets); the reference
itself publishes no numbers (BASELINE.md).
"""

from __future__ import annotations

import json
import time

import numpy as np


def make_batches(n_batches: int, batch_size: int, seed: int = 7):
    from grapevine_tpu.engine.state import ID_WORDS, KEY_WORDS, PAYLOAD_WORDS

    rng = np.random.default_rng(seed)
    idents = rng.integers(1, 2**31, (64, KEY_WORDS)).astype(np.uint32)
    batches = []
    for _ in range(n_batches):
        b = batch_size
        rt = rng.choice(
            np.array([1, 1, 2, 2, 3, 4], np.uint32), size=b
        )  # create-heavy mix; zero-id reads/deletes pop mailboxes
        auth = idents[rng.integers(0, len(idents), b)]
        recipient = idents[rng.integers(0, len(idents), b)]
        msg_id = np.zeros((b, ID_WORDS), np.uint32)
        explicit = rt == 3  # UPDATE needs nonzero id (grapevine.proto:95)
        msg_id[explicit] = rng.integers(1, 2**31, (int(explicit.sum()), ID_WORDS))
        batches.append(
            {
                "req_type": rt,
                "auth": auth,
                "msg_id": msg_id,
                "recipient": recipient,
                "payload": rng.integers(0, 2**31, (b, PAYLOAD_WORDS)).astype(
                    np.uint32
                ),
                "now": np.uint32(1_700_000_000),
            }
        )
    return batches


def main():
    import jax

    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.engine.state import EngineConfig, init_engine
    from grapevine_tpu.engine.round_step import engine_round_step

    cfg = GrapevineConfig(
        max_messages=1 << 16,
        max_recipients=1 << 12,
        batch_size=64,
        stash_size=128,
    )
    ecfg = EngineConfig.from_config(cfg)
    state = init_engine(ecfg, seed=0)
    step = jax.jit(engine_round_step, static_argnums=(0,), donate_argnums=(1,))

    batches = make_batches(8, cfg.batch_size)

    # warmup: compile + first dispatch
    state, resp, _ = step(ecfg, state, batches[0])
    jax.block_until_ready(resp)

    n_rounds = 16
    t0 = time.perf_counter()
    for i in range(n_rounds):
        state, resp, _ = step(ecfg, state, batches[i % len(batches)])
    jax.block_until_ready(resp)
    dt = time.perf_counter() - t0

    # a run that overflowed the stash (dropped blocks) is not a valid number
    overflow = int(np.asarray(state.rec.overflow)) + int(np.asarray(state.mb.overflow))
    assert overflow == 0, f"stash overflow during bench: {overflow}"

    ops = n_rounds * cfg.batch_size
    value = ops / dt
    print(
        json.dumps(
            {
                "metric": "oblivious_crud_ops_per_sec",
                "value": round(value, 2),
                "unit": "ops/s",
                "vs_baseline": round(value / 1_000_000, 6),
            }
        )
    )


if __name__ == "__main__":
    main()
