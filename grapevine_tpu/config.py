"""Configuration for the grapevine-tpu engine.

The reference fixes its knobs as compile-time constants (record size,
62-message mailbox cap, reference README.md:78-80,137-139) plus CLI flags
(expiry period, reference README.md:90). Here everything lives in one
dataclass; the device-engine geometry (tree heights, bucket slots, stash
size, batch size) are the TPU analogs of "how much EPC the enclave maps".

Capacity story: the records store is a Path-ORAM bucket tree with
``2**records_height`` leaves and a dense block space of the same size; the
mailbox store is a single-choice keyed-hash table (K mailboxes per bucket)
over its own Path-ORAM, run at low load so bucket overflow is negligible.
Maximum in-flight messages = ``max_messages`` (bounded by the free-block
list); maximum distinct recipients with mail = ``max_recipients`` (also
soft-bounded by table load; overflow reports TOO_MANY_RECIPIENTS).
"""

from __future__ import annotations

import dataclasses
import math

from .wire import constants as C


@dataclasses.dataclass(frozen=True)
class GrapevineConfig:
    # --- semantic capacities -------------------------------------------
    #: max in-flight messages on the bus (reference README.md:75-76)
    max_messages: int = 1 << 14
    #: max distinct recipients with in-flight messages
    max_recipients: int = 1 << 12
    #: per-recipient in-flight cap (reference README.md:78-80)
    mailbox_cap: int = C.MAILBOX_CAP
    #: message expiry period in seconds; 0 disables (reference README.md:86-98)
    expiry_period: int = 0

    # --- device engine geometry ----------------------------------------
    #: Path-ORAM bucket capacity (Z); upstream mc-oblivious uses Z=4 with
    #: 4096B buckets of 1024B blocks (SURVEY.md §7.4)
    bucket_slots: int = 4
    #: fixed stash slots per ORAM (overflow is a sticky internal error)
    stash_size: int = 96
    #: client ops per jit'd access round; host pads with dummy ops
    batch_size: int = 8
    #: mailboxes per hash bucket (one bucket = one mailbox-ORAM block)
    mailbox_slots: int = 4
    #: within-batch commit schedule: "phase" = phase-major batched rounds
    #: (engine/round_step.py — the production path: one path fetch per
    #: ORAM round instead of one per op), "op" = op-major sequential
    #: commits (engine/step.py — the original reference-shaped engine).
    #: Identical semantics for single-op batches; batch-hazard semantics
    #: documented in round_step.py.
    commit: str = "phase"

    def __post_init__(self):
        if self.commit not in ("phase", "op"):
            raise ValueError(
                f"commit must be 'phase' or 'op', got {self.commit!r}"
            )
    #: per-slot load target; table buckets = ceil(
    #: max_recipients / (mailbox_slots * mailbox_load)). Low load keeps the
    #: single-choice hash table's overflow probability negligible; a
    #: relocating cuckoo scheme is a planned later optimization.
    mailbox_load: float = 0.125

    @property
    def records_height(self) -> int:
        """Tree height of the records ORAM: leaves = 2**height >= max_messages."""
        return max(1, math.ceil(math.log2(self.max_messages)))

    @property
    def records_leaves(self) -> int:
        return 1 << self.records_height

    @property
    def mailbox_table_buckets(self) -> int:
        """Hash table size (power of two) for the mailbox map."""
        want = max(
            2, math.ceil(self.max_recipients / (self.mailbox_slots * self.mailbox_load))
        )
        return 1 << max(1, math.ceil(math.log2(want)))

    @property
    def mailbox_height(self) -> int:
        """Tree height of the mailbox ORAM: block space = hash-table buckets."""
        return max(1, math.ceil(math.log2(self.mailbox_table_buckets)))

    @property
    def mailbox_leaves(self) -> int:
        return 1 << self.mailbox_height


DEFAULT_CONFIG = GrapevineConfig()
