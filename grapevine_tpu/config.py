"""Configuration for the grapevine-tpu engine.

The reference fixes its knobs as compile-time constants (record size,
62-message mailbox cap, reference README.md:78-80,137-139) plus CLI flags
(expiry period, reference README.md:90). Here everything lives in one
dataclass; the device-engine geometry (tree heights, bucket slots, stash
size, batch size) are the TPU analogs of "how much EPC the enclave maps".

Capacity story: the records store is a Path-ORAM bucket tree with
``2**records_height`` leaves and a dense block space of the same size; the
mailbox store is a keyed two-choice hash table (K mailboxes per bucket)
over its own Path-ORAM, run at a load where bucket overflow is negligible.
Maximum in-flight messages = ``max_messages`` (bounded by the free-block
list); maximum distinct recipients with mail = ``max_recipients`` (also
soft-bounded by table load; overflow reports TOO_MANY_RECIPIENTS).
"""

from __future__ import annotations

import dataclasses
import math
import os as _os

from .wire import constants as C

#: backend names that mean "a real TPU executes the program" (Mosaic
#: compiles, interpret mode off): the direct PJRT plugin reports
#: "tpu"; the axon relay tunnel reports "axon" (BENCH_r02.json tail)
#: while still driving one real chip.
TPU_BACKENDS = ("tpu", "axon")

#: persistent XLA compilation-cache dir shared by bench.py and
#: tools/tpu_capture.py — full-size TPU compiles cost minutes through
#: the relay's one weak core, and the capture and the driver bench must
#: never pay for the same program twice in one session
JAX_CACHE_DIR = _os.environ.get("GRAPEVINE_JAX_CACHE", "/tmp/jax_cache_r5")


@dataclasses.dataclass(frozen=True)
class GrapevineConfig:
    # --- semantic capacities -------------------------------------------
    #: max in-flight messages on the bus (reference README.md:75-76)
    max_messages: int = 1 << 14
    #: max distinct recipients with in-flight messages
    max_recipients: int = 1 << 12
    #: per-recipient in-flight cap (reference README.md:78-80)
    mailbox_cap: int = C.MAILBOX_CAP
    #: message expiry period in seconds; 0 disables (reference README.md:86-98)
    expiry_period: int = 0

    # --- device engine geometry ----------------------------------------
    #: Path-ORAM bucket capacity (Z); upstream mc-oblivious uses Z=4 with
    #: 4096B buckets of 1024B blocks (SURVEY.md §7.4)
    bucket_slots: int = 4
    #: fixed stash slots per ORAM (overflow is a sticky internal error)
    stash_size: int = 96
    #: client ops per jit'd access round; host pads with dummy ops
    batch_size: int = 8
    #: mailboxes per hash bucket (one bucket = one mailbox-ORAM block)
    mailbox_slots: int = 4
    #: within-batch commit schedule: "phase" = phase-major batched rounds
    #: (engine/round_step.py — the production path: one path fetch per
    #: ORAM round instead of one per op), "op" = op-major sequential
    #: commits (engine/step.py — the original reference-shaped engine).
    #: Identical semantics for single-op batches; batch-hazard semantics
    #: documented in round_step.py.
    commit: str = "phase"
    #: ChaCha rounds for at-rest bucket-tree encryption in HBM — the EPC
    #: analog (oblivious/bucket_cipher.py). 8 = ChaCha8 (default),
    #: 20 = RFC ChaCha20, 0 = plaintext trees.
    bucket_cipher_rounds: int = 8
    #: cipher implementation: "jnp" (XLA, keystream materialized in
    #: HBM), "pallas" (fused VMEM keystream+XOR kernel,
    #: oblivious/pallas_cipher.py), "pallas_fused" ("pallas" plus the
    #: path fetch fused into the decrypt — one HBM pass per fetched row,
    #: oblivious/pallas_gather.py; single-chip fetches only, the sharded
    #: path keeps decrypt-after-psum so plaintext never transits ICI),
    #: or "pallas_fused_tiled" (same contract, 8 rows per grid step via
    #: manual HBM->VMEM DMAs — amortizes grid overhead and fills the
    #: ChaCha tile's sublanes). Interpret mode off-TPU; bit-identical
    #: ciphertext in all four.
    bucket_cipher_impl: str = "jnp"
    #: per-request signature scheme: "schnorrkel" (sr25519, byte-compatible
    #: with the reference's sign_schnorrkel clients — README.md:193-199,
    #: session/schnorrkel.py) or "rfc9496" (the same-shape plain Schnorr
    #: this repo shipped first, session/ristretto.py). Server and clients
    #: must agree.
    signature_scheme: str = "schnorrkel"

    def __post_init__(self):
        if self.commit not in ("phase", "op"):
            raise ValueError(
                f"commit must be 'phase' or 'op', got {self.commit!r}"
            )
        # 0 = plaintext; otherwise an even round count ≥ 8 (ChaCha rounds
        # come in column+diagonal pairs; odd values would silently floor,
        # and rounds < 8 have no security story — a 0-round "cipher"
        # exposes 2*key in every keystream block)
        r = self.bucket_cipher_rounds
        if r != 0 and (r < 8 or r % 2 != 0):
            raise ValueError(
                f"bucket_cipher_rounds must be 0 or an even value >= 8, got {r}"
            )
        if self.bucket_cipher_impl not in (
            "jnp", "pallas", "pallas_fused", "pallas_fused_tiled"
        ):
            raise ValueError(
                f"bucket_cipher_impl must be 'jnp', 'pallas', "
                f"'pallas_fused' or 'pallas_fused_tiled', got "
                f"{self.bucket_cipher_impl!r}"
            )
        if self.signature_scheme not in ("schnorrkel", "rfc9496"):
            raise ValueError(
                f"signature_scheme must be 'schnorrkel' or 'rfc9496', got "
                f"{self.signature_scheme!r}"
            )
        if self.vphases_impl not in (None, "dense", "scan"):
            raise ValueError(
                f"vphases_impl must be None, 'dense' or 'scan', got "
                f"{self.vphases_impl!r}"
            )
        if self.sort_impl not in (None, "xla", "radix"):
            raise ValueError(
                f"sort_impl must be None, 'xla' or 'radix', got "
                f"{self.sort_impl!r}"
            )
        if self.max_messages < 2 or self.max_messages & (self.max_messages - 1):
            raise ValueError("max_messages must be a power of two >= 2")
        if self.tree_density not in (1, 2, 4):
            raise ValueError(
                f"tree_density must be 1, 2, or 4, got {self.tree_density}"
            )
        if self.mailbox_choices not in (None, 1, 2):
            raise ValueError(
                f"mailbox_choices must be None, 1 or 2, got "
                f"{self.mailbox_choices}"
            )
        if self.commit == "op" and self.mailbox_choices == 2:
            raise ValueError(
                "commit='op' (the differential-oracle engine) supports "
                "only mailbox_choices=1"
            )
        if self.posmap_impl not in (None, "flat", "recursive"):
            raise ValueError(
                f"posmap_impl must be None, 'flat' or 'recursive', got "
                f"{self.posmap_impl!r}"
            )
        if self.commit == "op" and self.posmap_impl == "recursive":
            raise ValueError(
                "commit='op' (the differential-oracle engine) supports "
                "only posmap_impl='flat' — the recursive position map "
                "rides the phase-major batched round"
            )
        tc = self.tree_top_cache_levels
        if tc is not None and (not isinstance(tc, int) or tc < 0):
            raise ValueError(
                f"tree_top_cache_levels must be None (auto) or an int "
                f">= 0, got {tc!r}"
            )
        if self.pipeline_depth not in (None, 1, 2):
            raise ValueError(
                f"pipeline_depth must be None (auto), 1 or 2, got "
                f"{self.pipeline_depth!r}"
            )
        ee = self.evict_every
        if ee is not None and (not isinstance(ee, int) or ee < 1):
            raise ValueError(
                f"evict_every must be None (auto) or an int >= 1, got "
                f"{ee!r}"
            )
        if self.commit == "op" and ee not in (None, 1):
            raise ValueError(
                "commit='op' (the differential-oracle engine) supports "
                "only evict_every=1 — delayed batched eviction rides the "
                "phase-major batched round"
            )
        ebs = self.evict_buffer_slots
        if ebs is not None and (not isinstance(ebs, int) or ebs < 1):
            raise ValueError(
                f"evict_buffer_slots must be None (auto) or an int >= 1, "
                f"got {ebs!r}"
            )
        if self.commit == "op" and tc not in (None, 0):
            raise ValueError(
                "commit='op' (the differential-oracle engine) supports "
                "only tree_top_cache_levels=0 — the tree-top cache "
                "rides the phase-major batched round, and the op-major "
                "engine stays cache-free as the differential oracle"
            )
        sh = self.shards
        if not isinstance(sh, int) or sh < 1 or sh & (sh - 1):
            raise ValueError(
                f"shards must be a power-of-two int >= 1, got {sh!r} — "
                "the bucket trees shard as contiguous equal heap ranges "
                "(parallel/mesh.py)"
            )
        if self.commit == "op" and sh != 1:
            raise ValueError(
                "commit='op' (the differential-oracle engine) supports "
                "only shards=1 — the sharded step/flush programs wrap "
                "the phase-major batched round (parallel/mesh.py "
                "make_sharded_step), and the op-major engine stays "
                "single-chip as the differential oracle"
            )
    #: slot-order semantics implementation for the phase-major engine's
    #: vectorized phases (engine/vphases.py): "dense" = [B,B] masked
    #: matrices + one-hot bool-matmuls (MXU-shaped; O(B²) compute and
    #: intermediate memory), "scan" = group-sort + segmented scans
    #: (O(B log B), no [B,B] intermediate — the form that scales past
    #: B=2048). Bit-identical responses and final engine state
    #: (tests/test_vphases_scan.py). None = auto by backend: "dense" on
    #: TPU backends (the MXU eats the masks; flip after the
    #: tools/tpu_capture.py ``vphases_perf`` A/B says otherwise),
    #: "scan" elsewhere — on CPU the aggregation machinery itself
    #: measures ~1.4× faster at B=256 rising to ~23× at B=4096, while
    #: whole-round CPU gains stay small below B≈2048 (the round is
    #: gather/scatter-bound; measured curve + the B=4096 dense memory
    #: math: PERF.md Round 6).
    vphases_impl: str | None = None

    #: bounded-key sort engine for the device round (oblivious/radix.py):
    #: "xla" = the comparison sorts XLA lowers natively (a serial
    #: ``while`` thunk on XLA:CPU — the round's measured floor after
    #: PR 3, PERF.md Round 6 — and a bitonic network on TPU), "radix" =
    #: data-oblivious LSD counting passes for every sort whose key
    #: carries a declared bit bound: eviction's leaf sort and round
    #: dedup (oram/round.py), the scan impl's bucket/record group sorts
    #: and the admission walk's slot grouping (engine/vphases.py). The
    #: 256-bit recipient-key sort stays on lax.sort under either
    #: setting (explicit key-bits gate: radix refuses keys wider than
    #: MAX_RADIX_BITS rather than hashing them down). Bit-identical
    #: responses and final engine state (tests/test_radix.py /
    #: test_sort_radix.py; the radix ORAM round traces ZERO ``sort``
    #: HLO ops, CI-audited). None = auto: currently "xla" on every
    #: backend — on XLA:CPU the native serial sort beats any
    #: scatter-per-pass radix formulation (each pass costs one ~80
    #: ns/elem serial scatter; measured, bench.py ``sort_ab`` / PERF.md
    #: Round 7), and on TPU — where scatters vectorize and lax.sort is
    #: the O(n log² n) bitonic side — the default flips only on the
    #: capture's ``sort_perf`` device A/B (the vphases_impl playbook).
    sort_impl: str | None = None

    #: position-map implementation for both ORAMs (oram/posmap.py):
    #: "flat" = the private u32[blocks+1] table in working memory —
    #: bit-for-bit the pre-PR-7 engine; "recursive" = the classic
    #: recursive construction (Path ORAM §"recursive construction",
    #: arXiv:1202.5150) one level deep — k ≈ sqrt(blocks) position
    #: entries packed per block of a smaller internal Path ORAM whose
    #: bucket tree lives in encrypted, shardable HBM, leaving only a
    #: blocks/k-entry table resident (the ≥2^30-record capacity path,
    #: ROADMAP item 5; geometry auto-derived from capacity, sizing
    #: table in OPERATIONS.md §13). Bit-identical responses and final
    #: payload-tree state either way (tests/test_posmap_ab.py); each
    #: outer round resolves ALL B positions through exactly B internal
    #: accesses, so the transcript's access count stays data-
    #: independent (CI-audited, tools/check_posmap_oblivious.py).
    #: None = auto: currently "flat" on every backend — the recursive
    #: map pays ~2× HBM path traffic per round for a ~k× smaller
    #: resident footprint, a trade that only *wins* once capacity
    #: exceeds private memory; flip per capacity (OPERATIONS.md §13)
    #: or after tools/tpu_capture.py's ``posmap_perf`` stage prices it
    #: on a real chip (the vphases/sort playbook). Requires
    #: commit="phase" and power-of-two block spaces >= 8 on both trees.
    posmap_impl: str | None = None

    #: tree-top cache depth for every Path-ORAM bucket tree (records,
    #: mailbox, and — under posmap_impl="recursive" — the internal
    #: position trees; oram/path_oram.py). The top k levels (2^k−1
    #: buckets) are on EVERY root→leaf path, so they are promoted out of
    #: the per-access encrypted HBM gather/scatter into decrypted-
    #: resident cache planes with the stash's private standing: path
    #: fetch/write-back then touch only the bottom height+1−k levels of
    #: the big tree arrays and the per-access cipher work shrinks by the
    #: same fraction ("Optimizing Path ORAM for Cloud Storage
    #: Applications" measures the ~2-3× path-bandwidth cut; Palermo
    #: co-designs the same cache in hardware — ROADMAP item 1).
    #: Access-pattern-neutral by construction — the cached levels are
    #: touched by every access anyway, and the cache is read/written
    #: with constant-shape programs (CI-audited,
    #: tools/check_tree_cache_oblivious.py). Responses and logical state
    #: are bit-identical at every k (tests/test_tree_cache.py).
    #: 0 = off (bit-for-bit the uncached program); k is clamped to each
    #: tree's height (at least the leaf level stays in HBM); memory cost
    #: is (2^k−1)·bucket-row bytes per tree (OPERATIONS.md §14 sizing
    #: table). None = auto per backend: 4 on TPU backends AND on CPU —
    #: the cache strictly removes gather/scatter/cipher rows rather than
    #: trading one algorithm for another, and the CPU A/B (bench.py
    #: ``tree_cache_ab``, PERF.md Round 10) confirms the win off-TPU;
    #: the on-chip number lands via tools/tpu_capture.py
    #: ``tree_cache_perf``. Requires commit="phase".
    tree_top_cache_levels: int | None = None

    #: round-pipeline depth: the number of dispatched-but-unresolved
    #: engine rounds a driver holds at rest (engine/batcher.py,
    #: server/scheduler.py; the scheduler's dispatch-then-settle order
    #: — the depth-1 legacy sequence — means depth+1 rounds are
    #: transiently in flight during each settle wait, so size device
    #: resp/transcript residency as depth+1 rounds). 1 = the serial pre-PR-10 program, bit for
    #: bit: a round fully settles (device wait + demux + delivery)
    #: before the next one's window would close behind it. 2 = the
    #: staged pipeline (ROADMAP item 2; Palermo's protocol/hardware
    #: pipelining, arXiv:2411.05400): while round k executes on the
    #: device, round k+1 is assembled and verified on the host and its
    #: journal frame is appended AND fsynced — the fsync overlaps
    #: device execution instead of serializing with it, so steady-state
    #: cadence approaches max(host, fsync, device) and p99 commit
    #: latency stops paying the fsync whenever a device round is in
    #: flight behind it. Durability ordering is unchanged: a round is
    #: journaled (and fsynced, per journal_fsync_every) strictly BEFORE
    #: it dispatches, and rounds dispatch in journal order, so replay
    #: order is journal order at every depth — never completion order
    #: (the chaos invariant; tools/chaos_run.py --pipeline-depth 2).
    #: Responses and final state are bit-identical at both depths
    #: (tests/test_pipeline.py). None = auto: 2 on TPU backends — the
    #: device round is the long pole there, overlap is the whole win,
    #: and the on-chip A/B lands via tools/tpu_capture.py
    #: ``pipeline_perf`` — and 1 elsewhere: on a host-bound CPU
    #: (bubble ratio ≈ 0.0002) the second in-flight round has no device
    #: window to hide work behind, and under open-loop sustained load
    #: every op's round dispatches behind one extra unfinished device
    #: round (+1 round of p99, measured; closed-loop bursty traffic
    #: instead sees a modest fsync-overlap win — bench.py
    #: ``pipeline_ab``, PERF.md Round 11 has both numbers honestly).
    pipeline_depth: int | None = None

    #: delayed batched eviction (ROADMAP item 1; Palermo arXiv:2411.05400
    #: and the cloud-storage Path-ORAM line arXiv:1501.01721 both
    #: decouple write-back from fetch): every E engine rounds the
    #: scatter+encrypt half of the round runs ONCE as a batched flush
    #: over the union of the window's fetched paths, and the steady-state
    #: round is gather+decrypt+stash-update only. Fetched path contents
    #: accumulate in a bounded, private per-tree **eviction buffer** with
    #: the stash's standing (checkpointed, journaled, swept, re-keyed
    #: like the stash; overflow rides the same sticky counter), and the
    #: flush cadence is a pure function of the round counter — never of
    #: the buffer's contents — so the write schedule stays
    #: recipient-independent (CI-audited: tools/check_oblivious.py
    #: evict axis + the E-round row accounting in
    #: tools/check_tree_cache_oblivious.py). 1 = bit-for-bit the
    #: pre-PR-15 evict-every-round program; E > 1 amortizes the
    #: scatter+encrypt cost 1/E (bench.py ``evict_ab``). Responses and
    #: final LOGICAL state (live blocks, positions, scalars) are
    #: bit-identical at every E — physical tree placement legitimately
    #: differs, which testing/compare.py:assert_logical_content_equal
    #: normalizes. None = auto: currently 1 on every backend — on this
    #: host-bound CPU the flush amortization is real but the on-chip
    #: number (flush overlapped into the device-idle window the
    #: bubble-ratio gauge prices) belongs to tools/tpu_capture.py's
    #: ``evict_perf`` stage (the vphases/sort/posmap flip-on-evidence
    #: playbook). Requires commit="phase".
    evict_every: int | None = None

    #: eviction-buffer capacity (rows) for the payload trees under
    #: evict_every > 1. None = auto per tree:
    #: min(blocks, 2·Z·window·fetches + 4·fetches) — ~2 live blocks per
    #: fetched path per round of headroom, clamped by the whole block
    #: space (a buffer that can hold every live block can never
    #: overflow). Sizing theory + the near-overflow canary are
    #: OPERATIONS.md §19; overflow increments the same sticky counter
    #: the stash uses and trips the health fold.
    evict_buffer_slots: int | None = None

    #: bucket-tree shard count across the device mesh (parallel/mesh.py):
    #: 1 = single-chip (the default; no mesh machinery compiled), N > 1
    #: = both payload trees (+ nonce planes) shard as contiguous heap
    #: ranges over the first N devices, everything else replicated; the
    #: engine's round AND flush dispatch through make_sharded_step /
    #: make_sharded_flush (evict_every composes — the owner-masked
    #: flush). Deliberately NOT part of EngineConfig and therefore NOT
    #: covered by the checkpoint/journal fingerprint: responses, final
    #: state, and the journal stream are bit-identical at every shard
    #: count (tests/test_parallel.py), so a journal written on one chip
    #: replays bit-identically on a mesh and vice versa — the same
    #: standing as pipeline_depth. Requires commit="phase", a
    #: power-of-two count that divides both trees' padded bucket counts,
    #: and at least that many JAX devices at engine construction.
    shards: int = 1

    #: hash choices per recipient in the mailbox table. 2 (default for
    #: the phase-major engine) = power-of-two-choices: a new recipient
    #: claims a slot in the emptier of two keyed-hash candidate buckets
    #: (occupancy read at round start; choice resolved obliviously —
    #: every op fetches BOTH candidate paths every time, so the
    #: transcript never reveals which bucket holds a recipient). 1 =
    #: the round-3 single-choice table (required by the op-major
    #: ``commit="op"`` differential-oracle engine, which keeps the
    #: simpler scheme). None = auto: 2 for phase commit, 1 for op.
    mailbox_choices: int | None = None

    #: per-slot load target; table buckets M = ceil(
    #: max_recipients / (mailbox_slots * load)). None = auto by choice
    #: count: 0.5 under two-choice, 0.125 under single-choice.
    #:
    #: The mailbox tier approximates the reference's bucketed-cuckoo map
    #: (README.md:78-80) with a RELOCATION-FREE two-choice table — no
    #: eviction chains on device. The quantified bargain
    #: (tests/test_mailbox_load.py):
    #:
    #: - **Early failures**: a recipient whose candidate bucket(s) are
    #:   full gets TOO_MANY_RECIPIENTS before the aggregate cap. At
    #:   K=4: single-choice load 0.125 gives Poisson(λ=0.5) tails —
    #:   ≈1.4 expected early failures at M=8192, fill 100%. Two-choice
    #:   at load 0.5 needs BOTH candidates full: simulated (20 trials,
    #:   M=4096) ≈0 failures through fill 75% and ≈0.3 expected at
    #:   fill 100% — strictly fewer failures than single-choice at
    #:   1/4 the bucket count. The spec permits TOO_MANY_RECIPIENTS at
    #:   any recipient count; the oracle models only the aggregate cap,
    #:   so randomized oracle-equality suites run at low fill.
    #: - **Memory**: mailbox-tier HBM per recipient is 1/load × the
    #:   mailbox size — 2× under two-choice vs the reference cuckoo's
    #:   ~1.2×, and vs 8× for round-3's single-choice table.
    #: - **Bandwidth**: every op pays a second mailbox path fetch in
    #:   rounds A and C (both candidates touched unconditionally). The
    #:   mailbox tree is the small tier, so this trades ~0.3 ms/round
    #:   of cheap bandwidth for 4× less mailbox HBM.
    mailbox_load: float | None = None

    #: blocks per tree leaf for both ORAMs. The classic Path ORAM shape
    #: is 1 (total slots = 8× blocks — 12.5% utilization); 2 halves tree
    #: HBM per block and shortens every path by one level at a still-
    #: conservative 25% utilization; 4 (50%) is the aggressive setting —
    #: stash occupancy under density is exercised in tests/test_oram.py.
    tree_density: int = 2

    @property
    def records_height(self) -> int:
        """Tree height of the records ORAM: leaves = blocks / density."""
        return max(
            1,
            math.ceil(math.log2(self.max_messages))
            - (self.tree_density.bit_length() - 1),
        )

    @property
    def records_leaves(self) -> int:
        return 1 << self.records_height

    @property
    def resolved_mailbox_choices(self) -> int:
        """1 or 2: the explicit knob, else 2 for phase / 1 for op."""
        if self.mailbox_choices is not None:
            return self.mailbox_choices
        return 2 if self.commit == "phase" else 1

    @property
    def resolved_mailbox_load(self) -> float:
        """Load target: the explicit knob, else by choice count."""
        if self.mailbox_load is not None:
            return self.mailbox_load
        return 0.5 if self.resolved_mailbox_choices == 2 else 0.125

    @property
    def mailbox_table_buckets(self) -> int:
        """Hash table size (power of two) for the mailbox map.

        Floor of 16: keeps the mailbox bucket tree shardable over an
        8-chip mesh at toy capacities and gives the two-choice hash a
        meaningful candidate space; the cost at tiny configs is a few
        KiB."""
        want = max(
            16,
            math.ceil(
                self.max_recipients
                / (self.mailbox_slots * self.resolved_mailbox_load)
            ),
        )
        return 1 << max(1, math.ceil(math.log2(want)))

    @property
    def mailbox_height(self) -> int:
        """Tree height of the mailbox ORAM: block space = hash-table buckets."""
        return max(
            1,
            math.ceil(math.log2(self.mailbox_table_buckets))
            - (self.tree_density.bit_length() - 1),
        )

    @property
    def mailbox_leaves(self) -> int:
        return 1 << self.mailbox_height


@dataclasses.dataclass(frozen=True)
class DurabilityConfig:
    """Crash-safety knobs (engine/checkpoint.py, engine/journal.py).

    With a ``state_dir`` set, the engine journals every admitted batch
    (sealed, fsync-batched) before dispatching it and periodically dumps
    a sealed whole-``EngineState`` checkpoint; restart = load the last
    checkpoint + deterministically replay the journal tail. Whole-state
    dumps and whole-batch journal records are access-pattern-free by
    construction — they are written for every round regardless of what
    the ops inside are, so durability adds no obliviousness leak
    (OPERATIONS.md §11).
    """

    #: directory holding checkpoints, journal segments, and (by default)
    #: the auto-generated root seal key
    state_dir: str
    #: rounds+sweeps between sealed checkpoints (RTO knob: recovery
    #: replays at most this many journal records)
    checkpoint_every_rounds: int = 64
    #: journal records per fsync. 1 (default) = every record is durable
    #: before its round dispatches (RPO 0 for acknowledged ops); larger
    #: values amortize the fsync at the cost of losing up to N-1
    #: acknowledged rounds on a *machine* crash (a process crash alone
    #: loses nothing — the page cache survives)
    journal_fsync_every: int = 1
    #: 32-byte root seal key file; None = ``<state_dir>/root.key``,
    #: auto-generated 0600 on first start. Point it at a separately
    #: mounted secret in production — a sealed checkpoint next to its
    #: key is integrity-protected but not confidential (OPERATIONS.md
    #: §11 key management)
    seal_key_file: str | None = None

    def __post_init__(self):
        if not self.state_dir:
            raise ValueError("durability requires a state_dir")
        if self.checkpoint_every_rounds < 1:
            raise ValueError("checkpoint_every_rounds must be >= 1")
        if self.journal_fsync_every < 1:
            raise ValueError("journal_fsync_every must be >= 1")


DEFAULT_CONFIG = GrapevineConfig()
