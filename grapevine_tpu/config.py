"""Configuration for the grapevine-tpu engine.

The reference fixes its knobs as compile-time constants (record size,
62-message mailbox cap, reference README.md:78-80,137-139) plus CLI flags
(expiry period, reference README.md:90). Here everything lives in one
dataclass; the device-engine geometry (tree heights, bucket slots, stash
size, batch size) are the TPU analogs of "how much EPC the enclave maps".

Capacity story: the records store is a Path-ORAM bucket tree with
``2**records_height`` leaves and a dense block space of the same size; the
mailbox store is a single-choice keyed-hash table (K mailboxes per bucket)
over its own Path-ORAM, run at low load so bucket overflow is negligible.
Maximum in-flight messages = ``max_messages`` (bounded by the free-block
list); maximum distinct recipients with mail = ``max_recipients`` (also
soft-bounded by table load; overflow reports TOO_MANY_RECIPIENTS).
"""

from __future__ import annotations

import dataclasses
import math

from .wire import constants as C


@dataclasses.dataclass(frozen=True)
class GrapevineConfig:
    # --- semantic capacities -------------------------------------------
    #: max in-flight messages on the bus (reference README.md:75-76)
    max_messages: int = 1 << 14
    #: max distinct recipients with in-flight messages
    max_recipients: int = 1 << 12
    #: per-recipient in-flight cap (reference README.md:78-80)
    mailbox_cap: int = C.MAILBOX_CAP
    #: message expiry period in seconds; 0 disables (reference README.md:86-98)
    expiry_period: int = 0

    # --- device engine geometry ----------------------------------------
    #: Path-ORAM bucket capacity (Z); upstream mc-oblivious uses Z=4 with
    #: 4096B buckets of 1024B blocks (SURVEY.md §7.4)
    bucket_slots: int = 4
    #: fixed stash slots per ORAM (overflow is a sticky internal error)
    stash_size: int = 96
    #: client ops per jit'd access round; host pads with dummy ops
    batch_size: int = 8
    #: mailboxes per hash bucket (one bucket = one mailbox-ORAM block)
    mailbox_slots: int = 4
    #: within-batch commit schedule: "phase" = phase-major batched rounds
    #: (engine/round_step.py — the production path: one path fetch per
    #: ORAM round instead of one per op), "op" = op-major sequential
    #: commits (engine/step.py — the original reference-shaped engine).
    #: Identical semantics for single-op batches; batch-hazard semantics
    #: documented in round_step.py.
    commit: str = "phase"
    #: ChaCha rounds for at-rest bucket-tree encryption in HBM — the EPC
    #: analog (oblivious/bucket_cipher.py). 8 = ChaCha8 (default),
    #: 20 = RFC ChaCha20, 0 = plaintext trees.
    bucket_cipher_rounds: int = 8
    #: cipher implementation: "jnp" (XLA, keystream materialized in HBM)
    #: or "pallas" (fused VMEM keystream+XOR kernel,
    #: oblivious/pallas_cipher.py; interpret mode off-TPU). Bit-identical
    #: ciphertext either way.
    bucket_cipher_impl: str = "jnp"
    #: per-request signature scheme: "schnorrkel" (sr25519, byte-compatible
    #: with the reference's sign_schnorrkel clients — README.md:193-199,
    #: session/schnorrkel.py) or "rfc9496" (the same-shape plain Schnorr
    #: this repo shipped first, session/ristretto.py). Server and clients
    #: must agree.
    signature_scheme: str = "schnorrkel"

    def __post_init__(self):
        if self.commit not in ("phase", "op"):
            raise ValueError(
                f"commit must be 'phase' or 'op', got {self.commit!r}"
            )
        # 0 = plaintext; otherwise an even round count ≥ 8 (ChaCha rounds
        # come in column+diagonal pairs; odd values would silently floor,
        # and rounds < 8 have no security story — a 0-round "cipher"
        # exposes 2*key in every keystream block)
        r = self.bucket_cipher_rounds
        if r != 0 and (r < 8 or r % 2 != 0):
            raise ValueError(
                f"bucket_cipher_rounds must be 0 or an even value >= 8, got {r}"
            )
        if self.bucket_cipher_impl not in ("jnp", "pallas"):
            raise ValueError(
                f"bucket_cipher_impl must be 'jnp' or 'pallas', got "
                f"{self.bucket_cipher_impl!r}"
            )
        if self.signature_scheme not in ("schnorrkel", "rfc9496"):
            raise ValueError(
                f"signature_scheme must be 'schnorrkel' or 'rfc9496', got "
                f"{self.signature_scheme!r}"
            )
        if self.max_messages < 2 or self.max_messages & (self.max_messages - 1):
            raise ValueError("max_messages must be a power of two >= 2")
        if self.tree_density not in (1, 2, 4):
            raise ValueError(
                f"tree_density must be 1, 2, or 4, got {self.tree_density}"
            )
    #: per-slot load target; table buckets M = ceil(
    #: max_recipients / (mailbox_slots * mailbox_load)).
    #:
    #: The mailbox tier is a keyed SINGLE-CHOICE hash table of K-slot
    #: buckets, not the reference's bucketed cuckoo (README.md:78-80).
    #: The quantified bargain (tests/test_mailbox_load.py):
    #:
    #: - **Early failures**: a recipient whose bucket is full gets
    #:   TOO_MANY_RECIPIENTS before the aggregate cap is reached. With
    #:   R = fill · max_recipients uniform recipients, per-bucket
    #:   occupancy is ≈ Poisson(λ = K·load·fill); expected early
    #:   failures ≈ M · P(X ≥ K+1). At the default (K=4, load=0.125):
    #:   fill 50% ⇒ λ=0.25, P ≈ 6.6e-6 (≈0.05 expected at M=8192);
    #:   fill 100% ⇒ λ=0.5, P ≈ 1.7e-4 (≈1.4 expected at M=8192) —
    #:   i.e. near the aggregate cap, a handful of recipients may be
    #:   refused early. The spec permits TOO_MANY_RECIPIENTS at any
    #:   recipient count; the oracle models only the aggregate cap, so
    #:   randomized oracle-equality suites run at low fill.
    #: - **Memory**: mailbox-tier HBM per recipient is 1/load × the
    #:   mailbox size — 8× at the default (the price of no relocation).
    #:   In absolute terms the tier is small: at a 2^20-message bus with
    #:   2^12 recipients the mailbox tree is ~0.13 GB against the 4 GB
    #:   records tree (~3% of engine HBM), so the 8× factor costs ~0.11
    #:   GB — the records tier, not the mailbox tier, bounds capacity.
    #:
    #: A relocating scheme (two-choice or cuckoo with bounded-iteration
    #: masked eviction chains) would shrink the factor to ~2× and kill
    #: early failures; it costs a second mailbox path fetch per op and a
    #: substantially hairier within-round claim/occupancy resolution in
    #: engine/vphases.py. Deliberately deferred: the memory it saves is
    #: ~3% of the engine while the records tree dominates, and the
    #: early-failure path is analyzed and tested (test_mailbox_load).
    mailbox_load: float = 0.125

    #: blocks per tree leaf for both ORAMs. The classic Path ORAM shape
    #: is 1 (total slots = 8× blocks — 12.5% utilization); 2 halves tree
    #: HBM per block and shortens every path by one level at a still-
    #: conservative 25% utilization; 4 (50%) is the aggressive setting —
    #: stash occupancy under density is exercised in tests/test_oram.py.
    tree_density: int = 2

    @property
    def records_height(self) -> int:
        """Tree height of the records ORAM: leaves = blocks / density."""
        return max(
            1,
            math.ceil(math.log2(self.max_messages))
            - (self.tree_density.bit_length() - 1),
        )

    @property
    def records_leaves(self) -> int:
        return 1 << self.records_height

    @property
    def mailbox_table_buckets(self) -> int:
        """Hash table size (power of two) for the mailbox map."""
        want = max(
            2, math.ceil(self.max_recipients / (self.mailbox_slots * self.mailbox_load))
        )
        return 1 << max(1, math.ceil(math.log2(want)))

    @property
    def mailbox_height(self) -> int:
        """Tree height of the mailbox ORAM: block space = hash-table buckets."""
        return max(
            1,
            math.ceil(math.log2(self.mailbox_table_buckets))
            - (self.tree_density.bit_length() - 1),
        )

    @property
    def mailbox_leaves(self) -> int:
        return 1 << self.mailbox_height


DEFAULT_CONFIG = GrapevineConfig()
