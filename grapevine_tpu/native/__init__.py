"""Build-on-first-import ctypes loader for the native session library.

r255.c is compiled with the system C compiler into a cached shared
object next to the source (the build-time codegen analog of the
reference's api/build.rs protoc step). If no compiler is available the
package degrades to the pure-Python paths — callers must treat ``lib``
as Optional.

Thread-safety contract, per wrapper class:

- group/MSM wrappers (verify1, batch_check, reencode, mult_base) hold
  the module lock because their C functions use static scratch buffers
  (they are called from the scheduler's single collector thread anyway);
- the STROBE/merlin/keccak wrappers are deliberately LOCK-FREE and in
  exchange their C functions must never use static scratch — they touch
  only the caller's buffers, because gRPC worker threads run them
  concurrently on distinct transcripts (one per in-flight signature).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

_DIR = Path(__file__).parent
_SRC = _DIR / "r255.c"
_SO = _DIR / "_r255.so"

_lock = threading.Lock()
lib = None


def _build() -> Path | None:
    try:
        if _SO.exists() and _SO.stat().st_mtime >= _SRC.stat().st_mtime:
            return _SO
    except OSError:
        # a cached .so without the C source (or vice versa): use the .so
        # if present, otherwise fall back to pure Python
        return _SO if _SO.exists() else None
    # compile to a private temp file, then atomically rename: concurrent
    # importers (pytest workers, server + bench) must never dlopen a
    # half-written .so or have a mapped one rewritten under them
    tmp = _DIR / f"_r255.{os.getpid()}.tmp.so"
    cc = os.environ.get("CC", "cc")
    cmd = [cc, "-O2", "-shared", "-fPIC", "-o", str(tmp), str(_SRC)]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
    except (OSError, subprocess.SubprocessError):
        tmp.unlink(missing_ok=True)
        return None
    return _SO


def _load():
    global lib
    so = _build()
    if so is None:
        return None
    try:
        handle = ctypes.CDLL(str(so))
    except OSError:
        return None
    try:
        return _bind(handle)
    except AttributeError:
        # a cached .so built from older source (missing a newer export):
        # degrade to pure Python rather than failing the package import
        return None


def _bind(handle):
    handle.r255_init.restype = ctypes.c_int
    handle.r255_verify1.restype = ctypes.c_int
    handle.r255_verify1.argtypes = [ctypes.c_char_p] * 4
    handle.r255_batch_check.restype = ctypes.c_int
    handle.r255_batch_check.argtypes = [ctypes.c_size_t] + [ctypes.c_char_p] * 5
    handle.r255_encode.restype = ctypes.c_int
    handle.r255_encode.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    handle.r255_mult_base.restype = ctypes.c_int
    handle.r255_mult_base.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    handle.r255_keccak_f1600.restype = None
    handle.r255_keccak_f1600.argtypes = [ctypes.POINTER(ctypes.c_char)]
    handle.r255_strobe_op.restype = ctypes.c_int
    handle.r255_strobe_op.argtypes = [
        ctypes.POINTER(ctypes.c_char), ctypes.c_int, ctypes.c_char_p,
        ctypes.c_size_t, ctypes.POINTER(ctypes.c_char), ctypes.c_int,
    ]
    handle.r255_merlin_append.restype = None
    handle.r255_merlin_append.argtypes = [
        ctypes.POINTER(ctypes.c_char), ctypes.c_char_p, ctypes.c_size_t,
        ctypes.c_char_p, ctypes.c_size_t,
    ]
    handle.r255_merlin_challenge.restype = None
    handle.r255_merlin_challenge.argtypes = [
        ctypes.POINTER(ctypes.c_char), ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_char), ctypes.c_size_t,
    ]
    handle.r255_schnorrkel_challenge.restype = None
    handle.r255_schnorrkel_challenge.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.c_char_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_char),
    ]
    if handle.r255_init() != 0:
        return None
    return handle


lib = _load()


def verify1(pub: bytes, r_enc: bytes, s: bytes, k: bytes) -> int:
    """1 valid, 0 invalid, -1 malformed. Requires ``lib is not None``."""
    with _lock:
        return lib.r255_verify1(pub, r_enc, s, k)


def batch_check(rs: bytes, as_: bytes, z: bytes, zk: bytes, sb: bytes) -> int:
    n = len(rs) // 32
    with _lock:
        return lib.r255_batch_check(n, rs, as_, z, zk, sb)


def reencode(enc: bytes) -> bytes | None:
    out = ctypes.create_string_buffer(32)
    with _lock:
        rc = lib.r255_encode(out, enc)
    return bytes(out.raw) if rc == 0 else None


def keccak_f1600(state: bytearray) -> None:
    """In-place Keccak-f[1600] on a 200-byte state (merlin hot path).

    No module lock: the C function writes only the caller's buffer (no
    static scratch), so concurrent calls on distinct states are safe."""
    buf = (ctypes.c_char * 200).from_buffer(state)
    lib.r255_keccak_f1600(buf)


def mult_base(scalar_le: bytes) -> bytes | None:
    """Encoded ``scalar * basepoint`` (scalar: 32B LE, already reduced).

    The client-side signing hot path (session/ristretto.py:sign does two
    of these per request when cold, one when the pubkey is cached)."""
    out = ctypes.create_string_buffer(32)
    with _lock:
        rc = lib.r255_mult_base(out, scalar_le)
    return bytes(out.raw) if rc == 0 else None


# -- STROBE-128 / merlin transcript ops (session/merlin.py hot path) ---
# No module lock on any of these: the C functions touch only the
# caller's 203-byte blob (state ‖ pos ‖ pos_begin ‖ cur_flags), so
# concurrent calls on distinct transcripts are safe.

def strobe_op(blob: bytearray, op: int, data: bytes, more: bool) -> int:
    """One STROBE op: 0=meta_ad 1=ad 3=key. Returns 0, or <0 on a
    continued-op flag mismatch (caller raises)."""
    buf = (ctypes.c_char * 203).from_buffer(blob)
    return lib.r255_strobe_op(buf, op, data, len(data), None, 1 if more else 0)


def strobe_prf(blob: bytearray, n: int, more: bool) -> bytes | None:
    """PRF squeeze of ``n`` bytes; None on flag mismatch."""
    buf = (ctypes.c_char * 203).from_buffer(blob)
    out = ctypes.create_string_buffer(n)
    rc = lib.r255_strobe_op(buf, 2, None, n, out, 1 if more else 0)
    return bytes(out.raw) if rc == 0 else None


def merlin_append(blob: bytearray, label: bytes, message: bytes) -> None:
    """merlin append_message in one crossing (meta_ad + len + ad)."""
    buf = (ctypes.c_char * 203).from_buffer(blob)
    lib.r255_merlin_append(buf, label, len(label), message, len(message))


def merlin_challenge(blob: bytearray, label: bytes, n: int) -> bytes:
    """merlin challenge_bytes in one crossing (meta_ad + len + PRF)."""
    buf = (ctypes.c_char * 203).from_buffer(blob)
    out = ctypes.create_string_buffer(n)
    lib.r255_merlin_challenge(buf, label, len(label), out, n)
    return bytes(out.raw)


def schnorrkel_challenge(
    prefix_blob: bytes, message: bytes, pub: bytes, r_enc: bytes
) -> bytes:
    """64 challenge bytes from the cached SigningContext prefix in ONE
    crossing (clone + 4 appends + PRF; schnorrkel sign.rs labels).
    ``prefix_blob`` is the 203-byte transcript blob after
    ``Transcript(b"SigCtx")`` + ``append_message(b"", context)``."""
    out = ctypes.create_string_buffer(64)
    lib.r255_schnorrkel_challenge(
        bytes(prefix_blob), message, len(message), pub, r_enc, out
    )
    return bytes(out.raw)
