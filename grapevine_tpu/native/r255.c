/* ristretto255 group operations for batched Schnorr verification.
 *
 * The host-side native layer of the session stack (the analog of the
 * reference's Rust mc-crypto-keys dependency, reference
 * types/src/lib.rs:13, README.md:199): field arithmetic mod 2^255-19
 * with 5x51-bit limbs (unsigned __int128 products), extended-Edwards
 * point ops, RFC 9496 ristretto decode/encode, a precomputed fixed-base
 * nibble table, and a Straus interleaved multi-scalar multiplication.
 * Scalar-field (mod L) arithmetic and all hashing stay in Python — the
 * caller passes fully reduced 256-bit little-endian scalars.
 *
 * Exposed via ctypes (grapevine_tpu/native/__init__.py):
 *   r255_init()                     build the basepoint table (idempotent)
 *   r255_verify1(pub, R, s, k)      s*B == R + k*A          -> 1/0/-1
 *   r255_batch_check(n, Rs, As, z, zk, sb)
 *       fixed(sb) == sum z_i*R_i + zk_i*A_i                 -> 1/0/-1
 *
 * Verification-only: nothing here handles secrets, so variable-time
 * arithmetic is fine (same stance as the pure-Python path it
 * accelerates, session/ristretto.py).
 *
 * Built by `cc -O2 -shared -fPIC` at first import; correctness is
 * pinned by cross-checking against the pure-Python implementation over
 * random points/scalars and the RFC 9496 test vectors
 * (tests/test_native_r255.py).
 */

#include <stdint.h>
#include <stddef.h>
#include <string.h>

typedef uint64_t u64;
typedef unsigned __int128 u128;

#define MASK51 0x7FFFFFFFFFFFFULL

typedef struct { u64 v[5]; } fe;

/* ---------------- field arithmetic mod 2^255-19 ---------------- */

static void fe_zero(fe *r) { memset(r, 0, sizeof *r); }
static void fe_one(fe *r) { fe_zero(r); r->v[0] = 1; }
static void fe_copy(fe *r, const fe *a) { *r = *a; }

static void fe_add(fe *r, const fe *a, const fe *b) {
    for (int i = 0; i < 5; i++) r->v[i] = a->v[i] + b->v[i];
}

/* r = a - b, with a bias of 2p to keep limbs nonnegative */
static void fe_sub(fe *r, const fe *a, const fe *b) {
    r->v[0] = a->v[0] + 0xFFFFFFFFFFFDAULL - b->v[0];
    r->v[1] = a->v[1] + 0xFFFFFFFFFFFFEULL - b->v[1];
    r->v[2] = a->v[2] + 0xFFFFFFFFFFFFEULL - b->v[2];
    r->v[3] = a->v[3] + 0xFFFFFFFFFFFFEULL - b->v[3];
    r->v[4] = a->v[4] + 0xFFFFFFFFFFFFEULL - b->v[4];
}

static void fe_carry(fe *r) {
    for (int rep = 0; rep < 2; rep++) {
        u64 c;
        c = r->v[0] >> 51; r->v[0] &= MASK51; r->v[1] += c;
        c = r->v[1] >> 51; r->v[1] &= MASK51; r->v[2] += c;
        c = r->v[2] >> 51; r->v[2] &= MASK51; r->v[3] += c;
        c = r->v[3] >> 51; r->v[3] &= MASK51; r->v[4] += c;
        c = r->v[4] >> 51; r->v[4] &= MASK51; r->v[0] += c * 19;
    }
}

static void fe_mul(fe *r, const fe *a, const fe *b) {
    u128 t0, t1, t2, t3, t4;
    u64 a0 = a->v[0], a1 = a->v[1], a2 = a->v[2], a3 = a->v[3], a4 = a->v[4];
    u64 b0 = b->v[0], b1 = b->v[1], b2 = b->v[2], b3 = b->v[3], b4 = b->v[4];
    u64 b1_19 = b1 * 19, b2_19 = b2 * 19, b3_19 = b3 * 19, b4_19 = b4 * 19;

    t0 = (u128)a0*b0 + (u128)a1*b4_19 + (u128)a2*b3_19 + (u128)a3*b2_19 + (u128)a4*b1_19;
    t1 = (u128)a0*b1 + (u128)a1*b0    + (u128)a2*b4_19 + (u128)a3*b3_19 + (u128)a4*b2_19;
    t2 = (u128)a0*b2 + (u128)a1*b1    + (u128)a2*b0    + (u128)a3*b4_19 + (u128)a4*b3_19;
    t3 = (u128)a0*b3 + (u128)a1*b2    + (u128)a2*b1    + (u128)a3*b0    + (u128)a4*b4_19;
    t4 = (u128)a0*b4 + (u128)a1*b3    + (u128)a2*b2    + (u128)a3*b1    + (u128)a4*b0;

    u64 c;
    u64 r0 = (u64)t0 & MASK51; c = (u64)(t0 >> 51);
    t1 += c;
    u64 r1 = (u64)t1 & MASK51; c = (u64)(t1 >> 51);
    t2 += c;
    u64 r2 = (u64)t2 & MASK51; c = (u64)(t2 >> 51);
    t3 += c;
    u64 r3 = (u64)t3 & MASK51; c = (u64)(t3 >> 51);
    t4 += c;
    u64 r4 = (u64)t4 & MASK51; c = (u64)(t4 >> 51);
    r0 += c * 19;
    c = r0 >> 51; r0 &= MASK51; r1 += c;
    r->v[0] = r0; r->v[1] = r1; r->v[2] = r2; r->v[3] = r3; r->v[4] = r4;
}

static void fe_sq(fe *r, const fe *a) { fe_mul(r, a, a); }

/* r = a^(2^n) */
static void fe_sqn(fe *r, const fe *a, int n) {
    fe_copy(r, a);
    for (int i = 0; i < n; i++) fe_sq(r, r);
}

/* a^(2^252 - 3): shared chain for invert and sqrt (ref10 structure) */
static void fe_pow22523(fe *out, const fe *z) {
    fe t0, t1, t2;
    fe_sq(&t0, z);                 /* 2 */
    fe_sqn(&t1, &t0, 2);           /* 8 */
    fe_mul(&t1, z, &t1);           /* 9 */
    fe_mul(&t0, &t0, &t1);         /* 11 */
    fe_sq(&t0, &t0);               /* 22 */
    fe_mul(&t0, &t1, &t0);         /* 2^5 - 1 */
    fe_sqn(&t1, &t0, 5);
    fe_mul(&t0, &t1, &t0);         /* 2^10 - 1 */
    fe_sqn(&t1, &t0, 10);
    fe_mul(&t1, &t1, &t0);         /* 2^20 - 1 */
    fe_sqn(&t2, &t1, 20);
    fe_mul(&t1, &t2, &t1);         /* 2^40 - 1 */
    fe_sqn(&t1, &t1, 10);
    fe_mul(&t0, &t1, &t0);         /* 2^50 - 1 */
    fe_sqn(&t1, &t0, 50);
    fe_mul(&t1, &t1, &t0);         /* 2^100 - 1 */
    fe_sqn(&t2, &t1, 100);
    fe_mul(&t1, &t2, &t1);         /* 2^200 - 1 */
    fe_sqn(&t1, &t1, 50);
    fe_mul(&t0, &t1, &t0);         /* 2^250 - 1 */
    fe_sqn(&t0, &t0, 2);
    fe_mul(out, &t0, z);           /* 2^252 - 3 */
}

static void fe_invert(fe *out, const fe *z) {
    /* z^(p-2) = z^(2^255 - 21) via the classic chain */
    fe t0, t1, t2, t3;
    fe_sq(&t0, z);
    fe_sqn(&t1, &t0, 2);
    fe_mul(&t1, z, &t1);
    fe_mul(&t0, &t0, &t1);
    fe_sq(&t2, &t0);
    fe_mul(&t1, &t1, &t2);
    fe_sqn(&t2, &t1, 5);
    fe_mul(&t1, &t2, &t1);
    fe_sqn(&t2, &t1, 10);
    fe_mul(&t2, &t2, &t1);
    fe_sqn(&t3, &t2, 20);
    fe_mul(&t2, &t3, &t2);
    fe_sqn(&t2, &t2, 10);
    fe_mul(&t1, &t2, &t1);
    fe_sqn(&t2, &t1, 50);
    fe_mul(&t2, &t2, &t1);
    fe_sqn(&t3, &t2, 100);
    fe_mul(&t2, &t3, &t2);
    fe_sqn(&t2, &t2, 50);
    fe_mul(&t1, &t2, &t1);
    fe_sqn(&t1, &t1, 5);
    fe_mul(out, &t1, &t0);
}

static void fe_frombytes(fe *r, const uint8_t s[32]) {
    u64 w0, w1, w2, w3;
    memcpy(&w0, s, 8); memcpy(&w1, s + 8, 8);
    memcpy(&w2, s + 16, 8); memcpy(&w3, s + 24, 8);
    r->v[0] = w0 & MASK51;
    r->v[1] = ((w0 >> 51) | (w1 << 13)) & MASK51;
    r->v[2] = ((w1 >> 38) | (w2 << 26)) & MASK51;
    r->v[3] = ((w2 >> 25) | (w3 << 39)) & MASK51;
    r->v[4] = (w3 >> 12) & MASK51;
}

static void fe_tobytes(uint8_t s[32], const fe *a) {
    fe t = *a;
    fe_carry(&t);
    /* full reduction: add 19, fold, then subtract 2^255 bit */
    u64 q = (t.v[0] + 19) >> 51;
    q = (t.v[1] + q) >> 51;
    q = (t.v[2] + q) >> 51;
    q = (t.v[3] + q) >> 51;
    q = (t.v[4] + q) >> 51;
    t.v[0] += 19 * q;
    u64 c;
    c = t.v[0] >> 51; t.v[0] &= MASK51; t.v[1] += c;
    c = t.v[1] >> 51; t.v[1] &= MASK51; t.v[2] += c;
    c = t.v[2] >> 51; t.v[2] &= MASK51; t.v[3] += c;
    c = t.v[3] >> 51; t.v[3] &= MASK51; t.v[4] += c;
    t.v[4] &= MASK51;
    u64 w0 = t.v[0] | (t.v[1] << 51);
    u64 w1 = (t.v[1] >> 13) | (t.v[2] << 38);
    u64 w2 = (t.v[2] >> 26) | (t.v[3] << 25);
    u64 w3 = (t.v[3] >> 39) | (t.v[4] << 12);
    memcpy(s, &w0, 8); memcpy(s + 8, &w1, 8);
    memcpy(s + 16, &w2, 8); memcpy(s + 24, &w3, 8);
}

static int fe_isnegative(const fe *a) {
    uint8_t s[32];
    fe_tobytes(s, a);
    return s[0] & 1;
}

static int fe_iszero(const fe *a) {
    uint8_t s[32];
    static const uint8_t zero[32] = {0};
    fe_tobytes(s, a);
    return memcmp(s, zero, 32) == 0;
}

static int fe_eq(const fe *a, const fe *b) {
    fe d;
    fe_sub(&d, a, b);
    return fe_iszero(&d);
}

static void fe_neg(fe *r, const fe *a) {
    fe z;
    fe_zero(&z);
    fe_sub(r, &z, a);
}

static void fe_cabs(fe *r, const fe *a) {  /* |a| = -a if negative */
    if (fe_isnegative(a)) fe_neg(r, a); else fe_copy(r, a);
    fe_carry(r);
}

/* ---------------- curve constants ---------------- */

static fe FE_D, FE_SQRT_M1, FE_INVSQRT_A_MINUS_D, FE_ONE;

/* d = -121665/121666 */
static const uint8_t D_BYTES[32] = {
    0xa3,0x78,0x59,0x13,0xca,0x4d,0xeb,0x75,0xab,0xd8,0x41,0x41,
    0x4d,0x0a,0x70,0x00,0x98,0xe8,0x79,0x77,0x79,0x40,0xc7,0x8c,
    0x73,0xfe,0x6f,0x2b,0xee,0x6c,0x03,0x52};
static const uint8_t SQRT_M1_BYTES[32] = {
    0xb0,0xa0,0x0e,0x4a,0x27,0x1b,0xee,0xc4,0x78,0xe4,0x2f,0xad,
    0x06,0x18,0x43,0x2f,0xa7,0xd7,0xfb,0x3d,0x99,0x00,0x4d,0x2b,
    0x0b,0xdf,0xc1,0x4f,0x80,0x24,0x83,0x2b};

typedef struct { fe x, y, z, t; } ge;  /* extended coordinates, a=-1 */

static void ge_identity(ge *r) {
    fe_zero(&r->x); fe_one(&r->y); fe_one(&r->z); fe_zero(&r->t);
}

static void ge_add(ge *r, const ge *p, const ge *q) {
    fe a, b, c, d, e, f, g, h, t0, t1;
    fe_sub(&t0, &p->y, &p->x); fe_carry(&t0);
    fe_sub(&t1, &q->y, &q->x); fe_carry(&t1);
    fe_mul(&a, &t0, &t1);
    fe_add(&t0, &p->y, &p->x);
    fe_add(&t1, &q->y, &q->x);
    fe_mul(&b, &t0, &t1);
    fe_mul(&c, &p->t, &FE_D);
    fe_add(&c, &c, &c);
    fe_carry(&c);
    fe_mul(&c, &c, &q->t);
    fe_mul(&d, &p->z, &q->z);
    fe_add(&d, &d, &d);
    fe_sub(&e, &b, &a); fe_carry(&e);
    fe_sub(&f, &d, &c); fe_carry(&f);
    fe_add(&g, &d, &c); fe_carry(&g);
    fe_add(&h, &b, &a); fe_carry(&h);
    fe_mul(&r->x, &e, &f);
    fe_mul(&r->y, &g, &h);
    fe_mul(&r->z, &f, &g);
    fe_mul(&r->t, &e, &h);
}

/* RFC 9496 SQRT_RATIO_M1. Returns was_square; *r = sqrt(u/v) or sqrt(i*u/v), abs. */
static int sqrt_ratio_m1(fe *r, const fe *u, const fe *v) {
    fe v3, v7, t, check, u_neg, u_neg_i, rr;
    fe_sq(&v3, v); fe_mul(&v3, &v3, v);          /* v^3 */
    fe_sq(&v7, &v3); fe_mul(&v7, &v7, v);        /* v^7 */
    fe_mul(&t, u, &v7);
    fe_pow22523(&t, &t);                         /* (u v^7)^((p-5)/8) */
    fe_mul(&rr, u, &v3); fe_mul(&rr, &rr, &t);
    fe_sq(&check, &rr); fe_mul(&check, &check, v);
    fe_neg(&u_neg, u);
    fe_mul(&u_neg_i, &u_neg, &FE_SQRT_M1);
    int correct = fe_eq(&check, u);
    int flipped = fe_eq(&check, &u_neg);
    int flipped_i = fe_eq(&check, &u_neg_i);
    if (flipped || flipped_i) fe_mul(&rr, &rr, &FE_SQRT_M1);
    fe_cabs(r, &rr);
    return correct || flipped;
}

/* RFC 9496 decode; returns 0 ok, -1 invalid */
static int ristretto_decode(ge *p, const uint8_t s_bytes[32]) {
    fe s, ss, u1, u2, u2_sqr, v, t, den_x, den_y, x, y;
    /* canonical check: bytes must re-encode identically and be non-negative */
    fe_frombytes(&s, s_bytes);
    uint8_t chk[32];
    fe_tobytes(chk, &s);
    if (memcmp(chk, s_bytes, 32) != 0) return -1;
    if (s_bytes[0] & 1) return -1;

    fe_sq(&ss, &s);
    fe_one(&u1); fe_sub(&u1, &u1, &ss); fe_carry(&u1);      /* 1 - s^2 */
    fe_one(&u2); fe_add(&u2, &u2, &ss); fe_carry(&u2);      /* 1 + s^2 */
    fe_sq(&u2_sqr, &u2);
    fe_sq(&t, &u1); fe_mul(&t, &t, &FE_D);                  /* d u1^2 */
    fe_neg(&v, &t);
    fe_sub(&v, &v, &u2_sqr); fe_carry(&v);                  /* -(d u1^2) - u2^2 */
    fe mulv;
    fe_mul(&mulv, &v, &u2_sqr);
    fe one;
    fe_one(&one);
    int was_square = sqrt_ratio_m1(&t, &one, &mulv);        /* invsqrt */
    fe_mul(&den_x, &t, &u2);
    fe_mul(&den_y, &t, &den_x); fe_mul(&den_y, &den_y, &v);
    fe_add(&x, &s, &s);
    fe_mul(&x, &x, &den_x);
    fe_cabs(&x, &x);
    fe_mul(&y, &u1, &den_y);
    fe_mul(&t, &x, &y);
    if (!was_square || fe_isnegative(&t) || fe_iszero(&y)) return -1;
    fe_copy(&p->x, &x); fe_copy(&p->y, &y);
    fe_one(&p->z);
    fe_copy(&p->t, &t);
    return 0;
}

/* ristretto coset equality: X1 Y2 == Y1 X2  OR  Y1 Y2 == X1 X2 */
static int ristretto_eq(const ge *p, const ge *q) {
    fe a, b;
    fe_mul(&a, &p->x, &q->y);
    fe_mul(&b, &p->y, &q->x);
    if (fe_eq(&a, &b)) return 1;
    fe_mul(&a, &p->y, &q->y);
    fe_mul(&b, &p->x, &q->x);
    return fe_eq(&a, &b);
}

/* ---------------- fixed-base table ---------------- */

static const uint8_t BASEPOINT_BYTES[32] = {
    0xe2,0xf2,0xae,0x0a,0x6a,0xbc,0x4e,0x71,0xa8,0x84,0xa9,0x61,
    0xc5,0x00,0x51,0x5f,0x58,0xe3,0x0b,0x6a,0xa5,0x82,0xdd,0x8d,
    0xb6,0xa6,0x59,0x45,0xe0,0x8d,0x2d,0x76};

static ge FIXED_TABLE[64][16];
static int INITIALIZED = 0;

int r255_init(void) {
    if (INITIALIZED) return 0;
    fe_frombytes(&FE_D, D_BYTES);
    fe_frombytes(&FE_SQRT_M1, SQRT_M1_BYTES);
    fe_one(&FE_ONE);
    ge base;
    if (ristretto_decode(&base, BASEPOINT_BYTES) != 0) return -1;
    for (int w = 0; w < 64; w++) {
        ge_identity(&FIXED_TABLE[w][0]);
        for (int d = 1; d < 16; d++)
            ge_add(&FIXED_TABLE[w][d], &FIXED_TABLE[w][d - 1], &base);
        ge next;
        ge_add(&next, &FIXED_TABLE[w][1], &FIXED_TABLE[w][15]);  /* 16*base */
        base = next;
    }
    INITIALIZED = 1;
    return 0;
}

/* constant-time select: r = table[d] scanned with masks, no secret-
 * dependent branches or indices (the scalar is secret on the signing
 * path — r255_mult_base computes nonce*B and key*B) */
static void ge_ct_select(ge *r, const ge table[16], int d) {
    const u64 *src0 = (const u64 *)&table[0];
    u64 *dst = (u64 *)r;
    size_t words = sizeof(ge) / sizeof(u64);
    for (size_t i = 0; i < words; i++) dst[i] = src0[i];
    for (int j = 1; j < 16; j++) {
        u64 mask = (u64)0 - (u64)(((uint32_t)(j ^ d) - 1u) >> 31); /* all-1 iff j==d */
        const u64 *src = (const u64 *)&table[j];
        for (size_t i = 0; i < words; i++)
            dst[i] ^= mask & (dst[i] ^ src[i]);
    }
}

static void fixed_mult(ge *r, const uint8_t s[32]) {
    /* window 0 via select from identity-rooted table; remaining windows
     * always add (Edwards unified addition is complete, so adding the
     * selected entry — identity when the nibble is 0 — is safe) */
    ge t;
    ge_ct_select(r, FIXED_TABLE[0], s[0] & 0xF);
    for (int w = 1; w < 64; w++) {
        int d = (s[w >> 1] >> ((w & 1) * 4)) & 0xF;
        ge_ct_select(&t, FIXED_TABLE[w], d);
        ge_add(r, r, &t);
    }
}

/* Multi-scalar multiplication; scalars are 32-byte LE, verification-
 * only (variable time is fine — same stance as the Python path).
 *
 * Small n: Straus with per-point 4-bit tables (cheap setup).
 * Large n: Pippenger bucket method — per window of c bits, scatter
 * every point into one of 2^c-1 buckets (one add each), then fold the
 * buckets with the running-sum trick (2*(2^c-1) adds) and shift the
 * accumulator by c doublings. Total ≈ (256/c)*(n + 2^(c+1)) adds vs
 * Straus's ~74n: at n=4096 (a 2048-signature round, 2 points each)
 * that is ~2x fewer point additions, and the bucket scratch is O(2^c)
 * instead of Straus's n*16 table. */
#define MSM_MAX 4096
#define STRAUS_MAX 64

static int msm_straus(ge *out, size_t n, const ge *pts, const uint8_t *scalars) {
    static ge tables[STRAUS_MAX][16];
    if (n > STRAUS_MAX) return -1;
    for (size_t i = 0; i < n; i++) {
        ge_identity(&tables[i][0]);
        tables[i][1] = pts[i];
        for (int d = 2; d < 16; d++)
            ge_add(&tables[i][d], &tables[i][d - 1], &pts[i]);
    }
    ge acc;
    ge_identity(&acc);
    for (int w = 63; w >= 0; w--) {
        ge_add(&acc, &acc, &acc);
        ge_add(&acc, &acc, &acc);
        ge_add(&acc, &acc, &acc);
        ge_add(&acc, &acc, &acc);
        for (size_t i = 0; i < n; i++) {
            int d = (scalars[i * 32 + (w >> 1)] >> ((w & 1) * 4)) & 0xF;
            if (d) ge_add(&acc, &acc, &tables[i][d]);
        }
    }
    *out = acc;
    return 0;
}

/* c bits of a 32-byte LE scalar starting at bit position `bit` (c <= 8,
 * so two bytes always cover the window) */
static int scalar_window(const uint8_t *s, int bit, int c) {
    int byte = bit >> 3, shift = bit & 7;
    uint32_t v = s[byte];
    if (byte + 1 < 32) v |= (uint32_t)s[byte + 1] << 8;
    return (int)((v >> shift) & ((1u << c) - 1));
}

static int msm_pippenger(ge *out, size_t n, const ge *pts,
                         const uint8_t *scalars) {
    int c = n < 1024 ? 6 : 8; /* ~optimal where this path runs */
    int nbuckets = (1 << c) - 1;
    static ge buckets[255];
    int windows = (256 + c - 1) / c;
    ge acc;
    ge_identity(&acc);
    for (int w = windows - 1; w >= 0; w--) {
        for (int j = 0; j < c; j++) ge_add(&acc, &acc, &acc);
        for (int j = 0; j < nbuckets; j++) ge_identity(&buckets[j]);
        int bit = w * c;
        for (size_t i = 0; i < n; i++) {
            int d = scalar_window(scalars + 32 * i, bit, c);
            if (d) ge_add(&buckets[d - 1], &buckets[d - 1], &pts[i]);
        }
        /* sum_d d*bucket[d] = sum of suffix running sums */
        ge sum, runsum;
        ge_identity(&sum);
        ge_identity(&runsum);
        for (int j = nbuckets - 1; j >= 0; j--) {
            ge_add(&runsum, &runsum, &buckets[j]);
            ge_add(&sum, &sum, &runsum);
        }
        ge_add(&acc, &acc, &sum);
    }
    *out = acc;
    return 0;
}

static int msm(ge *out, size_t n, const ge *pts, const uint8_t *scalars) {
    if (n > MSM_MAX) return -1;
    if (n <= STRAUS_MAX) return msm_straus(out, n, pts, scalars);
    return msm_pippenger(out, n, pts, scalars);
}

/* Decoded-public-key cache: ristretto decode costs one field
 * exponentiation (~15-19 us on a weak core) and the batch equation
 * decodes TWO points per signature — but the A_i are client identity
 * keys, which repeat heavily across a session's requests, while the
 * R_i are fresh nonce points every time. Direct-mapped, keyed by the
 * full 32-byte encoding; stores only successfully-decoded canonical
 * points, so a hit is exactly equivalent to a fresh decode. Callers
 * (r255_verify1 / r255_batch_check) run under the Python wrapper's
 * module lock, which serializes all access to this static table. */
#define PUBCACHE_BITS 13
#define PUBCACHE_N (1 << PUBCACHE_BITS)
static struct { uint8_t key[32]; ge val; uint8_t full; } pubcache[PUBCACHE_N];

static int ristretto_decode_pub(ge *out, const uint8_t enc[32]) {
    uint64_t h;
    memcpy(&h, enc, 8);
    uint32_t slot = (uint32_t)(h ^ (h >> 17) ^ (h >> 31)) & (PUBCACHE_N - 1);
    if (pubcache[slot].full && memcmp(pubcache[slot].key, enc, 32) == 0) {
        *out = pubcache[slot].val;
        return 0;
    }
    if (ristretto_decode(out, enc) != 0) return -1;
    memcpy(pubcache[slot].key, enc, 32);
    pubcache[slot].val = *out;
    pubcache[slot].full = 1;
    return 0;
}

/* ---------------- exported checks ---------------- */

/* s*B == R + k*A; all inputs 32-byte LE. 1 valid, 0 invalid, -1 bad input */
int r255_verify1(const uint8_t pub[32], const uint8_t r_enc[32],
                 const uint8_t s[32], const uint8_t k[32]) {
    if (r255_init() != 0) return -1;
    ge a_pt, big_r, left, right;
    if (ristretto_decode_pub(&a_pt, pub) != 0) return -1;
    if (ristretto_decode(&big_r, r_enc) != 0) return -1;
    fixed_mult(&left, s);
    ge pts[1] = {a_pt};
    if (msm(&right, 1, pts, k) != 0) return -1;
    ge_add(&right, &right, &big_r);
    return ristretto_eq(&left, &right);
}

/* fixed(sb) == sum z_i*R_i + zk_i*A_i over n items.
 * rs/as_: n*32 bytes of encodings; z/zk: n*32 LE reduced scalars. */
int r255_batch_check(size_t n, const uint8_t *rs, const uint8_t *as_,
                     const uint8_t *z, const uint8_t *zk,
                     const uint8_t sb[32]) {
    if (r255_init() != 0) return -1;
    if (2 * n > MSM_MAX) return -1;
    static ge pts[MSM_MAX];
    static uint8_t scal[MSM_MAX * 32];
    for (size_t i = 0; i < n; i++) {
        if (ristretto_decode(&pts[2 * i], rs + 32 * i) != 0) return -1;
        if (ristretto_decode_pub(&pts[2 * i + 1], as_ + 32 * i) != 0) return -1;
        memcpy(scal + 64 * i, z + 32 * i, 32);
        memcpy(scal + 64 * i + 32, zk + 32 * i, 32);
    }
    ge left, right;
    fixed_mult(&left, sb);
    if (msm(&right, 2 * n, pts, scal) != 0) return -1;
    return ristretto_eq(&left, &right);
}

/* RFC 9496 encode of an internal point */
static void ristretto_encode_ge(uint8_t out[32], const ge *pp) {
    ge p = *pp;
    fe u1, u2, t, den1, den2, z_inv, ix0, iy0, enchanted, x, y, den_inv, s_out;
    fe_add(&u1, &p.z, &p.y);
    fe_sub(&t, &p.z, &p.y); fe_carry(&t);
    fe_mul(&u1, &u1, &t);
    fe_mul(&u2, &p.x, &p.y);
    fe u2sq, mulv;
    fe_sq(&u2sq, &u2);
    fe_mul(&mulv, &u1, &u2sq);
    fe one;
    fe_one(&one);
    fe invsqrt;
    sqrt_ratio_m1(&invsqrt, &one, &mulv);
    fe_mul(&den1, &invsqrt, &u1);
    fe_mul(&den2, &invsqrt, &u2);
    fe_mul(&z_inv, &den1, &den2);
    fe_mul(&z_inv, &z_inv, &p.t);
    fe_mul(&ix0, &p.x, &FE_SQRT_M1);
    fe_mul(&iy0, &p.y, &FE_SQRT_M1);
    /* INVSQRT_A_MINUS_D = 1/sqrt(a-d) with a=-1: sqrt_ratio(1, -1-d) */
    fe amd;
    fe_one(&amd);
    fe_neg(&amd, &amd);
    fe_sub(&amd, &amd, &FE_D); fe_carry(&amd);
    sqrt_ratio_m1(&enchanted, &one, &amd);
    fe_mul(&enchanted, &den1, &enchanted);
    fe tz;
    fe_mul(&tz, &p.t, &z_inv);
    int rotate = fe_isnegative(&tz);
    if (rotate) {
        fe_copy(&x, &iy0); fe_copy(&y, &ix0); fe_copy(&den_inv, &enchanted);
    } else {
        fe_copy(&x, &p.x); fe_copy(&y, &p.y); fe_copy(&den_inv, &den2);
    }
    fe xz;
    fe_mul(&xz, &x, &z_inv);
    if (fe_isnegative(&xz)) fe_neg(&y, &y);
    fe_sub(&t, &p.z, &y); fe_carry(&t);
    fe_mul(&s_out, &den_inv, &t);
    fe_cabs(&s_out, &s_out);
    fe_tobytes(out, &s_out);
}

/* test hook: decode+re-encode (canonicality / round-trip checks) */
int r255_encode(uint8_t out[32], const uint8_t in[32]) {
    if (r255_init() != 0) return -1;
    ge p;
    if (ristretto_decode(&p, in) != 0) return -1;
    ristretto_encode_ge(out, &p);
    return 0;
}

/* out = s*B (fixed-base, for client-side signing). 0 ok, -1 init fail */
int r255_mult_base(uint8_t out[32], const uint8_t s[32]) {
    if (r255_init() != 0) return -1;
    ge p;
    fixed_mult(&p, s);
    ristretto_encode_ge(out, &p);
    return 0;
}

/* ------------------------------------------------------------------ */
/* Keccak-f[1600] (FIPS 202 permutation), for the merlin/STROBE layer  */
/* under sr25519 signatures (session/merlin.py).  The pure-Python      */
/* permutation costs ~10^2 us; per-request signature verification runs */
/* several permutations, so the hot path dispatches here when loaded.  */
/* State: 200 bytes, 25 little-endian u64 lanes.                       */
/* ------------------------------------------------------------------ */

static const uint64_t keccak_rc[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808aULL,
    0x8000000080008000ULL, 0x000000000000808bULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008aULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000aULL,
    0x000000008000808bULL, 0x800000000000008bULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800aULL, 0x800000008000000aULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL,
};

static const int keccak_rot[25] = {
    0, 1, 62, 28, 27, 36, 44, 6, 55, 20, 3, 10, 43, 25, 39,
    41, 45, 15, 21, 8, 18, 2, 61, 56, 14,
};

static uint64_t rotl64(uint64_t v, int n) {
    return n == 0 ? v : (v << n) | (v >> (64 - n));
}

void r255_keccak_f1600(uint8_t state[200]) {
    uint64_t a[25];
    for (int i = 0; i < 25; i++) {
        uint64_t v = 0;
        for (int j = 7; j >= 0; j--) v = (v << 8) | state[8 * i + j];
        a[i] = v;
    }
    for (int round = 0; round < 24; round++) {
        uint64_t c[5], d[5], b[25];
        for (int x = 0; x < 5; x++)
            c[x] = a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20];
        for (int x = 0; x < 5; x++)
            d[x] = c[(x + 4) % 5] ^ rotl64(c[(x + 1) % 5], 1);
        for (int x = 0; x < 5; x++)
            for (int y = 0; y < 25; y += 5) a[x + y] ^= d[x];
        for (int x = 0; x < 5; x++)
            for (int y = 0; y < 5; y++)
                b[y + 5 * ((2 * x + 3 * y) % 5)] =
                    rotl64(a[x + 5 * y], keccak_rot[x + 5 * y]);
        for (int x = 0; x < 5; x++)
            for (int y = 0; y < 25; y += 5)
                a[x + y] = b[x + y] ^ (~b[(x + 1) % 5 + y] & b[(x + 2) % 5 + y]);
        a[0] ^= keccak_rc[round];
    }
    for (int i = 0; i < 25; i++) {
        uint64_t v = a[i];
        for (int j = 0; j < 8; j++) { state[8 * i + j] = (uint8_t)v; v >>= 8; }
    }
}

/* ------------------------------------------------------------------ */
/* STROBE-128 duplex (the trimmed subset merlin embeds) over the      */
/* permutation above — the per-request signature hot path runs ~8     */
/* transcript ops per challenge derivation, and the Python framing    */
/* (session/merlin.py) costs ~85 us/challenge; these C ops cut that   */
/* to single-digit us. Layout: one 203-byte blob shared with Python:  */
/*   [0..200) keccak state | [200] pos | [201] pos_begin | [202] cur_flags */
/* merlin.py's pure-Python Strobe128 is the correctness oracle.       */
/* ------------------------------------------------------------------ */

#define STROBE_R 166
#define SF_I 1
#define SF_A 2
#define SF_C 4
#define SF_T 8
#define SF_M 16
#define SF_K 32

static void strobe_run_f(uint8_t *b) {
    b[b[200]] ^= b[201];
    b[b[200] + 1] ^= 0x04;
    b[STROBE_R + 1] ^= 0x80;
    r255_keccak_f1600(b);
    b[200] = 0;
    b[201] = 0;
}

static void strobe_absorb(uint8_t *b, const uint8_t *d, size_t n) {
    uint8_t pos = b[200];
    for (size_t i = 0; i < n; i++) {
        b[pos++] ^= d[i];
        if (pos == STROBE_R) {
            b[200] = pos;
            strobe_run_f(b);
            pos = 0;
        }
    }
    b[200] = pos;
}

static void strobe_overwrite(uint8_t *b, const uint8_t *d, size_t n) {
    uint8_t pos = b[200];
    for (size_t i = 0; i < n; i++) {
        b[pos++] = d[i];
        if (pos == STROBE_R) {
            b[200] = pos;
            strobe_run_f(b);
            pos = 0;
        }
    }
    b[200] = pos;
}

static void strobe_squeeze(uint8_t *b, uint8_t *out, size_t n) {
    uint8_t pos = b[200];
    for (size_t i = 0; i < n; i++) {
        out[i] = b[pos];
        b[pos++] = 0;
        if (pos == STROBE_R) {
            b[200] = pos;
            strobe_run_f(b);
            pos = 0;
        }
    }
    b[200] = pos;
}

static int strobe_begin_op(uint8_t *b, uint8_t flags, int more) {
    if (more) return flags == b[202] ? 0 : -1;
    if (flags & SF_T) return -2;
    uint8_t header[2];
    header[0] = b[201];           /* old pos_begin */
    header[1] = flags;
    b[201] = b[200] + 1;
    b[202] = flags;
    strobe_absorb(b, header, 2);
    if ((flags & (SF_C | SF_K)) && b[200] != 0) strobe_run_f(b);
    return 0;
}

/* op: 0 = meta_ad, 1 = ad, 2 = prf (data unused, out filled), 3 = key */
int r255_strobe_op(uint8_t *b, int op, const uint8_t *data, size_t n,
                   uint8_t *out, int more) {
    int rc;
    switch (op) {
    case 0:
        rc = strobe_begin_op(b, SF_M | SF_A, more);
        if (rc) return rc;
        strobe_absorb(b, data, n);
        return 0;
    case 1:
        rc = strobe_begin_op(b, SF_A, more);
        if (rc) return rc;
        strobe_absorb(b, data, n);
        return 0;
    case 2:
        rc = strobe_begin_op(b, SF_I | SF_A | SF_C, more);
        if (rc) return rc;
        strobe_squeeze(b, out, n);
        return 0;
    case 3:
        rc = strobe_begin_op(b, SF_A | SF_C, more);
        if (rc) return rc;
        strobe_overwrite(b, data, n);
        return 0;
    }
    return -3;
}

/* merlin append_message: meta_ad(label) ‖ meta_ad(LE32(len), more) ‖ ad(msg)
   — one library crossing instead of three (transcript.rs framing). */
void r255_merlin_append(uint8_t *b, const uint8_t *label, size_t llen,
                        const uint8_t *msg, size_t mlen) {
    uint8_t le[4] = {(uint8_t)mlen, (uint8_t)(mlen >> 8),
                     (uint8_t)(mlen >> 16), (uint8_t)(mlen >> 24)};
    strobe_begin_op(b, SF_M | SF_A, 0);
    strobe_absorb(b, label, llen);
    strobe_absorb(b, le, 4);
    strobe_begin_op(b, SF_A, 0);
    strobe_absorb(b, msg, mlen);
}

/* merlin challenge_bytes: meta_ad(label) ‖ meta_ad(LE32(n), more) ‖ PRF(n). */
void r255_merlin_challenge(uint8_t *b, const uint8_t *label, size_t llen,
                           uint8_t *out, size_t n) {
    uint8_t le[4] = {(uint8_t)n, (uint8_t)(n >> 8), (uint8_t)(n >> 16),
                     (uint8_t)(n >> 24)};
    strobe_begin_op(b, SF_M | SF_A, 0);
    strobe_absorb(b, label, llen);
    strobe_absorb(b, le, 4);
    strobe_begin_op(b, SF_I | SF_A | SF_C, 0);
    strobe_squeeze(b, out, n);
}

/* The full schnorrkel Fiat–Shamir challenge in one crossing: clone the
   cached SigningContext prefix (203-byte blob), absorb the message and
   the sign.rs label sequence, squeeze 64 challenge bytes. Labels are
   schnorrkel-og 0.11 sign.rs/context.rs; session/merlin.py's Python
   framing is the oracle (tests/test_merlin.py equivalence). */
void r255_schnorrkel_challenge(const uint8_t *prefix_blob,
                               const uint8_t *msg, size_t mlen,
                               const uint8_t *pub, const uint8_t *r_enc,
                               uint8_t *out64) {
    uint8_t b[203];
    memcpy(b, prefix_blob, 203);
    r255_merlin_append(b, (const uint8_t *)"sign-bytes", 10, msg, mlen);
    r255_merlin_append(b, (const uint8_t *)"proto-name", 10,
                       (const uint8_t *)"Schnorr-sig", 11);
    r255_merlin_append(b, (const uint8_t *)"sign:pk", 7, pub, 32);
    r255_merlin_append(b, (const uint8_t *)"sign:R", 6, r_enc, 32);
    r255_merlin_challenge(b, (const uint8_t *)"sign:c", 6, out64, 64);
}
