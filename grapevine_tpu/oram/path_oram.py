"""Batched Path ORAM as a branchless JAX array program.

Re-designs the reference's storage layer (upstream ``mc-oblivious-ram``'s
PathORAM-4096-Z4 over ``aligned-cmov``; named at reference README.md:16,49
and SURVEY.md §2b) for TPU:

- the bucket tree lives in HBM as two arrays chosen for XLA-TPU layout
  behavior (each alternative was measured to force multi-GB relayout
  copies or pathological strided slices — see the layout note on
  ``OramState``): a flat 1-D slot-index array ``tree_idx[n*Z]`` and a
  2-D value array ``tree_val[n, Z*V]`` whose 4080-byte rows match
  upstream's PathORAM-4096 bucket granularity;
- per-block leaf assignments are **not** stored in the tree: the flat
  position map in private memory is authoritative, and working-set
  leaves are one private gather away. (Upstream stores leaves in bucket
  metadata because its enclave cannot afford a big in-EPC posmap; here
  the posmap is already resident private state.)
- the stash is a fixed-size array scanned with masked selects (the
  vectorized constant-time linear scan);
- eviction is the textbook greedy deepest-first assignment, computed as
  masked prefix-sums + one conflict-free scatter per access.

Threat model (the TPU translation of "inside the enclave" vs "untrusted
host", SURVEY.md §1): the *public access transcript* is the sequence of
bucket-tree paths (equivalently leaf indices) touched on the big HBM tree
arrays. Obliviousness means this sequence is independent of which logical
blocks are accessed and what operations are performed. The position map,
stash, free lists, and scalar engine state are private working state (the
EPC analog); upstream likewise keeps its top-level position map inside the
enclave.

Algorithm per access (Path ORAM, Stefanov et al., PAPERS.md):
  1. ``leaf = posmap[idx]``; remap ``posmap[idx] = new_leaf`` (caller
     supplies fresh uniform randomness — keeping the module deterministic
     given its inputs, which is what makes transcript replay testable).
  2. Fetch the ``height+1`` buckets on the root→leaf path into a working
     set alongside the stash.
  3. One masked scan finds the block; the caller's branchless ``fn``
     computes the new value / keep / insert decision.
  4. Greedy eviction reassigns every working-set entry to the deepest
     bucket on the fetched path compatible with its leaf (common-prefix
     depth), at most ``bucket_slots`` per bucket; leftovers return to the
     stash. Stash overflow is counted in a sticky uint32 — it must never
     fire at the configured geometry (tests assert this; Z=4 theory says
     negligible).
  5. Write the path back (same addresses — the write transcript equals the
     read transcript).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, NamedTuple

import jax

from ..config import TPU_BACKENDS as _TPU_BACKENDS
import jax.numpy as jnp

from ..oblivious.bucket_cipher import epoch_next, row_keystream  # noqa: F401  (row_keystream used by cipher_rows)
from ..oblivious.primitives import SENTINEL, first_true_onehot, onehot_select, rank_of
from ..obs.phases import device_phase

U32 = jnp.uint32

#: u32-lane certified geometry (rangelint, OPERATIONS.md §18): the
#: largest tree this codebase's index arithmetic provably never wraps
#: at. OramConfig.__post_init__ refuses anything bigger.
MAX_U32_HEIGHT = 29
MAX_U32_BLOCKS = 1 << 30


def RANGELINT_BOUNDS(cfg: "OramConfig", prefix: str = "state") -> dict:
    """Rangelint input-interval anchors (analysis/rangelint.py) for one
    ``OramState`` pytree under ``prefix`` — the declared invariants of
    the private planes where geometry-bounded values enter a traced
    round:

    - position values (flat table, stash/cache leaf metadata, the
      recursive map's internal table and packed entry values) are
      leaves: ``< cfg.leaves``;
    - everything encrypted at rest (HBM tree planes under the cipher)
      or sentinel-bearing (stash/cache idx) stays at the full u32 lane
      — ciphertext is opaque to interval reasoning, and the round's own
      clamps/masks re-establish bounds after decryption (the posmap
      ``& (leaves-1)`` masks, the eviction bid clamp).

    Declared bounds are *assumptions* the rest of the program is
    certified against; each is an invariant an existing test pins."""
    lv = cfg.leaves - 1
    b = {
        f"{prefix}.stash_leaf": (0, lv),
        f"{prefix}.cache_leaf": (0, lv),
        # sticky diagnostic counter with a declared per-run increment
        # budget (2^16 ≫ any round's possible drops): the budgeted
        # headroom is what certifies `overflow + dropped` wrap-free
        f"{prefix}.overflow": (0, 2**32 - 2**16),
    }
    if cfg.delayed_eviction:
        # delayed-eviction planes (evict_window > 1): ebuf_idx/ebuf_val
        # are sentinel-bearing/opaque like stash_idx (full lane);
        # ebuf_leaf carries leaf values like stash_leaf; the public
        # window ledger holds former transcript leaves. The counters
        # carry their window invariants — ebuf_rounds resets at every
        # flush and the accumulate round increments within the declared
        # [0, W] budget; ebuf_gen/fetch_tag are monotone generation
        # counters with the sticky-counter increment budget (one bump
        # per flush; 2^32−2^16 flushes ≫ any run).
        b[f"{prefix}.ebuf_leaf"] = (0, lv)
        b[f"{prefix}.ebuf_paths"] = (0, lv)
        b[f"{prefix}.ebuf_rounds"] = (0, cfg.evict_window)
        b[f"{prefix}.ebuf_gen"] = (0, 2**32 - 2**16)
        b[f"{prefix}.fetch_tag"] = (0, 2**32 - 2**16)
    if not cfg.encrypted:
        # plaintext trees carry their leaf metadata un-ciphered
        b[f"{prefix}.tree_leaf"] = (0, lv)
    if cfg.posmap is None:
        b[f"{prefix}.posmap"] = (0, lv)
    else:
        from .posmap import inner_oram_config

        icfg = inner_oram_config(cfg.posmap)
        inner = f"{prefix}.posmap.inner"
        # the internal ORAM's block values are packed OUTER leaf
        # entries; its own flat map holds INTERNAL leaves
        b[f"{inner}.posmap"] = (0, icfg.leaves - 1)
        b[f"{inner}.stash_val"] = (0, lv)
        b[f"{inner}.cache_val"] = (0, lv)
        b[f"{inner}.overflow"] = (0, 2**32 - 2**16)
        if icfg.delayed_eviction:
            # the internal tree's deferral planes: buffer values are
            # packed OUTER leaf entries (like stash_val), its leaf
            # mirror and window ledger hold INTERNAL leaves
            b[f"{inner}.ebuf_val"] = (0, lv)
            b[f"{inner}.ebuf_leaf"] = (0, icfg.leaves - 1)
            b[f"{inner}.ebuf_paths"] = (0, icfg.leaves - 1)
            b[f"{inner}.ebuf_rounds"] = (0, icfg.evict_window)
            b[f"{inner}.ebuf_gen"] = (0, 2**32 - 2**16)
            b[f"{inner}.fetch_tag"] = (0, 2**32 - 2**16)
        if not icfg.encrypted:
            b[f"{inner}.tree_val"] = (0, lv)
        b[f"{prefix}.posmap.dummy_entry"] = (0, lv)
    return b


def cipher_rows(
    cfg: "OramConfig",
    key: jax.Array,
    buckets: jax.Array,  # u32[R] heap bucket ids
    epochs: jax.Array,  # u32[R, 2] per-row (lo, hi) nonce (0 = identity)
    pidx: jax.Array,  # u32[R, Z]
    pval: jax.Array,  # u32[R, Z*V]
):
    """XOR bucket rows with their keystream (encrypt ≡ decrypt).

    One ChaCha stream per (bucket, epoch) covers the Z slot-index words
    followed by the Z*V value words — a memory snapshot of the tree
    arrays reveals neither slot occupancy nor contents.

    ``cfg.cipher_impl == "pallas"`` routes through the fused Pallas
    kernel (keystream generated in VMEM and XORed in one pass — no HBM
    keystream materialization; oblivious/pallas_cipher.py). Both
    implementations produce bit-identical ciphertext."""
    if not cfg.encrypted:
        return pidx, pval
    z = cfg.bucket_slots
    if cfg.cipher_impl in ("pallas", "pallas_fused", "pallas_fused_tiled"):
        from ..oblivious.pallas_cipher import cipher_rows_pallas

        interpret = jax.default_backend() not in _TPU_BACKENDS
        if interpret and pidx.shape[0] >= 2048:
            # trace-time (once per compile), not per round: interpret
            # mode on a production-size engine means thousands of
            # per-tile host dispatches — a silent perf cliff on any
            # non-TPU backend (ADVICE r3). Correctness is unaffected.
            import warnings

            warnings.warn(
                f"pallas bucket cipher running in interpret mode on "
                f"backend {jax.default_backend()!r} with "
                f"{pidx.shape[0]} rows/round — expect a severe "
                f"slowdown; use bucket_cipher_impl='jnp' off-TPU",
                RuntimeWarning,
                stacklevel=2,
            )
        return cipher_rows_pallas(
            key, buckets, epochs, pidx, pval, cfg.cipher_rounds,
            interpret=interpret,
        )
    ks = row_keystream(key, buckets, epochs, cfg.row_words, cfg.cipher_rounds)
    return pidx ^ ks[:, :z], pval ^ ks[:, z:]


@dataclasses.dataclass(frozen=True)
class OramConfig:
    """Static geometry (hashable: safe as a jit static argument).

    The logical block-index space and the leaf space are decoupled:
    ``blocks`` defaults to ``leaves`` (the classic ~12.5%-utilization
    Path ORAM shape) but may exceed it — ``blocks = 2·leaves`` halves
    tree HBM per block at 25% slot utilization (still conservative:
    total slots = 8·leaves = 4·blocks), and shortens every path by one
    level. Stash behavior at elevated density is covered by the
    randomized density tests (tests/test_oram.py)."""

    height: int  # leaves = 2**height
    value_words: int  # uint32 words per block value
    bucket_slots: int = 4  # Z
    stash_size: int = 96
    #: ChaCha rounds for at-rest bucket encryption; 0 disables the
    #: cipher (oblivious/bucket_cipher.py — the EPC-encryption analog)
    cipher_rounds: int = 0
    #: "jnp" or "pallas" (fused VMEM keystream+XOR kernel; see
    #: cipher_rows and oblivious/pallas_cipher.py)
    cipher_impl: str = "jnp"
    #: logical block index space [0, n_blocks); None = leaves
    n_blocks: int | None = None
    #: position-map geometry (oram/posmap.py): None = the flat private
    #: u32[blocks+1] table; a PosMapSpec = the recursive position ORAM
    #: (state.posmap becomes a RecursivePosMapState pytree, and the
    #: bucket tree carries a per-slot leaf-metadata plane so eviction
    #: never consults the map). Part of the hashable static geometry —
    #: jit static args and the checkpoint fingerprint cover it.
    posmap: "object | None" = None
    #: tree-top cache (ROADMAP item 1, arXiv:1501.01721 §tree-top
    #: caching): the top k levels — 2^k−1 buckets, on EVERY root→leaf
    #: path, so caching them is access-pattern-neutral by construction —
    #: live decrypted in the dense ``cache_*`` planes (private working
    #: state, the stash's standing) instead of the encrypted HBM tree
    #: rows. Path fetch/write-back then touch only the bottom
    #: ``path_len − k`` levels of the big tree arrays, and the
    #: per-access cipher work shrinks by the same fraction. 0 = off,
    #: bit-for-bit the uncached program.
    top_cache_levels: int = 0
    #: delayed batched eviction (ROADMAP item 1, PR 15; config.py
    #: ``evict_every``): the number of ``oram_round`` fetch calls
    #: between eviction flushes. 1 = evict+write-back every round,
    #: bit-for-bit the pre-PR-15 program (the ``ebuf_*``/``fetch_tag``
    #: planes are zero-length). > 1 = ``oram_round`` runs the
    #: fetch-only program — gather+decrypt+stash/buffer update, ZERO
    #: tree scatters and zero encrypt work — and :func:`oram_flush`
    #: performs one batched eviction+write-back over the union of the
    #: window's fetched paths. The engine maps its ``evict_every=E`` to
    #: window E on the records tree and 2E on the mailbox tree (two
    #: mailbox rounds per engine round).
    evict_window: int = 1
    #: paths fetched per ``oram_round`` call (B for the records tree,
    #: B·D for the mailbox tree); sizes the public ``ebuf_paths`` plane.
    #: Required > 0 iff ``evict_window > 1``.
    evict_fetch_count: int = 0
    #: eviction-buffer capacity in rows (the bounded private buffer
    #: fetched path contents accumulate in between flushes — stash
    #: standing). Required > 0 iff ``evict_window > 1``.
    evict_buffer_slots: int = 0

    def __post_init__(self):
        k = self.top_cache_levels
        if not (0 <= k <= self.height):
            raise ValueError(
                f"top_cache_levels must be in [0, height={self.height}] "
                f"(at least the leaf level stays in the HBM tree), got {k}"
            )
        w = self.evict_window
        if w < 1:
            raise ValueError(f"evict_window must be >= 1, got {w}")
        if w > 1 and (self.evict_fetch_count < 1
                      or self.evict_buffer_slots < 1):
            raise ValueError(
                "evict_window > 1 (delayed batched eviction) needs "
                "evict_fetch_count and evict_buffer_slots > 0, got "
                f"fetch_count={self.evict_fetch_count}, "
                f"buffer_slots={self.evict_buffer_slots}"
            )
        # rangelint certified-geometry guard (analysis/rangelint.py;
        # tools/check_ranges.py cites this refusal in its report): every
        # device lane is u32 and every gather/scatter index converts to
        # int32 on the way into XLA, so the geometry must keep (a) heap
        # bucket ids plus the bucket-axis OOB-drop sentinel
        # (n_buckets_padded) within int32, (b) the leaf-plane cipher's
        # domain-separation offset (bucket + n_buckets_padded) within
        # u32, and (c) block ids plus the row-map sentinel (blocks + 2)
        # within int32 and below SENTINEL. height <= 29 and blocks <=
        # 2^30 certify all three with margin (the full argument is the
        # certified-geometry table, OPERATIONS.md §18). Scaling past
        # this bound is recipient-space sharding (ROADMAP item 2) or a
        # deeper recursion with widened lanes (item 4) — never a silent
        # wraparound.
        if self.height > MAX_U32_HEIGHT:
            raise ValueError(
                f"height {self.height} exceeds the u32-lane certified "
                f"bound (height <= {MAX_U32_HEIGHT}: heap bucket ids and "
                "int32 index conversions wrap past it — rangelint "
                "certified geometry, OPERATIONS.md §18); shard the "
                "recipient space or widen the lanes instead"
            )
        if self.blocks > MAX_U32_BLOCKS:
            raise ValueError(
                f"blocks {self.blocks} exceeds the u32-lane certified "
                f"bound (blocks <= {MAX_U32_BLOCKS} = 2^30: block ids, "
                "the dummy index, and the row-map drop sentinel must fit "
                "int32 below SENTINEL — rangelint certified geometry, "
                "OPERATIONS.md §18); shard the recipient space or widen "
                "the lanes instead"
            )

    @property
    def encrypted(self) -> bool:
        return self.cipher_rounds > 0

    @property
    def delayed_eviction(self) -> bool:
        """True iff this tree accumulates fetches and flushes in batches
        (``evict_window > 1``); False = the classic per-round program."""
        return self.evict_window > 1

    @property
    def cache_buckets(self) -> int:
        """Buckets resident in the tree-top cache: 2^k − 1 (heap indices
        [0, 2^k−1) — the top k levels are a contiguous heap prefix, so
        the cache planes are indexed by heap id directly)."""
        return (1 << self.top_cache_levels) - 1

    @property
    def row_words(self) -> int:
        """Keystream width per bucket: Z slot-index words + Z*V value
        words, enciphered as one row under one (bucket, epoch) nonce."""
        return self.bucket_slots + self.bucket_slots * self.value_words

    @property
    def leaves(self) -> int:
        return 1 << self.height

    @property
    def blocks(self) -> int:
        return self.n_blocks if self.n_blocks is not None else self.leaves

    @property
    def n_buckets(self) -> int:
        return (1 << (self.height + 1)) - 1

    @property
    def n_buckets_padded(self) -> int:
        """Tree arrays are allocated one bucket past the heap (a power of
        two) so the bucket axis divides evenly across any power-of-two
        device mesh; heap indices never address the pad bucket."""
        return 1 << (self.height + 1)

    @property
    def path_len(self) -> int:
        return self.height + 1

    @property
    def work_size(self) -> int:
        return self.stash_size + self.path_len * self.bucket_slots

    #: reserved block index used by dummy accesses; never stored in the tree
    @property
    def dummy_index(self) -> int:
        return self.blocks


class OramState(NamedTuple):
    """ORAM state; a pytree (NamedTuple) so it jits/shards cleanly.

    Layout note (all measured on v5e, see git history): a 3-D value
    array ``[n, Z, V]`` with V=255 makes XLA relayout-copy the whole
    tree on gather (8 GB HLO temp, OOM at 2^20 capacity); narrow 2-D
    metadata ``[n, Z]`` gets a transposed ``{0,1}`` layout whose path
    slices dominate the round; a fully packed ``[n, Z*(2+V)]`` row
    (1028 words) is not lane-aligned, padding every row to 1152 words
    and again relayout-copying the tree. The split below keeps the
    value rows exactly ``Z*V`` words (1020 rec / 4096 mb — tile-clean)
    and the slot metadata 1-D, which XLA never transposes.
    """

    tree_idx: jax.Array  # u32[n_buckets * Z] flat; SENTINEL = empty slot
    tree_val: jax.Array  # u32[n_buckets, Z*V]; one row per bucket
    #: tree-top cache planes (cfg.top_cache_levels = k > 0; zero-length
    #: otherwise): the decrypted-resident image of heap buckets
    #: [0, 2^k−1) — the authoritative copy; those buckets' HBM tree rows
    #: go stale (empty-at-init ciphertext, re-keyed but never read).
    #: Private working state with the stash's standing (the EPC analog:
    #: VMEM/registers on TPU, a donated array elsewhere) — every path
    #: touches all k cached levels, so cache accesses are
    #: access-pattern-neutral and the plane needs no cipher or nonces.
    #: Sealed checkpoints cover it like any other leaf (engine/
    #: checkpoint.py serializes the whole pytree).
    cache_idx: jax.Array  # u32[cache_buckets * Z] (or u32[0])
    cache_val: jax.Array  # u32[cache_buckets, Z*V] (or u32[0, Z*V])
    #: cache mirror of tree_leaf (recursive posmap only; u32[0] else)
    cache_leaf: jax.Array
    #: per-slot leaf assignment plane, recursive posmap only (u32[0]
    #: under a flat map): with the map demoted to its own ORAM, eviction
    #: can no longer gather the whole working set's leaves from a
    #: private array, so each tree slot carries its block's leaf — the
    #: classic recursive-construction bucket metadata (upstream
    #: mc-oblivious stores leaves in buckets for exactly this reason).
    #: Same shape/standing as tree_idx; encrypted at rest alongside it
    #: (leaf_plane_cipher — a leaf is a *future* fetch path, strictly
    #: snapshot-sensitive). Invariant: for every live block, this plane
    #: equals what the position map answers (both are written from the
    #: same op's new_leaf at its last within-round occurrence).
    tree_leaf: jax.Array  # u32[n_buckets * Z] flat (or u32[0])
    stash_idx: jax.Array  # u32[S]
    stash_val: jax.Array  # u32[S, V]
    #: stash mirror of tree_leaf (u32[S] recursive, u32[0] flat)
    stash_leaf: jax.Array
    #: delayed-eviction buffer planes (cfg.evict_window = W > 1;
    #: zero-length at W=1 — bit-for-bit the per-round-eviction layout):
    #: live blocks pulled off fetched paths accumulate here between
    #: flushes instead of being evicted back every round. Private
    #: working state with the stash's standing — checkpointed, swept by
    #: expiry exactly like the stash, recompacted buffer-first (the
    #: stash stays the spill/pressure signal), overflow shared with the
    #: stash's sticky counter. oram_flush drains it every W rounds.
    ebuf_idx: jax.Array  # u32[C]; SENTINEL = empty row
    ebuf_val: jax.Array  # u32[C, V]
    #: buffer mirror of stash_leaf (u32[C] recursive, u32[0] flat)
    ebuf_leaf: jax.Array
    #: PUBLIC flush-window bookkeeping (all derivable from the public
    #: transcript — the leaves fetched since the last flush and the
    #: round count — so none of it is an oblint taint anchor):
    #: the window's fetched leaves, row r·F.. holding round r's F leaves
    ebuf_paths: jax.Array  # u32[W * F] (u32[0] at W=1)
    #: fetch rounds since the last flush, in [0, W]
    ebuf_rounds: jax.Array  # u32 scalar
    #: flush generation counter (starts at 1); a bucket whose
    #: ``fetch_tag`` equals the current generation was fetched since the
    #: last flush — its HBM/cache copy is stale (the live rows moved to
    #: the buffer) and is masked out of working sets and sweeps.
    #: Bumping the generation at flush re-validates every bucket in O(1)
    #: with no plane-wide clear.
    ebuf_gen: jax.Array  # u32 scalar
    fetch_tag: jax.Array  # u32[n_buckets_padded] (u32[0] at W=1)
    #: position map: u32[blocks + 1] private table under a flat map
    #: (last entry backs the dummy index), or a RecursivePosMapState
    #: pytree (oram/posmap.py) when cfg.posmap is a PosMapSpec
    posmap: jax.Array
    overflow: jax.Array  # u32 scalar, sticky count of dropped blocks
    #: at-rest cipher state (zero-sized semantics when cfg.cipher_rounds
    #: == 0): per-bucket 64-bit write-epoch nonce (0 = never written ⇒
    #: identity keystream), the ChaCha key, and the global epoch counter
    nonces: jax.Array  # u32[n_buckets_padded, 2] (lo, hi)
    cipher_key: jax.Array  # u32[8]
    epoch: jax.Array  # u32[2] (lo, hi), next write epoch (starts at 1)


def init_oram(cfg: OramConfig, key: jax.Array) -> OramState:
    """Empty tree; position map initialized with uniform random leaves.

    With the cipher enabled the all-zero initial tree is its own
    ciphertext (epoch-0 convention, oblivious/bucket_cipher.py). The
    posmap pytree comes from oram/posmap.py: the flat u32[blocks+1]
    table under ``cfg.posmap is None`` (bit-for-bit the pre-PR-7 draw),
    or a RecursivePosMapState packing the same table values into an
    internal Path ORAM. The recursive layout also activates the
    per-slot leaf-metadata planes (zero-length otherwise)."""
    from .posmap import init_posmap

    z, v = cfg.bucket_slots, cfg.value_words
    k_pos, k_cipher = jax.random.split(key)
    n_leaf = cfg.n_buckets_padded * z if cfg.posmap is not None else 0
    n_sleaf = cfg.stash_size if cfg.posmap is not None else 0
    cb = cfg.cache_buckets
    n_cleaf = cb * z if cfg.posmap is not None else 0
    delayed = cfg.delayed_eviction
    c = cfg.evict_buffer_slots if delayed else 0
    n_eleaf = c if cfg.posmap is not None else 0
    npaths = cfg.evict_window * cfg.evict_fetch_count if delayed else 0
    ntag = cfg.n_buckets_padded if delayed else 0
    return OramState(
        tree_idx=jnp.full((cfg.n_buckets_padded * z,), SENTINEL, U32),
        tree_val=jnp.zeros((cfg.n_buckets_padded, z * v), U32),
        cache_idx=jnp.full((cb * z,), SENTINEL, U32),
        cache_val=jnp.zeros((cb, z * v), U32),
        cache_leaf=jnp.zeros((n_cleaf,), U32),
        tree_leaf=jnp.zeros((n_leaf,), U32),
        stash_idx=jnp.full((cfg.stash_size,), SENTINEL, U32),
        stash_val=jnp.zeros((cfg.stash_size, v), U32),
        stash_leaf=jnp.zeros((n_sleaf,), U32),
        ebuf_idx=jnp.full((c,), SENTINEL, U32),
        ebuf_val=jnp.zeros((c, v), U32),
        ebuf_leaf=jnp.zeros((n_eleaf,), U32),
        ebuf_paths=jnp.zeros((npaths,), U32),
        ebuf_rounds=jnp.zeros((), U32),
        # generation 1 with an all-zero tag plane: nothing is stale
        ebuf_gen=jnp.ones((), U32),
        fetch_tag=jnp.zeros((ntag,), U32),
        posmap=init_posmap(cfg, k_pos),
        overflow=jnp.zeros((), U32),
        nonces=jnp.zeros((cfg.n_buckets_padded, 2), U32),
        cipher_key=jax.random.bits(k_cipher, (8,), U32),
        epoch=jnp.array([1, 0], U32),
    )


def leaf_plane_cipher(
    cfg: OramConfig,
    key: jax.Array,
    buckets: jax.Array,  # u32[R] heap bucket ids
    epochs: jax.Array,  # u32[R, 2] per-row (lo, hi) nonce (0 = identity)
    pleaf: jax.Array,  # u32[R, Z]
) -> jax.Array:
    """XOR leaf-metadata rows with their keystream (encrypt ≡ decrypt).

    Recursive-posmap only: a slot's leaf value is the block's *future*
    fetch path — at least as snapshot-sensitive as the slot index — so
    the plane rides the bucket cipher. Domain separation from the
    idx/val row keystream (cipher_rows) is the nonce's bucket word
    offset by ``n_buckets_padded``: heap ids never reach that range, so
    the leaf stream can never two-time-pad against the row stream under
    the same (bucket, epoch). Kept out of ``cipher_rows`` on purpose —
    the fused Pallas fetch/write kernels cover only the idx/val planes,
    and this jnp path composes with all cipher_impls."""
    if not cfg.encrypted:
        return pleaf
    ks = row_keystream(
        key, buckets + U32(cfg.n_buckets_padded), epochs,
        cfg.bucket_slots, cfg.cipher_rounds,
    )
    return pleaf ^ ks


def path_bucket_indices(cfg: OramConfig, leaf: jax.Array) -> jax.Array:
    """Heap indices of the root→leaf path buckets. leaf: u32 → u32[path_len]."""
    depths = jnp.arange(cfg.path_len, dtype=U32)
    return ((jnp.uint32(1) << depths) - 1) + (leaf >> (cfg.height - depths))


def _common_prefix_depth(cfg: OramConfig, leaves_a: jax.Array, leaf_b: jax.Array):
    """Deepest path level where a block with leaf ``leaves_a[i]`` may live on
    the path to ``leaf_b``: the length of the common prefix of the two
    height-bit leaf numbers. Exact integer computation, unrolled over the
    (static) height."""
    # range argument (rangelint): the shifts stay in the u32 leaf lane
    # (shift amounts are trace-time constants in [0, height-1]) and the
    # int32 accumulator is bounded by height <= MAX_U32_HEIGHT — the
    # depth never approaches either lane's ceiling.
    d = jnp.zeros(leaves_a.shape, jnp.int32)
    for j in range(1, cfg.height + 1):
        shift = U32(cfg.height - j)
        d = d + (leaves_a >> shift == leaf_b >> shift).astype(jnp.int32)
    return d  # in [0, height]


def _path_gather(tree: jax.Array, path_b: jax.Array, axis_name: str | None):
    """Fetch the path bucket rows from a (possibly device-sharded) array.

    With ``axis_name`` set, the call runs inside ``shard_map`` and ``tree``
    is the local shard (contiguous range per device along axis 0). Each
    chip contributes the rows it owns, masked to zero elsewhere, and one
    ``psum`` over ICI assembles the full path on every chip — the
    collective form of BASELINE config 5's sharded bucket tree. The
    addresses touched remain exactly the public path, preserving the
    transcript."""
    if axis_name is None:
        return tree[path_b]
    n_local = tree.shape[0]
    base = (jax.lax.axis_index(axis_name) * n_local).astype(U32)
    loc = path_b - base
    mine = (path_b >= base) & (path_b < base + U32(n_local))
    vals = tree[jnp.where(mine, loc, 0)]
    mask = mine.reshape(mine.shape + (1,) * (vals.ndim - 1))
    return jax.lax.psum(jnp.where(mask, vals, jnp.zeros_like(vals)), axis_name)


def _path_scatter(
    tree: jax.Array,
    path_b: jax.Array,
    new_vals: jax.Array,
    axis_name: str | None,
    owner: jax.Array | None = None,
):
    """Write the path rows back; each chip writes only rows it owns
    (every heap index has exactly one owner, so the global write is
    consistent with no collective). ``owner`` optionally masks out slots
    that must not be written at all (round.py's duplicate-bucket copies);
    masked slots are dropped via out-of-range targets."""
    if axis_name is None:
        if owner is None:
            return tree.at[path_b].set(new_vals, unique_indices=True)
        tgt = jnp.where(owner, path_b, U32(tree.shape[0]))
        # in-bounds targets are unique by construction: the owner map
        # gives every heap bucket exactly one owning column, so at most
        # one write lands on any row (the rest drop out of bounds)
        return tree.at[tgt].set(new_vals, mode="drop", unique_indices=True)
    n_local = tree.shape[0]
    base = (jax.lax.axis_index(axis_name) * n_local).astype(U32)
    loc = path_b - base
    mine = (path_b >= base) & (path_b < base + U32(n_local))
    if owner is not None:
        mine = mine & owner
    tgt = jnp.where(mine, loc, U32(n_local))  # out of range = dropped
    return tree.at[tgt].set(new_vals, mode="drop", unique_indices=True)


def path_slot_indices(cfg: OramConfig, path_b: jax.Array) -> jax.Array:
    """Flat tree_idx slot indices for path buckets: [...,] → [..., Z]."""
    z = cfg.bucket_slots
    return path_b[..., None] * U32(z) + jnp.arange(z, dtype=U32)[None, :]


def working_leaves(
    state_posmap: jax.Array, cfg: OramConfig, idxs: jax.Array
) -> jax.Array:
    """Leaf assignment for working-set entries from the private posmap.

    SENTINEL/dummy slots read the throwaway posmap entry (cfg.blocks);
    their value is never used (eviction masks invalid entries)."""
    safe = jnp.where(idxs < U32(cfg.blocks), idxs, U32(cfg.blocks))
    return state_posmap[safe]


def oram_access(
    cfg: OramConfig,
    state: OramState,
    idx: jax.Array,  # u32 scalar block index (or cfg.dummy_index)
    new_leaf: jax.Array,  # u32 scalar, fresh uniform in [0, leaves)
    operand,
    fn: Callable,
    axis_name: str | None = None,
    pm_leaf: jax.Array | None = None,
):
    """One oblivious read-modify-write access.

    ``fn(value u32[V], present bool, operand) -> (new_value u32[V],
    keep bool, insert bool, out pytree)``:

    - if the block is present, its value becomes ``new_value``; ``keep``
      False removes it (DELETE);
    - if absent and ``insert``, ``(idx, new_value)`` is added (CREATE);
    - ``out`` is returned to the caller (fetched fields, status bits).

    ``fn`` must itself be branchless; it receives the *masked* value
    (zeros when absent). Returns ``(state', out, leaf)`` where ``leaf`` is
    the public transcript entry for this access — a u32 scalar under a
    flat map, u32[2] (payload leaf, internal posmap leaf) under a
    recursive one (``cfg.posmap`` set; ``pm_leaf`` must then supply a
    fresh uniform internal leaf — oram/posmap.py:lookup_remap_one).

    With ``axis_name`` set (inside ``shard_map``), the tree arrays are
    sharded along the bucket axis across the mesh and path fetch/write-back
    become masked collectives; stash, position map, and all decision logic
    are replicated — every chip runs the identical branchless program.
    """
    z, v, plen = cfg.bucket_slots, cfg.value_words, cfg.path_len
    recursive = cfg.posmap is not None

    if recursive:
        from .posmap import lookup_remap_one

        posmap, leaf, inner_leaf = lookup_remap_one(
            cfg, state.posmap, idx, new_leaf, pm_leaf
        )
    else:
        leaf = state.posmap[idx]
        posmap = state.posmap.at[idx].set(new_leaf)

    path_b = path_bucket_indices(cfg, leaf)  # u32[plen]

    # tree-top cache split: levels [0, kc) live decrypted in the cache
    # planes; only the bottom plen−kc levels touch the encrypted HBM
    # tree (and pay cipher work). kc=0 degenerates to the full path.
    # Slot-plane HBM addressing is bucket-axis ([n, Z] reshape views —
    # free, layout-identical): flat slot ids (bucket·Z + slot) escape
    # u32/int32 one geometry doubling before bucket ids do, so the
    # certified bound rides the bucket axis (rangelint; OPERATIONS.md
    # §18). The tiny cache planes keep flat slot addressing.
    kc = cfg.top_cache_levels
    bot_b = path_b[kc:]
    # runtime identity: top-kc heap ids are < cache_buckets by level
    # structure (see the matching clamp in round.py)
    top_b = jnp.minimum(path_b[:kc], U32(max(cfg.cache_buckets, 1) - 1))
    top_slots = path_slot_indices(cfg, top_b).reshape(-1)

    # --- fetch path ∪ stash into the working set -----------------------
    with device_phase("oram_fetch"):
        pidx = _path_gather(state.tree_idx.reshape(-1, z), bot_b, axis_name)
        pval = _path_gather(state.tree_val, bot_b, axis_name)
        pnonce = _path_gather(state.nonces, bot_b, axis_name)
        pidx, pval = cipher_rows(
            cfg, state.cipher_key, bot_b, pnonce, pidx, pval,
        )
        if kc:
            # cached top levels: plain private gathers (same standing as
            # the stash concatenate below — every path touches them)
            pidx = jnp.concatenate(
                [state.cache_idx[top_slots].reshape(kc, z), pidx]
            )
            pval = jnp.concatenate([state.cache_val[top_b], pval], axis=0)
        if recursive:
            pleaf = _path_gather(
                state.tree_leaf.reshape(-1, z), bot_b, axis_name
            )
            pleaf = leaf_plane_cipher(
                cfg, state.cipher_key, bot_b, pnonce, pleaf,
            )
            if kc:
                pleaf = jnp.concatenate(
                    [state.cache_leaf[top_slots].reshape(kc, z), pleaf]
                )
            pleaf = pleaf.reshape(-1)
    pidx = pidx.reshape(-1)
    pval = pval.reshape(-1, v)
    widx = jnp.concatenate([state.stash_idx, pidx])
    wval = jnp.concatenate([state.stash_val, pval], axis=0)
    if recursive:
        # leaves ride the per-slot metadata plane (the map can no longer
        # be gathered); the accessed block reads its fresh leaf below
        wleaf = jnp.concatenate([state.stash_leaf, pleaf])
    else:
        # leaves come from the (already remapped) private posmap: for the
        # accessed block that is new_leaf, for others their current leaf
        wleaf = working_leaves(posmap, cfg, widx)

    valid = widx != SENTINEL
    match = valid & (widx == idx)
    if recursive:
        # posmap↔metadata invariant: the map's entry for idx is already
        # new_leaf (remapped above), so the metadata row follows suit
        wleaf = jnp.where(match, new_leaf, wleaf)
    present = jnp.any(match)
    value = onehot_select(match, wval)

    new_value, keep, insert, out = fn(value, present, operand)

    # --- apply the modification obliviously ----------------------------
    wval = jnp.where(match[:, None], new_value[None, :], wval)
    drop = match & ~keep
    widx = jnp.where(drop, SENTINEL, widx)

    do_insert = insert & ~present & (idx != cfg.dummy_index)
    free = widx == SENTINEL
    ins_slot = first_true_onehot(free) & do_insert
    inserted = jnp.any(ins_slot)
    widx = jnp.where(ins_slot, idx, widx)
    wleaf = jnp.where(ins_slot, new_leaf, wleaf)
    wval = jnp.where(ins_slot[:, None], new_value[None, :], wval)
    # a full working set on insert is an overflow (cannot happen at sane
    # geometry: the path fetch alone frees plen*z slots)
    insert_dropped = do_insert & ~inserted

    # --- greedy deepest-first eviction ---------------------------------
    with device_phase("oram_evict"):
        valid = widx != SENTINEL
        depth = _common_prefix_depth(cfg, wleaf, leaf)  # int32[W]
        assign = jnp.full(valid.shape, -1, jnp.int32)  # path level, -1 = stash
        pos = jnp.zeros(valid.shape, jnp.int32)  # slot within the bucket
        placed = jnp.zeros(valid.shape, jnp.bool_)
        for level in range(cfg.height, -1, -1):
            eligible = valid & ~placed & (depth >= level)
            r = rank_of(eligible)
            chosen = eligible & (r < z)
            assign = jnp.where(chosen, level, assign)
            pos = jnp.where(chosen, r, pos)
            placed = placed | chosen

        # scatter placed entries into fresh path arrays (conflict-free:
        # each (level, pos) pair is chosen at most once)
        target = jnp.where(placed, assign * z + pos, plen * z)  # OOB = dropped
        new_pidx = jnp.full((plen * z,), SENTINEL, U32).at[target].set(widx, mode="drop")
        new_pval = jnp.zeros((plen * z, v), U32).at[target].set(wval, mode="drop")

    if recursive:
        new_pleaf = jnp.zeros((plen * z,), U32).at[target].set(wleaf, mode="drop")

    # --- compact the leftovers back into the stash ---------------------
    leftover = valid & ~placed
    srank = rank_of(leftover)
    starget = jnp.where(leftover, srank, cfg.stash_size)  # OOB = dropped
    stash_idx = jnp.full((cfg.stash_size,), SENTINEL, U32).at[starget].set(
        widx, mode="drop"
    )
    stash_val = jnp.zeros((cfg.stash_size, v), U32).at[starget].set(wval, mode="drop")
    stash_leaf = (
        jnp.zeros((cfg.stash_size,), U32).at[starget].set(wleaf, mode="drop")
        if recursive
        else state.stash_leaf
    )
    # == n_left - min(n_left, stash_size), interval-transparent form
    stash_dropped = jnp.maximum(
        jnp.sum(leftover.astype(jnp.int32)) - cfg.stash_size, 0
    )

    overflow = (
        state.overflow
        + stash_dropped.astype(U32)
        + insert_dropped.astype(U32)
    )

    # --- write the path back (write transcript ≡ read transcript) ------
    with device_phase("oram_writeback"):
        epochs_w = jnp.broadcast_to(state.epoch[None, :], (plen - kc, 2))
        enc_pidx, enc_pval = cipher_rows(
            cfg,
            state.cipher_key,
            bot_b,
            epochs_w,
            new_pidx.reshape(plen, z)[kc:],
            new_pval.reshape(plen, z * v)[kc:],
        )
        nonces = (
            _path_scatter(state.nonces, bot_b, epochs_w, axis_name)
            if cfg.encrypted
            else state.nonces
        )
        if kc:
            # cached levels write back plaintext into the cache planes
            # (a single path's buckets are distinct → unique targets)
            cache_idx = state.cache_idx.at[top_slots].set(
                new_pidx[: kc * z], unique_indices=True
            )
            cache_val = state.cache_val.at[top_b].set(
                new_pval.reshape(plen, z * v)[:kc], unique_indices=True
            )
        else:
            cache_idx, cache_val = state.cache_idx, state.cache_val
        cache_leaf = state.cache_leaf
        if recursive:
            enc_pleaf = leaf_plane_cipher(
                cfg, state.cipher_key, bot_b, epochs_w,
                new_pleaf.reshape(plen, z)[kc:],
            )
            tree_leaf = _path_scatter(
                state.tree_leaf.reshape(-1, z), bot_b, enc_pleaf, axis_name
            ).reshape(-1)
            if kc:
                cache_leaf = state.cache_leaf.at[top_slots].set(
                    new_pleaf[: kc * z], unique_indices=True
                )
        else:
            tree_leaf = state.tree_leaf
    new_state = OramState(
        tree_idx=_path_scatter(
            state.tree_idx.reshape(-1, z), bot_b, enc_pidx, axis_name
        ).reshape(-1),
        tree_val=_path_scatter(state.tree_val, bot_b, enc_pval, axis_name),
        cache_idx=cache_idx,
        cache_val=cache_val,
        cache_leaf=cache_leaf,
        tree_leaf=tree_leaf,
        stash_idx=stash_idx,
        stash_val=stash_val,
        stash_leaf=stash_leaf,
        # the op-major path never runs delayed eviction (config.py
        # forbids commit='op' + evict_every>1): zero-length passthrough
        ebuf_idx=state.ebuf_idx,
        ebuf_val=state.ebuf_val,
        ebuf_leaf=state.ebuf_leaf,
        ebuf_paths=state.ebuf_paths,
        ebuf_rounds=state.ebuf_rounds,
        ebuf_gen=state.ebuf_gen,
        fetch_tag=state.fetch_tag,
        posmap=posmap,
        overflow=overflow,
        nonces=nonces,
        cipher_key=state.cipher_key,
        epoch=epoch_next(state.epoch),
    )
    if recursive:
        leaf = jnp.stack([leaf, inner_leaf])
    return new_state, out, leaf


def oram_access_batch(
    cfg: OramConfig,
    state: OramState,
    idxs: jax.Array,  # u32[B]
    new_leaves: jax.Array,  # u32[B]
    operands,  # pytree with leading batch axis
    fn: Callable,
    axis_name: str | None = None,
    pm_leaves: jax.Array | None = None,  # u32[B] (recursive posmap only)
):
    """Sequentially-committed batch of accesses under one ``lax.scan``.

    Within-batch ordering is "commit in slot order" — the semantics this
    framework documents for batch hazards (two ops on one key in a round;
    SURVEY.md §7.6). Each scan iteration is itself a wide vector program,
    so the device pipelines the per-op work without host round-trips.

    Returns ``(state', outs, leaves)`` with outs/leaves batched; under a
    recursive posmap (``cfg.posmap`` set) ``pm_leaves`` supplies one
    fresh uniform internal leaf per access and ``leaves`` is u32[B, 2].
    """
    recursive = cfg.posmap is not None
    if recursive and pm_leaves is None:
        raise ValueError(
            "recursive posmap batch needs pm_leaves (fresh uniform "
            "internal leaves, one per access)"
        )

    def step(carry, xs):
        idx, new_leaf, pm_leaf, opnd = xs
        carry, out, leaf = oram_access(
            cfg, carry, idx, new_leaf, opnd, fn, axis_name, pm_leaf=pm_leaf
        )
        return carry, (out, leaf)

    pm = pm_leaves if recursive else jnp.zeros_like(new_leaves)
    state, (outs, leaves) = jax.lax.scan(
        step, state, (idxs, new_leaves, pm, operands)
    )
    return state, outs, leaves


def tree_cache_private_bytes(cfg: OramConfig) -> int:
    """Decrypted-resident bytes the tree-top cache pins for this tree
    (sizing helper for OPERATIONS.md §14 and bench.py tree_cache_ab):
    2^k−1 bucket rows of idx + val (+ leaf-metadata under a recursive
    posmap), all plaintext private state with the stash's standing."""
    z, v = cfg.bucket_slots, cfg.value_words
    leaf = z if cfg.posmap is not None else 0
    return cfg.cache_buckets * 4 * (z + z * v + leaf)


def derive_evict_buffer_slots(blocks: int, window: int, fetch_count: int,
                              z: int) -> int:
    """Auto buffer capacity for delayed eviction (OPERATIONS.md §19).

    ~2·Z live blocks of headroom per fetched path per window round plus
    insert slack, clamped by the whole block space: a buffer that can
    hold every live block can never overflow, and at small geometries
    the clamp is what fires. At production shapes the heuristic side
    wins — steady-state Path ORAM carries ~density live blocks per
    path (most mass at the leaves), so 2·Z ≈ 4·density is conservative;
    the sticky overflow counter + health canary catch undersizing."""
    return min(blocks, 2 * z * window * fetch_count + 4 * fetch_count)


def evict_buffer_private_bytes(cfg: OramConfig) -> int:
    """Resident plaintext bytes the eviction buffer pins for this tree
    (stash standing; OPERATIONS.md §18 sizing): C rows of idx + val
    (+ leaf under a recursive posmap), plus the public window
    bookkeeping (paths plane + per-bucket fetch tags)."""
    if not cfg.delayed_eviction:
        return 0
    c, v = cfg.evict_buffer_slots, cfg.value_words
    leaf = 1 if cfg.posmap is not None else 0
    rows = c * 4 * (1 + v + leaf)
    public = 4 * (cfg.evict_window * cfg.evict_fetch_count
                  + cfg.n_buckets_padded + 2)
    return rows + public


def stash_occupancy(state: OramState) -> jax.Array:
    """Number of live stash entries (test/metrics helper)."""
    return jnp.sum(state.stash_idx != SENTINEL)


def evict_buffer_occupancy(state: OramState) -> jax.Array:
    """Number of live eviction-buffer rows (health/metrics helper);
    0 under evict_window=1 (zero-length planes)."""
    return jnp.sum(state.ebuf_idx != SENTINEL)


def tree_occupancy(state: OramState) -> jax.Array:
    """Number of live blocks in the tree (test/metrics helper)."""
    return jnp.sum(state.tree_idx != SENTINEL)
