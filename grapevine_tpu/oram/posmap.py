"""Pluggable position maps: flat private table vs recursive position ORAM.

The position map is the recipient→leaf oracle every Path-ORAM access
starts from. Until PR 7 it was hard-coded as a flat u32[blocks+1] array
inside ``OramState`` — private working memory (the EPC analog, see the
threat model in path_oram.py) that must live resident, be sealed into
every checkpoint, and be replicated per shard. At 2^24 records that is
64 MiB (cheap); at 2^30 it is 4 GiB per replica, which caps capacity at
one HBM/host (ROADMAP open item 5).

This module makes the map a subsystem with two implementations behind
one constant-shape contract (``GrapevineConfig.posmap_impl``, the
PR-3/PR-5 selectable-impl playbook):

- **flat** — today's array, bit-for-bit: ``lookup`` is one private
  gather, ``remap`` one private scatter.
- **recursive** — the classic recursive construction (Path ORAM
  §"recursive construction", arXiv:1202.5150; the Pyramid scheme's
  hierarchical layout, arXiv:1712.07882) re-platformed as shape-static
  JAX, one level deep: ``k = entries_per_block`` position entries are
  packed per block of a smaller *internal* Path ORAM whose bucket tree
  lives in (encrypted, shardable) HBM like the payload tree. Only the
  internal ORAM's own flat map + stash stay resident — ``blocks/k``
  entries instead of ``blocks`` — so private position-handling memory
  shrinks by ``k`` (see :func:`posmap_private_bytes`; the 2^30 sizing
  table is OPERATIONS.md §13).

Obliviousness: a batch of B outer accesses resolves through EXACTLY B
internal-ORAM accesses every round — outer dummies become internal
dummies, and duplicate internal blocks are deduplicated by the internal
round's own occurrence machinery (dummy re-fetches of fresh uniform
paths), so every internal transcript entry is an independent uniform
internal leaf. Recursion depth and lookup batch shape are static
geometry; the access *count* per round is a constant, never a function
of which indices were queried (CI-audited in tests/test_posmap.py: the
traced lookup has a B-independent gather/scatter census and no control
flow). The internal leaves are returned to the caller and ride the
public transcript into the leak monitor (obs/leakmon.py ``*_pm``
streams).

Bit-identity contract with the flat map (tests/test_posmap_ab.py):
responses AND the final payload-tree state are bit-identical
flat↔recursive, because (a) the initial table is generated from the
same PRNG key by the same draw, (b) every lookup returns the
round-start entry and every remap commits the round's last write —
exactly the flat read/scatter semantics — and (c) the payload tree
additionally carries a per-slot leaf-metadata plane (recursive mode
only) so eviction resolves working-set leaves without consulting the
map, with values equal to the flat ``working_leaves`` gather by the
posmap↔metadata invariant (maintained at every insert/remap).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..obs.phases import device_phase

U32 = jnp.uint32

#: refuse recursion below this block count: the internal tree needs at
#: least 4 blocks for a height-1 two-per-leaf layout, and a map this
#: small is resident noise anyway
MIN_RECURSIVE_BLOCKS = 8

#: k cap: 2^10 entries = 4 KiB internal block values — the payload
#: bucket-row scale XLA layouts are already tuned for
MAX_ENTRIES_PER_BLOCK_LOG2 = 10


@dataclasses.dataclass(frozen=True)
class PosMapSpec:
    """Static geometry of a *recursive* position map.

    Hashable and embedded in ``OramConfig.posmap``, so it is covered by
    jit static arguments, ``repr``-based checkpoint geometry
    fingerprints (engine/checkpoint.py — a flat checkpoint can never
    silently restore into a recursive engine), and the journal AAD.
    """

    #: k: position entries packed per internal-ORAM block
    entries_per_block: int
    #: internal block space = outer blocks / k
    inner_blocks: int
    #: internal tree height (leaves = 2**inner_height; two blocks per
    #: leaf — the tree_density=2 shape the payload trees default to)
    inner_height: int
    inner_bucket_slots: int = 4
    inner_stash_size: int = 96
    #: at-rest cipher rounds for the internal bucket tree (inherits the
    #: outer tree's setting; the internal map holds future fetch paths,
    #: so it is at least as snapshot-sensitive as payload)
    inner_cipher_rounds: int = 0
    #: tree-top cache depth for the INTERNAL bucket tree (ROADMAP item
    #: 1 ∘ item 5 composition: the internal tree's top levels are
    #: touched every round too — path_oram.OramConfig.top_cache_levels,
    #: clamped to inner_height by derive_posmap_spec)
    inner_top_cache_levels: int = 0
    #: delayed batched eviction for the INTERNAL bucket tree (PR 15;
    #: path_oram.OramConfig.evict_window and friends): the internal
    #: ORAM runs one fetch round per outer round, so its window and
    #: per-round fetch count mirror the outer tree's; its buffer is
    #: flushed by the same oram_flush pass (round.py recurses into the
    #: inner state). Defaults keep the classic per-round eviction.
    inner_evict_window: int = 1
    inner_evict_fetch_count: int = 0
    inner_evict_buffer_slots: int = 0

    @property
    def inner_leaves(self) -> int:
        return 1 << self.inner_height


def derive_posmap_spec(
    blocks: int,
    stash_size: int = 96,
    cipher_rounds: int = 0,
    entries_per_block: int | None = None,
    top_cache_levels: int = 0,
    evict_window: int = 1,
    evict_fetch_count: int = 0,
) -> PosMapSpec:
    """Auto-derive recursion geometry from capacity.

    ``k`` targets ~sqrt(blocks) (capped at 2^10): private memory shrinks
    by k while internal block values stay bucket-row-sized. Explicit
    ``entries_per_block`` overrides (power of two, blocks/k >= 4).
    """
    if blocks < MIN_RECURSIVE_BLOCKS or blocks & (blocks - 1):
        raise ValueError(
            f"recursive posmap needs a power-of-two block space >= "
            f"{MIN_RECURSIVE_BLOCKS}, got {blocks} — use posmap_impl='flat' "
            "at this capacity"
        )
    if entries_per_block is None:
        k = 1 << max(1, min(MAX_ENTRIES_PER_BLOCK_LOG2,
                            (blocks.bit_length() - 1) // 2))
        while blocks // k < 4:
            k >>= 1
    else:
        k = entries_per_block
        if k < 2 or k & (k - 1) or blocks // k < 4 or blocks % k:
            raise ValueError(
                f"entries_per_block must be a power of two >= 2 with "
                f"blocks/k >= 4, got k={k} at blocks={blocks}"
            )
    inner_blocks = blocks // k
    ih = max(1, inner_blocks.bit_length() - 2)
    ebs = 0
    if evict_window > 1:
        from .path_oram import derive_evict_buffer_slots

        ebs = derive_evict_buffer_slots(
            inner_blocks, evict_window, evict_fetch_count, 4
        )
    return PosMapSpec(
        entries_per_block=k,
        inner_blocks=inner_blocks,
        inner_height=ih,
        inner_stash_size=stash_size,
        inner_cipher_rounds=cipher_rounds,
        inner_top_cache_levels=min(top_cache_levels, ih),
        inner_evict_window=evict_window,
        inner_evict_fetch_count=evict_fetch_count if evict_window > 1 else 0,
        inner_evict_buffer_slots=ebs,
    )


def inner_oram_config(spec: PosMapSpec):
    """The internal Path ORAM's OramConfig (always a flat-posmap ORAM —
    one level of recursion; cipher impl pinned to "jnp": internal rows
    are k words, far below the sizes the Pallas kernels pay off at)."""
    from .path_oram import OramConfig

    return OramConfig(
        height=spec.inner_height,
        value_words=spec.entries_per_block,
        bucket_slots=spec.inner_bucket_slots,
        stash_size=spec.inner_stash_size,
        cipher_rounds=spec.inner_cipher_rounds,
        cipher_impl="jnp",
        n_blocks=spec.inner_blocks,
        top_cache_levels=spec.inner_top_cache_levels,
        evict_window=spec.inner_evict_window,
        evict_fetch_count=spec.inner_evict_fetch_count,
        evict_buffer_slots=spec.inner_evict_buffer_slots,
    )


class RecursivePosMapState(NamedTuple):
    """Recursive position-map state pytree.

    ``inner``: the internal Path ORAM (an OramState whose block values
    are packed entry vectors). ``dummy_entry``: the throwaway slot flat
    keeps at ``table[blocks]`` — read/remapped by op-major dummy
    accesses, reproduced here so flat↔recursive stay bit-identical."""

    inner: object  # OramState
    dummy_entry: jax.Array  # u32 scalar


def _flat_table(cfg, key: jax.Array) -> jax.Array:
    """The flat table draw — THE one place the initial position values
    come from, under either impl (bit-identity anchor)."""
    return jax.random.randint(
        key, (cfg.blocks + 1,), 0, cfg.leaves, dtype=jnp.int32
    ).astype(U32)


def init_posmap(cfg, key: jax.Array):
    """Initial position-map pytree for an ``OramConfig``.

    Flat: the u32[blocks+1] table exactly as before. Recursive: the
    same table values packed k-per-block into an internal Path ORAM
    initialized FULL — every internal block placed at a secret uniformly
    random leaf-slot (a random permutation over two-per-leaf slots:
    marginally uniform, jointly exchangeable under index relabeling, so
    the first-fetch transcript stays data-independent), with the
    internal flat map set to match. With the internal cipher on, the
    pre-placed rows are encrypted under epoch 1 before they ever sit in
    HBM (epoch-0 plaintext would hand a snapshot the initial map)."""
    if cfg.posmap is None:
        return _flat_table(cfg, key)
    from .path_oram import cipher_rows, init_oram

    spec = cfg.posmap
    icfg = inner_oram_config(spec)
    k = spec.entries_per_block
    nb = spec.inner_blocks
    z = icfg.bucket_slots
    k_tab, k_inner, k_perm = (
        key, jax.random.fold_in(key, 1), jax.random.fold_in(key, 2)
    )
    table = _flat_table(cfg, k_tab)
    inner = init_oram(icfg, k_inner)

    vals = table[: cfg.blocks].reshape(nb, k)  # blocks = nb * k exactly
    perm = jax.random.permutation(k_perm, nb).astype(U32)  # slot s ↦ block
    density = nb // icfg.leaves  # 2 by construction (inner_height = lg nb - 1)
    slot_iota = jnp.arange(nb, dtype=U32)
    leaf_of_slot = slot_iota // U32(density)
    hb = (U32(1) << U32(icfg.height)) - U32(1) + leaf_of_slot  # leaf buckets
    flat_slot = hb * U32(z) + slot_iota % U32(density)

    tree_idx = inner.tree_idx.at[flat_slot].set(perm, unique_indices=True)
    val_slots = (
        jnp.zeros((icfg.n_buckets_padded * z, k), U32)
        .at[flat_slot]
        .set(vals[perm], unique_indices=True)
    )
    tree_val = val_slots.reshape(icfg.n_buckets_padded, z * k)
    pm = inner.posmap.at[perm].set(leaf_of_slot)

    nonces, epoch = inner.nonces, inner.epoch
    if icfg.encrypted:
        ep1 = jnp.broadcast_to(
            jnp.array([1, 0], U32)[None, :], (icfg.n_buckets_padded, 2)
        )
        buckets = jnp.arange(icfg.n_buckets_padded, dtype=U32)
        enc_idx, enc_val = cipher_rows(
            icfg, inner.cipher_key, buckets, ep1,
            tree_idx.reshape(icfg.n_buckets_padded, z), tree_val,
        )
        tree_idx, tree_val = enc_idx.reshape(-1), enc_val
        nonces, epoch = ep1, jnp.array([2, 0], U32)

    inner = inner._replace(
        tree_idx=tree_idx, tree_val=tree_val, posmap=pm,
        nonces=nonces, epoch=epoch,
    )
    return RecursivePosMapState(inner=inner, dummy_entry=table[cfg.blocks])


def _group_last_slot(idxs, dummy_index, occ_impl, sort_impl, key_bits):
    """u32[B]: the slot of the round's LAST op on the same (real) index;
    dummies get their own slot — the mirror of ``occurrence_masks``'
    first-occurrence ``chain_slot``, in both the dense [B,B] and the
    sorted O(B log B) forms (matching the engine's impl knobs so the
    scan engine's no-[B,B] jaxpr audit holds through the posmap glue)."""
    b = idxs.shape[0]
    slot_iota = jnp.arange(b, dtype=U32)
    is_real = idxs != U32(dummy_index)
    if occ_impl == "scan":
        from ..oblivious.segmented import segment_bounds

        if sort_impl == "radix":
            from ..oblivious.radix import radix_group_sort

            perm, inv, seg_start = radix_group_sort([idxs], key_bits)
        else:
            from ..oblivious.segmented import multiword_group_sort

            perm, inv, seg_start = multiword_group_sort([idxs])
        _, end = segment_bounds(seg_start)
        return jnp.where(is_real, perm[end][inv].astype(U32), slot_iota)
    eq = (idxs[:, None] == idxs[None, :]) & is_real[:, None] & is_real[None, :]
    last = U32(b - 1) - jnp.argmax(eq[:, ::-1], axis=1).astype(U32)
    return jnp.where(is_real, last, slot_iota)


#: oblint taint anchors (analysis/oblint.py): the secret inputs of one
#: ``lookup_remap_round`` — the queried indices, every position the map
#: holds (flat table contents, or the whole recursive pytree: internal
#: tree plaintext via its cipher key, internal stash/posmap), the fresh
#: remap/dummy leaves (future fetch paths), and the occurrence masks
#: (functions of the secret indices).
OBLINT_SECRETS = (
    "idxs", "pm_state", "new_leaves", "dummy_leaves",
    "first_occ", "last_occ", "pm_new_leaves", "pm_dummy_leaves",
)


def RANGELINT_BOUNDS(cfg, prefix: str = "pm_state") -> dict:
    """Rangelint input-interval anchors (analysis/rangelint.py) for one
    ``lookup_remap_round`` / ``oram_round`` argument set at geometry
    ``cfg``: queried indices are block ids or the dummy, every leaf
    argument (remap targets, dummy fetches, internal-map remaps) is a
    fresh uniform draw below its tree's leaf count, and the map state
    itself carries the per-plane invariants of
    :func:`path_oram.RANGELINT_BOUNDS`.  The k-entry packing offsets
    (``idx >> lg k``, ``idx & (k-1)``, ``last_slot·k + off``) are then
    *derived* clean from these bounds — the packing-offset audit the
    satellite names."""
    lv = cfg.leaves - 1
    b = {
        "idxs": (0, cfg.dummy_index),
        "new_leaves": (0, lv),
        "dummy_leaves": (0, lv),
    }
    # the map-state pytree: flat = the bare table; recursive = the
    # RecursivePosMapState (inner OramState + dummy_entry)
    if cfg.posmap is None:
        b[prefix] = (0, lv)
    else:
        icfg = inner_oram_config(cfg.posmap)
        il = icfg.leaves - 1
        b["pm_new_leaves"] = (0, il)
        b["pm_dummy_leaves"] = (0, il)
        b[f"{prefix}.inner.posmap"] = (0, il)
        b[f"{prefix}.inner.stash_val"] = (0, lv)
        b[f"{prefix}.inner.cache_val"] = (0, lv)
        b[f"{prefix}.inner.overflow"] = (0, 2**32 - 2**16)
        if not icfg.encrypted:
            b[f"{prefix}.inner.tree_val"] = (0, lv)
        b[f"{prefix}.dummy_entry"] = (0, lv)
    return b


def lookup_remap_round(
    cfg,
    pm_state,
    idxs: jax.Array,  # u32[B]; cfg.dummy_index = dummy op
    new_leaves: jax.Array,  # u32[B] remap targets
    dummy_leaves: jax.Array,  # u32[B] leaves for non-first-occurrence ops
    first_occ: jax.Array,  # bool[B] (this op performs the real fetch)
    last_occ: jax.Array,  # bool[B] (this op's remap wins)
    pm_new_leaves: jax.Array | None = None,  # u32[B] internal remaps
    pm_dummy_leaves: jax.Array | None = None,  # u32[B] internal dummies
    occ_impl: str = "dense",
    sort_impl: str = "xla",
):
    """Resolve B positions with a fixed access schedule.

    Returns ``(pm_state', leaves u32[B], inner_leaves u32[B] | None)``:
    ``leaves[i]`` is the round-start entry for first occurrences and
    ``dummy_leaves[i]`` otherwise; the last occurrence's ``new_leaves``
    wins each index's remap — exactly the flat semantics.
    ``inner_leaves`` is the internal ORAM's public transcript (None for
    flat)."""
    if cfg.posmap is None:
        leaves = jnp.where(first_occ, pm_state[idxs], dummy_leaves)
        remap_tgt = jnp.where(last_occ, idxs, U32(cfg.blocks + 1))
        pm2 = pm_state.at[remap_tgt].set(
            new_leaves, mode="drop", unique_indices=True
        )
        return pm2, leaves, None
    if pm_new_leaves is None or pm_dummy_leaves is None:
        raise ValueError(
            "recursive posmap lookup needs pm_new_leaves/pm_dummy_leaves "
            "(fresh uniform internal leaves)"
        )
    from .round import oram_round

    spec = cfg.posmap
    icfg = inner_oram_config(spec)
    k = spec.entries_per_block
    lgk = k.bit_length() - 1
    b = idxs.shape[0]
    is_real = idxs != U32(cfg.dummy_index)
    inner_idxs = jnp.where(is_real, idxs >> lgk, U32(icfg.dummy_index))
    offs = idxs & U32(k - 1)  # garbage for dummies; never committed

    # the internal round commits each internal block's final value at
    # its LAST within-round occurrence — scatter every winning remap
    # onto that row so one committed row carries all of its block's
    # entry writes (distinct outer indices in one block have distinct
    # offsets, so in-bounds targets are unique)
    last_slot = _group_last_slot(
        inner_idxs, icfg.dummy_index, occ_impl, sort_impl,
        key_bits=max(1, icfg.dummy_index.bit_length()),
    )

    def apply_pm(vals0, present0):
        # vals0 u32[B, k]: each op's internal block at round start —
        # the lookup reads its own offset; remaps overlay the last rows
        looked = jnp.take_along_axis(
            vals0, offs[:, None].astype(jnp.int32), axis=1
        )[:, 0]
        tgt = jnp.where(
            last_occ & is_real, last_slot * U32(k) + offs, U32(b * k)
        )
        final = (
            vals0.reshape(b * k)
            .at[tgt]
            .set(new_leaves, mode="drop", unique_indices=True)
            .reshape(b, k)
        )
        # internal blocks are created full at init and never leave
        return looked, final, jnp.ones((b,), jnp.bool_)

    with device_phase("posmap"):
        inner2, looked, inner_leaves = oram_round(
            icfg, pm_state.inner, inner_idxs, pm_new_leaves,
            pm_dummy_leaves, apply_pm,
            occ_impl=occ_impl, sort_impl=sort_impl,
        )
    # looked-up entries come out of the (decrypted) internal tree, which
    # interval reasoning must treat as opaque; the mask re-establishes
    # the `< leaves` invariant the entries were stored under (identity
    # for honest state — leaves is a power of two — and defense in depth
    # against corrupt ciphertext steering a path fetch out of range)
    looked = looked & U32(cfg.leaves - 1)
    leaves = jnp.where(first_occ, looked, dummy_leaves)
    return pm_state._replace(inner=inner2), leaves, inner_leaves


def lookup_remap_one(cfg, pm_state, idx, new_leaf, pm_leaf=None):
    """Single-access lookup+remap (the op-major engine's path).

    Returns ``(pm_state', leaf, inner_leaf | None)``. Flat: the exact
    legacy gather/scatter pair. Recursive: ONE internal ORAM access per
    outer access, dummy-for-dummy (fixed schedule); the throwaway
    ``dummy_entry`` reproduces flat's ``table[blocks]`` read/remap."""
    if cfg.posmap is None:
        leaf = pm_state[idx]
        return pm_state.at[idx].set(new_leaf), leaf, None
    if pm_leaf is None:
        raise ValueError(
            "recursive posmap lookup needs pm_leaf (a fresh uniform "
            "internal leaf)"
        )
    from .path_oram import oram_access

    spec = cfg.posmap
    icfg = inner_oram_config(spec)
    k = spec.entries_per_block
    lgk = k.bit_length() - 1
    is_dummy = idx == U32(cfg.dummy_index)
    inner_idx = jnp.where(is_dummy, U32(icfg.dummy_index), idx >> lgk)
    off = (idx & U32(k - 1)).astype(jnp.int32)

    def fn(value, present, operand):
        looked = value[off]
        # remap the entry; keep the block, never insert (always present
        # for real indices — the internal tree is initialized full)
        return value.at[off].set(new_leaf), jnp.bool_(True), jnp.bool_(False), looked

    with device_phase("posmap"):
        inner2, looked, inner_leaf = oram_access(
            icfg, pm_state.inner, inner_idx, pm_leaf, None, fn
        )
    # same `< leaves` re-establishment as lookup_remap_round: decrypted
    # internal-tree entries are opaque to interval reasoning
    looked = looked & U32(cfg.leaves - 1)
    leaf = jnp.where(is_dummy, pm_state.dummy_entry, looked)
    dummy2 = jnp.where(is_dummy, new_leaf, pm_state.dummy_entry)
    return (
        pm_state._replace(inner=inner2, dummy_entry=dummy2),
        leaf,
        inner_leaf,
    )


# -- sizing + test/debug views ------------------------------------------


def posmap_private_bytes(cfg) -> int:
    """Resident/replicated position-handling bytes — the part that must
    live in private memory on every replica and shard (flat: the whole
    table; recursive: the internal ORAM's flat map, stash, and scalars
    — its bucket tree is encrypted, shardable HBM storage like the
    payload tree). The capacity acceptance (2^30 at <= 1/64 of flat)
    and the OPERATIONS.md §13 sizing table are computed from this."""
    if cfg.posmap is None:
        return 4 * (cfg.blocks + 1)
    spec = cfg.posmap
    icfg = inner_oram_config(spec)
    s, k = icfg.stash_size, spec.entries_per_block
    table = 4 * (icfg.blocks + 1)
    stash = 4 * s + 4 * s * k  # stash_idx + stash_val + stash_leaf(0)
    scalars = 4 * (1 + 1 + 8 + 2)  # dummy_entry, overflow, key, epoch
    # internal tree-top cache planes are decrypted-resident private
    # state (stash standing), so they count against the private budget
    z = icfg.bucket_slots
    cache = icfg.cache_buckets * (4 * z + 4 * z * k)
    return table + stash + scalars + cache


def posmap_hbm_bytes(cfg) -> int:
    """Shardable HBM bytes the map adds (recursive only): the internal
    bucket tree planes plus the payload tree's leaf-metadata plane."""
    if cfg.posmap is None:
        return 0
    icfg = inner_oram_config(cfg.posmap)
    z, k = icfg.bucket_slots, cfg.posmap.entries_per_block
    inner_tree = icfg.n_buckets_padded * (4 * z + 4 * z * k + 8)
    leaf_plane = 4 * cfg.n_buckets_padded * cfg.bucket_slots
    return inner_tree + leaf_plane


def read_table(cfg, pm_state):
    """TEST/DEBUG: materialize the full logical table u32[blocks] from
    either impl (decrypting the internal tree as needed). Host-side —
    never on the round path."""
    import numpy as np

    if cfg.posmap is None:
        return np.asarray(pm_state)[: cfg.blocks].copy()
    from ..oblivious.bucket_cipher import row_keystream
    from ..oblivious.primitives import SENTINEL

    spec = cfg.posmap
    icfg = inner_oram_config(spec)
    k, z = spec.entries_per_block, icfg.bucket_slots
    inner = pm_state.inner
    tidx = np.asarray(inner.tree_idx).reshape(-1, z)
    tval = np.asarray(inner.tree_val)
    if icfg.encrypted:
        buckets = jnp.arange(icfg.n_buckets_padded, dtype=U32)
        ks = np.asarray(row_keystream(
            inner.cipher_key, buckets, inner.nonces, icfg.row_words,
            icfg.cipher_rounds,
        ))
        tidx = tidx ^ ks[:, :z]
        tval = tval ^ ks[:, z:]
    out = np.zeros((cfg.blocks,), np.uint32)
    seen = np.zeros((spec.inner_blocks,), bool)
    rows = tval.reshape(-1, k)
    flat_idx = tidx.reshape(-1)
    live = flat_idx != int(SENTINEL)
    # delayed eviction: buckets fetched since the last flush hold stale
    # copies (the live rows moved to the eviction buffer) — mask their
    # tree AND cache slots; the buffer is read below like the stash
    stale_b = None
    if icfg.delayed_eviction:
        stale_b = np.asarray(inner.fetch_tag) == int(
            np.asarray(inner.ebuf_gen)
        )
        live &= ~np.repeat(stale_b, z)
    # tree-top cache: cached buckets' HBM rows are stale (decrypt to
    # empty — never written while cached); the authoritative plaintext
    # rows live in the cache planes
    ncache = int(np.asarray(inner.cache_idx).size)
    if ncache:
        live[:ncache] = False
        crows = np.asarray(inner.cache_val).reshape(-1, k)
        cidx = np.asarray(inner.cache_idx).copy()
        if stale_b is not None:
            cidx[np.repeat(stale_b[: ncache // z], z)] = int(SENTINEL)
        for slot in np.nonzero(cidx != int(SENTINEL))[0]:
            blk = int(cidx[slot])
            out[blk * k: (blk + 1) * k] = crows[slot]
            seen[blk] = True
    for slot in np.nonzero(live)[0]:
        blk = int(flat_idx[slot])
        out[blk * k: (blk + 1) * k] = rows[slot]
        seen[blk] = True
    for pidx, pval in (
        (inner.ebuf_idx, inner.ebuf_val),
        (inner.stash_idx, inner.stash_val),
    ):
        sidx = np.asarray(pidx)
        sval = np.asarray(pval)
        for j in np.nonzero(sidx != int(SENTINEL))[0]:
            blk = int(sidx[j])
            out[blk * k: (blk + 1) * k] = sval[j]
            seen[blk] = True
    assert seen.all(), "recursive posmap lost internal blocks"
    return out
