"""Batched Path-ORAM access rounds: one fetch, N ops, one eviction.

The sequential engine (`oram_access_batch`) commits each access as its own
path fetch → stash scan → evict → write-back, so a B-op batch costs 3·B
dependent HBM round trips — latency-bound on TPU. This module implements
the OPRAM-style *batched round* instead (cf. the batching discussion in
PAPERS.md and SURVEY.md §7 "hard parts" 6):

1. **Dedup + fetch**: each op's path is resolved up front. Duplicate
   logical indices within the round do a *dummy* fetch of a fresh random
   path after the first occurrence — the classic OPRAM conflict trick.
   This is also a security requirement, not just an optimization: if two
   ops on one key both fetched ``posmap[idx]`` the transcript would show
   two identical leaves, correlating ops on the same key. With dedup every
   transcript entry is an independent uniform leaf. All B paths are then
   fetched in one gather; buckets shared by several paths (always true
   near the root) are attributed to a single *owner* path slot and
   invalidated elsewhere, so each live block enters the working set once.
2. **Apply**: the fetched blocks join the stash in one combined working
   set. Ops are applied in slot order (the documented within-batch commit
   order, SURVEY.md §7.6) under a `lax.scan`, but each step is O(W + V):
   a match scan over the W-entry index vector plus one row gather/update
   at the matched position. The row gather is a secret-position access
   into *private working memory* — the same standing the flat position
   map already has (see the threat model in path_oram.py): obliviousness
   is claimed for the HBM bucket-tree transcript, and the working set,
   like the stash and position map, is EPC-analog private state.
3. **Evict**: one level-synchronous greedy pass assigns every working-set
   entry to the deepest fetched bucket on its own path, jointly across
   all B paths (an entry's path meets each level in exactly one bucket,
   so levels vectorize with no conflicts). Leftovers recompact into the
   stash; one scatter writes all owned buckets back (write transcript ≡
   read transcript).

Net effect per round: 2 large HBM transfers (gather + scatter) per tree
array instead of 2·B small dependent ones, and the only remaining
sequential chain is the cheap apply scan.

Semantics note: `apply_fn` threads an engine carry through the ops, which
is what lets the query engine keep its capacity counters sequentially
consistent inside a round (engine/round_step.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..oblivious.primitives import SENTINEL, rank_of
from .path_oram import (
    OramConfig,
    OramState,
    _path_gather,
    _path_scatter,
    path_bucket_indices,
)

U32 = jnp.uint32


def occurrence_masks(idxs: jax.Array, dummy_index: int):
    """(first_occ, last_occ) over real (non-dummy) indices.

    first_occ[i]: no earlier op in the round touches the same index —
    this op performs the real path fetch. last_occ[i]: no later op does —
    this op's fresh leaf wins the position-map remap.
    """
    is_real = idxs != U32(dummy_index)
    eq = (idxs[:, None] == idxs[None, :]) & is_real[:, None] & is_real[None, :]
    b = idxs.shape[0]
    earlier = jnp.tril(jnp.ones((b, b), jnp.bool_), k=-1)
    first_occ = is_real & ~jnp.any(eq & earlier, axis=1)
    last_occ = is_real & ~jnp.any(eq & earlier.T, axis=1)
    return first_occ, last_occ


def _owner_mask(flat_b: jax.Array) -> jax.Array:
    """fowner[k]: flat path-slot k is the first occurrence of its bucket.

    Shared buckets (all paths share the root; prefixes shared pairwise)
    must contribute their blocks to the working set exactly once, and be
    written back exactly once.
    """
    n = flat_b.shape[0]
    eq = flat_b[:, None] == flat_b[None, :]
    earlier = jnp.tril(jnp.ones((n, n), jnp.bool_), k=-1)
    return ~jnp.any(eq & earlier, axis=1)


def oram_round(
    cfg: OramConfig,
    state: OramState,
    idxs: jax.Array,  # u32[B] block indices (cfg.dummy_index = dummy op)
    new_leaves: jax.Array,  # u32[B] fresh uniform leaves (remap targets)
    dummy_leaves: jax.Array,  # u32[B] fresh uniform leaves (dummy fetches)
    operands,  # pytree, leading batch axis
    apply_fn,
    carry,
    axis_name: str | None = None,
):
    """One batched oblivious access round over this ORAM.

    ``apply_fn(carry, value u32[V], present bool, operand) ->
    (carry, new_value u32[V], keep bool, insert bool, out pytree)`` with
    the same branchless contract as `oram_access`'s ``fn``, plus the
    threaded engine carry.

    Returns ``(state', carry, outs, leaves)``; ``leaves`` u32[B] is the
    public transcript (every entry an independent uniform draw).
    """
    b = idxs.shape[0]
    z, v, plen, h = cfg.bucket_slots, cfg.value_words, cfg.path_len, cfg.height
    s = cfg.stash_size
    nslots = b * plen * z

    # --- 1. dedup, position-map read/remap, path fetch -----------------
    first_occ, last_occ = occurrence_masks(idxs, cfg.dummy_index)
    leaves = jnp.where(first_occ, state.posmap[idxs], dummy_leaves)
    # last occurrence wins the remap; others retarget the throwaway
    # dummy-index slot (posmap[leaves] backs cfg.dummy_index)
    remap_tgt = jnp.where(last_occ, idxs, U32(cfg.leaves))
    posmap = state.posmap.at[remap_tgt].set(new_leaves)

    path_b = jax.vmap(lambda lf: path_bucket_indices(cfg, lf))(leaves)  # [B,plen]
    flat_b = path_b.reshape(b * plen)
    fowner = _owner_mask(flat_b)

    pidx = _path_gather(state.tree_idx, flat_b, axis_name)  # [B*plen, z]
    pleaf = _path_gather(state.tree_leaf, flat_b, axis_name)
    pval = _path_gather(state.tree_val, flat_b, axis_name)
    # non-owner copies of shared buckets are invalidated
    pidx = jnp.where(fowner[:, None], pidx, SENTINEL)

    widx = jnp.concatenate([state.stash_idx, pidx.reshape(-1)])
    wleaf = jnp.concatenate([state.stash_leaf, pleaf.reshape(-1)])
    wval = jnp.concatenate([state.stash_val, pval.reshape(-1, v)], axis=0)
    w = s + nslots

    # --- 2. slot-order apply over the combined working set -------------
    def step(sc, xs):
        widx, wleaf, wval, carry, dropped = sc
        idx, new_leaf, opnd = xs
        match = (widx == idx) & (widx != SENTINEL)
        present = jnp.any(match)
        pos = jnp.argmax(match)  # 0 when absent; guarded below
        raw = wval[pos]
        value = jnp.where(present, raw, jnp.zeros_like(raw))

        carry, new_value, keep, insert, out = apply_fn(carry, value, present, opnd)

        # in-place modify (writes are no-ops when absent)
        widx = widx.at[pos].set(
            jnp.where(present & ~keep, SENTINEL, widx[pos])
        )
        wleaf = wleaf.at[pos].set(jnp.where(present, new_leaf, wleaf[pos]))
        wval = wval.at[pos].set(jnp.where(present, new_value, raw))

        do_insert = insert & ~present & (idx != U32(cfg.dummy_index))
        free = widx == SENTINEL
        has_free = jnp.any(free)
        fpos = jnp.argmax(free)
        ins = do_insert & has_free
        widx = widx.at[fpos].set(jnp.where(ins, idx, widx[fpos]))
        wleaf = wleaf.at[fpos].set(jnp.where(ins, new_leaf, wleaf[fpos]))
        wval = wval.at[fpos].set(jnp.where(ins, new_value, wval[fpos]))
        dropped = dropped + (do_insert & ~has_free).astype(U32)
        return (widx, wleaf, wval, carry, dropped), out

    (widx, wleaf, wval, carry, insert_dropped), outs = jax.lax.scan(
        step,
        (widx, wleaf, wval, carry, jnp.zeros((), U32)),
        (idxs, new_leaves, operands),
    )

    # --- 3. joint level-synchronous greedy eviction --------------------
    valid = widx != SENTINEL
    placed = jnp.zeros((w,), jnp.bool_)
    slot_tgt = jnp.full((w,), nslots, U32)  # OOB = not placed
    col_owner = fowner.reshape(b, plen)  # [B, plen]
    for level in range(h, -1, -1):
        # the one bucket on each entry's own path at this level
        hb = (U32(1) << U32(level)) - U32(1) + (wleaf >> U32(h - level))
        colb = path_b[:, level]  # [B] buckets fetched at this level
        m = (hb[:, None] == colb[None, :]) & col_owner[None, :, level]  # [W,B]
        elig = valid & ~placed & jnp.any(m, axis=1)
        me = m & elig[:, None]
        mi = me.astype(jnp.int32)
        rank = jnp.sum((jnp.cumsum(mi, axis=0) - mi) * mi, axis=1)  # within-col
        chosen = elig & (rank < z)
        col = jnp.argmax(m, axis=1).astype(U32)  # unique column per entry
        slot = (col * U32(plen) + U32(level)) * U32(z) + rank.astype(U32)
        slot_tgt = jnp.where(chosen, slot, slot_tgt)
        placed = placed | chosen

    new_pidx = jnp.full((nslots,), SENTINEL, U32).at[slot_tgt].set(widx, mode="drop")
    new_pleaf = jnp.zeros((nslots,), U32).at[slot_tgt].set(wleaf, mode="drop")
    new_pval = jnp.zeros((nslots, v), U32).at[slot_tgt].set(wval, mode="drop")

    # --- 4. stash recompaction + write-back ----------------------------
    leftover = valid & ~placed
    srank = rank_of(leftover)
    starget = jnp.where(leftover, srank, s)  # OOB = dropped
    stash_idx = jnp.full((s,), SENTINEL, U32).at[starget].set(widx, mode="drop")
    stash_leaf = jnp.zeros((s,), U32).at[starget].set(wleaf, mode="drop")
    stash_val = jnp.zeros((s, v), U32).at[starget].set(wval, mode="drop")
    n_left = jnp.sum(leftover.astype(jnp.int32))
    stash_dropped = (n_left - jnp.minimum(n_left, s)).astype(U32)

    new_state = OramState(
        tree_idx=_path_scatter(
            state.tree_idx, flat_b, new_pidx.reshape(b * plen, z), axis_name, fowner
        ),
        tree_leaf=_path_scatter(
            state.tree_leaf, flat_b, new_pleaf.reshape(b * plen, z), axis_name, fowner
        ),
        tree_val=_path_scatter(
            state.tree_val, flat_b, new_pval.reshape(b * plen, z, v), axis_name, fowner
        ),
        stash_idx=stash_idx,
        stash_leaf=stash_leaf,
        stash_val=stash_val,
        posmap=posmap,
        overflow=state.overflow + stash_dropped + insert_dropped,
    )
    return new_state, carry, outs, leaves
