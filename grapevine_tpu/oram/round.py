"""Batched Path-ORAM access rounds: one fetch, N ops, one eviction.

The sequential engine (`oram_access_batch`) commits each access as its own
path fetch → stash scan → evict → write-back, so a B-op batch costs 3·B
dependent HBM round trips — latency-bound on TPU. This module implements
the OPRAM-style *batched round* instead (cf. the batching discussion in
PAPERS.md and SURVEY.md §7 "hard parts" 6):

1. **Dedup + fetch**: each op's path is resolved up front. Duplicate
   logical indices within the round do a *dummy* fetch of a fresh random
   path after the first occurrence — the classic OPRAM conflict trick.
   This is also a security requirement, not just an optimization: if two
   ops on one key both fetched ``posmap[idx]`` the transcript would show
   two identical leaves, correlating ops on the same key. With dedup every
   transcript entry is an independent uniform leaf. All B paths are then
   fetched in one gather; buckets shared by several paths (always true
   near the root) are attributed to a single *owner* path slot and
   invalidated elsewhere, so each live block enters the working set once.
2. **Apply**: slot-order semantics (the documented within-batch commit
   order, SURVEY.md §7.6) are resolved by a fully **vectorized** batch
   callback — there is NO per-op `lax.scan` anywhere in the round. A
   sequential scan body costs ~30-130µs *per iteration* on TPU (profiled;
   it dominated the entire framework), so within-round read-after-write
   chains are instead computed in parallel: the round hands the callback
   each op's *initial* row value + presence (one static [B, W] compare +
   one B-row gather), and the callback resolves same-key chains with
   same-key matrices / segmented scans (see engine/vphases.py and
   oblivious/segmented.py) and returns each op's outputs plus the final
   per-key committed state. The [B, W] compare and row gathers are
   private-working-memory accesses — the same standing the flat position
   map already has (see the threat model in path_oram.py): obliviousness
   is claimed for the HBM bucket-tree transcript; the working set, like
   the stash and position map, is EPC-analog private state. The final
   (value, alive) of each key is scattered back to its working-set row —
   net inserts go to B reserved rows — and eviction proceeds.
3. **Evict**: one level-synchronous greedy pass assigns every working-set
   entry to the deepest fetched bucket on its own path, jointly across
   all B paths (an entry's path meets each level in exactly one bucket,
   so levels vectorize with no conflicts). Leftovers recompact into the
   stash; one scatter writes all owned buckets back (write transcript ≡
   read transcript).

Net effect per round: 2 large HBM transfers (gather + scatter) per tree
array instead of 2·B small dependent ones, with all decision logic in
O(log B)-depth parallel form.
"""

from __future__ import annotations

import jax

from ..config import TPU_BACKENDS as _TPU_BACKENDS
import jax.numpy as jnp

from ..oblivious.primitives import SENTINEL, rank_of
from ..oblivious.radix import radix_rank
from ..oblivious.bucket_cipher import epoch_next
from ..obs.phases import device_phase
from .path_oram import (
    OramConfig,
    OramState,
    _path_gather,
    _path_scatter,
    cipher_rows,
    path_bucket_indices,
    path_slot_indices,
    working_leaves,
)

U32 = jnp.uint32

#: oblint taint anchors (analysis/oblint.py): the secret inputs of one
#: ``oram_round(cfg, state, idxs, new_leaves, dummy_leaves, ...)`` —
#: block indices, every current/future position (posmap contents and the
#: fresh remap/dummy leaves are all future fetch paths), the private
#: stash/cache planes, and the at-rest cipher key (tainting the key is
#: what marks every *decrypted* tree row secret: plaintext is
#: key-derived, ciphertext is public). Argument-name/dotted-path
#: prefixes over the function's signature; tools/check_oblivious.py
#: resolves them against the flattened trace.
OBLINT_SECRETS = (
    "idxs", "new_leaves", "dummy_leaves",
    "pm_new_leaves", "pm_dummy_leaves",
    "state.posmap", "state.stash_idx", "state.stash_val",
    "state.stash_leaf", "state.cache_idx", "state.cache_val",
    "state.cache_leaf", "state.ebuf_idx", "state.ebuf_val",
    "state.ebuf_leaf", "state.cipher_key",
)
# Deliberately NOT secret: ebuf_paths / ebuf_rounds / ebuf_gen /
# fetch_tag — the flush-window bookkeeping is a pure function of the
# public transcript (the fetched leaves and the round counter), and the
# flush cadence must remain derivable from it alone (a flush that
# consulted buffer *contents* would be the leak the seeded
# flush_on_buffer_contents mutant pins).


def occurrence_masks(idxs: jax.Array, dummy_index: int):
    """(first_occ, last_occ, chain_slot) over real (non-dummy) indices.

    first_occ[i]: no earlier op in the round touches the same index —
    this op performs the real path fetch. last_occ[i]: no later op does —
    this op's fresh leaf wins the position-map remap. chain_slot[i]: the
    slot of the round's first op on the same index (dummies get their own
    slot) — the shared chain-buffer slot for within-round read-after-write.

    The classic [B,B]-mask form; `occurrence_masks_sorted` computes the
    identical masks in O(B log B) for the scan engine.
    """
    is_real = idxs != U32(dummy_index)
    eq = (idxs[:, None] == idxs[None, :]) & is_real[:, None] & is_real[None, :]
    b = idxs.shape[0]
    earlier = jnp.tril(jnp.ones((b, b), jnp.bool_), k=-1)
    first_occ = is_real & ~jnp.any(eq & earlier, axis=1)
    last_occ = is_real & ~jnp.any(eq & earlier.T, axis=1)
    slot_iota = jnp.arange(b, dtype=U32)
    chain_slot = jnp.where(is_real, jnp.argmax(eq, axis=1).astype(U32), slot_iota)
    return first_occ, last_occ, chain_slot


def occurrence_masks_sorted(idxs: jax.Array, dummy_index: int,
                            sort_impl: str = "xla",
                            key_bits: int | None = None):
    """`occurrence_masks` in O(B log B): one sort by (index, slot), then
    segment boundaries in sorted order mark first/last occurrences — no
    [B,B] intermediate (bit-identical outputs; tests/test_round.py).

    ``sort_impl="radix"`` with a declared ``key_bits`` bound (block
    indices are ≤ log2(blocks)+1 bits — oram_round passes the bound
    from its geometry) replaces the comparison sort with counting
    passes (oblivious/radix.py); identical masks either way."""
    from ..oblivious.segmented import multiword_group_sort, segment_bounds

    b = idxs.shape[0]
    is_real = idxs != U32(dummy_index)
    slot_iota = jnp.arange(b, dtype=U32)
    if sort_impl == "radix" and key_bits is not None:
        from ..oblivious.radix import radix_group_sort

        perm, inv, seg_start = radix_group_sort([idxs], key_bits)
    else:
        perm, inv, seg_start = multiword_group_sort([idxs])
    start, end = segment_bounds(seg_start)
    iota_i = jnp.arange(b, dtype=jnp.int32)
    first_occ = is_real & ((iota_i == start)[inv])
    last_occ = is_real & ((iota_i == end)[inv])
    chain_slot = jnp.where(is_real, perm[start][inv], slot_iota)
    return first_occ, last_occ, chain_slot


def _bucket_owner_map(cfg: OramConfig, flat_b: jax.Array) -> jax.Array:
    """Dense heap-bucket → owner-column map for this round's fetch.

    Buckets shared by several fetched paths (always true near the root)
    must contribute their blocks to the working set exactly once and be
    written back exactly once; the owner is the lowest batch column
    touching the bucket. One scatter-min over the (unique) heap bucket
    ids replaces the O((B·plen)²) all-pairs mask this supersedes, and
    doubles as the eviction-eligibility oracle: ``map[hb] != B`` iff
    bucket ``hb`` was fetched this round. (searchsorted/sorted-neighbor
    alternatives lower to serial scalar loops on TPU — measured at
    ~0.17 ms per call — while scatter/gather stay vectorized.)
    """
    b_plen = flat_b.shape[0]
    plen = cfg.path_len
    b = b_plen // plen
    cols = jnp.repeat(jnp.arange(b, dtype=U32), plen)
    return jnp.full((cfg.n_buckets_padded,), U32(b)).at[flat_b].min(cols)


def _assign_evictions(
    cfg: OramConfig,
    valid: jax.Array,  # bool[W] live working-set rows
    wleaf: jax.Array,  # u32[W] leaf assignment per row
    bucket_map: jax.Array,  # u32[n_buckets_padded] heap bucket -> target
    n_targets: int,  # target-space size; doubles as the "not fetched" sentinel
    nslots: int,  # flat output slots (the OOB = unplaced sentinel)
    sort_impl: str,
    slot_of,  # (target u32[W], level, rank u32[W]) -> flat output slot
):
    """Joint level-synchronous greedy eviction assignment (module
    docstring step 3): one sort of the working set by leaf, then per
    level a segmented rank caps each bucket at Z — O(W) work per level
    with no [W, n_targets] masks. Returns ``(slot_tgt, placed)`` in
    working-set order; ``slot_tgt`` indexes a flat output of ``nslots``
    slots (OOB = unplaced). ONE body serves both write layouts — the
    placement itself (which entry lands in which bucket) is the same
    greedy function either way, which the cross-E bit-identity contract
    depends on:

    - per-round eviction (oram_round): ``bucket_map`` = owner columns,
      ``n_targets`` = B, ``slot_of`` = [col, level, slot] layout over
      the fetched paths;
    - delayed flush (oram_flush): ``bucket_map`` = deduplicated target
      slots, ``n_targets`` = flush_target_slots, ``slot_of`` =
      [target, slot] layout over the compacted window union.
    """
    h, z = cfg.height, cfg.bucket_slots
    w = valid.shape[0]
    skey = jnp.where(valid, wleaf, U32(0xFFFFFFFF))
    if sort_impl == "radix":
        # leaves are h bits; invalid rows sort last under the 2^h
        # sentinel exactly as they do under 0xFFFFFFFF (both stable
        # sorts keep equal keys in working-set order), so the
        # permutation is bit-identical to the argsort — at h+1
        # declared key bits instead of a 32-bit comparison sort
        with device_phase("oram_evict_sort"):
            eperm = radix_rank(
                jnp.where(valid, wleaf, U32(1) << U32(h)), h + 1
            )
    else:
        eperm = jnp.argsort(skey)
    sleaf = skey[eperm]
    svalid = valid[eperm]
    iota_w = jnp.arange(w, dtype=jnp.int32)
    placed = jnp.zeros((w,), jnp.bool_)  # sorted order
    slot_tgt_s = jnp.full((w,), nslots, U32)  # sorted order; OOB = unplaced
    # invalid rows carry the sort sentinel (0xFFFFFFFF / 2^h) in
    # sleaf; clamp to the real leaf range BEFORE the heap-id
    # arithmetic so `hb` provably fits u32 at every certified
    # geometry (the unclamped sentinel wrapped hb mod 2^32 —
    # harmless only because svalid masked those rows downstream;
    # rangelint flags exactly that kind of masked wraparound).
    # Clamped sentinel rows merge into the last real segment; they
    # are a sorted suffix and never eligible, so real rows' segment
    # starts and ranks are unchanged.
    bleaf = jnp.minimum(sleaf, U32(cfg.leaves - 1))
    for level in range(h, -1, -1):
        shift = U32(h - level)
        bid = bleaf >> shift  # bucket prefix per entry; sorted ⇒ contiguous
        hb = (U32(1) << U32(level)) - U32(1) + bid  # heap bucket index
        # one gather answers both "was my bucket fetched" (target !=
        # n_targets) and which output rows hold it
        tgt = bucket_map[jnp.minimum(hb, U32(cfg.n_buckets_padded - 1))]
        bnd = jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), bid[1:] != bid[:-1]]
        )
        elig = svalid & ~placed & (tgt != U32(n_targets))
        ei = elig.astype(jnp.int32)
        # exclusive count of eligibles, as the shifted inclusive
        # cumsum (interval-transparent, see primitives.rank_of)
        ecum = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), jnp.cumsum(ei)[:-1]]
        )
        start = jax.lax.cummax(jnp.where(bnd, iota_w, 0))  # my segment start
        # exclusive rank within my bucket: >= 0 because ecum is
        # monotone and start[i] <= i; the max states that invariant
        # for interval reasoning (identity at runtime)
        rank = jnp.maximum(ecum - ecum[start], 0)
        chosen = elig & (rank < z)
        slot = slot_of(tgt, level, rank.astype(U32))
        slot_tgt_s = jnp.where(chosen, slot, slot_tgt_s)
        placed = placed | chosen
    # back to working-set order (a [W] scatter, so values need no permute)
    slot_tgt = (
        jnp.full((w,), nslots, U32).at[eperm].set(slot_tgt_s, unique_indices=True)
    )
    placed = (
        jnp.zeros((w,), jnp.bool_).at[eperm].set(placed, unique_indices=True)
    )
    return slot_tgt, placed


def oram_round(
    cfg: OramConfig,
    state: OramState,
    idxs: jax.Array,  # u32[B] block indices (cfg.dummy_index = dummy op)
    new_leaves: jax.Array,  # u32[B] fresh uniform leaves (remap targets)
    dummy_leaves: jax.Array,  # u32[B] fresh uniform leaves (dummy fetches)
    apply_batch,
    axis_name: str | None = None,
    occ_impl: str = "dense",
    sort_impl: str = "xla",
    pm_new_leaves: jax.Array | None = None,  # u32[B] (recursive posmap)
    pm_dummy_leaves: jax.Array | None = None,  # u32[B] (recursive posmap)
):
    """One batched oblivious access round over this ORAM.

    ``apply_batch(vals0 u32[B,V], present0 bool[B]) ->
    (outs pytree, final_val u32[B,V], final_alive bool[B])``:

    - ``vals0[j]``/``present0[j]``: the pre-round value (zeros if absent)
      and presence of op j's key in the working set;
    - the callback resolves within-round slot-order chain semantics
      itself, **vectorized** (same-key matrices / segmented scans; it
      knows which ops share keys — typically via `occurrence_masks` on
      the same ``idxs``);
    - ``final_val[j]`` / ``final_alive[j]``: the key's state after the
      whole round. Only the values at each key's *last* occurrence are
      committed; the callback must put the final state there.

    Returns ``(state', outs, leaves)``; ``leaves`` u32[B] is the public
    transcript (every entry an independent uniform draw).

    ``occ_impl``: "dense" = [B,B]-mask dedup, "scan" = sorted dedup with
    no quadratic intermediate (bit-identical; matches the engine's
    ``vphases_impl`` knob).

    ``sort_impl``: "xla" = the comparison sorts XLA lowers natively,
    "radix" = bounded-key counting passes (oblivious/radix.py) for the
    eviction leaf sort and the sorted dedup — bit-identical
    permutations, zero ``sort`` HLO in this round (matches the engine's
    ``GrapevineConfig.sort_impl`` knob; CI-audited in
    tests/test_radix.py).

    With a recursive position map (``cfg.posmap`` set; oram/posmap.py)
    ``pm_new_leaves``/``pm_dummy_leaves`` must supply fresh uniform
    *internal* leaves and the returned ``leaves`` is u32[B, 2]: column 0
    the payload-tree transcript, column 1 the internal posmap ORAM's —
    exactly B internal accesses per round regardless of the indices.
    """
    if cfg.delayed_eviction:
        # evict_window > 1 (config.py evict_every): this round is
        # fetch-only — gather+decrypt+stash/buffer update, ZERO tree
        # writes; oram_flush drains the accumulated window every
        # evict_window rounds on the round-counter cadence
        return _oram_fetch_round(
            cfg, state, idxs, new_leaves, dummy_leaves, apply_batch,
            axis_name=axis_name, occ_impl=occ_impl, sort_impl=sort_impl,
            pm_new_leaves=pm_new_leaves, pm_dummy_leaves=pm_dummy_leaves,
        )
    from .posmap import lookup_remap_round

    b = idxs.shape[0]
    z, v, plen, h = cfg.bucket_slots, cfg.value_words, cfg.path_len, cfg.height
    s = cfg.stash_size
    nslots = b * plen * z
    recursive = cfg.posmap is not None

    # --- 1. dedup, position-map read/remap, path fetch -----------------
    if occ_impl == "scan":
        # block indices are bounded: real < blocks, dummy = blocks
        first_occ, last_occ, _ = occurrence_masks_sorted(
            idxs, cfg.dummy_index, sort_impl=sort_impl,
            key_bits=max(1, cfg.dummy_index.bit_length()),
        )
    else:
        first_occ, last_occ, _ = occurrence_masks(idxs, cfg.dummy_index)
    posmap, leaves, inner_leaves = lookup_remap_round(
        cfg, state.posmap, idxs, new_leaves, dummy_leaves,
        first_occ, last_occ,
        pm_new_leaves=pm_new_leaves, pm_dummy_leaves=pm_dummy_leaves,
        occ_impl=occ_impl, sort_impl=sort_impl,
    )

    path_b = jax.vmap(lambda lf: path_bucket_indices(cfg, lf))(leaves)  # [B,plen]
    flat_b = path_b.reshape(b * plen)
    bmap = _bucket_owner_map(cfg, flat_b)  # heap bucket → owner column
    cols_flat = jnp.repeat(jnp.arange(b, dtype=U32), plen)
    fowner = bmap[flat_b] == cols_flat

    # tree-top cache split (cfg.top_cache_levels = kc): the top kc
    # levels of every path resolve against the decrypted-resident cache
    # planes; ONLY the bottom plen−kc levels touch the encrypted HBM
    # tree arrays — the round's HBM path traffic and cipher row count
    # both shrink by kc/plen (the jaxpr audit in
    # tools/check_tree_cache_oblivious.py pins this). kc=0 degenerates
    # to the full-path program bit-for-bit.
    # HBM slot planes are addressed on the bucket axis ([n, Z] reshape
    # views — free, layout-identical): flat slot ids (bucket·Z + slot)
    # escape u32/int32 one geometry doubling before bucket ids do, so
    # the certified u32 bound rides the bucket axis (rangelint;
    # OPERATIONS.md §18). The tiny cache planes keep flat addressing.
    kc = cfg.top_cache_levels
    nbot = plen - kc
    bot_b = path_b[:, kc:].reshape(b * nbot)
    # level ℓ < kc heap ids are < 2^kc − 1 = cache_buckets by
    # construction (path_bucket_indices level structure); the min
    # states that per-level invariant, which a whole-array interval
    # cannot carry through the column slice (runtime identity)
    top_b = jnp.minimum(
        path_b[:, :kc].reshape(b * kc),
        U32(max(cfg.cache_buckets, 1) - 1),
    )
    top_slots = path_slot_indices(cfg, top_b).reshape(-1)  # [B*kc*z]

    fused = cfg.cipher_impl in ("pallas_fused", "pallas_fused_tiled")
    with device_phase("oram_fetch"):
        if axis_name is None and fused and cfg.encrypted:
            # single-chip fast path: gather + decrypt in ONE HBM pass
            # (oblivious/pallas_gather.py); the sharded path below keeps
            # decrypt-after-psum so tree plaintext never transits ICI
            from ..oblivious.pallas_gather import (
                gather_decrypt_rows,
                gather_decrypt_rows_tiled,
            )

            g = (gather_decrypt_rows_tiled
                 if cfg.cipher_impl == "pallas_fused_tiled"
                 else gather_decrypt_rows)
            pidx, pval = g(
                state.cipher_key, state.tree_idx, state.tree_val, state.nonces,
                bot_b, z=z, rounds=cfg.cipher_rounds,
                interpret=jax.default_backend() not in _TPU_BACKENDS,
            )
        else:
            pidx = _path_gather(
                state.tree_idx.reshape(-1, z), bot_b, axis_name
            )  # [B*nbot, z]
            pval = _path_gather(state.tree_val, bot_b, axis_name)  # [B*nbot, z*v]
            pnonce = _path_gather(state.nonces, bot_b, axis_name)
            pidx, pval = cipher_rows(
                cfg, state.cipher_key, bot_b, pnonce, pidx, pval
            )
        if kc:
            # cached top levels: plain private gathers, no cipher — the
            # cache planes are plaintext working state like the stash
            pidx = jnp.concatenate(
                [state.cache_idx[top_slots].reshape(b, kc, z),
                 pidx.reshape(b, nbot, z)], axis=1,
            ).reshape(b * plen, z)
            pval = jnp.concatenate(
                [state.cache_val[top_b].reshape(b, kc, z * v),
                 pval.reshape(b, nbot, z * v)], axis=1,
            ).reshape(b * plen, z * v)
        # non-owner copies of shared buckets are invalidated
        pidx = jnp.where(fowner[:, None], pidx, SENTINEL)
        if recursive:
            # per-slot leaf metadata rides its own (jnp) cipher plane —
            # the fused kernels cover only the idx/val planes
            from .path_oram import leaf_plane_cipher

            pleaf = _path_gather(
                state.tree_leaf.reshape(-1, z), bot_b, axis_name
            )
            pnonce_l = _path_gather(state.nonces, bot_b, axis_name)
            pleaf = leaf_plane_cipher(
                cfg, state.cipher_key, bot_b, pnonce_l, pleaf,
            )
            if kc:
                pleaf = jnp.concatenate(
                    [state.cache_leaf[top_slots].reshape(b, kc, z),
                     pleaf.reshape(b, nbot, z)], axis=1,
                )
            pleaf = pleaf.reshape(-1)

    w = s + nslots + b  # + b reserved rows for net inserts
    widx0 = jnp.concatenate(
        [state.stash_idx, pidx.reshape(-1), jnp.full((b,), SENTINEL, U32)]
    )
    wval0 = jnp.concatenate(
        [state.stash_val, pval.reshape(-1, v), jnp.zeros((b, v), U32)], axis=0
    )

    # --- 2. vectorized slot-order apply --------------------------------
    # Initial presence via a dense block-index → working-set-row map (one
    # scatter + one gather; block indices are unique among live blocks,
    # so at most one row writes each map slot). Replaces a [B, W] compare
    # that costs O(B·W) — ~3·10^8 bools per round at B=2048. The map is
    # private working memory, same standing as the posmap.
    iota_w = jnp.arange(w, dtype=U32)
    # non-real rows (SENTINEL, dummy) drop out of bounds: a live block
    # occupies exactly one working-set row, so in-bounds targets are
    # unique and the scatter can use the parallel lowering
    row_map = jnp.full((cfg.blocks + 2,), U32(w)).at[
        jnp.where(widx0 < U32(cfg.blocks), widx0, U32(cfg.blocks + 2))
    ].set(iota_w, mode="drop", unique_indices=True)
    pos0 = row_map[jnp.minimum(idxs, U32(cfg.blocks))]  # u32[B]; w = absent
    present0 = pos0 != U32(w)
    pos0 = jnp.minimum(pos0, U32(w - 1))
    vals0 = jnp.where(
        present0[:, None], wval0[pos0.astype(jnp.int32)], 0
    )  # u32[B, V]

    with device_phase("oram_apply"):
        outs, final_val, final_alive = apply_batch(vals0, present0)

    # --- final per-key state → working-set rows ------------------------
    # the round's last op on each key commits the callback's final state:
    # updates rewrite (or kill) the existing row; net inserts land in the
    # b reserved trailing rows (row s + nslots + slot index)
    upd = last_occ & present0
    ins = last_occ & ~present0 & final_alive

    slot_iota = jnp.arange(b, dtype=U32)
    row_tgt = jnp.where(
        upd, pos0, jnp.where(ins, U32(s + nslots) + slot_iota, U32(w))
    )  # OOB = no write
    widx = widx0.at[row_tgt].set(
        jnp.where(final_alive, idxs, SENTINEL), mode="drop"
    )
    wval = wval0.at[row_tgt.astype(jnp.int32)].set(final_val, mode="drop")

    if recursive:
        # leaves ride the per-slot metadata plane (the map is its own
        # ORAM now — it cannot be gathered); rows committed this round
        # take their key's winning fresh leaf, the same value the map's
        # remap just recorded (the posmap↔metadata invariant)
        wleaf = jnp.concatenate(
            [state.stash_leaf, pleaf, jnp.zeros((b,), U32)]
        ).at[row_tgt].set(new_leaves, mode="drop")
    else:
        # leaves for the whole working set come from the remapped private
        # posmap (the authoritative assignment — the tree stores no
        # leaves): rows touched this round already read back their op's
        # new leaf
        wleaf = working_leaves(posmap, cfg, widx)

    # --- 3. joint level-synchronous greedy eviction --------------------
    # One argsort of the working set by leaf, then per level: entries
    # destined to one bucket are contiguous in sorted order (a bucket at
    # level L is a leaf prefix, and sorting by leaf sorts by every
    # prefix), so within-bucket ranks are segmented cumsums — O(W) work
    # per level with no [W, B] masks (which at B=1024, plen=21 would be
    # ~10^8 bools per level).
    with device_phase("oram_evict"):
        valid = widx != SENTINEL
        slot_tgt, placed = _assign_evictions(
            cfg, valid, wleaf, bmap, b, nslots, sort_impl,
            # [col, level, slot] layout over the B fetched paths
            lambda oc, level, rank:
                (oc * U32(plen) + U32(level)) * U32(z) + rank,
        )

        # eviction slots are unique by construction (rank < z within a
        # bucket, disjoint slot ranges across buckets); unplaced rows drop
        new_pidx = jnp.full((nslots,), SENTINEL, U32).at[slot_tgt].set(
            widx, mode="drop", unique_indices=True
        )
        new_pval = jnp.zeros((nslots, v), U32).at[slot_tgt].set(
            wval, mode="drop", unique_indices=True
        )
        if recursive:
            new_pleaf = jnp.zeros((nslots,), U32).at[slot_tgt].set(
                wleaf, mode="drop", unique_indices=True
            )

        # --- 4. stash recompaction -------------------------------------
        leftover = valid & ~placed
        srank = rank_of(leftover)
        starget = jnp.where(leftover, srank, s)  # OOB = dropped
        stash_idx = jnp.full((s,), SENTINEL, U32).at[starget].set(
            widx, mode="drop", unique_indices=True
        )
        stash_val = jnp.zeros((s, v), U32).at[starget].set(
            wval, mode="drop", unique_indices=True
        )
        stash_leaf = (
            jnp.zeros((s,), U32).at[starget].set(
                wleaf, mode="drop", unique_indices=True
            )
            if recursive
            else state.stash_leaf
        )
        n_left = jnp.sum(leftover.astype(jnp.int32))
        # == n_left - min(n_left, s), in the interval-transparent form
        stash_dropped = jnp.maximum(n_left - s, 0).astype(U32)

    # the eviction output new_pidx/new_pval is [col, level, slot]-
    # ordered, so the top-kc/bottom split is a contiguous reshape per
    # column; one owner bit per bucket row covers all z slots on the
    # bucket-axis scatters below
    fowner_bot = fowner.reshape(b, plen)[:, kc:].reshape(b * nbot)
    bot_pidx = new_pidx.reshape(b, plen, z)[:, kc:].reshape(b * nbot, z)
    bot_pval = new_pval.reshape(b, plen, z * v)[:, kc:].reshape(
        b * nbot, z * v
    )
    epochs_w = jnp.broadcast_to(state.epoch[None, :], (b * nbot, 2))
    with device_phase("oram_writeback"):
        if axis_name is None and fused and cfg.encrypted:
            # single-chip fast path: encrypt + scatter in ONE HBM pass (the
            # write-back mirror of the fused fetch; pallas_gather.py) —
            # the nonce commit rides the same kernel, so this branch has no
            # XLA scatter at all
            from ..oblivious.pallas_gather import (
                scatter_encrypt_rows,
                scatter_encrypt_rows_tiled,
            )

            sc = (scatter_encrypt_rows_tiled
                  if cfg.cipher_impl == "pallas_fused_tiled"
                  else scatter_encrypt_rows)
            tree_idx_new, tree_val_new, nonces = sc(
                state.cipher_key, state.tree_idx, state.tree_val, state.nonces,
                bot_b, fowner_bot, state.epoch,
                bot_pidx, bot_pval,
                z=z, rounds=cfg.cipher_rounds,
                interpret=jax.default_backend() not in _TPU_BACKENDS,
            )
        else:
            enc_pidx, enc_pval = cipher_rows(
                cfg,
                state.cipher_key,
                bot_b,
                epochs_w,
                bot_pidx,
                bot_pval,
            )
            tree_idx_new = _path_scatter(
                state.tree_idx.reshape(-1, z), bot_b, enc_pidx, axis_name,
                fowner_bot,
            ).reshape(-1)
            tree_val_new = _path_scatter(
                state.tree_val, bot_b, enc_pval, axis_name, fowner_bot
            )
            nonces = (
                _path_scatter(
                    state.nonces, bot_b, epochs_w, axis_name, fowner_bot
                )
                if cfg.encrypted
                else state.nonces
            )
        if kc:
            # cached levels write back plaintext, owner-masked exactly
            # like the tree scatters (one owning column per bucket ⇒
            # unique in-bounds targets); replicated private state, so no
            # collective even under sharding — every chip writes the
            # identical values (the stash-recompaction standing)
            fowner_top = fowner.reshape(b, plen)[:, :kc].reshape(b * kc)
            cache_idx_new = _path_scatter(
                state.cache_idx, top_slots,
                new_pidx.reshape(b, plen, z)[:, :kc].reshape(-1), None,
                jnp.repeat(fowner_top, z),
            )
            cache_val_new = _path_scatter(
                state.cache_val, top_b,
                new_pval.reshape(b, plen, z * v)[:, :kc].reshape(
                    b * kc, z * v
                ),
                None, fowner_top,
            )
        else:
            cache_idx_new = state.cache_idx
            cache_val_new = state.cache_val
        cache_leaf_new = state.cache_leaf
        if recursive:
            from .path_oram import leaf_plane_cipher

            pleaf3 = new_pleaf.reshape(b, plen, z)
            enc_pleaf = leaf_plane_cipher(
                cfg, state.cipher_key, bot_b, epochs_w,
                pleaf3[:, kc:].reshape(b * nbot, z),
            )
            tree_leaf_new = _path_scatter(
                state.tree_leaf.reshape(-1, z), bot_b, enc_pleaf, axis_name,
                fowner_bot,
            ).reshape(-1)
            if kc:
                cache_leaf_new = _path_scatter(
                    state.cache_leaf, top_slots,
                    pleaf3[:, :kc].reshape(-1), None,
                    jnp.repeat(fowner_top, z),
                )
        else:
            tree_leaf_new = state.tree_leaf
    new_state = OramState(
        tree_idx=tree_idx_new,
        tree_val=tree_val_new,
        cache_idx=cache_idx_new,
        cache_val=cache_val_new,
        cache_leaf=cache_leaf_new,
        tree_leaf=tree_leaf_new,
        stash_idx=stash_idx,
        stash_val=stash_val,
        stash_leaf=stash_leaf,
        # evict_window == 1: the buffer planes are zero-length and the
        # window bookkeeping never advances — bit-for-bit the pre-PR-15
        # per-round-eviction program
        ebuf_idx=state.ebuf_idx,
        ebuf_val=state.ebuf_val,
        ebuf_leaf=state.ebuf_leaf,
        ebuf_paths=state.ebuf_paths,
        ebuf_rounds=state.ebuf_rounds,
        ebuf_gen=state.ebuf_gen,
        fetch_tag=state.fetch_tag,
        posmap=posmap,
        overflow=state.overflow + stash_dropped,
        nonces=nonces,
        cipher_key=state.cipher_key,
        epoch=epoch_next(state.epoch),
    )
    if recursive:
        leaves = jnp.stack([leaves, inner_leaves], axis=1)
    return new_state, outs, leaves


def _oram_fetch_round(
    cfg: OramConfig,
    state: OramState,
    idxs: jax.Array,  # u32[B] block indices (cfg.dummy_index = dummy op)
    new_leaves: jax.Array,  # u32[B] fresh uniform leaves (remap targets)
    dummy_leaves: jax.Array,  # u32[B] fresh uniform leaves (dummy fetches)
    apply_batch,
    axis_name: str | None = None,
    occ_impl: str = "dense",
    sort_impl: str = "xla",
    pm_new_leaves: jax.Array | None = None,
    pm_dummy_leaves: jax.Array | None = None,
):
    """The delayed-eviction fetch round (``cfg.evict_window > 1``).

    Identical contract to :func:`oram_round` — same dedup, position
    resolution, gather+decrypt, and vectorized apply — but the
    scatter+encrypt half of the round is GONE: instead of evicting back
    into the fetched buckets, every live working-set row recompacts into
    the private eviction buffer (buffer-first; the stash catches the
    spill, keeping stash occupancy the pressure signal), the round's
    leaves are appended to the public window ledger (``ebuf_paths``),
    and the fetched buckets are tagged with the current flush
    generation. Tagged buckets' HBM/cache copies are *stale* — their
    live rows moved to the buffer at their fetch round — so re-fetches
    within one window invalidate them from the working set exactly like
    non-owner duplicates (each live block still enters the working set
    at most once, which the block→row map's uniqueness relies on). The
    tree arrays, cache planes, nonces, and the cipher epoch are
    untouched: the steady-state round performs ZERO HBM tree scatters
    and zero encrypt work (CI-audited row accounting,
    tools/check_tree_cache_oblivious.py:check_evict_round_accounting).
    :func:`oram_flush` drains the window.
    """
    from .posmap import lookup_remap_round

    b = idxs.shape[0]
    z, v, plen = cfg.bucket_slots, cfg.value_words, cfg.path_len
    s, c = cfg.stash_size, cfg.evict_buffer_slots
    nslots = b * plen * z
    recursive = cfg.posmap is not None

    # --- 1. dedup, position-map read/remap, path fetch (as E=1) --------
    if occ_impl == "scan":
        first_occ, last_occ, _ = occurrence_masks_sorted(
            idxs, cfg.dummy_index, sort_impl=sort_impl,
            key_bits=max(1, cfg.dummy_index.bit_length()),
        )
    else:
        first_occ, last_occ, _ = occurrence_masks(idxs, cfg.dummy_index)
    posmap, leaves, inner_leaves = lookup_remap_round(
        cfg, state.posmap, idxs, new_leaves, dummy_leaves,
        first_occ, last_occ,
        pm_new_leaves=pm_new_leaves, pm_dummy_leaves=pm_dummy_leaves,
        occ_impl=occ_impl, sort_impl=sort_impl,
    )

    path_b = jax.vmap(lambda lf: path_bucket_indices(cfg, lf))(leaves)
    flat_b = path_b.reshape(b * plen)
    bmap = _bucket_owner_map(cfg, flat_b)
    cols_flat = jnp.repeat(jnp.arange(b, dtype=U32), plen)
    # keep = this round's owner copy of a bucket that is NOT stale: a
    # bucket tagged earlier in this flush window already surrendered its
    # live rows to the buffer, so its HBM/cache bytes are dead copies
    fresh = state.fetch_tag[flat_b] != state.ebuf_gen
    keep = (bmap[flat_b] == cols_flat) & fresh

    # HBM slot planes are addressed on the bucket axis ([n, Z] reshape
    # views) exactly as in oram_round — flat slot ids escape u32/int32
    # one geometry doubling before bucket ids do (rangelint;
    # OPERATIONS.md §18). The tiny cache planes keep flat addressing.
    kc = cfg.top_cache_levels
    nbot = plen - kc
    bot_b = path_b[:, kc:].reshape(b * nbot)
    # level ℓ < kc heap ids are < 2^kc − 1 = cache_buckets by
    # construction; the min states that per-level invariant for
    # interval reasoning (runtime identity, see oram_round)
    top_b = jnp.minimum(
        path_b[:, :kc].reshape(b * kc),
        U32(max(cfg.cache_buckets, 1) - 1),
    )
    top_slots = path_slot_indices(cfg, top_b).reshape(-1)

    fused = cfg.cipher_impl in ("pallas_fused", "pallas_fused_tiled")
    with device_phase("oram_fetch"):
        if axis_name is None and fused and cfg.encrypted:
            from ..oblivious.pallas_gather import (
                gather_decrypt_rows,
                gather_decrypt_rows_tiled,
            )

            g = (gather_decrypt_rows_tiled
                 if cfg.cipher_impl == "pallas_fused_tiled"
                 else gather_decrypt_rows)
            pidx, pval = g(
                state.cipher_key, state.tree_idx, state.tree_val, state.nonces,
                bot_b, z=z, rounds=cfg.cipher_rounds,
                interpret=jax.default_backend() not in _TPU_BACKENDS,
            )
        else:
            pidx = _path_gather(
                state.tree_idx.reshape(-1, z), bot_b, axis_name
            )  # [B*nbot, z]
            pval = _path_gather(state.tree_val, bot_b, axis_name)
            pnonce = _path_gather(state.nonces, bot_b, axis_name)
            pidx, pval = cipher_rows(
                cfg, state.cipher_key, bot_b, pnonce, pidx, pval
            )
        if kc:
            pidx = jnp.concatenate(
                [state.cache_idx[top_slots].reshape(b, kc, z),
                 pidx.reshape(b, nbot, z)], axis=1,
            ).reshape(b * plen, z)
            pval = jnp.concatenate(
                [state.cache_val[top_b].reshape(b, kc, z * v),
                 pval.reshape(b, nbot, z * v)], axis=1,
            ).reshape(b * plen, z * v)
        # non-owner copies AND stale copies are invalidated
        pidx = jnp.where(keep[:, None], pidx, SENTINEL)
        if recursive:
            from .path_oram import leaf_plane_cipher

            pleaf = _path_gather(
                state.tree_leaf.reshape(-1, z), bot_b, axis_name
            )
            pnonce_l = _path_gather(state.nonces, bot_b, axis_name)
            pleaf = leaf_plane_cipher(
                cfg, state.cipher_key, bot_b, pnonce_l, pleaf,
            )
            if kc:
                pleaf = jnp.concatenate(
                    [state.cache_leaf[top_slots].reshape(b, kc, z),
                     pleaf.reshape(b, nbot, z)], axis=1,
                )
            pleaf = pleaf.reshape(-1)

    # working set = stash ∪ buffer ∪ fetched paths ∪ B insert rows
    w = s + c + nslots + b
    widx0 = jnp.concatenate(
        [state.stash_idx, state.ebuf_idx, pidx.reshape(-1),
         jnp.full((b,), SENTINEL, U32)]
    )
    wval0 = jnp.concatenate(
        [state.stash_val, state.ebuf_val, pval.reshape(-1, v),
         jnp.zeros((b, v), U32)], axis=0
    )

    # --- 2. vectorized slot-order apply (as E=1; see oram_round) -------
    iota_w = jnp.arange(w, dtype=U32)
    row_map = jnp.full((cfg.blocks + 2,), U32(w)).at[
        jnp.where(widx0 < U32(cfg.blocks), widx0, U32(cfg.blocks + 2))
    ].set(iota_w, mode="drop", unique_indices=True)
    pos0 = row_map[jnp.minimum(idxs, U32(cfg.blocks))]
    present0 = pos0 != U32(w)
    pos0 = jnp.minimum(pos0, U32(w - 1))
    vals0 = jnp.where(
        present0[:, None], wval0[pos0.astype(jnp.int32)], 0
    )

    with device_phase("oram_apply"):
        outs, final_val, final_alive = apply_batch(vals0, present0)

    upd = last_occ & present0
    ins = last_occ & ~present0 & final_alive

    slot_iota = jnp.arange(b, dtype=U32)
    row_tgt = jnp.where(
        upd, pos0, jnp.where(ins, U32(s + c + nslots) + slot_iota, U32(w))
    )
    widx = widx0.at[row_tgt].set(
        jnp.where(final_alive, idxs, SENTINEL), mode="drop"
    )
    wval = wval0.at[row_tgt.astype(jnp.int32)].set(final_val, mode="drop")

    if recursive:
        # the only consumer of leaf assignments in the fetch round is
        # the recursive per-row leaf plane below (flat maps resolve
        # leaves from the posmap at FLUSH time — no eviction happens
        # here, so tracing a working_leaves gather would add a dead
        # secret-indexed access for the analyzers to walk)
        wleaf = jnp.concatenate(
            [state.stash_leaf, state.ebuf_leaf, pleaf, jnp.zeros((b,), U32)]
        ).at[row_tgt].set(new_leaves, mode="drop")

    # --- 3. recompact EVERYTHING into buffer ∪ stash (no eviction) -----
    # buffer-first: the buffer is where window contents are expected to
    # live, the stash is the spill — so stash occupancy remains the
    # overflow-pressure signal the health fold watches. One rank + two
    # split scatters; total live past C+S drops into the shared sticky
    # overflow counter (the buffer-occupancy canary).
    with device_phase("oram_evict"):
        valid = widx != SENTINEL
        crank = rank_of(valid)
        ctarget = jnp.where(valid, crank, c + s)  # OOB = dropped
        comb_idx = jnp.full((c + s,), SENTINEL, U32).at[ctarget].set(
            widx, mode="drop", unique_indices=True
        )
        comb_val = jnp.zeros((c + s, v), U32).at[ctarget].set(
            wval, mode="drop", unique_indices=True
        )
        ebuf_idx, stash_idx = comb_idx[:c], comb_idx[c:]
        ebuf_val, stash_val = comb_val[:c], comb_val[c:]
        if recursive:
            comb_leaf = jnp.zeros((c + s,), U32).at[ctarget].set(
                wleaf, mode="drop", unique_indices=True
            )
            ebuf_leaf, stash_leaf = comb_leaf[:c], comb_leaf[c:]
        else:
            ebuf_leaf, stash_leaf = state.ebuf_leaf, state.stash_leaf
        n_live = jnp.sum(valid.astype(jnp.int32))
        # == n_live - min(n_live, c+s), in the interval-transparent
        # form (rangelint; the sticky counter's 2^16 budget absorbs it)
        dropped = jnp.maximum(n_live - (c + s), 0).astype(U32)

    # --- 4. window bookkeeping; the tree/cache/nonces are UNTOUCHED ----
    # the append row: rounds < W whenever a fetch round runs (the
    # batcher flushes at W and resets the counter); the min states that
    # schedule invariant, which the declared [0, W] state bound cannot
    # carry by itself (runtime identity — without it the slice start
    # could reach the plane's end and XLA would clamp the write)
    ebuf_paths = jax.lax.dynamic_update_slice(
        state.ebuf_paths, leaves,
        ((jnp.minimum(state.ebuf_rounds, U32(cfg.evict_window - 1))
          * U32(b)).astype(jnp.int32),),
    )
    # monotone generations make scatter-max exact for duplicate buckets
    fetch_tag = state.fetch_tag.at[flat_b].max(state.ebuf_gen)

    new_state = OramState(
        tree_idx=state.tree_idx,
        tree_val=state.tree_val,
        cache_idx=state.cache_idx,
        cache_val=state.cache_val,
        cache_leaf=state.cache_leaf,
        tree_leaf=state.tree_leaf,
        stash_idx=stash_idx,
        stash_val=stash_val,
        stash_leaf=stash_leaf,
        ebuf_idx=ebuf_idx,
        ebuf_val=ebuf_val,
        ebuf_leaf=ebuf_leaf,
        ebuf_paths=ebuf_paths,
        ebuf_rounds=state.ebuf_rounds + U32(1),
        ebuf_gen=state.ebuf_gen,
        fetch_tag=fetch_tag,
        posmap=posmap,
        overflow=state.overflow + dropped,
        nonces=state.nonces,
        cipher_key=state.cipher_key,
        epoch=state.epoch,
    )
    if recursive:
        leaves = jnp.stack([leaves, inner_leaves], axis=1)
    return new_state, outs, leaves


def flush_target_slots(cfg: OramConfig) -> int:
    """Static write-target count of one flush: the window's fetched
    buckets deduplicated — at most ``window·fetch_count·path_len``
    path slots, and never more than the whole (padded) heap. The
    ``min`` is THE amortization: once ``E·F`` paths cover the tree,
    each extra window round adds fetch traffic but no write traffic,
    so the amortized scatter+encrypt cost per round falls as 1/E
    toward ``n_buckets/(E·F)`` rows (bench.py ``evict_ab`` measures
    the curve; the row-accounting gate pins the shape)."""
    return min(cfg.evict_window * cfg.evict_fetch_count * cfg.path_len,
               cfg.n_buckets_padded)


def oram_flush(
    cfg: OramConfig,
    state: OramState,
    axis_name: str | None = None,
    sort_impl: str = "xla",
) -> OramState:
    """Batched eviction + write-back of one accumulated flush window.

    Runs every ``evict_window`` fetch rounds on the round-counter
    cadence (never on buffer contents — the schedule must stay
    recipient-independent; the seeded flush_on_buffer_contents mutant
    pins the failure mode). One pass:

    1. the window's fetched paths (the public ``ebuf_paths`` ledger —
       ``window·fetch_count`` leaves, rounds beyond ``ebuf_rounds``
       masked inactive) expand to bucket ids and DEDUPLICATE into a
       static ``flush_target_slots`` array: every bucket fetched this
       window appears exactly once, so the window's shared buckets —
       the whole top of the tree, re-fetched every round — are written
       once per window instead of once per round. The dedup sort runs
       on PUBLIC data (bucket ids are the past transcript);
    2. the working set — eviction buffer ∪ stash — is greedily assigned
       to the deepest target bucket on each entry's own path
       (the SAME ``_assign_evictions`` body the per-round eviction
       runs, with the compacted [target, slot] output layout);
    3. one scatter+encrypt writes every target bucket back under the
       current epoch — ``flush_target_slots`` rows, cached top buckets
       peeled off to the plaintext cache planes by the heap-prefix
       mask;
    4. leftovers recompact into the stash, the buffer empties, and the
       flush generation bumps (re-validating every tagged bucket in
       O(1)).

    Every tagged bucket MUST be rewritten here: its HBM bytes are a
    stale copy of rows that moved to the buffer at fetch time, and a
    later window would re-fetch them as fresh after the generation
    bump. Deterministic given the state (no RNG), so journal replay
    re-executes it bit-identically (engine/journal.py KIND_FLUSH).
    Recursive position maps flush their internal tree in the same call.

    **Sharded (``axis_name`` set, inside shard_map).** The dedup, the
    eviction assignment, and the stash/buffer recompaction all run on
    the replicated private working set — identical on every chip, no
    collective — and only the final tree/nonce scatters change: the
    ``_path_scatter`` sharded branch ANDs the ``tree_tgt`` owner mask
    with each chip's contiguous heap range, so every chip writes
    exactly the target rows it owns and the union across the mesh is
    the single-chip flush bit for bit. The per-chip scatter still
    carries all ``t`` compacted rows (uniform static shape — row
    counts stay a pure function of geometry, never contents); non-owned
    rows drop out of bounds. Cache planes and the recursive inner tree
    are replicated private state and always take the axis-free path.
    """
    from .posmap import inner_oram_config

    z, v, plen = cfg.bucket_slots, cfg.value_words, cfg.path_len
    s, c = cfg.stash_size, cfg.evict_buffer_slots
    ncols = cfg.evict_window * cfg.evict_fetch_count
    f = cfg.evict_fetch_count
    pad = cfg.n_buckets_padded
    t = flush_target_slots(cfg)
    recursive = cfg.posmap is not None

    posmap = state.posmap
    if recursive:
        icfg = inner_oram_config(cfg.posmap)
        # the INNER tree is replicated private state (mesh.py P() specs),
        # never sharded — its flush must run the axis-free program on
        # every chip (the same convention oram_round uses for inner
        # accesses). Passing the outer axis_name here would owner-mask a
        # replicated plane against its FULL size: shard 0 would own
        # everything and every other replica nothing, silently diverging
        # the replicas on the first recursive flush.
        posmap = posmap._replace(
            inner=oram_flush(icfg, posmap.inner, None, sort_impl)
        )

    with device_phase("oram_flush"):
        leaves = state.ebuf_paths  # u32[ncols], public window ledger
        active = (jnp.arange(ncols, dtype=U32) // U32(f)) < state.ebuf_rounds
        path_b = jax.vmap(lambda lf: path_bucket_indices(cfg, lf))(leaves)
        flat_b = path_b.reshape(ncols * plen)
        active_flat = jnp.repeat(active, plen)
        # -- 1. public dedup: window bucket set → t compacted targets
        sb = jnp.sort(jnp.where(active_flat, flat_b, U32(pad)))
        first = jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), sb[1:] != sb[:-1]]
        ) & (sb < U32(pad))
        fi = first.astype(U32)
        # compacted slot of each unique run: the exclusive count of
        # earlier firsts, as the shifted inclusive cumsum (the
        # interval-transparent form, see primitives.rank_of — cumsum−fi
        # reads as a full-lane u32 subtraction to interval reasoning)
        crank = jnp.concatenate(
            [jnp.zeros((1,), U32), jnp.cumsum(fi)[:-1]]
        )
        # target slot → bucket id (pad = unused slot, dropped on write)
        tgt_b = jnp.full((t,), U32(pad)).at[
            jnp.where(first, crank, U32(t))
        ].set(sb, mode="drop", unique_indices=True)
        # dense bucket id → target slot (t = not a target this window)
        dmap = jnp.full((pad,), U32(t)).at[
            jnp.where(first, sb, U32(pad))
        ].set(crank, mode="drop", unique_indices=True)

        # working set = buffer ∪ stash (buffer-first, the fetch-round
        # recompaction order)
        widx = jnp.concatenate([state.ebuf_idx, state.stash_idx])
        wval = jnp.concatenate([state.ebuf_val, state.stash_val], axis=0)
        if recursive:
            wleaf = jnp.concatenate([state.ebuf_leaf, state.stash_leaf])
        else:
            wleaf = working_leaves(posmap, cfg, widx)

        valid = widx != SENTINEL
        slot_tgt, placed = _assign_evictions(
            cfg, valid, wleaf, dmap, t, t * z, sort_impl,
            # [target, slot] layout over the compacted window union
            lambda ts, level, rank: ts * U32(z) + rank,
        )
        new_pidx = jnp.full((t * z,), SENTINEL, U32).at[slot_tgt].set(
            widx, mode="drop", unique_indices=True
        )
        new_pval = jnp.zeros((t * z, v), U32).at[slot_tgt].set(
            wval, mode="drop", unique_indices=True
        )
        if recursive:
            new_pleaf = jnp.zeros((t * z,), U32).at[slot_tgt].set(
                wleaf, mode="drop", unique_indices=True
            )

        # leftovers recompact into the stash; the buffer empties
        leftover = valid & ~placed
        srank = rank_of(leftover)
        starget = jnp.where(leftover, srank, s)  # OOB = dropped
        stash_idx = jnp.full((s,), SENTINEL, U32).at[starget].set(
            widx, mode="drop", unique_indices=True
        )
        stash_val = jnp.zeros((s, v), U32).at[starget].set(
            wval, mode="drop", unique_indices=True
        )
        stash_leaf = (
            jnp.zeros((s,), U32).at[starget].set(
                wleaf, mode="drop", unique_indices=True
            )
            if recursive
            else state.stash_leaf
        )
        n_left = jnp.sum(leftover.astype(jnp.int32))
        # == n_left - min(n_left, s), in the interval-transparent form
        stash_dropped = jnp.maximum(n_left - s, 0).astype(U32)

        # --- write-back: every target bucket once, cached top buckets
        # (a heap-id prefix) peeled off to the plaintext cache planes.
        # Shapes are t rows per plane; masked slots drop out of bounds.
        # HBM slot planes are addressed on the bucket axis ([n, Z]
        # reshape views) as in oram_round — flat slot ids escape
        # u32/int32 one geometry doubling before bucket ids (rangelint,
        # OPERATIONS.md §18); the tiny cache planes keep flat slot
        # addressing over CLAMPED bucket ids (cached targets are < cb
        # by the is_cached mask; the min states it for the intervals).
        kc = cfg.top_cache_levels
        cb = cfg.cache_buckets
        valid_tgt = tgt_b < U32(pad)
        is_cached = tgt_b < U32(cb)  # kc=0 → cb=0 → all False
        tree_tgt = valid_tgt & ~is_cached
        cache_tgt_slots = path_slot_indices(
            cfg, jnp.minimum(tgt_b, U32(max(cb, 1) - 1))
        ).reshape(-1)  # [t*z] flat cache-plane slots
        pidx2 = new_pidx.reshape(t, z)
        pval2 = new_pval.reshape(t, z * v)
        epochs_w = jnp.broadcast_to(state.epoch[None, :], (t, 2))
        fused = cfg.cipher_impl in ("pallas_fused", "pallas_fused_tiled")
        if axis_name is None and fused and cfg.encrypted:
            from ..oblivious.pallas_gather import (
                scatter_encrypt_rows,
                scatter_encrypt_rows_tiled,
            )

            sc = (scatter_encrypt_rows_tiled
                  if cfg.cipher_impl == "pallas_fused_tiled"
                  else scatter_encrypt_rows)
            tree_idx_new, tree_val_new, nonces = sc(
                state.cipher_key, state.tree_idx, state.tree_val,
                state.nonces, tgt_b, tree_tgt, state.epoch,
                pidx2, pval2,
                z=z, rounds=cfg.cipher_rounds,
                interpret=jax.default_backend() not in _TPU_BACKENDS,
            )
        else:
            enc_pidx, enc_pval = cipher_rows(
                cfg, state.cipher_key, tgt_b, epochs_w, pidx2, pval2
            )
            tree_idx_new = _path_scatter(
                state.tree_idx.reshape(-1, z), tgt_b, enc_pidx, axis_name,
                tree_tgt,
            ).reshape(-1)
            tree_val_new = _path_scatter(
                state.tree_val, tgt_b, enc_pval, axis_name, tree_tgt
            )
            nonces = (
                _path_scatter(
                    state.nonces, tgt_b, epochs_w, axis_name, tree_tgt
                )
                if cfg.encrypted
                else state.nonces
            )
        if kc:
            # cache planes are indexed by heap id directly (a heap
            # prefix), so the clamped tgt_b slots address them; only
            # cached targets land, the rest drop out of bounds
            cache_idx_new = _path_scatter(
                state.cache_idx, cache_tgt_slots, new_pidx, None,
                jnp.repeat(is_cached, z),
            )
            cache_val_new = _path_scatter(
                state.cache_val, tgt_b, pval2, None, is_cached
            )
        else:
            cache_idx_new = state.cache_idx
            cache_val_new = state.cache_val
        cache_leaf_new = state.cache_leaf
        if recursive:
            from .path_oram import leaf_plane_cipher

            pleaf2 = new_pleaf.reshape(t, z)
            enc_pleaf = leaf_plane_cipher(
                cfg, state.cipher_key, tgt_b, epochs_w, pleaf2
            )
            tree_leaf_new = _path_scatter(
                state.tree_leaf.reshape(-1, z), tgt_b, enc_pleaf, axis_name,
                tree_tgt,
            ).reshape(-1)
            if kc:
                cache_leaf_new = _path_scatter(
                    state.cache_leaf, cache_tgt_slots, new_pleaf, None,
                    jnp.repeat(is_cached, z),
                )
        else:
            tree_leaf_new = state.tree_leaf

    return OramState(
        tree_idx=tree_idx_new,
        tree_val=tree_val_new,
        cache_idx=cache_idx_new,
        cache_val=cache_val_new,
        cache_leaf=cache_leaf_new,
        tree_leaf=tree_leaf_new,
        stash_idx=stash_idx,
        stash_val=stash_val,
        stash_leaf=stash_leaf,
        ebuf_idx=jnp.full((c,), SENTINEL, U32),
        ebuf_val=jnp.zeros((c, v), U32),
        ebuf_leaf=jnp.zeros_like(state.ebuf_leaf),
        ebuf_paths=state.ebuf_paths,  # inactive at rounds=0; public
        ebuf_rounds=jnp.zeros((), U32),
        ebuf_gen=state.ebuf_gen + U32(1),
        fetch_tag=state.fetch_tag,  # generation bump re-validates all
        posmap=posmap,
        overflow=state.overflow + stash_dropped,
        nonces=nonces,
        cipher_key=state.cipher_key,
        epoch=epoch_next(state.epoch),
    )
