"""Path-ORAM over an HBM-resident SoA bucket tree (the storage heart).

TPU-native re-design of the reference's storage engine (upstream
``mc-oblivious-ram`` PathORAM-4096-Z4, SURVEY.md §2b): structure-of-arrays
bucket tree, flat position map, fixed-size stash with masked linear scan,
and greedy masked eviction — all as jit-compiled branchless array programs.
"""

from .path_oram import OramConfig, OramState, init_oram, oram_access  # noqa: F401
