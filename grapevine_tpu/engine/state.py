"""Engine state: the two ORAMs plus private scalar bookkeeping.

Value layouts (all uint32 words, little-endian byte order on the host
side; timestamps and the insertion sequence counter are u64 carried as
two u32 lanes (lo, hi) — matching the wire's u64 timestamp with no 2106
rollover and no 2^32-creates lifetime bound):

records ORAM block (one Record, reference README.md:132-136):
    id[4] | sender[8] | recipient[8] | ts[2] | payload[234]   = 256 words
    (exactly the reference's 1024-byte record)

mailbox ORAM block (one hash bucket of K mailboxes):
    per mailbox: key[8] |
        entries[cap × (blk[1] | idw[1] | seq[2] | ts[2])]
    → K * (8 + 6*cap) words.

A mailbox entry stores only the record's block index plus the second
msg-id word; the full 128-bit id lives in (and is verified against) the
records ORAM. Truncated entry matching is only ever used to *locate* an
entry after the records ORAM has verified the full id (phases B→C), or
for zero-id selection where the mailbox invariant supplies correctness;
block indices are unique among live records, so at most one entry can
match.

Private (non-transcript) state, the EPC analog — see the threat model in
oram/path_oram.py: the free-block stack, live-recipient count, the global
insertion sequence counter, the mailbox hash key, and the RNG key.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..config import GrapevineConfig
from ..wire import constants as C
from ..oram.path_oram import OramConfig, OramState, init_oram

U32 = jnp.uint32

# records block layout offsets (words); u64 fields = (lo, hi) u32 lanes
REC_ID = slice(0, 4)
REC_SENDER = slice(4, 12)
REC_RECIPIENT = slice(12, 20)
REC_TS = 20  # u64 low lane; high lane at REC_TSH
REC_TSH = 21
PAYLOAD_WORDS = C.PAYLOAD_SIZE // 4  # 234 @1KB records, 490 @2KB
REC_PAYLOAD = slice(22, 22 + PAYLOAD_WORDS)
REC_WORDS = 22 + PAYLOAD_WORDS  # 256 @1KB (exactly the 1024B record)
KEY_WORDS = 8
ID_WORDS = 4
ENTRY_WORDS = 6  # blk | msg-id word 1 | seq lo | seq hi | ts lo | ts hi
ENT_BLK = 0
ENT_IDW = 1
ENT_SEQ = 2  # u64 low lane
ENT_SEQH = 3
ENT_TS = 4  # u64 low lane
ENT_TSH = 5


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Static engine geometry derived from a GrapevineConfig."""

    max_messages: int
    max_recipients: int
    mailbox_cap: int
    expiry_period: int
    batch_size: int
    rec: OramConfig
    mb: OramConfig
    mb_table_buckets: int
    mb_slots: int  # K mailboxes per hash bucket
    mb_choices: int = 1  # hash choices per recipient (2 = power-of-two)
    #: slot-order machinery (engine/vphases.py): "dense" [B,B] masks or
    #: "scan" sort + segmented scans — bit-identical semantics
    vphases_impl: str = "dense"
    #: bounded-key sort engine (oblivious/radix.py): "xla" comparison
    #: sorts or "radix" counting passes — bit-identical permutations
    sort_impl: str = "xla"
    #: resolved position-map implementation (oram/posmap.py): "flat" or
    #: "recursive" — the per-tree geometry lives in rec.posmap/mb.posmap
    #: (PosMapSpec), which the checkpoint fingerprint covers via repr
    posmap_impl: str = "flat"
    #: resolved tree-top cache depth (the requested k before per-tree
    #: clamping; each tree's effective depth lives in
    #: rec/mb.top_cache_levels and the inner posmap specs — all covered
    #: by the checkpoint fingerprint via repr, so a cached checkpoint
    #: can never silently restore into a differently-cached engine)
    tree_top_cache_levels: int = 0
    #: resolved delayed-eviction cadence E (config.py ``evict_every``;
    #: 1 = per-round eviction, bit-for-bit pre-PR-15). Per-tree windows
    #: live in rec/mb.evict_window (E and 2E — two mailbox rounds per
    #: engine round) and the inner posmap specs; all covered by the
    #: checkpoint fingerprint via repr, so a buffer-bearing checkpoint
    #: can never silently restore into a differently-cadenced engine
    #: (the buffer planes are state leaves with E-dependent shapes).
    evict_every: int = 1

    @property
    def id_bits(self) -> int:
        """PRP domain bits for msg-id word 0-1 (the block index space)."""
        return max(1, self.max_messages.bit_length() - 1)

    @classmethod
    def from_config(cls, cfg: GrapevineConfig) -> "EngineConfig":
        m = cfg.mailbox_table_buckets
        k = max(1, cfg.mailbox_slots)
        mb_value_words = k * (KEY_WORDS + ENTRY_WORDS * cfg.mailbox_cap)
        vimpl = cfg.vphases_impl
        simpl = cfg.sort_impl
        if vimpl is None or simpl is None:
            # per-backend defaults: the MXU eats the [B,B] masks and
            # lowers lax.sort to a parallel bitonic network; scalar
            # backends pay O(B²) masks and *serial* comparison sorts
            # directly (config.py knob docstrings). Resolved here —
            # engine construction time — because config objects must
            # stay importable without initializing a JAX backend.
            from ..config import TPU_BACKENDS

            on_tpu = jax.default_backend() in TPU_BACKENDS
            if vimpl is None:
                vimpl = "dense" if on_tpu else "scan"
            if simpl is None:
                # "xla" on EVERY backend until measured otherwise: on
                # XLA:CPU the native serial sort (~0.4 µs/elem) beats
                # any scatter-per-pass radix formulation (~80 ns/elem
                # PER scatter, one per pass — bench.py `sort_ab`,
                # PERF.md Round 7); on TPU — where scatters vectorize
                # and the bitonic lax.sort is the O(n log² n) side —
                # the decision belongs to tools/tpu_capture.py's
                # `sort_perf` A/B on a real chip (the vphases_impl
                # playbook).
                simpl = "xla"
        # position-map impl: auto resolves to "flat" on every backend —
        # the recursive map trades ~2× HBM path traffic per round for a
        # ~sqrt(blocks)× smaller resident footprint, a win only once
        # capacity outgrows private memory (flip per OPERATIONS.md §13
        # or after tools/tpu_capture.py posmap_perf prices it on-chip)
        pimpl = cfg.posmap_impl if cfg.posmap_impl is not None else "flat"
        # tree-top cache: auto = 4 on every backend under the phase
        # engine (0 under commit="op" — the differential oracle stays
        # cache-free). Unlike the radix/recursive knobs, caching never
        # trades one algorithm for another: it strictly removes HBM
        # gather/scatter rows and cipher work from every access, and the
        # CPU A/B confirms the win off-TPU (bench.py tree_cache_ab,
        # PERF.md Round 10); per-k sizing and flip guidance in
        # OPERATIONS.md §14. Clamped per tree so at least the leaf
        # level stays in HBM.
        tc = cfg.tree_top_cache_levels
        if tc is None:
            tc = 4 if cfg.commit == "phase" else 0
        rec_tc = min(tc, cfg.records_height)
        mb_tc = min(tc, cfg.mailbox_height)
        # delayed batched eviction (config.py evict_every): auto = 1 on
        # every backend until tools/tpu_capture.py's evict_perf stage
        # prices the flush-overlap win on a real chip (the
        # vphases/sort/posmap/tree-cache flip-on-evidence playbook).
        # Per-tree fetch-round windows: the records tree runs one round
        # per engine round (window = E, F = B), the mailbox tree two
        # (rounds A and C: window = 2E, F = B·D).
        ee = cfg.evict_every if cfg.evict_every is not None else 1
        d_choices = cfg.resolved_mailbox_choices
        rec_w = ee
        mb_w = 2 * ee
        rec_f = cfg.batch_size if ee > 1 else 0
        mb_f = cfg.batch_size * d_choices if ee > 1 else 0
        rec_c = mb_c = 0
        if ee > 1:
            from ..oram.path_oram import derive_evict_buffer_slots

            if cfg.evict_buffer_slots is not None:
                rec_c = mb_c = cfg.evict_buffer_slots
            else:
                rec_c = derive_evict_buffer_slots(
                    cfg.max_messages, rec_w, rec_f, cfg.bucket_slots
                )
                mb_c = derive_evict_buffer_slots(
                    m, mb_w, mb_f, cfg.bucket_slots
                )
        rec_pm = mb_pm = None
        if pimpl == "recursive":
            from ..oram.posmap import derive_posmap_spec

            rec_pm = derive_posmap_spec(
                cfg.max_messages,
                stash_size=cfg.stash_size,
                cipher_rounds=cfg.bucket_cipher_rounds,
                top_cache_levels=tc,
                evict_window=rec_w if ee > 1 else 1,
                evict_fetch_count=rec_f,
            )
            mb_pm = derive_posmap_spec(
                m,
                stash_size=cfg.stash_size,
                cipher_rounds=cfg.bucket_cipher_rounds,
                top_cache_levels=tc,
                evict_window=mb_w if ee > 1 else 1,
                evict_fetch_count=mb_f,
            )
        return cls(
            max_messages=cfg.max_messages,
            max_recipients=cfg.max_recipients,
            mailbox_cap=cfg.mailbox_cap,
            expiry_period=cfg.expiry_period,
            batch_size=cfg.batch_size,
            rec=OramConfig(
                height=cfg.records_height,
                value_words=REC_WORDS,
                bucket_slots=cfg.bucket_slots,
                stash_size=cfg.stash_size,
                cipher_rounds=cfg.bucket_cipher_rounds,
                cipher_impl=cfg.bucket_cipher_impl,
                n_blocks=cfg.max_messages,
                posmap=rec_pm,
                top_cache_levels=rec_tc,
                evict_window=rec_w if ee > 1 else 1,
                evict_fetch_count=rec_f,
                evict_buffer_slots=rec_c,
            ),
            mb=OramConfig(
                height=cfg.mailbox_height,
                value_words=mb_value_words,
                bucket_slots=cfg.bucket_slots,
                stash_size=cfg.stash_size,
                cipher_rounds=cfg.bucket_cipher_rounds,
                cipher_impl=cfg.bucket_cipher_impl,
                n_blocks=m,
                posmap=mb_pm,
                top_cache_levels=mb_tc,
                evict_window=mb_w if ee > 1 else 1,
                evict_fetch_count=mb_f,
                evict_buffer_slots=mb_c,
            ),
            mb_table_buckets=m,
            mb_slots=k,
            mb_choices=d_choices,
            vphases_impl=vimpl,
            sort_impl=simpl,
            posmap_impl=pimpl,
            tree_top_cache_levels=tc,
            evict_every=ee,
        )


class EngineState(NamedTuple):
    rec: OramState
    mb: OramState
    freelist: jax.Array  # u32[max_messages]; [0:free_top] = free block indices
    free_top: jax.Array  # u32 scalar
    recipients: jax.Array  # u32 scalar: live recipients
    seq: jax.Array  # u32[2] (lo, hi): u64 global insertion counter
    hash_key: jax.Array  # u32[2]: keyed mailbox-bucket PRF
    id_key: jax.Array  # u32[4]: block-index PRP key (oblivious/prp.py)
    rng: jax.Array  # jax PRNG key


def init_engine(ecfg: EngineConfig, seed: int = 0) -> EngineState:
    key = jax.random.PRNGKey(seed)
    k_rec, k_mb, k_hash, k_id, k_rng = jax.random.split(key, 5)
    return EngineState(
        rec=init_oram(ecfg.rec, k_rec),
        mb=init_oram(ecfg.mb, k_mb),
        freelist=jnp.arange(ecfg.max_messages, dtype=U32),
        free_top=jnp.uint32(ecfg.max_messages),
        recipients=jnp.uint32(0),
        seq=jnp.array([1, 0], U32),
        hash_key=jax.random.bits(k_hash, (2,), U32),
        id_key=jax.random.bits(k_id, (4,), U32),
        rng=k_rng,
    )


def state_spec(ecfg: EngineConfig):
    """Flattened leaf template of an EngineState for this geometry.

    Returns ``(treedef, leaves)`` where ``leaves`` are ShapeDtypeStructs
    in deterministic pytree order — the serialization contract
    engine/checkpoint.py seals against. Computed with ``eval_shape`` so
    no device arrays are materialized."""
    tmpl = jax.eval_shape(lambda: init_engine(ecfg, 0))
    leaves, treedef = jax.tree_util.tree_flatten(tmpl)
    return treedef, leaves


def mb_parse(ecfg: EngineConfig, value: jax.Array):
    """Split a mailbox block value into (keys [K,8], entries [K,cap,4])."""
    k, cap = ecfg.mb_slots, ecfg.mailbox_cap
    v = value.reshape(k, KEY_WORDS + ENTRY_WORDS * cap)
    keys = v[:, :KEY_WORDS]
    entries = v[:, KEY_WORDS:].reshape(k, cap, ENTRY_WORDS)
    return keys, entries


def mb_pack(ecfg: EngineConfig, keys: jax.Array, entries: jax.Array) -> jax.Array:
    k, cap = ecfg.mb_slots, ecfg.mailbox_cap
    flat = jnp.concatenate(
        [keys, entries.reshape(k, cap * ENTRY_WORDS)], axis=1
    )
    return flat.reshape(k * (KEY_WORDS + ENTRY_WORDS * cap))


def mb_bucket_hash(
    hash_key: jax.Array, recipient: jax.Array, n_buckets: int, salt: int = 0
):
    """Keyed PRF: recipient (8 words) → bucket index in [0, n_buckets).

    A small ARX/multiply mixer (murmur-style finalizer per word). Secret
    ``hash_key`` keeps bucket choices unpredictable to clients, thwarting
    targeted hash-flooding of one bucket (the analog of the reference's
    enclave-private hashing). ``salt`` domain-separates the two
    independent hash functions of the two-choice table (h_c = salt c).
    """
    h = hash_key[0] ^ jnp.uint32(salt * 0x9E3779B9)
    c1, c2 = jnp.uint32(0xCC9E2D51), jnp.uint32(0x1B873593)
    for w in range(KEY_WORDS):
        x = recipient[..., w] * c1
        x = (x << 15) | (x >> 17)
        x = x * c2
        h = h ^ x
        h = (h << 13) | (h >> 19)
        h = h * jnp.uint32(5) + jnp.uint32(0xE6546B64)
    h = h ^ hash_key[1]
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h & jnp.uint32(n_buckets - 1)
