"""Vectorized phase semantics: the whole batch resolved in parallel.

The engine's three phases (mailbox, records, mailbox — engine/step.py
documents the semantics per phase) were originally applied op-by-op under
``lax.scan``. On TPU a scan body costs ~30-130µs *per iteration* (each
tiny op in the body pays fixed sequencer overhead), which made the scans
>99% of round latency. This module computes identical slot-order
semantics with **no per-op loop at all**, via one of two selectable
implementations (``ecfg.vphases_impl``):

- ``"dense"``: same-key chains (ops on one record / one mailbox in one
  round) become [B,B] masked matrices — "did any earlier op of my group
  do X" — with OR-aggregates as one-hot bool-matmuls on the MXU. O(B²)
  compute and intermediate memory, but every op is a wide
  matrix/reduction the MXU/VPU eat for free at moderate B.
- ``"scan"``: the same aggregations in O(B log B) with **no [B,B]
  intermediate at all** — sort ops by (group key, slot), answer
  count/any-of-earlier-flagged and OR/sum-over-group queries as
  segmented scans over the sorted order (oblivious/segmented.py), then
  invert the permutation back to slot order. This is the
  bandwidth-shaped form accelerator oblivious-map work (BOLT, Palermo —
  PAPERS.md) gets its throughput from, and the form that scales past
  B=2048 where the [B,B] masks start to own the round.

Both implementations are bit-identical in responses AND final engine
state (tests/test_vphases_scan.py holds them equal against each other
and the CPU oracle); the per-backend default lives in
``EngineConfig.from_config`` (engine/state.py).

Common machinery either way:

- the mailbox occupancy walk (CREATE = min(count+1, cap), zero-id DELETE
  pop = max(count-1, 0)) is a *saturating-counter* walk, computed exactly
  with a segmented associative scan in O(log B) depth
  (oblivious/segmented.py) — both impls share it;
- entry selection ("pop the oldest") becomes a per-mailbox sort by seq +
  a rank gather;
- final block values are rebuilt once per touched bucket with shifts and
  conflict-free scatters.

Admission quotas (bus capacity, recipient-table capacity) couple ops
*across* groups. When headroom covers the whole batch — the steady
state — admission decouples and everything above is exact. When the bus
or recipient table is within B of saturation, a fallback ``lax.scan``
over [B] resolves just the admission bits sequentially (tiny body —
counters only, no values; identical under both impls). The branch
predicate reveals only "bus or recipient table nearly full", an
aggregate the reference's own error responses already expose to clients
(and Create is permitted to be distinguishable, reference
grapevine.proto:120-122); per-op secrets never influence the branch.

Obliviousness note for the scan impl: it gathers at sort permutations
and segment-boundary indices, which are functions of the batch's
same-key structure — exactly the standing the existing admission walk's
``group_sort`` already has (and the working-set row maps in
oram/round.py): these are private-working-memory accesses, the EPC
analog, not the HBM bucket-tree transcript obliviousness is claimed
for. Dedup inside oram_round keeps same-key ops uncorrelated in the
public transcript under either impl.

Semantics notes vs the original chain engine (mirrored by the oracle):

- **Sticky mailbox slots**: a recipient's hash-table slot persists when
  its mailbox drains to empty; only the expiry sweep reclaims slots and
  decrements the recipient count. (Freeing mid-round would couple every
  recipient's walk to every other's through bucket-slot occupancy; the
  reference never specifies reclamation timing.)
- **Seq numbering by slot**: a created entry's order stamp is
  ``seq0 + slot`` and ``seq`` advances by B per round, preserving
  slot-order semantics with gaps. The counter is u64 (two u32 lanes) —
  no realistic wraparound.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..oblivious.primitives import (
    is_zero_words,
    lex_argsort,
    rank_of,
    u64_add_u32,
    words_equal,
)
from ..oblivious.prp import prp2_encrypt
from ..oblivious.segmented import (
    group_sort,
    multiword_group_sort,
    sat_apply,
    segment_bounds,
    segmented_exclusive_sat_scan,
    segmented_scan,
    segmented_sum_before,
    segmented_sum_total,
)
from ..wire import constants as C
from .state import (
    ENT_BLK,
    ENT_IDW,
    ENT_SEQ,
    ENT_SEQH,
    ENT_TS,
    ENT_TSH,
    ENTRY_WORDS,
    EngineConfig,
    KEY_WORDS,
    REC_ID,
    REC_PAYLOAD,
    REC_RECIPIENT,
    REC_SENDER,
    REC_TS,
    REC_TSH,
)

U32 = jnp.uint32
I32 = jnp.int32


def _tril(b: int, strict: bool = True):
    return jnp.tril(jnp.ones((b, b), jnp.bool_), k=-1 if strict else 0)


def _counts_before(same: jax.Array, flags: jax.Array) -> jax.Array:
    """#flagged earlier ops of my group, per op: i32[B]."""
    b = same.shape[0]
    return jnp.sum(same & _tril(b) & flags[None, :], axis=1).astype(I32)


def _any_before(same: jax.Array, flags: jax.Array) -> jax.Array:
    b = same.shape[0]
    return jnp.any(same & _tril(b) & flags[None, :], axis=1)


def _bool_matmul(m: jax.Array, u: jax.Array) -> jax.Array:
    """OR-aggregate u's rows over m's True columns: bool[B,B] x bool[B,N]
    → bool[B,N], computed on the MXU (sums < 2^24 are exact in f32)."""
    return (
        jnp.matmul(
            m.astype(jnp.float32),
            u.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        > 0.5
    )


# ----------------------------------------------------------------------
# group aggregation engine: one semantics, two implementations
# ----------------------------------------------------------------------
#
# Every within-round chain question the three phases ask is one of a
# small set of group aggregations ("my group" = ops sharing a recipient
# key / effective bucket / record block; dummies are singleton groups):
#
#   counts_before(f)       #flagged strictly-earlier ops of my group
#   any_before(f)          counts_before > 0
#   total_sum(f)/total_or  sum / OR over my whole group (self included)
#   *_rows(u)              the same, aggregating bool[B,N] row vectors
#   group_first/group_last smallest / largest slot index in my group
#   first_flag_index(f)    slot of my group's first flagged op (+ found)
#   last_flag_index[_upto] slot of my group's last flagged op
#                          (optionally restricted to at-or-before me)
#   select_by_rank(f,v,q)  v-row of my group's q-th flagged op (0 if none)
#
# _DenseGroups answers them with [B,B] masks and one-hot matmuls;
# _SortedGroups answers them in O(B log B) with one multi-word sort and
# segmented scans. The two are bit-identical on every method for the
# flag patterns the phases produce (dummy ops never raise flags — all
# flags are masked by is_real), which the A/B test suite enforces
# end-to-end.


class _DenseGroups:
    """[B,B]-mask implementation (``vphases_impl="dense"``)."""

    def __init__(self, same: jax.Array):
        b = same.shape[0]
        self.b = b
        # real ops already include themselves in `same`; adding the
        # diagonal only turns dummy rows into singleton groups, which
        # matches the sorted impl and never changes a flagged result
        # (dummies raise no flags)
        self.m = same | jnp.eye(b, dtype=jnp.bool_)
        self._same = same

    def counts_before(self, flags):
        return _counts_before(self._same, flags)

    def any_before(self, flags):
        return _any_before(self._same, flags)

    def total_sum(self, flags):
        return jnp.sum(self.m & flags[None, :], axis=1).astype(I32)

    def total_or(self, flags):
        return jnp.any(self.m & flags[None, :], axis=1)

    def total_sum_rows(self, u):
        return jnp.matmul(
            self.m.astype(jnp.float32),
            u.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        ).astype(I32)

    def total_or_rows(self, u):
        return _bool_matmul(self.m, u)

    def group_first(self):
        return jnp.argmax(self.m, axis=1).astype(U32)

    def group_last(self):
        iota = jnp.arange(self.b, dtype=U32)
        return jnp.max(jnp.where(self.m, iota[None, :], 0), axis=1)

    def first_flag_index(self, flags):
        oh = self.m & flags[None, :]
        return jnp.argmax(oh, axis=1).astype(I32), jnp.any(oh, axis=1)

    def last_flag_index_upto(self, flags):
        iota = jnp.arange(self.b, dtype=I32)
        wm = self.m & flags[None, :] & _tril(self.b, strict=False)
        return jnp.max(jnp.where(wm, iota[None, :], -1), axis=1)

    def last_flag_index(self, flags):
        iota = jnp.arange(self.b, dtype=I32)
        wm = self.m & flags[None, :]
        return jnp.max(jnp.where(wm, iota[None, :], -1), axis=1)

    def select_by_rank(self, flags, vals, q):
        rank = self.counts_before(flags)
        oh = self.m & flags[None, :] & (rank[None, :] == q[:, None])
        return jnp.sum(oh[:, :, None] * vals[None, :, :], axis=1).astype(
            vals.dtype
        )


class _SortedGroups:
    """Sort + segmented-scan implementation (``vphases_impl="scan"``).

    One O(B log B) variadic sort orders ops by (group key, slot); every
    aggregation is then a cumsum / segmented scan over the sorted order
    plus a permutation inverse — no [B,B] intermediate anywhere.

    ``sort_impl="radix"`` with a declared per-column ``key_bits`` bound
    swaps the comparison sort for bounded-key counting passes
    (oblivious/radix.py) — bit-identical (perm, inv, seg). Callers that
    cannot bound their key (the 256-bit recipient pubkey) pass
    ``key_bits=None`` and keep ``lax.sort``; radix itself refuses keys
    wider than ``MAX_RADIX_BITS`` so correctness can never silently
    ride on a hashed-down key.
    """

    def __init__(self, cols, key_bits=None, sort_impl: str = "xla"):
        if sort_impl == "radix" and key_bits is not None:
            from ..oblivious.radix import radix_group_sort

            self.perm, self.inv, self.seg = radix_group_sort(cols, key_bits)
        else:
            self.perm, self.inv, self.seg = multiword_group_sort(cols)
        b = self.perm.shape[0]
        self.b = b
        self.start, self.end = segment_bounds(self.seg)
        self._pi = self.perm.astype(I32)

    def _to(self, x):
        return x[self.perm]

    def _back(self, x):
        return x[self.inv]

    def _counts_before_sorted(self, f):
        return segmented_sum_before(f, self.seg, (self.start, self.end))

    def _total_sorted(self, x):
        return segmented_sum_total(x, self.seg, (self.start, self.end))

    def counts_before(self, flags):
        return self._back(self._counts_before_sorted(self._to(flags)))

    def any_before(self, flags):
        return self.counts_before(flags) > 0

    def total_sum(self, flags):
        return self._back(self._total_sorted(self._to(flags)))

    def total_or(self, flags):
        return self.total_sum(flags) > 0

    def total_sum_rows(self, u):
        return self._back(self._total_sorted(self._to(u)))

    def total_or_rows(self, u):
        return self.total_sum_rows(u) > 0

    def group_first(self):
        return self._back(self.perm[self.start])

    def group_last(self):
        return self._back(self.perm[self.end])

    def first_flag_index(self, flags):
        v = jnp.where(self._to(flags), self._pi, I32(self.b))
        m = segmented_scan(v, self.seg, jnp.minimum)[self.end]
        has = m < self.b
        return self._back(jnp.clip(m, 0, self.b - 1)), self._back(has)

    def last_flag_index_upto(self, flags):
        v = jnp.where(self._to(flags), self._pi, -1)
        # within a segment ops sit in slot order, so position-≤-mine is
        # exactly slot-≤-mine: the inclusive segmented max IS "last
        # flagged at or before me"
        return self._back(segmented_scan(v, self.seg, jnp.maximum))

    def last_flag_index(self, flags):
        v = jnp.where(self._to(flags), self._pi, -1)
        return self._back(segmented_scan(v, self.seg, jnp.maximum)[self.end])

    def select_by_rank(self, flags, vals, q):
        f = self._to(flags)
        rank = self._counts_before_sorted(f)
        # each flagged op owns sorted slot (segment start + its rank):
        # in-segment, collision-free — scatter values, gather at q
        tgt = jnp.where(f, self.start + rank, I32(self.b))
        table = (
            jnp.zeros((self.b,) + vals.shape[1:], vals.dtype)
            .at[tgt]
            .set(self._to(vals), mode="drop", unique_indices=True)
        )
        q_s = self._to(q)
        nfl = self._total_sorted(f)
        pos = jnp.clip(self.start + q_s, 0, self.b - 1)
        ok = (q_s >= 0) & (q_s < nfl)
        return self._back(jnp.where(ok[:, None], table[pos], 0))


def _recipient_groups(ecfg: EngineConfig, ka: jax.Array, is_real: jax.Array):
    """Groups over the recipient key ka (dummies singleton)."""
    b = ka.shape[0]
    if ecfg.vphases_impl == "dense":
        requal = (
            words_equal(ka[:, None, :], ka[None, :, :])
            & is_real[:, None]
            & is_real[None, :]
        )
        return _DenseGroups(requal)
    iota = jnp.arange(b, dtype=U32)
    # key = (real?, ka words, dummy-uniquifier): real ops group by ka,
    # each dummy is its own group. 1 + 8·32 + 32 declared bits — far
    # past MAX_RADIX_BITS, so this sort stays on lax.sort under every
    # sort_impl (radix would demand a hashed-down key, and grouping
    # correctness must never depend on a hash).
    cols = (
        [(~is_real).astype(U32)]
        + [ka[:, w] for w in range(KEY_WORDS)]
        + [jnp.where(is_real, U32(0), iota)]
    )
    return _SortedGroups(cols)


def _index_groups(ecfg: EngineConfig, idx: jax.Array, is_real: jax.Array,
                  dummy_base: int):
    """Groups over a single u32 index column (bucket / record block).

    ``dummy_base``: sorted-impl uniquifier base for dummy ops — any
    value with ``dummy_base + iota`` disjoint from real index values.
    """
    b = idx.shape[0]
    if ecfg.vphases_impl == "dense":
        eq = (
            (idx[:, None] == idx[None, :])
            & is_real[:, None]
            & is_real[None, :]
        )
        return _DenseGroups(eq)
    iota = jnp.arange(b, dtype=U32)
    # bounded key: real < dummy_base, dummies dummy_base..dummy_base+B-1
    return _SortedGroups(
        [jnp.where(is_real, idx, U32(dummy_base) + iota)],
        key_bits=max(1, (dummy_base + b - 1).bit_length()),
        sort_impl=ecfg.sort_impl,
    )


def _mb_parse_batch(ecfg: EngineConfig, vals: jax.Array):
    """[B, Vmb] → keys [B,K,8], entries [B,K,cap,ENTRY_WORDS]."""
    b = vals.shape[0]
    k, cap, ew = ecfg.mb_slots, ecfg.mailbox_cap, ENTRY_WORDS
    kw = KEY_WORDS
    v = vals.reshape(b, k, kw + ew * cap)
    return v[:, :, :kw], v[:, :, kw:].reshape(b, k, cap, ew)


def _mb_pack_batch(ecfg: EngineConfig, keys: jax.Array, entries: jax.Array):
    b = keys.shape[0]
    k, cap, ew = ecfg.mb_slots, ecfg.mailbox_cap, ENTRY_WORDS
    flat = jnp.concatenate([keys, entries.reshape(b, k, cap * ew)], axis=2)
    return flat.reshape(b, k * (KEY_WORDS + ew * cap))


# ----------------------------------------------------------------------
# admission: who gets to create / claim / pop, exactly, in slot order
# ----------------------------------------------------------------------


def _admission_fast(
    ecfg,
    *,
    is_create_cand,
    is_pop_cand,
    found0,
    first_create,
    free_slots0,
    init_count,
    groups_r,
    groups_g,
    rslot,
):
    """Quota-decoupled admission (bus + recipient headroom ≥ B)."""
    b = rslot.shape[0]
    cap = ecfg.mailbox_cap

    claim_cand = first_create & ~found0
    claim_rank = groups_g.counts_before(claim_cand)
    claim_ok = claim_cand & (claim_rank < free_slots0)
    # my recipient's claim, if any (claims live at the first-create op)
    claimed_r = groups_r.total_or(claim_ok)
    active = found0 | claimed_r

    # saturating occupancy walk per recipient, segmented by first-occ slot
    create_elem = is_create_cand & active
    pop_elem = is_pop_cand & active
    add = jnp.where(create_elem, 1, jnp.where(pop_elem, -1, 0)).astype(I32)
    lo = jnp.zeros((b,), I32)
    hi = jnp.full((b,), cap, I32)
    # rslot is a slot index (< B) — bounded, so the walk's grouping sort
    # follows the sort_impl knob under BOTH vphases impls
    perm, inv, seg = group_sort(
        rslot, sort_impl=ecfg.sort_impl,
        key_bits=max(1, (b - 1).bit_length()),
    )
    pre = segmented_exclusive_sat_scan((add[perm], lo[perm], hi[perm]), seg)
    count_before = sat_apply(pre, init_count[perm])[inv]

    create_ok = create_elem & (count_before < cap)
    pop_ok = pop_elem & (count_before > 0)
    can_alloc = jnp.ones((b,), jnp.bool_)
    return dict(
        create_ok=create_ok,
        pop_ok=pop_ok,
        claim_ok=claim_ok,
        count_before=count_before,
        can_alloc=can_alloc,
        active=active,
    )


def _admission_slow(
    ecfg,
    *,
    is_create_cand,
    is_pop_cand,
    found0,
    first_create,
    free_slots0,
    init_count,
    rslot,
    gslot,
    free_top0,
    recipients0,
):
    """Exact sequential admission for the near-saturation regime.

    A tiny scan over counters only — no block values — so its per-op cost
    is bounded by a dozen scalar/[B]-element ops. Runs only when the bus
    or recipient table is within B of full (see module docstring for the
    leak analysis of the branch). Shared verbatim by both vphases
    implementations."""
    b = rslot.shape[0]
    cap = ecfg.mailbox_cap
    iota = jnp.arange(b, dtype=U32)
    first_r = rslot == iota  # first op of each recipient group
    first_g = gslot == iota
    counts0 = jnp.where(first_r, init_count, 0)
    frees0 = jnp.where(first_g, free_slots0, 0)

    def step(carry, xs):
        n_alloc, recips, counts, frees, claimed = carry
        j, crt, pop, fnd, fc, r, g = xs
        cnt = counts[r]
        fs = frees[g]
        can_alloc = n_alloc < free_top0
        room = recips < U32(ecfg.max_recipients)
        claim = fc & ~fnd
        claim_ok = claim & (fs > 0) & room & can_alloc
        active = fnd | claimed[r] | claim_ok
        create_ok = crt & can_alloc & active & (cnt < cap)
        pop_ok = pop & active & (cnt > 0)
        counts = counts.at[r].set(cnt + create_ok.astype(I32) - pop_ok.astype(I32))
        frees = frees.at[g].set(fs - claim_ok.astype(I32))
        claimed = claimed.at[r].set(claimed[r] | claim_ok)
        n_alloc = n_alloc + create_ok.astype(U32)
        recips = recips + claim_ok.astype(U32)
        out = (create_ok, pop_ok, claim_ok, cnt, can_alloc, active)
        return (n_alloc, recips, counts, frees, claimed), out

    (_, _, _, _, _), outs = jax.lax.scan(
        step,
        (
            jnp.zeros((), U32),
            jnp.asarray(recipients0, U32),
            counts0.astype(I32),
            frees0.astype(I32),
            jnp.zeros((b,), jnp.bool_),
        ),
        (iota, is_create_cand, is_pop_cand, found0, first_create, rslot, gslot),
    )
    create_ok, pop_ok, claim_ok, count_before, can_alloc, active = outs
    return dict(
        create_ok=create_ok,
        pop_ok=pop_ok,
        claim_ok=claim_ok,
        count_before=count_before,
        can_alloc=can_alloc,
        active=active,
    )


# ----------------------------------------------------------------------
# phase A: mailbox round (capacity, append, zero-id select/pop)
# ----------------------------------------------------------------------


def phase_a_batch(ecfg: EngineConfig, ctx: dict):
    """Build the round-A ``apply_batch`` callback.

    ``ctx``: is_real/is_create/is_read/is_update/is_delete bool[B],
    id_zero, zero_recip bool[B]; ka u32[B,8]; idxs_mb2 u32[B,D] (the
    D=ecfg.mb_choices candidate table buckets per op; the round fetches
    all of them, flattened row-major); cand_idx u32[B]; id_rand u32[B,3];
    free_top0, recipients0, seq0 u32; now u32. The callback receives
    [B*D] rows and returns (out_a, final_val [B*D,V], final_alive [B*D]).

    Two-choice (D=2) semantics: an op's *effective* bucket is the
    candidate containing its recipient key, else — for a fresh claim —
    the candidate with more free key slots **at round start** (ties →
    candidate 0). The choice is resolved with masks over both fetched
    candidates, and both candidate rows are always written back, so the
    transcript never shows which candidate holds a recipient. Choosing
    by round-start occupancy (not claim-by-claim) keeps the admission
    walk vectorized; a claim can still fail if earlier in-round claims
    fill its chosen bucket — same order-sensitivity class as the
    existing claim_rank < free_slots0 rule, invisible to the oracle
    (placement never surfaces in responses)."""

    b = ctx["ka"].shape[0]
    d = ecfg.mb_choices
    k, cap = ecfg.mb_slots, ecfg.mailbox_cap
    is_real = ctx["is_real"]
    is_create_cand = ctx["is_create"] & is_real & ~ctx["zero_recip"]
    is_pop_cand = ctx["is_delete"] & ctx["id_zero"] & is_real
    is_zsel = (ctx["is_read"] | ctx["is_delete"]) & ctx["id_zero"] & is_real
    ka = ctx["ka"]
    idxs_mb2 = ctx["idxs_mb2"]  # u32[B,D]
    now = ctx["now"]
    m_sentinel = U32(ecfg.mb_table_buckets)
    iota = jnp.arange(b, dtype=U32)

    # recipient groups (ka equality); bucket groups move inside the
    # callback — the effective bucket depends on fetched occupancy
    groups_r = _recipient_groups(ecfg, ka, is_real)
    rslot = groups_r.group_first()

    def apply_batch(vals0, present0):
        # --- candidate choice: [B*D] rows → per-op chosen views -------
        keys_c, entries_c = _mb_parse_batch(ecfg, vals0)  # [B*D,K,..]
        keys_c = keys_c.reshape(b, d, k, 8)
        entries_c = entries_c.reshape(b, d, k, cap, ENTRY_WORDS)
        key_valid_c = ~is_zero_words(keys_c)  # [B,D,K]
        match_c = key_valid_c & words_equal(keys_c, ka[:, None, None, :])
        found_c = jnp.any(match_c, axis=2)  # [B,D]
        free_c = (k - jnp.sum(key_valid_c, axis=2)).astype(I32)  # [B,D]
        if d == 1:
            chosen = jnp.zeros((b,), I32)
        else:
            emptier = jnp.argmax(free_c, axis=1).astype(I32)  # ties → 0
            chosen = jnp.where(
                jnp.any(found_c, axis=1),
                jnp.argmax(found_c, axis=1).astype(I32),
                emptier,
            )
        ch = chosen[:, None, None, None]
        keys0 = jnp.take_along_axis(keys_c, ch.astype(I32), axis=1)[:, 0]
        entries0 = jnp.take_along_axis(
            entries_c, ch[..., None].astype(I32), axis=1
        )[:, 0]
        eff_idx = jnp.take_along_axis(idxs_mb2, chosen[:, None], axis=1)[:, 0]
        eff_idx = jnp.where(is_real, eff_idx, m_sentinel + U32(1) + iota)

        # bucket groups over the effective bucket (dummies unique)
        groups_g = _index_groups(
            ecfg, eff_idx, is_real, ecfg.mb_table_buckets + 1
        )
        gslot = groups_g.group_first()
        glast = groups_g.group_last()

        key_valid0 = ~is_zero_words(keys0)  # [B,K]
        slot_match0 = key_valid0 & words_equal(keys0, ka[:, None, :])  # [B,K]
        found0 = jnp.any(slot_match0, axis=1) & is_real
        free_slots0 = (k - jnp.sum(key_valid0, axis=1)).astype(I32)
        # my recipient's entries (zeros when mailbox absent)
        ent_r = jnp.sum(
            entries0 * slot_match0[:, :, None, None].astype(U32), axis=1
        )  # [B,cap,ENTRY_WORDS]
        ent_valid = (ent_r[:, :, ENT_SEQ] | ent_r[:, :, ENT_SEQH]) != 0
        init_count = jnp.sum(ent_valid, axis=1).astype(I32)

        first_create = is_create_cand & ~groups_r.any_before(is_create_cand)

        common = dict(
            is_create_cand=is_create_cand,
            is_pop_cand=is_pop_cand,
            found0=found0,
            first_create=first_create,
            free_slots0=free_slots0,
            init_count=init_count,
            rslot=rslot,
        )
        fast_ok = (ctx["free_top0"] >= U32(b)) & (
            ctx["recipients0"] + U32(b) <= U32(ecfg.max_recipients)
        )
        adm = jax.lax.cond(
            fast_ok,
            lambda: _admission_fast(
                ecfg, **common, groups_r=groups_r, groups_g=groups_g
            ),
            lambda: _admission_slow(
                ecfg,
                **common,
                gslot=gslot,
                free_top0=ctx["free_top0"],
                recipients0=ctx["recipients0"],
            ),
        )
        create_ok = adm["create_ok"]
        pop_ok = adm["pop_ok"]
        claim_ok = adm["claim_ok"]
        count_before = adm["count_before"]
        can_alloc = adm["can_alloc"]
        active = adm["active"]

        # --- allocation + ids (n-th successful create takes candidate n)
        grank = rank_of(create_ok)
        # clamp to the CANDIDATE array's extent, not this round's lane
        # count: under mailbox_choices=2 the lanes are B·D wide while
        # cand_idx is B wide, so `b - 1` let non-create lanes index past
        # the array (formally UB under PROMISE_IN_BOUNDS; XLA happened
        # to clamp). Create lanes always rank < B — the quota caps
        # successful creates at the batch size (rangelint finding).
        cand_cap = ctx["cand_idx"].shape[0] - 1
        alloc_idx = ctx["cand_idx"][jnp.minimum(grank, cand_cap)]
        # id words 0-1 = PRP-encrypted (nonce, block index): decodable
        # on-device, fresh random-looking values on every create even
        # when the LIFO freelist reuses a block (oblivious/prp.py; the
        # reference's random-id requirement, grapevine.proto:66-79).
        # Word 3 is forced odd so a real id is never all-zeroes.
        idr = ctx["id_rand"]
        w0, w1 = prp2_encrypt(
            ctx["id_key"], alloc_idx, idr[:, 0], ecfg.id_bits
        )
        new_id = jnp.stack([w0, w1, idr[:, 1], idr[:, 2] | U32(1)], axis=1)

        # --- zero-id selection: p-th oldest of [initial sorted ++ creates]
        pops_before = groups_r.counts_before(pop_ok)
        crank = groups_r.counts_before(create_ok)
        inf = U32(0xFFFFFFFF)
        sk_lo = jnp.where(ent_valid, ent_r[:, :, ENT_SEQ], inf)
        sk_hi = jnp.where(ent_valid, ent_r[:, :, ENT_SEQH], inf)
        order = lex_argsort(sk_lo, sk_hi, axis=1)
        sorted_ent = jnp.take_along_axis(ent_r, order[:, :, None], axis=1)
        p = pops_before
        sel_from_init = p < init_count
        pi = jnp.clip(p, 0, cap - 1)
        init_sel = jnp.take_along_axis(sorted_ent, pi[:, None, None], axis=1)[
            :, 0, :
        ]  # [B, ENTRY_WORDS]
        q = p - init_count
        created = groups_r.select_by_rank(create_ok, new_id[:, :2], q)
        created_blk = created[:, 0]
        created_idw = created[:, 1]
        sel_blk = jnp.where(sel_from_init, init_sel[:, ENT_BLK], created_blk)
        sel_idw = jnp.where(sel_from_init, init_sel[:, ENT_IDW], created_idw)
        sel_found = is_zsel & active & (count_before > 0)
        rm_a = pop_ok

        # --- status (precedence documented in testing/reference.py) ----
        status_a = jnp.where(
            ctx["zero_recip"],
            U32(C.STATUS_CODE_INVALID_RECIPIENT),
            jnp.where(
                ~can_alloc,
                U32(C.STATUS_CODE_TOO_MANY_MESSAGES),
                jnp.where(
                    ~active,
                    U32(C.STATUS_CODE_TOO_MANY_RECIPIENTS),
                    jnp.where(
                        count_before >= cap,
                        U32(C.STATUS_CODE_TOO_MANY_MESSAGES_FOR_RECIPIENT),
                        U32(C.STATUS_CODE_SUCCESS),
                    ),
                ),
            ),
        )

        # --- final block assembly (committed at each group's last op) --
        # claimed key slot per claim op: the claim_rank-th free slot
        free_rank = jnp.cumsum(~key_valid0, axis=1) - (~key_valid0)  # [B,K]
        claim_rank = groups_g.counts_before(claim_ok)
        claim_slot_oh = (
            (~key_valid0) & (free_rank == claim_rank[:, None]) & claim_ok[:, None]
        )  # [B,K]
        # my recipient's key slot (original or claimed). The claim lives
        # at the group's first-*create* op, which need not be the group's
        # first op (a zero-id R/D by the same recipient may precede it in
        # slot order), so OR-aggregate over the whole group — at most one
        # op per group has claim_ok.
        claim_slot_r = groups_r.total_or_rows(claim_slot_oh)  # [B,K]
        mslot_oh = jnp.where(found0[:, None], slot_match0, claim_slot_r)
        mslot_idx = jnp.argmax(mslot_oh, axis=1).astype(U32)
        has_mslot = jnp.any(mslot_oh, axis=1)

        # keys: scatter claims into their group-representative rows
        ctgt = (
            jnp.where(claim_ok, glast, U32(b)),
            jnp.where(claim_ok, jnp.argmax(claim_slot_oh, axis=1).astype(U32), U32(k)),
        )
        # at most one claim per group (claim_ok), and claims target
        # their group-representative row — in-bounds targets unique
        keys_fin = keys0.at[ctgt].set(ka, mode="drop", unique_indices=True)

        # initial entries: survivors shift down by popped_init per slot
        # T[r,s]: total pops in r's group landing on slot s
        pop_sl = mslot_oh & pop_ok[:, None]  # [B,K]
        T = groups_g.total_sum_rows(pop_sl)  # [B,K] i32
        valid_all = (
            entries0[:, :, :, ENT_SEQ] | entries0[:, :, :, ENT_SEQH]
        ) != 0
        icount_sl = jnp.sum(valid_all, axis=2).astype(I32)
        popped_init_sl = jnp.minimum(T, icount_sl)  # [B,K]
        sk_lo_all = jnp.where(valid_all, entries0[:, :, :, ENT_SEQ], inf)
        sk_hi_all = jnp.where(valid_all, entries0[:, :, :, ENT_SEQH], inf)
        order_all = lex_argsort(sk_lo_all, sk_hi_all, axis=2)
        sorted_all = jnp.take_along_axis(entries0, order_all[:, :, :, None], axis=2)
        e_iota = jnp.arange(cap, dtype=I32)[None, None, :]
        src = e_iota + popped_init_sl[:, :, None]  # [B,K,cap]
        keepm = src < icount_sl[:, :, None]
        ents_fin = jnp.where(
            keepm[:, :, :, None],
            jnp.take_along_axis(
                sorted_all, jnp.clip(src, 0, cap - 1)[:, :, :, None], axis=2
            ),
            U32(0),
        )

        # created entries: survivors append after the surviving initials
        T_r = groups_r.total_sum(pop_ok)  # total pops in my group
        popped_init_r = jnp.minimum(T_r, init_count)
        popped_created_r = T_r - popped_init_r
        surv = create_ok & (crank >= popped_created_r) & has_mslot
        # pos >= 0 on every lane etgt consumes: surv requires
        # crank >= popped_created_r, and popped_init_r = min(T, init) <=
        # init_count always; the max states that invariant for interval
        # reasoning (non-surv lanes carry masked garbage either way)
        pos = jnp.maximum(
            (init_count - popped_init_r) + (crank - popped_created_r), 0
        )
        etgt = (
            jnp.where(surv, glast, U32(b)),
            jnp.where(surv, mslot_idx, U32(k)),
            jnp.where(surv, pos.astype(U32), U32(cap)),
        )
        sq_lo, sq_hi = u64_add_u32(ctx["seq0"][0], ctx["seq0"][1], iota)
        new_entry = jnp.stack(
            [
                new_id[:, 0],
                new_id[:, 1],
                sq_lo,
                sq_hi,
                jnp.full((b,), now, U32),
                jnp.full((b,), ctx["now_hi"], U32),
            ],
            axis=1,
        )
        # distinct (group row, slot, rank) per surviving create — unique
        ents_fin = ents_fin.at[etgt].set(
            new_entry, mode="drop", unique_indices=True
        )

        assembled = _mb_pack_batch(ecfg, keys_fin, ents_fin)  # [B,V]
        assembled_alive = jnp.any(~is_zero_words(keys_fin), axis=1)  # [B]

        # --- row commit: every fetched row of a bucket carries the
        # bucket's final state (oram_round commits whichever row is the
        # bucket's LAST occurrence in the flattened [B*D] order — which
        # may be another op's *unchosen* candidate, so pass-through rows
        # must hold the committed value too). Dense bucket → last-
        # choosing-op map: one scatter-max + one gather.
        op_map = (
            jnp.full((ecfg.mb_table_buckets + 1,), -1, I32)
            .at[jnp.where(is_real, eff_idx, m_sentinel + U32(1))]
            .max(iota.astype(I32), mode="drop")
        )
        rows_idx = idxs_mb2.reshape(b * d)
        g = op_map[jnp.minimum(rows_idx, m_sentinel)]  # [B*D]; -1 = none
        has_g = (g >= 0) & (rows_idx < m_sentinel)
        gc = jnp.clip(g, 0, b - 1)
        final_val = jnp.where(has_g[:, None], assembled[gc], vals0)
        final_alive = jnp.where(has_g, assembled_alive[gc], present0)

        out_a = {
            "create_ok": create_ok,
            "status_a": status_a,
            "sel_blk": sel_blk,
            "sel_idw": sel_idw,
            "sel_found": sel_found,
            "rm_a": rm_a,
            "alloc_idx": alloc_idx,
            "new_id": new_id,
            "n_claims": jnp.sum(claim_ok.astype(U32)),
            "n_allocs": jnp.sum(create_ok.astype(U32)),
        }
        return out_a, final_val, final_alive

    return apply_batch


# ----------------------------------------------------------------------
# phase B: records round (verify, insert, mutate, remove)
# ----------------------------------------------------------------------


def phase_b_batch(ecfg: EngineConfig, ctx: dict):
    """Round-B callback. ``ctx`` adds: idx_b u32[B] (dummy-routed block
    keys), real_b bool[B], create_ok, new_id u32[B,4], sel_blk, sel_idw,
    auth/recipient u32[B,8], msg_id u32[B,4], payload u32[B,234],
    plus the request-type masks and now."""

    b = ctx["idx_b"].shape[0]
    realb = ctx["real_b"]
    # record-block groups; dummies (idx_b = rec.dummy_index, shared)
    # must stay singletons, exactly as the realb-masked dense equality
    groups_k = _index_groups(ecfg, ctx["idx_b"], realb, ecfg.rec.blocks + 1)
    now = ctx["now"]
    create_ev = ctx["is_create"] & ctx["create_ok"] & realb

    def apply_batch(vals0, present0):
        init_id = vals0[:, REC_ID]
        init_sender = vals0[:, REC_SENDER]
        init_recip = vals0[:, REC_RECIPIENT]
        init_ts = vals0[:, REC_TS : REC_TSH + 1]  # u32[B,2] (lo, hi)
        init_payload = vals0[:, REC_PAYLOAD]

        # identity fields are fixed per key: creation (in-round) or initial
        c_idx, has_c = groups_k.first_flag_index(create_ev)
        sid = jnp.where(has_c[:, None], ctx["new_id"][c_idx], init_id)
        ssender = jnp.where(has_c[:, None], ctx["auth"][c_idx], init_sender)
        srecip = jnp.where(has_c[:, None], ctx["recipient"][c_idx], init_recip)

        match4 = words_equal(sid, ctx["msg_id"])
        match2 = (sid[:, 0] == ctx["sel_blk"]) & (sid[:, 1] == ctx["sel_idw"])
        mtc = jnp.where(ctx["id_zero"], match2, match4) & ~ctx["is_create"] & realb
        auth_ok = words_equal(ctx["auth"], ssender) | words_equal(
            ctx["auth"], srecip
        )
        recip_match = words_equal(ctx["recipient"], srecip)

        del_pred = (
            ctx["is_delete"] & mtc & auth_ok & (ctx["id_zero"] | recip_match)
        )
        created_before = groups_k.any_before(create_ev)
        base_alive = (present0 & realb) | created_before
        killed_before = groups_k.any_before(del_pred & base_alive)
        alive = base_alive & ~killed_before

        match_ok = alive & mtc
        read_ok = ctx["is_read"] & match_ok & auth_ok
        upd_ok = ctx["is_update"] & match_ok & auth_ok & recip_match
        del_ok = del_pred & alive

        # last payload/ts writer at-or-before me (me included for my own
        # update/create); reads see the state before themselves
        W = create_ev | upd_ok
        lw = groups_k.last_flag_index_upto(W)
        has_w = lw >= 0
        lwc = jnp.clip(lw, 0, b - 1)
        resp_payload = jnp.where(
            has_w[:, None], ctx["payload"][lwc], init_payload
        )
        now2 = jnp.stack([now, ctx["now_hi"]]).astype(U32)
        resp_ts = jnp.where(has_w[:, None], now2[None, :], init_ts)

        out_b = {
            "read_ok": read_ok,
            "upd_ok": upd_ok,
            "del_ok": del_ok,
            "match_ok": mtc & alive,
            "auth_ok": auth_ok,
            "recip_match": recip_match,
            "resp_id": sid,
            "resp_sender": ssender,
            "resp_recipient": srecip,
            "resp_ts": resp_ts,
            "resp_payload": resp_payload,
        }

        # final per-key state
        any_create = groups_k.total_or(create_ev)
        any_del = groups_k.total_or(del_ok)
        final_alive = ((present0 & realb) | any_create) & ~any_del
        lwf = groups_k.last_flag_index(W)
        has_wf = lwf >= 0
        lwfc = jnp.clip(lwf, 0, b - 1)
        fin_payload = jnp.where(
            has_wf[:, None], ctx["payload"][lwfc], init_payload
        )
        fin_ts = jnp.where(has_wf[:, None], now2[None, :], init_ts)
        final_val = jnp.concatenate(
            [sid, ssender, srecip, fin_ts, fin_payload], axis=1
        )
        return out_b, final_val, final_alive

    return apply_batch


# ----------------------------------------------------------------------
# phase C: mailbox finalization (explicit-delete removal, update refresh)
# ----------------------------------------------------------------------


def phase_c_batch(ecfg: EngineConfig, ctx: dict):
    """Round-C callback. ``ctx`` adds: del_ok, upd_ok, rm_a bool[B] (from
    rounds A/B), msg_id u32[B,4], ka u32[B,8], idxs_mb2 u32[B,D].

    Like round A the callback sees all D candidate rows per op; an op's
    mutations (explicit-delete clear, update timestamp refresh) land in
    whichever candidate holds its recipient key, and are aggregated onto
    EVERY fetched row of that bucket so the round's last-occurrence
    commit (oram_round) writes them regardless of which op's row wins.
    The dense impl aggregates with a [B·D,B] one-hot matmul; the scan
    impl scatter-adds per-bucket mutation vectors into a
    [table_buckets, K·cap] table and gathers per row — the same dense
    bucket-table idiom phase A's op_map already uses."""

    b = ctx["ka"].shape[0]
    d = ecfg.mb_choices
    k, cap = ecfg.mb_slots, ecfg.mailbox_cap
    is_real = ctx["is_real"]
    idxs_mb2 = ctx["idxs_mb2"]
    m_sentinel = U32(ecfg.mb_table_buckets)
    rm_c = ctx["del_ok"] & ~ctx["rm_a"] & is_real
    refresh = ctx["upd_ok"] & is_real
    now = ctx["now"]

    def apply_batch(vals0, present0):
        keys_c, entries_c = _mb_parse_batch(ecfg, vals0)
        keys_c = keys_c.reshape(b, d, k, 8)
        entries_c = entries_c.reshape(b, d, k, cap, ENTRY_WORDS)
        key_valid_c = ~is_zero_words(keys_c)
        match_c = key_valid_c & words_equal(
            keys_c, ctx["ka"][:, None, None, :]
        )  # [B,D,K]
        found_c = jnp.any(match_c, axis=2)  # [B,D]
        chosen = (
            jnp.zeros((b,), I32)
            if d == 1
            else jnp.argmax(found_c, axis=1).astype(I32)
        )
        ch = chosen[:, None, None, None]
        slot_match = jnp.take_along_axis(match_c, ch[:, :, :, 0], axis=1)[:, 0]
        entries0 = jnp.take_along_axis(
            entries_c, ch[..., None].astype(I32), axis=1
        )[:, 0]  # [B,K,cap,4]
        eff_idx = jnp.take_along_axis(idxs_mb2, chosen[:, None], axis=1)[:, 0]
        mutating = (rm_c | refresh) & jnp.any(found_c, axis=1)
        eff_idx = jnp.where(mutating, eff_idx, m_sentinel)

        # my (slot, entry) matches: entry holds my msg_id's (blk, idw)
        ent_valid = (
            entries0[:, :, :, ENT_SEQ] | entries0[:, :, :, ENT_SEQH]
        ) != 0
        em = (
            ent_valid
            & (entries0[:, :, :, ENT_BLK] == ctx["msg_id"][:, 0, None, None])
            & (entries0[:, :, :, ENT_IDW] == ctx["msg_id"][:, 1, None, None])
            & slot_match[:, :, None]
        )  # [B,K,cap]
        u_clear = (em & rm_c[:, None, None]).reshape(b, k * cap)
        u_refresh = (em & refresh[:, None, None]).reshape(b, k * cap)

        # aggregate op mutations onto every row of the op's bucket
        rows_idx = idxs_mb2.reshape(b * d)  # [B*D]
        if ecfg.vphases_impl == "dense":
            row_op = (rows_idx[:, None] == eff_idx[None, :]) & mutating[None, :]
            clear = _bool_matmul(row_op, u_clear).reshape(b * d, k, cap)
            refr = _bool_matmul(row_op, u_refresh).reshape(b * d, k, cap)
        else:
            # bucket table: non-mutating ops scatter all-false vectors
            # into the sentinel row, which dummy/unmutated rows then read
            # back as zeros — identical to the masked matmul
            u2 = jnp.stack([u_clear, u_refresh], axis=1).astype(I32)
            tbl = (
                jnp.zeros((ecfg.mb_table_buckets + 1, 2, k * cap), I32)
                .at[jnp.minimum(eff_idx, m_sentinel)]
                .add(u2)
            )
            agg = tbl[jnp.minimum(rows_idx, m_sentinel)] > 0
            clear = agg[:, 0].reshape(b * d, k, cap)
            refr = agg[:, 1].reshape(b * d, k, cap)

        rows_entries = entries_c.reshape(b * d, k, cap, ENTRY_WORDS)
        rows_keys = keys_c.reshape(b * d, k, 8)
        ents = jnp.where(
            refr[:, :, :, None],
            rows_entries.at[:, :, :, ENT_TS]
            .set(now)
            .at[:, :, :, ENT_TSH]
            .set(ctx["now_hi"]),
            rows_entries,
        )
        ents = jnp.where(clear[:, :, :, None], U32(0), ents)
        final_val = _mb_pack_batch(ecfg, rows_keys, ents)
        final_alive = present0  # sticky slots: blocks persist until sweep
        return {}, final_val, final_alive

    return apply_batch
