"""The oblivious query engine: batched, branchless CRUD over two ORAMs.

The TPU re-design of the reference's enclave query engine (the absent
``enclave/trusted`` crate specified at reference grapevine.proto:57-122;
SURVEY.md §1 layer 4-5). Architecture:

- **records store**: Path ORAM with a dense block space; the server-assigned
  msg_id *encodes* the block index (word 0) plus 96 random bits, so record
  lookup is a single ORAM access with full-id verification in the stash —
  no separate hash map and no id collisions (a deliberate deviation from
  the reference's random-id + map design, grapevine.proto:66-79; ids remain
  unguessable and the operator never sees them — they ride the encrypted
  channel).
- **mailbox store**: a keyed-hash table (recipient → bucket of K mailboxes)
  over its own Path ORAM; each mailbox holds up to 62 entries
  (reference README.md:78-80) of (msg_id, seq, ts).
- **uniform access sequence**: every operation — Create, Read, Update,
  Delete, and padding dummies — performs exactly [mailbox, records,
  mailbox] ORAM accesses, so R/U/D are indistinguishable in the public
  transcript as required (reference grapevine.proto:120-122); Create is
  *allowed* to be distinguishable but is uniform here too.
"""

from .state import EngineConfig, EngineState, init_engine  # noqa: F401
from .step import engine_step  # noqa: F401
from .expiry import expiry_sweep  # noqa: F401
