"""Response/status assembly shared by the op-major and phase-major engines.

Both engines end with the identical mapping from phase outputs to the
constant-shape response record + status code (the status-precedence tree
documented in testing/reference.py). The helper is shape-generic: the
op-major engine calls it per op under `lax.scan` (scalar masks), the
phase-major engine calls it once per batch (``[B]`` masks) — `[..., None]`
broadcasting covers both.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..obs.phases import device_phase
from ..wire import constants as C

U32 = jnp.uint32


def assemble_responses(*args, **kwargs):
    """Trace-annotated wrapper; see ``_assemble_responses`` for the
    semantics. The named scope makes the response-assembly HLO show up
    as its own span in TPU profiler captures (obs/phases.py)."""
    with device_phase("respond"):
        return _assemble_responses(*args, **kwargs)


def _assemble_responses(
    *,
    is_real,
    is_create,
    is_update,
    is_delete,
    id_zero,
    status_a,
    create_ok,
    out_b,
    new_id,
    auth,
    recipient,
    payload,
    now2,
):
    """Build the response pytree. All mask args are bool scalars or
    bool[B]; multi-word fields have one trailing word axis. ``now2`` is
    the u64 server clock as u32[2] (lo, hi); the timestamp field is
    likewise two lanes."""
    ok_rud = out_b["read_ok"] | out_b["upd_ok"] | out_b["del_ok"]
    status = jnp.where(
        ~is_real,
        U32(0),
        jnp.where(
            is_create,
            status_a,
            jnp.where(
                ok_rud,
                U32(C.STATUS_CODE_SUCCESS),
                jnp.where(
                    (is_update | is_delete)
                    & ~id_zero
                    & out_b["match_ok"]
                    & out_b["auth_ok"]
                    & ~out_b["recip_match"],
                    U32(C.STATUS_CODE_INVALID_RECIPIENT),
                    U32(C.STATUS_CODE_NOT_FOUND),
                ),
            ),
        ),
    )
    created = is_create & create_ok
    cr = created[..., None]
    okr = ok_rud[..., None]
    return {
        "status": status,
        "msg_id": jnp.where(cr, new_id, jnp.where(okr, out_b["resp_id"], U32(0))),
        "sender": jnp.where(
            cr, auth, jnp.where(okr, out_b["resp_sender"], U32(0))
        ),
        "recipient": jnp.where(
            cr, recipient, jnp.where(okr, out_b["resp_recipient"], U32(0))
        ),
        "timestamp": jnp.where(
            (created | ok_rud)[..., None],
            jnp.where(created[..., None], now2, out_b["resp_ts"]),
            jnp.where(is_real[..., None], now2, U32(0)),
        ),
        "payload": jnp.where(
            cr, payload, jnp.where(okr, out_b["resp_payload"], U32(0))
        ),
    }
