"""Batch-level engine metrics (SURVEY §5 observability).

The obliviousness requirement constrains telemetry: nothing here is
keyed by client identity or op type — per-op timing/type breakdowns
would themselves be the side channel the engine exists to close
(reference grapevine.proto:120-122). What IS safe to export, and what
operators need (the reference's `mc-common` logging analog):

- round counters: rounds run, real ops, padded slots → batch occupancy;
- round latency: a fixed-size ring of recent wall times → p50/p99
  (BASELINE.json tracks p99 access latency as a first-class metric);
- expiry sweeps run and records evicted;
- auth: batch verifications, failed signatures (counts only);
- stash pressure: sampled occupancy high-water mark per tree (polled at
  ``snapshot()`` — a per-round device reduction would stall the
  dispatch pipeline for a gauge nobody reads between scrapes).

Thread-safety: all counters are guarded by this module's own lock and
every recording entry point may be called from any thread —
`record_round` in particular runs from `PendingRound.resolve()` outside
the engine lock (the pipelined scheduler resolves a round after
dispatching the next one). Do not weaken the internal lock based on
who currently calls what.
"""

from __future__ import annotations

import threading

import numpy as np


class EngineMetrics:
    """Monotonic counters + a latency ring; `snapshot()` is the export."""

    def __init__(self, ring_size: int = 1024):
        self._lock = threading.Lock()
        self._ring = np.zeros((ring_size,), np.float64)
        self._ring_n = 0  # total rounds ever recorded
        self.real_ops = 0
        self.padded_slots = 0
        self.sweeps = 0
        self.evicted = 0
        self.batch_verifies = 0
        self.auth_failures = 0
        self.stash_high_water = 0

    # -- recording ------------------------------------------------------

    def record_round(self, n_real: int, batch_size: int, seconds: float) -> None:
        with self._lock:
            self._ring[self._ring_n % self._ring.size] = seconds
            self._ring_n += 1
            self.real_ops += n_real
            self.padded_slots += batch_size - n_real

    def record_sweep(self, evicted: int) -> None:
        with self._lock:
            self.sweeps += 1
            self.evicted += evicted

    def record_auth(self, failures: int = 0) -> None:
        with self._lock:
            self.batch_verifies += 1
            self.auth_failures += failures

    def observe_stash(self, occupancy: int) -> None:
        with self._lock:
            self.stash_high_water = max(self.stash_high_water, occupancy)

    # -- export ---------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            rounds = self._ring_n
            lat = self._ring[: min(rounds, self._ring.size)]
            slots = self.real_ops + self.padded_slots
            out = {
                "rounds": rounds,
                "real_ops": self.real_ops,
                "batch_occupancy": (self.real_ops / slots) if slots else 0.0,
                "sweeps": self.sweeps,
                "evicted": self.evicted,
                "batch_verifies": self.batch_verifies,
                "auth_failures": self.auth_failures,
                "stash_high_water": self.stash_high_water,
            }
            if len(lat):
                out["round_ms_p50"] = round(float(np.percentile(lat, 50)) * 1e3, 3)
                out["round_ms_p99"] = round(float(np.percentile(lat, 99)) * 1e3, 3)
        return out
