"""Batch-level engine metrics (SURVEY §5 observability).

The obliviousness requirement constrains telemetry: nothing here is
keyed by client identity or op type — per-op timing/type breakdowns
would themselves be the side channel the engine exists to close
(reference grapevine.proto:120-122). What IS safe to export, and what
operators need (the reference's `mc-common` logging analog):

- round counters: rounds run, real ops, padded slots → batch occupancy;
- round latency: a fixed-size ring of recent wall times → p50/p99
  (BASELINE.json tracks p99 access latency as a first-class metric);
- per-phase round timing (assembly/verify/dispatch/evict/demux/sweep)
  as fixed-bucket histograms — every phase covers the whole fixed-size
  round, so durations are functions of (capacity, batch size), never of
  the ops inside (obs/phases.py);
- scheduler/queue health: depth, high-water, under-full rounds,
  collector stalls;
- expiry sweeps run and records evicted;
- auth: batch verifications, failed signatures (counts only);
- stash pressure: sampled occupancy high-water mark per tree (polled at
  ``snapshot()`` — a per-round device reduction would stall the
  dispatch pipeline for a gauge nobody reads between scrapes).

All of it lives in an obs.TelemetryRegistry whose label allowlist makes
a per-client/per-op series a registration-time error, and which the
leak audit (tools/check_telemetry_policy.py) re-checks in tier-1.

Thread-safety: the ring is guarded by this module's own lock, registry
samples by per-child locks, and every recording entry point may be
called from any thread — `record_round` in particular runs from
`PendingRound.resolve()` outside the engine lock (the pipelined
scheduler resolves a round after dispatching the next one). Do not
weaken the internal locks based on who currently calls what.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..obs.phases import PHASE_BUCKETS, PHASES, STASH_BUCKETS, phase_timer
from ..obs.registry import TelemetryRegistry


class EngineMetrics:
    """Monotonic counters + a latency ring on a TelemetryRegistry;
    `snapshot()` is the merged flat export, the registry the scrapable
    one (obs/exporter.py)."""

    def __init__(self, ring_size: int = 1024, registry: TelemetryRegistry | None = None):
        self._lock = threading.Lock()
        self._ring = np.zeros((ring_size,), np.float64)
        self._ring_n = 0  # total rounds ever recorded
        self._last_round_mono: float | None = None
        r = self.registry = registry or TelemetryRegistry()
        self._c_rounds = r.counter(
            "grapevine_rounds_total", "oblivious rounds committed")
        self._c_real = r.counter(
            "grapevine_real_ops_total", "real (non-padding) ops committed")
        self._c_padded = r.counter(
            "grapevine_padded_slots_total", "dummy-padded slots committed")
        self._c_underfull = r.counter(
            "grapevine_underfull_rounds_total",
            "rounds dispatched with fewer real ops than batch_size")
        self._c_sweeps = r.counter(
            "grapevine_expiry_sweeps_total", "expiry sweeps run")
        self._c_evicted = r.counter(
            "grapevine_expired_records_total", "records evicted by expiry")
        self._c_flushes = r.counter(
            "grapevine_evict_flushes_total",
            "delayed-eviction window flushes dispatched (cadence is a "
            "pure function of the round counter — the fleet uniformity "
            "monitor compares flush phase across shards)")
        self._c_verifies = r.counter(
            "grapevine_batch_verifies_total",
            "round-level batched signature verifications")
        self._c_authfail = r.counter(
            "grapevine_auth_failures_total",
            "challenge signatures that failed verification (count only)")
        self._c_stalls = r.counter(
            "grapevine_collector_stalls_total",
            "collection windows that hit the max_wait cap before filling")
        self._c_worker_crash = r.counter(
            "grapevine_worker_crash_total",
            "scheduler collector thread deaths (crashes, not clean close)")
        self._g_occupancy = r.gauge(
            "grapevine_batch_occupancy",
            "real ops / batch slots of the last committed round")
        self._g_qdepth = r.gauge(
            "grapevine_queue_depth", "ops waiting in the scheduler queue")
        self._g_qdepth_hw = r.gauge(
            "grapevine_queue_depth_high_water",
            "max scheduler queue depth observed")
        self._g_stash_hw = r.gauge(
            "grapevine_stash_high_water",
            "max sampled ORAM stash occupancy (must stay far below "
            "stash_size; overflow means the eviction invariant broke)")
        self._g_ebuf = r.gauge(
            "grapevine_evict_buffer_occupancy",
            "sampled delayed-eviction buffer occupancy, summed over "
            "trees (rows; batch-level — the buffer holds whole fetched "
            "paths, never per-client state); 0 with evict_every=1")
        self._g_ebuf_hw = r.gauge(
            "grapevine_evict_buffer_high_water",
            "max sampled delayed-eviction buffer occupancy (the "
            "near-overflow canary: approaching evict_buffer_slots "
            "means the window is undersized — OPERATIONS.md §19)")
        self._h_phase = r.histogram(
            "grapevine_phase_seconds",
            "wall time per round phase (batch-level; obs/phases.py)",
            buckets=PHASE_BUCKETS, labels={"phase": PHASES})
        self._h_round = r.histogram(
            "grapevine_round_seconds",
            "dispatch-to-delivery commit latency per round",
            buckets=PHASE_BUCKETS)
        self._h_stash = r.histogram(
            "grapevine_stash_occupancy",
            "sampled stash occupancy (entries)", buckets=STASH_BUCKETS)

    # -- recording ------------------------------------------------------

    def record_round(self, n_real: int, batch_size: int, seconds: float) -> None:
        with self._lock:
            self._ring[self._ring_n % self._ring.size] = seconds
            self._ring_n += 1
            self._last_round_mono = time.monotonic()
        self._c_rounds.inc()
        self._c_real.inc(n_real)
        self._c_padded.inc(batch_size - n_real)
        if n_real < batch_size:
            self._c_underfull.inc()
        self._g_occupancy.set(n_real / batch_size if batch_size else 0.0)
        self._h_round.observe(seconds)

    def record_sweep(self, evicted: int) -> None:
        self._c_sweeps.inc()
        self._c_evicted.inc(evicted)

    def record_flush(self) -> None:
        self._c_flushes.inc()

    def record_auth(self, failures: int = 0) -> None:
        self._c_verifies.inc()
        if failures:
            self._c_authfail.inc(failures)

    def observe_stash(self, occupancy: int) -> None:
        self._g_stash_hw.set_max(occupancy)
        self._h_stash.observe(occupancy)

    def observe_evict_buffer(self, occupancy: int) -> None:
        """Sampled delayed-eviction buffer occupancy (rows, summed over
        trees) — scrape-cadence like the stash gauge, never per round."""
        self._g_ebuf.set(occupancy)
        self._g_ebuf_hw.set_max(occupancy)

    def observe_phase(self, phase: str, seconds: float) -> None:
        self._h_phase.observe(seconds, phase=phase)

    def time_phase(self, phase: str):
        """Context manager timing one host-side phase (+ profiler span)."""
        return phase_timer(self._h_phase, phase)

    def observe_queue_depth(self, depth: int) -> None:
        self._g_qdepth.set(depth)
        self._g_qdepth_hw.set_max(depth)

    def record_stall(self) -> None:
        self._c_stalls.inc()

    def record_worker_crash(self) -> None:
        self._c_worker_crash.inc()

    # -- health probes --------------------------------------------------

    def last_round_age(self) -> float | None:
        """Seconds since the last committed round; None before the first.
        Lock-free read path on purpose: healthz must answer while a
        wedged recorder holds the ring lock."""
        t = self._last_round_mono
        return None if t is None else time.monotonic() - t

    # -- compat counter views (legacy attribute names) ------------------

    @property
    def real_ops(self) -> int:
        return int(self._c_real.get())

    @property
    def padded_slots(self) -> int:
        return int(self._c_padded.get())

    @property
    def stash_high_water(self) -> int:
        return int(self._g_stash_hw.get())

    # -- export ---------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            rounds = self._ring_n
            # ring slice is valid both pre-wrap (first `rounds` cells)
            # and post-wrap (the whole ring holds the last ring_size)
            lat = np.sort(self._ring[: min(rounds, self._ring.size)])
        real = int(self._c_real.get())
        slots = real + int(self._c_padded.get())
        out = {
            "rounds": rounds,
            "real_ops": real,
            "batch_occupancy": (real / slots) if slots else 0.0,
            "sweeps": int(self._c_sweeps.get()),
            "evicted": int(self._c_evicted.get()),
            "batch_verifies": int(self._c_verifies.get()),
            "auth_failures": int(self._c_authfail.get()),
            "stash_high_water": int(self._g_stash_hw.get()),
            "underfull_rounds": int(self._c_underfull.get()),
            "collector_stalls": int(self._c_stalls.get()),
            "queue_depth": int(self._g_qdepth.get()),
            "queue_depth_high_water": int(self._g_qdepth_hw.get()),
        }
        if len(lat):
            # method="higher" (a real order statistic, never below a
            # sample): linear interpolation over a small ring
            # under-reports p99 — at 20 rounds it averaged the 19th and
            # 20th samples instead of reporting the 20th
            out["round_ms_p50"] = round(
                float(np.percentile(lat, 50, method="higher")) * 1e3, 3)
            out["round_ms_p99"] = round(
                float(np.percentile(lat, 99, method="higher")) * 1e3, 3)
        # the merged registry view (phase histograms, gauges): one flat
        # dict so loopback health readers see engine + scheduler + ORAM
        # telemetry without a second endpoint (server/service.py)
        out.update(self.registry.snapshot())
        return out
