"""Journal-shipped hot standby: streaming replication, fenced takeover.

Topology (OPERATIONS.md §23): the primary engine keeps its normal
append-before-dispatch journal; a :class:`JournalShipper` tails it and
streams every sealed frame — verbatim bytes — to a
:class:`StandbyReplica` over a length-prefixed TCP connection. The
standby appends each frame to its OWN journal (same fsync discipline,
``BatchJournal.append_raw``) and immediately replays it through the
same jitted step/sweep/flush programs crash recovery uses
(``GrapevineEngine._replay_record``), so its warm state trails the
primary by shipping latency alone and the existing
``grapevine_journal_applied_seq`` / fleet lag gauges price that gap
with zero new schema.

Obliviousness: a shipped frame IS the sealed journal frame — constant
size per kind, one per journaled record, shipped at round cadence.
Shipping traffic is a pure function of the round counter, never of
buffer contents, so leakmon's existing cadence policing extends to the
replication link verbatim (``EngineLeakMonitor.attach_shipper`` folds
the byte-cadence books into the verdict schema).

Fenced takeover: :meth:`StandbyReplica.promote` (1) plants a fence
marker in the dead primary's state dir (O_EXCL — a double-promote race
has exactly one winner) carrying the bumped journal epoch, so a revived
(or still-running) stale primary's next append fails with a hard
``JournalError``; (2) drains the primary's durable journal tail
straight off disk — RPO 0 for durable frames, because a SIGKILL leaves
everything written in page cache; (3) completes a pending eviction
flush exactly like the crash-recovery constructor; then serves from the
warm state. RTO is therefore the tail drain + replay alone — measured,
returned, and banked by ``bench.py failover_ab``.

Knob interplay (the RPO/RTO table in OPERATIONS.md §23): the standby's
local ``checkpoint_every_rounds`` bounds its own restart replay; the
primary's bounds how far a never-connected standby must drain at
promote; ``journal_fsync_every`` bounds what a *machine* crash (not a
process kill) can lose; ``ship_every`` batches doorbell wakeups without
changing what ships.

Cross-knob legality: journal frames encode batches, not tree-cache
placement, so a ``tree_top_cache_levels=0`` standby legally replays a
k=4 primary's frames from genesis (:func:`replication_fingerprint` is
the frame-compatibility check). Sealed checkpoints DO encode placement
— shipping one requires the full geometry fingerprint to match, so a
cross-knob standby must bootstrap from an unpruned journal instead.
Both dirs must share the root seal key (``seal_key_file``).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import socket
import struct
import threading
import time

from ..config import DurabilityConfig, GrapevineConfig
from .checkpoint import engine_fingerprint, find_latest_checkpoint
from .journal import (
    _HEADER,
    BatchJournal,
    JournalError,
    read_epoch,
    write_epoch,
    write_fence,
)
from .state import EngineConfig

log = logging.getLogger("grapevine_tpu.replication")

#: wire protocol: ``u32 total_len | u8 type | payload``
MSG_HELLO = 1  # JSON handshake, standby speaks first
MSG_CKPT = 2  # u64 seq | sealed checkpoint file bytes
MSG_FRAME = 3  # one raw journal frame, verbatim

_LEN = struct.Struct("<I")


class ReplicationError(RuntimeError):
    """Replication protocol/transport failure (retryable by reconnect)."""


class FatalReplicationError(ReplicationError):
    """A mismatch reconnecting can never fix (fingerprint, stale epoch)."""


def _parse_addr(target) -> tuple[str, int]:
    if isinstance(target, (tuple, list)):
        return str(target[0]), int(target[1])
    host, _, port = str(target).rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"replication address must be host:port, got {target!r}")
    return host, int(port)


def _send_msg(sock: socket.socket, mtype: int, payload: bytes) -> None:
    sock.sendall(_LEN.pack(1 + len(payload)) + bytes([mtype]) + payload)


def _recv_exact(sock: socket.socket, n: int, *, start: bool) -> bytes | None:
    """Read exactly ``n`` bytes. EOF at a message boundary (``start``)
    returns None — a clean disconnect; EOF mid-message raises (the peer
    died mid-send; the partial bytes are discarded, never applied)."""
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            if start and not buf:
                return None
            raise ReplicationError(
                f"peer closed mid-message ({len(buf)}/{n} bytes)"
            )
        buf += chunk
    return buf


def _recv_msg(sock: socket.socket) -> tuple[int, bytes] | None:
    hdr = _recv_exact(sock, _LEN.size, start=True)
    if hdr is None:
        return None
    (total,) = _LEN.unpack(hdr)
    if total < 1:
        raise ReplicationError("zero-length replication message")
    body = _recv_exact(sock, total, start=False)
    return body[0], body[1:]


def replication_fingerprint(config: GrapevineConfig) -> str:
    """Frame-compatibility fingerprint: the full engine fingerprint
    resolved with ``tree_top_cache_levels`` normalized to 0.

    Journal frames serialize batches — no tree-cache placement — and
    the tree-top cache only re-places bits (PR 14's equivalence
    suites), so replaying a k=4 primary's frames on a k=0 standby is
    legal (the rolling-upgrade drill). Everything else that the full
    fingerprint covers (geometry, eviction cadence, posmap impl) still
    fences: frames are only replayable under the identical resolved
    program."""
    norm = dataclasses.replace(config, tree_top_cache_levels=0)
    return engine_fingerprint(EngineConfig.from_config(norm))


# -- primary side -------------------------------------------------------


class JournalShipper:
    """Primary-side replication: tail the engine's sealed journal and
    stream frames to one standby.

    One daemon thread: connect (with backoff) → handshake → catch up →
    drain. The journal file itself is the only source of truth — the
    ``on_append`` hook installed under the engine lock is a pure
    doorbell (one counter bump + event set, no I/O, so the engine
    lock-hold cost is unchanged and locklint's single-hold contract is
    untouched); the shipper thread re-reads frames off disk with a
    read-only ``BatchJournal`` (page cache, no fsync wait), which makes
    reconnects and races resync-free by construction.
    """

    def __init__(self, engine, target, ship_every: int = 1,
                 connect_backoff_s: float = 0.25):
        if engine.durability is None:
            raise ReplicationError(
                "--replicate-to needs --state-dir: the shipper tails "
                "the sealed journal"
            )
        self.engine = engine
        self.target = _parse_addr(target)
        self.ship_every = max(1, int(ship_every))
        self.connect_backoff_s = connect_backoff_s
        dm = engine.durability
        self._dm = dm
        self._reader = BatchJournal(dm.dcfg.state_dir, dm.root_key, dm.ecfg)
        #: legal on-wire frame sizes for this geometry — the leakmon
        #: cadence book: every shipped frame must be one of these
        #: constants, whatever the ops inside are
        self._legal_frame_lens = frozenset(
            _HEADER.size + bl for bl in self._reader._valid_blob_lens
        )
        registry = engine.metrics.registry
        self._c_shipped = registry.counter(
            "grapevine_replication_frames_shipped_total",
            "sealed journal frames streamed to the standby")
        self._c_reconnects = registry.counter(
            "grapevine_replication_reconnects_total",
            "replication link (re)connection attempts")
        self._g_connected = registry.gauge(
            "grapevine_replication_connected",
            "1 while the replication link to the standby is up")
        self._frames_shipped = 0
        self._bytes_shipped = 0
        self._frames_appended = 0
        self._illegal_frames = 0
        self.fatal: str | None = None
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="journal-shipper"
        )

    def start(self) -> None:
        self._dm.journal.on_append = self._on_append
        self._thread.start()

    # runs under the engine lock with the append: doorbell only
    def _on_append(self, seq: int, frame: bytes) -> None:
        self._frames_appended += 1
        if self._frames_appended % self.ship_every == 0:
            self._wake.set()

    def _run(self) -> None:
        backoff = self.connect_backoff_s
        while not self._stop.is_set():
            self._c_reconnects.inc()
            try:
                self._ship_session()
                backoff = self.connect_backoff_s
            except FatalReplicationError as exc:
                self.fatal = str(exc)
                log.error("replication halted: %s", exc)
                return
            except (OSError, ReplicationError, JournalError) as exc:
                log.info("replication link lost: %s", exc)
            self._stop.wait(backoff)
            backoff = min(backoff * 2, 5.0)

    def _ship_session(self) -> None:
        dm = self._dm
        sock = socket.create_connection(self.target, timeout=5.0)
        try:
            sock.settimeout(10.0)
            msg = _recv_msg(sock)
            if msg is None or msg[0] != MSG_HELLO:
                raise ReplicationError("standby did not send hello")
            hello = json.loads(msg[1])
            my_full = engine_fingerprint(dm.ecfg)
            my_repl = replication_fingerprint(self.engine.config)
            if hello.get("replication_fingerprint") != my_repl:
                raise FatalReplicationError(
                    "standby geometry fingerprint does not match — "
                    "journal frames are only replayable under the "
                    "identical resolved geometry; refusing to ship"
                )
            if int(hello.get("epoch", 0)) > dm.journal.epoch:
                raise FatalReplicationError(
                    f"standby is at journal epoch {hello['epoch']} > "
                    f"this primary's {dm.journal.epoch} — this primary "
                    "is stale (fenced); refusing to ship"
                )
            _send_msg(sock, MSG_HELLO, json.dumps({
                "fingerprint": my_full,
                "replication_fingerprint": my_repl,
                "epoch": dm.journal.epoch,
                "ckpt_seq": dm.ckpt_seq,
                "seq": dm.seq,
            }).encode())
            sent = int(hello.get("applied_seq", 0))
            if sent < dm.ckpt_seq:
                # frames at or below the checkpoint horizon are pruned:
                # bootstrap from the sealed checkpoint. Checkpoints
                # encode placement, so this path needs the FULL
                # fingerprint — a cross-knob standby can only replay
                # from genesis (OPERATIONS.md §23).
                if hello.get("fingerprint") != my_full:
                    raise FatalReplicationError(
                        "cross-knob standby must replay the journal "
                        "from genesis, but this primary pruned through "
                        f"seq {dm.ckpt_seq} — bring the standby up "
                        "before the first checkpoint, or match knobs"
                    )
                latest = find_latest_checkpoint(dm.dcfg.state_dir)
                if latest is None:
                    raise ReplicationError(
                        "checkpoint horizon is non-zero but no sealed "
                        "checkpoint is on disk"
                    )
                with open(latest[1], "rb") as fh:
                    blob = fh.read()
                _send_msg(sock, MSG_CKPT, struct.pack("<Q", latest[0]) + blob)
                sent = latest[0]
            sock.settimeout(None)
            self._g_connected.set(1)
            while not self._stop.is_set():
                for seq, frame in self._reader.follow_frames(after_seq=sent):
                    if len(frame) not in self._legal_frame_lens:
                        # unreachable by construction (follow_frames
                        # validated the length); kept as the cadence
                        # book's tripwire rather than silent trust
                        self._illegal_frames += 1
                    _send_msg(sock, MSG_FRAME, frame)
                    sent = seq
                    self._frames_shipped += 1
                    self._bytes_shipped += _LEN.size + 1 + len(frame)
                    self._c_shipped.inc()
                self._wake.wait(0.2)
                self._wake.clear()
        finally:
            self._g_connected.set(0)
            sock.close()

    def stats(self) -> dict:
        """The leakmon cadence books (obs/leakmon.py
        ``attach_shipper``): shipping totals plus the content-
        independence verdict — every byte on the wire must be one of
        the geometry's constant frame sizes plus constant framing."""
        return {
            "frames_shipped": self._frames_shipped,
            "bytes_shipped": self._bytes_shipped,
            "frames_appended": self._frames_appended,
            "illegal_frames": self._illegal_frames,
            "cadence_ok": self._illegal_frames == 0,
        }

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._dm.journal.on_append is self._on_append:
            self._dm.journal.on_append = None
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)


# -- standby side -------------------------------------------------------


class StandbyReplica:
    """Warm follower: journals shipped frames locally, applies them
    through the live jitted programs, checkpoints on its own cadence
    (bounding both its restart replay and the promote-time tail), and
    takes over via :meth:`promote`.

    Construction builds a full durable engine over the standby's OWN
    state dir — a standby that restarts recovers its warm state from
    local checkpoint + journal exactly like a primary would. The
    standby never runs rounds of its own until promoted.
    """

    def __init__(self, config: GrapevineConfig | None = None,
                 seed: int = 0,
                 durability: DurabilityConfig | None = None):
        from .batcher import GrapevineEngine

        if durability is None:
            raise ReplicationError(
                "a standby needs its own state dir (DurabilityConfig)"
            )
        self.config = config or GrapevineConfig()
        self.engine = GrapevineEngine(
            self.config, seed=seed, durability=durability
        )
        self.dm = self.engine.durability
        self.registry = self.engine.metrics.registry
        self.full_fingerprint = engine_fingerprint(self.engine.ecfg)
        self.repl_fingerprint = replication_fingerprint(self.config)
        self.promoted = False
        self.connected = False
        self._c_applied = self.registry.counter(
            "grapevine_replication_frames_applied_total",
            "shipped journal frames applied to standby state")
        self._c_promotions = self.registry.counter(
            "grapevine_replication_promotions_total",
            "fenced takeovers served from this replica")
        self._g_connected = self.registry.gauge(
            "grapevine_replication_connected",
            "1 while a primary is feeding this standby")
        self._g_epoch = self.registry.gauge(
            "grapevine_replication_epoch",
            "journal epoch this replica serves under")
        self._g_rto = self.registry.gauge(
            "grapevine_replication_last_rto_seconds",
            "measured promote() wall time (fence + tail drain + replay)")
        self._g_epoch.set(self.dm.journal.epoch)
        self._stop = threading.Event()
        self._lsock: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._metrics_server = None

    # -- frame application ---------------------------------------------

    def _decode_frame(self, seq: int, frame: bytes):
        """Verify + decode one shipped frame (seal checked under the
        shared root key with the header as AAD, body validated against
        this standby's geometry) — BEFORE it becomes local durable
        state."""
        from .checkpoint import SealError, unseal

        if len(frame) < _HEADER.size:
            raise ReplicationError(f"frame {seq}: shorter than a header")
        header = frame[: _HEADER.size]
        try:
            body = unseal(
                self.dm.root_key, b"journal", frame[_HEADER.size:],
                aad=header,
            )
        except SealError as exc:
            raise ReplicationError(
                f"shipped frame {seq} failed its integrity check: {exc}"
            ) from exc
        return self.dm.journal._decode_body(seq, body)

    def _apply_locked(self, seq: int, frame: bytes) -> bool:
        """Journal + apply one frame; caller holds the engine lock.
        Duplicates (reconnect overlap) are skipped; a gap is a protocol
        error — the journal's own contiguity check would refuse it
        anyway, but failing before the decode gives a clearer story."""
        eng = self.engine
        if seq <= self.dm.seq:
            return False
        if seq != self.dm.seq + 1:
            raise ReplicationError(
                f"shipped frame {seq} but the standby journal is at "
                f"{self.dm.seq} — a frame went missing in transit"
            )
        rec = self._decode_frame(seq, frame)
        self.dm.append_raw_frame(seq, frame)
        eng.state = eng._replay_record(eng.state, rec)
        self.dm.note_applied_seq(seq)
        self._c_applied.inc()
        if self.dm.should_checkpoint():
            self.dm.checkpoint(eng.state)
        return True

    def apply_frame(self, seq: int, frame: bytes) -> bool:
        with self.engine._lock:
            if self.promoted:
                raise ReplicationError(
                    "promoted replicas do not accept shipped frames"
                )
            return self._apply_locked(seq, frame)

    def _install_checkpoint(self, seq: int, blob: bytes) -> None:
        eng = self.engine
        with eng._lock:
            if self.promoted:
                raise ReplicationError(
                    "promoted replicas do not accept shipped checkpoints"
                )
            if seq <= self.dm.seq:
                return
            state = self.dm.install_checkpoint(seq, blob)
            if eng._mesh is not None:
                state = eng._shard_state(state, eng._mesh)
            eng.state = state
            # re-anchor the replay cadence audit at the new base
            eng._replay_since = None

    # -- transport ------------------------------------------------------

    def listen(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Accept primary connections on ``host:port`` (0 = ephemeral);
        returns the bound port. One primary at a time — the handshake
        refuses stale epochs, so after a promotion the revived old
        primary cannot feed anyone."""
        self._lsock = socket.create_server((host, port))
        self._lsock.settimeout(0.5)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="standby-listener"
        )
        self._accept_thread.start()
        return self._lsock.getsockname()[1]

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                self._serve_conn(conn)
            except (OSError, ReplicationError, JournalError) as exc:
                log.info("replication feed dropped: %s", exc)
            finally:
                self.connected = False
                self._g_connected.set(0)
                conn.close()

    def _serve_conn(self, conn: socket.socket) -> None:
        if self.promoted:
            return  # serving now; the stale primary gets a closed socket
        conn.settimeout(10.0)
        _send_msg(conn, MSG_HELLO, json.dumps({
            "fingerprint": self.full_fingerprint,
            "replication_fingerprint": self.repl_fingerprint,
            "epoch": self.dm.journal.epoch,
            "applied_seq": self.dm.seq,
        }).encode())
        msg = _recv_msg(conn)
        if msg is None or msg[0] != MSG_HELLO:
            raise ReplicationError("primary did not send hello")
        hello = json.loads(msg[1])
        if hello.get("replication_fingerprint") != self.repl_fingerprint:
            raise ReplicationError(
                "primary geometry fingerprint does not match — refusing "
                "the feed"
            )
        if int(hello.get("epoch", 0)) < self.dm.journal.epoch:
            raise ReplicationError(
                f"primary is at journal epoch {hello.get('epoch', 0)} < "
                f"this replica's {self.dm.journal.epoch} — stale primary "
                "refused (split-brain guard)"
            )
        conn.settimeout(0.5)
        self.connected = True
        self._g_connected.set(1)
        while not self._stop.is_set() and not self.promoted:
            try:
                msg = _recv_msg(conn)
            except socket.timeout:
                continue
            if msg is None:
                return  # primary went away cleanly (or was killed)
            mtype, payload = msg
            if mtype == MSG_CKPT:
                if len(payload) < 8:
                    raise ReplicationError("short checkpoint message")
                (seq,) = struct.unpack_from("<Q", payload)
                self._install_checkpoint(seq, payload[8:])
            elif mtype == MSG_FRAME:
                if len(payload) < _HEADER.size:
                    raise ReplicationError("short frame message")
                _magic, seq, _bl = _HEADER.unpack_from(payload, 0)
                self.apply_frame(seq, payload)
            else:
                raise ReplicationError(f"unknown message type {mtype}")

    # -- takeover -------------------------------------------------------

    def promote(self, primary_state_dir: str | None = None) -> dict:
        """Fenced takeover; returns the measured promotion record.

        1. Plant the fence in ``primary_state_dir`` (O_EXCL: exactly
           one winner in a double-promote race) at the bumped epoch —
           from this instant the stale primary's appends raise.
        2. Drain the primary's durable journal tail straight off disk
           and apply it — RPO 0 for durable frames (page cache survives
           a SIGKILL; only un-fsynced frames lost to a *machine* crash
           are gone, bounded by the primary's ``journal_fsync_every``).
        3. Complete a pending eviction flush exactly like the
           crash-recovery constructor, so the promoted journal keeps
           the [round_E, flush] adjacency an uninterrupted run writes.
        4. Record the epoch locally and serve.

        RTO is the measured wall time of 1–3 (the jitted programs are
        already warm — that is the point of a hot standby)."""
        import jax

        t0 = time.monotonic()
        eng = self.engine
        with eng._lock:
            if self.promoted:
                raise ReplicationError("already promoted")
            new_epoch = self.dm.journal.epoch + 1
            drained = 0
            if primary_state_dir is not None:
                new_epoch = max(new_epoch, read_epoch(primary_state_dir) + 1)
                write_fence(primary_state_dir, epoch=new_epoch,
                            fingerprint=self.repl_fingerprint)
                latest = find_latest_checkpoint(primary_state_dir)
                if latest is not None and latest[0] > self.dm.seq:
                    # the standby fell behind the primary's prune
                    # horizon (e.g. disconnected across a checkpoint +
                    # roll): the sealed checkpoint IS durable state, so
                    # RPO 0 still holds — install it, then drain the
                    # frames past it. Checkpoints encode placement, so
                    # this path needs the full fingerprint; a cross-knob
                    # standby must have been fed continuously.
                    with open(latest[1], "rb") as fh:
                        blob = fh.read()
                    state = self.dm.install_checkpoint(latest[0], blob)
                    if eng._mesh is not None:
                        state = eng._shard_state(state, eng._mesh)
                    eng.state = state
                    eng._replay_since = None
                reader = BatchJournal(
                    primary_state_dir, self.dm.root_key, self.dm.ecfg
                )
                for seq, frame in reader.follow_frames(after_seq=self.dm.seq):
                    self._apply_locked(seq, frame)
                    drained += 1
            if eng.evict_every > 1:
                # cadence counter from state, never a host mirror —
                # then complete a flush the dead primary journaled
                # rounds for but never got to (mid-window kill)
                eng._rounds_since_flush = int(eng.state.rec.ebuf_rounds)
                if eng._rounds_since_flush >= eng.evict_every:
                    eng._flush_window_locked(min_rounds=eng.evict_every)
            jax.block_until_ready(eng.state.free_top)
            self.dm.journal.sync()
            write_epoch(self.dm.dcfg.state_dir, new_epoch)
            self.dm.journal.epoch = new_epoch
            self.promoted = True
        rto = time.monotonic() - t0
        self._c_promotions.inc()
        self._g_epoch.set(new_epoch)
        self._g_rto.set(round(rto, 6))
        log.info(
            "promoted to epoch %d: drained %d durable frames, rto %.3fs",
            new_epoch, drained, rto,
        )
        return {
            "epoch": new_epoch,
            "rto_seconds": rto,
            "drained_frames": drained,
            "applied_seq": self.dm.applied_seq,
            "rpo_durable_frames": 0,
        }

    # -- serving surface ------------------------------------------------

    def healthz(self) -> tuple[bool, dict]:
        """Standby liveness: healthy while fed (or once promoted). The
        ``role`` tag is what the fleet aggregator keys its standby fold
        on (obs/fleet.py); a disconnected un-promoted standby is
        unhealthy — it is not providing the DR it exists for."""
        detail = {
            "role": "standby",
            "promoted": self.promoted,
            "replication_connected": self.connected,
            "journal_epoch": self.dm.journal.epoch,
            "durability": self.dm.status(),
        }
        return (self.promoted or self.connected), detail

    def start_metrics(self, port: int = 0, host: str = "127.0.0.1") -> int:
        from ..obs import MetricsServer

        self._metrics_server = MetricsServer(
            self.registry, health=self.healthz, host=host, port=port,
        )
        return self._metrics_server.start()

    def close(self) -> None:
        self._stop.set()
        if self._lsock is not None:
            self._lsock.close()
        if self._accept_thread is not None and self._accept_thread.is_alive():
            self._accept_thread.join(timeout=5.0)
        if self._metrics_server is not None:
            self._metrics_server.stop()
            self._metrics_server = None
        self.engine.close()
