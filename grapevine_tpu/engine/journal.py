"""Sealed batch journal: append-before-dispatch, replay on recovery.

Every batch the engine admits is appended here — sealed under the
journal subkey of the root key, fsync-batched — *before* it dispatches
to the device, so the journal is always ahead of (or equal to) the
device state. Recovery loads the newest sealed checkpoint and replays
the journal tail through the deterministic engine step; PR-3's
oracle-equality suites are what pin "deterministic given (state,
batch)".

Layout: segment files ``journal-<firstseq>.wal`` in the state dir. A
segment is a concatenation of frames::

    frame  = b"GVJ1" | u64 seq | u32 blob_len | blob
    blob   = nonce(12) | ChaCha20(body) | HMAC-SHA256 tag(32)
             (sealed with aad = the 16-byte frame header, so a frame
             cannot be re-sequenced or length-mangled undetected)
    body   = round: u8 1 | u32 n_real | u32 B | u32 now | u32 now_hi
                    | req_type u32[B] | auth u32[B,8] | msg_id u32[B,4]
                    | recipient u32[B,8] | payload u32[B,PW]
             sweep: u8 2 | u32 now | u32 now_hi | u32 period
             flush: u8 3   (delayed-eviction flush, PR 15 — carries no
                    payload: the flush is deterministic given the state,
                    and replay re-executes it in journal order exactly
                    like rounds and sweeps)

A frame serializes the *whole* fixed-size batch (padding included)
whatever the ops inside are — like the checkpoint, its size and write
pattern are functions of the geometry only, so journaling leaks nothing
the round cadence didn't already (OPERATIONS.md §11).

Torn-tail contract: a crash mid-append leaves a partial (or
tag-invalid) final frame in the final segment — that frame's batch
never dispatched with durability=1, and is discarded with a warning.
Any anomaly *before* the final frame of the final segment (bad magic,
failed tag, sequence gap) is real corruption and raises
:class:`JournalError` — the journal is never half-loaded silently.

At each checkpoint the journal **rolls**: a fresh segment starts at the
next sequence and every older segment (fully covered by the checkpoint)
is deleted. Sequence numbers in frame headers make the crash windows
safe: records at or below the checkpoint seq are simply skipped on
replay wherever they survive.
"""

from __future__ import annotations

import json
import logging
import os
import struct
import time
from typing import Iterator, NamedTuple

import numpy as np

from ..testing import faults
from .state import EngineConfig, ID_WORDS, KEY_WORDS, PAYLOAD_WORDS

log = logging.getLogger("grapevine_tpu.journal")

FRAME_MAGIC = b"GVJ1"
_HEADER = struct.Struct("<4sQI")  # magic, seq, blob_len
_SEAL_OVERHEAD = 12 + 32  # nonce + tag

KIND_ROUND = 1
KIND_SWEEP = 2
KIND_FLUSH = 3

#: round batch columns in serialization order, with their per-op widths
_ROUND_COLS = (
    ("req_type", 1),
    ("auth", KEY_WORDS),
    ("msg_id", ID_WORDS),
    ("recipient", KEY_WORDS),
    ("payload", PAYLOAD_WORDS),
)


class JournalError(RuntimeError):
    """Journal corruption that replay must not paper over."""


# -- epoch fencing (engine/replication.py promote(); OPERATIONS.md §23) --
#
# A promoting standby plants a ``fenced`` marker in the old primary's
# state dir carrying the bumped journal epoch. The marker is created
# O_EXCL, so a double-promote race has exactly one winner; a revived (or
# still-running) stale primary refuses to append the moment it sees an
# epoch newer than its own — the split-brain guard. The promoted
# replica's own dir records its epoch in an ``epoch`` file instead, so
# a later failover chain keeps monotonic generations.

FENCE_FILE = "fenced"
EPOCH_FILE = "epoch"


def fence_path(state_dir: str) -> str:
    return os.path.join(state_dir, FENCE_FILE)


def read_fence(state_dir: str) -> dict | None:
    """The fence marker's payload, or None when the dir is unfenced."""
    try:
        with open(fence_path(state_dir), "r", encoding="utf-8") as fh:
            return json.load(fh)
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as exc:
        # an unreadable fence still fences: fail closed, loudly
        raise JournalError(f"unreadable fence marker: {exc}") from exc


def read_epoch(state_dir: str) -> int:
    """This state dir's journal epoch (0 = never promoted into)."""
    try:
        with open(os.path.join(state_dir, EPOCH_FILE), encoding="utf-8") as fh:
            return int(fh.read().strip() or 0)
    except FileNotFoundError:
        return 0


def write_epoch(state_dir: str, epoch: int) -> None:
    """Durably record this dir's journal epoch (promote() on the
    replica's own dir)."""
    from .checkpoint import write_all

    path = os.path.join(state_dir, EPOCH_FILE)
    tmp = f"{path}.tmp.{os.getpid()}"
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
    try:
        write_all(fd, str(int(epoch)).encode())
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, path)
    dfd = os.open(state_dir, os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def write_fence(state_dir: str, epoch: int, fingerprint: str) -> dict:
    """Fence a (presumed dead) primary's state dir at ``epoch``.

    O_EXCL: in a double-promote race exactly one caller returns; the
    loser gets a hard JournalError and must not serve."""
    from .checkpoint import write_all

    payload = {"epoch": int(epoch), "fingerprint": fingerprint,
               "fenced_unix": int(time.time())}
    path = fence_path(state_dir)
    try:
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
    except FileExistsError:
        existing = read_fence(state_dir)
        raise JournalError(
            f"journal already fenced at epoch "
            f"{existing.get('epoch') if existing else '?'} — another "
            "replica won the promotion race; this one must not serve"
        ) from None
    try:
        write_all(fd, json.dumps(payload).encode())
        os.fsync(fd)
    finally:
        os.close(fd)
    dfd = os.open(state_dir, os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)
    return payload


class JournalRecord(NamedTuple):
    seq: int
    kind: int  # KIND_ROUND | KIND_SWEEP
    batch: dict | None  # round: the pack_batch-shaped device dict
    n_real: int  # round: real (non-padding) ops
    now: int  # sweep: u64 low lane
    now_hi: int  # sweep: u64 high lane
    period: int  # sweep: expiry period


def _segment_first_seq(name: str) -> int | None:
    if name.startswith("journal-") and name.endswith(".wal"):
        try:
            return int(name[len("journal-") : -len(".wal")])
        except ValueError:
            return None
    return None


class BatchJournal:
    """One engine's sealed write-ahead journal (see module docstring).

    Not internally locked: every call runs under the engine lock
    (appends are serialized with the rounds they precede)."""

    def __init__(self, state_dir: str, root_key: bytes,
                 ecfg: EngineConfig, fsync_every: int = 1, on_fsync=None):
        self.state_dir = state_dir
        self.root_key = root_key
        self.ecfg = ecfg
        self.fsync_every = max(1, int(fsync_every))
        self.on_fsync = on_fsync
        #: last sequence appended or observed during replay
        self.seq = 0
        #: last sequence known fsynced (machine-crash durable; a mere
        #: process crash also keeps everything written, via page cache)
        self.durable_seq = 0
        self._fd: int | None = None
        self._since_fsync = 0
        self._tail: tuple[str, int] | None = None  # (path, valid_end)
        self._cur_path: str | None = None  # segment open for append
        self._scanned = False
        #: journal generation this writer serves under (epoch file in
        #: the state dir, bumped by a promoting standby). An append is
        #: refused the moment a fence marker with a newer epoch appears
        #: — the split-brain guard (engine/replication.py promote()).
        self.epoch = read_epoch(state_dir)
        #: replication doorbell: ``on_append(seq, frame_bytes)`` called
        #: after each frame lands in the file (page-cache durable — the
        #: same durability a SIGKILL leaves behind). Runs under the
        #: engine lock with the append, so it must only enqueue/signal,
        #: never block on I/O (engine/replication.py JournalShipper).
        self.on_append = None
        #: the only two legal blob lengths for this geometry (round
        #: bodies are constant-size given B; sweeps are fixed). Replay
        #: uses this to tell a corrupted length field (raise) from a
        #: genuinely truncated final frame (torn tail, discard).
        round_body = 17 + 4 * ecfg.batch_size * sum(
            w for _, w in _ROUND_COLS
        )
        # RANGELINT_BOUNDS (host prong, analysis/rangelint.py): the
        # frame header's blob_len is u32 on the wire. Host-side byte
        # products are unbounded Python ints, so the one real ceiling
        # is this format field — refuse at construction rather than
        # truncate a frame length at append time (a torn-tail that
        # replay could never tell from corruption). ~2^20-op batches of
        # 2 KiB records are still an order of magnitude below it.
        if round_body + _SEAL_OVERHEAD > 0xFFFFFFFF:
            raise ValueError(
                f"journal frame for batch_size {ecfg.batch_size} would "
                f"be {round_body + _SEAL_OVERHEAD} bytes — past the u32 "
                "blob_len wire field (rangelint certified bound, "
                "OPERATIONS.md §18); shard the batch instead"
            )
        self._valid_blob_lens = frozenset(
            body + _SEAL_OVERHEAD for body in (round_body, 13, 1)
        )

    # -- codec ----------------------------------------------------------

    def _encode_round(self, batch: dict, n_real: int) -> bytes:
        b = self.ecfg.batch_size
        if int(batch["req_type"].shape[0]) != b:
            raise ValueError(
                f"batch rows {batch['req_type'].shape[0]} != batch_size {b}"
            )
        parts = [struct.pack(
            "<BIIII", KIND_ROUND, n_real, b,
            int(batch["now"]), int(batch.get("now_hi", 0)),
        )]
        for name, words in _ROUND_COLS:
            arr = np.ascontiguousarray(np.asarray(batch[name]), dtype="<u4")
            if arr.size != b * words:
                raise ValueError(
                    f"batch column {name!r}: {arr.size} words, "
                    f"want {b * words}"
                )
            parts.append(arr.tobytes())
        return b"".join(parts)

    def _decode_body(self, seq: int, body: bytes) -> JournalRecord:
        if not body:
            raise JournalError(f"journal frame {seq}: empty body")
        kind = body[0]
        if kind == KIND_SWEEP:
            if len(body) != 13:
                raise JournalError(
                    f"journal frame {seq}: sweep body is {len(body)} bytes"
                )
            now, now_hi, period = struct.unpack_from("<III", body, 1)
            return JournalRecord(seq, KIND_SWEEP, None, 0, now, now_hi, period)
        if kind == KIND_FLUSH:
            if len(body) != 1:
                raise JournalError(
                    f"journal frame {seq}: flush body is {len(body)} bytes"
                )
            return JournalRecord(seq, KIND_FLUSH, None, 0, 0, 0, 0)
        if kind != KIND_ROUND:
            raise JournalError(f"journal frame {seq}: unknown kind {kind}")
        n_real, b, now, now_hi = struct.unpack_from("<IIII", body, 1)
        if b != self.ecfg.batch_size:
            raise JournalError(
                f"journal frame {seq}: batch_size {b} does not match this "
                f"engine's {self.ecfg.batch_size} — replay requires the "
                "identical geometry the journal was written under"
            )
        off = 17
        batch: dict = {}
        for name, words in _ROUND_COLS:
            nbytes = b * words * 4
            if off + nbytes > len(body):
                raise JournalError(
                    f"journal frame {seq}: column {name!r} cut short"
                )
            arr = np.frombuffer(body, "<u4", count=b * words, offset=off)
            arr = arr.astype(np.uint32)  # native order, writable copy
            batch[name] = arr.reshape(b, words) if words > 1 else arr
            off += nbytes
        if off != len(body):
            raise JournalError(
                f"journal frame {seq}: {len(body) - off} trailing bytes"
            )
        batch["now"] = np.uint32(now)
        batch["now_hi"] = np.uint32(now_hi)
        return JournalRecord(seq, KIND_ROUND, batch, n_real, now, now_hi, 0)

    # -- replay ---------------------------------------------------------

    def _segments(self) -> list[tuple[int, str]]:
        out = []
        for name in os.listdir(self.state_dir):
            first = _segment_first_seq(name)
            if first is not None:
                out.append((first, os.path.join(self.state_dir, name)))
        return sorted(out)

    def replay(self, after_seq: int = 0) -> Iterator[JournalRecord]:
        """Yield decoded records with seq > ``after_seq`` across all
        segments, oldest first, enforcing sequence contiguity. Tolerates
        exactly one torn/invalid *final* frame in the *final* segment;
        anything else raises JournalError. Must run (to exhaustion)
        before :meth:`open_for_append`."""
        from .checkpoint import SealError, unseal

        segments = self._segments()
        self.seq = after_seq
        self._tail = None
        self._scanned = True
        expected = None
        for si, (_, path) in enumerate(segments):
            last_seg = si == len(segments) - 1
            with open(path, "rb") as fh:
                data = fh.read()
            off = 0
            if last_seg:
                self._tail = (path, 0)
            while off < len(data):
                # parse one frame; on anomaly decide torn tail vs
                # corrupt. A torn write leaves a PREFIX of a valid
                # frame at EOF — anything else (full header present but
                # wrong magic or an impossible length, bad tag with
                # frames after it) is corruption and must raise, never
                # silently truncate durable frames.
                anomaly, mid_file, body, end, seq = None, False, b"", off, -1
                if off + _HEADER.size > len(data):
                    anomaly = "partial frame header"
                    mid_file = not FRAME_MAGIC.startswith(
                        data[off : off + len(FRAME_MAGIC)]
                    )
                else:
                    magic, seq, blob_len = _HEADER.unpack_from(data, off)
                    if magic != FRAME_MAGIC:
                        anomaly = "bad frame magic"
                        mid_file = True  # full header present: not a prefix
                    elif blob_len not in self._valid_blob_lens:
                        anomaly = (
                            f"frame {seq}: impossible blob length "
                            f"{blob_len} (legal: "
                            f"{sorted(self._valid_blob_lens)})"
                        )
                        mid_file = True
                    else:
                        end = off + _HEADER.size + blob_len
                        if end > len(data):
                            anomaly = f"frame {seq} cut short"
                        else:
                            header = data[off : off + _HEADER.size]
                            try:
                                body = unseal(
                                    self.root_key, b"journal",
                                    data[off + _HEADER.size : end],
                                    aad=header,
                                )
                            except SealError as exc:
                                anomaly = (
                                    f"frame {seq} failed its integrity "
                                    f"check: {exc}"
                                )
                                # a torn write truncates the file — a
                                # complete frame with bytes after it is
                                # not a crash artifact
                                mid_file = end < len(data)
                if anomaly is not None:
                    if last_seg and not mid_file:
                        log.warning(
                            "discarding torn journal tail (%s@%d: %s) — "
                            "the batch in it never became durable",
                            path, off, anomaly,
                        )
                        break
                    raise JournalError(f"{path}@{off}: {anomaly}")
                if seq > after_seq:
                    if expected is None:
                        if seq != after_seq + 1:
                            raise JournalError(
                                f"{path}@{off}: journal starts at seq "
                                f"{seq} but the checkpoint covers "
                                f"{after_seq} — missing segment(s)"
                            )
                    elif seq != expected:
                        raise JournalError(
                            f"{path}@{off}: sequence gap (frame {seq}, "
                            f"expected {expected})"
                        )
                    expected = seq + 1
                    self.seq = seq
                    yield self._decode_body(seq, body)
                off = end
                if last_seg:
                    self._tail = (path, off)
        self.durable_seq = self.seq

    def _read_segment(self, path: str) -> bytes:
        """Follower-path segment read with bounded-backoff retry on
        transient errors (EIO from a flaky mount and friends). A
        vanished file propagates FileNotFoundError — the scan loop
        rescans the directory, because a roll/prune racing the reader
        is normal, not an error."""
        delay = 0.01
        for attempt in range(4):
            try:
                with open(path, "rb") as fh:
                    return fh.read()
            except FileNotFoundError:
                raise
            except OSError as exc:
                if attempt == 3:
                    raise JournalError(
                        f"{path}: transient read errors exhausted: {exc}"
                    ) from exc
                time.sleep(delay)
                delay *= 2
        raise AssertionError("unreachable")

    def _follow_scan(self, after_seq: int):
        """Hardened live-tail scan shared by :meth:`follow` and
        :meth:`follow_frames`: yield ``(seq, body, frame_bytes)`` for
        every frame with seq > ``after_seq``, oldest first, stopping
        silently at the physical tail.

        Liveness contract (ISSUE 19):

        - a torn/incomplete FINAL frame at the physical end of the
          final segment means "not yet durable — poll again", never an
          error (the writer is mid-append, or died mid-append; either
          way the bytes may still arrive or be truncated at the
          writer's next open);
        - a segment roll or checkpoint-prune racing the reader triggers
          a directory rescan — segments the reader already consumed may
          vanish freely; only genuinely missing data (the reader fell
          behind the prune horizon) raises;
        - transient read errors retry with bounded backoff before
          raising (:meth:`_read_segment`).

        Mid-file anomalies are still corruption and raise exactly like
        :meth:`replay`."""
        from .checkpoint import SealError, unseal

        rescans = 0
        while True:
            try:
                segments = self._segments()
            except OSError as exc:
                rescans += 1
                if rescans > 8:
                    raise JournalError(
                        f"{self.state_dir}: directory scan errors "
                        f"exhausted: {exc}"
                    ) from exc
                time.sleep(0.01 * rescans)
                continue
            # drop segments the reader has fully consumed: segment i is
            # fully covered when its successor starts at or below
            # after_seq + 1 (so a prune deleting it cannot matter)
            while len(segments) > 1 and segments[1][0] <= after_seq + 1:
                segments.pop(0)
            if segments and segments[0][0] > after_seq + 1:
                raise JournalError(
                    f"follower at seq {after_seq} fell behind the prune "
                    f"horizon — the earliest live segment starts at "
                    f"{segments[0][0]}; re-bootstrap from a checkpoint"
                )
            try:
                for si, (_, path) in enumerate(segments):
                    last_seg = si == len(segments) - 1
                    data = self._read_segment(path)
                    off = 0
                    while off < len(data):
                        anomaly, mid_file = None, False
                        body, end, seq = b"", off, -1
                        if off + _HEADER.size > len(data):
                            anomaly = "partial frame header"
                            mid_file = not FRAME_MAGIC.startswith(
                                data[off : off + len(FRAME_MAGIC)]
                            )
                        else:
                            magic, seq, blob_len = _HEADER.unpack_from(
                                data, off
                            )
                            if magic != FRAME_MAGIC:
                                anomaly = "bad frame magic"
                                mid_file = True
                            elif blob_len not in self._valid_blob_lens:
                                anomaly = (
                                    f"frame {seq}: impossible blob "
                                    f"length {blob_len}"
                                )
                                mid_file = True
                            else:
                                end = off + _HEADER.size + blob_len
                                if end > len(data):
                                    anomaly = f"frame {seq} cut short"
                                else:
                                    header = data[off : off + _HEADER.size]
                                    try:
                                        body = unseal(
                                            self.root_key, b"journal",
                                            data[off + _HEADER.size : end],
                                            aad=header,
                                        )
                                    except SealError as exc:
                                        anomaly = (
                                            f"frame {seq} failed its "
                                            f"integrity check: {exc}"
                                        )
                                        mid_file = end < len(data)
                        if anomaly is not None:
                            if last_seg and not mid_file:
                                # physical tail not yet durable: poll
                                # again on the next call — never an
                                # error, never a warning per poll
                                log.debug(
                                    "follow: tail not yet durable "
                                    "(%s@%d: %s)", path, off, anomaly,
                                )
                                return
                            raise JournalError(f"{path}@{off}: {anomaly}")
                        if seq > after_seq:
                            if seq != after_seq + 1:
                                raise JournalError(
                                    f"{path}@{off}: sequence gap (frame "
                                    f"{seq}, expected {after_seq + 1})"
                                )
                            yield seq, body, data[off:end]
                            after_seq = seq
                            rescans = 0
                        off = end
                return
            except FileNotFoundError:
                # roll/prune raced the reader between listdir and open —
                # rescan; data that is genuinely gone trips the prune-
                # horizon check above on the next pass
                rescans += 1
                if rescans > 8:
                    raise JournalError(
                        f"{self.state_dir}: segments kept vanishing "
                        "mid-scan across 8 rescans"
                    ) from None
                continue

    def follow(self, after_seq: int = 0) -> Iterator[JournalRecord]:
        """Read-only replication tail: yield decoded records with seq >
        ``after_seq`` for a follower that will never append — apply
        them to standby state and report progress via
        ``DurabilityManager.note_applied_seq`` (the
        ``grapevine_journal_applied_seq`` gauge the fleet aggregator
        turns into replication lag; OPERATIONS.md §20/§23). Each call
        rescans the directory, so repeated calls pick up newly written
        frames and freshly rolled segments; a torn final frame is
        skipped this call and retried on the next (see
        :meth:`_follow_scan` for the full liveness contract)."""
        if self._fd is not None:
            raise RuntimeError(
                "follow() is for read-only followers; this journal is "
                "open for append"
            )
        for seq, body, _frame in self._follow_scan(after_seq):
            yield self._decode_body(seq, body)

    def follow_frames(self, after_seq: int = 0) -> Iterator[tuple[int, bytes]]:
        """Raw shipping tail: ``(seq, frame_bytes)`` with seq >
        ``after_seq``, integrity-verified but not decoded — the
        JournalShipper streams these bytes verbatim and the standby
        re-journals them as-is (engine/replication.py). Same liveness
        contract as :meth:`follow`."""
        if self._fd is not None:
            raise RuntimeError(
                "follow_frames() is for read-only followers; this "
                "journal is open for append"
            )
        for seq, _body, frame in self._follow_scan(after_seq):
            yield seq, frame

    # -- append ---------------------------------------------------------

    def open_for_append(self) -> None:
        """Open the journal for appends after :meth:`replay`: truncate
        the final segment past its last valid frame (torn tails die
        here), or start a fresh segment when none exists."""
        if not self._scanned:
            raise RuntimeError("replay() must run before open_for_append()")
        if self._fd is not None:
            return
        # a revived stale primary must refuse HERE, before it truncates
        # the tail a promoted replica already drained (split-brain guard)
        self._check_fence()
        if self._tail is not None:
            path, valid_end = self._tail
            self._fd = os.open(path, os.O_WRONLY)
            os.ftruncate(self._fd, valid_end)
            os.lseek(self._fd, 0, os.SEEK_END)
            self._cur_path = path
        else:
            self._create_segment(self.seq + 1)
        self._since_fsync = 0

    def _create_segment(self, first_seq: int) -> None:
        path = os.path.join(self.state_dir, f"journal-{first_seq:016d}.wal")
        self._fd = os.open(
            path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600
        )
        self._tail = (path, 0)
        self._cur_path = path
        dfd = os.open(self.state_dir, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)

    def _check_fence(self) -> None:
        """Refuse to write under a newer epoch's fence (one stat per
        append — noise next to the seal + write it guards)."""
        fence = read_fence(self.state_dir)
        if fence is not None and int(fence.get("epoch", 0)) > self.epoch:
            raise JournalError(
                f"journal fenced: epoch {fence['epoch']} supersedes this "
                f"writer's epoch {self.epoch} — a standby promoted and "
                "owns the sequence now; refusing append (split-brain "
                "guard, OPERATIONS.md §23)"
            )

    def _append(self, body: bytes) -> int:
        from .checkpoint import seal, write_all

        if self._fd is None:
            raise RuntimeError("journal not open for append")
        self._check_fence()
        seq = self.seq + 1
        blob_len = len(body) + _SEAL_OVERHEAD
        header = _HEADER.pack(FRAME_MAGIC, seq, blob_len)
        frame = header + seal(self.root_key, b"journal", body, aad=header)
        if faults.active():
            faults.crash("journal.append.pre")
            if faults.hit("journal.append.torn"):
                write_all(self._fd, frame[: len(frame) // 2])
                os.fsync(self._fd)
                faults.die()
        write_all(self._fd, frame)
        if faults.active():
            faults.crash("journal.append.post_write")
        self.seq = seq
        if self.on_append is not None:
            # replication doorbell: frame bytes are page-cache durable
            # (what a SIGKILL leaves behind), so shipping pre-fsync
            # keeps the standby at most the fsync batch behind
            self.on_append(seq, frame)
        self._since_fsync += 1
        if self._since_fsync >= self.fsync_every:
            self.sync()
        if faults.active():
            faults.crash("journal.append.post_fsync")
        return seq

    def append_round(self, batch: dict, n_real: int) -> int:
        return self._append(self._encode_round(batch, n_real))

    def append_sweep(self, now: int, now_hi: int, period: int) -> int:
        return self._append(
            struct.pack("<BIII", KIND_SWEEP, now, now_hi, period)
        )

    def append_flush(self) -> int:
        """Delayed-eviction flush marker: no payload — the flush is a
        deterministic function of the state, so the record only fixes
        its position in the replay order."""
        return self._append(struct.pack("<B", KIND_FLUSH))

    def append_raw(self, seq: int, frame: bytes) -> int:
        """Follower-side append of a shipped frame verbatim (the bytes
        the primary wrote, seal and all — the standby verified the seal
        when it decoded the frame for apply). Contiguity and header
        consistency are enforced here so a shipping bug can never write
        a gap or a mislabeled frame the next recovery would refuse."""
        from .checkpoint import write_all

        if self._fd is None:
            raise RuntimeError("journal not open for append")
        self._check_fence()
        if seq != self.seq + 1:
            raise JournalError(
                f"raw append out of order: frame {seq}, journal at "
                f"{self.seq}"
            )
        if len(frame) < _HEADER.size:
            raise JournalError(f"raw append: frame {seq} shorter than a header")
        magic, hseq, blob_len = _HEADER.unpack_from(frame, 0)
        if (
            magic != FRAME_MAGIC
            or hseq != seq
            or blob_len not in self._valid_blob_lens
            or len(frame) != _HEADER.size + blob_len
        ):
            raise JournalError(
                f"raw append: malformed frame for seq {seq} "
                f"(header seq {hseq}, {len(frame)} bytes)"
            )
        write_all(self._fd, frame)
        self.seq = seq
        self._since_fsync += 1
        if self._since_fsync >= self.fsync_every:
            self.sync()
        return seq

    def sync(self) -> None:
        """fsync pending appends (the durability barrier)."""
        if self._fd is not None and self._since_fsync:
            os.fsync(self._fd)
            self._since_fsync = 0
            self.durable_seq = self.seq
            if self.on_fsync is not None:
                self.on_fsync(self.durable_seq)

    def roll(self) -> None:
        """Start a fresh segment at the next seq and delete the older
        ones — called only after a checkpoint covering ``self.seq`` is
        durably on disk."""
        self.sync()
        current = os.path.join(
            self.state_dir, f"journal-{self.seq + 1:016d}.wal"
        )
        if self._cur_path != current:
            # the usual case; equality means nothing was appended since
            # the last roll (e.g. a drain checkpoint right after one) —
            # the fresh segment is already in place
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None
            self._create_segment(self.seq + 1)
        for _, path in self._segments():
            if path != current:
                try:
                    os.unlink(path)
                except OSError:  # pragma: no cover - concurrent cleanup
                    pass

    def close(self) -> None:
        if self._fd is not None:
            self.sync()
            os.close(self._fd)
            self._fd = None
