"""Expiry sweep: full-tree timestamped eviction (reference README.md:86-98).

One jit'd data-independent pass over both ORAMs (the access pattern is
the whole tree — revealing nothing): records older than the expiry period
are invalidated, their mailbox entries cleared, emptied mailboxes release
their recipient slot, and the free-block list is rebuilt. The reference
MVP never finished hashmap eviction (README.md:98-99); this completes it.

Timestamps come from the untrusted host clock, as in the reference
(README.md:92-97); a tampered clock can evict early/late but the sweep
touches every bucket regardless, so it cannot reveal sender/recipient
linkage.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..oblivious.primitives import SENTINEL, is_zero_words
from ..oram.path_oram import decrypt_tree, encrypt_tree
from .state import ENT_SEQ, ENT_TS, EngineConfig, EngineState, REC_TS

U32 = jnp.uint32


def _expired(ts: jnp.ndarray, now, period) -> jnp.ndarray:
    """Strict '>' age test, matching the oracle (now - ts > period).

    Guarded against u32 wraparound: a record stamped *ahead* of the sweep
    clock (NTP step-back, caller-supplied smaller ``now``) must never be
    treated as ancient — the oracle's signed comparison keeps it, so we
    must too."""
    return (ts <= now) & ((now - ts) > period)


def expiry_sweep(ecfg: EngineConfig, state: EngineState, now, period) -> EngineState:
    now = U32(now)
    period = U32(period)

    # at-rest bucket cipher: the sweep is a whole-tree pass (uniform
    # transcript), so decrypt both trees up front and re-encrypt them
    # under a fresh epoch at the end (oram/path_oram.py helpers, chunked)
    state = state._replace(
        rec=decrypt_tree(ecfg.rec, state.rec),
        mb=decrypt_tree(ecfg.mb, state.mb),
    )

    # --- records ORAM: invalidate expired blocks -----------------------
    def sweep_records(idx, ts):
        live = idx != SENTINEL
        dead = live & _expired(ts, now, period)
        return jnp.where(dead, SENTINEL, idx)

    rec = state.rec
    z, v = ecfg.rec.bucket_slots, ecfg.rec.value_words
    # tree_idx is flat [n*Z]; per-slot timestamps are a V-strided slice
    # of the [n, Z*V] value rows — no relayout of the big array
    rec_tree_idx = sweep_records(
        rec.tree_idx.reshape(-1, z), rec.tree_val[:, REC_TS::v][:, :z]
    )
    rec_stash_idx = sweep_records(rec.stash_idx, rec.stash_val[:, REC_TS])
    rec = rec._replace(
        tree_idx=rec_tree_idx.reshape(-1), stash_idx=rec_stash_idx
    )

    # --- mailbox ORAM: clear expired entries, drop empty mailboxes -----
    def sweep_mb(idx, val):
        # idx: [...]; val: tree [n, Z*V] or stash [S, V] — one block per
        # idx entry either way once flattened to rows of V words
        lead = idx.shape
        k, cap = ecfg.mb_slots, ecfg.mailbox_cap
        flat = val.reshape((-1, k * (8 + 4 * cap)))
        keys = flat.reshape(-1, k, 8 + 4 * cap)[:, :, :8]
        entries = flat.reshape(-1, k, 8 + 4 * cap)[:, :, 8:].reshape(-1, k, cap, 4)
        valid = entries[..., ENT_SEQ] != 0
        dead = valid & _expired(entries[..., ENT_TS], now, period)
        entries = jnp.where(dead[..., None], jnp.zeros((4,), U32), entries)
        mbox_live = jnp.any(entries[..., ENT_SEQ] != 0, axis=-1)  # [n, k]
        keys = jnp.where(mbox_live[..., None], keys, jnp.zeros((8,), U32))
        out = jnp.concatenate(
            [keys, entries.reshape(-1, k, cap * 4)], axis=-1
        ).reshape(flat.shape)
        # blocks with no live mailbox leave the ORAM entirely
        any_key = jnp.any(
            ~is_zero_words(keys.reshape(-1, k, 8)).reshape(-1, k), axis=-1
        ).reshape(lead)
        new_idx = jnp.where(idx != SENTINEL, jnp.where(any_key, idx, SENTINEL), idx)
        return new_idx, out.reshape(val.shape), keys.reshape(lead + (k, 8))

    mb = state.mb
    zm = ecfg.mb.bucket_slots
    mb_tree_idx, mb_tree_val, tree_keys = sweep_mb(
        mb.tree_idx.reshape(-1, zm), mb.tree_val
    )
    mb_stash_idx, mb_stash_val, stash_keys = sweep_mb(mb.stash_idx, mb.stash_val)
    mb = mb._replace(
        tree_idx=mb_tree_idx.reshape(-1),
        tree_val=mb_tree_val,
        stash_idx=mb_stash_idx,
        stash_val=mb_stash_val,
    )

    # --- recount live recipients (keys survive only in live blocks) ----
    def live_keys(keys, idx):
        lead_live = idx != SENTINEL
        kv = ~is_zero_words(keys)
        return jnp.sum(kv & lead_live[..., None])

    recipients = (
        live_keys(tree_keys, mb_tree_idx) + live_keys(stash_keys, mb_stash_idx)
    ).astype(U32)

    # --- rebuild the free-block list from surviving record indices -----
    n = ecfg.max_messages
    present = jnp.zeros((n,), jnp.bool_)
    for idx in (rec_tree_idx.reshape(-1), rec_stash_idx.reshape(-1)):
        safe = jnp.where(idx != SENTINEL, idx, n)  # OOB drops
        present = present.at[safe].set(True, mode="drop")
    order = jnp.argsort(present, stable=True)  # free (False) indices first
    freelist = order.astype(U32)
    free_top = (n - jnp.sum(present)).astype(U32)

    return state._replace(
        rec=encrypt_tree(ecfg.rec, rec),
        mb=encrypt_tree(ecfg.mb, mb),
        freelist=freelist,
        free_top=free_top,
        recipients=recipients,
    )
