"""Expiry sweep: full-tree timestamped eviction (reference README.md:86-98).

One jit'd data-independent pass over both ORAMs (the access pattern is
the whole tree — revealing nothing): records older than the expiry period
are invalidated, their mailbox entries cleared, emptied mailboxes release
their recipient slot, and the free-block list is rebuilt. The reference
MVP never finished hashmap eviction (README.md:98-99); this completes it.

Timestamps come from the untrusted host clock, as in the reference
(README.md:92-97); a tampered clock can evict early/late but the sweep
touches every bucket regardless, so it cannot reveal sender/recipient
linkage.

With the at-rest bucket cipher enabled, each tree is processed in row
chunks under ``lax.scan``: decrypt chunk → expire → re-encrypt under the
next epoch, all inside one scan body — at no point does more than one
chunk of plaintext exist in HBM (a mid-sweep memory snapshot exposes at
most ~8 M words, not the bus).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..oblivious.bucket_cipher import epoch_next, row_keystream
from ..oblivious.primitives import SENTINEL, is_zero_words, u64_le, u64_sub
from ..oblivious.radix import partition_rank
from ..obs.phases import device_phase
from ..oram.path_oram import OramConfig, OramState
from .state import (
    ENT_SEQ,
    ENT_SEQH,
    ENT_TS,
    ENT_TSH,
    ENTRY_WORDS,
    EngineConfig,
    EngineState,
    KEY_WORDS,
    REC_TS,
    REC_TSH,
)

U32 = jnp.uint32

#: oblint taint anchors (analysis/oblint.py): the secret inputs of one
#: ``expiry_sweep(ecfg, state, now, period, now_hi)`` — THE SAME
#: private-plane/key/freelist anchors as the engine round, imported
#: from round_step so a new private plane cannot be tainted in one
#: audit and forgotten in the other; the sweep's chunk walk itself is
#: iota-driven and must stay untainted. ``now``/``period`` are the
#: untrusted host clock: public.
from .round_step import _tree_secrets as _rs_tree_secrets  # noqa: E402

OBLINT_SECRETS = (
    _rs_tree_secrets("state.rec")
    + _rs_tree_secrets("state.mb")
    + ("state.freelist", "state.hash_key", "state.id_key", "state.rng")
)


def RANGELINT_BOUNDS(ecfg: EngineConfig) -> dict:
    """Rangelint input-interval anchors for ``expiry_sweep(ecfg, state,
    now, period, now_hi)``: the same per-plane state invariants as the
    engine round (imported, so a new plane cannot be bounded in one
    audit and forgotten in the other). ``now``/``period`` are the
    untrusted host clock — full lane, never assumed. The sweep's own
    counters (chunk liveness, recipient recount) are *derived* bounded:
    the scan-carry fixpoint extrapolates their per-chunk budget over
    the chunk count, which tops out at total tree slots ≪ 2^32 at
    every certified geometry."""
    from .round_step import RANGELINT_BOUNDS as _rs_bounds

    return _rs_bounds(ecfg)


def _expired(ts_lo, ts_hi, now_lo, now_hi, period) -> jnp.ndarray:
    """Strict '>' age test over u64 lane pairs (now - ts > period).

    Guarded against wraparound: a record stamped *ahead* of the sweep
    clock (NTP step-back, caller-supplied smaller ``now``) must never be
    treated as ancient — the oracle's signed comparison keeps it, so we
    must too."""
    le = u64_le(ts_lo, ts_hi, now_lo, now_hi)
    d_lo, d_hi = u64_sub(now_lo, now_hi, ts_lo, ts_hi)
    return le & ((d_hi > 0) | (d_lo > period))


def _chunk_rows(cfg: OramConfig) -> int:
    """Rows per scan chunk: power of two, ~8M words of keystream."""
    n = cfg.n_buckets_padded
    rpc = 1
    while rpc * 2 <= n and rpc * 2 * cfg.row_words <= (1 << 23):
        rpc *= 2
    return rpc


def _chunked_tree_sweep(cfg: OramConfig, oram: OramState, carry0, body):
    """Run ``body(carry, (plaintext idx [rpc, Z], plaintext val
    [rpc, Z*V])) -> (carry, (idx', val'))`` over the whole tree in
    chunks, with per-chunk decrypt/re-encrypt when the cipher is on.
    Returns (carry, OramState with new tree + nonces/epoch advanced).

    A recursive position map (cfg.posmap set, oram/posmap.py) adds the
    per-slot leaf-metadata plane, encrypted under the same per-bucket
    nonces as the idx/val rows: the sweep re-keys every nonce, so the
    plane must be decrypt/re-encrypted in the same pass — its values
    never change (expiry only kills blocks; dead slots are masked by
    the SENTINEL idx), but its ciphertext epoch must follow the bucket.
    """
    z, v = cfg.bucket_slots, cfg.value_words
    n = cfg.n_buckets_padded
    rpc = _chunk_rows(cfg)
    nch = n // rpc
    bids = jnp.arange(n, dtype=U32).reshape(nch, rpc)
    idx3 = oram.tree_idx.reshape(nch, rpc, z)
    val3 = oram.tree_val.reshape(nch, rpc, z * v)
    eps = oram.nonces.reshape(nch, rpc, 2)
    recrypt_leaf = cfg.posmap is not None and cfg.encrypted
    leaf3 = (
        oram.tree_leaf.reshape(nch, rpc, z)
        if recrypt_leaf
        else jnp.zeros((nch, rpc, 0), U32)
    )

    delayed = cfg.delayed_eviction
    tag3 = (
        oram.fetch_tag.reshape(nch, rpc)
        if delayed
        else jnp.zeros((nch, rpc), U32)
    )

    def scan_body(carry, xs):
        bid, ix, vl, ep, lf, tg = xs
        if cfg.encrypted:
            ks = row_keystream(
                oram.cipher_key, bid, ep, cfg.row_words, cfg.cipher_rounds
            )
            ix = ix ^ ks[:, :z]
            vl = vl ^ ks[:, z:]
        if delayed:
            # delayed eviction (PR 15): buckets fetched since the last
            # flush hold stale copies — their live rows moved to the
            # eviction buffer (swept separately, like the stash).
            # Masking them here keeps liveness/recipient counts exact
            # AND performs the tree-side invalidation for free: the
            # re-encrypt below writes the cleaned rows back, and the
            # next flush overwrites these buckets anyway.
            stale = tg == oram.ebuf_gen
            ix = jnp.where(stale[:, None], SENTINEL, ix)
        carry, (ix, vl) = body(carry, (ix, vl))
        if cfg.encrypted:
            epn = jnp.broadcast_to(oram.epoch[None, :], (rpc, 2))
            ks = row_keystream(
                oram.cipher_key, bid, epn, cfg.row_words, cfg.cipher_rounds
            )
            ix = ix ^ ks[:, :z]
            vl = vl ^ ks[:, z:]
            if recrypt_leaf:
                # leaf-plane stream: same (bucket, epoch), bucket word
                # offset by n_buckets_padded (path_oram.leaf_plane_cipher
                # domain separation)
                boff = bid + U32(cfg.n_buckets_padded)
                lf = lf ^ row_keystream(
                    oram.cipher_key, boff, ep, z, cfg.cipher_rounds
                )
                lf = lf ^ row_keystream(
                    oram.cipher_key, boff, epn, z, cfg.cipher_rounds
                )
        return carry, (ix, vl, lf)

    carry, (idx_o, val_o, leaf_o) = jax.lax.scan(
        scan_body, carry0, (bids, idx3, val3, eps, leaf3, tag3)
    )
    new = oram._replace(
        tree_idx=idx_o.reshape(-1), tree_val=val_o.reshape(n, z * v)
    )
    if recrypt_leaf:
        new = new._replace(tree_leaf=leaf_o.reshape(-1))
    if cfg.encrypted:
        new = new._replace(
            nonces=jnp.broadcast_to(oram.epoch[None, :], oram.nonces.shape),
            epoch=epoch_next(oram.epoch),
        )
    return carry, new


def expiry_sweep(
    ecfg: EngineConfig, state: EngineState, now, period, now_hi=0
) -> EngineState:
    now = U32(now)
    now_hi = U32(now_hi)
    period = U32(period)

    # --- records ORAM: invalidate expired blocks, gather liveness ------
    rcfg = ecfg.rec
    v = rcfg.value_words
    n_msgs = ecfg.max_messages

    def rec_body(present, xs):
        ix, vl = xs  # [rpc, Z], [rpc, Z*V] plaintext
        ts_lo = vl[:, REC_TS::v][:, : rcfg.bucket_slots]
        ts_hi = vl[:, REC_TSH::v][:, : rcfg.bucket_slots]
        live = ix != SENTINEL
        dead = live & _expired(ts_lo, ts_hi, now, now_hi, period)
        ix = jnp.where(dead, SENTINEL, ix)
        # decrypted slot ids are opaque to interval reasoning; the min
        # keeps the liveness index inside the int32 scatter lane
        # (garbage >= n_msgs still drops — same OOB row as the sentinel)
        safe = jnp.minimum(
            jnp.where(ix != SENTINEL, ix, U32(n_msgs)), U32(n_msgs)
        ).reshape(-1)
        present = present.at[safe].set(True, mode="drop")
        return present, (ix, vl)

    present0 = jnp.zeros((n_msgs,), jnp.bool_)
    with device_phase("sweep_records"):
        present, rec = _chunked_tree_sweep(rcfg, state.rec, present0, rec_body)

        # tree-top cache planes (cfg.top_cache_levels > 0): the cached
        # top buckets' live blocks exist ONLY here — their HBM rows are
        # stale empty ciphertext, which the chunked pass above decrypts
        # to empty rows and re-keys harmlessly. The cache is plaintext
        # private state (stash standing), so it sweeps exactly like the
        # stash: no cipher, no re-key, same expire body.
        if rcfg.top_cache_levels:
            zc = rcfg.bucket_slots
            cidx = rec.cache_idx.reshape(-1, zc)
            if rcfg.delayed_eviction:
                # stale cached buckets' live rows are in the buffer
                stale_c = (
                    rec.fetch_tag[: rcfg.cache_buckets] == rec.ebuf_gen
                )
                cidx = jnp.where(stale_c[:, None], SENTINEL, cidx)
            present, (cix, cvl) = rec_body(
                present,
                (cidx, rec.cache_val),
            )
            rec = rec._replace(cache_idx=cix.reshape(-1), cache_val=cvl)

    # stash (and, under delayed eviction, the eviction buffer — same
    # plaintext private standing) rows sweep directly
    def rec_private_sweep(pidx, pval):
        live = pidx != SENTINEL
        dead = live & _expired(
            pval[:, REC_TS], pval[:, REC_TSH], now, now_hi, period
        )
        return jnp.where(dead, SENTINEL, pidx)

    rec_stash_idx = rec_private_sweep(
        state.rec.stash_idx, state.rec.stash_val
    )
    safe = jnp.minimum(
        jnp.where(rec_stash_idx != SENTINEL, rec_stash_idx, U32(n_msgs)),
        U32(n_msgs),
    )
    present = present.at[safe].set(True, mode="drop")
    rec = rec._replace(stash_idx=rec_stash_idx)
    if rcfg.delayed_eviction:
        rec_ebuf_idx = rec_private_sweep(
            state.rec.ebuf_idx, state.rec.ebuf_val
        )
        safe = jnp.minimum(
            jnp.where(rec_ebuf_idx != SENTINEL, rec_ebuf_idx, U32(n_msgs)),
            U32(n_msgs),
        )
        present = present.at[safe].set(True, mode="drop")
        rec = rec._replace(ebuf_idx=rec_ebuf_idx)

    # --- mailbox ORAM: clear expired entries, drop empty mailboxes -----
    k, cap = ecfg.mb_slots, ecfg.mailbox_cap

    def sweep_mb(idx, val):
        # idx: [...]; val: blocks of V words — one block per idx entry
        lead = idx.shape
        ew = ENTRY_WORDS
        mw = KEY_WORDS + ew * cap
        flat = val.reshape((-1, k * mw))
        keys = flat.reshape(-1, k, mw)[:, :, :KEY_WORDS]
        entries = flat.reshape(-1, k, mw)[:, :, KEY_WORDS:].reshape(-1, k, cap, ew)
        valid = (entries[..., ENT_SEQ] | entries[..., ENT_SEQH]) != 0
        dead = valid & _expired(
            entries[..., ENT_TS], entries[..., ENT_TSH], now, now_hi, period
        )
        entries = jnp.where(dead[..., None], jnp.zeros((ew,), U32), entries)
        mbox_live = jnp.any(
            (entries[..., ENT_SEQ] | entries[..., ENT_SEQH]) != 0, axis=-1
        )  # [n, k]
        keys = jnp.where(mbox_live[..., None], keys, jnp.zeros((8,), U32))
        out = jnp.concatenate(
            [keys, entries.reshape(-1, k, cap * ew)], axis=-1
        ).reshape(flat.shape)
        # blocks with no live mailbox leave the ORAM entirely
        any_key = jnp.any(
            ~is_zero_words(keys.reshape(-1, k, 8)).reshape(-1, k), axis=-1
        ).reshape(lead)
        new_idx = jnp.where(idx != SENTINEL, jnp.where(any_key, idx, SENTINEL), idx)
        return new_idx, out.reshape(val.shape), keys.reshape(lead + (k, 8))

    def live_keys(keys, idx):
        lead_live = idx != SENTINEL
        kv = ~is_zero_words(keys)
        return jnp.sum(kv & lead_live[..., None]).astype(U32)

    def mb_body(cnt, xs):
        ix, vl = xs  # [rpc, Zm], [rpc, Zm*Vm] plaintext
        new_idx, out_val, keys = sweep_mb(ix, vl)
        return cnt + live_keys(keys, new_idx), (new_idx, out_val)

    with device_phase("sweep_mailbox"):
        recips, mb = _chunked_tree_sweep(
            ecfg.mb, state.mb, jnp.zeros((), U32), mb_body
        )
        # mailbox tree-top cache: plaintext pass, stash standing (see
        # the records cache sweep above)
        if ecfg.mb.top_cache_levels:
            zc = ecfg.mb.bucket_slots
            mcidx = mb.cache_idx.reshape(-1, zc)
            if ecfg.mb.delayed_eviction:
                stale_c = (
                    mb.fetch_tag[: ecfg.mb.cache_buckets] == mb.ebuf_gen
                )
                mcidx = jnp.where(stale_c[:, None], SENTINEL, mcidx)
            mc_idx, mc_val, mc_keys = sweep_mb(mcidx, mb.cache_val)
            recips = recips + live_keys(mc_keys, mc_idx)
            mb = mb._replace(
                cache_idx=mc_idx.reshape(-1), cache_val=mc_val
            )
    mb_stash_idx, mb_stash_val, stash_keys = sweep_mb(
        state.mb.stash_idx, state.mb.stash_val
    )
    recipients = recips + live_keys(stash_keys, mb_stash_idx)
    mb = mb._replace(stash_idx=mb_stash_idx, stash_val=mb_stash_val)
    if ecfg.mb.delayed_eviction:
        # the mailbox eviction buffer sweeps exactly like the stash
        mb_ebuf_idx, mb_ebuf_val, ebuf_keys = sweep_mb(
            state.mb.ebuf_idx, state.mb.ebuf_val
        )
        recipients = recipients + live_keys(ebuf_keys, mb_ebuf_idx)
        mb = mb._replace(ebuf_idx=mb_ebuf_idx, ebuf_val=mb_ebuf_val)

    # --- rebuild the free-block list from surviving record liveness ----
    # stable partition (free indices first, each side in index order):
    # the 1-bit counting pass of the radix-rank engine — two exclusive
    # ranks + one unique scatter, O(n), sort-free under every sort_impl
    # (this site's O(n log n) argsort was retired in Round 5; the shared
    # primitive keeps the idiom in one place). Identical output by
    # construction: pos is exactly where a stable free-first partition
    # puts each index.
    pos = partition_rank(present).astype(U32)
    freelist = (
        jnp.zeros((n_msgs,), U32)
        .at[pos]
        .set(jnp.arange(n_msgs, dtype=U32), unique_indices=True)
    )
    free_top = (U32(n_msgs) - jnp.sum(present.astype(U32))).astype(U32)

    return state._replace(
        rec=rec,
        mb=mb,
        freelist=freelist,
        free_top=free_top,
        recipients=recipients,
    )
