"""Sealed whole-state checkpoints + the durability orchestrator.

The reference treats engine state as enclave-volatile; production Path
ORAM deployments do not (Stefanov et al. assume a persistent backing
store), and "oblivious redis" is meaningless if a SIGKILL wipes the bus.
This module makes the engine crash-safe without touching the oblivious
round itself:

- **Sealing**: checkpoints and journal frames are encrypted with
  ChaCha20 under per-domain subkeys of a 32-byte root key and
  authenticated encrypt-then-MAC with HMAC-SHA256. A torn, truncated,
  or tampered file fails the tag check and is *rejected whole* — there
  is no partial load. Pure stdlib + the in-repo RFC 7539 stream (the
  ``cryptography`` wheel is optional in this container), with the bulk
  keystream vectorized in numpy (the session-layer block function is a
  per-32-byte-draw path; a checkpoint is megabytes).
- **Obliviousness**: a checkpoint serializes the *entire*
  ``EngineState`` every time, and a journal frame serializes the
  *entire* fixed-size batch every round — both are constant-shape
  functions of the geometry, written at round cadence regardless of
  what the ops inside are. Like the device transcript, the file-system
  access pattern of durability is data-independent by construction
  (OPERATIONS.md §11).
- **Atomicity**: checkpoints are written tmp + fsync + ``os.replace`` +
  directory fsync, so the newest ``ckpt-*.sealed`` is always complete;
  recovery = newest checkpoint + deterministic replay of the journal
  tail (engine/journal.py) — the engine round is deterministic given
  (state, batch), which the PR-3 oracle-equality suites pin.

Crash points for the fault harness (testing/faults.py) are inlined at
the protocol-critical spots.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
import re
import struct
import time

import jax
import numpy as np

from ..config import DurabilityConfig
from ..testing import faults
from .state import EngineConfig, EngineState, state_spec

MAGIC = b"GVCKPT1\0"
VERSION = 1

_CKPT_RE = re.compile(r"^ckpt-(\d{16})\.sealed$")


class DurabilityError(RuntimeError):
    """Base for checkpoint/journal failures (never a partial load)."""


class CheckpointError(DurabilityError):
    pass


class SealError(DurabilityError):
    """Sealed blob failed structural or integrity checks."""


def write_all(fd: int, data: bytes) -> None:
    """os.write until every byte lands: one write() is capped (~2 GiB
    on Linux) and may return short on ENOSPC-adjacent conditions — an
    unchecked short count would publish a truncated sealed file."""
    view = memoryview(data)
    while view:
        n = os.write(fd, view)
        view = view[n:]


# -- sealing primitives (shared with engine/journal.py) -----------------


def _chacha_block_words(key_words, counter0: int, nonce_words, n_blocks: int):
    """RFC 7539 ChaCha20 keystream for ``n_blocks`` consecutive counters,
    vectorized over the block axis with numpy (the session-layer
    pure-Python path is O(n²) byte-appends — unusable at checkpoint
    sizes). Returns u32[n_blocks, 16]; pinned to session/chacha.py's
    stream in tests/test_checkpoint.py."""
    const = np.frombuffer(b"expand 32-byte k", dtype="<u4")
    ctrs = (np.arange(n_blocks, dtype=np.uint64) + np.uint64(counter0)).astype(
        np.uint32
    )
    init = np.empty((n_blocks, 16), np.uint32)
    init[:, 0:4] = const
    init[:, 4:12] = key_words
    init[:, 12] = ctrs
    init[:, 13:16] = nonce_words
    x = init.copy()

    def rot(v, n):
        return (v << np.uint32(n)) | (v >> np.uint32(32 - n))

    def qr(a, b, c, d):
        x[:, a] += x[:, b]
        x[:, d] = rot(x[:, d] ^ x[:, a], 16)
        x[:, c] += x[:, d]
        x[:, b] = rot(x[:, b] ^ x[:, c], 12)
        x[:, a] += x[:, b]
        x[:, d] = rot(x[:, d] ^ x[:, a], 8)
        x[:, c] += x[:, d]
        x[:, b] = rot(x[:, b] ^ x[:, c], 7)

    with np.errstate(over="ignore"):
        for _ in range(10):
            qr(0, 4, 8, 12)
            qr(1, 5, 9, 13)
            qr(2, 6, 10, 14)
            qr(3, 7, 11, 15)
            qr(0, 5, 10, 15)
            qr(1, 6, 11, 12)
            qr(2, 7, 8, 13)
            qr(3, 4, 9, 14)
        x += init
    return x


def chacha20_xor(key: bytes, nonce: bytes, data: bytes, counter: int = 0) -> bytes:
    """ChaCha20-XOR ``data`` (encrypt ≡ decrypt), bulk-vectorized."""
    if len(key) != 32 or len(nonce) != 12:
        raise ValueError("key must be 32 bytes, nonce 12")
    n_blocks = (len(data) + 63) // 64
    if n_blocks == 0:
        return b""
    ks = _chacha_block_words(
        np.frombuffer(key, "<u4"),
        counter,
        np.frombuffer(nonce, "<u4"),
        n_blocks,
    )
    ks_bytes = ks.astype("<u4").tobytes()[: len(data)]
    return (
        np.frombuffer(data, np.uint8) ^ np.frombuffer(ks_bytes, np.uint8)
    ).tobytes()


def derive_key(root_key: bytes, label: bytes) -> bytes:
    """Per-domain 32-byte subkey: HMAC-SHA256(root, label)."""
    if len(root_key) != 32:
        raise ValueError("root key must be 32 bytes")
    return hmac.new(root_key, label, hashlib.sha256).digest()


def seal(root_key: bytes, domain: bytes, plaintext: bytes,
         aad: bytes = b"") -> bytes:
    """Encrypt-then-MAC: returns ``nonce(12) | ct | tag(32)``.

    ``domain`` separates key schedules (checkpoint vs journal);
    ``aad`` binds plaintext headers (magic, seq) into the tag without
    encrypting them."""
    enc = derive_key(root_key, b"grapevine-seal-enc:" + domain)
    mac = derive_key(root_key, b"grapevine-seal-mac:" + domain)
    nonce = os.urandom(12)
    ct = chacha20_xor(enc, nonce, plaintext)
    tag = hmac.new(mac, aad + nonce + ct, hashlib.sha256).digest()
    return nonce + ct + tag


def unseal(root_key: bytes, domain: bytes, blob: bytes,
           aad: bytes = b"") -> bytes:
    """Verify and decrypt a :func:`seal` blob; raises SealError on any
    truncation or integrity failure — never returns partial plaintext."""
    if len(blob) < 12 + 32:
        raise SealError("sealed blob truncated (shorter than nonce + tag)")
    nonce, ct, tag = blob[:12], blob[12:-32], blob[-32:]
    mac = derive_key(root_key, b"grapevine-seal-mac:" + domain)
    want = hmac.new(mac, aad + nonce + ct, hashlib.sha256).digest()
    if not hmac.compare_digest(tag, want):
        raise SealError(
            "sealed blob failed integrity check (torn, truncated, "
            "tampered, or sealed under a different root key)"
        )
    enc = derive_key(root_key, b"grapevine-seal-enc:" + domain)
    return chacha20_xor(enc, nonce, ct)


def load_or_create_root_key(path: str) -> bytes:
    """32-byte root seal key at ``path``; generated 0600 on first use."""
    try:
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
    except FileExistsError:
        with open(path, "rb") as fh:
            key = fh.read()
        if len(key) != 32:
            raise SealError(
                f"root key file {path!r} is {len(key)} bytes, want 32"
            )
        return key
    try:
        key = os.urandom(32)
        os.write(fd, key)
        os.fsync(fd)
    finally:
        os.close(fd)
    return key


# -- EngineState <-> bytes ---------------------------------------------


def engine_fingerprint(ecfg: EngineConfig) -> str:
    """Geometry fingerprint a checkpoint/journal is only valid against.

    ``repr`` of the frozen dataclass tree is deterministic and covers
    every field that shapes the state arrays or the replay semantics."""
    return hashlib.sha256(repr(ecfg).encode()).hexdigest()


def state_to_bytes(ecfg: EngineConfig, state: EngineState) -> bytes:
    """Serialize a (host-synced) EngineState: JSON manifest + raw leaf
    buffers in pytree order. Blocks until the device state is ready."""
    leaves = jax.tree_util.tree_leaves(state)
    arrays = [np.asarray(leaf) for leaf in leaves]
    manifest = {
        "version": VERSION,
        "fingerprint": engine_fingerprint(ecfg),
        "leaves": [[a.dtype.str, list(a.shape)] for a in arrays],
    }
    head = json.dumps(manifest, separators=(",", ":")).encode()
    parts = [struct.pack("<I", len(head)), head]
    for a in arrays:
        # copy=False: a no-op on little-endian hosts — tobytes() is the
        # single unavoidable copy per leaf (this runs under the engine
        # lock; every avoided full-state copy shortens the round stall)
        le = np.ascontiguousarray(a).astype(
            a.dtype.newbyteorder("<"), copy=False
        )
        parts.append(le.tobytes())
    return b"".join(parts)


def bytes_to_state(ecfg: EngineConfig, data: bytes) -> EngineState:
    """Inverse of :func:`state_to_bytes`; rejects geometry mismatches and
    truncated buffers whole (CheckpointError)."""
    if len(data) < 4:
        raise CheckpointError("state payload truncated (no manifest)")
    (head_len,) = struct.unpack_from("<I", data, 0)
    if len(data) < 4 + head_len:
        raise CheckpointError("state payload truncated (manifest cut short)")
    try:
        manifest = json.loads(data[4 : 4 + head_len])
    except ValueError as exc:
        raise CheckpointError(f"state manifest unparseable: {exc}") from None
    if manifest.get("version") != VERSION:
        raise CheckpointError(
            f"state payload version {manifest.get('version')!r}, "
            f"want {VERSION}"
        )
    if manifest.get("fingerprint") != engine_fingerprint(ecfg):
        raise CheckpointError(
            "checkpoint geometry fingerprint does not match this engine "
            "config — restore requires the identical GrapevineConfig "
            "(capacities, heights, batch size, cipher) it was taken under"
        )
    treedef, spec = state_spec(ecfg)
    decl = manifest.get("leaves", [])
    if len(decl) != len(spec):
        raise CheckpointError(
            f"state payload has {len(decl)} leaves, geometry wants "
            f"{len(spec)}"
        )
    off = 4 + head_len
    leaves = []
    for (dt_str, shape), want in zip(decl, spec):
        dt = np.dtype(dt_str)
        shape = tuple(shape)
        if shape != tuple(want.shape) or dt.newbyteorder("=") != np.dtype(
            want.dtype
        ):
            raise CheckpointError(
                f"state leaf mismatch: payload {dt_str}{shape}, geometry "
                f"wants {np.dtype(want.dtype).str}{tuple(want.shape)}"
            )
        nbytes = dt.itemsize * int(np.prod(shape, dtype=np.int64))
        if off + nbytes > len(data):
            raise CheckpointError("state payload truncated (leaf cut short)")
        arr = np.frombuffer(data, dt, count=nbytes // dt.itemsize, offset=off)
        leaves.append(jax.numpy.asarray(arr.reshape(shape).astype(dt.newbyteorder("="))))
        off += nbytes
    if off != len(data):
        raise CheckpointError(
            f"state payload has {len(data) - off} trailing bytes"
        )
    return jax.tree_util.tree_unflatten(treedef, leaves)


# -- sealed checkpoint files -------------------------------------------


def checkpoint_path(state_dir: str, seq: int) -> str:
    return os.path.join(state_dir, f"ckpt-{seq:016d}.sealed")


def write_checkpoint(
    state_dir: str, root_key: bytes, ecfg: EngineConfig,
    state: EngineState, seq: int,
) -> str:
    """Atomically write the sealed checkpoint for journal seq ``seq``.

    tmp + fsync + rename + directory fsync: a crash at any point leaves
    either the previous checkpoint set or the new file complete — never
    a half-written ``ckpt-*.sealed``."""
    payload = struct.pack("<Q", seq) + state_to_bytes(ecfg, state)
    head = MAGIC + struct.pack("<I", VERSION)
    blob = head + seal(root_key, b"checkpoint", payload, aad=head)
    path = checkpoint_path(state_dir, seq)
    tmp = path + f".tmp.{os.getpid()}"
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    try:
        if faults.active() and faults.hit("checkpoint.tmp.torn"):
            write_all(fd, blob[: len(blob) // 2])
            os.fsync(fd)
            faults.die()
        write_all(fd, blob)
        os.fsync(fd)
    finally:
        os.close(fd)
    if faults.active():
        faults.crash("checkpoint.pre_rename")
    os.replace(tmp, path)
    dfd = os.open(state_dir, os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)
    if faults.active():
        faults.crash("checkpoint.post_rename")
    return path


def load_checkpoint(
    path: str, root_key: bytes, ecfg: EngineConfig
) -> tuple[int, EngineState]:
    """Load a sealed checkpoint; returns ``(seq, state)``. Any
    truncation, tamper, or geometry mismatch raises CheckpointError —
    the state is never half-loaded."""
    with open(path, "rb") as fh:
        blob = fh.read()
    head = MAGIC + struct.pack("<I", VERSION)
    if len(blob) < len(head) or blob[: len(MAGIC)] != MAGIC:
        raise CheckpointError(f"{path}: not a grapevine checkpoint")
    if blob[len(MAGIC) : len(head)] != head[len(MAGIC) :]:
        (ver,) = struct.unpack_from("<I", blob, len(MAGIC))
        raise CheckpointError(f"{path}: version {ver}, want {VERSION}")
    try:
        payload = unseal(root_key, b"checkpoint", blob[len(head):], aad=head)
    except SealError as exc:
        raise CheckpointError(f"{path}: {exc}") from None
    if len(payload) < 8:
        raise CheckpointError(f"{path}: payload truncated")
    (seq,) = struct.unpack_from("<Q", payload, 0)
    return seq, bytes_to_state(ecfg, payload[8:])


def find_latest_checkpoint(state_dir: str) -> tuple[int, str] | None:
    """Newest ``ckpt-<seq>.sealed`` by sequence number, or None."""
    best = None
    try:
        names = os.listdir(state_dir)
    except FileNotFoundError:
        return None
    for name in names:
        m = _CKPT_RE.match(name)
        if m:
            seq = int(m.group(1))
            if best is None or seq > best[0]:
                best = (seq, os.path.join(state_dir, name))
    return best


def prune_checkpoints(state_dir: str, keep_seq: int) -> None:
    """Delete every checkpoint except ``keep_seq``'s (called only after
    the kept one is durably renamed)."""
    for name in os.listdir(state_dir):
        m = _CKPT_RE.match(name)
        if m and int(m.group(1)) != keep_seq:
            try:
                os.unlink(os.path.join(state_dir, name))
            except OSError:  # pragma: no cover - concurrent cleanup
                pass
    # stale tmp files from crashed checkpoint attempts are dead weight
    for name in os.listdir(state_dir):
        if ".sealed.tmp." in name:
            try:
                os.unlink(os.path.join(state_dir, name))
            except OSError:  # pragma: no cover
                pass


# -- the durability orchestrator ---------------------------------------


class DurabilityManager:
    """Owns a state dir: root key, journal, checkpoints, recovery.

    One per engine, driven from ``GrapevineEngine`` under the engine
    lock (appends and checkpoints are serialized with rounds by
    construction). Telemetry is batch-level only: sequence numbers,
    counts, and durations — never content."""

    def __init__(self, dcfg: DurabilityConfig, ecfg: EngineConfig,
                 registry=None):
        from .journal import BatchJournal

        self.dcfg = dcfg
        self.ecfg = ecfg
        os.makedirs(dcfg.state_dir, exist_ok=True)
        key_path = dcfg.seal_key_file or os.path.join(
            dcfg.state_dir, "root.key"
        )
        self.root_key = load_or_create_root_key(key_path)
        self._c_records = self._c_fsyncs = self._c_ckpts = None
        self._g_durable = self._g_ckpt = self._g_replayed = None
        self._g_recovery_s = self._g_applied = None
        if registry is not None:
            self._c_records = registry.counter(
                "grapevine_journal_records_total",
                "batches + sweeps appended to the sealed journal")
            self._c_fsyncs = registry.counter(
                "grapevine_journal_fsyncs_total",
                "journal fsync barriers issued")
            self._c_ckpts = registry.counter(
                "grapevine_checkpoints_total",
                "sealed whole-state checkpoints written")
            self._g_durable = registry.gauge(
                "grapevine_last_durable_seq",
                "highest journal sequence fsynced to disk")
            self._g_ckpt = registry.gauge(
                "grapevine_last_checkpoint_seq",
                "journal sequence of the newest sealed checkpoint")
            self._g_replayed = registry.gauge(
                "grapevine_recovery_replayed_records",
                "journal records replayed during the last recovery")
            self._g_recovery_s = registry.gauge(
                "grapevine_recovery_seconds",
                "wall time of the last startup recovery")
            self._g_applied = registry.gauge(
                "grapevine_journal_applied_seq",
                "highest journal sequence applied to engine state (on "
                "the primary this tracks journal_seq; on a follower "
                "replaying shipped journal frames it is the replication "
                "frontier — the fleet aggregator derives "
                "grapevine_fleet_journal_lag_seq from it; ROADMAP "
                "item 4, OPERATIONS.md §20)")
        self.journal = BatchJournal(
            dcfg.state_dir, self.root_key, ecfg,
            fsync_every=dcfg.journal_fsync_every,
            on_fsync=self._note_fsync,
        )
        self.ckpt_seq = 0  # journal seq covered by the newest checkpoint
        #: highest journal seq applied to engine state. On the primary
        #: this tracks journal.seq (each record is applied as part of
        #: the round that journals it); on a follower consuming shipped
        #: frames it trails the primary's durable seq — the replication
        #: lag the fleet aggregator prices (obs/fleet.py).
        self.applied_seq = 0
        self.replayed = 0
        self.recovered_from_checkpoint = False

    # journal callback — runs under the engine lock with the append
    def _note_fsync(self, durable_seq: int) -> None:
        if self._c_fsyncs is not None:
            self._c_fsyncs.inc()
            self._g_durable.set(durable_seq)

    # -- recovery -------------------------------------------------------

    def recover(self, init_state: EngineState, apply_fn):
        """Restore state: newest checkpoint (if any) + journal replay.

        ``apply_fn(state, record)`` applies one journal record and
        returns the next state (the engine's jitted step/sweep).
        Corrupt checkpoints and mid-journal corruption raise — only a
        torn *tail* frame (the crash-mid-append case) is discarded."""
        t0 = time.monotonic()
        state = init_state
        latest = find_latest_checkpoint(self.dcfg.state_dir)
        if latest is not None:
            seq, state = load_checkpoint(
                latest[1], self.root_key, self.ecfg
            )
            if seq != latest[0]:
                # the filename seq picks which file to load; the sealed
                # payload seq is what replay trusts — a renamed file
                # must not shift the replay base
                raise CheckpointError(
                    f"{latest[1]}: filename seq {latest[0]} != sealed "
                    f"payload seq {seq} (file renamed?)"
                )
            self.ckpt_seq = seq
            self.recovered_from_checkpoint = True
        self.replayed = 0
        self.note_applied_seq(self.ckpt_seq)
        for rec in self.journal.replay(after_seq=self.ckpt_seq):
            state = apply_fn(state, rec)
            self.replayed += 1
            self.note_applied_seq(self.journal.seq)
            if self._g_replayed is not None:
                self._g_replayed.set(self.replayed)
        self.journal.open_for_append()
        if self._g_ckpt is not None:
            self._g_ckpt.set(self.ckpt_seq)
            self._g_durable.set(self.journal.seq)
            self._g_recovery_s.set(round(time.monotonic() - t0, 6))
        return state

    # -- steady state ---------------------------------------------------

    @property
    def seq(self) -> int:
        return self.journal.seq

    def note_applied_seq(self, seq: int) -> None:
        """Record that engine state now reflects journal records up to
        ``seq``. The primary calls this implicitly from the append path;
        a follower replaying shipped frames calls it per applied record
        — the gauge is what the fleet aggregator scrapes to derive
        replication lag."""
        self.applied_seq = seq
        if self._g_applied is not None:
            self._g_applied.set(seq)

    def append_round(self, batch: dict, n_real: int) -> int:
        seq = self.journal.append_round(batch, n_real)
        if self._c_records is not None:
            self._c_records.inc()
        self.note_applied_seq(seq)
        return seq

    def append_sweep(self, now: int, now_hi: int, period: int) -> int:
        seq = self.journal.append_sweep(now, now_hi, period)
        if self._c_records is not None:
            self._c_records.inc()
        self.note_applied_seq(seq)
        return seq

    def append_flush(self) -> int:
        """Delayed-eviction flush marker (engine/journal.py KIND_FLUSH);
        counts toward the checkpoint cadence like rounds and sweeps."""
        seq = self.journal.append_flush()
        if self._c_records is not None:
            self._c_records.inc()
        self.note_applied_seq(seq)
        return seq

    def append_raw_frame(self, seq: int, frame: bytes) -> int:
        """Follower path (engine/replication.py): persist one shipped
        journal frame verbatim. Counts in the records telemetry exactly
        like a locally encoded record; the caller notes the applied seq
        only after the device apply succeeds."""
        seq = self.journal.append_raw(seq, frame)
        if self._c_records is not None:
            self._c_records.inc()
        return seq

    def install_checkpoint(self, seq: int, blob: bytes):
        """Standby bootstrap: persist a primary-shipped sealed
        checkpoint and re-base the local journal at it. The blob goes
        through the normal load path (seal + geometry fingerprint +
        payload seq) before anything is re-based, so a cross-knob or
        tampered checkpoint refuses with the standard fingerprint
        error; returns the loaded EngineState."""
        path = checkpoint_path(self.dcfg.state_dir, seq)
        tmp = f"{path}.tmp.{os.getpid()}"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
        try:
            write_all(fd, blob)
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, path)
        dfd = os.open(self.dcfg.state_dir, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        got_seq, state = load_checkpoint(path, self.root_key, self.ecfg)
        if got_seq != seq:
            raise CheckpointError(
                f"{path}: shipped checkpoint payload seq {got_seq} != "
                f"advertised {seq}"
            )
        # re-base: fresh segment at seq+1; every older file is covered
        self.journal.seq = seq
        self.journal.durable_seq = seq
        self.journal.roll()
        prune_checkpoints(self.dcfg.state_dir, seq)
        self.ckpt_seq = seq
        self.recovered_from_checkpoint = True
        if self._c_ckpts is not None:
            self._c_ckpts.inc()
            self._g_ckpt.set(seq)
            self._g_durable.set(seq)
        self.note_applied_seq(seq)
        return state

    def should_checkpoint(self) -> bool:
        return (
            self.journal.seq - self.ckpt_seq
            >= self.dcfg.checkpoint_every_rounds
        )

    def checkpoint(self, state: EngineState) -> int:
        """Seal the current state at the current journal seq, then roll
        the journal and prune files the new checkpoint covers. Returns
        the checkpointed seq (also when skipped because nothing new was
        journaled)."""
        seq = self.journal.seq
        if seq == self.ckpt_seq and self.recovered_from_checkpoint:
            return seq  # nothing journaled since the last checkpoint
        # make the journal tail durable first: if the checkpoint crashes
        # half-way, recovery must still reach seq via the old chain
        self.journal.sync()
        write_checkpoint(
            self.dcfg.state_dir, self.root_key, self.ecfg, state, seq
        )
        self.ckpt_seq = seq
        self.recovered_from_checkpoint = True
        self.journal.roll()
        prune_checkpoints(self.dcfg.state_dir, seq)
        if self._c_ckpts is not None:
            self._c_ckpts.inc()
            self._g_ckpt.set(seq)
        return seq

    def status(self) -> dict:
        """Batch-level durability detail for /healthz."""
        return {
            "last_durable_seq": self.journal.durable_seq,
            "journal_seq": self.journal.seq,
            "applied_seq": self.applied_seq,
            "last_checkpoint_seq": self.ckpt_seq,
            "recovery_replayed_records": self.replayed,
            "journal_epoch": self.journal.epoch,
        }

    def close(self) -> None:
        self.journal.close()
