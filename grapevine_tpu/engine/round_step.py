"""Phase-major batched engine step: three ORAM rounds per batch.

`engine/step.py` commits each op's three phases before the next op starts
(op-major), which serializes 3·B dependent path fetches. This module runs
the same three phases *phase-major* over the batched round primitive
(oram/round.py): one mailbox round applying phase A for every op in slot
order, one records round applying phase B, one mailbox round applying
phase C. The semantic phase functions are shared with the op-major engine
verbatim — only the commit schedule differs.

**Phase-major commit semantics** (the documented batch-hazard behavior of
this engine; the reference never faced batches, SURVEY.md §7.6). Within
one batch, in slot order:

- phase-A effects (mailbox capacity checks and appends for CREATE,
  zero-id selection, zero-id DELETE's mailbox pop, record-slot
  reservation) are visible to later ops' phase A;
- phase-B effects (record insert/mutate/remove) are visible to later
  ops' phase B;
- phase-C effects (explicit DELETE's mailbox removal, UPDATE's mailbox
  timestamp refresh) are visible only to the *next* batch — as are
  record slots freed by any DELETE.

Consequences, all mirrored bit-for-bit by the CPU oracle's
``handle_batch`` (testing/reference.py): a CREATE cannot reuse capacity
freed by a DELETE in the same batch; a zero-id op whose mailbox-selected
message was explicitly deleted earlier in the batch reports NOT_FOUND
(the record is already gone in phase B) rather than selecting the next
message. For single-op batches phase-major and op-major semantics are
identical (no cross-op window), which tests assert.

Obliviousness: the public transcript is one uniform leaf per op per
round, [mailbox, records, mailbox] — identical in distribution for every
op type including padding dummies; duplicate-index dedup inside
oram_round keeps same-key ops uncorrelated in the transcript.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..oblivious.primitives import is_zero_words, rank_of
from ..wire import constants as C
from ..oram.round import oram_round
from .responses import assemble_responses
from .state import EngineConfig, EngineState, mb_bucket_hash
from .step import _phase_a, _phase_b, _phase_c

U32 = jnp.uint32


def engine_round_step(
    ecfg: EngineConfig,
    state: EngineState,
    batch: dict,
    axis_name: str | None = None,
):
    """Process one batch as three phase-major ORAM rounds.

    Same signature and return shape as `engine_step`: ``(state',
    responses, transcripts u32[B, 3])``.
    """
    b = batch["req_type"].shape[0]
    now = batch["now"].astype(U32)
    rt = batch["req_type"].astype(U32)
    auth = batch["auth"]
    msg_id = batch["msg_id"]
    recipient = batch["recipient"]
    payload = batch["payload"]

    keys = jax.random.split(state.rng, 8)
    k_next = keys[7]
    nl_a, nl_b, nl_c = (
        jax.random.bits(keys[0], (b,), U32) & U32(ecfg.mb.leaves - 1),
        jax.random.bits(keys[1], (b,), U32) & U32(ecfg.rec.leaves - 1),
        jax.random.bits(keys[2], (b,), U32) & U32(ecfg.mb.leaves - 1),
    )
    dl_a, dl_b, dl_c = (
        jax.random.bits(keys[3], (b,), U32) & U32(ecfg.mb.leaves - 1),
        jax.random.bits(keys[4], (b,), U32) & U32(ecfg.rec.leaves - 1),
        jax.random.bits(keys[5], (b,), U32) & U32(ecfg.mb.leaves - 1),
    )
    id_rand = jax.random.bits(keys[6], (b, 3), U32)

    is_create = rt == C.REQUEST_TYPE_CREATE
    is_read = rt == C.REQUEST_TYPE_READ
    is_update = rt == C.REQUEST_TYPE_UPDATE
    is_delete = rt == C.REQUEST_TYPE_DELETE
    is_real = is_create | is_read | is_update | is_delete
    id_zero = is_zero_words(msg_id)
    zero_recip = is_zero_words(recipient)

    ka = jnp.where((is_create | ~id_zero)[:, None], recipient, auth)
    bucket = jax.vmap(
        lambda k: mb_bucket_hash(state.hash_key, k, ecfg.mb_table_buckets)
    )(ka)
    idxs_mb = jnp.where(is_real, bucket, U32(ecfg.mb.dummy_index))

    # ---- round A: mailbox (capacity, append, zero-id select/pop) ------
    # Freelist discipline: the big freelist array never enters a scan
    # carry (a mode="drop" scatter on a capacity-sized array inside a
    # scan body stalls every iteration on a fresh copy — profiled at
    # ~25 ms/round at 2^20). Instead the top B candidate blocks are
    # pre-gathered here; the scan only advances a counter; frees are
    # pushed back in one vectorized scatter after round B.
    ks = jnp.arange(b, dtype=U32)
    cand_pos = jnp.where(ks < state.free_top, state.free_top - U32(1) - ks, 0)
    cand_idx = state.freelist[cand_pos]

    opnd_a = {
        "ka": ka,
        "idr": id_rand,
        "is_create": is_create & is_real,
        "is_delete": is_delete,
        "id_zero": id_zero,
        "zero_recip": zero_recip,
    }

    def apply_a(carry, value, present, o):
        n_alloc, recipients, seq = carry
        can_alloc = n_alloc < state.free_top
        alloc_idx = cand_idx[jnp.minimum(n_alloc, U32(b - 1))]
        new_id = jnp.stack(
            [alloc_idx, o["idr"][0] | U32(1), o["idr"][1], o["idr"][2]]
        )
        oo = {
            **o,
            "can_alloc": can_alloc,
            "alloc_idx": alloc_idx,
            "new_id": new_id,
            "recipients": recipients,
            "seq": seq,
            "now": now,
        }
        new_value, keep, insert, out = _phase_a(ecfg, value, present, oo)
        out = {**out, "alloc_idx": alloc_idx, "new_id": new_id}
        n_alloc = n_alloc + out["create_ok"].astype(U32)
        recipients = (recipients.astype(jnp.int32) + out["recip_delta"]).astype(U32)
        seq = seq + out["create_ok"].astype(U32)
        return (n_alloc, recipients, seq), new_value, keep, insert, out

    mb1, (n_alloc, recipients, seq), out_a, leaf_a = oram_round(
        ecfg.mb,
        state.mb,
        idxs_mb,
        nl_a,
        dl_a,
        opnd_a,
        apply_a,
        (jnp.zeros((), U32), state.recipients, state.seq),
        axis_name,
    )
    free_top = state.free_top - n_alloc

    # ---- round B: records (verify, insert, mutate, remove) ------------
    create_ok = out_a["create_ok"]
    lookup_blk = jnp.where(
        create_ok,
        out_a["alloc_idx"],
        jnp.where(id_zero, out_a["sel_blk"], msg_id[:, 0]),
    )
    real_b = is_real & (
        create_ok | (~is_create & (~id_zero | out_a["sel_found"]))
    )
    idx_b = jnp.where(
        real_b, lookup_blk & U32(ecfg.rec.leaves - 1), U32(ecfg.rec.dummy_index)
    )
    opnd_b = {
        "sel_blk": out_a["sel_blk"],
        "sel_idw": out_a["sel_idw"],
        "msg_id": msg_id,
        "id_zero": id_zero,
        "is_create": is_create & is_real,
        "is_read": is_read,
        "is_update": is_update,
        "is_delete": is_delete,
        "auth": auth,
        "recipient": recipient,
        "payload": payload,
        "create_ok": create_ok,
        "new_id": out_a["new_id"],
    }

    def apply_b(carry, value, present, o):
        new_value, keep, insert, out = _phase_b(ecfg, value, present, {**o, "now": now})
        return carry, new_value, keep, insert, out

    rec1, _, out_b, leaf_b = oram_round(
        ecfg.rec,
        state.rec,
        idx_b,
        nl_b,
        dl_b,
        opnd_b,
        apply_b,
        jnp.zeros((), U32),
        axis_name,
    )

    # freed blocks return to the freelist in slot order — one vectorized
    # scatter, visible only to the next batch (round_step commit schedule)
    dels = out_b["del_ok"]
    push_pos = jnp.where(
        dels, free_top + rank_of(dels).astype(U32), U32(ecfg.max_messages)
    )
    freelist = state.freelist.at[push_pos].set(idx_b, mode="drop")
    free_top = free_top + jnp.sum(dels.astype(U32))

    # ---- round C: mailbox finalization --------------------------------
    opnd_c = {
        "ka": ka,
        "msg_id": msg_id,
        "del_ok": out_b["del_ok"],
        "upd_ok": out_b["upd_ok"],
        "rm_a": out_a["rm_a"],
    }

    def apply_c(carry, value, present, o):
        new_value, keep, insert, out = _phase_c(ecfg, value, present, {**o, "now": now})
        recipients = (carry.astype(jnp.int32) + out["recip_delta"]).astype(U32)
        return recipients, new_value, keep, insert, out

    mb2, recipients, _out_c, leaf_c = oram_round(
        ecfg.mb, mb1, idxs_mb, nl_c, dl_c, opnd_c, apply_c, recipients, axis_name
    )

    # ---- response assembly (shared with the op-major engine) ----------
    responses = assemble_responses(
        is_real=is_real,
        is_create=is_create,
        is_update=is_update,
        is_delete=is_delete,
        id_zero=id_zero,
        status_a=out_a["status_a"],
        create_ok=create_ok,
        out_b=out_b,
        new_id=out_a["new_id"],
        auth=auth,
        recipient=recipient,
        payload=payload,
        now=now,
    )
    transcripts = jnp.stack([leaf_a, leaf_b, leaf_c], axis=1)

    new_state = EngineState(
        rec=rec1,
        mb=mb2,
        freelist=freelist,
        free_top=free_top,
        recipients=recipients,
        seq=seq,
        hash_key=state.hash_key,
        rng=k_next,
    )
    return new_state, responses, transcripts
