"""Phase-major batched engine step: three vectorized ORAM rounds per batch.

`engine/step.py` commits each op's three phases before the next op starts
(op-major), which serializes 3·B dependent path fetches. This module runs
the same three phases *phase-major* over the batched round primitive
(oram/round.py): one mailbox round applying phase A for every op in slot
order, one records round applying phase B, one mailbox round applying
phase C. Within each round the slot-order semantics are resolved fully in
parallel (engine/vphases.py) — there is no per-op loop anywhere on the
device hot path.

**Phase-major commit semantics** (the documented batch-hazard behavior of
this engine; the reference never faced batches, SURVEY.md §7.6). Within
one batch, in slot order:

- phase-A effects (mailbox capacity checks and appends for CREATE,
  zero-id selection, zero-id DELETE's mailbox pop, record-slot
  reservation) are visible to later ops' phase A;
- phase-B effects (record insert/mutate/remove) are visible to later
  ops' phase B;
- phase-C effects (explicit DELETE's mailbox removal, UPDATE's mailbox
  timestamp refresh) are visible only to the *next* batch — as are
  record slots freed by any DELETE.

Consequences, all mirrored bit-for-bit by the CPU oracle's
``handle_batch`` (testing/reference.py): a CREATE cannot reuse capacity
freed by a DELETE in the same batch; a zero-id op whose mailbox-selected
message was explicitly deleted earlier in the batch reports NOT_FOUND
(the record is already gone in phase B) rather than selecting the next
message. For single-op batches phase-major and op-major semantics are
identical (no cross-op window), which tests assert.

Obliviousness: the public transcript is one uniform leaf per op per
round, [mailbox, records, mailbox] — identical in distribution for every
op type including padding dummies; duplicate-index dedup inside
oram_round keeps same-key ops uncorrelated in the transcript. Quota
admission may branch on *aggregate* saturation (bus or recipient table
within B of full) — see the leak analysis in engine/vphases.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..oblivious.primitives import is_zero_words, rank_of
from ..oblivious.prp import prp2_decrypt
from ..obs.phases import device_phase
from ..wire import constants as C
from ..oram.round import oram_round
from .responses import assemble_responses
from ..oblivious.primitives import u64_add_u32
from .state import EngineConfig, EngineState, mb_bucket_hash
from .vphases import phase_a_batch, phase_b_batch, phase_c_batch

U32 = jnp.uint32


def _tree_secrets(prefix: str) -> tuple:
    """The private planes of one OramState under ``prefix``: positions
    (posmap — recursively, the whole pytree under a recursive map),
    stash and cache contents, and the at-rest cipher key (key-taint is
    what marks decrypted tree rows secret; ciphertext stays public)."""
    return tuple(
        f"{prefix}.{p}"
        for p in (
            "posmap", "stash_idx", "stash_val", "stash_leaf",
            "cache_idx", "cache_val", "cache_leaf",
            "ebuf_idx", "ebuf_val", "ebuf_leaf", "cipher_key",
        )
    )
    # NOT tainted: ebuf_paths / ebuf_rounds / ebuf_gen / fetch_tag — the
    # flush-window bookkeeping is a pure function of the public
    # transcript (oram/round.py OBLINT_SECRETS note)


#: oblint taint anchors (analysis/oblint.py): the secret inputs of one
#: full engine round ``engine_round_step(ecfg, state, batch)`` — every
#: per-op column of the batch (who, which message, what type, what
#: payload), both trees' private planes, and the engine's key material
#: (hash/PRP keys mix secrets; the rng's draws become future positions).
#: The freelist is secret too: its *contents* are freed block ids in
#: deletion order (private EPC-analog state per the threat model in
#: engine/state.py), even though its *height* (free_top) is the public
#: aggregate the quota-admission standing branches on (vphases.py).
#: Deliberately NOT secret: free_top/recipients/seq (aggregate
#: saturation counters), nonces/epoch (public write-epoch counters),
#: and the HBM tree ciphertext planes.
OBLINT_SECRETS = (
    ("batch.req_type", "batch.auth", "batch.msg_id", "batch.recipient",
     "batch.payload", "state.freelist", "state.hash_key",
     "state.id_key", "state.rng")
    + _tree_secrets("state.rec")
    + _tree_secrets("state.mb")
)


def RANGELINT_BOUNDS(ecfg: EngineConfig) -> dict:
    """Rangelint input-interval anchors (analysis/rangelint.py) for one
    full engine round ``engine_round_step(ecfg, state, batch)`` — the
    geometry-derived invariants where values enter the compiled round:

    - both trees' private planes carry the per-plane bounds of
      :func:`oram.path_oram.RANGELINT_BOUNDS` (position values below
      their leaf counts; ciphertext opaque);
    - the freelist holds block ids, ``free_top`` counts at most
      ``max_messages`` of them (stack invariant: pushes are exactly the
      oracle-pinned deletes), ``recipients`` is capped by admission at
      ``max_recipients`` — the counters' per-run increment budget is one
      batch (≤ B), which the u32 lane absorbs with 2^31 of margin;
    - batch columns, the u64 clock lanes, and the seq counter stay at
      the full lane (untrusted inputs / two-lane counters whose wrap is
      the allowlisted carry idiom).
    """
    from ..oram.path_oram import RANGELINT_BOUNDS as tree_bounds

    return {
        **tree_bounds(ecfg.rec, prefix="state.rec"),
        **tree_bounds(ecfg.mb, prefix="state.mb"),
        "state.freelist": (0, ecfg.max_messages - 1),
        "state.free_top": (0, ecfg.max_messages),
        "state.recipients": (0, ecfg.max_recipients),
    }


def transcript_key_groups(batch: dict, mb_choices: int):
    """Host-side mirror of this step's key selection, for the leak
    monitor (obs/leakmon.py).

    Returns ``((mb_keys, mb_stable), (rec_keys, rec_stable))`` aligned
    to the transcript columns ``[a_0..a_{D-1}, b, c_0..c_{D-1}]``:

    - ``mb_keys`` i64[B·D]: within-round group ids over the flattened
      mailbox fetch slots — two slots share a group iff they fetch the
      same candidate bucket on the device, i.e. same ``ka`` (the
      recipient for CREATE/explicit-id ops, else the auth identity —
      the ``ka`` select above) and same choice column. ``-1`` = padding
      dummy (no key). Grouping by ``ka`` rather than the keyed bucket
      hash (device-resident ``hash_key``) can only *miss* accidental
      hash collisions between distinct ``ka`` — an undercount of
      same-key pairs, never a false SUSPECT.
    - ``rec_keys`` i64[B]: records-round groups; explicit-id non-CREATE
      ops group by ``msg_id`` (one msg_id = one PRP-resolved block).
      CREATE (allocates a fresh block) and zero-id ops (block selected
      inside the oblivious round) are not host-resolvable → ``-1``.
    - ``*_stable``: per-slot cross-round-stable ids (bytes) for the
      repeat tracker, ``None`` where keyless.

    The key material stays in process memory (the monitor's standing —
    same as the position map); only windowed aggregates are exported.
    """
    rt = np.asarray(batch["req_type"]).astype(np.uint32)
    auth = np.asarray(batch["auth"], dtype=np.uint32)
    recipient = np.asarray(batch["recipient"], dtype=np.uint32)
    msg_id = np.asarray(batch["msg_id"], dtype=np.uint32)
    b = rt.shape[0]
    is_real = (rt >= C.REQUEST_TYPE_CREATE) & (rt <= C.REQUEST_TYPE_DELETE)
    is_create = rt == C.REQUEST_TYPE_CREATE
    id_zero = ~msg_id.any(axis=1)
    ka = np.where((is_create | ~id_zero)[:, None], recipient, auth)

    d = mb_choices
    mb_keys = np.full((b * d,), -1, np.int64)
    mb_stable: list[bytes | None] = [None] * (b * d)
    mb_groups: dict[bytes, int] = {}
    rec_keys = np.full((b,), -1, np.int64)
    rec_stable: list[bytes | None] = [None] * b
    rec_groups: dict[bytes, int] = {}
    for j in range(b):
        if not is_real[j]:
            continue
        kb = ka[j].tobytes()
        g = mb_groups.setdefault(kb, len(mb_groups))
        for c in range(d):
            mb_keys[j * d + c] = g * d + c
            mb_stable[j * d + c] = kb + bytes([c])
        if not is_create[j] and not id_zero[j]:
            mid = msg_id[j].tobytes()
            rec_keys[j] = rec_groups.setdefault(mid, len(rec_groups))
            rec_stable[j] = mid
    return (mb_keys, mb_stable), (rec_keys, rec_stable)


def engine_round_step(
    ecfg: EngineConfig,
    state: EngineState,
    batch: dict,
    axis_name: str | None = None,
):
    """Process one batch as three phase-major ORAM rounds.

    Same signature and return shape as `engine_step`: ``(state',
    responses, transcripts u32[B, 3])``.
    """
    b = batch["req_type"].shape[0]
    now = batch["now"].astype(U32)
    # u64 clock: low lane in "now", optional high lane in "now_hi"
    # (absent in pre-widening batch dicts — membership is trace-static)
    now_hi = (
        batch["now_hi"].astype(U32) if "now_hi" in batch else jnp.zeros((), U32)
    )
    rt = batch["req_type"].astype(U32)
    auth = batch["auth"]
    msg_id = batch["msg_id"]
    recipient = batch["recipient"]
    payload = batch["payload"]

    d = ecfg.mb_choices  # candidate buckets fetched per op (mailbox tier)
    keys = jax.random.split(state.rng, 8)
    k_next = keys[7]
    nl_a, nl_b, nl_c = (
        jax.random.bits(keys[0], (b * d,), U32) & U32(ecfg.mb.leaves - 1),
        jax.random.bits(keys[1], (b,), U32) & U32(ecfg.rec.leaves - 1),
        jax.random.bits(keys[2], (b * d,), U32) & U32(ecfg.mb.leaves - 1),
    )
    dl_a, dl_b, dl_c = (
        jax.random.bits(keys[3], (b * d,), U32) & U32(ecfg.mb.leaves - 1),
        jax.random.bits(keys[4], (b,), U32) & U32(ecfg.rec.leaves - 1),
        jax.random.bits(keys[5], (b * d,), U32) & U32(ecfg.mb.leaves - 1),
    )
    id_rand = jax.random.bits(keys[6], (b, 3), U32)

    # recursive position map (oram/posmap.py): each round additionally
    # needs fresh uniform *internal* leaves — drawn from a fold_in side
    # stream so the flat engine's draws above are untouched bit-for-bit
    # (the flat↔recursive response/state identity contract)
    recursive = ecfg.rec.posmap is not None
    pm = {"a": (None, None), "b": (None, None), "c": (None, None)}
    if recursive:
        mb_il = ecfg.mb.posmap.inner_leaves
        rec_il = ecfg.rec.posmap.inner_leaves
        kpm = jax.random.split(jax.random.fold_in(state.rng, 0x504D), 6)
        pm = {
            "a": (jax.random.bits(kpm[0], (b * d,), U32) & U32(mb_il - 1),
                  jax.random.bits(kpm[1], (b * d,), U32) & U32(mb_il - 1)),
            "b": (jax.random.bits(kpm[2], (b,), U32) & U32(rec_il - 1),
                  jax.random.bits(kpm[3], (b,), U32) & U32(rec_il - 1)),
            "c": (jax.random.bits(kpm[4], (b * d,), U32) & U32(mb_il - 1),
                  jax.random.bits(kpm[5], (b * d,), U32) & U32(mb_il - 1)),
        }

    is_create = rt == C.REQUEST_TYPE_CREATE
    is_read = rt == C.REQUEST_TYPE_READ
    is_update = rt == C.REQUEST_TYPE_UPDATE
    is_delete = rt == C.REQUEST_TYPE_DELETE
    is_real = is_create | is_read | is_update | is_delete
    id_zero = is_zero_words(msg_id)
    zero_recip = is_zero_words(recipient)

    ka = jnp.where((is_create | ~id_zero)[:, None], recipient, auth)
    # D candidate buckets per op (salted independent keyed hashes);
    # every op fetches ALL candidates so the transcript hides which one
    # holds the recipient (vphases.phase_a_batch chooses with masks)
    bucket2 = jnp.stack(
        [
            jax.vmap(
                lambda k, c=c: mb_bucket_hash(
                    state.hash_key, k, ecfg.mb_table_buckets, salt=c
                )
            )(ka)
            for c in range(d)
        ],
        axis=1,
    )  # u32[B,D]
    idxs_mb2 = jnp.where(is_real[:, None], bucket2, U32(ecfg.mb.dummy_index))
    idxs_mb_flat = idxs_mb2.reshape(b * d)

    # allocation candidates: the top B free blocks, pre-gathered so the
    # freelist array never enters device decision logic (vphases assigns
    # the n-th successful create candidate n). The rank arithmetic uses
    # the +max_messages modular bias so lanes past the stack top never
    # wrap below zero in u32 (free_top + mm - 1 <= 2^31 - 1 at the
    # certified blocks <= 2^30 bound; the & mask is mod mm) — bit-
    # identical to free_top-1-ks on every selected lane, and interval-
    # transparent to rangelint instead of a masked wraparound.
    ks = jnp.arange(b, dtype=U32)
    mm_mask = U32(ecfg.max_messages - 1)
    cand_pos = jnp.where(
        ks < state.free_top,
        (state.free_top + mm_mask - ks) & mm_mask,
        U32(0),
    )
    cand_idx = state.freelist[cand_pos]

    # ---- round A: mailbox (capacity, append, zero-id select/pop) ------
    ctx = {
        "is_real": is_real,
        "is_create": is_create,
        "is_read": is_read,
        "is_update": is_update,
        "is_delete": is_delete,
        "id_zero": id_zero,
        "zero_recip": zero_recip,
        "ka": ka,
        "idxs_mb2": idxs_mb2,
        "cand_idx": cand_idx,
        "id_key": state.id_key,
        "id_rand": id_rand,
        "free_top0": state.free_top,
        "recipients0": state.recipients,
        "seq0": state.seq,
        "now": now,
        "now_hi": now_hi,
        "auth": auth,
        "recipient": recipient,
        "msg_id": msg_id,
        "payload": payload,
    }
    with device_phase("round_a_mailbox"):
        mb1, out_a, leaf_a = oram_round(
            ecfg.mb, state.mb, idxs_mb_flat, nl_a, dl_a,
            phase_a_batch(ecfg, ctx), axis_name,
            occ_impl=ecfg.vphases_impl, sort_impl=ecfg.sort_impl,
            pm_new_leaves=pm["a"][0], pm_dummy_leaves=pm["a"][1],
        )
    # n_allocs <= free_top by phase-A admission (the quota invariant the
    # oracle-equality suites pin), so the subtraction cannot wrap; that
    # argument lives in RANGE_ALLOWLIST, and the min re-establishes the
    # stack bound for interval reasoning downstream (identity at runtime)
    free_top = jnp.minimum(
        state.free_top - out_a["n_allocs"], U32(ecfg.max_messages)
    )
    recipients = state.recipients + out_a["n_claims"]
    seq_lo, seq_hi = u64_add_u32(state.seq[0], state.seq[1], U32(b))
    seq = jnp.stack([seq_lo, seq_hi])

    # ---- round B: records (verify, insert, mutate, remove) ------------
    # id words 0-1 are the PRP-encrypted (nonce, block index)
    # (oblivious/prp.py); mailbox entries store the same encrypted form,
    # so one decrypt covers explicit-id and zero-id-selected lookups
    create_ok = out_a["create_ok"]
    enc_w0 = jnp.where(id_zero, out_a["sel_blk"], msg_id[:, 0])
    enc_w1 = jnp.where(id_zero, out_a["sel_idw"], msg_id[:, 1])
    dec_blk = prp2_decrypt(state.id_key, enc_w0, enc_w1, ecfg.id_bits)
    lookup_blk = jnp.where(create_ok, out_a["alloc_idx"], dec_blk)
    real_b = is_real & (
        create_ok | (~is_create & (~id_zero | out_a["sel_found"]))
    )
    idx_b = jnp.where(
        real_b, lookup_blk & U32(ecfg.rec.blocks - 1), U32(ecfg.rec.dummy_index)
    )
    ctx_b = {
        **ctx,
        "idx_b": idx_b,
        "real_b": real_b,
        "create_ok": create_ok,
        "new_id": out_a["new_id"],
        "sel_blk": out_a["sel_blk"],
        "sel_idw": out_a["sel_idw"],
    }
    with device_phase("round_b_records"):
        rec1, out_b, leaf_b = oram_round(
            ecfg.rec, state.rec, idx_b, nl_b, dl_b,
            phase_b_batch(ecfg, ctx_b), axis_name,
            occ_impl=ecfg.vphases_impl, sort_impl=ecfg.sort_impl,
            pm_new_leaves=pm["b"][0], pm_dummy_leaves=pm["b"][1],
        )

    # freed blocks return to the freelist in slot order — one vectorized
    # scatter, visible only to the next batch (phase-major commit rule)
    dels = out_b["del_ok"]
    push_pos = jnp.where(
        dels, free_top + rank_of(dels).astype(U32), U32(ecfg.max_messages)
    )
    freelist = state.freelist.at[push_pos].set(idx_b, mode="drop")
    free_top = free_top + jnp.sum(dels.astype(U32))

    # ---- round C: mailbox finalization --------------------------------
    ctx_c = {
        **ctx,
        "del_ok": out_b["del_ok"],
        "upd_ok": out_b["upd_ok"],
        "rm_a": out_a["rm_a"],
    }
    with device_phase("round_c_mailbox"):
        mb2, _out_c, leaf_c = oram_round(
            ecfg.mb, mb1, idxs_mb_flat, nl_c, dl_c,
            phase_c_batch(ecfg, ctx_c), axis_name,
            occ_impl=ecfg.vphases_impl, sort_impl=ecfg.sort_impl,
            pm_new_leaves=pm["c"][0], pm_dummy_leaves=pm["c"][1],
        )

    # ---- response assembly (shared with the op-major engine) ----------
    responses = assemble_responses(
        is_real=is_real,
        is_create=is_create,
        is_update=is_update,
        is_delete=is_delete,
        id_zero=id_zero,
        status_a=out_a["status_a"],
        create_ok=create_ok,
        out_b=out_b,
        new_id=out_a["new_id"],
        auth=auth,
        recipient=recipient,
        payload=payload,
        now2=jnp.stack([now, now_hi]).astype(U32),
    )
    # transcript: D leaves per mailbox round + 1 records leaf per op —
    # [B, 2D+1] columns (a_0..a_{D-1}, b, c_0..c_{D-1}); every entry an
    # independent uniform draw either way. Recursive posmap: the
    # internal ORAM's accesses are public transcript too — the same
    # layout is appended as columns [2D+1, 2(2D+1)) so the leak monitor
    # audits the position-resolution traffic alongside the payload's
    # (obs/leakmon.py mb_pm/rec_pm streams)
    if recursive:
        transcripts = jnp.concatenate(
            [
                leaf_a[:, 0].reshape(b, d), leaf_b[:, 0:1],
                leaf_c[:, 0].reshape(b, d),
                leaf_a[:, 1].reshape(b, d), leaf_b[:, 1:2],
                leaf_c[:, 1].reshape(b, d),
            ],
            axis=1,
        )
    else:
        transcripts = jnp.concatenate(
            [leaf_a.reshape(b, d), leaf_b[:, None], leaf_c.reshape(b, d)],
            axis=1,
        )

    new_state = EngineState(
        rec=rec1,
        mb=mb2,
        freelist=freelist,
        free_top=free_top,
        recipients=recipients,
        seq=seq,
        hash_key=state.hash_key,
        id_key=state.id_key,
        rng=k_next,
    )
    return new_state, responses, transcripts


#: oblint taint anchors for one ``engine_flush_step(ecfg, state)`` — the
#: flush consumes no batch; its secrets are exactly both trees' private
#: planes plus the key material (the rng passes through untouched but a
#: PRNG key is working key material either way). The flush's bucket
#: targets derive ONLY from the untainted public window ledger
#: (ebuf_paths) — that independence is the whole leak argument.
FLUSH_OBLINT_SECRETS = (
    ("state.freelist", "state.hash_key", "state.id_key", "state.rng")
    + _tree_secrets("state.rec")
    + _tree_secrets("state.mb")
)


def engine_flush_step(
    ecfg: EngineConfig,
    state: EngineState,
    axis_name: str | None = None,
) -> EngineState:
    """One delayed-eviction flush over both trees (PR 15; ROADMAP item 1).

    Called by the engine every ``evict_every`` rounds on the
    round-counter cadence — an op-independent schedule; never triggered
    by buffer occupancy. Deterministic given the state (no RNG), so
    journal replay re-executes it bit-identically (KIND_FLUSH,
    engine/journal.py). Under a recursive position map the internal
    trees flush inside the same call (oram/round.py:oram_flush
    recurses). A no-op-shaped pass at ``evict_every == 1`` is never
    dispatched — the engine only compiles this program when delayed
    eviction is on.

    With ``axis_name`` set the call runs inside ``shard_map``
    (parallel/mesh.py:make_sharded_flush): both trees' write-back
    scatters are owner-masked to each chip's heap range and everything
    else — eviction buffer, stash, dedup, the recursive inner trees —
    stays the replicated axis-free program (the oram_flush docstring
    carries the leak argument).
    """
    from ..oram.round import oram_flush

    with device_phase("engine_flush"):
        rec = oram_flush(ecfg.rec, state.rec, axis_name,
                         sort_impl=ecfg.sort_impl)
        mb = oram_flush(ecfg.mb, state.mb, axis_name,
                        sort_impl=ecfg.sort_impl)
    return state._replace(rec=rec, mb=mb)
