"""Host-side batching: wire records ↔ device SoA arrays, and the engine facade.

The request batcher is the TPU analog of the reference's per-request
enclave ECALL path (SURVEY.md §2c): N client operations are packed into
one fixed-size jit'd access round; under-full batches are padded with
dummy operations (request_type 0) that perform the identical ORAM access
pattern, preserving the fixed cadence.

Hard protocol errors (zero auth identity, UPDATE with zero id — the
reference's fail-fast gRPC errors, grapevine.proto:60-64,95) are raised
here on the host before anything reaches the device, exactly as the
reference rejects them before the oblivious path.

Pipelined round execution (PR 10, ROADMAP item 2): a round passes
through four stages — assemble (validate + pack, lock-free), journal
(sealed append + fsync, under the engine lock), dispatch (async jit
enqueue with the donated state, under the same lock hold), resolve
(device wait + demux, lock-free). ``handle_queries_async`` composes the
first three and returns the :class:`PendingRound` whose ``resolve()`` is
stage four; callers (``handle_queries`` here, the BatchScheduler, the
chaos harness) keep up to ``config.pipeline_depth`` rounds in flight
between dispatch and resolve, so round k+1's host assembly and journal
fsync overlap round k's device execution — with two donated engine
states rotating through XLA's buffer donation, steady-state cadence
approaches ``max(host, fsync, device)`` instead of their sum. The
durability ordering is depth-independent: journal-append (and its
fsync barrier) strictly precedes the same round's dispatch, and rounds
journal and dispatch inside one lock hold, so replay order is journal
order — never completion order (OPERATIONS.md §16).
"""

from __future__ import annotations

import threading
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from ..config import DurabilityConfig, GrapevineConfig
from ..testing import faults
from ..wire import constants as C
from ..wire.records import QueryRequest, QueryResponse, Record
from ..wire.validate import validate_request  # noqa: F401  (re-export —
# moved to the jax-free wire package so hostpipe workers can validate
# without importing the engine; existing callers import it from here)
from .expiry import expiry_sweep
from .state import (
    EngineConfig,
    EngineState,
    ID_WORDS,
    KEY_WORDS,
    PAYLOAD_WORDS,
    init_engine,
)
from .metrics import EngineMetrics
from .round_step import engine_flush_step, engine_round_step
from .step import engine_step


def pack_batch(reqs: list[QueryRequest], batch_size: int, now: int) -> dict:
    """Pack ≤batch_size validated requests into device arrays, dummy-padded.

    Columnar: one ``b"".join`` + ``frombuffer`` per field instead of a
    per-request assignment loop — at B=2048 the loop was ~14 ms of host
    time per round, on par with the device round itself (PERF.md)."""
    n = len(reqs)
    if n > batch_size:
        raise ValueError("too many requests for one batch")
    b = batch_size

    def col(words: int, chunks) -> np.ndarray:
        arr = np.zeros((b, words), np.uint32)
        if n:
            arr[:n] = np.frombuffer(b"".join(chunks), "<u4").reshape(n, words)
        return arr

    rt = np.zeros((b,), np.uint32)
    rt[:n] = [r.request_type for r in reqs]
    return {
        "req_type": rt,
        "auth": col(KEY_WORDS, (r.auth_identity for r in reqs)),
        "msg_id": col(ID_WORDS, (r.record.msg_id for r in reqs)),
        "recipient": col(KEY_WORDS, (r.record.recipient for r in reqs)),
        "payload": col(PAYLOAD_WORDS, (r.record.payload for r in reqs)),
        # u64 clock as two u32 lanes (wire timestamps are u64; no 2106
        # rollover on the device path either)
        "now": np.uint32(int(now) & 0xFFFFFFFF),
        "now_hi": np.uint32((int(now) >> 32) & 0xFFFFFFFF),
    }


def unpack_responses(resp: dict, n: int) -> list[QueryResponse]:
    """Columnar device→wire conversion: one ``tobytes`` per field, rows
    sliced out of the flat buffer (bytes slicing is C-speed; the old
    per-row ``tobytes`` loop was ~8 ms at B=2048)."""
    status = np.asarray(resp["status"])[:n].tolist()
    ts_lanes = np.asarray(resp["timestamp"])[:n].astype(np.uint64)
    ts = (ts_lanes[:, 0] | (ts_lanes[:, 1] << np.uint64(32))).tolist()

    def rows(name: str, words: int) -> list[bytes]:
        flat = np.ascontiguousarray(
            np.asarray(resp[name])[:n], dtype="<u4"
        ).tobytes()
        sz = words * 4
        return [flat[i * sz : (i + 1) * sz] for i in range(n)]

    mids = rows("msg_id", ID_WORDS)
    snds = rows("sender", KEY_WORDS)
    rcps = rows("recipient", KEY_WORDS)
    pls = rows("payload", PAYLOAD_WORDS)
    return [
        QueryResponse(
            record=Record(
                msg_id=mids[i],
                sender=snds[i],
                recipient=rcps[i],
                timestamp=int(ts[i]),
                payload=pls[i],
            ),
            status_code=int(status[i]),
        )
        for i in range(n)
    ]


class PendingRound:
    """Handle to a dispatched-but-unsynced round; ``resolve()`` blocks."""

    __slots__ = ("_engine", "_resp", "_n", "_t0", "_transcript", "_batch",
                 "_spans", "_enq", "_qdepth")

    def __init__(self, engine, resp, n, t0, transcript=None, batch=None,
                 spans=None):
        self._engine = engine
        self._resp = resp
        self._n = n
        self._t0 = t0
        #: leak-monitor hand-off (engine.leakmon set): the round's public
        #: transcript (still a device array — the copy happens on the
        #: monitor thread) plus the host-side batch dict its key groups
        #: derive from
        self._transcript = transcript
        self._batch = batch
        #: {phase: (start_s, dur_s)} spans recorded so far on the
        #: perf_counter clock (dispatch/journal/checkpoint) — the round
        #: tracer's ledger accumulates here, and the leak monitor's
        #: phase durations derive from it
        self._spans = spans
        #: perf_counter enqueue time of the round's OLDEST op, stamped
        #: by the scheduler (set_enqueued_at) — the SLO's enqueue→settle
        #: anchor; None on the direct (schedulerless) path
        self._enq = None
        #: scheduler queue depth at dispatch (ops left waiting after
        #: this round's chunk was taken) — the workload telemetry's
        #: backlog sample (obs/workload.py); None on the direct path
        self._qdepth = None

    def set_enqueued_at(self, t_enq: float) -> None:
        """Stamp the oldest op's enqueue time (perf_counter seconds);
        must be called before ``resolve()``."""
        self._enq = t_enq

    def set_queue_depth(self, depth: int) -> None:
        """Stamp the post-dispatch scheduler backlog (an aggregate of
        the queue, never of any op in it); must be called before
        ``resolve()``."""
        self._qdepth = int(depth)

    def note_span(self, name: str, start_s: float, dur_s: float) -> None:
        """Add a collector-side span (assembly/verify) to this round's
        ledger — exact pairing even under the pipelined scheduler, where
        a staged hand-off would attach round k+1's window to round k.
        Must be called before ``resolve()``."""
        if self._spans is None:
            self._spans = {}
        self._spans[name] = (start_s, dur_s)

    def resolve(self) -> list[QueryResponse]:
        m = self._engine.metrics
        # "evict" = device round completion measured from the host: the
        # jit'd fetch/apply/evict/write-back program finishes inside this
        # wait (per-stage device splits live in the profiler trace via
        # jax.named_scope — the host cannot time inside one XLA program)
        t_ev = time.perf_counter()
        with m.time_phase("evict"):
            jax.block_until_ready(self._resp)
        t_dm = time.perf_counter()
        with m.time_phase("demux"):
            out = unpack_responses(self._resp, self._n)
        t_done = time.perf_counter()
        # recorded duration = dispatch → results delivered. Under the
        # pipelined scheduler this includes the next round's collection
        # window (resolve runs after the next dispatch), i.e. it is the
        # round *commit latency* a client observes, not pure device time
        bs = self._engine.ecfg.batch_size
        m.record_round(self._n, bs, t_done - self._t0)
        spans = dict(self._spans or {})
        spans["evict"] = (t_ev, t_dm - t_ev)
        spans["demux"] = (t_dm, t_done - t_dm)
        # the host-observed device window (async enqueue → readiness
        # OBSERVED at resolve), emitted on EVERY config — durability on
        # or off — so the trace JSON shape is stable across configs
        # (obs/tracer.py zero-fills the journal/checkpoint spans it
        # never sees). Under the pipelined scheduler resolve runs after
        # the next round's collection window, so this is an UPPER bound
        # on device-busy time — exact only when the evict wait is
        # nonzero (the device was still running when the host arrived)
        spans["device"] = (self._t0, t_dm - self._t0)
        r0 = min(s for s, _ in spans.values())
        spans["round"] = (r0, t_done - r0)
        tracer = self._engine.tracer
        if tracer is not None:
            # a few dict ops + schema check; the ring is lock-cheap
            tracer.record_round(spans)
        slo = self._engine.slo
        if slo is not None:
            # enqueue→settle commit latency, worst op in the batch: the
            # scheduler stamped the oldest op's enqueue; the direct path
            # anchors at dispatch start (no queue wait to account)
            slo.observe(t_done - (self._enq if self._enq is not None else r0))
        wl = getattr(self._engine, "workload", None)
        if wl is not None:
            # batch fill + dispatch-time backlog + per-phase utilization
            # from this round's span ledger (obs/workload.py) — a few
            # histogram/gauge samples on the collector thread
            wl.observe_round(self._n, bs, self._qdepth, spans)
        cmn = getattr(self._engine, "costmon", None)
        if cmn is not None:
            # device span vs the modeled roofline floor (obs/costmon.py)
            # — two gauge sets per round
            cmn.observe_round(spans)
        lm = self._engine.leakmon
        if lm is not None and self._transcript is not None:
            # one non-blocking queue put; detectors run on the monitor's
            # own thread (obs/leakmon.py), never on the round path.
            # "device" stays tracer-only — the flightrec phase schema is
            # the canonical PHASES (+ round)
            phases = {k: d for k, (_, d) in spans.items() if k != "device"}
            lm.submit_round(self._batch, self._transcript, self._n, bs,
                            phases, queue_depth=self._qdepth)
        return out


class GrapevineEngine:
    """The in-process oblivious engine: the TPU analog of the enclave.

    Thread-safe facade owning device state; the gRPC server calls
    ``handle_queries`` with decrypted, authenticated requests and the
    expiry timer calls ``expire``.
    """

    def __init__(self, config: GrapevineConfig | None = None, seed: int = 0,
                 durability: DurabilityConfig | None = None):
        self.config = config or GrapevineConfig()
        self.ecfg = EngineConfig.from_config(self.config)
        self.state: EngineState = init_engine(self.ecfg, seed)
        #: bucket-axis sharding (config.py ``shards``; parallel/mesh.py):
        #: at shards > 1 the step/flush dispatch through the shard_map'd
        #: programs on a mesh over the first N devices. The adapters
        #: below keep the single-chip call signatures (ecfg, state, ...)
        #: so every dispatch/replay/flush site stays shard-agnostic —
        #: bit-identical results are the mesh contract, so nothing
        #: downstream (journal, checkpoint, leakmon, oracle suites) can
        #: tell the difference.
        self._mesh = None
        if self.config.shards > 1:
            from ..parallel import (
                make_mesh, make_sharded_step, shard_engine_state,
            )

            devs = jax.devices()
            if len(devs) < self.config.shards:
                raise ValueError(
                    f"shards={self.config.shards} but only {len(devs)} "
                    "JAX device(s) are visible — the bucket trees shard "
                    "one contiguous heap range per device"
                )
            self._mesh = make_mesh(devs[: self.config.shards])
            self._shard_state = shard_engine_state
            self.state = shard_engine_state(self.state, self._mesh)
            sstep = make_sharded_step(self.ecfg, self._mesh)
            step_fn = lambda _ecfg, state, batch: sstep(state, batch)  # noqa: E731
            self._step = step_fn
        else:
            step_fn = (engine_round_step if self.config.commit == "phase"
                       else engine_step)
            # donate the state: trees update in place (no per-round copy,
            # and the fused pallas scatter's input/output aliasing would
            # otherwise force XLA to defensively copy both tree arrays)
            self._step = jax.jit(
                step_fn, static_argnums=(0,), donate_argnums=(1,)
            )
        self._sweep = jax.jit(
            expiry_sweep, static_argnums=(0,), donate_argnums=(1,)
        )
        #: delayed batched eviction (PR 15, config.py evict_every): the
        #: resolved cadence E and the jitted flush program. Flush fires
        #: strictly every E dispatched rounds — a pure function of the
        #: round counter, never of buffer contents or op mix (the
        #: schedule-independence claim CI pins) — inside the same lock
        #: hold as the E-th round, journaled (KIND_FLUSH) before it
        #: dispatches like everything else. The counter itself is
        #: recovered from state (rec.ebuf_rounds) so a crash can never
        #: desynchronize cadence from content.
        self.evict_every = self.ecfg.evict_every
        if self.evict_every <= 1:
            self._flush_step = None
        elif self._mesh is not None:
            from ..parallel import make_sharded_flush

            sflush = make_sharded_flush(self.ecfg, self._mesh)
            self._flush_step = lambda _ecfg, state: sflush(state)
        else:
            self._flush_step = jax.jit(
                engine_flush_step, static_argnums=(0,), donate_argnums=(1,)
            )
        self._rounds_since_flush = 0
        #: replay-time cadence audit (see _replay_record): rounds seen
        #: since the last KIND_FLUSH record; None until the first
        #: replayed record initializes it from the recovered state
        self._replay_since: int | None = None
        self._lock = threading.Lock()
        #: resolved round-pipeline depth: the max dispatched-but-
        #: unresolved rounds a driver keeps in flight (config.py knob;
        #: module docstring). Deliberately NOT part of EngineConfig —
        #: the checkpoint/journal fingerprint must not cover it, because
        #: a journal written at depth 2 replays bit-identically on a
        #: depth-1 engine (replay order is journal order at every
        #: depth; tests/test_pipeline.py pins the cross-depth restore).
        #: Auto: 2 on TPU backends (the device round is the long pole —
        #: overlapping host work and the journal fsync behind it is the
        #: whole win, priced on-chip by tools/tpu_capture.py
        #: ``pipeline_perf``), 1 elsewhere — on a host-bound CPU the
        #: extra in-flight round buys no overlap but costs up to one
        #: full device round of open-loop commit latency (measured:
        #: PERF.md Round 11; the vphases/sort flip-on-evidence playbook)
        if self.config.pipeline_depth is not None:
            self.pipeline_depth = self.config.pipeline_depth
        else:
            from ..config import TPU_BACKENDS

            self.pipeline_depth = (
                2 if jax.default_backend() in TPU_BACKENDS else 1
            )
        self.metrics = EngineMetrics()
        #: last sampled per-tree eviction-buffer occupancy (health view)
        self._ebuf_counts: dict = {}
        #: streaming obliviousness auditor (obs/leakmon.py), attached by
        #: the serving layer when --leakmon is on; None = no monitoring
        self.leakmon = None
        #: round-trace profiler (obs/tracer.py) and commit-latency SLO
        #: tracker (obs/slo.py), attached by the serving layer; None =
        #: rounds are not traced / measured against an SLO
        self.tracer = None
        self.slo = None
        #: workload telemetry (obs/workload.py): batch fill / queue
        #: depth / arrival-rate / utilization signals, attached by the
        #: serving layer or the load harness; None = not sampled
        self.workload = None
        #: cost observatory (obs/costmon.py): static grapevine_cost_*
        #: ledger gauges plus the per-round roofline residual, attached
        #: by the serving layer; None = rounds are not scored
        self.costmon = None
        #: crash safety (engine/checkpoint.py): with a DurabilityConfig,
        #: every admitted batch is journaled before dispatch and the
        #: whole state checkpointed every N records; construction runs
        #: recovery (checkpoint load + deterministic journal replay), so
        #: a freshly built engine already holds the pre-crash state
        self.durability = None
        if durability is not None:
            from .checkpoint import DurabilityManager

            self.durability = DurabilityManager(
                durability, self.ecfg, registry=self.metrics.registry
            )
            with self.metrics.time_phase("replay"):
                self.state = self.durability.recover(
                    self.state, self._replay_record
                )
                if self._mesh is not None:
                    # a loaded checkpoint materializes host-side on the
                    # default device; re-place it on the mesh so the
                    # first live round doesn't pay an implicit reshard
                    # (replayed rounds already ran the sharded program,
                    # so this is a no-op re-placement in that case)
                    self.state = self._shard_state(self.state, self._mesh)
                jax.block_until_ready(self.state.free_top)
        if self.evict_every > 1:
            # cadence counter recovered FROM STATE, never from a host
            # mirror: the records tree runs exactly one fetch round per
            # engine round, so its window counter IS rounds-since-flush
            self._rounds_since_flush = int(self.state.rec.ebuf_rounds)
            if self._rounds_since_flush >= self.evict_every:
                # a crash landed between the E-th round's journal frame
                # and its flush frame — complete the pending flush NOW
                # (journaled), so the replayed journal keeps the exact
                # [round_E, flush] adjacency an uninterrupted run writes
                # and recovered placement stays bit-identical to it
                with self._lock:
                    self._flush_window_locked(min_rounds=self.evict_every)
                jax.block_until_ready(self.state.free_top)

    def _replay_record(self, state: EngineState, rec) -> EngineState:
        """Apply one journal record through the same jitted programs the
        live path uses — replay IS re-execution, so recovered state is
        bit-identical by the engine's own determinism.

        Cadence audit: the journal frames validate batch geometry but
        not the eviction cadence (the checkpoint fingerprint covers E;
        a journal-only recovery would not), so replay cross-checks it —
        a KIND_FLUSH record on an evict_every=1 engine, or more rounds
        than one window between flush records on an E>1 engine, means
        the journal was written under a DIFFERENT cadence and silently
        replaying it would corrupt the window ledger. Raise instead."""
        from .journal import JournalError, KIND_FLUSH, KIND_ROUND

        if self._flush_step is not None and self._replay_since is None:
            # one device read at replay start: the recovered base
            # state's window position anchors the cadence count
            self._replay_since = int(state.rec.ebuf_rounds)
        if rec.kind == KIND_ROUND:
            if self._flush_step is not None:
                self._replay_since += 1
                if self._replay_since > self.evict_every:
                    raise JournalError(
                        f"journal frame {rec.seq}: {self._replay_since} "
                        f"rounds since the last flush record but this "
                        f"engine flushes every {self.evict_every} — the "
                        "journal was written under a different "
                        "evict_every; replay requires the identical "
                        "cadence"
                    )
            state, _resp, _transcript = self._step(self.ecfg, state, rec.batch)
            return state
        if rec.kind == KIND_FLUSH:
            if self._flush_step is None:
                raise JournalError(
                    f"journal frame {rec.seq}: delayed-eviction flush "
                    "record but this engine runs evict_every=1 — replay "
                    "requires the cadence the journal was written under"
                )
            self._replay_since = 0
            return self._flush_step(self.ecfg, state)
        return self._sweep(
            self.ecfg, state,
            np.uint32(rec.now), np.uint32(rec.period), np.uint32(rec.now_hi),
        )

    # -- delayed batched eviction (PR 15; oram/round.py:oram_flush) -----

    def _flush_window_locked(self, count_round: bool = False,
                             min_rounds: int = 1) -> bool:
        """Journal + dispatch one flush when the window is due; caller
        holds the engine lock (every call site sits directly in a lock
        region — analysis/locklint.py verifies it statically).

        ``count_round=True`` counts one dispatched round first and
        flushes only when the window closes (the steady-state cadence —
        a pure function of the round counter, never of buffer
        contents); ``count_round=False`` flushes iff at least
        ``min_rounds`` rounds are buffered (recovery completion passes
        ``min_rounds=evict_every`` so a crash mid-window never flushes
        early; ``flush_now`` passes 1). The async dispatch is the
        point: the flush rides the device queue behind the window's
        last round, filling the idle window the bubble-ratio gauge
        prices (tools/tpu_capture.py ``evict_perf`` banks the on-chip
        overlap number) — the ``flush`` phase series measures enqueue
        cost; device time lands in the next round's ``evict`` wait
        like all device work."""
        if self._flush_step is None:
            return False
        if count_round:
            self._rounds_since_flush += 1
        due = self.evict_every if count_round else max(1, min_rounds)
        if self._rounds_since_flush < due:
            return False
        if self.durability is not None:
            with self.metrics.time_phase("journal"):
                self.durability.append_flush()
        if faults.active():
            # the kill-at-flush window: the flush frame is durable but
            # the flush itself has not dispatched
            faults.crash("flush.pre_dispatch")
        with self.metrics.time_phase("flush"):
            self.state = self._flush_step(self.ecfg, self.state)
        self.metrics.record_flush()
        if faults.active():
            faults.crash("flush.post_dispatch")
        lm = self.leakmon
        if lm is not None:
            # flush-cadence audit (obs/leakmon.py note_flush): report
            # the observed interval before the counter resets; only the
            # automatic cadence is judged (count_round)
            note = getattr(lm, "note_flush", None)
            if note is not None:
                note(self._rounds_since_flush, scheduled=count_round)
        self._rounds_since_flush = 0
        return True

    def flush_now(self) -> bool:
        """Operator/test hook: flush a partial window immediately
        (journaled). Returns False when delayed eviction is off or the
        window is empty. NOT part of the steady-state cadence — the
        schedule-independence claim is about the automatic trigger."""
        with self._lock:
            return self._flush_window_locked()

    def flush_bubble_pending(self) -> bool:
        """True between a flush dispatch and the next round dispatch:
        the NEXT collection window overlaps the flush's device time (the
        bubble the scheduler's flush-aware stretch fills — server/
        scheduler.py). A pure function of the cadence counter — which is
        itself a pure function of the round count — never of buffer
        contents or op mix, so the stretched window leaks nothing the
        round counter does not (the schedule-independence claim;
        analysis/mutants.py seeds the contents-dependent variant).
        Engine start reads as a bubble too: the first window overlaps
        compilation, which is the same trade. Benign unlocked int read.
        """
        return self._flush_step is not None and self._rounds_since_flush == 0

    def checkpoint_now(self) -> int | None:
        """Force a sealed checkpoint of the current state (the drain
        path: scheduler settled → checkpoint → exit). No-op returning
        None without durability."""
        if self.durability is None:
            return None
        with self._lock:
            with self.metrics.time_phase("checkpoint"):
                return self.durability.checkpoint(self.state)

    def attach_leakmon(self, monitor) -> None:
        """Attach an EngineLeakMonitor; subsequent rounds hand their
        transcripts to it off the jit path (PendingRound.resolve)."""
        self.leakmon = monitor

    def attach_tracer(self, tracer) -> None:
        """Attach a RoundTracer; subsequent rounds append their span
        ledgers to its ring (PendingRound.resolve)."""
        self.tracer = tracer

    def attach_slo(self, slo) -> None:
        """Attach an SloTracker; subsequent rounds observe their
        enqueue→settle commit latency against it."""
        self.slo = slo

    def attach_workload(self, workload) -> None:
        """Attach a WorkloadTelemetry; subsequent rounds observe their
        fill/backlog/utilization and the scheduler notes arrivals."""
        self.workload = workload

    def attach_costmon(self, costmon) -> None:
        """Attach a CostMonitor; subsequent rounds score their device
        span against the modeled roofline floor."""
        self.costmon = costmon

    def calibrate_sort_phase(self, reps: int = 5) -> float:
        """Measure the round's bounded-key sort workload standalone and
        record it under the ``sort`` phase (obs/phases.py).

        The host cannot time inside the fused round program, but every
        sort the round runs is shape-static and data-independent
        (oblivious), so a standalone jitted run of the same sort
        machinery at the same geometry IS the per-round sort cost. The
        workload reproduces each sort site at its round shape under the
        engine's configured ``sort_impl``/``vphases_impl``: the three
        eviction leaf-rank sorts at their working-set sizes, the
        admission walk's slot grouping (both vphases impls), and —
        scan impl — the three dedup group sorts, the per-phase
        bucket/record index group sorts, and the wide-key recipient
        grouping sort (always ``lax.sort``, counted because the round
        pays it). Called once at serving startup (CLI engine/mono
        roles) — one small jit compile, zero hot-path cost. Returns
        the min-of-``reps`` seconds (the unbiased estimator for a
        shape-static program under scheduler noise).
        """
        ecfg = self.ecfg
        b, d = ecfg.batch_size, ecfg.mb_choices
        jobs = []  # one per ORAM round: A (mailbox), B (records), C (mailbox)
        for cfg, nb in ((ecfg.mb, b * d), (ecfg.rec, b), (ecfg.mb, b * d)):
            w = cfg.stash_size + nb * cfg.path_len * cfg.bucket_slots + nb
            jobs.append(
                (w, cfg.height, max(1, cfg.dummy_index.bit_length()), nb)
            )
        simpl, vimpl = ecfg.sort_impl, ecfg.vphases_impl
        slot_bits = max(1, (b - 1).bit_length())
        # per-phase index group bounds (vphases._index_groups): bucket
        # groups in rounds A/C, record-block groups in round B
        g_bits = (
            max(1, (ecfg.mb_table_buckets + 1 + b - 1).bit_length()),
            max(1, (ecfg.rec.blocks + 1 + b - 1).bit_length()),
            max(1, (ecfg.mb_table_buckets + 1 + b - 1).bit_length()),
        )

        def workload(key):
            from ..oblivious.radix import radix_group_sort, radix_rank
            from ..oblivious.segmented import (
                group_sort,
                multiword_group_sort,
            )

            u32 = jnp.uint32
            outs = []
            ks = jax.random.split(key, 3 * len(jobs) + 2)
            for i, (w, h, kb, nb) in enumerate(jobs):
                leaf = jax.random.bits(ks[3 * i], (w,), u32) & u32(
                    (1 << h) - 1
                )
                if simpl == "radix":
                    outs.append(radix_rank(leaf, h + 1))
                else:
                    outs.append(jnp.argsort(leaf))
                if vimpl == "scan":
                    idxs = jax.random.bits(ks[3 * i + 1], (nb,), u32) & u32(
                        (1 << kb) - 1
                    )
                    gs = (
                        radix_group_sort([idxs], kb)
                        if simpl == "radix"
                        else multiword_group_sort([idxs])
                    )
                    outs.extend(gs)
                    gi = jax.random.bits(ks[3 * i + 2], (b,), u32) & u32(
                        (1 << g_bits[i]) - 1
                    )
                    outs.extend(
                        group_sort(gi, sort_impl=simpl, key_bits=g_bits[i])
                    )
            # admission slot grouping (runs under BOTH vphases impls)
            rslot = jax.random.bits(ks[-2], (b,), u32) & u32(
                (1 << slot_bits) - 1
            )
            outs.extend(
                group_sort(rslot, sort_impl=simpl, key_bits=slot_bits)
            )
            if vimpl == "scan":
                # recipient grouping: 10-word wide key, always lax.sort
                kcols = [
                    jax.random.bits(ks[-1], (b,), u32) for _ in range(10)
                ]
                outs.extend(multiword_group_sort(kcols))
            return outs

        fn = jax.jit(workload)
        key = jax.random.PRNGKey(0)
        jax.block_until_ready(fn(key))  # compile + warm
        best = None
        for _ in range(max(1, reps)):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(key))
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        self.metrics.observe_phase("sort", best)
        return best

    def calibrate_posmap_phase(self, reps: int = 5) -> float:
        """Measure the round's position-resolution workload standalone
        and record it under the ``posmap`` phase (obs/phases.py).

        Same calibration stance as ``calibrate_sort_phase``: the host
        cannot time inside the fused round program, but position
        resolution is shape-static and data-independent (that is the
        whole obliviousness claim — tools/check_posmap_oblivious.py), so
        a standalone jitted run of the SAME ``lookup_remap_round``
        machinery at the round's exact geometry — all three ORAM rounds'
        batch lookups (mailbox A, records B, mailbox C) — IS the
        per-round position-handling cost. Under ``posmap_impl="flat"``
        that is one private gather + scatter per round; under
        ``"recursive"`` it is the internal ORAM's full rounds, which is
        exactly the number /trace needs to attribute separately from
        ``oram_evict``. One small jit compile at serving startup, zero
        hot-path cost; min-of-``reps`` seconds returned.
        """
        import time as _time

        from ..oram.posmap import init_posmap, lookup_remap_round
        from ..oram.round import occurrence_masks, occurrence_masks_sorted

        ecfg = self.ecfg
        b, d = ecfg.batch_size, ecfg.mb_choices
        jobs = [(ecfg.mb, b * d), (ecfg.rec, b), (ecfg.mb, b * d)]
        occ, simpl = ecfg.vphases_impl, ecfg.sort_impl

        # fresh per-tree posmap pytrees at the engine's geometry: the
        # cost is data-independent, so a fresh state prices the live one
        # without touching device state under the lock. init_posmap, not
        # init_oram — materializing full payload-scale trees just to
        # read .posmap would transiently double tree memory at startup
        pms = [
            init_posmap(cfg, jax.random.PRNGKey(17 + i))
            for i, (cfg, _) in enumerate(jobs)
        ]

        def workload(key, pms):
            outs = []
            ks = jax.random.split(key, 4 * len(jobs))
            for i, (cfg, nb) in enumerate(jobs):
                u32 = jnp.uint32
                idxs = jax.random.bits(ks[4 * i], (nb,), u32) % u32(
                    cfg.blocks + 1
                )
                nl = jax.random.bits(ks[4 * i + 1], (nb,), u32) & u32(
                    cfg.leaves - 1
                )
                dl = jax.random.bits(ks[4 * i + 2], (nb,), u32) & u32(
                    cfg.leaves - 1
                )
                if occ == "scan":
                    fo, lo, _ = occurrence_masks_sorted(
                        idxs, cfg.dummy_index, sort_impl=simpl,
                        key_bits=max(1, cfg.dummy_index.bit_length()),
                    )
                else:
                    fo, lo, _ = occurrence_masks(idxs, cfg.dummy_index)
                pm_nl = pm_dl = None
                if cfg.posmap is not None:
                    il = cfg.posmap.inner_leaves
                    pm_bits = jax.random.bits(ks[4 * i + 3], (2, nb), u32)
                    pm_nl = pm_bits[0] & u32(il - 1)
                    pm_dl = pm_bits[1] & u32(il - 1)
                pm2, leaves, inner = lookup_remap_round(
                    cfg, pms[i], idxs, nl, dl, fo, lo,
                    pm_new_leaves=pm_nl, pm_dummy_leaves=pm_dl,
                    occ_impl=occ, sort_impl=simpl,
                )
                # the updated map must be a live output — an unused pm2
                # lets XLA dead-code-eliminate the remap scatter (flat)
                # / the internal round's eviction write-back (recursive)
                # and the phase gauge would undercount
                outs.append((pm2, leaves))
                if inner is not None:
                    outs.append(inner)
            return outs

        fn = jax.jit(workload)
        key = jax.random.PRNGKey(0)
        jax.block_until_ready(fn(key, pms))  # compile + warm
        best = None
        for _ in range(max(1, reps)):
            t0 = _time.perf_counter()
            jax.block_until_ready(fn(key, pms))
            dt = _time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        self.metrics.observe_phase("posmap", best)
        return best

    def handle_queries(
        self, reqs: list[QueryRequest], now: int
    ) -> list[QueryResponse]:
        """Process requests in slot order (padding to full batches).

        Atomicity is **per round**, not per call: the engine lock is
        taken per batch_size chunk, so two concurrent multi-batch calls
        may interleave at round boundaries (each round itself is atomic
        and slot-ordered). This is intended — it is exactly the
        interleaving concurrent gRPC clients produce through the
        scheduler, and the soak suite exercises it; a caller needing a
        multi-round transaction must hold its own lock.

        Multi-chunk calls pipeline: up to ``pipeline_depth`` chunks stay
        dispatched-but-unresolved, so chunk k+1's pack + journal fsync
        overlap chunk k's device execution. Responses come back in
        request order regardless (rounds resolve in dispatch order), and
        depth 1 is bit-for-bit the serial resolve-before-next-dispatch
        program."""
        for r in reqs:  # all-or-nothing: nothing commits if any is malformed
            validate_request(r)
        out: list[QueryResponse] = []
        bs = self.ecfg.batch_size
        depth = max(1, self.pipeline_depth)
        ledger: deque[PendingRound] = deque()
        # resolve everything dispatched even when a dispatch or an
        # earlier resolve raises — an abandoned PendingRound would leave
        # its journal/leakmon/metrics hand-off forever unaccounted. The
        # FIRST exception stays the primary one; the drain never stops
        # on a failed resolve.
        exc0: BaseException | None = None
        try:
            for i in range(0, len(reqs), bs):
                while len(ledger) >= depth:
                    out.extend(ledger.popleft().resolve())
                ledger.append(
                    self.handle_queries_async(reqs[i : i + bs], now)
                )
        except BaseException as exc:
            exc0 = exc
        while ledger:
            try:
                out.extend(ledger.popleft().resolve())
            except BaseException as exc:
                if exc0 is None:
                    exc0 = exc
        if exc0 is not None:
            raise exc0
        return out

    # -- the staged round pipeline (module docstring; OPERATIONS.md §16)

    def _assemble_round(self, reqs: list[QueryRequest], now: int) -> dict:
        """Stage 1 — assemble: validate + pack the wire records into the
        fixed-size device batch. Lock-free host work; under the
        pipelined scheduler this runs while earlier rounds execute."""
        for r in reqs:
            validate_request(r)
        if int(now) <= 0:
            raise ValueError("server clock must be positive")
        bs = self.ecfg.batch_size
        if len(reqs) > bs:
            raise ValueError("async path is one round at a time")
        return pack_batch(reqs, bs, now)

    def _journal_round(self, batch: dict, n_real: int, spans: dict) -> None:
        """Stage 2 — journal: sealed append + fsync barrier (per
        ``journal_fsync_every``) BEFORE the round may dispatch — the
        crash-safety contract. Runs under the engine lock in the same
        hold as stage 3, so journal order IS dispatch order and replay
        order is journal order at every pipeline depth. With a round
        already in flight (pipeline_depth=2) the fsync overlaps its
        device execution instead of serializing with it — the PR-10
        point; the "journal" series isolates what it costs."""
        if self.durability is not None:
            t_j0 = time.perf_counter()
            self.durability.append_round(batch, n_real)
            j_s = time.perf_counter() - t_j0
            self.metrics.observe_phase("journal", j_s)
            spans["journal"] = (t_j0, j_s)
        if faults.active():
            # the pipelined crash window: this round is durable (its
            # frame is fsynced) but not yet dispatched, while the
            # previous round may still be mid-flight on the device
            faults.crash("round.pre_dispatch")

    def _dispatch_round(self, batch: dict):
        """Stage 3 — dispatch: enqueue the jit'd round on the device and
        chain ``self.state`` onto its (donated) output. JAX dispatch is
        asynchronous — this returns at enqueue, and with two rounds in
        flight XLA rotates two donated state buffers. Same lock hold as
        stage 2 (see there)."""
        t0 = time.perf_counter()
        self.state, resp, transcript = self._step(
            self.ecfg, self.state, batch
        )
        return t0, resp, transcript

    def handle_queries_async(
        self, reqs: list[QueryRequest], now: int
    ) -> "PendingRound":
        """Dispatch one round without waiting for the device.

        Composes pipeline stages 1-3 (assemble → journal+fsync →
        dispatch) and returns the round's handle; ``resolve()`` is stage
        4. JAX dispatch is asynchronous: this returns as soon as the
        round is enqueued, so a caller (the scheduler, or
        ``handle_queries`` on a multi-chunk call) can assemble, verify,
        and journal the *next* round — and keep up to ``pipeline_depth``
        rounds un-resolved — while the device executes this one (the
        dispatch/compute overlap PERF.md's cost model calls for).
        Rounds are serialized by the engine lock; ``resolve()`` blocks
        for the results."""
        batch = self._assemble_round(reqs, now)
        lm = self.leakmon
        with self._lock:
            # "dispatch" = async device enqueue (JAX returns at
            # enqueue; the device round itself lands in "evict"); the
            # host pack now runs in stage 1 OUTSIDE the lock, where the
            # pipeline can overlap it. With durability on, dispatch
            # also spans the journal barrier — append-before-dispatch
            # is the crash-safety contract, and its fsync is genuinely
            # part of the commit latency (the "journal" series
            # isolates it).
            t_d0 = time.perf_counter()
            spans: dict = {}
            with self.metrics.time_phase("dispatch"):
                self._journal_round(batch, len(reqs), spans)
                t0, resp, transcript = self._dispatch_round(batch)
            if faults.active():
                faults.crash("round.post_dispatch")
            # delayed eviction: the E-th round's flush journals and
            # dispatches in this same hold — the flush enqueues behind
            # the round on the device and resolves inside the next
            # round's evict wait (the overlap window). The span lands
            # on THIS round's ledger (the window-closing round), so the
            # tracer and flight recorder show which rounds paid a flush
            # enqueue — the cadence is public (a pure round count)
            t_f0 = time.perf_counter()
            if self._flush_window_locked(count_round=True):
                spans["flush"] = (t_f0, time.perf_counter() - t_f0)
            if self.durability is not None and self.durability.should_checkpoint():
                # blocks this round's slot until the sealed state is on
                # disk — the RTO/RPO trade --checkpoint-every-rounds
                # buys. state_to_bytes waits for every dispatched round
                # (this one included), so the sealed state is exactly
                # the journal's seq even with the pipeline full — the
                # checkpoint is itself a pipeline barrier.
                t_c0 = time.perf_counter()
                with self.metrics.time_phase("checkpoint"):
                    self.durability.checkpoint(self.state)
                spans["checkpoint"] = (t_c0, time.perf_counter() - t_c0)
            spans["dispatch"] = (t_d0, time.perf_counter() - t_d0)
        if lm is None:
            return PendingRound(self, resp, len(reqs), t0, spans=spans)
        # hand the monitor only the key-material columns: retaining the
        # full batch dict would pin the (B, PAYLOAD_WORDS) payload array
        # in the monitor queue for grouping that never reads it
        key_cols = {
            k: batch[k] for k in ("req_type", "auth", "msg_id", "recipient")
        }
        return PendingRound(
            self, resp, len(reqs), t0,
            transcript=transcript, batch=key_cols, spans=spans,
        )

    def handle_queries_with_transcript(self, reqs, now):
        """Test/bench variant returning the public transcript as well."""
        for r in reqs:
            validate_request(r)
        bs = self.ecfg.batch_size
        if len(reqs) > bs:
            raise ValueError("single batch only")
        # stage-1 pack stays outside the lock, same staging as the
        # async path (analysis/locklint.py flags pack-under-lock)
        batch = pack_batch(reqs, bs, now)
        with self._lock:
            if self.durability is not None:  # same contract as the async path
                self.durability.append_round(batch, len(reqs))
            self.state, resp, transcript = self._step(self.ecfg, self.state, batch)
            out = unpack_responses(resp, len(reqs)), np.asarray(transcript)
            self._flush_window_locked(count_round=True)
            return out

    def expire(self, now: int, period: int | None = None) -> int:
        """Run the expiry sweep; returns the number of records evicted."""
        period = self.config.expiry_period if period is None else period
        if period <= 0:
            return 0
        with self._lock:
            before = int(self.state.free_top)
            if self.durability is not None:
                # journal-before-mutate, same as rounds: a crash between
                # append and apply replays the sweep (apply ≡ replay)
                self.durability.append_sweep(
                    int(now) & 0xFFFFFFFF, (int(now) >> 32) & 0xFFFFFFFF,
                    int(period),
                )
            with self.metrics.time_phase("sweep"):
                self.state = self._sweep(
                    self.ecfg,
                    self.state,
                    np.uint32(int(now) & 0xFFFFFFFF),
                    np.uint32(period),
                    np.uint32((int(now) >> 32) & 0xFFFFFFFF),
                )
                jax.block_until_ready(self.state.free_top)
            evicted = int(self.state.free_top) - before
            self.metrics.record_sweep(evicted)
            if self.durability is not None and self.durability.should_checkpoint():
                # sweeps count against the cadence like rounds do — an
                # idle server with expiry on must not grow the journal
                # (and its replay-time RTO) without bound
                with self.metrics.time_phase("checkpoint"):
                    self.durability.checkpoint(self.state)
            return evicted

    def close(self) -> None:
        """Flush and close the durability store (if any)."""
        if self.durability is not None:
            with self._lock:
                self.durability.close()

    # -- metrics (never keyed by client identity; SURVEY.md §5) ---------

    def message_count(self) -> int:
        return self.ecfg.max_messages - int(self.state.free_top)

    def recipient_count(self) -> int:
        return int(self.state.recipients)

    def sample_stash(self) -> dict:
        """Sample stash occupancy of both trees into the metrics gauges;
        returns the per-tree counts so health() reuses them instead of
        re-running the device reductions under the lock.

        Called at scrape/health cadence, not per round: a device
        reduction every round would serialize the dispatch pipeline for
        a gauge that is only read between scrapes (it is also the
        /metrics endpoint's pre-scrape refresh hook, obs/httpd.py)."""
        from ..oram.path_oram import evict_buffer_occupancy, stash_occupancy

        with self._lock:
            state = self.state
            trees = {"rec": state.rec, "mb": state.mb}
            if self.ecfg.rec.posmap is not None:
                # recursive position maps (oram/posmap.py) carry their
                # own internal ORAM whose stash fills under the same
                # pressure — invisible here would mean silent position
                # loss with a green gauge
                trees["rec_pm"] = state.rec.posmap.inner
                trees["mb_pm"] = state.mb.posmap.inner
            counts = {
                name: int(stash_occupancy(tree))
                for name, tree in trees.items()
            }
            ebuf = (
                {
                    name: int(evict_buffer_occupancy(tree))
                    for name, tree in trees.items()
                }
                if self.evict_every > 1
                else {}
            )
            self._ebuf_counts = ebuf
        for n in counts.values():
            self.metrics.observe_stash(n)
        if ebuf:
            # the buffer-occupancy canary (grapevine_evict_buffer_*):
            # summed over trees at scrape cadence, high-water kept —
            # approaching evict_buffer_slots means the sizing theory is
            # being violated before overflow ever fires
            self.metrics.observe_evict_buffer(sum(ebuf.values()))
        return counts

    def health(self) -> dict:
        """Aggregate state + batch-level counters (never per-client)."""
        # per-tree stash occupancy, batch-level (a tree-top cache bug
        # would first show up as silent stash drift — the directed
        # cached↔uncached soak in tests/test_tree_cache.py reads these,
        # and operators get the same early signal)
        occupancy = self.sample_stash()
        with self._lock:
            state = self.state  # one round's state for a consistent snapshot
            overflow = int(state.rec.overflow) + int(state.mb.overflow)
            if self.ecfg.rec.posmap is not None:
                # internal position-ORAM overflow loses k position
                # entries per dropped block — every bit as unhealthy as
                # payload stash loss
                overflow += int(state.rec.posmap.inner.overflow)
                overflow += int(state.mb.posmap.inner.overflow)
            out = {
                "messages": self.ecfg.max_messages - int(state.free_top),
                "recipients": int(state.recipients),
                "stash_overflow": overflow,
                "stash_occupancy": occupancy,
                **self.metrics.snapshot(),
            }
            if self.evict_every > 1:
                # delayed-eviction canary: per-tree buffer occupancy
                # (sampled by sample_stash above) + capacity, so an
                # operator sees near-overflow pressure before the shared
                # sticky overflow counter ever fires. Buffer overflow
                # rides stash_overflow — the buffer has the stash's
                # standing, and a drop is a drop.
                out["evict_buffer_occupancy"] = dict(
                    getattr(self, "_ebuf_counts", {})
                )
                out["evict_buffer_slots"] = {
                    "rec": self.ecfg.rec.evict_buffer_slots,
                    "mb": self.ecfg.mb.evict_buffer_slots,
                }
                out["evict_rounds_since_flush"] = self._rounds_since_flush
            return out
