"""The batched query step: uniform [mailbox, records, mailbox] access rounds.

Implements the complete CRUD semantics of the reference spec
(grapevine.proto:57-122) as one branchless program per operation,
sequentially committed over the batch under ``lax.scan`` (within-batch
ordering = slot order; the reference never faced batch hazards — this
framework documents slot-order commit, SURVEY.md §7.6).

Why three phases, and what each decides (designed so that *every* op type
touches the two ORAMs in the identical pattern, grapevine.proto:120-122):

- **Phase A** (mailbox bucket of the operative recipient key):
  CREATE runs all its capacity checks and appends the new entry (every
  failure mode of CREATE is decidable here: zero recipient and bus-full
  are known before any access, mailbox-cap and table-room are properties
  of this bucket). Zero-id READ/DELETE select the oldest entry (min seq);
  zero-id DELETE removes it immediately (the mailbox invariant guarantees
  phase B succeeds). Other ops read and write back unchanged.
- **Phase B** (records block): full id verification, auth check
  (sender-or-recipient, reference grapevine.proto:83-86), recipient-match
  check for UPDATE/DELETE (grapevine.proto:101-113), payload/timestamp
  rewrite for UPDATE, removal for DELETE, insertion for CREATE.
- **Phase C** (same mailbox bucket again): sender-authorized DELETE
  removal (needed B's sender check), UPDATE's entry-timestamp refresh
  (keeps mailbox expiry in sync with the record), dummies elsewhere.

The msg_id returned by CREATE is [PRP(nonce, block_index), r2, r3|1] —
random and nonzero as required (grapevine.proto:66-79). Words 0-1 are
the record's physical block index plus a fresh 32-bit nonce, jointly
encrypted under a secret per-bus Feistel PRP (oblivious/prp.py), so
lookup needs no id→block oblivious map while clients learn nothing
about allocator state from their ids (the nonce keeps LIFO block reuse
invisible); MESSAGE_ID_ALREADY_IN_USE is structurally unreachable (the
reference deems collisions "unlikely"; here the id map is a bijection).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..oblivious.primitives import (
    argmin_u64_onehot,
    first_true_onehot,
    is_zero_words,
    onehot_select,
    words_equal,
)
from ..oblivious.prp import prp2_decrypt, prp2_encrypt
from ..wire import constants as C
from ..oram.path_oram import oram_access
from .responses import assemble_responses
from ..oblivious.primitives import u64_add_u32
from .state import (
    ENT_BLK,
    ENT_IDW,
    ENT_SEQ,
    ENT_SEQH,
    ENT_TS,
    ENT_TSH,
    ENTRY_WORDS,
    EngineConfig,
    EngineState,
    REC_ID,
    REC_PAYLOAD,
    REC_RECIPIENT,
    REC_SENDER,
    REC_TS,
    REC_TSH,
    mb_bucket_hash,
    mb_pack,
    mb_parse,
)

U32 = jnp.uint32


def _phase_a(ecfg: EngineConfig, value, present, o):
    keys, entries = mb_parse(ecfg, value)
    key_valid = ~is_zero_words(keys)
    slot_match = key_valid & words_equal(keys, o["ka"][None, :])
    found = jnp.any(slot_match)
    free_slot_oh = first_true_onehot(~key_valid)
    has_free_slot = jnp.any(~key_valid)
    tgt_oh = jnp.where(found, slot_match, free_slot_oh)

    tgt_entries = onehot_select(tgt_oh, entries)  # [cap, ENTRY_WORDS]
    ent_valid = (tgt_entries[:, ENT_SEQ] | tgt_entries[:, ENT_SEQH]) != 0
    count = jnp.sum(ent_valid.astype(jnp.int32))

    # --- CREATE decision tree (status precedence documented in
    # testing/reference.py) -------------------------------------------
    room_for_new_recipient = has_free_slot & (o["recipients"] < ecfg.max_recipients)
    cap_ok = count < ecfg.mailbox_cap
    create_ok = (
        o["is_create"]
        & ~o["zero_recip"]
        & o["can_alloc"]
        & (found | room_for_new_recipient)
        & cap_ok
    )
    status_a = jnp.where(
        o["zero_recip"],
        C.STATUS_CODE_INVALID_RECIPIENT,
        jnp.where(
            ~o["can_alloc"],
            C.STATUS_CODE_TOO_MANY_MESSAGES,
            jnp.where(
                ~found & ~room_for_new_recipient,
                C.STATUS_CODE_TOO_MANY_RECIPIENTS,
                jnp.where(
                    ~cap_ok,
                    C.STATUS_CODE_TOO_MANY_MESSAGES_FOR_RECIPIENT,
                    C.STATUS_CODE_SUCCESS,
                ),
            ),
        ),
    ).astype(U32)

    # --- zero-id selection: oldest entry (min seq) ---------------------
    sel_oh, sel_found = argmin_u64_onehot(
        ent_valid, tgt_entries[:, ENT_SEQH], tgt_entries[:, ENT_SEQ]
    )
    sel_entry = onehot_select(sel_oh, tgt_entries)
    sel_found = sel_found & found

    # --- zero-id DELETE ("pop next") removal ---------------------------
    # Only the zero-id case may act here: the selected entry's record is
    # guaranteed live with recipient == the mailbox key (invariant), and
    # the caller IS that key, so phase B's checks cannot fail. Explicit-id
    # deletes always wait for phase B's full 128-bit id + auth verification
    # and are finalized in phase C — acting early on a truncated id match
    # would desync mailbox and records on a half-guessed id.
    rm_a = o["is_delete"] & o["id_zero"] & sel_found
    rm_oh = sel_oh

    # --- apply append / removal to the target mailbox ------------------
    append_oh = first_true_onehot(~ent_valid) & create_ok
    new_entry = jnp.stack(
        [
            o["new_id"][0],
            o["new_id"][1],
            o["seq"][0],
            o["seq"][1],
            o["now"],
            o["now_hi"],
        ]
    )
    ent_mod = jnp.where(append_oh[:, None], new_entry[None, :], tgt_entries)
    ent_mod = jnp.where(
        (rm_oh & rm_a)[:, None], jnp.zeros((ENTRY_WORDS,), U32)[None, :], ent_mod
    )

    # sticky mailbox slots: a drained mailbox keeps its key slot until
    # the expiry sweep reclaims it (see engine/vphases.py docstring)
    new_key = jnp.where(
        create_ok & ~found, o["ka"], onehot_select(tgt_oh, keys)
    )

    keys_out = jnp.where(tgt_oh[:, None], new_key[None, :], keys)
    entries_out = jnp.where(tgt_oh[:, None, None], ent_mod[None, :, :], entries)

    recip_delta = (create_ok & ~found).astype(jnp.int32)
    keep = jnp.bool_(True)  # sticky: mailbox blocks persist until sweep
    insert = create_ok & ~present

    out = {
        "found": found,
        "sel_blk": sel_entry[ENT_BLK],
        "sel_idw": sel_entry[ENT_IDW],
        "sel_found": sel_found,
        "create_ok": create_ok,
        "status_a": status_a,
        "rm_a": rm_a,
        "recip_delta": recip_delta,
    }
    return mb_pack(ecfg, keys_out, entries_out), keep, insert, out


def _phase_b(ecfg: EngineConfig, value, present, o):
    stored_id = value[REC_ID]
    sender = value[REC_SENDER]
    recip_st = value[REC_RECIPIENT]
    ts2 = value[REC_TS : REC_TSH + 1]  # u32[2] (lo, hi)

    match2 = (stored_id[0] == o["sel_blk"]) & (stored_id[1] == o["sel_idw"])
    match4 = words_equal(stored_id, o["msg_id"])
    match_ok = present & jnp.where(o["id_zero"], match2, match4) & ~o["is_create"]

    auth_ok = words_equal(o["auth"], sender) | words_equal(o["auth"], recip_st)
    recip_match = words_equal(o["recipient"], recip_st)

    read_ok = o["is_read"] & match_ok & auth_ok
    upd_ok = o["is_update"] & match_ok & auth_ok & recip_match
    del_ok = o["is_delete"] & match_ok & auth_ok & (o["id_zero"] | recip_match)

    now2 = jnp.stack([o["now"], o["now_hi"]]).astype(U32)
    new_rec = jnp.concatenate(
        [
            o["new_id"],
            o["auth"],
            o["recipient"],
            now2,
            o["payload"],
        ]
    )
    updated = (
        value.at[REC_TS].set(o["now"])
        .at[REC_TSH].set(o["now_hi"])
        .at[REC_PAYLOAD].set(o["payload"])
    )
    new_value = jnp.where(
        o["create_ok"], new_rec, jnp.where(upd_ok, updated, value)
    )
    keep = ~del_ok
    insert = o["create_ok"]

    out = {
        "read_ok": read_ok,
        "upd_ok": upd_ok,
        "del_ok": del_ok,
        "match_ok": match_ok,
        "auth_ok": auth_ok,
        "recip_match": recip_match,
        "resp_id": stored_id,
        "resp_sender": sender,
        "resp_recipient": recip_st,
        "resp_ts": jnp.where(upd_ok, now2, ts2),
        "resp_payload": jnp.where(upd_ok, o["payload"], value[REC_PAYLOAD]),
    }
    return new_value, keep, insert, out


def _phase_c(ecfg: EngineConfig, value, present, o):
    keys, entries = mb_parse(ecfg, value)
    key_valid = ~is_zero_words(keys)
    slot_match = key_valid & words_equal(keys, o["ka"][None, :])
    found = jnp.any(slot_match)
    tgt_entries = onehot_select(slot_match, entries)
    ent_valid = (tgt_entries[:, ENT_SEQ] | tgt_entries[:, ENT_SEQH]) != 0

    ent_match = (
        ent_valid
        & (tgt_entries[:, ENT_BLK] == o["msg_id"][0])
        & (tgt_entries[:, ENT_IDW] == o["msg_id"][1])
    )

    # sender-authorized delete finalization (B proved del_ok; A did not act)
    rm_c = o["del_ok"] & ~o["rm_a"] & found
    ent_mod = jnp.where(
        (ent_match & rm_c)[:, None],
        jnp.zeros((ENTRY_WORDS,), U32)[None, :],
        tgt_entries,
    )
    # update refreshes the entry's expiry timestamp (record ts moved in B)
    refresh = o["upd_ok"] & found
    ent_mod = jnp.where(
        (ent_match & refresh)[:, None],
        ent_mod.at[:, ENT_TS].set(o["now"]).at[:, ENT_TSH].set(o["now_hi"]),
        ent_mod,
    )

    # sticky mailbox slots: never clear keys here (sweep reclaims)
    entries_out = jnp.where(slot_match[:, None, None], ent_mod[None, :, :], entries)

    recip_delta = jnp.zeros((), jnp.int32)
    keep = jnp.bool_(True)
    out = {"recip_delta": recip_delta}
    return mb_pack(ecfg, keys, entries_out), keep, jnp.bool_(False), out


def engine_step(
    ecfg: EngineConfig,
    state: EngineState,
    batch: dict,
    axis_name: str | None = None,
):
    """Process one fixed-size batch of (already authenticated) requests.

    ``batch``: req_type u32[B] (0 = padding dummy), auth u32[B,8],
    msg_id u32[B,4], recipient u32[B,8], payload u32[B,234], now u32.

    Returns ``(state', responses, transcript)``; responses carry status
    u32[B] (0 for dummies) and full record fields; the transcript is the
    public per-op leaf triple (mailbox, records, mailbox) — identical in
    distribution for every op type.

    ``axis_name`` names the mesh axis when running inside ``shard_map``
    with the two bucket trees sharded across chips (parallel/mesh.py);
    everything except tree fetch/write-back is replicated.
    """
    B = batch["req_type"].shape[0]
    now = batch["now"].astype(U32)
    now_hi = (
        batch["now_hi"].astype(U32) if "now_hi" in batch else jnp.zeros((), U32)
    )

    k_a, k_b, k_c, k_id, k_next = jax.random.split(state.rng, 5)
    leaves_a = jax.random.bits(k_a, (B,), U32) & U32(ecfg.mb.leaves - 1)
    leaves_b = jax.random.bits(k_b, (B,), U32) & U32(ecfg.rec.leaves - 1)
    leaves_c = jax.random.bits(k_c, (B,), U32) & U32(ecfg.mb.leaves - 1)
    id_rand = jax.random.bits(k_id, (B, 3), U32)

    def step(carry: EngineState, xs):
        rt, auth, msg_id, recipient, payload, nl_a, nl_b, nl_c, idr = xs

        is_create = rt == C.REQUEST_TYPE_CREATE
        is_read = rt == C.REQUEST_TYPE_READ
        is_update = rt == C.REQUEST_TYPE_UPDATE
        is_delete = rt == C.REQUEST_TYPE_DELETE
        is_real = is_create | is_read | is_update | is_delete
        id_zero = is_zero_words(msg_id)
        zero_recip = is_zero_words(recipient)

        can_alloc = carry.free_top > 0
        alloc_pos = jnp.where(can_alloc, carry.free_top - 1, 0)
        alloc_idx = carry.freelist[alloc_pos]
        # id words 0-1 = PRP-encrypted (nonce, block index); word 3 odd
        # so a real id is never all-zeroes (oblivious/prp.py)
        w0, w1 = prp2_encrypt(carry.id_key, alloc_idx, idr[0], ecfg.id_bits)
        new_id = jnp.stack([w0, w1, idr[1], idr[2] | 1])

        # operative mailbox key: the recipient for create / explicit-id ops,
        # the caller for zero-id next-message ops
        ka = jnp.where(is_create | ~id_zero, recipient, auth)
        bucket = mb_bucket_hash(carry.hash_key, ka, ecfg.mb_table_buckets)

        o = {
            "ka": ka,
            "auth": auth,
            "msg_id": msg_id,
            "recipient": recipient,
            "payload": payload,
            "now": now,
            "now_hi": now_hi,
            "seq": carry.seq,
            "recipients": carry.recipients,
            "alloc_idx": alloc_idx,
            "new_id": new_id,
            "is_create": is_create & is_real,
            "is_read": is_read,
            "is_update": is_update,
            "is_delete": is_delete,
            "id_zero": id_zero,
            "zero_recip": zero_recip,
            "can_alloc": can_alloc,
        }

        # -- phase A: mailbox ------------------------------------------
        mb1, out_a, leaf_a = oram_access(
            ecfg.mb,
            carry.mb,
            jnp.where(is_real, bucket, U32(ecfg.mb.dummy_index)),
            nl_a,
            o,
            lambda v, p, oo: _phase_a(ecfg, v, p, oo),
            axis_name,
        )
        o.update(out_a)

        # -- phase B: records ------------------------------------------
        enc_w0 = jnp.where(id_zero, out_a["sel_blk"], msg_id[0])
        enc_w1 = jnp.where(id_zero, out_a["sel_idw"], msg_id[1])
        lookup_blk = jnp.where(
            out_a["create_ok"],
            alloc_idx,
            prp2_decrypt(carry.id_key, enc_w0, enc_w1, ecfg.id_bits),
        )
        real_b = is_real & (
            out_a["create_ok"]
            | (~is_create & (~id_zero | out_a["sel_found"]))
        )
        idx_b = jnp.where(
            real_b, lookup_blk & U32(ecfg.rec.blocks - 1), U32(ecfg.rec.dummy_index)
        )
        rec1, out_b, leaf_b = oram_access(
            ecfg.rec,
            carry.rec,
            idx_b,
            nl_b,
            o,
            lambda v, p, oo: _phase_b(ecfg, v, p, oo),
            axis_name,
        )
        o.update({"del_ok": out_b["del_ok"], "upd_ok": out_b["upd_ok"]})

        # -- freelist bookkeeping (private memory) ---------------------
        free_top1 = carry.free_top - out_a["create_ok"].astype(U32)
        push_pos = jnp.where(out_b["del_ok"], free_top1, U32(ecfg.max_messages))
        freelist = carry.freelist.at[push_pos].set(idx_b, mode="drop")
        free_top2 = free_top1 + out_b["del_ok"].astype(U32)

        # -- phase C: mailbox again ------------------------------------
        mb2, out_c, leaf_c = oram_access(
            ecfg.mb,
            mb1,
            jnp.where(is_real, bucket, U32(ecfg.mb.dummy_index)),
            nl_c,
            o,
            lambda v, p, oo: _phase_c(ecfg, v, p, oo),
            axis_name,
        )

        recipients = (
            carry.recipients.astype(jnp.int32)
            + out_a["recip_delta"]
            + out_c["recip_delta"]
        ).astype(U32)
        sq_lo, sq_hi = u64_add_u32(
            carry.seq[0], carry.seq[1], out_a["create_ok"].astype(U32)
        )
        seq = jnp.stack([sq_lo, sq_hi])

        # -- response assembly (shared with the phase-major engine) -----
        resp = assemble_responses(
            is_real=is_real,
            is_create=is_create,
            is_update=is_update,
            is_delete=is_delete,
            id_zero=id_zero,
            status_a=out_a["status_a"],
            create_ok=out_a["create_ok"],
            out_b=out_b,
            new_id=new_id,
            auth=auth,
            recipient=recipient,
            payload=payload,
            now2=jnp.stack([now, now_hi]).astype(U32),
        )
        transcript = jnp.stack([leaf_a, leaf_b, leaf_c])

        carry = EngineState(
            rec=rec1,
            mb=mb2,
            freelist=freelist,
            free_top=free_top2,
            recipients=recipients,
            seq=seq,
            hash_key=carry.hash_key,
            id_key=carry.id_key,
            rng=carry.rng,
        )
        return carry, (resp, transcript)

    xs = (
        batch["req_type"].astype(U32),
        batch["auth"],
        batch["msg_id"],
        batch["recipient"],
        batch["payload"],
        leaves_a,
        leaves_b,
        leaves_c,
        id_rand,
    )
    state, (responses, transcripts) = jax.lax.scan(step, state, xs)
    state = state._replace(rng=k_next)
    return state, responses, transcripts
