"""Merlin transcripts (STROBE-128 over Keccak-f[1600]), pure Python.

Schnorrkel (sr25519) signatures — the reference's per-request auth
scheme (reference README.md:193-199, types/src/lib.rs:13, Cargo.toml:62
pinning ``schnorrkel-og 0.11.0-pre.0``) — derive their Fiat–Shamir
challenge from a *merlin* transcript, not a plain hash. Byte-for-byte
signature compatibility with reference clients therefore requires this
exact construction:

- Keccak-f[1600] (FIPS 202 permutation, 24 rounds);
- STROBE-128 (rate 166, the trimmed subset merlin embeds: AD / meta-AD /
  PRF / KEY operations only);
- the merlin framing: protocol label ``b"Merlin v1.0"``, ``dom-sep``
  domain separator, ``append_message`` = meta-AD(label ‖ LE32(len)) +
  AD(data), ``challenge_bytes`` = meta-AD(label ‖ LE32(len)) + PRF.

Pinned by test against merlin's published transcript test vector
(tests/test_merlin.py). Host-side only; never on the device path.
"""

from __future__ import annotations

import struct

__all__ = ["Strobe128", "Transcript", "keccak_f1600"]

_MASK = (1 << 64) - 1

# FIPS 202 round constants for Keccak-f[1600]
_RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]

# rotation offsets r[x][y], indexed by lane x + 5y
_ROT = [
    0, 1, 62, 28, 27,
    36, 44, 6, 55, 20,
    3, 10, 43, 25, 39,
    41, 45, 15, 21, 8,
    18, 2, 61, 56, 14,
]


def _rol(v: int, n: int) -> int:
    n &= 63
    return ((v << n) | (v >> (64 - n))) & _MASK


def keccak_f1600(state: bytearray) -> None:
    """In-place Keccak-f[1600] on a 200-byte little-endian lane state.

    Dispatches to the native C permutation when the session library is
    loaded (~100× the pure-Python throughput; signature verification
    runs several permutations per request). The Python path below is
    the fallback and the oracle (tests/test_merlin.py cross-checks)."""
    from .. import native as _native

    if _native.lib is not None:
        _native.keccak_f1600(state)
        return
    _keccak_f1600_py(state)


def _keccak_f1600_py(state: bytearray) -> None:
    """Pure-Python permutation (fallback + correctness oracle)."""
    lanes = list(struct.unpack("<25Q", state))
    for rc in _RC:
        # θ
        c = [lanes[x] ^ lanes[x + 5] ^ lanes[x + 10] ^ lanes[x + 15]
             ^ lanes[x + 20] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rol(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(0, 25, 5):
                lanes[x + y] ^= d[x]
        # ρ and π
        b = [0] * 25
        for x in range(5):
            for y in range(5):
                b[y + 5 * ((2 * x + 3 * y) % 5)] = _rol(
                    lanes[x + 5 * y], _ROT[x + 5 * y]
                )
        # χ
        for x in range(5):
            for y in range(0, 25, 5):
                lanes[x + y] = b[x + y] ^ (
                    (~b[(x + 1) % 5 + y]) & b[(x + 2) % 5 + y] & _MASK
                )
        # ι
        lanes[0] ^= rc
    state[:] = struct.pack("<25Q", *lanes)


_STROBE_R = 166  # STROBE-128 rate: 200 - (2·128)/8 - 2
_FLAG_I = 1
_FLAG_A = 1 << 1
_FLAG_C = 1 << 2
_FLAG_T = 1 << 3
_FLAG_M = 1 << 4
_FLAG_K = 1 << 5


def _native_strobe():
    """The native module iff the STROBE ops are loaded, else None."""
    from .. import native as _native

    return _native if _native.lib is not None else None


class Strobe128:
    """The trimmed STROBE-128 duplex merlin embeds (merlin strobe.rs).

    The whole duplex lives in one 203-byte blob —
    ``state[200] ‖ pos ‖ pos_begin ‖ cur_flags`` — shared byte-for-byte
    with the C ops in native/r255.c, so a transcript can move freely
    between the native fast path (one library crossing per op) and the
    pure-Python oracle below. The per-request signature path runs ~8
    transcript ops per challenge; the Python framing alone cost ~85 µs
    before the C ops (measured, PERF.md host table)."""

    __slots__ = ("blob",)

    def __init__(self, protocol_label: bytes):
        blob = bytearray(203)
        blob[0:6] = bytes([1, _STROBE_R + 2, 1, 0, 1, 96])
        blob[6:18] = b"STROBEv1.0.2"
        self.blob = blob
        self._f1600()
        self.meta_ad(protocol_label, False)

    # -- pos / pos_begin / cur_flags live in the blob tail ---------------

    @property
    def pos(self) -> int:
        return self.blob[200]

    @property
    def pos_begin(self) -> int:
        return self.blob[201]

    @property
    def cur_flags(self) -> int:
        return self.blob[202]

    def _f1600(self) -> None:
        """Permute the first 200 blob bytes in place.

        Dispatches on the native *library* directly (not the STROBE-op
        dispatch hook): the C permutation predates the C duplex ops, so
        a pure-Python-framing configuration must still use it — that is
        the configuration that actually shipped before the duplex moved
        to C, and what tools/host_ceiling.py --legacy reproduces."""
        from .. import native as _native

        if _native.lib is not None:
            _native.keccak_f1600(self.blob)  # c_char*200 view, 203 buffer
        else:
            st = bytearray(self.blob[:200])
            _keccak_f1600_py(st)
            self.blob[:200] = st

    def _run_f(self) -> None:
        b = self.blob
        b[b[200]] ^= b[201]
        b[b[200] + 1] ^= 0x04
        b[_STROBE_R + 1] ^= 0x80
        self._f1600()
        b[200] = 0
        b[201] = 0

    # the pure-Python duplex ops work in rate-bounded slices, not per
    # byte; they are the oracle for the C ops (tests/test_merlin.py
    # cross-checks every op against this path)

    def _absorb(self, data: bytes) -> None:
        i, n, b = 0, len(data), self.blob
        while i < n:
            take = min(_STROBE_R - b[200], n - i)
            p = b[200]
            b[p : p + take] = (
                int.from_bytes(b[p : p + take], "little")
                ^ int.from_bytes(data[i : i + take], "little")
            ).to_bytes(take, "little")
            b[200] += take
            i += take
            if b[200] == _STROBE_R:
                self._run_f()

    def _overwrite(self, data: bytes) -> None:
        i, n, b = 0, len(data), self.blob
        while i < n:
            take = min(_STROBE_R - b[200], n - i)
            b[b[200] : b[200] + take] = data[i : i + take]
            b[200] += take
            i += take
            if b[200] == _STROBE_R:
                self._run_f()

    def _squeeze(self, n: int) -> bytes:
        out = bytearray(n)
        i, b = 0, self.blob
        while i < n:
            take = min(_STROBE_R - b[200], n - i)
            out[i : i + take] = b[b[200] : b[200] + take]
            b[b[200] : b[200] + take] = bytes(take)
            b[200] += take
            i += take
            if b[200] == _STROBE_R:
                self._run_f()
        return bytes(out)

    def _begin_op(self, flags: int, more: bool) -> None:
        b = self.blob
        if more:
            if flags != b[202]:
                raise ValueError(
                    f"continued op flag mismatch: {flags} != {b[202]}"
                )
            return
        if flags & _FLAG_T:
            raise ValueError("transport ops unsupported in merlin strobe")
        old_begin = b[201]
        b[201] = b[200] + 1
        b[202] = flags
        self._absorb(bytes([old_begin, flags]))
        if (flags & (_FLAG_C | _FLAG_K)) and b[200] != 0:
            self._run_f()

    def meta_ad(self, data: bytes, more: bool) -> None:
        nat = _native_strobe()
        if nat is not None:
            if nat.strobe_op(self.blob, 0, bytes(data), more):
                raise ValueError("continued op flag mismatch")
            return
        self._begin_op(_FLAG_M | _FLAG_A, more)
        self._absorb(data)

    def ad(self, data: bytes, more: bool) -> None:
        nat = _native_strobe()
        if nat is not None:
            if nat.strobe_op(self.blob, 1, bytes(data), more):
                raise ValueError("continued op flag mismatch")
            return
        self._begin_op(_FLAG_A, more)
        self._absorb(data)

    def prf(self, n: int, more: bool) -> bytes:
        nat = _native_strobe()
        if nat is not None:
            out = nat.strobe_prf(self.blob, n, more)
            if out is None:
                raise ValueError("continued op flag mismatch")
            return out
        self._begin_op(_FLAG_I | _FLAG_A | _FLAG_C, more)
        return self._squeeze(n)

    def key(self, data: bytes, more: bool) -> None:
        nat = _native_strobe()
        if nat is not None:
            if nat.strobe_op(self.blob, 3, bytes(data), more):
                raise ValueError("continued op flag mismatch")
            return
        self._begin_op(_FLAG_A | _FLAG_C, more)
        self._overwrite(data)

    def clone(self) -> "Strobe128":
        dup = object.__new__(Strobe128)
        dup.blob = bytearray(self.blob)
        return dup


class Transcript:
    """merlin::Transcript (merlin transcript.rs), byte-compatible."""

    __slots__ = ("strobe",)

    def __init__(self, label: bytes):
        self.strobe = Strobe128(b"Merlin v1.0")
        self.append_message(b"dom-sep", label)

    def append_message(self, label: bytes, message: bytes) -> None:
        nat = _native_strobe()
        if nat is not None:
            # one library crossing for the whole merlin framing
            nat.merlin_append(self.strobe.blob, bytes(label), bytes(message))
            return
        self.strobe.meta_ad(label, False)
        self.strobe.meta_ad(struct.pack("<I", len(message)), True)
        self.strobe.ad(message, False)

    def append_u64(self, label: bytes, value: int) -> None:
        self.append_message(label, struct.pack("<Q", value))

    def challenge_bytes(self, label: bytes, n: int) -> bytes:
        nat = _native_strobe()
        if nat is not None:
            return nat.merlin_challenge(self.strobe.blob, bytes(label), n)
        self.strobe.meta_ad(label, False)
        self.strobe.meta_ad(struct.pack("<I", n), True)
        return self.strobe.prf(n, False)

    def clone(self) -> "Transcript":
        dup = object.__new__(Transcript)
        dup.strobe = self.strobe.clone()
        return dup
