"""Session layer: encrypted channel, challenge RNG, request signatures.

Host-side re-design of the reference's attestation/session stack
(``mc-attest-ake`` / ``mc-crypto-noise`` / ``mc-crypto-keys``; reference
grapevine.proto:17-36 and README.md:177-199, SURVEY.md §2b):

- :mod:`chacha`     — ChaCha20 keystream; the per-request challenge RNG
  that client and server advance in lockstep (README.md:195-196).
- :mod:`ristretto`  — ristretto255 group (pure Python) and plain Schnorr
  signatures with the ``b"grapevine-challenge"`` signing context
  (reference types/src/lib.rs:13).
- :mod:`merlin`     — merlin transcripts (STROBE-128 / Keccak-f[1600]),
  vector-pinned; the transcript layer under sr25519.
- :mod:`schnorrkel` — sr25519 signatures byte-compatible with the
  reference's ``sign_schnorrkel`` clients (README.md:193-199).
- :mod:`channel`    — X25519 + ChaCha20-Poly1305 encrypted channel with a
  pluggable attestation-evidence interface. TPU has no enclave; the
  evidence hook keeps SGX/TDX/none swappable (SURVEY.md §1 layer 2).

Nothing in this package touches the device: channel crypto terminates on
the host, exactly as the reference's session layer terminates at the
enclave boundary.
"""

from .chacha import ChaCha20, ChallengeRng  # noqa: F401
from .ristretto import (  # noqa: F401
    RistrettoPoint,
    keygen,
    public_key,
    sign,
    verify,
)

# The channel layer runs on either crypto backend: the `cryptography`
# wheel when present (OpenSSL, constant-time), else the stdlib + numpy
# fallback (session/stdcrypto.py) — bit-compatible wire format either
# way, so this import never needs the historical wheel gate.
from .channel import (  # noqa: F401
    CRYPTO_BACKEND,
    NullAttestation,
    SecureChannel,
    client_handshake,
    server_handshake,
)


def get_signature_scheme(name: str):
    """Module with sign/verify/batch_verify/keygen for a scheme name."""
    if name == "schnorrkel":
        from . import schnorrkel

        return schnorrkel
    if name == "rfc9496":
        from . import ristretto

        return ristretto
    raise ValueError(f"unknown signature scheme {name!r}")
