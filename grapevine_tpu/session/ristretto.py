"""ristretto255 group and Schnorr request signatures (pure Python).

The reference authenticates every request with a deterministic Schnorrkel
(sr25519) signature over the 32-byte challenge, under the signing context
``b"grapevine-challenge"`` (reference README.md:193-199,
types/src/lib.rs:13,44-52). This module provides the same *shape* of
scheme on the same group: 32-byte ristretto255 public keys, 64-byte
(R ‖ s) Schnorr signatures, deterministic nonces, context-separated
hashing — implemented against RFC 9496 (ristretto255) with SHA-512 as the
hash. It is deliberately **not** byte-compatible with schnorrkel (which
uses merlin/STROBE transcripts); the signature scheme is a session-layer
choice and the wire sizes are identical.

Host-side only and not constant-time (Python ints): the server only
*verifies* public signatures; client signing keys never touch the
service. A constant-time native implementation is a later hardening item.
"""

from __future__ import annotations

import functools
import hashlib

from .. import native as _native

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)

_NONCE_DOMAIN = b"grapevine-tpu-schnorr-nonce"
_CHAL_DOMAIN = b"grapevine-tpu-schnorr-chal"


def _inv(x: int) -> int:
    return pow(x, P - 2, P)


def _is_neg(x: int) -> bool:
    return (x & 1) == 1


def _abs(x: int) -> int:
    return (-x) % P if _is_neg(x) else x


def _sqrt_ratio_m1(u: int, v: int) -> tuple[bool, int]:
    """RFC 9496 SQRT_RATIO_M1: (was_square, sqrt(u/v) or sqrt(i·u/v))."""
    v3 = v * v % P * v % P
    v7 = v3 * v3 % P * v % P
    r = u * v3 % P * pow(u * v7 % P, (P - 5) // 8, P) % P
    check = v * r % P * r % P
    u_neg = (-u) % P
    correct = check == u % P
    flipped = check == u_neg
    flipped_i = check == u_neg * SQRT_M1 % P
    if flipped or flipped_i:
        r = r * SQRT_M1 % P
    return (correct or flipped), _abs(r)


INVSQRT_A_MINUS_D = _sqrt_ratio_m1(1, (-1 - D) % P)[1]


class RistrettoPoint:
    """Extended Edwards coordinates on edwards25519 (a = -1)."""

    __slots__ = ("x", "y", "z", "t")

    def __init__(self, x: int, y: int, z: int, t: int):
        self.x, self.y, self.z, self.t = x % P, y % P, z % P, t % P

    # -- group ops ------------------------------------------------------

    def __add__(self, other: "RistrettoPoint") -> "RistrettoPoint":
        a = (self.y - self.x) * (other.y - other.x) % P
        b = (self.y + self.x) * (other.y + other.x) % P
        c = self.t * (2 * D) % P * other.t % P
        d = self.z * 2 % P * other.z % P
        e, f, g, h = (b - a) % P, (d - c) % P, (d + c) % P, (b + a) % P
        return RistrettoPoint(e * f, g * h, f * g, e * h)

    def __neg__(self) -> "RistrettoPoint":
        return RistrettoPoint((-self.x) % P, self.y, self.z, (-self.t) % P)

    def __mul__(self, k: int) -> "RistrettoPoint":
        k %= L
        acc = IDENTITY
        add = self
        while k:
            if k & 1:
                acc = acc + add
            add = add + add
            k >>= 1
        return acc

    __rmul__ = __mul__

    def __eq__(self, other) -> bool:
        # ristretto equality over the coset (RFC 9496 §4.5):
        # X1·Y2 == Y1·X2  OR  Y1·Y2 == X1·X2 (curve parameter a = -1)
        if not isinstance(other, RistrettoPoint):
            return NotImplemented
        return (
            self.x * other.y % P == self.y * other.x % P
            or self.y * other.y % P == self.x * other.x % P
        )

    def __hash__(self):
        return hash(self.encode())

    # -- RFC 9496 encode / decode --------------------------------------

    def encode(self) -> bytes:
        x0, y0, z0, t0 = self.x, self.y, self.z, self.t
        u1 = (z0 + y0) * (z0 - y0) % P
        u2 = x0 * y0 % P
        _, invsqrt = _sqrt_ratio_m1(1, u1 * u2 % P * u2 % P)
        den1 = invsqrt * u1 % P
        den2 = invsqrt * u2 % P
        z_inv = den1 * den2 % P * t0 % P
        ix0 = x0 * SQRT_M1 % P
        iy0 = y0 * SQRT_M1 % P
        enchanted = den1 * INVSQRT_A_MINUS_D % P
        rotate = _is_neg(t0 * z_inv % P)
        if rotate:
            x, y, den_inv = iy0, ix0, enchanted
        else:
            x, y, den_inv = x0, y0, den2
        if _is_neg(x * z_inv % P):
            y = (-y) % P
        s = _abs(den_inv * ((z0 - y) % P) % P)
        return s.to_bytes(32, "little")

    @classmethod
    def decode(cls, data: bytes) -> "RistrettoPoint":
        if len(data) != 32:
            raise ValueError("ristretto encoding must be 32 bytes")
        s = int.from_bytes(data, "little")
        if s >= P or _is_neg(s):
            raise ValueError("non-canonical ristretto encoding")
        ss = s * s % P
        u1 = (1 - ss) % P
        u2 = (1 + ss) % P
        u2_sqr = u2 * u2 % P
        v = (-(D * u1 % P * u1 % P) - u2_sqr) % P
        was_square, invsqrt = _sqrt_ratio_m1(1, v * u2_sqr % P)
        den_x = invsqrt * u2 % P
        den_y = invsqrt * den_x % P * v % P
        x = _abs(2 * s % P * den_x % P)
        y = u1 * den_y % P
        t = x * y % P
        if not was_square or _is_neg(t) or y == 0:
            raise ValueError("invalid ristretto encoding")
        return cls(x, y, 1, t)


IDENTITY = RistrettoPoint(0, 1, 1, 0)
BASEPOINT = RistrettoPoint(
    15112221349535400772501151409588531511454012693041857206046113283949847762202,
    46316835694926478169428394003475163141307993866256225615783033603165251855960,
    1,
    15112221349535400772501151409588531511454012693041857206046113283949847762202
    * 46316835694926478169428394003475163141307993866256225615783033603165251855960
    % P,
)


# -- Schnorr signatures ------------------------------------------------


def _h_scalar(*parts: bytes) -> int:
    h = hashlib.sha512()
    for part in parts:
        h.update(len(part).to_bytes(8, "little"))
        h.update(part)
    return int.from_bytes(h.digest(), "little") % L


def keygen(seed: bytes) -> tuple[bytes, bytes]:
    """Derive (private_scalar_bytes, public_key_bytes) from a 32-byte seed."""
    if len(seed) != 32:
        raise ValueError("seed must be 32 bytes")
    a = _h_scalar(b"grapevine-tpu-keygen", seed)
    if a == 0:
        a = 1
    pub = (a * BASEPOINT).encode()
    return a.to_bytes(32, "little"), pub


def _mult_base_enc(scalar: int) -> bytes:
    """Encoded ``scalar·B``, native when available (~0.05 ms vs ~2 ms
    pure Python — the client-side signing hot path)."""
    if _native.lib is not None:
        enc = _native.mult_base((scalar % L).to_bytes(32, "little"))
        if enc is not None:
            return enc
    return (scalar % L * BASEPOINT).encode()


@functools.lru_cache(maxsize=4096)
def public_key(sk: bytes) -> bytes:
    """sk bytes → encoded public point. LRU-cached: sign() is on the
    client per-request path and must not redo the basepoint mult."""
    return _mult_base_enc(int.from_bytes(sk, "little") % L)


def sign(sk: bytes, context: bytes, message: bytes) -> bytes:
    """Deterministic context-separated Schnorr signature (64 bytes: R ‖ s)."""
    a = int.from_bytes(sk, "little") % L
    if a == 0:
        raise ValueError("invalid private key")
    pub = public_key(sk)
    r = _h_scalar(_NONCE_DOMAIN, sk, context, message)
    if r == 0:
        r = 1
    big_r = _mult_base_enc(r)
    k = _h_scalar(_CHAL_DOMAIN, context, big_r, pub, message)
    s = (r + k * a) % L
    return big_r + s.to_bytes(32, "little")


def verify_core(pub: bytes, r_enc: bytes, s: int, k: int) -> bool:
    """Scheme-independent single-signature check: s·B == R + k·A.

    Native library when available (~0.1 ms/verify), pure Python as the
    fallback and correctness oracle (tests/test_native_r255.py). Shared
    by this module's plain Schnorr and session/schnorrkel.py — the
    schemes differ only in how k is derived and how s is parsed."""
    if _native.lib is not None:
        return (
            _native.verify1(
                pub, r_enc, s.to_bytes(32, "little"), k.to_bytes(32, "little")
            )
            == 1
        )
    try:
        big_r = RistrettoPoint.decode(r_enc)
        a_pt = _decode_pub_cached(pub)
    except ValueError:
        return False
    return _fixed_base_mult(s) == (big_r + k * a_pt)


def verify(pub: bytes, context: bytes, message: bytes, signature: bytes) -> bool:
    """True iff the signature is valid. Never raises on malformed input."""
    if len(signature) != 64 or len(pub) != 32:
        return False
    s = int.from_bytes(signature[32:], "little")
    if s >= L:
        return False
    k = _h_scalar(_CHAL_DOMAIN, context, signature[:32], pub, message)
    return verify_core(pub, signature[:32], s, k)


# -- batch verification (one multi-scalar multiplication per round) ----
#
# The per-request path costs two scalar multiplications in pure Python —
# ~9 ms/verify measured, capping the gRPC server far below engine
# throughput (SURVEY.md §2b mc-crypto-keys: "consider batch verify").
# Standard random-linear-combination batching: with fresh random z_i,
#
#     Σ z_i·s_i · B  ==  Σ z_i·R_i + Σ (z_i·k_i mod L)·A_i
#
# holds for all-valid batches, and a batch containing any forgery passes
# with probability ≤ 2^-128. The right side is one Straus interleaved
# multi-scalar multiplication (window 4), the left one fixed-base
# multiply from a precomputed nibble table — ~15× fewer group ops than
# verifying individually.


#: max items per native MSM call (2 points each; r255.c MSM_MAX = 4096)
_NATIVE_CHUNK = 2048


@functools.lru_cache(maxsize=4096)
def _decode_pub_cached(pub: bytes) -> RistrettoPoint:
    """Clients re-send the same identity every request; cache the decode."""
    return RistrettoPoint.decode(pub)


@functools.lru_cache(maxsize=1)
def _fixed_base_table():
    """table[w][d] = d · 16^w · B for w < 64, d < 16."""
    table = []
    base = BASEPOINT
    for _ in range(64):
        row = [IDENTITY]
        for d in range(15):
            row.append(row[-1] + base)
        table.append(row)
        base = row[1] + row[15]  # 16 · 16^w · B
    return table


def _fixed_base_mult(s: int) -> RistrettoPoint:
    table = _fixed_base_table()
    acc = IDENTITY
    s %= L
    for w in range(64):
        d = (s >> (4 * w)) & 0xF
        if d:
            acc = acc + table[w][d]
    return acc


def _msm(points: list[RistrettoPoint], scalars: list[int]) -> RistrettoPoint:
    """Straus interleaved multi-scalar multiplication, 4-bit windows."""
    if not points:
        return IDENTITY
    tables = []
    for p in points:
        row = [IDENTITY, p]
        for _ in range(14):
            row.append(row[-1] + p)
        tables.append(row)
    n_windows = (max(s.bit_length() for s in scalars) + 3) // 4 or 1
    acc = IDENTITY
    for w in range(n_windows - 1, -1, -1):
        if acc is not IDENTITY:
            acc = acc + acc
            acc = acc + acc
            acc = acc + acc
            acc = acc + acc
        for t, s in zip(tables, scalars):
            d = (s >> (4 * w)) & 0xF
            if d:
                acc = acc + t[d]
    return acc


def batch_verify_core(
    parsed: list[tuple[bytes, bytes, int, int]],
    rng=None,
) -> bool:
    """Random-linear-combination batch check over pre-parsed items.

    ``parsed`` holds (R_enc, pub_enc, s, k) per signature — the scheme
    layer (this module's plain Schnorr, or session/schnorrkel.py's
    merlin-transcript challenge) computes k; the group equation

        Σ z_i·s_i · B  ==  Σ z_i·R_i + Σ (z_i·k_i mod L)·A_i

    is scheme-independent. Shared so both schemes ride the same native
    one-MSM path. ``rng`` must be unpredictable to clients."""
    import os

    # the native MSM scratch caps one call at _NATIVE_CHUNK items; larger
    # batches split into independently-checked chunks (each chunk is its
    # own random-linear-combination equation), so there is no silent
    # fallback cliff at any batch size
    if len(parsed) > _NATIVE_CHUNK:
        return all(
            batch_verify_core(parsed[i : i + _NATIVE_CHUNK], rng)
            for i in range(0, len(parsed), _NATIVE_CHUNK)
        )
    if not parsed:
        return True

    randbytes = rng.randbytes if rng is not None else os.urandom
    use_native = _native.lib is not None
    rs: list[bytes] = []
    pubs: list[bytes] = []
    zs: list[bytes] = []
    zks: list[bytes] = []
    points: list[RistrettoPoint] = []
    scalars: list[int] = []
    sb = 0
    for r_enc, pub, s, k in parsed:
        if not use_native:
            try:
                points.append(RistrettoPoint.decode(r_enc))
                points.append(_decode_pub_cached(pub))
            except ValueError:
                return False
        z = int.from_bytes(randbytes(16), "little") | 1
        sb = (sb + z * s) % L
        if use_native:
            rs.append(r_enc)
            pubs.append(pub)
            zs.append(z.to_bytes(32, "little"))
            zks.append((z * k % L).to_bytes(32, "little"))
        else:
            scalars.append(z)
            scalars.append(z * k % L)
    if use_native:
        return (
            _native.batch_check(
                b"".join(rs),
                b"".join(pubs),
                b"".join(zs),
                b"".join(zks),
                sb.to_bytes(32, "little"),
            )
            == 1
        )
    return _fixed_base_mult(sb) == _msm(points, scalars)


def batch_verify(
    items: list[tuple[bytes, bytes, bytes, bytes]],
    rng=None,
) -> bool:
    """True iff EVERY (pub, context, message, signature) verifies.

    One multi-scalar multiplication for the whole batch (native library
    when available: ~0.05 ms/signature at batch 64). On False the caller
    falls back to per-item verify to identify offenders. ``rng`` must be
    unpredictable to clients (default: os.urandom)."""
    parsed = []
    for pub, context, message, signature in items:
        if len(signature) != 64 or len(pub) != 32:
            return False
        s = int.from_bytes(signature[32:], "little")
        if s >= L:
            return False
        k = _h_scalar(_CHAL_DOMAIN, context, signature[:32], pub, message)
        parsed.append((signature[:32], pub, s, k))
    return batch_verify_core(parsed, rng)
