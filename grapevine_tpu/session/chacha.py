"""ChaCha20 keystream and the lockstep challenge RNG.

The reference seeds a ChaCha20 stream with 32 enclave-chosen bytes at
connection time and both sides draw 32 bytes per request to stay in sync
(reference grapevine.proto:20-25, README.md:189-196). This module
implements RFC 7539 ChaCha20 and the :class:`ChallengeRng` wrapper.

Stream parameters: key = the 32-byte seed, nonce = 12 zero bytes, block
counter starting at 0. This pins the cross-implementation contract; the
RFC 7539 test vector is asserted in tests.

Two backends, same stream: an OpenSSL-backed streaming cipher (the
per-request server hot path — the pure-Python block function measured
91 µs per 32-byte draw, ~30% of the host's per-op budget, PERF.md) and
the pure-Python block function below as the spec oracle
(tests/test_session.py pins both to the RFC vector and to each other).
"""

from __future__ import annotations

import struct

try:  # OpenSSL ChaCha20: 16-byte nonce = LE32 initial counter ‖ RFC nonce
    from cryptography.hazmat.primitives.ciphers import Cipher as _Cipher
    from cryptography.hazmat.primitives.ciphers.algorithms import (
        ChaCha20 as _OpenSSLChaCha20,
    )
except ImportError:  # wheel-less container: numpy keystream fallback
    _Cipher = None


def _rotl(x: int, n: int) -> int:
    return ((x << n) | (x >> (32 - n))) & 0xFFFFFFFF


def _quarter(s, a, b, c, d):
    s[a] = (s[a] + s[b]) & 0xFFFFFFFF
    s[d] = _rotl(s[d] ^ s[a], 16)
    s[c] = (s[c] + s[d]) & 0xFFFFFFFF
    s[b] = _rotl(s[b] ^ s[c], 12)
    s[a] = (s[a] + s[b]) & 0xFFFFFFFF
    s[d] = _rotl(s[d] ^ s[a], 8)
    s[c] = (s[c] + s[d]) & 0xFFFFFFFF
    s[b] = _rotl(s[b] ^ s[c], 7)


class ChaCha20:
    """RFC 7539 ChaCha20 keystream generator.

    Streams from OpenSSL when available (stateful encryptor over a zero
    plaintext — the encryptor carries the block counter and partial-
    block position, so arbitrary draw sizes stay aligned with the pure
    path); falls back to the pure-Python block function."""

    def __init__(self, key: bytes, nonce: bytes = b"\x00" * 12, counter: int = 0):
        if len(key) != 32:
            raise ValueError("key must be 32 bytes")
        if len(nonce) != 12:
            raise ValueError("nonce must be 12 bytes")
        self._const = struct.unpack("<4I", b"expand 32-byte k")
        self._key = struct.unpack("<8I", key)
        self._nonce = struct.unpack("<3I", nonce)
        self._key_bytes = key
        self._nonce_bytes = nonce
        self._counter = counter
        self._buf = b""
        self._openssl = None
        if _Cipher is not None:
            full_nonce = struct.pack("<I", counter & 0xFFFFFFFF) + nonce
            self._openssl = _Cipher(
                _OpenSSLChaCha20(key, full_nonce), mode=None
            ).encryptor()

    def _block(self, counter: int) -> bytes:
        init = list(self._const + self._key + (counter & 0xFFFFFFFF,) + self._nonce)
        s = list(init)
        for _ in range(10):
            _quarter(s, 0, 4, 8, 12)
            _quarter(s, 1, 5, 9, 13)
            _quarter(s, 2, 6, 10, 14)
            _quarter(s, 3, 7, 11, 15)
            _quarter(s, 0, 5, 10, 15)
            _quarter(s, 1, 6, 11, 12)
            _quarter(s, 2, 7, 8, 13)
            _quarter(s, 3, 4, 9, 14)
        out = [(a + b) & 0xFFFFFFFF for a, b in zip(s, init)]
        return struct.pack("<16I", *out)

    def keystream(self, n: int) -> bytes:
        if self._openssl is not None:
            return self._openssl.update(bytes(n))
        if len(self._buf) < n:
            # wheel-less fallback: draw whole blocks from the numpy
            # block-axis keystream (stdcrypto.py) instead of the 91 µs
            # pure-Python block — _block stays as the spec oracle the
            # tests pin both streams against
            from . import stdcrypto

            n_blocks = (n - len(self._buf) + 63) // 64
            self._buf += stdcrypto.chacha20_keystream(
                self._key_bytes, self._nonce_bytes, n_blocks * 64, self._counter
            )
            self._counter += n_blocks
        out, self._buf = self._buf[:n], self._buf[n:]
        return out


class ChallengeRng:
    """Draws 32-byte challenges; client and server each hold one, seeded
    identically, and advance it on *every* request (reference
    README.md:195-196) — a desync is an implicit session kill."""

    CHALLENGE_SIZE = 32

    def __init__(self, seed: bytes):
        if len(seed) != 32:
            raise ValueError("challenge seed must be 32 bytes")
        self._stream = ChaCha20(seed)
        self.draws = 0

    def next_challenge(self) -> bytes:
        self.draws += 1
        return self._stream.keystream(self.CHALLENGE_SIZE)
