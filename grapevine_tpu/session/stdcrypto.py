"""Stdlib + numpy channel crypto: the wheel-less backend for channel.py.

The IX channel (session/channel.py) originally required the
``cryptography`` wheel for three primitives — X25519, ChaCha20-Poly1305,
and HKDF-SHA256. Minimal containers (including this one) don't ship the
wheel, which used to skip every session/server test module and report
``server_loopback`` as skipped. This module supplies the same three
primitives from the standard library + numpy, bit-compatible with the
wheel-backed implementations by construction (each is a direct RFC
transcription, pinned to the RFC test vectors in
tests/test_stdcrypto.py, and pinned against the wheel's output in the
same tests whenever the wheel *is* present):

- :func:`x25519` — RFC 7748 §5 Montgomery ladder over Python ints.
  A full exchange is ~1 ms; handshakes happen once per connection, so
  this never touches the per-request path.
- :class:`ChaCha20Poly1305` — RFC 8439 AEAD composed from the
  numpy-vectorized ChaCha20 keystream below (the same block-axis
  vectorization engine/checkpoint.py uses for sealing — the session
  layer's per-32-byte pure-Python draw is a spec oracle, not a bulk
  cipher) and a big-int Poly1305. API-compatible with
  ``cryptography.hazmat.primitives.ciphers.aead.ChaCha20Poly1305``.
- :func:`hkdf_sha256` — RFC 5869 extract-then-expand over stdlib hmac.

Deliberately jax-free: hostpipe worker processes (server/hostpipe.py)
import this for frame codec work and must not drag a device runtime
into every worker.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import struct

import numpy as np

__all__ = [
    "ChaCha20Poly1305",
    "InvalidTag",
    "X25519PrivateKey",
    "X25519PublicKey",
    "chacha20_xor",
    "hkdf_sha256",
    "poly1305",
    "x25519",
]


class InvalidTag(Exception):
    """AEAD authentication failure (mirrors cryptography.exceptions)."""


# -- ChaCha20 (RFC 8439 §2.3), vectorized over the block axis ------------


def _chacha_block_words(key_words, counter0: int, nonce_words, n_blocks: int):
    """u32[n_blocks, 16] keystream blocks for consecutive counters.

    Same construction as engine/checkpoint.py's sealing keystream
    (pinned to each other and to session/chacha.py's pure block function
    in tests); duplicated rather than imported so the session layer and
    hostpipe workers stay jax-free."""
    const = np.frombuffer(b"expand 32-byte k", dtype="<u4")
    ctrs = (np.arange(n_blocks, dtype=np.uint64) + np.uint64(counter0)).astype(
        np.uint32
    )
    init = np.empty((n_blocks, 16), np.uint32)
    init[:, 0:4] = const
    init[:, 4:12] = key_words
    init[:, 12] = ctrs
    init[:, 13:16] = nonce_words
    x = init.copy()

    def rot(v, n):
        return (v << np.uint32(n)) | (v >> np.uint32(32 - n))

    def qr(a, b, c, d):
        x[:, a] += x[:, b]
        x[:, d] = rot(x[:, d] ^ x[:, a], 16)
        x[:, c] += x[:, d]
        x[:, b] = rot(x[:, b] ^ x[:, c], 12)
        x[:, a] += x[:, b]
        x[:, d] = rot(x[:, d] ^ x[:, a], 8)
        x[:, c] += x[:, d]
        x[:, b] = rot(x[:, b] ^ x[:, c], 7)

    with np.errstate(over="ignore"):
        for _ in range(10):
            qr(0, 4, 8, 12)
            qr(1, 5, 9, 13)
            qr(2, 6, 10, 14)
            qr(3, 7, 11, 15)
            qr(0, 5, 10, 15)
            qr(1, 6, 11, 12)
            qr(2, 7, 8, 13)
            qr(3, 4, 9, 14)
        x += init
    return x


def chacha20_keystream(key: bytes, nonce: bytes, n: int, counter: int = 0) -> bytes:
    """``n`` keystream bytes starting at block ``counter``."""
    if len(key) != 32 or len(nonce) != 12:
        raise ValueError("key must be 32 bytes, nonce 12")
    n_blocks = (n + 63) // 64
    if n_blocks == 0:
        return b""
    ks = _chacha_block_words(
        np.frombuffer(key, "<u4"), counter, np.frombuffer(nonce, "<u4"), n_blocks
    )
    return ks.astype("<u4").tobytes()[:n]


def chacha20_xor(key: bytes, nonce: bytes, data: bytes, counter: int = 0) -> bytes:
    """ChaCha20-XOR ``data`` (encrypt ≡ decrypt)."""
    if not data:
        return b""
    ks = chacha20_keystream(key, nonce, len(data), counter)
    return (
        np.frombuffer(data, np.uint8) ^ np.frombuffer(ks, np.uint8)
    ).tobytes()


# -- Poly1305 (RFC 8439 §2.5) -------------------------------------------

_P1305 = (1 << 130) - 5
_CLAMP = 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF


def poly1305(key: bytes, msg: bytes) -> bytes:
    """One-shot Poly1305 MAC; ``key`` = r(16) ‖ s(16)."""
    if len(key) != 32:
        raise ValueError("poly1305 key must be 32 bytes")
    r = int.from_bytes(key[:16], "little") & _CLAMP
    s = int.from_bytes(key[16:], "little")
    acc = 0
    for i in range(0, len(msg), 16):
        blk = msg[i : i + 16]
        acc = (acc + int.from_bytes(blk, "little") + (1 << (8 * len(blk)))) * r
        acc %= _P1305
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def _pad16(n: int) -> bytes:
    return b"\x00" * (-n % 16)


class ChaCha20Poly1305:
    """RFC 8439 AEAD, API-compatible with the ``cryptography`` class:
    ``encrypt(nonce, data, aad) -> ct ‖ tag(16)`` and ``decrypt``
    raising :class:`InvalidTag` on any authentication failure."""

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError("ChaCha20Poly1305 key must be 32 bytes")
        self._key = key

    def _tag(self, nonce: bytes, ct: bytes, aad: bytes) -> bytes:
        poly_key = chacha20_keystream(self._key, nonce, 32, counter=0)
        mac_data = (
            aad
            + _pad16(len(aad))
            + ct
            + _pad16(len(ct))
            + struct.pack("<QQ", len(aad), len(ct))
        )
        return poly1305(poly_key, mac_data)

    def encrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
        if len(nonce) != 12:
            raise ValueError("nonce must be 12 bytes")
        aad = aad or b""
        ct = chacha20_xor(self._key, nonce, data, counter=1)
        return ct + self._tag(nonce, ct, aad)

    def decrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
        if len(nonce) != 12:
            raise ValueError("nonce must be 12 bytes")
        if len(data) < 16:
            raise InvalidTag("ciphertext shorter than the tag")
        aad = aad or b""
        ct, tag = data[:-16], data[-16:]
        if not hmac.compare_digest(tag, self._tag(nonce, ct, aad)):
            raise InvalidTag("AEAD tag mismatch")
        return chacha20_xor(self._key, nonce, ct, counter=1)


# -- X25519 (RFC 7748 §5) -----------------------------------------------

_P25519 = 2**255 - 19
_A24 = 121665
_BASE_U = (9).to_bytes(32, "little")


def _decode_scalar(k: bytes) -> int:
    b = bytearray(k)
    b[0] &= 248
    b[31] &= 127
    b[31] |= 64
    return int.from_bytes(bytes(b), "little")


def x25519(scalar: bytes, u: bytes) -> bytes:
    """The X25519 function: constant formula sequence per ladder step
    (the Python big-int timing is not secret-independent — acceptable
    for this reproduction's once-per-connection handshakes, stated in
    SECURITY.md terms; the wheel-backed path is constant-time)."""
    if len(scalar) != 32 or len(u) != 32:
        raise ValueError("x25519 scalar and u-coordinate must be 32 bytes")
    k = _decode_scalar(scalar)
    # mask the high bit of the u-coordinate per RFC 7748 §5
    x1 = int.from_bytes(u[:31] + bytes([u[31] & 0x7F]), "little")
    x2, z2, x3, z3 = 1, 0, x1, 1
    swap = 0
    for t in reversed(range(255)):
        k_t = (k >> t) & 1
        if swap ^ k_t:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = k_t
        a = (x2 + z2) % _P25519
        aa = a * a % _P25519
        b = (x2 - z2) % _P25519
        bb = b * b % _P25519
        e = (aa - bb) % _P25519
        c = (x3 + z3) % _P25519
        d = (x3 - z3) % _P25519
        da = d * a % _P25519
        cb = c * b % _P25519
        x3 = (da + cb) % _P25519
        x3 = x3 * x3 % _P25519
        z3 = x1 * (da - cb) * (da - cb) % _P25519
        x2 = aa * bb % _P25519
        z2 = e * (aa + _A24 * e) % _P25519
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    return (x2 * pow(z2, _P25519 - 2, _P25519) % _P25519).to_bytes(32, "little")


class X25519PublicKey:
    """Raw 32-byte u-coordinate, wheel-compatible constructor surface."""

    def __init__(self, raw: bytes):
        if len(raw) != 32:
            raise ValueError("X25519 public key must be 32 bytes")
        self._raw = bytes(raw)

    @classmethod
    def from_public_bytes(cls, data: bytes) -> "X25519PublicKey":
        return cls(data)

    def public_bytes_raw(self) -> bytes:
        return self._raw


class X25519PrivateKey:
    """Raw 32-byte scalar, wheel-compatible constructor surface."""

    def __init__(self, raw: bytes):
        if len(raw) != 32:
            raise ValueError("X25519 private key must be 32 bytes")
        self._raw = bytes(raw)

    @classmethod
    def generate(cls) -> "X25519PrivateKey":
        return cls(os.urandom(32))

    @classmethod
    def from_private_bytes(cls, data: bytes) -> "X25519PrivateKey":
        return cls(data)

    def private_bytes_raw(self) -> bytes:
        return self._raw

    def public_key(self) -> X25519PublicKey:
        return X25519PublicKey(x25519(self._raw, _BASE_U))

    def exchange(self, peer_public_key: X25519PublicKey) -> bytes:
        out = x25519(self._raw, peer_public_key.public_bytes_raw())
        if out == b"\x00" * 32:
            # contributory-behavior check, same stance as the wheel:
            # a low-order peer point must not yield a usable secret
            raise ValueError("computed X25519 shared secret is all zeros")
        return out


# -- HKDF-SHA256 (RFC 5869) ---------------------------------------------


def hkdf_sha256(ikm: bytes, salt: bytes, info: bytes, length: int) -> bytes:
    """Extract-then-expand; ``length`` ≤ 255·32 (channel.py asks ≤ 64)."""
    if length > 255 * 32:
        raise ValueError("hkdf_sha256 length too large")
    prk = hmac.new(salt or b"\x00" * 32, ikm, hashlib.sha256).digest()
    okm = b""
    block = b""
    counter = 1
    while len(okm) < length:
        block = hmac.new(prk, block + info + bytes([counter]), hashlib.sha256).digest()
        okm += block
        counter += 1
    return okm[:length]
