"""Schnorrkel (sr25519) signatures, byte-compatible with the reference.

The reference authenticates every request with a deterministic
Schnorrkel signature over the 32-byte challenge under the signing
context ``b"grapevine-challenge"`` (reference README.md:193-199,
types/src/lib.rs:13,44-52; ``schnorrkel-og 0.11.0-pre.0`` pinned at
Cargo.toml:62). Round 3 shipped a same-shape RFC-9496 Schnorr instead
(session/ristretto.py); this module closes the gap so a reference-stack
client's ``sign_schnorrkel`` output verifies here unchanged.

The construction (schnorrkel sign.rs / context.rs, v0.11):

- transcript: ``Transcript::new(b"SigCtx")`` ‖ ``append_message(b"",
  context)`` ‖ ``append_message(b"sign-bytes", message)`` — the
  ``SigningContext::new(ctx).bytes(msg)`` path used by
  ``verify_simple`` / ``sign_simple``;
- challenge: append ``proto-name``=``Schnorr-sig``, ``sign:pk``=
  compressed public, ``sign:R``=compressed nonce point, then 64
  challenge bytes at label ``sign:c`` reduced mod L;
- signature bytes: ``R ‖ s`` with bit 7 of byte 63 set as the
  "marked schnorrkel" flag (sign.rs ``to_bytes``); ``from_bytes``
  REQUIRES the marker and clears it before the canonical-scalar check;
- verify: ``s·B == R + k·A``.

Nonce choice is signer-local (any ``r`` verifies): ours is
deterministic, SHA-512 over a domain-separated (sk, context, message)
tuple — same determinism property the reference's fork provides.

The merlin layer is vector-pinned (tests/test_merlin.py); the group and
batch equation ride session/ristretto.py's RFC-9496 machinery and its
native one-MSM path.

**Validation caveat** (stated, not hidden): the merlin/STROBE/Keccak
layers are pinned against published vectors, and the construction above
cites schnorrkel-og's sign.rs/context.rs labels line by line — but no
Rust-generated sr25519 signature vector is checked in-tree, because
this build environment has no Rust toolchain and no network. The
schnorrkel-level surface (label set, ``append_message(b"", context)``)
is exactly what a cross-stack vector would pin. To validate against the
real crate:  ``let kp = Keypair::from(SecretKey::from_bytes(..));
let sig = kp.sign_simple(b"grapevine-challenge", msg);`` then assert
``verify(pub, b"grapevine-challenge", msg, sig.to_bytes())`` here.
tests/test_schnorrkel.py pins this implementation's own golden values
so any drift is at least loud.
"""

from __future__ import annotations

import functools
import hashlib

from . import ristretto as _r
from .merlin import Transcript

__all__ = ["sign", "verify", "batch_verify", "keygen", "public_key"]

_NONCE_DOMAIN = b"grapevine-tpu-sr25519-nonce"

#: schnorrkel signing-context transcript label (context.rs)
_SIGCTX = b"SigCtx"
#: schnorrkel protocol name (sign.rs)
_PROTO = b"Schnorr-sig"


@functools.lru_cache(maxsize=64)
def _context_prefix(context: bytes) -> Transcript:
    """SigningContext prefix transcript, cached per context.

    The context is a handful of fixed strings (this service:
    ``b"grapevine-challenge"``); cloning the absorbed prefix per
    signature skips re-running the init permutations on the hot path.
    Callers must clone — never mutate the cached instance."""
    t = Transcript(_SIGCTX)
    t.append_message(b"", context)
    return t


def _challenge_scalar(
    context: bytes, message: bytes, pub: bytes, r_enc: bytes
) -> int:
    """The Fiat–Shamir challenge k, exactly as schnorrkel derives it."""
    t = _context_prefix(bytes(context)).clone()
    t.append_message(b"sign-bytes", message)
    t.append_message(b"proto-name", _PROTO)
    t.append_message(b"sign:pk", pub)
    t.append_message(b"sign:R", r_enc)
    wide = t.challenge_bytes(b"sign:c", 64)
    return int.from_bytes(wide, "little") % _r.L


# keys are plain ristretto scalars exactly like the reference's
# RistrettoPrivate (mc-crypto-keys builds the schnorrkel keypair from
# the bare scalar); reuse ristretto.py's derivation and caching
keygen = _r.keygen
public_key = _r.public_key


def sign(sk: bytes, context: bytes, message: bytes) -> bytes:
    """Deterministic sr25519 signature (64 bytes, schnorrkel-marked)."""
    a = int.from_bytes(sk, "little") % _r.L
    if a == 0:
        raise ValueError("invalid private key")
    pub = public_key(sk)
    h = hashlib.sha512()
    for part in (_NONCE_DOMAIN, sk, context, message):
        h.update(len(part).to_bytes(8, "little"))
        h.update(part)
    r = int.from_bytes(h.digest(), "little") % _r.L
    if r == 0:
        r = 1
    r_enc = _r._mult_base_enc(r)
    k = _challenge_scalar(context, message, pub, r_enc)
    s = (r + k * a) % _r.L
    sig = bytearray(r_enc + s.to_bytes(32, "little"))
    sig[63] |= 0x80  # schnorrkel marker bit (sign.rs to_bytes)
    return bytes(sig)


def _parse(signature: bytes) -> tuple[bytes, int] | None:
    """(R_enc, s) from marked signature bytes, or None if malformed.

    Mirrors schnorrkel ``Signature::from_bytes``: the marker bit MUST
    be set (unmarked ed25519-style bytes are rejected), and s must be a
    canonical scalar after clearing it."""
    if len(signature) != 64 or not signature[63] & 0x80:
        return None
    s_bytes = bytearray(signature[32:])
    s_bytes[31] &= 0x7F
    s = int.from_bytes(s_bytes, "little")
    if s >= _r.L:
        return None
    return signature[:32], s


def verify(pub: bytes, context: bytes, message: bytes, signature: bytes) -> bool:
    """True iff a schnorrkel signature verifies. Never raises."""
    if len(pub) != 32:
        return False
    parsed = _parse(signature)
    if parsed is None:
        return False
    r_enc, s = parsed
    k = _challenge_scalar(context, message, pub, r_enc)
    return _r.verify_core(pub, r_enc, s, k)


def batch_verify(
    items: list[tuple[bytes, bytes, bytes, bytes]],
    rng=None,
) -> bool:
    """True iff EVERY (pub, context, message, signature) verifies —
    one multi-scalar multiplication per chunk, shared with the RFC-9496
    scheme through ristretto.batch_verify_core."""
    parsed_items = []
    for pub, context, message, signature in items:
        if len(pub) != 32:
            return False
        parsed = _parse(signature)
        if parsed is None:
            return False
        r_enc, s = parsed
        k = _challenge_scalar(context, message, pub, r_enc)
        parsed_items.append((r_enc, pub, s, k))
    return _r.batch_verify_core(parsed_items, rng)
