"""Encrypted session channel: X25519 handshake + ChaCha20-Poly1305 frames.

The analog of the reference's attested noise channel (``mc-attest-ake``'s
IX handshake + ``mc-crypto-noise`` cipher states; reference
grapevine.proto:10-15, README.md:177-183). The handshake is
ephemeral-ephemeral X25519 with HKDF-SHA256 key derivation and directional
ChaCha20-Poly1305 cipher states with counter nonces.

Attestation is a pluggable evidence interface: TPU offers no SGX-style
remote attestation, so :class:`NullAttestation` ships empty evidence and
accepts peers — the interface point is kept so SGX/TDX/vTPM evidence can
slot in without touching the protocol (SURVEY.md §1 layer-2 mapping).

Auth RPC wire shape (mirrors AuthMessageWithChallengeSeed,
grapevine.proto:26-36): the server's handshake reply carries its ephemeral
public key + evidence, and the 32-byte challenge seed travels only as
ciphertext under the freshly established channel.
"""

from __future__ import annotations

import os
import struct

from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey,
    X25519PublicKey,
)
from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
from cryptography.hazmat.primitives.kdf.hkdf import HKDF
from cryptography.hazmat.primitives import hashes

_HKDF_INFO = b"grapevine-tpu-channel-v1"


class NullAttestation:
    """No-enclave evidence provider: empty evidence, accepts all peers."""

    def evidence(self) -> bytes:
        return b""

    def verify(self, evidence: bytes) -> bool:
        return True


class SecureChannel:
    """Directional AEAD cipher states with 96-bit counter nonces."""

    def __init__(self, send_key: bytes, recv_key: bytes):
        self._send = ChaCha20Poly1305(send_key)
        self._recv = ChaCha20Poly1305(recv_key)
        self._send_n = 0
        self._recv_n = 0

    @staticmethod
    def _nonce(counter: int) -> bytes:
        return struct.pack("<Q", counter) + b"\x00" * 4

    def encrypt(self, plaintext: bytes, aad: bytes = b"") -> bytes:
        ct = self._send.encrypt(self._nonce(self._send_n), plaintext, aad)
        self._send_n += 1
        return ct

    def decrypt(self, ciphertext: bytes, aad: bytes = b"") -> bytes:
        pt = self._recv.decrypt(self._nonce(self._recv_n), ciphertext, aad)
        self._recv_n += 1
        return pt


def _derive(shared: bytes, transcript: bytes) -> tuple[bytes, bytes]:
    okm = HKDF(
        algorithm=hashes.SHA256(), length=64, salt=transcript, info=_HKDF_INFO
    ).derive(shared)
    return okm[:32], okm[32:]


def client_handshake():
    """Start a handshake: returns (state, first_message_bytes)."""
    priv = X25519PrivateKey.generate()
    pub = priv.public_key().public_bytes_raw()
    return priv, pub


def client_finish(priv: X25519PrivateKey, server_msg: bytes, attestation=None):
    """Complete the handshake from the server's reply.

    ``server_msg`` = server ephemeral pub (32) ‖ evidence. Returns a
    :class:`SecureChannel` (client perspective).
    """
    attestation = attestation or NullAttestation()
    if len(server_msg) < 32:
        raise ValueError("short handshake reply")
    server_pub, evidence = server_msg[:32], server_msg[32:]
    if not attestation.verify(evidence):
        raise ValueError("attestation evidence rejected")
    shared = priv.exchange(X25519PublicKey.from_public_bytes(server_pub))
    transcript = priv.public_key().public_bytes_raw() + server_pub
    k_c2s, k_s2c = _derive(shared, transcript)
    return SecureChannel(send_key=k_c2s, recv_key=k_s2c)


def server_handshake(client_msg: bytes, attestation=None):
    """Server side: returns (reply_bytes, channel).

    ``client_msg`` = client ephemeral pub (32). The reply embeds this
    side's ephemeral pub + attestation evidence.
    """
    attestation = attestation or NullAttestation()
    if len(client_msg) != 32:
        raise ValueError("handshake message must be a 32-byte public key")
    priv = X25519PrivateKey.generate()
    pub = priv.public_key().public_bytes_raw()
    shared = priv.exchange(X25519PublicKey.from_public_bytes(client_msg))
    transcript = client_msg + pub
    k_c2s, k_s2c = _derive(shared, transcript)
    channel = SecureChannel(send_key=k_s2c, recv_key=k_c2s)
    return pub + attestation.evidence(), channel


def new_challenge_seed() -> bytes:
    return os.urandom(32)
