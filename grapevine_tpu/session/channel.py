"""Encrypted session channel: X25519 IX handshake + ChaCha20-Poly1305.

The analog of the reference's attested noise channel (``mc-attest-ake``'s
Noise **IX** handshake + ``mc-crypto-noise`` cipher states; reference
grapevine.proto:10-15, README.md:177-183). Like IX, both sides' static
keys are authenticated *inside* the handshake:

- message 1 (client → server): ``e_c ‖ s_c`` — client ephemeral plus
  client static (all-zero s_c = anonymous client; per-request identity
  still comes from the sr25519 challenge signatures either way);
- message 2 (server → client): ``e_r ‖ AEAD(k_h, s_r ‖ evidence)`` —
  server ephemeral, then the server *static* and attestation evidence
  encrypted under a key derived from the ephemeral-ephemeral secret and
  bound to the transcript hash as AAD;
- channel keys = HKDF(ee ‖ es ‖ se, salt = transcript hash): the
  server can only derive them by owning ``s_r`` (es), and a client that
  sent a static can only derive them by owning ``s_c`` (se) — the IX
  mutual-authentication property. An active MITM that substitutes
  either static changes the transcript and the DH mix; the first frame
  on the channel fails AEAD (tests/test_ix_handshake.py MITM tests).

Server identity policy is the caller's: clients pin the expected server
static (``expected_server_static=``) and/or verify attestation evidence
bound to (static, transcript). With ``NullAttestation`` and no pinning,
``insecure-grapevine://`` sessions are confidential against passive
observers only — stated in SECURITY.md.

Attestation is a pluggable evidence interface: TPU offers no SGX-style
remote attestation, so :class:`NullAttestation` ships empty evidence and
accepts peers — the interface point is kept so SGX/TDX/vTPM evidence can
slot in without touching the protocol (SURVEY.md §1 layer-2 mapping).
Evidence is *transcript-bound*: ``verify(evidence, binding=...)``
receives the hash covering both handshake messages and the server
static, so real evidence can sign it and preclude evidence replay.

Auth RPC wire shape (mirrors AuthMessageWithChallengeSeed,
grapevine.proto:26-36): the server's handshake reply carries its
handshake message + evidence, and the 32-byte challenge seed travels
only as ciphertext under the freshly established channel.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import struct

try:
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF
    from cryptography.hazmat.primitives import hashes

    CRYPTO_BACKEND = "cryptography"

    def _hkdf(ikm: bytes, salt: bytes, info: bytes, length: int) -> bytes:
        return HKDF(
            algorithm=hashes.SHA256(), length=length, salt=salt, info=info
        ).derive(ikm)

except ModuleNotFoundError:
    # Wheel-less container: the stdlib + numpy backend (stdcrypto.py) is
    # bit-compatible by RFC construction, so channels interoperate across
    # backends — a stdlib client speaks to a wheel-backed server and
    # vice versa (pinned in tests/test_stdcrypto.py when both exist).
    from .stdcrypto import (
        ChaCha20Poly1305,
        X25519PrivateKey,
        X25519PublicKey,
        hkdf_sha256 as _hkdf,
    )

    CRYPTO_BACKEND = "stdlib"

_HKDF_INFO = b"grapevine-tpu-channel-ix-v1"
_HS_INFO = b"grapevine-tpu-ix-handshake"
_PROTO_TAG = b"grapevine-tpu-ix-v1"
_ZERO32 = b"\x00" * 32


class NullAttestation:
    """No-enclave evidence provider: empty evidence, accepts all peers."""

    def evidence(self, binding: bytes = b"") -> bytes:
        return b""

    def verify(self, evidence: bytes, binding: bytes = b"") -> bool:
        return True


class SecureChannel:
    """Directional AEAD cipher states with 96-bit counter nonces."""

    def __init__(self, send_key: bytes, recv_key: bytes):
        self._send = ChaCha20Poly1305(send_key)
        self._recv = ChaCha20Poly1305(recv_key)
        self._send_keyb = send_key
        self._recv_keyb = recv_key
        self._send_n = 0
        self._recv_n = 0

    def export_keys(self) -> tuple[bytes, bytes, int, int]:
        """(send_key, recv_key, send_n, recv_n) — the hostpipe session
        hand-off (server/hostpipe.py): the sticky worker rebuilds both
        directional cipher states, counters included, in its own
        process; this side must stop using the channel afterwards or
        the nonce counters fork."""
        return self._send_keyb, self._recv_keyb, self._send_n, self._recv_n

    @staticmethod
    def _nonce(counter: int) -> bytes:
        return struct.pack("<Q", counter) + b"\x00" * 4

    def encrypt(self, plaintext: bytes, aad: bytes = b"") -> bytes:
        ct = self._send.encrypt(self._nonce(self._send_n), plaintext, aad)
        self._send_n += 1
        return ct

    def decrypt(self, ciphertext: bytes, aad: bytes = b"") -> bytes:
        pt = self._recv.decrypt(self._nonce(self._recv_n), ciphertext, aad)
        self._recv_n += 1
        return pt


def _derive_channel(
    ee: bytes, es: bytes, se: bytes, transcript: bytes
) -> tuple[bytes, bytes]:
    """(k_c2s, k_s2c) from the concatenated DH outputs + transcript."""
    okm = _hkdf(ee + es + se, transcript, _HKDF_INFO, 64)
    return okm[:32], okm[32:]


def _hs_key(ee: bytes, transcript: bytes) -> bytes:
    """Handshake-message key: encrypts the server static + evidence."""
    return _hkdf(ee, transcript, _HS_INFO, 32)


class ServerIdentity:
    """The server's static X25519 keypair (the IX responder static)."""

    def __init__(self, priv: X25519PrivateKey):
        self._priv = priv
        self.public = priv.public_key().public_bytes_raw()

    @classmethod
    def generate(cls) -> "ServerIdentity":
        return cls(X25519PrivateKey.generate())

    @classmethod
    def from_seed(cls, seed: bytes) -> "ServerIdentity":
        if len(seed) != 32:
            raise ValueError("identity seed must be 32 bytes")
        # domain-separate so a leaked channel seed never doubles as a key
        key = hashlib.sha256(b"grapevine-tpu-server-static" + seed).digest()
        return cls(X25519PrivateKey.from_private_bytes(key))


@dataclasses.dataclass
class ClientHandshake:
    """Client-side handshake state between message 1 and message 2."""

    eph_priv: X25519PrivateKey
    static_priv: X25519PrivateKey | None
    msg1: bytes


def client_handshake(client_static: X25519PrivateKey | None = None):
    """Start an IX handshake: returns (state, first_message_bytes).

    ``client_static`` authenticates the client inside the handshake
    (the IX ``s``/``se`` tokens); None sends the all-zero placeholder —
    an anonymous client, still request-authenticated via sr25519.
    """
    eph = X25519PrivateKey.generate()
    s_pub = (
        client_static.public_key().public_bytes_raw()
        if client_static is not None
        else _ZERO32
    )
    msg1 = eph.public_key().public_bytes_raw() + s_pub
    return ClientHandshake(eph, client_static, msg1), msg1


def client_finish(
    state: ClientHandshake,
    server_msg: bytes,
    attestation=None,
    expected_server_static: bytes | None = None,
):
    """Complete the handshake from the server's reply.

    ``server_msg`` = ``e_r (32) ‖ AEAD(k_h, s_r ‖ evidence)``. Verifies
    the transcript-bound AEAD, optionally pins the server static, and
    hands the evidence (with its transcript binding) to ``attestation``.
    Returns a :class:`SecureChannel`; the authenticated server static is
    exposed as ``channel.peer_static``.
    """
    attestation = attestation or NullAttestation()
    if len(server_msg) < 32 + 32 + 16:  # e_r + AEAD(s_r) at minimum
        raise ValueError("short handshake reply")
    e_r, ct = server_msg[:32], server_msg[32:]
    transcript1 = hashlib.sha256(_PROTO_TAG + state.msg1 + e_r).digest()
    ee = state.eph_priv.exchange(X25519PublicKey.from_public_bytes(e_r))
    try:
        inner = ChaCha20Poly1305(_hs_key(ee, transcript1)).decrypt(
            b"\x00" * 12, ct, transcript1
        )
    except Exception:
        raise ValueError("handshake reply failed authentication") from None
    s_r, evidence = inner[:32], inner[32:]
    if expected_server_static is not None and s_r != expected_server_static:
        raise ValueError("server static key does not match the pinned key")
    # the evidence binding covers both handshake messages AND the server
    # static, and is the SAME value the server signed over — a real
    # provider signs binding, the verifier checks that signature against
    # an identical binding (evidence itself excluded: the signer cannot
    # sign a hash of its own signature)
    binding = hashlib.sha256(transcript1 + s_r).digest()
    if not attestation.verify(evidence, binding=binding):
        raise ValueError("attestation evidence rejected")
    transcript2 = hashlib.sha256(transcript1 + s_r + evidence).digest()
    es = state.eph_priv.exchange(X25519PublicKey.from_public_bytes(s_r))
    se = (
        state.static_priv.exchange(X25519PublicKey.from_public_bytes(e_r))
        if state.static_priv is not None
        else b""
    )
    k_c2s, k_s2c = _derive_channel(ee, es, se, transcript2)
    channel = SecureChannel(send_key=k_c2s, recv_key=k_s2c)
    channel.peer_static = s_r
    return channel


def server_handshake(client_msg: bytes, attestation=None, identity=None):
    """Server side: returns (reply_bytes, channel).

    ``client_msg`` = ``e_c (32) ‖ s_c (32)`` (s_c all-zero = anonymous).
    ``identity`` is the server's :class:`ServerIdentity`; generated
    fresh when omitted (callers wanting a stable, pinnable identity
    pass one — GrapevineServer does). The claimed client static is
    exposed as ``channel.peer_static`` (None when anonymous); its
    ownership is proven by the ``se`` mix — a liar cannot decrypt
    anything on the resulting channel.
    """
    attestation = attestation or NullAttestation()
    identity = identity or ServerIdentity.generate()
    if len(client_msg) != 64:
        raise ValueError("handshake message must be e_c(32) ‖ s_c(32)")
    e_c, s_c = client_msg[:32], client_msg[32:]
    eph = X25519PrivateKey.generate()
    e_r = eph.public_key().public_bytes_raw()
    transcript1 = hashlib.sha256(_PROTO_TAG + client_msg + e_r).digest()
    ee = eph.exchange(X25519PublicKey.from_public_bytes(e_c))
    # same binding the client verifies against: msg1 ‖ e_r ‖ s_r
    evidence = attestation.evidence(
        binding=hashlib.sha256(transcript1 + identity.public).digest()
    )
    inner = identity.public + evidence
    ct = ChaCha20Poly1305(_hs_key(ee, transcript1)).encrypt(
        b"\x00" * 12, inner, transcript1
    )
    transcript2 = hashlib.sha256(transcript1 + identity.public + evidence).digest()
    es = identity._priv.exchange(X25519PublicKey.from_public_bytes(e_c))
    se = (
        eph.exchange(X25519PublicKey.from_public_bytes(s_c))
        if s_c != _ZERO32
        else b""
    )
    k_c2s, k_s2c = _derive_channel(ee, es, se, transcript2)
    channel = SecureChannel(send_key=k_s2c, recv_key=k_c2s)
    channel.peer_static = None if s_c == _ZERO32 else s_c
    return e_r + ct, channel


def new_challenge_seed() -> bytes:
    return os.urandom(32)
