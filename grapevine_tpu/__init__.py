"""grapevine_tpu: a TPU-native oblivious message bus framework.

A ground-up rebuild of the capabilities of mobilecoinofficial/grapevine
(reference: an SGX-enclave CRUD message broker over MobileCoin's Path-ORAM,
see /root/reference/README.md:9-16) designed for TPU hardware:

- the oblivious storage engine is a batched, branchless, jit-compiled
  Path-ORAM over an HBM-resident SoA bucket tree (``grapevine_tpu.oram``),
- CRUD semantics run as a uniform masked access sequence so that
  Read/Update/Delete are indistinguishable in the device access transcript
  (reference spec: grapevine.proto:120-122),
- the session layer (noise-style channel, ChaCha20 challenge RNG,
  ristretto/Schnorr request signatures) runs host-side
  (``grapevine_tpu.session``),
- scaling across chips uses a jax.sharding Mesh with the record space
  partitioned per-chip and responses gathered over ICI
  (``grapevine_tpu.parallel``).
"""

__version__ = "0.1.0"
