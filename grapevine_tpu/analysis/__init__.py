"""Static obliviousness + overflow + concurrency analysis (ISSUEs 12/14).

Three prongs, one package:

- :mod:`oblint` — jaxpr-level taint-propagation analyzer proving that no
  gather/scatter index, cond/while predicate, dynamic-slice start, or
  host callback operand in a traced engine round is secret-derived,
  modulo an explicit reviewed allowlist (:mod:`allowlist`) of
  oblivious-by-construction sites. Shared jaxpr-walking/census helpers
  (:mod:`jaxpr_walk`) back both this analyzer and the legacy CI gates
  (tools/check_posmap_oblivious.py, tools/check_tree_cache_oblivious.py)
  so the three tools cannot drift.
- :mod:`rangelint` — interval-domain abstract interpreter over the same
  equation walk, certifying the round's u32/int32 lanes wraparound-,
  truncation-, and clamped-OOB-free at the declared geometry
  (RANGELINT_BOUNDS anchors; the mod-2^32-by-design sites ride
  ``allowlist.RANGE_ALLOWLIST``). Driven by tools/check_ranges.py up to
  the certified bound and the 2^36 design-point refusal.
- :mod:`locklint` — AST lock-discipline lint for the pipelined host path
  (engine/batcher.py, server/scheduler.py, engine/journal.py): the PR-10
  single-lock-hold invariant, stage-1-outside-the-lock, lock-ordering
  acyclicity, and shared-mutable-attribute coverage.

Driven by tools/check_oblivious.py + tools/check_ranges.py across the
live knob matrix, with :mod:`mutants` as the seeded positive controls
for BOTH analyzers (each must FAIL).
"""

from .jaxpr_walk import census, plane_rows, site_of, walk_eqns
from .oblint import (
    AllowEntry,
    OblintReport,
    Violation,
    analyze,
    census_equal,
)
from .rangelint import RangeFinding, RangeReport, analyze_ranges

__all__ = [
    "AllowEntry",
    "OblintReport",
    "RangeFinding",
    "RangeReport",
    "Violation",
    "analyze",
    "analyze_ranges",
    "census",
    "census_equal",
    "plane_rows",
    "site_of",
    "walk_eqns",
]
