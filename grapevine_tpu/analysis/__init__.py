"""Static obliviousness + concurrency analysis (ISSUE 12).

Two prongs, one package:

- :mod:`oblint` — jaxpr-level taint-propagation analyzer proving that no
  gather/scatter index, cond/while predicate, dynamic-slice start, or
  host callback operand in a traced engine round is secret-derived,
  modulo an explicit reviewed allowlist (:mod:`allowlist`) of
  oblivious-by-construction sites. Shared jaxpr-walking/census helpers
  (:mod:`jaxpr_walk`) back both this analyzer and the legacy CI gates
  (tools/check_posmap_oblivious.py, tools/check_tree_cache_oblivious.py)
  so the three tools cannot drift.
- :mod:`locklint` — AST lock-discipline lint for the pipelined host path
  (engine/batcher.py, server/scheduler.py, engine/journal.py): the PR-10
  single-lock-hold invariant, stage-1-outside-the-lock, lock-ordering
  acyclicity, and shared-mutable-attribute coverage.

Driven by tools/check_oblivious.py across the live knob matrix, with
:mod:`mutants` as the seeded positive controls (each must FAIL).
"""

from .jaxpr_walk import census, plane_rows, site_of, walk_eqns
from .oblint import (
    AllowEntry,
    OblintReport,
    Violation,
    analyze,
    census_equal,
)

__all__ = [
    "AllowEntry",
    "OblintReport",
    "Violation",
    "analyze",
    "census",
    "census_equal",
    "plane_rows",
    "site_of",
    "walk_eqns",
]
