"""Locklint: static lock-discipline lint for the pipelined host path.

The device path's obliviousness proof (:mod:`.oblint`) has a host-side
twin: PR 10 made the batcher a staged pipeline whose correctness hangs
on a lock discipline that exists only in docstrings. This lint derives
the discipline from the AST of engine/batcher.py, server/scheduler.py,
and engine/journal.py and asserts it statically:

1. **Single-hold (PR 10)**: ``GrapevineEngine.handle_queries_async``
   journals AND dispatches inside exactly one ``self._lock`` hold —
   journal order IS dispatch order, so replay order is journal order at
   every pipeline depth. Neither stage acquires a lock of its own.
2. **Stage 1 outside the lock**: assemble/validate/pack
   (``_assemble_round``, ``pack_batch``, ``validate_request``) never
   run under any engine lock — the pipeline's whole point is that the
   next round's host work overlaps the device.
3. **Journal is lock-free**: ``BatchJournal`` documents "every call
   runs under the engine lock" — it must never grow a lock of its own
   (a second lock under the engine hold is an ordering hazard).
4. **No lock-ordering cycle**: the acquired-while-holding graph over
   every discovered lock (collector cv, engine lock, and any future
   addition) must be acyclic, including cross-object edges through
   known bindings (``BatchScheduler.engine`` is a GrapevineEngine).
5. **Shared-attribute coverage**: any attribute written outside
   ``__init__`` and touched from more than one thread role (the
   collector thread vs submitter/probe threads, derived from
   ``threading.Thread(target=self._run)``) must hold a lock at every
   access — unless a reviewed entry in LOCK_ALLOW documents the benign
   race. A new unprotected shared attribute fails the lint by default.

Nested helper functions (e.g. ``settle_head`` inside ``_run_inner``)
are folded into their defining method with the def-site lock context;
this matches current call sites and over-reports rather than misses.
"""

from __future__ import annotations

import ast
import dataclasses
import os

_LOCK_CTORS = ("Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore")


@dataclasses.dataclass(frozen=True)
class LockViolation:
    kind: str  # same-hold | stage1-under-lock | journal-lock |
    #            lock-cycle | shared-attr | missing-code
    where: str  # "Class.method" or "Class.attr"
    message: str

    def __str__(self) -> str:
        return f"{self.kind}: {self.where} — {self.message}"


@dataclasses.dataclass(frozen=True)
class LockAllow:
    """One reviewed benign race: (class, attr) plus its argument.

    ``reads_only=True`` tolerates unlocked *reads* while still failing
    an unlocked write — the single-writer-behind-the-lock pattern."""

    cls: str
    attr: str
    reason: str
    reads_only: bool = False


#: the reviewed benign-race list (the locklint analog of the oblint
#: allowlist; every entry carries its argument)
LOCK_ALLOW: tuple = (
    LockAllow("BatchScheduler", "_inflight_since",
              "single-writer collector float (written off-lock on the "
              "collector only); stall_age's unlocked read is "
              "monotonic-clock math, worst case one stale probe"),
    LockAllow("BatchScheduler", "_shutdown",
              "monotonic bool: set only under the cv by close(); the "
              "crash handler's unlocked read risks one extra supervised "
              "restart, never a wrong drain", reads_only=True),
    LockAllow("GrapevineEngine", "state",
              "every write runs under the engine lock (in-body or via "
              "the lock-held dispatch stage); message_count/"
              "recipient_count take an unlocked reference snapshot for "
              "gauges — atomic in CPython, one round stale at worst",
              reads_only=True),
    LockAllow("HostPipeline", "_closing",
              "monotonic shutdown latch (False -> True once, in "
              "close()): reader threads and submitters take unlocked "
              "reads; a stale False risks one submit racing close — it "
              "fails on the closed pipe with HostWorkerCrash, never a "
              "wrong result — and a stale True only skips crash "
              "handling the close path is about to do anyway"),
    LockAllow("GrapevineEngine", "_rounds_since_flush",
              "every write runs under the engine lock "
              "(_flush_window_locked / recovery); flush_bubble_pending "
              "takes one unlocked int read for the scheduler's window "
              "decision — CPython-atomic, one round stale at worst, "
              "and a stale read only mistimes a collection-window "
              "stretch (latency, never correctness or cadence: the "
              "flush itself still fires strictly every evict_every "
              "rounds under the lock)", reads_only=True),
    LockAllow("GrapevineEngine", "leakmon",
              "attach-before-serve single reference assignment"),
    LockAllow("GrapevineEngine", "tracer",
              "attach-before-serve single reference assignment"),
    LockAllow("GrapevineEngine", "slo",
              "attach-before-serve single reference assignment"),
    LockAllow("GrapevineEngine", "workload",
              "attach-before-serve single reference assignment"),
    LockAllow("GrapevineEngine", "costmon",
              "attach-before-serve single reference assignment"),
    LockAllow("GrapevineEngine", "_replay_since",
              "recovery-only scratch (the replay cadence audit): "
              "written exclusively inside __init__'s single-threaded "
              "journal replay, before any scheduler/collector thread "
              "exists; never touched after construction"),
)


# ---------------------------------------------------------------------------
# per-class fact extraction


@dataclasses.dataclass
class _Method:
    name: str
    acquired: set = dataclasses.field(default_factory=set)  # lock names
    #: (lock, region_id) -> set of callee keys in that region
    regions: dict = dataclasses.field(default_factory=dict)
    #: callee key -> set of frozenset(held) contexts it was called under
    calls: dict = dataclasses.field(default_factory=dict)
    #: attr -> list of (is_write, frozenset(held))
    attrs: dict = dataclasses.field(default_factory=dict)
    #: (held_lock, acquired_lock) pairs from directly nested `with`s
    nested: set = dataclasses.field(default_factory=set)
    worker_root: bool = False  # threading.Thread(target=self.<this>)


@dataclasses.dataclass
class _Class:
    name: str
    locks: set = dataclasses.field(default_factory=set)
    methods: dict = dataclasses.field(default_factory=dict)
    #: self.<attr> -> bound class name (constructor annotations)
    bindings: dict = dataclasses.field(default_factory=dict)


def _self_attr(node) -> str | None:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _callee_key(call: ast.Call):
    """'m' for self.m(), 'f' for f(), ('attr', 'm') for self.attr.m()."""
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        base = _self_attr(f)
        if base is not None:
            return f.attr  # self.m(...)
        inner = _self_attr(f.value) if isinstance(f.value, ast.AST) else None
        if inner is not None:
            return (inner, f.attr)  # self.attr.m(...)
    return None


class _MethodVisitor(ast.NodeVisitor):
    def __init__(self, cls: _Class, meth: _Method):
        self.cls = cls
        self.m = meth
        self.held: list = []
        self._region_n = 0

    # -- lock regions ---------------------------------------------------

    def visit_With(self, node: ast.With):
        lock_items = []
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr in self.cls.locks:
                lock_items.append(attr)
        for lk in lock_items:
            for held_lk, _ in self.held:
                if held_lk != lk:
                    self.m.nested.add((held_lk, lk))
            self._region_n += 1
            self.m.acquired.add(lk)
            self.m.regions[(lk, self._region_n)] = set()
            self.held.append((lk, self._region_n))
        for stmt in node.body:
            self.visit(stmt)
        for _ in lock_items:
            self.held.pop()
        # visit the context expressions too (e.g. time_phase(...) calls)
        for item in node.items:
            if _self_attr(item.context_expr) not in self.cls.locks:
                self.visit(item.context_expr)

    # -- calls ----------------------------------------------------------

    def visit_Call(self, node: ast.Call):
        key = _callee_key(node)
        held = frozenset(lk for lk, _ in self.held)
        if key is not None and not (
            isinstance(key, str) and key in self.cls.locks
        ):
            self.m.calls.setdefault(key, set()).add(held)
            for lk, rid in self.held:
                self.m.regions[(lk, rid)].add(key)
        # worker-root detection: threading.Thread(target=self._run)
        fn = node.func
        if (isinstance(fn, ast.Attribute) and fn.attr == "Thread") or (
            isinstance(fn, ast.Name) and fn.id == "Thread"
        ):
            for kw in node.keywords:
                tgt = kw.value
                if kw.arg == "target" and _self_attr(tgt) is not None:
                    root = _self_attr(tgt)
                    if root in self.cls.methods:
                        self.cls.methods[root].worker_root = True
                    else:  # method parsed later; mark via sentinel
                        self.cls.methods.setdefault(
                            root, _Method(root)
                        ).worker_root = True
        self.generic_visit(node)

    # -- attribute access ----------------------------------------------

    def visit_Attribute(self, node: ast.Attribute):
        attr = _self_attr(node)
        if attr is not None and attr not in self.cls.locks:
            is_write = isinstance(node.ctx, (ast.Store, ast.Del))
            self.m.attrs.setdefault(attr, []).append(
                (is_write, frozenset(lk for lk, _ in self.held))
            )
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        attr = _self_attr(node.target)
        if attr is not None and attr not in self.cls.locks:
            self.m.attrs.setdefault(attr, []).append(
                (True, frozenset(lk for lk, _ in self.held))
            )
        self.generic_visit(node)


def _extract(tree: ast.Module) -> dict:
    """module AST -> {class name: _Class facts}."""
    out: dict = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        cls = _Class(node.name)
        out[cls.name] = cls
        # pass 1: lock attributes + constructor bindings
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                attr = _self_attr(sub.targets[0])
                v = sub.value
                if attr and isinstance(v, ast.Call):
                    ctor = (
                        v.func.attr if isinstance(v.func, ast.Attribute)
                        else v.func.id if isinstance(v.func, ast.Name)
                        else None
                    )
                    if ctor in _LOCK_CTORS:
                        cls.locks.add(attr)
        for sub in node.body:
            if isinstance(sub, ast.FunctionDef) and sub.name == "__init__":
                for a in sub.args.args:
                    ann = a.annotation
                    if ann is not None:
                        nm = (
                            ann.id if isinstance(ann, ast.Name)
                            else ann.attr if isinstance(ann, ast.Attribute)
                            else None
                        )
                        if nm:
                            cls.bindings[a.arg] = nm
                # self.x = <argname> carries the annotation to the attr
                for st in ast.walk(sub):
                    if (isinstance(st, ast.Assign)
                            and len(st.targets) == 1
                            and isinstance(st.value, ast.Name)):
                        attr = _self_attr(st.targets[0])
                        argname = st.value.id
                        if attr and argname in cls.bindings:
                            cls.bindings[attr] = cls.bindings[argname]
        # pass 2: per-method walk
        for sub in node.body:
            if isinstance(sub, ast.FunctionDef):
                m = cls.methods.setdefault(sub.name, _Method(sub.name))
                m.name = sub.name
                _MethodVisitor(cls, m).visit(sub)
    return out


# ---------------------------------------------------------------------------
# derived facts


def _transitive_acquires(cls: _Class) -> dict:
    """method -> set of locks it may acquire (self-calls followed)."""
    acq = {n: set(m.acquired) for n, m in cls.methods.items()}
    changed = True
    while changed:
        changed = False
        for n, m in cls.methods.items():
            for key in m.calls:
                if isinstance(key, str) and key in acq:
                    if not acq[key] <= acq[n]:
                        acq[n] |= acq[key]
                        changed = True
    return acq


def _roles(cls: _Class) -> dict:
    """method -> {'worker'} | {'caller'} | both; worker = transitive
    closure of Thread-target roots, caller = everything else public or
    reachable from elsewhere. ``__init__`` is excluded entirely."""
    worker = {n for n, m in cls.methods.items() if m.worker_root}
    changed = True
    while changed:
        changed = False
        for n in list(worker):
            m = cls.methods.get(n)
            if m is None:
                continue
            for key in m.calls:
                if isinstance(key, str) and key in cls.methods \
                        and key not in worker:
                    worker.add(key)
                    changed = True
    roles = {}
    for n in cls.methods:
        if n == "__init__":
            continue
        roles[n] = {"worker"} if n in worker else {"caller"}
    return roles


def _lock_graph(classes: dict) -> list:
    """Edges (held_lock, acquired_lock) as (Class.lock, Class.lock)."""
    edges = set()
    for cls in classes.values():
        acq = _transitive_acquires(cls)
        for m in cls.methods.values():
            for (lk, _rid), callees in m.regions.items():
                src = f"{cls.name}.{lk}"
                for key in callees:
                    if isinstance(key, str):
                        if key in cls.locks:
                            continue
                        for lk2 in acq.get(key, ()):  # self.m() acquiring
                            edges.add((src, f"{cls.name}.{lk2}"))
                    elif isinstance(key, tuple):  # self.attr.m()
                        bound = cls.bindings.get(key[0])
                        tgt = classes.get(bound) if bound else None
                        if tgt is not None:
                            tacq = _transitive_acquires(tgt)
                            for lk2 in tacq.get(key[1], ()):
                                edges.add((src, f"{tgt.name}.{lk2}"))
            # directly nested `with` acquisitions (recorded at
            # acquisition time with the precise held set)
            for held_lk, acq_lk in m.nested:
                edges.add(
                    (f"{cls.name}.{held_lk}", f"{cls.name}.{acq_lk}")
                )
    return sorted(edges)


def _find_cycle(edges: list) -> list | None:
    graph: dict = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    seen, stack = set(), []

    def dfs(n):
        if n in stack:
            return stack[stack.index(n):] + [n]
        if n in seen:
            return None
        seen.add(n)
        stack.append(n)
        for nxt in graph.get(n, ()):
            cyc = dfs(nxt)
            if cyc:
                return cyc
        stack.pop()
        return None

    for n in list(graph):
        cyc = dfs(n)
        if cyc:
            return cyc
    return None


# ---------------------------------------------------------------------------
# the lint


def lint_sources(sources: dict, allow: tuple = LOCK_ALLOW) -> list:
    """Lint {filename: python source}; returns LockViolations.

    The invariant spec is fixed (it IS the repo's documented
    discipline): GrapevineEngine/_lock single-hold over
    _journal_round+_dispatch_round, stage-1 callees outside every lock,
    BatchJournal lock-free, acyclic lock graph, role-covered shared
    attributes in BatchScheduler and GrapevineEngine."""
    classes: dict = {}
    for fname, src in sources.items():
        classes.update(_extract(ast.parse(src, filename=fname)))
    out: list = []

    # 1. PR-10 single-hold --------------------------------------------------
    eng = classes.get("GrapevineEngine")
    if eng is None or "handle_queries_async" not in eng.methods:
        out.append(LockViolation(
            "missing-code", "GrapevineEngine.handle_queries_async",
            "the pipelined dispatch path is gone — the PR-10 invariant "
            "cannot be checked"))
    else:
        m = eng.methods["handle_queries_async"]
        lock_regions = [
            callees for (lk, _), callees in m.regions.items()
            if lk == "_lock"
        ]
        both = [
            r for r in lock_regions
            if "_journal_round" in r and "_dispatch_round" in r
        ]
        if len(lock_regions) != 1 or not both:
            out.append(LockViolation(
                "same-hold", "GrapevineEngine.handle_queries_async",
                f"journal+dispatch must share exactly ONE _lock hold "
                f"(found {len(lock_regions)} hold(s), "
                f"{len(both)} containing both stages) — split holds let "
                "another round dispatch between append and enqueue, and "
                "replay order stops being journal order"))
        acq = _transitive_acquires(eng)
        for stage in ("_journal_round", "_dispatch_round"):
            if acq.get(stage):
                out.append(LockViolation(
                    "same-hold", f"GrapevineEngine.{stage}",
                    f"stage acquires {sorted(acq[stage])} of its own — "
                    "stages run inside the caller's hold, a nested "
                    "acquire is an ordering hazard"))

    # 2. stage-1 outside every lock ----------------------------------------
    stage1 = ("_assemble_round", "pack_batch", "validate_request")
    if eng is not None:
        # does method m (when called) transitively reach a stage-1 fn?
        reaches: dict = {n: False for n in eng.methods}
        changed = True
        while changed:
            changed = False
            for n, m in eng.methods.items():
                if reaches[n]:
                    continue
                for key in m.calls:
                    if key in stage1 or (
                        isinstance(key, str) and reaches.get(key, False)
                    ):
                        reaches[n] = True
                        changed = True
        for n, m in eng.methods.items():
            for key, helds in m.calls.items():
                hits_stage1 = key in stage1 or (
                    isinstance(key, str) and reaches.get(key, False)
                )
                if hits_stage1 and any(helds_i for helds_i in helds
                                       if helds_i):
                    out.append(LockViolation(
                        "stage1-under-lock", f"GrapevineEngine.{n}",
                        f"{key} runs under "
                        f"{sorted(h for hs in helds for h in hs)} — "
                        "stage-1 host work under the engine lock "
                        "serializes the pipeline it exists to overlap"))

    # 3. journal lock-free --------------------------------------------------
    jr = classes.get("BatchJournal")
    if jr is None:
        out.append(LockViolation(
            "missing-code", "BatchJournal",
            "engine/journal.py no longer defines BatchJournal"))
    elif jr.locks:
        out.append(LockViolation(
            "journal-lock", "BatchJournal",
            f"declares lock(s) {sorted(jr.locks)} — the journal runs "
            "under the engine lock by contract; a second lock under "
            "that hold is an ordering hazard"))

    # 4. ordering cycle -----------------------------------------------------
    cyc = _find_cycle(_lock_graph(classes))
    if cyc:
        out.append(LockViolation(
            "lock-cycle", " -> ".join(cyc),
            "lock acquired while holding another along a cycle — "
            "two threads taking the ends concurrently deadlock"))

    # 5. shared attributes --------------------------------------------------
    allow_by_key = {(a.cls, a.attr): a for a in allow}
    used_allows: set = set()
    for cname in ("BatchScheduler", "GrapevineEngine", "HostPipeline"):
        cls = classes.get(cname)
        if cls is None:
            continue
        has_thread = any(m.worker_root for m in cls.methods.values())
        roles = _roles(cls)
        # a method whose every in-class call site holds a lock runs in
        # the caller's critical section — its accesses count as locked
        # (the batcher's journal/dispatch stages). Methods never called
        # in-class (public entry points, callbacks) don't qualify.
        call_sites: dict = {}
        for m in cls.methods.values():
            for key, helds in m.calls.items():
                if isinstance(key, str) and key in cls.methods:
                    call_sites.setdefault(key, []).extend(helds)
        lock_ctx = {
            n for n, sites in call_sites.items()
            if sites and all(sites)
        }
        per_attr: dict = {}
        for n, m in cls.methods.items():
            if n == "__init__":
                continue
            for attr, accesses in m.attrs.items():
                rec = per_attr.setdefault(
                    attr, {"roles_w": set(), "roles_r": set(),
                           "unlocked_w": [], "unlocked_r": []}
                )
                for is_write, held in accesses:
                    (rec["roles_w"] if is_write else rec["roles_r"]).update(
                        roles.get(n, set())
                    )
                    if not held and n not in lock_ctx:
                        rec["unlocked_w" if is_write else "unlocked_r"].append(n)
        for attr, rec in sorted(per_attr.items()):
            if not rec["roles_w"]:
                continue  # never written post-init: immutable publish
            # with an in-class collector thread, a single-role attr is
            # genuinely private to that thread; a pure lock facade
            # (GrapevineEngine) is called from arbitrary threads, so
            # every post-init-written attr is shared by standing
            shared = (
                len(rec["roles_w"] | rec["roles_r"]) > 1
                if has_thread else True
            )
            entry = allow_by_key.get((cname, attr))
            unlocked = rec["unlocked_w"] + (
                [] if entry is not None and entry.reads_only
                else rec["unlocked_r"]
            )
            if entry is not None and not entry.reads_only:
                unlocked = []
            if entry is not None and shared and (
                rec["unlocked_w"] or rec["unlocked_r"]
            ):
                used_allows.add((cname, attr))
            if shared and unlocked:
                sites = ", ".join(sorted(set(unlocked))[:4])
                out.append(LockViolation(
                    "shared-attr", f"{cname}.{attr}",
                    f"written post-init and reachable from multiple "
                    f"threads with unlocked access in [{sites}] — hold "
                    "the lock or add a reviewed LOCK_ALLOW entry with "
                    "the benign-race argument"))

    # 6. LOCK_ALLOW reachability: an entry that suppresses nothing is a
    # rotting permission (the oblint dead-entry rule, host-side)
    for a in allow:
        if a.cls in classes and (a.cls, a.attr) not in used_allows:
            out.append(LockViolation(
                "dead-allow", f"{a.cls}.{a.attr}",
                f"LOCK_ALLOW entry ({a.reason!r}) matches no unlocked "
                "shared access — the race it documented is gone; "
                "delete the entry"))
    return out


def repo_sources(root: str | None = None) -> dict:
    """The host-path files the lint covers, from the live tree."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = {}
    for rel in ("engine/batcher.py", "server/scheduler.py",
                "engine/journal.py", "server/hostpipe.py"):
        with open(os.path.join(root, rel)) as fh:
            out[rel] = fh.read()
    return out


def lint_repo(root: str | None = None) -> list:
    return lint_sources(repo_sources(root))
