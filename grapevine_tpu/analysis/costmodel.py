"""Static round-cost model: two independent derivations of the compiled
round's resource footprint, required to agree bit-exactly.

The CI censuses (tools/check_tree_cache_oblivious.py) already derive the
round's HBM row traffic from the traced jaxpr — and then throw it away.
This module keeps it: the same numbers become a :class:`CostLedger` —
per-phase HBM bytes (gather/scatter rows × row bytes), cipher rows, sort
key-volume, scatter elements, and the flush-amortized steady-state round
— computed TWICE, from two sources that share no code path:

1. **Analytic** (:func:`oram_round_rows` / :func:`oram_flush_rows` /
   :func:`engine_round_rows` / :func:`expiry_sweep_rows`): a pure
   function of geometry × knobs (``vphases/sort/posmap/cache-k/
   evict_every``), written from the round's documented schedule — fetch
   moves ``B·(path_len−k)`` bucket rows per HBM plane, cache planes move
   ``B·k``, the recursive leaf plane re-gathers the nonce plane, E=1
   write-back mirrors the fetch, a flush scatters exactly
   ``flush_target_slots`` rows with zero gathers, and the expiry sweep
   streams every tree plane through its chunked scan exactly once.
2. **Traced** (:func:`traced_access_rows` / :func:`traced_scan_rows`):
   an interpreter over the shared :mod:`.jaxpr_walk` equation stream —
   the identical accounting the obliviousness censuses gate on.

:func:`cross_validate_round` (and friends) require the two to agree
**bit-exactly per operand shape class**. Shape classes, not plane names:
``tree_idx`` and ``tree_leaf`` share the ``[n, Z]`` operand shape, and a
recursive position map's internal cache planes share the outer cache
planes' shapes, so name-level attribution double-counts where the
censuses only bound per-op rows — aggregating both derivations over
``(shape, divisor)`` classes makes the comparison exact by construction.

Seeded undercount mutants (:func:`run_cost_mutants`, reported through
the shared :func:`.mutants.control_failures` runner) corrupt the
analytic side one defect at a time — a dropped plane, a halved fetch,
a forgotten second nonce gather, a missed mailbox double-round — and
every one must trip :class:`CostModelMismatch`, proving the checker is
alive (the ISSUE-12/14 positive-control discipline).

Consumers: obs/costmon.py exports the ledger as ``grapevine_cost_*``
gauges plus the roofline-residual pairing against the tracer's device
spans; bench.py grades each A/B config's measured winner against
:func:`ab_verdict`; tools/check_cost_model.py is the tier-1 gate and
the trajectory grader; tools/tpu_capture.py ``cost_calibrate`` fits the
achieved-bandwidth constants on a real chip.
"""

from __future__ import annotations

import dataclasses

from .jaxpr_walk import plane_rows, walk_eqns

#: u32 word size — every HBM plane in the engine is u32-lane
WORD_BYTES = 4

#: phase labels the ledger (and the grapevine_cost_* gauges) aggregate
#: over — public schedule structure, never data
COST_PHASES = ("fetch", "writeback", "flush", "sweep")


class CostModelMismatch(AssertionError):
    """The analytic model and the traced census disagree.

    ``kind`` is the defect class (``gather-undercount`` /
    ``scatter-undercount`` / ``gather-overcount`` /
    ``scatter-overcount`` / ``arithmetic``) — the mutant controls match
    on it, exactly like the oblint/rangelint finding kinds."""

    def __init__(self, msg: str, kind: str):
        super().__init__(msg)
        self.kind = kind


@dataclasses.dataclass(frozen=True)
class PlaneRows:
    """One plane's predicted traffic for one traced program.

    ``hbm`` marks planes resident in device HBM (tree/nonce planes);
    the dense ``cache_*`` planes are private working state (the
    stash's standing) — their rows participate in the bit-exact
    cross-validation but are excluded from the ledger's HBM bytes."""

    shape: tuple  # operand shape the trace attributes on
    divisor: int  # flat slot planes report slots/divisor (jaxpr_walk)
    row_words: int  # u32 words per accounted row
    gather_rows: int
    scatter_rows: int
    hbm: bool = True

    def scaled(self, g_mult: int, s_mult: int | None = None) -> "PlaneRows":
        s_mult = g_mult if s_mult is None else s_mult
        return dataclasses.replace(
            self,
            gather_rows=self.gather_rows * g_mult,
            scatter_rows=self.scatter_rows * s_mult,
        )


# -- analytic derivation: rows as a pure function of geometry × knobs ---


def oram_planes(cfg, prefix: str = "") -> dict:
    """Every HBM plane one ``oram_round``/``oram_flush`` at geometry
    ``cfg`` can touch, in the shared ``plane_rows`` declaration format
    (name -> (shape, divisor)) — the tree-cache census's declarations
    plus the nonce plane's recursive alias and the internal posmap
    tree's planes (prefixed ``pm_``)."""
    z, v = cfg.bucket_slots, cfg.value_words
    n = cfg.n_buckets_padded
    cb = cfg.cache_buckets
    planes = {
        f"{prefix}tree_idx": ((n, z), 1),
        f"{prefix}tree_val": ((n, z * v), 1),
        f"{prefix}nonces": ((n, 2), 1),
    }
    if cfg.posmap is not None:
        planes[f"{prefix}tree_leaf"] = ((n, z), 1)
    if cb:
        planes[f"{prefix}cache_idx"] = ((cb * z,), z)
        planes[f"{prefix}cache_val"] = ((cb, z * v), 1)
        if cfg.posmap is not None:
            planes[f"{prefix}cache_leaf"] = ((cb * z,), z)
    if cfg.posmap is not None:
        from ..oram.posmap import inner_oram_config

        planes.update(oram_planes(inner_oram_config(cfg.posmap),
                                  prefix=f"{prefix}pm_"))
    return planes


def oram_round_rows(cfg, b: int, prefix: str = "") -> dict:
    """Predicted rows per plane for ONE ``oram_round(cfg, ·)`` with a
    batch of ``b`` indices — the E=1 fetch+write-back round, or the
    delayed-eviction fetch-only round when ``cfg.delayed_eviction``.

    The schedule being priced (oram/round.py):

    - fetch gathers ``R = b·(path_len−k)`` bucket rows per bottom HBM
      plane (idx, val, nonces; + the leaf plane under a recursive map,
      which re-gathers the nonce plane for its own keystream — the
      second nonce gather);
    - the tree-top cache serves the top ``k`` levels: ``C = b·k`` rows
      per cache plane;
    - E=1 write-back scatters the same row counts back (nonces only
      when the at-rest cipher is on — plaintext trees commit no epoch);
    - E>1 rounds are HBM-read-only: zero tree/cache scatters
      (the check_evict_round_accounting claim);
    - a recursive position map resolves the batch through exactly one
      internal round of the same ``b`` (oram/posmap.py), composed here
      under the ``pm_`` prefix.
    """
    z, v = cfg.bucket_slots, cfg.value_words
    n = cfg.n_buckets_padded
    k = cfg.top_cache_levels
    cb = cfg.cache_buckets
    recursive = cfg.posmap is not None
    wb = 0 if cfg.delayed_eviction else 1  # write-back present?
    R = b * (cfg.path_len - k)
    C = b * k

    rows = {
        f"{prefix}tree_idx": PlaneRows((n, z), 1, z, R, wb * R),
        f"{prefix}tree_val": PlaneRows((n, z * v), 1, z * v, R, wb * R),
        # the fetch always gathers the nonce plane (the keystream input
        # precedes the encrypted? branch); the epoch commit scatter only
        # exists under the cipher. Recursive leaf decrypt re-gathers it.
        f"{prefix}nonces": PlaneRows(
            (n, 2), 1, 2, R * (2 if recursive else 1),
            wb * R if cfg.encrypted else 0,
        ),
    }
    if recursive:
        rows[f"{prefix}tree_leaf"] = PlaneRows((n, z), 1, z, R, wb * R)
    if cb:
        rows[f"{prefix}cache_idx"] = PlaneRows(
            (cb * z,), z, z, C, wb * C, hbm=False
        )
        rows[f"{prefix}cache_val"] = PlaneRows(
            (cb, z * v), 1, z * v, C, wb * C, hbm=False
        )
        if recursive:
            rows[f"{prefix}cache_leaf"] = PlaneRows(
                (cb * z,), z, z, C, wb * C, hbm=False
            )
    if recursive:
        from ..oram.posmap import inner_oram_config

        rows.update(oram_round_rows(
            inner_oram_config(cfg.posmap), b, prefix=f"{prefix}pm_"
        ))
    return rows


def flush_target_rows(cfg) -> int:
    """The analytic flush write-target count — MUST equal
    ``round.flush_target_slots`` (cross-checked arithmetically by
    :func:`cross_validate_flush`; the ``min`` is the 1/E amortization
    past tree saturation)."""
    return min(cfg.evict_window * cfg.evict_fetch_count * cfg.path_len,
               cfg.n_buckets_padded)


def oram_flush_rows(cfg, prefix: str = "") -> dict:
    """Predicted rows per plane for ONE ``oram_flush(cfg, ·)``: every
    plane scatters exactly ``t = flush_target_rows`` rows (the window's
    fetched buckets, deduplicated), zero gathers anywhere — the window's
    live rows were pulled into the private buffer at fetch time. A
    recursive map's internal tree flushes inside the same call."""
    z, v = cfg.bucket_slots, cfg.value_words
    n = cfg.n_buckets_padded
    cb = cfg.cache_buckets
    recursive = cfg.posmap is not None
    t = flush_target_rows(cfg)

    rows = {
        f"{prefix}tree_idx": PlaneRows((n, z), 1, z, 0, t),
        f"{prefix}tree_val": PlaneRows((n, z * v), 1, z * v, 0, t),
        f"{prefix}nonces": PlaneRows(
            (n, 2), 1, 2, 0, t if cfg.encrypted else 0
        ),
    }
    if recursive:
        rows[f"{prefix}tree_leaf"] = PlaneRows((n, z), 1, z, 0, t)
    if cb:
        rows[f"{prefix}cache_idx"] = PlaneRows(
            (cb * z,), z, z, 0, t, hbm=False
        )
        rows[f"{prefix}cache_val"] = PlaneRows(
            (cb, z * v), 1, z * v, 0, t, hbm=False
        )
        if recursive:
            rows[f"{prefix}cache_leaf"] = PlaneRows(
                (cb * z,), z, z, 0, t, hbm=False
            )
    if recursive:
        from ..oram.posmap import inner_oram_config

        rows.update(oram_flush_rows(
            inner_oram_config(cfg.posmap), prefix=f"{prefix}pm_"
        ))
    return rows


def _sharded_plane(name: str) -> bool:
    """True for planes the mesh shards along the bucket axis: the outer
    tree/nonce planes of either engine tree. Inner posmap trees
    (``pm_``) and the tree-top cache planes replicate on every chip
    (parallel/mesh._oram_specs — the ROADMAP item 1/3 composition point
    keeps the internal map whole), so their scatters land in full per
    chip while the outer trees' owner-masked scatters partition."""
    if "pm_" in name:
        return False
    base = (name.split("_", 1)[1]
            if name.startswith(("rec_", "mb_")) else name)
    return base.startswith(("tree_", "nonces"))


def shard_local_rows(rows: dict, shards: int) -> dict:
    """The shard-LOCAL view of an analytic rows dict (ISSUE 18): every
    sharded plane's leading dim divides by the shard count (one
    contiguous heap range per chip), while replicated planes — cache,
    inner posmap trees — keep their full shape. Row COUNTS are
    untouched: each chip's fetch gathers the full uniform
    ``B·(path_len−k)`` masked rows from its local range, and each
    chip's flush dispatches the full uniform ``t``-row drop-mode
    scatter — the owner mask bounds which rows LAND, never the static
    per-chip op shape (the leak argument in oram/round.py)."""
    if shards < 1 or shards & (shards - 1):
        raise ValueError(f"shards={shards}: want a power of two >= 1")
    out = {}
    for name, pr in rows.items():
        if pr.hbm and _sharded_plane(name):
            n = pr.shape[0]
            if n % shards:
                raise ValueError(
                    f"{name}: {n} rows do not divide over {shards} "
                    "shards — the bucket axis shards as contiguous "
                    "equal heap ranges"
                )
            pr = dataclasses.replace(
                pr, shape=(n // shards,) + tuple(pr.shape[1:])
            )
        out[name] = pr
    return out


def engine_planes(ecfg) -> dict:
    """Both trees' plane declarations for one engine round/flush."""
    return {**oram_planes(ecfg.rec, "rec_"),
            **oram_planes(ecfg.mb, "mb_")}


def engine_round_rows(ecfg) -> dict:
    """One engine round = mailbox round A (``B·D`` fetches) + records
    round B (``B``) + mailbox round C (``B·D``) — the round_step.py
    composition, so the mailbox tree's per-round traffic is exactly
    twice its per-``oram_round`` traffic."""
    b, d = ecfg.batch_size, ecfg.mb_choices
    rows = {
        name: pr.scaled(1)
        for name, pr in oram_round_rows(ecfg.rec, b, "rec_").items()
    }
    for name, pr in oram_round_rows(ecfg.mb, b * d, "mb_").items():
        rows[name] = pr.scaled(2)
    return rows


def engine_flush_rows(ecfg) -> dict:
    """One ``engine_flush_step`` = records flush + mailbox flush (runs
    every ``evict_every`` engine rounds: the records window is E rounds
    of one fetch each; the mailbox window is 2E rounds, filled at two
    per engine round — both drain on the same cadence)."""
    return {**oram_flush_rows(ecfg.rec, "rec_"),
            **oram_flush_rows(ecfg.mb, "mb_")}


# -- analytic derivation: the expiry sweep's chunked full-tree pass -----


def sweep_chunk_planes(cfg, prefix: str = "") -> dict:
    """The chunk shapes one tree's expiry sweep streams through its
    ``lax.scan`` (engine/expiry.py ``_chunked_tree_sweep``): plane name
    -> (chunk shape, rows per full pass). The scan consumes each plane
    reshaped to ``[n_chunks, rows_per_chunk, ·]`` — whole-plane
    passes, not gathers, so the traced check reduces scan operands
    (:func:`traced_scan_rows`) instead of access primitives."""
    from ..engine.expiry import _chunk_rows

    z, v = cfg.bucket_slots, cfg.value_words
    n = cfg.n_buckets_padded
    rpc = _chunk_rows(cfg)
    nch = n // rpc
    planes = {
        f"{prefix}tree_idx": ((nch, rpc, z), n),
        f"{prefix}tree_val": ((nch, rpc, z * v), n),
        f"{prefix}nonces": ((nch, rpc, 2), n),
    }
    if cfg.posmap is not None and cfg.encrypted:
        planes[f"{prefix}tree_leaf"] = ((nch, rpc, z), n)
    return planes


def expiry_sweep_rows(ecfg) -> dict:
    """Predicted full-pass rows per tree plane for one expiry sweep:
    every chunked plane is read once and the idx/val (and recursive
    leaf) planes are written once — ``n_buckets_padded`` rows each.
    The nonce plane is re-keyed by a broadcast store outside the scan
    (counted in the ledger's sweep bytes, not in the scan check)."""
    out = {}
    for prefix, cfg in (("rec_", ecfg.rec), ("mb_", ecfg.mb)):
        n = cfg.n_buckets_padded
        z, v = cfg.bucket_slots, cfg.value_words
        out[f"{prefix}tree_idx"] = PlaneRows((n, z), 1, z, n, n)
        out[f"{prefix}tree_val"] = PlaneRows((n, z * v), 1, z * v, n, n)
        out[f"{prefix}nonces"] = PlaneRows((n, 2), 1, 2, n, n)
        if cfg.posmap is not None and cfg.encrypted:
            out[f"{prefix}tree_leaf"] = PlaneRows((n, z), 1, z, n, n)
    return out


# -- traced derivation: the jaxpr_walk interpreter ----------------------


def _shape_classes(planes: dict) -> dict:
    """Collapse plane declarations to unique (shape, divisor) classes —
    the granularity at which trace attribution is exact (tree_idx and
    tree_leaf share ``[n, Z]``; an internal posmap's cache planes share
    the outer cache shapes)."""
    uniq = {}
    for _, (shape, div) in planes.items():
        uniq[(tuple(shape), int(div))] = (tuple(shape), int(div))
    return {f"{s}/{d}": (s, d) for (s, d) in uniq.values()}


def traced_access_rows(jaxpr, planes: dict) -> dict:
    """Derivation #2: total gather/scatter rows per shape class from the
    traced program, via the shared census accounting
    (:func:`.jaxpr_walk.plane_rows`). Returns
    ``{(shape, divisor): (gather_rows, scatter_rows)}``."""
    classes = _shape_classes(planes)
    moved = plane_rows(jaxpr, classes)
    out = {}
    for cname, (shape, div) in classes.items():
        g = sum(r for op, r in moved[cname] if op == "gather")
        s = sum(r for op, r in moved[cname] if op != "gather")
        out[(shape, div)] = (g, s)
    return out


def predicted_access_rows(rows: dict) -> dict:
    """The analytic side of the same aggregation: per shape class,
    summed over the planes that share it."""
    out: dict = {}
    for _, pr in rows.items():
        key = (tuple(pr.shape), int(pr.divisor))
        g, s = out.get(key, (0, 0))
        out[key] = (g + pr.gather_rows, s + pr.scatter_rows)
    return out


def traced_scan_rows(jaxpr, chunk_planes: dict) -> dict:
    """Sweep derivation #2: rows streamed per chunk-shape class through
    ``lax.scan`` equations — a scan operand (read) or output (write)
    whose aval matches a declared chunk shape accounts one full pass of
    that many rows. Returns ``{chunk_shape: (read_rows, write_rows)}``."""
    classes = {}
    for _, (chunk_shape, pass_rows) in chunk_planes.items():
        classes[tuple(chunk_shape)] = int(pass_rows)
    out = {shape: [0, 0] for shape in classes}
    for eqn in walk_eqns(jaxpr):
        if eqn.primitive.name != "scan":
            continue
        for var in eqn.invars:
            shape = tuple(getattr(var.aval, "shape", ()))
            if shape in classes:
                out[shape][0] += classes[shape]
        for var in eqn.outvars:
            shape = tuple(getattr(var.aval, "shape", ()))
            if shape in classes:
                out[shape][1] += classes[shape]
    return {shape: (g, s) for shape, (g, s) in out.items()}


# -- trace builders (trace-only; no compile, the census discipline) -----


def _apply_noop(vals0, present0):
    import jax.numpy as jnp

    return jnp.sum(vals0, axis=1), vals0, present0


def trace_oram_round(cfg, b: int):
    """Jaxpr of one ``oram_round`` with concrete arange indices (the
    tree-cache census's tracing recipe — index choice cannot matter, by
    that census's own index-independence claim)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..oram.path_oram import init_oram
    from ..oram.round import oram_round

    state = jax.eval_shape(lambda: init_oram(cfg, jax.random.PRNGKey(0)))
    cidxs = jnp.asarray((np.arange(b) % cfg.blocks).astype(np.uint32))
    recursive = cfg.posmap is not None
    lf = jax.ShapeDtypeStruct((b,), jnp.uint32)

    def run(st, nl, dl, pm_nl, pm_dl):
        return oram_round(
            cfg, st, cidxs, nl, dl, _apply_noop,
            pm_new_leaves=pm_nl if recursive else None,
            pm_dummy_leaves=pm_dl if recursive else None,
        )

    return jax.make_jaxpr(run)(state, lf, lf, lf, lf)


def trace_oram_flush(cfg):
    import jax

    from ..oram.path_oram import init_oram
    from ..oram.round import oram_flush

    state = jax.eval_shape(lambda: init_oram(cfg, jax.random.PRNGKey(0)))
    return jax.make_jaxpr(lambda st: oram_flush(cfg, st))(state)


def trace_sharded_oram_flush(cfg, shards: int):
    """Jaxpr of one owner-masked sharded ``oram_flush`` under
    ``shard_map`` on a ``shards``-device mesh slice — the engine's
    exact sharding geometry (parallel/mesh.py), so ``walk_eqns``
    recurses into the shard body where every sharded plane operand
    carries its SHARD-LOCAL shape (the
    tools/check_tree_cache_oblivious.py sharded-audit recipe)."""
    import jax

    from ..oram.path_oram import init_oram
    from ..oram.round import oram_flush
    from ..parallel.mesh import (
        _SHARD_MAP_NOCHECK,
        TREE_AXIS,
        _oram_specs,
        _shard_map,
        make_mesh,
    )

    devs = jax.devices()
    if len(devs) < shards:
        raise ValueError(
            f"shards={shards} but only {len(devs)} JAX device(s) are "
            "visible — the sharded flush trace needs a real mesh slice"
        )
    mesh = make_mesh(devs[:shards])
    specs = _oram_specs()
    state = jax.eval_shape(lambda: init_oram(cfg, jax.random.PRNGKey(0)))
    fn = _shard_map(
        lambda st: oram_flush(cfg, st, TREE_AXIS),
        mesh=mesh, in_specs=(specs,), out_specs=specs,
        **_SHARD_MAP_NOCHECK,
    )
    return jax.make_jaxpr(fn)(state)


def _engine_batch_spec(ecfg):
    import jax
    import numpy as np

    from ..engine.state import ID_WORDS, KEY_WORDS, PAYLOAD_WORDS

    b = ecfg.batch_size

    def s(*sh):
        return jax.ShapeDtypeStruct(sh, np.uint32)

    return {
        "req_type": s(b), "auth": s(b, KEY_WORDS),
        "msg_id": s(b, ID_WORDS), "recipient": s(b, KEY_WORDS),
        "payload": s(b, PAYLOAD_WORDS), "now": s(), "now_hi": s(),
    }


def trace_engine_round(ecfg):
    import jax

    from ..engine.round_step import engine_round_step
    from ..engine.state import init_engine

    state = jax.eval_shape(lambda: init_engine(ecfg, 0))
    return jax.make_jaxpr(
        lambda st, ba: engine_round_step(ecfg, st, ba)
    )(state, _engine_batch_spec(ecfg))


def trace_engine_flush(ecfg):
    import jax

    from ..engine.round_step import engine_flush_step
    from ..engine.state import init_engine

    state = jax.eval_shape(lambda: init_engine(ecfg, 0))
    return jax.make_jaxpr(
        lambda st: engine_flush_step(ecfg, st)
    )(state)


def trace_expiry_sweep(ecfg):
    import jax
    import numpy as np

    from ..engine.expiry import expiry_sweep
    from ..engine.state import init_engine

    state = jax.eval_shape(lambda: init_engine(ecfg, 0))
    scalar = jax.ShapeDtypeStruct((), np.uint32)
    return jax.make_jaxpr(
        lambda st, now, per, nh: expiry_sweep(ecfg, st, now, per, nh)
    )(state, scalar, scalar, scalar)


# -- cross-validation: the two derivations must agree bit-exactly -------


def _compare(predicted: dict, traced: dict, context: str) -> dict:
    """Exact per-shape-class comparison; raises CostModelMismatch with
    the dominant defect class. Returns the agreed totals."""
    diffs = []
    kind = None
    for key in sorted(set(predicted) | set(traced), key=repr):
        pg, ps = predicted.get(key, (0, 0))
        tg, ts = traced.get(key, (0, 0))
        if (pg, ps) == (tg, ts):
            continue
        if pg < tg:
            kind = kind or "gather-undercount"
        elif pg > tg:
            kind = kind or "gather-overcount"
        elif ps < ts:
            kind = kind or "scatter-undercount"
        else:
            kind = kind or "scatter-overcount"
        diffs.append(
            f"  shape {key}: model (g={pg}, s={ps}) != trace "
            f"(g={tg}, s={ts})"
        )
    if diffs:
        raise CostModelMismatch(
            f"{context}: the analytic cost model and the traced census "
            f"disagree on HBM rows:\n" + "\n".join(diffs),
            kind=kind,
        )
    return predicted


def cross_validate_round(cfg, b: int, *, _corrupt=None) -> dict:
    """One ``oram_round`` at geometry ``cfg``: analytic rows == traced
    rows, per shape class, bit-exactly. ``_corrupt`` is the mutant hook
    (a transform on the predicted rows dict) — production callers never
    pass it."""
    pred = oram_round_rows(cfg, b)
    if _corrupt is not None:
        pred = _corrupt(pred)
    return _compare(
        predicted_access_rows(pred),
        traced_access_rows(trace_oram_round(cfg, b), oram_planes(cfg)),
        f"oram_round(b={b}, plen={cfg.path_len}, k={cfg.top_cache_levels},"
        f" E={cfg.evict_window}, recursive={cfg.posmap is not None})",
    )


def cross_validate_flush(cfg, *, _corrupt=None) -> dict:
    """One ``oram_flush``: analytic == traced, plus the arithmetic
    identity of :func:`flush_target_rows` against the shipped
    ``round.flush_target_slots`` (two derivations of the dedup bound —
    a model that drops the saturation ``min`` fails here even at
    unsaturated audit geometry)."""
    from ..oram.round import flush_target_slots

    t_model = flush_target_rows(cfg)
    if _corrupt is None and t_model != flush_target_slots(cfg):
        raise CostModelMismatch(
            f"flush_target_rows={t_model} != shipped flush_target_slots="
            f"{flush_target_slots(cfg)}", kind="arithmetic",
        )
    pred = oram_flush_rows(cfg)
    if _corrupt is not None:
        pred = _corrupt(pred)
    return _compare(
        predicted_access_rows(pred),
        traced_access_rows(trace_oram_flush(cfg), oram_planes(cfg)),
        f"oram_flush(E={cfg.evict_window}, F={cfg.evict_fetch_count}, "
        f"t={t_model}, recursive={cfg.posmap is not None})",
    )


def cross_validate_sharded_flush(cfg, shards: int, *,
                                 _corrupt=None) -> dict:
    """One owner-masked SHARDED ``oram_flush`` (ISSUE 18): the analytic
    shard-local rows — full-shape ``t``-row scatters against
    shard-local plane shapes, replicated inner-posmap planes untouched
    — must agree bit-exactly with the shard_map-traced census. A model
    that prices each chip's scatter at its owned share (``t/shards``)
    fails here as a scatter-undercount: the owner mask bounds which
    rows land, not the uniform static per-chip op shape."""
    t = flush_target_rows(cfg)
    n_local = cfg.n_buckets_padded // shards
    # audit-geometry ambiguity guard (the tree-cache census's caveat):
    # the flush compacts private buffers into exactly t-row arrays, so
    # t (and the buffer slot count) must not collide with any local
    # plane's leading dim or shape-class attribution goes ambiguous
    if t == n_local or cfg.evict_buffer_slots == n_local:
        raise ValueError(
            f"sharded-flush audit geometry ambiguity: t={t}, "
            f"buffer={cfg.evict_buffer_slots} vs n_local={n_local} — "
            "pick a window/fetch count whose dedup bound differs from "
            "the shard-local bucket count"
        )
    pred = shard_local_rows(oram_flush_rows(cfg), shards)
    if _corrupt is not None:
        pred = _corrupt(pred)
    planes = {name: (pr.shape, pr.divisor)
              for name, pr in shard_local_rows(
                  oram_flush_rows(cfg), shards).items()}
    return _compare(
        predicted_access_rows(pred),
        traced_access_rows(trace_sharded_oram_flush(cfg, shards), planes),
        f"sharded_oram_flush(shards={shards}, E={cfg.evict_window}, "
        f"F={cfg.evict_fetch_count}, t={t}, n_local={n_local}, "
        f"recursive={cfg.posmap is not None})",
    )


def cross_validate_engine_round(ecfg, *, _corrupt=None) -> dict:
    """One full engine round (rounds A+B+C): the composed analytic model
    — mailbox twice at ``B·D``, records once at ``B`` — against the
    traced ``engine_round_step`` census."""
    pred = engine_round_rows(ecfg)
    if _corrupt is not None:
        pred = _corrupt(pred)
    return _compare(
        predicted_access_rows(pred),
        traced_access_rows(trace_engine_round(ecfg), engine_planes(ecfg)),
        f"engine_round(B={ecfg.batch_size}, D={ecfg.mb_choices}, "
        f"E={ecfg.evict_every})",
    )


def cross_validate_engine_flush(ecfg, *, _corrupt=None) -> dict:
    pred = engine_flush_rows(ecfg)
    if _corrupt is not None:
        pred = _corrupt(pred)
    return _compare(
        predicted_access_rows(pred),
        traced_access_rows(trace_engine_flush(ecfg), engine_planes(ecfg)),
        f"engine_flush(E={ecfg.evict_every})",
    )


def cross_validate_sweep(ecfg, *, _corrupt=None) -> dict:
    """The expiry sweep: per chunk-shape class, the scan-streamed rows
    equal one full pass over each tree plane (reads) and one write pass
    over the idx/val/leaf planes (the nonce re-key is a broadcast store
    outside the scan — priced in the ledger, not checkable here)."""
    chunk = {**sweep_chunk_planes(ecfg.rec, "rec_"),
             **sweep_chunk_planes(ecfg.mb, "mb_")}
    pred_rows = expiry_sweep_rows(ecfg)
    if _corrupt is not None:
        pred_rows = _corrupt(pred_rows)
    # analytic side in chunk-shape space: reads for every chunked plane,
    # writes for the planes the scan carries back out (all but nonces)
    predicted: dict = {}
    for name, (chunk_shape, _) in chunk.items():
        pr = pred_rows.get(name)
        if pr is None:
            continue
        g, s = predicted.get(tuple(chunk_shape), (0, 0))
        writes = 0 if name.endswith("nonces") else pr.scatter_rows
        predicted[tuple(chunk_shape)] = (g + pr.gather_rows, s + writes)
    return _compare(
        predicted,
        traced_scan_rows(trace_expiry_sweep(ecfg), chunk),
        "expiry_sweep",
    )


# -- the ledger: bytes, cipher rows, sort volume, steady state ----------


@dataclasses.dataclass
class PhaseCost:
    """One phase's modeled resource footprint (all integers: counts)."""

    gather_rows: int = 0
    scatter_rows: int = 0
    gather_bytes: int = 0
    scatter_bytes: int = 0
    cipher_rows: int = 0  # rows through the bucket-cipher keystream
    sort_keys: int = 0  # keys entering sort/rank machinery
    scatter_elems: int = 0  # scattered u32 elements
    #: the subset of scatter_bytes landing in mesh-SHARDED planes
    #: (outer tree/nonce planes): under a sharded engine these
    #: partition by the owner mask, while the remainder (replicated
    #: inner-posmap trees) lands in full on every chip
    sharded_scatter_bytes: int = 0

    @property
    def hbm_bytes(self) -> int:
        return self.gather_bytes + self.scatter_bytes

    def per_chip_bytes(self, shards: int) -> float:
        """HBM bytes ONE chip of a ``shards``-way mesh moves for this
        phase: gathers keep their full uniform per-chip count (each
        chip reads the whole masked working set from its local range —
        the leak argument), owner-masked scatters partition (modeled
        uniform; the aggregate across chips is exactly the single-chip
        write set — shard counts are powers of two, so the binary
        division is exact), replicated-plane scatters land in full."""
        repl = self.scatter_bytes - self.sharded_scatter_bytes
        return (self.gather_bytes + repl
                + self.sharded_scatter_bytes / shards)

    def add_rows(self, rows: dict) -> "PhaseCost":
        """Accumulate the HBM-resident planes (private ``cache_*``
        planes carry no HBM traffic — they exist for the bit-exact
        row cross-validation, not the byte ledger)."""
        for name, pr in rows.items():
            if not pr.hbm:
                continue
            self.gather_rows += pr.gather_rows
            self.scatter_rows += pr.scatter_rows
            self.gather_bytes += pr.gather_rows * pr.row_words * WORD_BYTES
            self.scatter_bytes += (
                pr.scatter_rows * pr.row_words * WORD_BYTES
            )
            if _sharded_plane(name):
                self.sharded_scatter_bytes += (
                    pr.scatter_rows * pr.row_words * WORD_BYTES
                )
            self.scatter_elems += pr.scatter_rows * pr.row_words
        return self


@dataclasses.dataclass
class CostLedger:
    """Per-phase modeled costs for one engine geometry × knob setting,
    plus the flush-amortized steady-state round aggregate."""

    phases: dict  # phase name -> PhaseCost
    evict_every: int
    #: bucket-tree shard count the per-chip views divide over (ISSUE
    #: 18); 1 = single chip. Power of two, like the mesh it models.
    shards: int = 1

    @property
    def steady_round_bytes(self) -> float:
        """HBM bytes per steady-state engine round: fetch + write-back
        (E=1) + flush/E (E>1). The sweep is operator-cadenced and
        excluded — it has its own phase entry."""
        total = (self.phases["fetch"].hbm_bytes
                 + self.phases["writeback"].hbm_bytes)
        return total + self.phases["flush"].hbm_bytes / max(
            1, self.evict_every
        )

    @property
    def steady_round_cipher_rows(self) -> float:
        total = (self.phases["fetch"].cipher_rows
                 + self.phases["writeback"].cipher_rows)
        return total + self.phases["flush"].cipher_rows / max(
            1, self.evict_every
        )

    @property
    def steady_round_sort_keys(self) -> float:
        total = (self.phases["fetch"].sort_keys
                 + self.phases["writeback"].sort_keys)
        return total + self.phases["flush"].sort_keys / max(
            1, self.evict_every
        )

    @property
    def per_shard_steady_round_bytes(self) -> float:
        """HBM bytes ONE chip of the ``shards``-way mesh moves per
        steady-state round (ISSUE 18): gathers keep the full uniform
        per-chip row count (each chip reads the whole masked path
        working set from its local heap range — the leak argument),
        owner-masked scatters into the sharded outer trees partition
        (sum across chips = exactly the single-chip write set; the
        power-of-two division is exact in binary), and replicated-
        plane scatters (inner posmap trees) land in full per chip.
        ``shards=1`` reduces to :attr:`steady_round_bytes` exactly."""
        total = (self.phases["fetch"].per_chip_bytes(self.shards)
                 + self.phases["writeback"].per_chip_bytes(self.shards))
        return total + self.phases["flush"].per_chip_bytes(
            self.shards
        ) / max(1, self.evict_every)

    def floor_ms(self, gbytes_per_s: float) -> float:
        """Roofline round-time floor at a calibrated achieved
        bandwidth: modeled per-chip steady-state bytes / bandwidth
        (per-chip == total on a single chip)."""
        return self.per_shard_steady_round_bytes / (gbytes_per_s * 1e6)


def _round_sort_keys(cfg, b: int, sort_impl: str, occ_impl: str) -> int:
    """Sort key-volume of one oram_round: the eviction leaf argsort over
    the working set (E=1 only — fetch rounds recompact with rank_of,
    sort-free) plus the dedup group sorts under the scan occurrence
    machinery, composed recursively for the internal map round."""
    z = cfg.bucket_slots
    plen = cfg.path_len
    keys = 0
    if not cfg.delayed_eviction:
        w = cfg.stash_size + b * plen * z + b  # E=1 working set
        keys += w
    if occ_impl == "scan":
        keys += b  # occurrence group sort
    if cfg.posmap is not None:
        from ..oram.posmap import inner_oram_config

        if occ_impl == "scan":
            keys += b  # recursive group-last-slot sort
        keys += _round_sort_keys(
            inner_oram_config(cfg.posmap), b, sort_impl, occ_impl
        )
    return keys


def _flush_sort_keys(cfg) -> int:
    """One flush: the public window dedup sort plus the eviction
    argsort over buffer ∪ stash (recursing into the internal map)."""
    keys = (cfg.evict_window * cfg.evict_fetch_count * cfg.path_len
            + cfg.evict_buffer_slots + cfg.stash_size)
    if cfg.posmap is not None:
        from ..oram.posmap import inner_oram_config

        keys += _flush_sort_keys(inner_oram_config(cfg.posmap))
    return keys


def _round_cipher_rows(cfg, b: int) -> int:
    """Keystream rows of one oram_round: decrypt the fetched bottom
    rows (+ the recursive leaf plane's separate stream), and under E=1
    encrypt the same counts back."""
    if not cfg.encrypted:
        inner = 0
    else:
        R = b * (cfg.path_len - cfg.top_cache_levels)
        streams = 2 if cfg.posmap is not None else 1  # idx/val + leaf
        passes = 1 if cfg.delayed_eviction else 2  # fetch (+ write-back)
        inner = R * streams * passes
    if cfg.posmap is not None:
        from ..oram.posmap import inner_oram_config

        inner += _round_cipher_rows(inner_oram_config(cfg.posmap), b)
    return inner


def _flush_cipher_rows(cfg) -> int:
    if not cfg.encrypted:
        inner = 0
    else:
        streams = 2 if cfg.posmap is not None else 1
        inner = flush_target_rows(cfg) * streams
    if cfg.posmap is not None:
        from ..oram.posmap import inner_oram_config

        inner += _flush_cipher_rows(inner_oram_config(cfg.posmap))
    return inner


def engine_cost_ledger(ecfg, occ_impl: str | None = None,
                       shards: int = 1) -> CostLedger:
    """The full modeled ledger for one engine geometry × knob setting —
    the object obs/costmon.py exports and bench.py grades. ``shards``
    is the bucket-tree mesh width (GrapevineConfig.shards — engine
    geometry that deliberately lives OUTSIDE EngineConfig, so it is a
    parameter here, not a field read off ``ecfg``)."""
    if shards < 1 or shards & (shards - 1):
        raise ValueError(f"shards={shards}: want a power of two >= 1")
    occ = occ_impl if occ_impl is not None else (
        "scan" if ecfg.vphases_impl == "scan" else "dense"
    )
    b, d = ecfg.batch_size, ecfg.mb_choices
    round_rows = engine_round_rows(ecfg)
    fetch = PhaseCost().add_rows({
        n: dataclasses.replace(pr, scatter_rows=0)
        for n, pr in round_rows.items()
    })
    wb = PhaseCost().add_rows({
        n: dataclasses.replace(pr, gather_rows=0)
        for n, pr in round_rows.items()
    })
    flush = PhaseCost()
    if ecfg.evict_every > 1:
        flush.add_rows(engine_flush_rows(ecfg))
        flush.sort_keys = (_flush_sort_keys(ecfg.rec)
                           + _flush_sort_keys(ecfg.mb))
        flush.cipher_rows = (_flush_cipher_rows(ecfg.rec)
                             + _flush_cipher_rows(ecfg.mb))
    sweep = PhaseCost().add_rows(expiry_sweep_rows(ecfg))
    # the sweep's nonce re-key is a broadcast store over each tree's
    # whole nonce plane (outside the chunk scan)
    for cfg in (ecfg.rec, ecfg.mb):
        if cfg.encrypted:
            n = cfg.n_buckets_padded
            sweep.scatter_rows += n
            sweep.scatter_bytes += n * 2 * WORD_BYTES
            sweep.scatter_elems += n * 2
            sweep.cipher_rows += 2 * n * (
                2 if cfg.posmap is not None else 1
            )
    # round-phase cipher/sort volumes: records once, mailbox twice
    dec_total = (_round_cipher_rows(ecfg.rec, b)
                 + 2 * _round_cipher_rows(ecfg.mb, b * d))
    sort_total = (
        _round_sort_keys(ecfg.rec, b, ecfg.sort_impl, occ)
        + 2 * _round_sort_keys(ecfg.mb, b * d, ecfg.sort_impl, occ)
    )
    if ecfg.evict_every > 1:
        fetch.cipher_rows = dec_total
        fetch.sort_keys = sort_total
    else:
        # E=1: the fetch/write-back split of the joint round program is
        # half decrypt, half re-encrypt; the eviction sort rides the
        # write-back half
        fetch.cipher_rows = dec_total // 2
        wb.cipher_rows = dec_total - dec_total // 2
        wb.sort_keys = sort_total
    return CostLedger(
        phases={"fetch": fetch, "writeback": wb, "flush": flush,
                "sweep": sweep},
        evict_every=ecfg.evict_every,
        shards=shards,
    )


# -- knob A/B verdicts (the model-graded decisions) ---------------------


def machinery_oram_cfg(cap_n: int, b: int, *, k: int = 0, e: int = 1):
    """The records-shaped single-ORAM geometry the bench machinery
    A/Bs time (bench.py tree_cache_ab/evict_ab: density-2 payload
    shape, 64-word values, cipher on) — mirrored here so the model
    prices exactly the banked configuration."""
    from ..oram.path_oram import OramConfig, derive_evict_buffer_slots

    height = max(1, cap_n.bit_length() - 2)
    return OramConfig(
        height=height, value_words=64, n_blocks=cap_n,
        cipher_rounds=8, stash_size=max(96, b // 2 + 96),
        top_cache_levels=min(k, height),
        evict_window=e,
        evict_fetch_count=b if e > 1 else 0,
        evict_buffer_slots=(
            derive_evict_buffer_slots(cap_n, e, b, 4) if e > 1 else 0
        ),
    )


def sweep_engine_ecfg(batch: int, *, cap_log2: int = 12,
                      recipients_log2: int = 9, mailbox_cap: int = 8,
                      **knobs):
    """The engine geometry the bench whole-round sweeps time."""
    from ..config import GrapevineConfig
    from ..engine.state import EngineConfig

    return EngineConfig.from_config(GrapevineConfig(
        max_messages=1 << cap_log2,
        max_recipients=1 << recipients_log2,
        batch_size=batch, mailbox_cap=mailbox_cap,
        stash_size=max(128, batch // 2 + 96), tree_density=2, **knobs,
    ))


def oram_steady_bytes(cfg, b: int) -> float:
    """Amortized HBM bytes per round of one isolated ORAM: the round's
    gather (+ E=1 write-back) bytes plus flush bytes / E."""
    total = PhaseCost().add_rows(oram_round_rows(cfg, b)).hbm_bytes
    if cfg.delayed_eviction:
        total += (PhaseCost().add_rows(oram_flush_rows(cfg)).hbm_bytes
                  / cfg.evict_window)
    return float(total)


def oram_sharded_steady_bytes(cfg, b: int, shards: int) -> float:
    """Per-CHIP amortized HBM bytes per round of one isolated ORAM on a
    ``shards``-way mesh (ISSUE 18): gather bytes stay at the full
    uniform per-chip count, owner-masked scatter bytes into the sharded
    tree planes divide (the uniform-partition idealization — the true
    per-chip split is path-dependent over the contiguous heap ranges,
    but the aggregate is exactly the single-chip write set), and
    replicated inner-posmap scatters land in full. ``shards=1`` equals
    :func:`oram_steady_bytes` exactly."""
    if shards < 1 or shards & (shards - 1):
        raise ValueError(f"shards={shards}: want a power of two >= 1")
    pc = PhaseCost().add_rows(oram_round_rows(cfg, b))
    total = pc.per_chip_bytes(shards)
    if cfg.delayed_eviction:
        fl = PhaseCost().add_rows(oram_flush_rows(cfg))
        total += fl.per_chip_bytes(shards) / cfg.evict_window
    return float(total)


#: arms whose modeled bytes sit within this fraction of the best arm
#: are a byte-tie: the verdict then prefers the structurally smaller
#: arm (less machinery — no dedup sort, no buffer, no private cache)
TIE_BAND = 0.02


def _pick(arms: dict, order) -> str:
    """argmin bytes with the tie-band rule: among arms within TIE_BAND
    of the minimum, the first in ``order`` (least machinery) wins."""
    best = min(arms[a]["modeled_bytes"] for a in arms)
    for a in order:
        if arms[a]["modeled_bytes"] <= best * (1.0 + TIE_BAND):
            return a
    raise AssertionError("unreachable: some arm attains the minimum")


def ab_verdict(kind: str, *, scope: str = "machinery",
               cap_n: int = 65536, batch: int = 256, arms=None,
               backend: str = "cpu", shards: int = 1) -> dict:
    """The model's pick for one shipped A/B config — the number
    bench.py reports next to the measured winner and
    tools/check_cost_model.py grades against every banked
    BENCH_trajectory.jsonl line.

    The decision rule is modeled amortized HBM bytes with the
    :data:`TIE_BAND` preference for less machinery: a knob arm only
    wins when it actually removes traffic (tree-top cache converts
    HBM rows to private rows; delayed eviction drops bytes only past
    window saturation ``E·F·path_len > n_buckets_padded``, where the
    dedup ``min`` pays off). ``sort`` and ``pipeline`` swap machinery
    without changing plane traffic, so their verdicts are structural
    and flagged in ``basis``.
    """
    out: dict = {"kind": kind, "scope": scope, "arms": {}}
    if kind == "tree_cache":
        ks = tuple(arms) if arms else (0, 2, 4, 8)
        for k in ks:
            if scope == "machinery":
                cfg = machinery_oram_cfg(cap_n, batch, k=k)
                nbytes = oram_steady_bytes(cfg, batch)
            else:
                led = engine_cost_ledger(sweep_engine_ecfg(
                    batch, tree_top_cache_levels=k))
                nbytes = led.steady_round_bytes
            out["arms"][f"k{k}"] = {"modeled_bytes": int(nbytes)}
        out["winner"] = _pick(out["arms"], [f"k{k}" for k in ks])
        out["basis"] = (
            "each cached level converts B HBM path rows/plane to "
            "private rows both directions; bytes fall monotonically "
            "in k, so the deepest arm wins unless the cut is inside "
            "the tie band"
        )
    elif kind == "evict":
        es = tuple(arms) if arms else (1, 2, 4, 8)
        for e in es:
            if scope == "machinery":
                cfg = machinery_oram_cfg(cap_n, batch, e=e)
                nbytes = oram_steady_bytes(cfg, batch)
            else:
                led = engine_cost_ledger(sweep_engine_ecfg(
                    batch, evict_every=e))
                nbytes = led.steady_round_bytes
            out["arms"][f"e{e}"] = {"modeled_bytes": int(nbytes)}
        out["winner"] = _pick(out["arms"], [f"e{e}" for e in es])
        out["basis"] = (
            "amortized flush rows = min(E·F·path_len, n_buckets)/E: "
            "below saturation that equals the E=1 write-back exactly "
            "(a byte-tie, so the window's dedup sort + buffer are pure "
            "overhead and E=1 wins); past saturation the min clamps "
            "and larger E strictly drops bytes"
        )
    elif kind == "sharded_evict":
        es = tuple(arms) if arms else (1, 2, 4)
        out["shards"] = shards
        for e in es:
            cfg = machinery_oram_cfg(cap_n, batch, e=e)
            nbytes = oram_sharded_steady_bytes(cfg, batch, shards)
            out["arms"][f"e{e}"] = {"modeled_bytes": int(nbytes)}
        out["winner"] = _pick(out["arms"], [f"e{e}" for e in es])
        out["basis"] = (
            "per-chip bytes on the mesh: gathers replicate at the full "
            "uniform count (the leak argument), owner-masked scatters "
            "partition /shards with the union exactly the single-chip "
            "write set — the shard count scales only the scatter half, "
            "so the E verdict keeps the single-chip structure (byte-"
            "tie below window saturation, least machinery wins; past "
            "saturation the dedup min clamps and larger E strictly "
            "drops per-chip bytes)"
        )
    elif kind == "sort":
        out["arms"] = {"xla": {"model": "W·log2(W) compare sort"},
                       "radix": {"model": "ceil(key_bits/bpp) serial "
                                          "scatter passes over W keys"}}
        out["winner"] = "xla" if backend == "cpu" else "defer"
        out["basis"] = (
            "bytes-identical machinery swap: the banked PR-5 floor "
            "records show CPU serial-scatter constants price radix "
            "out at every banked W; the TPU verdict defers to the "
            "cost_calibrate/sort_perf capture"
        )
    elif kind == "pipeline":
        out["arms"] = {"depth1": {"model": "host + device serialized"},
                       "depth2": {"model": "max(host, device) overlap"}}
        out["winner"] = "depth2"
        out["basis"] = (
            "overlap is never negative: depth-2 throughput >= depth-1 "
            "whenever the host collection window is nonzero; the A/B "
            "prices the commit-latency cost of the extra in-flight "
            "round, not bytes"
        )
    else:
        raise ValueError(f"unknown A/B kind {kind!r}")
    return out


# -- seeded undercount mutants (the checker's positive controls) --------

#: name -> (corruption transform on the predicted rows dict,
#:          validator it must trip, validator kwargs, expected kind)
_COST_MUTANTS: dict = {}


def _cost_mutant(name: str, validator: str, kind: str, **vkw):
    def deco(fn):
        _COST_MUTANTS[name] = (fn, validator, vkw, kind)
        return fn
    return deco


def _scale_plane(rows, suffix, *, g=None, s=None):
    out = dict(rows)
    for name, pr in rows.items():
        if name.endswith(suffix):
            out[name] = dataclasses.replace(
                pr,
                gather_rows=pr.gather_rows if g is None
                else int(pr.gather_rows * g),
                scatter_rows=pr.scatter_rows if s is None
                else int(pr.scatter_rows * s),
            )
    return out


@_cost_mutant("halve_fetch_rows", "round", "gather-undercount")
def _halve_fetch(rows):
    """A model that forgets half the fetched path — the classic
    B·path_len vs B·(path_len)/2 slip."""
    return _scale_plane(rows, "tree_val", g=0.5)


@_cost_mutant("drop_recursive_nonce_regather", "round_recursive",
              "gather-undercount")
def _drop_nonce_regather(rows):
    """A model unaware the recursive leaf plane re-gathers the nonce
    plane for its own keystream (the second nonce gather)."""
    return _scale_plane(rows, "nonces", g=0.5)


@_cost_mutant("forget_cache_planes", "round_cached", "gather-undercount")
def _forget_cache(rows):
    """A model that prices the cached top levels as free."""
    rows = _scale_plane(rows, "cache_idx", g=0, s=0)
    rows = _scale_plane(rows, "cache_val", g=0, s=0)
    return _scale_plane(rows, "cache_leaf", g=0, s=0)


@_cost_mutant("forget_writeback_half", "round", "scatter-undercount")
def _forget_writeback(rows):
    """A model that treats the E=1 round as fetch-only (the delayed-
    eviction schedule applied to the wrong knob setting)."""
    out = {}
    for name, pr in rows.items():
        out[name] = dataclasses.replace(pr, scatter_rows=0)
    return out


@_cost_mutant("halve_flush_targets", "flush", "scatter-undercount")
def _halve_flush(rows):
    """A model that halves the flush's deduplicated write set."""
    return _scale_plane(rows, "tree_val", s=0.5)


@_cost_mutant("halve_sharded_flush_scatter", "flush_sharded",
              "scatter-undercount")
def _halve_sharded_flush(rows):
    """A model that prices each chip's flush scatter at its OWNED row
    share (t/shards) — the ISSUE-18 slip: the owner mask bounds which
    rows LAND in HBM (the byte ledger's division), never the uniform
    ``t``-row drop-mode scatter shape every chip dispatches (what the
    traced census counts — the leak argument)."""
    return _scale_plane(rows, "tree_val", s=0.5)


@_cost_mutant("forget_inner_posmap_round", "round_recursive",
              "gather-undercount")
def _forget_inner(rows):
    """A model that prices the recursive map's internal ORAM round as
    free — exactly the B internal accesses the posmap docs pin."""
    out = {}
    for name, pr in rows.items():
        if "pm_" in name:
            pr = dataclasses.replace(pr, gather_rows=0, scatter_rows=0)
        out[name] = pr
    return out


@_cost_mutant("forget_mailbox_double_round", "engine",
              "gather-undercount")
def _forget_mb_double(rows):
    """A model that counts the mailbox tree once per engine round —
    the round A + round C composition missed."""
    out = {}
    for name, pr in rows.items():
        if name.startswith("mb_"):
            pr = dataclasses.replace(
                pr,
                gather_rows=pr.gather_rows // 2,
                scatter_rows=pr.scatter_rows // 2,
            )
        out[name] = pr
    return out


@_cost_mutant("forget_sweep_value_pass", "sweep", "gather-undercount")
def _forget_sweep_val(rows):
    """A model that forgets the sweep streams the value planes."""
    return _scale_plane(rows, "tree_val", g=0, s=0)


def audit_oram_configs():
    """The shipped trace-only knob matrix the smoke gate and the tests
    cross-validate over: (name, cfg, b) per ``oram_round`` geometry,
    spanning cache-k × posmap × evict_every (the fetch/flush split).

    Audit-geometry discipline (the tree-cache census's caveat, made
    load-bearing here): shape-class attribution is exact only while no
    *private* intermediate shares a declared plane shape — so batch
    sizes are chosen with ``b·(path_len−k)`` (and its cipher-stream
    doubling) distinct from every padded bucket count, and eviction
    windows keep ``flush_target_rows < n_buckets_padded`` (saturated
    flushes compact private buffers into exactly plane-shaped arrays).
    A violated assumption shows up as a loud mismatch, never a silent
    undercount."""
    from ..oram.path_oram import OramConfig
    from ..oram.posmap import derive_posmap_spec

    flat = OramConfig(height=5, value_words=8, n_blocks=32,
                      cipher_rounds=8, top_cache_levels=0)
    cached = OramConfig(height=5, value_words=8, n_blocks=32,
                        cipher_rounds=8, top_cache_levels=2)
    plaintext = OramConfig(height=5, value_words=8, n_blocks=32,
                           top_cache_levels=2)
    recursive = OramConfig(
        height=5, value_words=8, n_blocks=32, cipher_rounds=8,
        top_cache_levels=2,
        posmap=derive_posmap_spec(32, top_cache_levels=2),
    )
    evict = OramConfig(height=7, value_words=8, n_blocks=128,
                       cipher_rounds=8, top_cache_levels=2,
                       evict_window=2, evict_fetch_count=8,
                       evict_buffer_slots=64)
    evict_rec = OramConfig(
        height=7, value_words=8, n_blocks=128, cipher_rounds=8,
        top_cache_levels=2, evict_window=2, evict_fetch_count=8,
        evict_buffer_slots=64,
        posmap=derive_posmap_spec(128, top_cache_levels=2,
                                  evict_window=2, evict_fetch_count=8),
    )
    return [
        ("flat_k0_e1", flat, 8),
        ("flat_k2_e1", cached, 8),
        ("flat_k2_e1_plaintext", plaintext, 8),
        ("recursive_k2_e1", recursive, 6),
        ("flat_k2_e2_fetch", evict, 8),
        ("recursive_k2_e2_fetch", evict_rec, 6),
    ]


def audit_sharded_flush_configs():
    """The sharded-flush audit geometries (ISSUE 18): the owner-masked
    flush cross-validated on the widest mesh slice actually visible
    (2-way when >=2 devices, else a degenerate 1-way mesh — still a
    real shard_map trace, so the recipe never silently skips). Flat and
    recursive (replicated inner trees flushing inside the same pass);
    ``F=6`` keeps the dedup bound ``t = 2*6*8 = 96`` distinct from the
    2-way local bucket count 128 (the ambiguity guard)."""
    import jax

    from ..oram.path_oram import OramConfig
    from ..oram.posmap import derive_posmap_spec

    shards = 2 if len(jax.devices()) >= 2 else 1
    geo = dict(height=7, value_words=8, n_blocks=128, cipher_rounds=8,
               top_cache_levels=2, evict_window=2, evict_fetch_count=6,
               evict_buffer_slots=64)
    flat = OramConfig(**geo)
    rec = OramConfig(**geo, posmap=derive_posmap_spec(
        128, top_cache_levels=2, evict_window=2, evict_fetch_count=6))
    return [("sharded_flush_flat", flat, shards),
            ("sharded_flush_recursive", rec, shards)]


def audit_engine_configs():
    """The engine-level audit geometries: E=1 (joint fetch+write-back
    round) and E=2 (fetch-only rounds + the flush), both sized so both
    trees' flush targets stay unsaturated and no private cipher
    working set matches a plane's padded bucket count."""
    from ..config import GrapevineConfig
    from ..engine.state import EngineConfig

    e1 = EngineConfig.from_config(GrapevineConfig(
        max_messages=1 << 8, max_recipients=1 << 7, batch_size=4,
    ))
    e2 = EngineConfig.from_config(GrapevineConfig(
        max_messages=1 << 8, max_recipients=1 << 8, batch_size=2,
        evict_every=2,
    ))
    return [("engine_e1", e1), ("engine_e2", e2)]


def _mutant_fixtures():
    """Small trace-only geometries, one per validator context."""
    by_name = {name: (cfg, b) for name, cfg, b in audit_oram_configs()}
    engines = dict(audit_engine_configs())
    flat, flat_b = by_name["flat_k0_e1"]
    cached, cached_b = by_name["flat_k2_e1"]
    recursive, rec_b = by_name["recursive_k2_e1"]
    evict, _ = by_name["flat_k2_e2_fetch"]
    _, sh_cfg, sh_n = audit_sharded_flush_configs()[0]
    return {
        "flush_sharded": (cross_validate_sharded_flush,
                          {"cfg": sh_cfg, "shards": sh_n}),
        "round": (cross_validate_round, {"cfg": flat, "b": flat_b}),
        "round_cached": (cross_validate_round,
                         {"cfg": cached, "b": cached_b}),
        "round_recursive": (cross_validate_round,
                            {"cfg": recursive, "b": rec_b}),
        "flush": (cross_validate_flush, {"cfg": evict}),
        "engine": (cross_validate_engine_round,
                   {"ecfg": engines["engine_e1"]}),
        "sweep": (cross_validate_sweep, {"ecfg": engines["engine_e1"]}),
    }


class _MutantReport:
    """Minimal report shape for mutants.control_failures (its
    ``findings`` protocol)."""

    def __init__(self, findings):
        self.findings = findings


def run_cost_mutants() -> dict:
    """Run every seeded undercount mutant through the same
    cross-validators the production smoke runs; returns
    ``name -> (report, expected_kind, failed_as_expected)`` — the
    shape :func:`.mutants.control_failures` reports over."""
    fixtures = _mutant_fixtures()
    out = {}
    for name, (corrupt, context, vkw, kind) in _COST_MUTANTS.items():
        validator, base_kw = fixtures[context]
        try:
            validator(**base_kw, **vkw, _corrupt=corrupt)
            findings, hit = [], False
        except CostModelMismatch as m:
            findings, hit = [m], m.kind == kind
        out[name] = (_MutantReport(findings), kind, hit)
    return out
