"""Oblint: jaxpr-level taint-propagation obliviousness analyzer.

Secret inputs (recipient keys, msg ids, ORAM positions, stash/cache
contents, cipher keys, per-op payloads — declared as ``OBLINT_SECRETS``
anchors next to the code where each secret enters, see oram/round.py,
oram/posmap.py, engine/round_step.py, engine/expiry.py) are marked
tainted at trace time; the analyzer walks the closed jaxpr of the traced
round and proves that nothing secret-derived reaches an access-deciding
sink:

- a ``gather`` index operand or any ``scatter*`` index operand,
- a ``dynamic_slice`` / ``dynamic_update_slice`` start index,
- a ``cond`` branch predicate or a ``while`` loop predicate,
- a host callback (``debug_callback`` & friends — a leaky debug print
  is an access pattern too: it reaches the operator's terminal).

Taint propagation is a conservative union over every primitive (a leak
can only be over-reported, never missed), recursing into pjit bodies,
custom-call wrappers, cond branches, and running scan/while bodies to a
carry-taint fixpoint. Secret-dependent *Python* control flow and
secret-shaped outputs cannot survive tracing at all — jax raises a
concretization error, which the analyzer converts into a
``trace-dependence`` violation rather than crashing the audit.

Sites that are oblivious **by construction** (the ORAM's own machinery:
path fetches by one-time uniform leaves, the stash's owner-masked
scatters, the private working-set row map …) are admitted through an
explicit reviewed allowlist (:mod:`.allowlist`) keyed by
``prim@file.py:function``; every entry carries its one-line leak
argument, and the driver (tools/check_oblivious.py) fails the run if an
entry is never reached in any shipped knob combination — dead allowlist
entries rot.

The census-equality check of the legacy tools rides along as
:func:`census_equal`: trace the same program with adversarially
different *concrete* secret values and require an identical primitive
census — the strongest form of "the program does not depend on the
data", and the teeth against secret-shaped outputs.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Iterable

from .jaxpr_walk import _sub_jaxprs, census, site_of

#: sink table: primitive -> (kind, fn(eqn) -> index operand atoms)
_CALLBACK_PRIMS = ("debug_callback", "pure_callback", "io_callback",
                   "host_callback_call", "outside_call")

EMPTY: frozenset = frozenset()


@dataclasses.dataclass(frozen=True)
class Violation:
    """One secret-derived value reaching an access-deciding sink."""

    kind: str  # gather-index | scatter-index | dynamic-slice-start |
    #            cond-predicate | while-predicate | callback |
    #            trace-dependence | program-mismatch
    site: str  # "file.py:function" (jaxpr_walk.site_of key)
    prim: str  # primitive name ("" for trace-level findings)
    labels: tuple  # sorted secret labels that reached the sink
    message: str = ""

    def __str__(self) -> str:
        via = f" via {', '.join(self.labels)}" if self.labels else ""
        msg = f" — {self.message}" if self.message else ""
        return f"{self.kind}: {self.prim or '<trace>'} at {self.site}{via}{msg}"


@dataclasses.dataclass(frozen=True)
class AllowEntry:
    """One reviewed oblivious-by-construction sink site.

    ``prim`` matches exactly or as a family prefix (``"scatter"`` covers
    ``scatter-add`` etc.); ``site`` is the ``file.py:function`` key. The
    ``reason`` is the entry's one-line leak argument — an entry without a
    real argument should not exist."""

    prim: str
    site: str
    reason: str

    @property
    def key(self) -> str:
        return f"{self.prim}@{self.site}"

    def matches(self, v: Violation) -> bool:
        if v.site != self.site:
            return False
        return v.prim == self.prim or v.prim.startswith(self.prim + "-")


@dataclasses.dataclass
class OblintReport:
    """Outcome of one analysis: surviving violations, allowlist hits
    (entry.key -> count), and the traced program's primitive census."""

    name: str
    violations: list
    allowed: dict
    census: dict
    n_eqns: int = 0

    @property
    def ok(self) -> bool:
        return not self.violations

    def summary(self) -> str:
        lines = [
            f"[oblint] {self.name}: {len(self.violations)} violation(s), "
            f"{sum(self.allowed.values())} allowlisted sink(s) at "
            f"{len(self.allowed)} site(s), {self.n_eqns} equations"
        ]
        lines += [f"  VIOLATION {v}" for v in self.violations]
        return "\n".join(lines)


class _Ctx:
    """Mutable walk state: violations dedup + allowlist hit counts."""

    def __init__(self, allowlist: Iterable[AllowEntry]):
        self.allowlist = tuple(allowlist)
        self.violations: dict = {}  # keyed for dedup across fixpoint passes
        self.allowed: dict = {}

    def sink(self, kind: str, eqn, labels: frozenset, message: str = ""):
        if not labels:
            return
        v = Violation(
            kind=kind, site=site_of(eqn), prim=eqn.primitive.name,
            labels=tuple(sorted(labels)), message=message,
        )
        for entry in self.allowlist:
            if entry.matches(v):
                self.allowed[entry.key] = self.allowed.get(entry.key, 0) + 1
                return
        self.violations.setdefault((v.kind, v.site, v.prim, v.labels), v)


def _propagate(closed, in_taints: list, ctx: _Ctx) -> list:
    """Walk one (closed) jaxpr, return per-outvar taints."""
    jaxpr = getattr(closed, "jaxpr", closed)
    env: dict = {}

    def read(atom):
        # Literals (have .val) are trace-time constants: public
        return EMPTY if hasattr(atom, "val") else env.get(atom, EMPTY)

    def write(var, t):
        if t:
            env[var] = t

    for v, t in zip(jaxpr.invars, in_taints):
        write(v, t)
    # consts of a closed jaxpr are trace-time constants: public
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        ins = [read(a) for a in eqn.invars]
        union = frozenset().union(*ins) if ins else EMPTY

        # ---- sinks -----------------------------------------------------
        if name == "gather":
            ctx.sink("gather-index", eqn, ins[1],
                     "gather indexed by a secret-derived value")
        elif name.startswith("scatter"):
            ctx.sink("scatter-index", eqn, ins[1],
                     "scatter targeted by a secret-derived value")
        elif name == "dynamic_slice":
            ctx.sink("dynamic-slice-start", eqn,
                     frozenset().union(*ins[1:]) if ins[1:] else EMPTY,
                     "slice start derived from a secret")
        elif name == "dynamic_update_slice":
            ctx.sink("dynamic-slice-start", eqn,
                     frozenset().union(*ins[2:]) if ins[2:] else EMPTY,
                     "update start derived from a secret")
        elif name in _CALLBACK_PRIMS:
            ctx.sink("callback", eqn, union,
                     "secret-derived value escapes to a host callback")

        # ---- taint transfer --------------------------------------------
        if name == "cond":
            ctx.sink("cond-predicate", eqn, ins[0],
                     "branch selected by a secret-derived predicate")
            outs = None
            for br in eqn.params["branches"]:
                bouts = _propagate(br, ins[1:], ctx)
                outs = (
                    bouts if outs is None
                    else [a | b for a, b in zip(outs, bouts)]
                )
            # a secret predicate taints every branch output
            outs = [t | ins[0] for t in (outs or [])]
        elif name == "while":
            ncc = eqn.params["cond_nconsts"]
            nbc = eqn.params["body_nconsts"]
            cond_c, body_c = ins[:ncc], ins[ncc:ncc + nbc]
            carry = list(ins[ncc + nbc:])
            for _ in range(len(carry) + 1):
                nxt = _propagate(eqn.params["body_jaxpr"], body_c + carry, ctx)
                merged = [a | b for a, b in zip(carry, nxt)]
                if merged == carry:
                    break
                carry = merged
            pred = _propagate(eqn.params["cond_jaxpr"], cond_c + carry, ctx)
            ctx.sink(
                "while-predicate", eqn,
                frozenset().union(*pred) if pred else EMPTY,
                "loop trip count depends on a secret",
            )
            outs = carry
        elif name == "scan":
            nc, ncar = eqn.params["num_consts"], eqn.params["num_carry"]
            consts, carry = ins[:nc], list(ins[nc:nc + ncar])
            xs = ins[nc + ncar:]
            ys: list = []
            for _ in range(len(carry) + 1):
                res = _propagate(eqn.params["jaxpr"], consts + carry + xs, ctx)
                nxt, ys = res[:ncar], res[ncar:]
                merged = [a | b for a, b in zip(carry, nxt)]
                if merged == carry:
                    break
                carry = merged
            outs = carry + ys
        else:
            # the SAME sub-jaxpr discovery the census walk uses
            # (tuple/list params included — custom_linear_solve and
            # friends park jaxprs inside namedtuples): a sub-jaxpr the
            # census sees but the taint walk skips would be a silent
            # hole in the "over-reported, never missed" contract
            subs = list(_sub_jaxprs(eqn))
            if subs:
                # pjit / closed_call / custom_jvp / remat wrappers: one
                # body whose invars align positionally when arities match;
                # otherwise broadcast the conservative union
                outs = None
                for sub in subs:
                    n_in = len(getattr(sub, "jaxpr", sub).invars)
                    sub_in = ins if n_in == len(ins) else [union] * n_in
                    souts = _propagate(sub, sub_in, ctx)
                    outs = (
                        souts if outs is None
                        else [a | b for a, b in zip(outs, souts)]
                    )
                if len(outs or []) != len(eqn.outvars):
                    outs = [union] * len(eqn.outvars)
            else:
                outs = [union] * len(eqn.outvars)

        for var, t in zip(eqn.outvars, outs):
            write(var, t)
    return [read(v) for v in jaxpr.outvars]


def _path_str(path) -> str:
    """'state.rec.stash_idx' / 'batch.auth' style labels from jax key
    paths (GetAttrKey / DictKey / SequenceKey / FlattenedIndexKey)."""
    parts = []
    for k in path:
        if hasattr(k, "name"):
            parts.append(str(k.name))
        elif hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:  # pragma: no cover - future key types
            parts.append(str(k))
    return ".".join(parts)


def _secret_match(label: str, prefixes) -> bool:
    return any(
        label == p or label.startswith(p + ".") for p in prefixes
    )


def analyze(
    fn: Callable,
    args: dict,
    secrets: Iterable[str],
    allowlist: Iterable[AllowEntry] = (),
    name: str = "program",
) -> OblintReport:
    """Trace ``fn(*args.values())`` and taint-check the closed jaxpr.

    ``args`` maps argument name -> example value (arrays or
    ShapeDtypeStructs; pytrees welcome). ``secrets`` are dotted label
    prefixes over those names (``"batch.auth"``, ``"state.rec.posmap"``)
    — every flattened leaf under a prefix is tainted with its own full
    label, so violations name the exact secret that reached the sink.

    Secret-dependent Python control flow or shapes abort tracing; that
    abort IS the finding (``trace-dependence``)."""
    import jax
    from jax import tree_util as jtu
    from .jaxpr_walk import walk_eqns

    secrets = tuple(secrets)
    ctx = _Ctx(allowlist)
    values = list(args.values())
    try:
        closed = jax.make_jaxpr(fn)(*values)
    except Exception as exc:  # concretization = data-dependent trace
        if type(exc).__name__ in (
            "TracerBoolConversionError", "ConcretizationTypeError",
            "TracerIntegerConversionError", "TracerArrayConversionError",
        ):
            v = Violation(
                kind="trace-dependence", site=name, prim="",
                labels=(), message=(
                    "tracing aborted on a data-dependent Python branch "
                    f"or shape: {type(exc).__name__}"
                ),
            )
            return OblintReport(name, [v], {}, {})
        raise

    # map flattened invars -> secret labels, argument by argument
    in_taints: list = []
    for argname, val in args.items():
        leaves_with_path = jtu.tree_flatten_with_path(val)[0]
        for path, _leaf in leaves_with_path:
            sub = _path_str(path)
            label = f"{argname}.{sub}" if sub else argname
            in_taints.append(
                frozenset([label]) if _secret_match(label, secrets) else EMPTY
            )
    if len(in_taints) != len(closed.jaxpr.invars):
        raise ValueError(
            f"oblint: {len(in_taints)} flattened args vs "
            f"{len(closed.jaxpr.invars)} jaxpr invars — static/implicit "
            "arguments must be closed over, not passed"
        )
    _propagate(closed, in_taints, ctx)
    return OblintReport(
        name=name,
        violations=sorted(
            ctx.violations.values(), key=lambda v: (v.site, v.kind)
        ),
        allowed=dict(ctx.allowed),
        census=dict(census(closed)),
        n_eqns=sum(1 for _ in walk_eqns(closed)),
    )


def census_equal(
    variants: dict, name: str = "program"
) -> list:
    """Trace each ``variants[vname] = (fn, args)`` (secrets baked into
    ``fn`` as concrete constants; public state passed via ``args``) and
    require identical primitive censuses.

    Constants are the strongest form of the check — a Python-level
    branch on the secret, a shortcut for special values, or a
    secret-shaped output traces to a *different program*, which
    taint analysis over one abstract trace can never see. Returns
    ``program-mismatch`` violations (empty = pass)."""
    import jax

    censuses = {
        vname: census(jax.make_jaxpr(fn)(*args))
        for vname, (fn, args) in variants.items()
    }
    base_name, base = next(iter(censuses.items()))
    out = []
    for vname, c in censuses.items():
        if c != base:
            diff = (c - base) + (base - c)
            out.append(Violation(
                kind="program-mismatch", site=name, prim="",
                labels=(vname, base_name),
                message=(
                    f"secret instantiation {vname!r} traces a DIFFERENT "
                    f"program than {base_name!r}: {dict(diff)} — the "
                    "compiled round depends on the secret values"
                ),
            ))
    return out
