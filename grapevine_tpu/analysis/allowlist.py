"""The reviewed allowlist: every secret-indexed sink the engine is
*allowed* to contain, each with its one-line leak argument.

Contract (enforced by tools/check_oblivious.py):

- any taint-flagged sink NOT listed here fails the audit — a new
  secret-derived gather/scatter/predicate cannot land without a review
  adding its entry and its argument;
- any entry never *reached* in the swept knob matrix fails the audit —
  dead entries rot into blanket permissions and are exactly how a later
  leak hides behind an old review.

The arguments fall into four standings, all rooted in the threat model
(oram/path_oram.py): the public transcript is the HBM bucket-tree
access sequence; the stash, position map, tree-top cache, and per-round
working set are EPC-analog **private working memory** (ciphertext at
rest IS public — which is why the cipher key is a taint anchor).

1. *one-time uniform paths*: tree accesses indexed by leaves that are
   consumed exactly once then remapped to fresh uniform draws — the
   Path-ORAM invariant; the transcript is i.i.d. uniform whatever the
   ops were.
2. *private working memory*: accesses into stash/posmap/cache/working
   rows; the round executes a fixed schedule of them (the census gates
   pin this), only their *contents* vary.
3. *oblivious permutation plumbing*: sort/rank/segmented-scan data
   movement over fixed [B]/[W] arrays — every row moves exactly once
   per pass; the permutation's value is secret, its shape is not.
4. *fixed full sweeps*: iota-scheduled walks that touch every row
   regardless of the data (the expiry sweep).
"""

from __future__ import annotations

from .oblint import AllowEntry

_A = AllowEntry

#: one ORAM access round (oram/round.py and everything under it)
_ORAM_CORE = (
    _A("gather", "oram/path_oram.py:_path_gather",
       "path fetch indexed by one-time leaves: each position is read "
       "once then remapped, so every fetched path is an independent "
       "uniform draw (Path-ORAM invariant)"),
    _A("scatter", "oram/path_oram.py:_path_scatter",
       "write-back of exactly the fetched paths, owner-masked — the "
       "write transcript is identical to the read transcript"),
    _A("gather", "oram/path_oram.py:working_leaves",
       "leaf lookup in the flat position table, private working memory "
       "(one fixed [W]-shaped gather per round)"),
    _A("gather", "oram/round.py:oram_round",
       "private working-set reads: block->row map, initial-value rows, "
       "and cache-top planes — stash-standing memory on a fixed "
       "per-round schedule"),
    _A("scatter", "oram/round.py:oram_round",
       "commits into private planes (working rows, eviction slots, "
       "stash recompaction, cache-top write-back): fixed shapes, "
       "unique in-bounds targets, owner-masked"),
    _A("scatter", "oram/round.py:_bucket_owner_map",
       "owner election: one scatter-min over exactly B*path_len heap "
       "slots per round into a private dense map, whatever the leaves"),
    _A("gather", "oram/round.py:occurrence_masks_sorted",
       "sorted dedup: permutation/boundary gathers over fixed [B] "
       "arrays — oblivious-sort data movement, schedule fixed by B"),
    _A("gather", "oram/round.py:_assign_evictions",
       "eviction assignment: sort-permutation and bucket-map gathers "
       "over the fixed working set — oblivious permutation plumbing; "
       "ONE body serves per-round eviction (owner columns over [W]) "
       "and the delayed flush (public deduplicated targets over "
       "[C+S])"),
    _A("scatter", "oram/round.py:_assign_evictions",
       "eviction assignment: inverse-permutation scatters over the "
       "fixed working set — every row written exactly once per pass "
       "(both the per-round and the flush layouts)"),
    # -- delayed batched eviction (PR 15): the fetch-only round and the
    # batched flush. The flush's bucket *targets* derive only from the
    # public window ledger (ebuf_paths — past transcript); under a
    # recursive posmap the blanket ``state.posmap`` pytree anchor
    # over-approximates and taints the INNER tree's ledger too, which
    # is why ledger-indexed sinks appear here at all (the engine-level
    # anchors leave the outer ledger untainted, and the row accounting
    # in check_tree_cache_oblivious.py pins the schedule shape).
    _A("gather", "oram/round.py:_oram_fetch_round",
       "fetch round: path fetch + stale-tag reads indexed by one-time "
       "uniform leaves (the Path-ORAM invariant), plus private "
       "working-set reads (block->row map, cache-top planes) on the "
       "fixed per-round schedule — the E=1 round's reads minus all "
       "write-back"),
    _A("scatter", "oram/round.py:_oram_fetch_round",
       "fetch round commits into private planes only: working rows, "
       "the buffer∪stash recompaction, and the fetch-tag mark over "
       "exactly B*path_len one-time-leaf slots — zero HBM tree "
       "scatters (CI-audited row accounting)"),
    _A("dynamic_update_slice", "oram/round.py:_oram_fetch_round",
       "window-ledger append at row ebuf_rounds*F — the start index is "
       "the public round counter; flagged only under the recursive "
       "posmap's blanket pytree taint (the inner counter rides "
       "state.posmap)"),
    _A("scatter", "oram/round.py:oram_flush",
       "flush write-back: owner-masked scatters into exactly the "
       "window's fetched buckets (write transcript = union of the "
       "window's read transcripts) plus stash recompaction into "
       "private planes — unique in-bounds targets throughout"),
)

#: position-map resolution (flat table and recursive internal ORAM)
_POSMAP = (
    _A("gather", "oram/posmap.py:lookup_remap_round",
       "flat position-map read: the table is private working memory; "
       "exactly one [B]-gather per round"),
    _A("scatter", "oram/posmap.py:lookup_remap_round",
       "flat position-map remap write: same private table, one "
       "[B]-scatter per round, OOB-dropped for non-winners"),
    _A("gather", "oram/posmap.py:apply_pm",
       "recursive map entry extract/merge inside the internal round's "
       "private working set (fixed per-round schedule)"),
    _A("scatter", "oram/posmap.py:apply_pm",
       "recursive map entry writes onto committed internal rows — "
       "private working set, unique in-bounds targets"),
    _A("gather", "oram/posmap.py:_group_last_slot",
       "sorted last-occurrence dedup: the occurrence_masks_sorted "
       "mirror, permutation gathers over fixed [B] arrays"),
)

#: oblivious sort/scan machinery (bit-identity with argsort is
#: separately pinned by tests/test_radix.py, test_segmented.py)
_SORTS = (
    _A("gather", "oblivious/primitives.py:lex_argsort",
       "two-pass stable 64-bit argsort: take_along_axis by the first "
       "pass's permutation — every row moves exactly once per pass"),
    _A("gather", "oblivious/radix.py:_rank_pass",
       "counting-sort rank pass: per-digit histogram reads, all B rows "
       "touched exactly once per pass"),
    _A("scatter", "oblivious/radix.py:_rank_pass",
       "counting-sort histogram scatter: fixed digit-bucket array, all "
       "B rows contribute exactly once per pass"),
    _A("gather", "oblivious/radix.py:radix_group_sort",
       "radix group sort: permutation gathers over fixed [B] arrays"),
    _A("scatter", "oblivious/radix.py:radix_group_sort",
       "radix group sort: rank-targeted scatter — targets are a "
       "permutation of [B], every row written once"),
    _A("scatter", "oblivious/radix.py:radix_rank",
       "radix rank materialization: permutation scatter over [W]"),
    _A("scatter", "oblivious/segmented.py:multiword_group_sort",
       "wide-key group sort: inverse-permutation scatter over fixed "
       "[B] arrays"),
    _A("gather", "oblivious/segmented.py:group_sort",
       "bounded-key group sort: permutation gathers over fixed [B]"),
    _A("gather", "oblivious/segmented.py:segmented_sum_before",
       "segmented scan boundary reads: permutation-indexed, fixed [B]"),
    _A("gather", "oblivious/segmented.py:segmented_sum_total",
       "segmented totals broadcast back by segment id: fixed [B]"),
)

#: slot-order semantics + admission (engine/vphases.py): all of it runs
#: over per-op [B] working rows — private memory with a per-round
#: schedule that is a constant of the geometry (the quota-admission
#: *aggregate* branch is the one documented exception, and it selects
#: between two always-executed programs, never skips one)
_VPHASES = (
    _A("gather", "engine/vphases.py:_admission_fast",
       "quota-decoupled admission: rank/slot gathers over [B] counters "
       "in private working memory"),
    _A("gather", "engine/vphases.py:apply_batch",
       "slot-order chain resolution: same-key row gathers over the "
       "fixed [B] working set"),
    _A("scatter", "engine/vphases.py:apply_batch",
       "slot-order chain commits: [B]-row scatters into private "
       "working rows, unique in-bounds targets"),
    _A("gather", "engine/vphases.py:select_by_rank",
       "k-th-flag selection: rank-indexed gather over fixed [B]"),
    _A("scatter", "engine/vphases.py:select_by_rank",
       "k-th-flag selection: rank scatter over fixed [B]"),
    _A("gather", "engine/vphases.py:group_first",
       "group-boundary gather over the sorted [B] slot order"),
    _A("gather", "engine/vphases.py:group_last",
       "group-boundary gather over the sorted [B] slot order"),
    _A("gather", "engine/vphases.py:first_flag_index",
       "first-flag rank gather over fixed [B]"),
    _A("gather", "engine/vphases.py:last_flag_index",
       "last-flag rank gather over fixed [B]"),
    _A("gather", "engine/vphases.py:_to",
       "scan-impl permutation into sorted order: fixed [B] gather"),
    _A("gather", "engine/vphases.py:_back",
       "scan-impl permutation out of sorted order: fixed [B] gather"),
    _A("scatter", "engine/vphases.py:step",
       "exact-admission scan body: per-op counter updates, private [B] "
       "state, fixed trip count"),
    _A("dynamic_slice", "engine/vphases.py:step",
       "exact-admission scan body: the scan's own per-op row slice — "
       "trip count and slice shape are constants of B"),
)

#: engine round glue + expiry sweep
_ENGINE = (
    _A("scatter", "engine/round_step.py:engine_round_step",
       "freed-block push: rank-compaction scatter into the private "
       "freelist — at most B unique in-bounds targets, fixed shape"),
    _A("scatter", "engine/expiry.py:expiry_sweep",
       "sweep bookkeeping (freelist rebuild, recipient release): "
       "rank-compaction scatters into private tables after an "
       "iota-scheduled full-tree walk"),
    _A("scatter", "engine/expiry.py:rec_body",
       "per-chunk liveness marking: presence bits scattered by private "
       "block ids into a private [max_messages] table; every tree row "
       "is visited on the fixed chunk schedule"),
)

#: the one reviewed list the driver sweeps (tools/check_oblivious.py)
ENGINE_ALLOWLIST: tuple = _ORAM_CORE + _POSMAP + _SORTS + _VPHASES + _ENGINE


def entries_by_key() -> dict:
    return {e.key: e for e in ENGINE_ALLOWLIST}


#: ----------------------------------------------------------------------
#: Rangelint's reviewed allowlist (analysis/rangelint.py; swept by
#: tools/check_ranges.py with the same dead-entry rule as the taint
#: list): every *intentionally* mod-2^32 operation in the compiled
#: round, each with its one-line range argument. The shape of every
#: argument is the same: the wrap is the operation's DEFINITION (a
#: cipher/mixer round, a two-lane carry), not an accident of geometry —
#: the pair/primitive downstream restores or never needed the
#: mathematical value. Anything wrapping outside these sites fails the
#: audit.
RANGE_ALLOWLIST: tuple = (
    # ChaCha (oblivious/bucket_cipher.py): ARX is arithmetic mod 2^32
    # by RFC 7539 — the keystream is DEFINED over the wrapped lanes
    _A("add", "oblivious/bucket_cipher.py:_qr",
       "ChaCha quarter-round addition is mod-2^32 by cipher definition"),
    _A("shift_left", "oblivious/bucket_cipher.py:_rotl",
       "rotate-left: the bits shifted past 32 re-enter via the OR'd "
       "logical right shift — no information leaves the lane"),
    _A("add", "oblivious/bucket_cipher.py:chacha_blocks",
       "the state+init feedforward of the ChaCha block function, "
       "mod-2^32 by RFC 7539"),
    _A("add", "oblivious/bucket_cipher.py:epoch_next",
       "u64 write-epoch as (lo, hi) u32 lanes: the lo lane wraps by "
       "design and the explicit carry feeds hi — the PAIR is the "
       "counter, 64-bit and unwrappable in any feasible lifetime"),
    # u64 two-lane helpers (oblivious/primitives.py)
    _A("add", "oblivious/primitives.py:u64_add_u32",
       "u64 carry arithmetic in u32 lanes: lo wraps mod 2^32, the "
       "comparison-derived carry moves the overflow into hi"),
    _A("sub", "oblivious/primitives.py:u64_sub",
       "u64 borrow arithmetic in u32 lanes: lo wraps mod 2^32, the "
       "comparison-derived borrow moves the underflow into hi"),
    # keyed mixers: mb_bucket_hash (engine/state.py) and the Feistel
    # PRP round function (oblivious/prp.py) — murmur-style ARX whose
    # output is masked to the table/domain width at the call site
    _A("mul", "engine/state.py:mb_bucket_hash",
       "keyed bucket-hash mixing multiplies are mod-2^32 by design; "
       "the result is masked to the (power-of-two) table width"),
    _A("add", "engine/state.py:mb_bucket_hash",
       "keyed bucket-hash mixing adds are mod-2^32 by design; the "
       "result is masked to the (power-of-two) table width"),
    _A("shift_left", "engine/state.py:mb_bucket_hash",
       "bucket-hash rotates: dropped high bits re-enter via the OR'd "
       "right shift"),
    _A("mul", "oblivious/prp.py:_f",
       "Feistel round-function multiplies are mod-2^32 by design; the "
       "half is masked to its domain width after each round"),
    _A("shift_left", "oblivious/prp.py:_f",
       "Feistel round-function rotate: dropped high bits re-enter via "
       "the OR'd right shift"),
    # invariant-backed sites: the wrap/blowup is impossible by a
    # reviewed program invariant an oracle-equality suite pins, which
    # a non-relational interval domain cannot express
    _A("sub", "engine/round_step.py:engine_round_step",
       "free_top - n_allocs: phase-A admission never allocates more "
       "blocks than the freelist holds (quota invariant, oracle-"
       "pinned); the adjacent min re-bounds the result for downstream"),
    _A("reduce_sum", "engine/vphases.py:apply_batch",
       "masked one-hot row selects (recipient-key slot match, at most "
       "one key matches per bucket — mailbox uniqueness invariant): "
       "the sum IS the selected row, never an accumulation"),
    _A("reduce_sum", "engine/vphases.py:select_by_rank",
       "rank-equality one-hot select: at most one lane of a group has "
       "rank q, so the masked sum is a private row select"),
    _A("add", "oblivious/radix.py:_rank_pass",
       "counting-rank recombination: zeros-rank + ones-rank of one "
       "stable partition is a permutation of [0, B) (sums below B "
       "pointwise, 2B only in interval arithmetic); the adjacent clip "
       "re-bounds the lane for downstream"),
    # owner-masked sharded write-back (parallel/mesh.py composition;
    # ISSUE 18): each chip rebases global heap rows into its local
    # shard range before the drop-mode scatter
    _A("sub", "oram/path_oram.py:_path_scatter",
       "path_b - axis_index*n_local rebase: non-owned lanes wrap mod "
       "2^32 by construction and the owner mask routes exactly those "
       "lanes to the out-of-range drop sentinel — a wrapped value is "
       "never a landing address (sharded==single-chip bit-equality, "
       "tests/test_parallel.py)"),
    _A("convert_element_type", "oram/path_oram.py:_path_scatter",
       "drop-mode scatter target cast u32->int32: owned lanes are "
       "< n_local (fits, at every certified geometry) by the owner "
       "mask the interval domain cannot relate; non-owned lanes carry "
       "the wrapped rebase and drop out of bounds — write-drop is the "
       "documented masking idiom, so the cast only ever narrows the "
       "drop sentinel"),
)
