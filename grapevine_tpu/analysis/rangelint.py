"""Rangelint: interval-domain overflow certification of a traced round.

The engine's device lanes are u32 end to end — leaf ids, heap bucket
ids, block indices, position tables, batch columns — and jnp integer
arithmetic wraps silently.  ROADMAP item 4 wants capacity at 2^36+
records, which is exactly where those lanes stop fitting, so "the index
arithmetic stays inside its dtype at the declared geometry" must be a
*checked invariant* of the compiled program, the same way Oblint
(:mod:`.oblint`) made obliviousness one.

This module is an abstract interpreter over the closed jaxpr (walked
with the same :mod:`.jaxpr_walk` equation stream the taint analyzer and
the legacy censuses use) with a per-variable integer interval domain:
every jaxpr var carries one ``[lo, hi]`` over unbounded Python ints
covering all of its elements.  Geometry-derived input ranges are
declared where the values enter the program (``RANGELINT_BOUNDS``
anchors in oram/path_oram.py, oram/posmap.py, engine/round_step.py,
engine/expiry.py; engine/journal.py carries the host-side byte-length
guard of the same discipline) and propagated through
add/mul/shift/concat/cast/gather/scatter, with an affine-widening
carry fixpoint for ``scan``/``while``.  Three finding classes:

- ``overflow`` — an integer op whose mathematical interval escapes the
  result dtype: the device value silently wraps (u32 leaf/bucket/index
  arithmetic past 2^32, int32 counters, reduce/cumsum blowups);
- ``trunc-cast`` — a narrowing ``convert_element_type`` whose source
  interval does not fit the target dtype (u32→int32 index conversions
  are the canonical case: an index that cannot be proven < 2^31 goes
  negative on the way into a gather);
- ``oob-index`` — a gather / dynamic-slice start index interval that
  can exceed the axis extent.  XLA *clamps* these, which hides the bug
  behind a silently-wrong row.  Scatters in ``FILL_OR_DROP`` mode are
  exempt: out-of-bounds-drops-the-write is this codebase's documented
  masking idiom (every ``.at[...]`` site), and the certified property
  there is that the *drop sentinel itself* fits the index dtype —
  which the trunc-cast check enforces.

Intentional mod-2^32 arithmetic (ChaCha ARX, the keyed bucket-hash
mixer, the Feistel PRP, the u64 two-lane carry/borrow helpers) is
admitted through a reviewed allowlist (:data:`.allowlist.RANGE_ALLOWLIST`)
reusing Oblint's ``AllowEntry`` keying (``prim@file.py:function``);
every entry carries a one-line *range argument*, and the driver
(tools/check_ranges.py) fails the run if an entry is never reached.

Like Oblint, findings can be over-reported but never missed inside the
modeled fragment: unknown primitives degrade to the full dtype range of
their outputs (sound, quiet), bitwise ops never flag (their result is
representable by construction), and interval growth in loop carries is
extrapolated over the declared trip count before the body is re-walked.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Iterable

import numpy as np

from .jaxpr_walk import _sub_jaxprs, census, site_of, walk_eqns

#: interval = (lo, hi) Python ints; None = unknown (floats, opaque ops)
Iv = "tuple[int, int] | None"

#: cap on shift amounts fed to Python ``<<`` during interval math (a
#: traced shift-by-2^32 must not allocate a billion-bit int)
_SHIFT_CAP = 128


def dtype_range(dtype) -> "tuple[int, int] | None":
    """Representable range of a dtype: ints/bools get exact bounds,
    floats/complex return None (no wraparound semantics to certify)."""
    try:
        dt = np.dtype(dtype)
    except TypeError:
        return None  # extended dtypes (PRNG keys): no lane to certify
    if dt.kind == "b":
        return (0, 1)
    if dt.kind in "iu":
        info = np.iinfo(dt)
        return (int(info.min), int(info.max))
    return None


def _join(a, b):
    if a is None or b is None:
        return None
    return (min(a[0], b[0]), max(a[1], b[1]))


def _clamp(iv, rng):
    if iv is None or rng is None:
        return rng
    return (max(iv[0], rng[0]), min(iv[1], rng[1]))


@dataclasses.dataclass(frozen=True)
class RangeFinding:
    """One interval escaping its lane (or its axis)."""

    kind: str  # overflow | trunc-cast | oob-index | trace-abort
    site: str  # "file.py:function" (jaxpr_walk.site_of key)
    prim: str  # primitive name ("" for trace-level findings)
    message: str = ""

    def __str__(self) -> str:
        msg = f" — {self.message}" if self.message else ""
        return f"{self.kind}: {self.prim or '<trace>'} at {self.site}{msg}"


@dataclasses.dataclass
class RangeReport:
    """Outcome of one analysis: surviving findings, allowlist hits
    (entry.key -> count), and the traced program's primitive census."""

    name: str
    findings: list
    allowed: dict
    census: dict
    n_eqns: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings

    def summary(self) -> str:
        lines = [
            f"[rangelint] {self.name}: {len(self.findings)} finding(s), "
            f"{sum(self.allowed.values())} allowlisted op(s) at "
            f"{len(self.allowed)} site(s), {self.n_eqns} equations"
        ]
        lines += [f"  FINDING {f}" for f in self.findings]
        return "\n".join(lines)


def _line_of(eqn, pkg: str = "grapevine_tpu") -> str:
    """``:line`` of the innermost user frame, for finding messages only
    (allowlist keys stay line-free so they survive churn)."""
    tb = getattr(eqn.source_info, "traceback", None)
    for fr in (tb.frames if tb is not None else []):
        fn = fr.file_name.replace("\\", "/")
        if fn.endswith("analysis/oblint.py") or \
                fn.endswith("analysis/rangelint.py"):
            continue
        if f"/{pkg}/" in fn or ("site-packages" not in fn
                                and "/jax/" not in fn):
            line = getattr(fr, "line_num", None)
            return f" (line {line})" if line else ""
    return ""


class _Ctx:
    """Mutable walk state: findings dedup + allowlist hit counts, plus
    the walk's shared value environment. ``env``/``alias``/``preds``
    span every (sub-)jaxpr of one analysis — jaxpr vars are globally
    unique objects, and sub-jaxpr invars *alias* their caller atoms
    (when arities match) so comparison provenance survives pjit
    boundaries (jnp.where wraps its select_n in a ``pjit[_where]``)."""

    def __init__(self, allowlist: Iterable):
        self.allowlist = tuple(allowlist)
        self.findings: dict = {}  # (kind, site, prim) -> RangeFinding
        self.allowed: dict = {}
        self.env: dict = {}  # var -> Iv
        self.alias: dict = {}  # sub-jaxpr invar -> caller atom
        self.preds: dict = {}  # pred var -> (rel, a_atom, b_atom)
        self.axis_sizes: dict = {}  # mesh axis name -> size (shard_map)

    def flag(self, kind: str, eqn, message: str):
        f = RangeFinding(
            kind=kind, site=site_of(eqn), prim=eqn.primitive.name,
            message=message + _line_of(eqn),
        )
        import os
        if os.environ.get("GRAPEVINE_RANGELINT_DEBUG"):  # pragma: no cover
            print(f"[rangelint-debug] {f}\n  eqn: {eqn}")
        for entry in self.allowlist:
            if entry.matches(f):
                self.allowed[entry.key] = self.allowed.get(entry.key, 0) + 1
                return
        # first (narrowest-interval) message wins; later passes only
        # widen the same site
        self.findings.setdefault((f.kind, f.site, f.prim), f)


def _lit_interval(val) -> Iv:
    a = np.asarray(val)
    if a.dtype.kind in "iub":
        if a.size == 0:
            return (0, 0)
        return (int(a.min()), int(a.max()))
    return None


def _checked(ctx, eqn, iv, rng, what: str) -> Iv:
    """Flag ``iv`` escaping ``rng`` (the result dtype), then clamp: a
    wrapped lane can hold anything representable, nothing more."""
    if iv is None or rng is None:
        return rng
    if iv[0] < rng[0] or iv[1] > rng[1]:
        ctx.flag(
            "overflow", eqn,
            f"{what}: interval [{iv[0]}, {iv[1]}] escapes "
            f"{eqn.outvars[0].aval.dtype} [{rng[0]}, {rng[1]}] — the "
            "lane wraps silently at this geometry",
        )
        return rng
    return iv


def _shift_candidates(a: Iv, s: Iv, op) -> Iv:
    if a is None or s is None:
        return None
    s_lo = max(0, min(s[0], _SHIFT_CAP))
    s_hi = max(0, min(s[1], _SHIFT_CAP))
    cands = [op(x, y) for x in a for y in (s_lo, s_hi)]
    return (min(cands), max(cands))


def _decide(rel: str, a: Iv, b: Iv) -> "bool | None":
    """Truth value of an elementwise comparison decidable from the
    operand intervals alone; None = undecidable."""
    if a is None or b is None:
        return None
    if rel == "lt":
        if a[1] < b[0]:
            return True
        if a[0] >= b[1]:
            return False
    elif rel == "le":
        if a[1] <= b[0]:
            return True
        if a[0] > b[1]:
            return False
    elif rel == "gt":
        if a[0] > b[1]:
            return True
        if a[1] <= b[0]:
            return False
    elif rel == "ge":
        if a[0] >= b[1]:
            return True
        if a[1] < b[0]:
            return False
    elif rel == "eq":
        if a[1] < b[0] or a[0] > b[1]:
            return False
        if a[0] == a[1] == b[0] == b[1]:
            return True
    elif rel == "ne":
        if a[1] < b[0] or a[0] > b[1]:
            return True
        if a[0] == a[1] == b[0] == b[1]:
            return False
    return None


def _bitwidth_bound(a: Iv, b: Iv) -> Iv:
    """or/xor of nonnegative ints: bounded by the next all-ones mask."""
    hi = max(a[1], b[1])
    return (0, (1 << max(1, hi.bit_length())) - 1)


def _index_extent(eqn) -> "tuple[int, int] | None":
    """Allowed start-index range for a gather/dynamic-slice eqn, or
    None when the op should not be checked (drop-mode scatters)."""
    name = eqn.primitive.name
    mode = eqn.params.get("mode")
    is_drop = mode is not None and getattr(mode, "name", "") == "FILL_OR_DROP"
    if name == "gather":
        if is_drop:
            return None  # explicit fill semantics: OOB reads the fill
        dnums = eqn.params["dimension_numbers"]
        slice_sizes = eqn.params["slice_sizes"]
        op_shape = eqn.invars[0].aval.shape
        dims = dnums.start_index_map
        if not dims:
            return None
        # one mapped dim = exact; several = the loosest extent (an
        # exceedance of the loosest bound is OOB on every column)
        hi = max(op_shape[d] - slice_sizes[d] for d in dims)
        return (0, hi)
    if name.startswith("scatter"):
        if is_drop:
            return None  # OOB-drops-the-write: the masking idiom
        dnums = eqn.params["dimension_numbers"]
        op_shape = eqn.invars[0].aval.shape
        dims = dnums.scatter_dims_to_operand_dims
        if not dims:
            return None
        return (0, max(op_shape[d] - 1 for d in dims))
    return None


def _propagate(closed, in_ivs, ctx: _Ctx, in_atoms=None) -> list:
    """Walk one (closed) jaxpr, return per-outvar intervals.

    ``in_atoms`` (pjit-style nesting with matching arity) aliases the
    body's invars to the caller's atoms instead of binding values, so
    comparison provenance — "this var IS the var that was compared" —
    survives the boundary; ``in_ivs`` (top level, loop carries) binds
    concrete intervals."""
    jaxpr = getattr(closed, "jaxpr", closed)
    env, alias, preds = ctx.env, ctx.alias, ctx.preds

    def resolve(atom):
        while not hasattr(atom, "val") and atom in alias:
            atom = alias[atom]
        return atom

    def read(atom) -> Iv:
        atom = resolve(atom)
        if hasattr(atom, "val"):
            return _lit_interval(atom.val)
        return env.get(atom, dtype_range(atom.aval.dtype))

    def narrow(case_atom, civ: Iv, rel, truth: bool) -> Iv:
        """Narrow a select case's interval by the select predicate."""
        case_atom = resolve(case_atom)
        if civ is None or hasattr(case_atom, "val"):
            return civ
        rel_name, a_atom, b_atom = rel
        if case_atom is a_atom:
            other, flip = b_atom, False
        elif case_atom is b_atom:
            other, flip = a_atom, True
        else:
            return civ
        biv = read(other)
        if biv is None:
            return civ
        # normalize to "case REL other": flipping swaps lt<->gt, le<->ge
        r = rel_name
        if flip:
            r = {"lt": "gt", "gt": "lt", "le": "ge", "ge": "le"}.get(r, r)
        if not truth:
            r = {"lt": "ge", "ge": "lt", "le": "gt", "gt": "le",
                 "eq": "ne", "ne": "eq"}.get(r, r)
        lo, hi = civ
        if r == "lt":
            hi = min(hi, biv[1] - 1)
        elif r == "le":
            hi = min(hi, biv[1])
        elif r == "gt":
            lo = max(lo, biv[0] + 1)
        elif r == "ge":
            lo = max(lo, biv[0])
        elif r == "eq":
            lo, hi = max(lo, biv[0]), min(hi, biv[1])
        if lo > hi:  # contradictory branch: never taken; keep sound
            return civ
        return (lo, hi)

    def write(var, iv):
        env[var] = _clamp(iv, dtype_range(var.aval.dtype))

    if in_atoms is not None:
        for v, atom in zip(jaxpr.invars, in_atoms):
            alias[v] = atom
            env.pop(v, None)  # aliased: resolve fresh through the caller
    else:
        for v, iv in zip(jaxpr.invars, in_ivs):
            alias.pop(v, None)  # re-bound (loop carry): value, not alias
            write(v, iv)
    for v, c in zip(jaxpr.constvars, getattr(closed, "consts", ())):
        write(v, _lit_interval(c))

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        ins = [read(a) for a in eqn.invars]
        out_rngs = [dtype_range(v.aval.dtype) for v in eqn.outvars]
        outs: "list | None" = None

        def binop(f) -> Iv:
            a, b = ins[0], ins[1]
            if a is None or b is None:
                return None
            cands = [f(x, y) for x in a for y in b]
            return (min(cands), max(cands))

        # ---- arithmetic: exact interval, wraparound check --------------
        if name == "add":
            outs = [_checked(ctx, eqn, binop(lambda x, y: x + y),
                             out_rngs[0], "add")]
        elif name == "sub":
            a, b = ins[0], ins[1]
            iv = None if a is None or b is None else (a[0] - b[1], a[1] - b[0])
            outs = [_checked(ctx, eqn, iv, out_rngs[0], "sub")]
        elif name == "mul":
            outs = [_checked(ctx, eqn, binop(lambda x, y: x * y),
                             out_rngs[0], "mul")]
        elif name == "neg":
            a = ins[0]
            iv = None if a is None else (-a[1], -a[0])
            outs = [_checked(ctx, eqn, iv, out_rngs[0], "neg")]
        elif name == "integer_pow":
            a, y = ins[0], eqn.params["y"]
            iv = None
            if a is not None and y >= 0:
                cands = [x ** y for x in a] + ([0] if a[0] < 0 < a[1] else [])
                iv = (min(cands), max(cands))
            outs = [_checked(ctx, eqn, iv, out_rngs[0], "integer_pow")]
        elif name == "shift_left":
            iv = _shift_candidates(ins[0], ins[1], lambda x, s: x << s)
            outs = [_checked(ctx, eqn, iv, out_rngs[0], "shift_left")]
        elif name in ("shift_right_logical", "shift_right_arithmetic"):
            a, s = ins[0], ins[1]
            if a is not None and s is not None and a[0] >= 0:
                outs = [(a[0] >> max(0, min(s[1], _SHIFT_CAP)),
                         a[1] >> max(0, min(s[0], _SHIFT_CAP)))]
            elif name == "shift_right_arithmetic":
                outs = [_shift_candidates(a, s, lambda x, sh: x >> sh)]
            else:
                outs = [out_rngs[0]]  # logical shift of a negative: bits
        elif name == "div":
            a, b = ins[0], ins[1]
            if a is None or b is None or b[0] <= 0 <= b[1]:
                outs = [out_rngs[0]]
            else:
                # truncation toward zero in exact integer arithmetic
                # (float division would round above 2^53)
                cands = [
                    -(-x // y) if (x < 0) != (y < 0) else x // y
                    for x in a for y in b
                ]
                outs = [(min(cands), max(cands))]
        elif name == "rem":
            a, b = ins[0], ins[1]
            if a is not None and b is not None and a[0] >= 0 and b[0] >= 1:
                outs = [(0, min(a[1], b[1] - 1))]
            else:
                outs = [out_rngs[0]]  # rem always fits its dtype
        elif name == "max":
            a, b = ins[0], ins[1]
            outs = [None if a is None or b is None
                    else (max(a[0], b[0]), max(a[1], b[1]))]
        elif name == "min":
            a, b = ins[0], ins[1]
            outs = [None if a is None or b is None
                    else (min(a[0], b[0]), min(a[1], b[1]))]
        elif name == "clamp":
            lo, x, hi = ins[0], ins[1], ins[2]
            if None in (lo, x, hi):
                outs = [None]
            else:
                outs = [(min(max(x[0], lo[0]), hi[0]),
                         min(max(x[1], lo[1]), hi[1]))]
        elif name in ("and", "or", "xor"):
            a, b = ins[0], ins[1]
            if a is None or b is None or a[0] < 0 or b[0] < 0:
                outs = [out_rngs[0]]  # bitwise never escapes its dtype
            elif name == "and":
                outs = [(0, min(a[1], b[1]))]
            else:
                outs = [_bitwidth_bound(a, b)]
        elif name == "not":
            outs = [out_rngs[0]]

        # ---- casts -----------------------------------------------------
        elif name == "convert_element_type":
            src = eqn.invars[0].aval.dtype
            iv, rng = ins[0], out_rngs[0]
            if (iv is not None and rng is not None
                    and np.dtype(src).kind in "iub"
                    and (iv[0] < rng[0] or iv[1] > rng[1])):
                ctx.flag(
                    "trunc-cast", eqn,
                    f"narrowing {src}->{eqn.outvars[0].aval.dtype}: source "
                    f"interval [{iv[0]}, {iv[1]}] does not fit "
                    f"[{rng[0]}, {rng[1]}] — values truncate/wrap",
                )
                outs = [rng]
            else:
                outs = [_clamp(iv, rng) if rng is not None else None]
        elif name == "bitcast_convert_type":
            outs = [out_rngs[0]]

        # ---- comparisons / structure ----------------------------------
        elif name in ("eq", "ne", "lt", "le", "gt", "ge", "is_finite"):
            if name != "is_finite":
                preds[eqn.outvars[0]] = (
                    name, resolve(eqn.invars[0]), resolve(eqn.invars[1])
                )
            outs = [(0, 1)]
        elif name == "select_n":
            pred = resolve(eqn.invars[0])
            rel = None if hasattr(pred, "val") else preds.get(pred)
            cases = eqn.invars[1:]
            # decidable predicate ⇒ one branch is dead and must not
            # pollute the union (jnp lowers x[i] with a signed index to
            # select(i < 0, i + n, i): for i provably >= 0 the i+n
            # branch is unreachable)
            decided = None
            if rel is not None and len(cases) == 2:
                decided = _decide(rel[0], read(rel[1]), read(rel[2]))
            iv = None
            for ci, case in enumerate(cases):
                if decided is not None and ci != int(decided):
                    continue
                civ = read(case)
                if rel is not None and len(cases) == 2:
                    # select_n(pred, on_false, on_true)
                    civ = narrow(case, civ, rel, truth=(ci == 1))
                iv = civ if iv is None else _join(iv, civ)
            outs = [iv if cases else out_rngs[0]]
        elif name in ("broadcast_in_dim", "reshape", "transpose", "squeeze",
                      "rev", "copy", "stop_gradient", "slice",
                      "expand_dims", "device_put", "reduce_precision",
                      "optimization_barrier"):
            if name in ("broadcast_in_dim", "reshape", "squeeze",
                        "expand_dims", "copy") and eqn.invars:
                src = resolve(eqn.invars[0])
                if not hasattr(src, "val") and src in preds:
                    preds[eqn.outvars[0]] = preds[src]
            outs = list(ins[: len(eqn.outvars)]) or [out_rngs[0]]
        elif name == "concatenate":
            iv = ins[0]
            for other in ins[1:]:
                iv = _join(iv, other)
            outs = [iv]
        elif name == "pad":
            outs = [_join(ins[0], ins[1])]
        elif name == "iota":
            dim = eqn.params["dimension"]
            n = eqn.outvars[0].aval.shape[dim]
            outs = [_checked(ctx, eqn, (0, max(0, n - 1)), out_rngs[0],
                             "iota")]
        elif name == "sort":
            outs = list(ins)

        # ---- reductions / scans over axes -----------------------------
        elif name in ("reduce_sum", "cumsum"):
            a = ins[0]
            if name == "reduce_sum":
                shape = eqn.invars[0].aval.shape
                n = 1
                for ax in eqn.params["axes"]:
                    n *= shape[ax]
            else:
                n = eqn.invars[0].aval.shape[eqn.params["axis"]]
            iv = None if a is None else (min(n * a[0], a[0]),
                                         max(n * a[1], a[1]))
            outs = [_checked(ctx, eqn, iv, out_rngs[0], f"{name}[n={n}]")]
        elif name in ("reduce_max", "reduce_min", "cummax", "cummin",
                      "reduce_and", "reduce_or"):
            outs = [ins[0]]
        elif name == "reduce_prod":
            outs = [out_rngs[0]]
        elif name in ("argmax", "argmin"):
            shape = eqn.invars[0].aval.shape
            hi = max(shape[ax] for ax in eqn.params["axes"]) - 1
            outs = [(0, max(0, hi))]

        # ---- memory ops: index checks ---------------------------------
        elif name == "gather" or name.startswith("scatter"):
            extent = _index_extent(eqn)
            idx = ins[1]
            if extent is not None and idx is not None and (
                    idx[0] < extent[0] or idx[1] > extent[1]):
                ctx.flag(
                    "oob-index", eqn,
                    f"index interval [{idx[0]}, {idx[1]}] can leave the "
                    f"axis extent [{extent[0]}, {extent[1]}] "
                    f"(operand {tuple(eqn.invars[0].aval.shape)}, "
                    f"indices {tuple(eqn.invars[1].aval.shape)}) — XLA "
                    "clamps, silently reading/writing the wrong row",
                )
            a, u = ins[0], (ins[2] if len(ins) > 2 else None)
            if name == "gather":
                outs = [ins[0]]
            elif name == "scatter":
                outs = [_join(a, u)]
            elif name == "scatter-min" and a is not None and u is not None:
                # each element is op or min(op, some updates)
                outs = [(min(a[0], u[0]), a[1])]
            elif name == "scatter-max" and a is not None and u is not None:
                outs = [(a[0], max(a[1], u[1]))]
            elif name == "scatter-add" and a is not None and u is not None:
                # worst case every update lands on one element
                n = 1
                for d in eqn.invars[2].aval.shape:
                    n *= d
                iv = (a[0] + min(0, n * u[0]), a[1] + max(0, n * u[1]))
                outs = [_checked(ctx, eqn, iv, out_rngs[0],
                                 f"scatter-add[n={n}]")]
            else:  # scatter-mul and friends: full range, quiet
                outs = [out_rngs[0]]
        elif name == "dynamic_slice":
            op_shape = eqn.invars[0].aval.shape
            sizes = eqn.params["slice_sizes"]
            for d, start in enumerate(ins[1:]):
                hi = op_shape[d] - sizes[d]
                if start is not None and (start[0] < 0 or start[1] > hi):
                    ctx.flag(
                        "oob-index", eqn,
                        f"slice start (dim {d}) interval "
                        f"[{start[0]}, {start[1]}] can leave [0, {hi}] — "
                        "XLA clamps, silently reading the wrong window",
                    )
            outs = [ins[0]]
        elif name == "dynamic_update_slice":
            op_shape = eqn.invars[0].aval.shape
            upd_shape = eqn.invars[1].aval.shape
            for d, start in enumerate(ins[2:]):
                hi = op_shape[d] - upd_shape[d]
                if start is not None and (start[0] < 0 or start[1] > hi):
                    ctx.flag(
                        "oob-index", eqn,
                        f"update start (dim {d}) interval "
                        f"[{start[0]}, {start[1]}] can leave [0, {hi}] — "
                        "XLA clamps, silently writing the wrong window",
                    )
            outs = [_join(ins[0], ins[1])]

        # ---- control flow ---------------------------------------------
        elif name == "cond":
            bouts = None
            for br in eqn.params["branches"]:
                res = _propagate(br, ins[1:], ctx)
                bouts = res if bouts is None else [
                    _join(a, b) for a, b in zip(bouts, res)
                ]
            outs = bouts or []
        elif name == "while":
            ncc = eqn.params["cond_nconsts"]
            nbc = eqn.params["body_nconsts"]
            cond_c, body_c = ins[:ncc], ins[ncc:ncc + nbc]
            carry = list(ins[ncc + nbc:])
            body_vars = eqn.params["body_jaxpr"].jaxpr.invars[nbc:]
            for _ in range(3):
                nxt = _propagate(eqn.params["body_jaxpr"], body_c + carry, ctx)
                merged = [_join(a, b) for a, b in zip(carry, nxt)]
                if merged == carry:
                    break
                carry = merged
            else:
                # no fixpoint in 3 joins: the carry is unbounded by the
                # loop itself — widen to the lane and re-walk (in-body
                # ops past the lane get flagged there)
                carry = [dtype_range(v.aval.dtype) for v in body_vars]
                _propagate(eqn.params["body_jaxpr"], body_c + carry, ctx)
            _propagate(eqn.params["cond_jaxpr"], cond_c + carry, ctx)
            outs = carry
        elif name == "scan":
            outs = _scan_transfer(eqn, ins, ctx)

        # ---- mesh collectives -----------------------------------------
        elif name == "axis_index":
            # a chip's coordinate along a shard_map mesh axis: exactly
            # [0, axis_size - 1]. Without this the owner-base arithmetic
            # in parallel/mesh.py (_path_gather/_path_scatter:
            # axis_index * n_local) degrades to full-u32 and every
            # downstream add/mul reads as a wrap.
            ax = ctx.axis_sizes.get(str(eqn.params.get("axis_name")))
            outs = [(0, ax - 1)] if ax else [out_rngs[0]]

        # ---- nesting / default ----------------------------------------
        else:
            subs = list(_sub_jaxprs(eqn))
            if subs:
                mesh = eqn.params.get("mesh")
                if mesh is not None and hasattr(mesh, "shape"):
                    # shard_map boundary: record axis sizes so inner
                    # axis_index eqns get their exact interval
                    try:
                        ctx.axis_sizes.update(
                            {str(k): int(v)
                             for k, v in dict(mesh.shape).items()}
                        )
                    except (TypeError, ValueError):  # pragma: no cover
                        pass
                outs = None
                for sub in subs:
                    n_in = len(getattr(sub, "jaxpr", sub).invars)
                    if n_in == len(ins):
                        # pjit-style body: alias invars to our atoms so
                        # value AND provenance flow through
                        souts = _propagate(
                            sub, None, ctx, in_atoms=list(eqn.invars)
                        )
                    else:
                        souts = _propagate(sub, [None] * n_in, ctx)
                    outs = souts if outs is None else [
                        _join(a, b) for a, b in zip(outs, souts)
                    ]
                if len(outs or []) != len(eqn.outvars):
                    outs = out_rngs
            else:
                # unknown primitive (PRNG cores, callbacks, custom
                # kernels): full lane range — sound and quiet
                outs = out_rngs

        for var, iv in zip(eqn.outvars, outs):
            write(var, iv)
    return [read(v) for v in jaxpr.outvars]


def _scan_transfer(eqn, ins: list, ctx: _Ctx) -> list:
    """Scan carry fixpoint with affine widening over the trip count.

    One body pass measures per-iteration growth; affine growth is
    extrapolated over ``length`` iterations (so a counter adding at most
    g per chunk certifies at carry0 + length·g, exactly); accelerating
    growth widens to the lane.  A carry whose extrapolated interval
    escapes its dtype is itself an ``overflow`` finding at the scan
    site — the "unbounded scan counter" class."""
    p = eqn.params
    nc, ncar = p["num_consts"], p["num_carry"]
    length = p["length"]
    consts, carry0 = ins[:nc], list(ins[nc:nc + ncar])
    xs = ins[nc + ncar:]
    carry_vars = p["jaxpr"].jaxpr.invars[nc:nc + ncar]

    res = _propagate(p["jaxpr"], consts + carry0 + xs, ctx)
    nxt = res[:ncar]
    joined = [_join(a, b) for a, b in zip(carry0, nxt)]
    if joined == carry0:
        return carry0 + res[ncar:]

    # second pass from the join: growth per iteration, and whether the
    # growth itself is stable (affine) or accelerating
    res2 = _propagate(p["jaxpr"], consts + joined + xs, ctx)
    nxt2 = res2[:ncar]
    carry_fix: list = []
    growths: list = []
    for c0, j, n2, var in zip(carry0, joined, nxt2, carry_vars):
        rng = dtype_range(var.aval.dtype)
        if j is None or n2 is None:
            carry_fix.append(rng)
            growths.append(None)
            continue
        g_hi = max(0, n2[1] - j[1])
        g_lo = max(0, j[0] - n2[0])
        first_hi = 0 if c0 is None else max(0, j[1] - c0[1])
        first_lo = 0 if c0 is None else max(0, c0[0] - j[0])
        if g_hi > first_hi or g_lo > first_lo:
            # accelerating (e.g. doubling): no affine bound — the lane
            carry_fix.append(rng)
            growths.append(None)
            continue
        ext = (j[0] - length * g_lo, j[1] + length * g_hi)
        if rng is not None and (ext[0] < rng[0] or ext[1] > rng[1]):
            ctx.flag(
                "overflow", eqn,
                f"scan carry grows to [{ext[0]}, {ext[1]}] over "
                f"{length} iterations, escaping {var.aval.dtype} "
                f"[{rng[0]}, {rng[1]}] — an unbounded counter at this "
                "geometry",
            )
        carry_fix.append(_clamp(ext, rng) if rng is not None else ext)
        growths.append((g_lo, g_hi))
    # verification pass: the extrapolation is only sound if it is
    # INDUCTIVE — growth measured FROM the extrapolated carry must not
    # exceed the rate measured near carry0. A carry-derived increment
    # (c + (c >> 10): exponential, but flat across two narrow passes)
    # fails this and widens to the lane, so the wrap gets flagged
    # inside the body instead of certified away.
    res3 = _propagate(p["jaxpr"], consts + carry_fix + xs, ctx)
    nxt3 = res3[:ncar]
    widened = False
    final: list = []
    for cf, n3, g, var in zip(carry_fix, nxt3, growths, carry_vars):
        rng = dtype_range(var.aval.dtype)
        if g is None or cf is None or n3 is None:
            final.append(cf)
            continue
        g_lo2 = max(0, cf[0] - n3[0])
        g_hi2 = max(0, n3[1] - cf[1])
        if g_hi2 > g[1] or g_lo2 > g[0]:
            final.append(rng)
            widened = True
        else:
            final.append(cf)
    if widened:
        res3 = _propagate(p["jaxpr"], consts + final + xs, ctx)
    return final + res3[ncar:]


def _path_str(path) -> str:
    parts = []
    for k in path:
        if hasattr(k, "name"):
            parts.append(str(k.name))
        elif hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:  # pragma: no cover - future key types
            parts.append(str(k))
    return ".".join(parts)


def _bound_for(label: str, bounds: dict) -> Iv:
    """Longest declared dotted-prefix match wins; None = lane default."""
    best = None
    best_len = -1
    for prefix, iv in bounds.items():
        if (label == prefix or label.startswith(prefix + ".")) \
                and len(prefix) > best_len:
            best, best_len = iv, len(prefix)
    return best


def analyze_ranges(
    fn: Callable,
    args: dict,
    bounds: "dict | None" = None,
    allowlist: Iterable = (),
    name: str = "program",
) -> RangeReport:
    """Trace ``fn(*args.values())`` and interval-check the closed jaxpr.

    ``args`` maps argument name -> example value (ShapeDtypeStructs or
    pytrees of them).  ``bounds`` maps dotted label prefixes over those
    names (``"idxs"``, ``"state.rec.posmap"``) to declared ``(lo, hi)``
    input intervals — the RANGELINT_BOUNDS anchors; undeclared leaves
    default to their full lane range (sound: certify what you declare).

    A geometry that cannot even trace (a construction-time guard fired,
    a numpy conversion refused an out-of-range literal) is converted
    into a ``trace-abort`` finding rather than crashing the audit."""
    import jax
    from jax import tree_util as jtu

    bounds = dict(bounds or {})
    ctx = _Ctx(allowlist)
    values = list(args.values())
    try:
        closed = jax.make_jaxpr(fn)(*values)
    except (OverflowError, ValueError) as exc:
        f = RangeFinding(
            kind="trace-abort", site=name, prim="",
            message=(
                "tracing aborted before any device op: "
                f"{type(exc).__name__}: {exc}"
            ),
        )
        return RangeReport(name, [f], {}, {})

    in_ivs: list = []
    for argname, val in args.items():
        for path, leaf in jtu.tree_flatten_with_path(val)[0]:
            sub = _path_str(path)
            label = f"{argname}.{sub}" if sub else argname
            declared = _bound_for(label, bounds)
            if declared is not None:
                in_ivs.append((int(declared[0]), int(declared[1])))
            else:
                in_ivs.append(dtype_range(leaf.dtype))
    if len(in_ivs) != len(closed.jaxpr.invars):
        raise ValueError(
            f"rangelint: {len(in_ivs)} flattened args vs "
            f"{len(closed.jaxpr.invars)} jaxpr invars — static/implicit "
            "arguments must be closed over, not passed"
        )
    _propagate(closed, in_ivs, ctx)
    return RangeReport(
        name=name,
        findings=sorted(
            ctx.findings.values(), key=lambda f: (f.site, f.kind, f.prim)
        ),
        allowed=dict(ctx.allowed),
        census=dict(census(closed)),
        n_eqns=sum(1 for _ in walk_eqns(closed)),
    )
