"""Stdlib fallback for the ruff tier-1 gate: unused-import lint (F401).

The repo pins ruff's pyflakes/import tier in pyproject.toml
(``[tool.ruff] select = ["E4", "E7", "E9", "F"]``) and tier-1 runs
``ruff check`` wherever the binary exists (tests/test_lint.py). This
container image has no ruff wheel and the build bakes its dependencies,
so the gate needs always-on teeth that never install anything: an AST
unused-import check — the F401 subset, plus the E9 subset for free
(``ast.parse`` failing IS a syntax error).

Deliberately conservative: a name counts as *used* if its identifier
token appears anywhere else in the file outside the import statement's
own line (string annotations, docstring'd doctests, ``__all__``,
getattr strings all count). That under-reports, never false-positives —
the right polarity for a merge gate. ``__init__.py`` re-exports are
exempt (mirroring the pyproject per-file-ignores), as is anything with
a ``# noqa`` on the import line.
"""

from __future__ import annotations

import ast
import os
import re


def _binding_names(node) -> list:
    """(bound_name, display) pairs for an import statement."""
    out = []
    if isinstance(node, ast.Import):
        for a in node.names:
            bound = a.asname or a.name.split(".")[0]
            out.append((bound, a.name))
    elif isinstance(node, ast.ImportFrom):
        if node.module == "__future__":
            return []
        for a in node.names:
            if a.name == "*":
                continue
            out.append((a.asname or a.name, a.name))
    return out


def check_source(src: str, filename: str = "<src>") -> list:
    """Unused-import findings for one file: (line, name, message)."""
    try:
        tree = ast.parse(src, filename=filename)
    except SyntaxError as exc:
        return [(exc.lineno or 0, "<syntax>", f"syntax error: {exc.msg}")]
    lines = src.splitlines()
    findings = []
    imports = []  # (lineno, end_lineno, bound, display)
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for bound, display in _binding_names(node):
                imports.append(
                    (node.lineno, node.end_lineno or node.lineno,
                     bound, display)
                )
    for lineno, end_lineno, bound, display in imports:
        if any(
            "noqa" in lines[i - 1]
            for i in range(lineno, min(end_lineno, len(lines)) + 1)
        ):
            continue
        if bound == "_":
            continue
        pat = re.compile(rf"\b{re.escape(bound)}\b")
        used = False
        for i, line in enumerate(lines, start=1):
            if lineno <= i <= end_lineno:
                continue
            if pat.search(line):
                used = True
                break
        if not used:
            findings.append(
                (lineno, bound,
                 f"F401 {display!r} imported but unused")
            )
    return findings


def check_tree(root: str, skip_init: bool = True) -> dict:
    """Lint every .py under ``root``; returns {relpath: findings}."""
    out = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [
            d for d in dirnames
            if d not in ("__pycache__", ".git", ".claude")
        ]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            if skip_init and fn == "__init__.py":
                continue
            path = os.path.join(dirpath, fn)
            with open(path, encoding="utf-8") as fh:
                findings = check_source(fh.read(), filename=path)
            if findings:
                out[os.path.relpath(path, root)] = findings
    return out


def main(argv=None) -> int:
    roots = argv if argv else [
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ]
    n = 0
    for root in roots:
        for rel, findings in sorted(check_tree(root).items()):
            for lineno, _name, msg in findings:
                print(f"{os.path.join(root, rel)}:{lineno}: {msg}")
                n += 1
    print(f"[importlint] {n} finding(s)")
    return 1 if n else 0


if __name__ == "__main__":
    import sys

    sys.exit(main(sys.argv[1:]))
