"""Stdlib fallback for the ruff tier-1 gate: F401 + F841 + E722 (+ E9).

The repo pins ruff's pyflakes/import tier in pyproject.toml
(``[tool.ruff] select = ["E4", "E7", "E9", "F"]``) and tier-1 runs
``ruff check`` wherever the binary exists (tests/test_lint.py). This
container image has no ruff wheel and the build bakes its dependencies,
so the gate needs always-on teeth that never install anything — an AST
checker for the subsets that matter and never false-positive:

- **F401** unused import (the original check);
- **F841** unused local variable — simple ``name = value`` bindings
  (and ``except ... as name`` handlers) whose name is never read
  anywhere in the enclosing function, skipping underscore names,
  augmented/annotated/tuple targets, declared globals/nonlocals, and
  any function that touches ``locals()``/``eval``/``exec``;
- **E722** bare ``except:`` — swallows ``KeyboardInterrupt`` and
  ``SystemExit``; name the exception (``except Exception:`` at
  minimum);
- **E9** for free (``ast.parse`` failing IS a syntax error).

Deliberately conservative throughout: for F401 a name counts as *used*
if its identifier token appears anywhere else in the file outside the
import statement's own line (string annotations, docstring'd doctests,
``__all__``, getattr strings all count). That under-reports, never
false-positives — the right polarity for a merge gate. ``__init__.py``
re-exports are exempt (mirroring the pyproject per-file-ignores), as is
any line carrying ``# noqa``.
"""

from __future__ import annotations

import ast
import os
import re


def _binding_names(node) -> list:
    """(bound_name, display) pairs for an import statement."""
    out = []
    if isinstance(node, ast.Import):
        for a in node.names:
            bound = a.asname or a.name.split(".")[0]
            out.append((bound, a.name))
    elif isinstance(node, ast.ImportFrom):
        if node.module == "__future__":
            return []
        for a in node.names:
            if a.name == "*":
                continue
            out.append((a.asname or a.name, a.name))
    return out


def _own_scope_stores(fn_node) -> list:
    """Simple-name Assign targets and ``except as`` names in THIS
    function's scope only — nested function/class scopes bind their own
    locals and are skipped."""
    out = []

    def visit(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue  # separate scope
            if isinstance(child, ast.Assign) and len(child.targets) == 1 \
                    and isinstance(child.targets[0], ast.Name):
                out.append((child.lineno, child.targets[0].id))
            elif isinstance(child, ast.ExceptHandler) and child.name:
                out.append((child.lineno, child.name))
            visit(child)

    visit(fn_node)
    return out


def _function_f841(fn_node, noqa_lines: set) -> list:
    """F841 findings for one function node (conservative, see module
    docstring): stores from this scope, loads from the whole subtree
    (closures in nested defs legitimately read enclosing locals)."""
    dynamic = False
    declared: set = set()
    loads: set = set()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name) and f.id in ("locals", "eval",
                                                    "exec", "vars"):
                dynamic = True
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            declared.update(node.names)
        elif isinstance(node, ast.AugAssign) and isinstance(
                node.target, ast.Name):
            # x += 1 reads AND writes x: the prior binding is required
            # (deleting it raises UnboundLocalError), so it counts as a
            # use — the never-false-positive polarity
            loads.add(node.target.id)
        elif isinstance(node, ast.Name) and not isinstance(
                node.ctx, ast.Store):
            loads.add(node.id)  # Load and Del both count as uses
    if dynamic:
        return []
    out = []
    seen: set = set()
    for lineno, name in _own_scope_stores(fn_node):
        if (name in loads or name in declared or name.startswith("_")
                or name in seen or lineno in noqa_lines):
            continue
        seen.add(name)
        out.append(
            (lineno, name,
             f"F841 local variable {name!r} is assigned to but never used")
        )
    return out


def check_source(src: str, filename: str = "<src>") -> list:
    """F401/F841/E722 findings for one file: (line, name, message)."""
    try:
        tree = ast.parse(src, filename=filename)
    except SyntaxError as exc:
        return [(exc.lineno or 0, "<syntax>", f"syntax error: {exc.msg}")]
    lines = src.splitlines()
    noqa_lines = {
        i for i, line in enumerate(lines, start=1) if "noqa" in line
    }
    findings = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None \
                and node.lineno not in noqa_lines:
            findings.append(
                (node.lineno, "<bare-except>",
                 "E722 bare 'except:' swallows KeyboardInterrupt/"
                 "SystemExit — name the exception class")
            )
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            findings.extend(_function_f841(node, noqa_lines))
    imports = []  # (lineno, end_lineno, bound, display)
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for bound, display in _binding_names(node):
                imports.append(
                    (node.lineno, node.end_lineno or node.lineno,
                     bound, display)
                )
    for lineno, end_lineno, bound, display in imports:
        if any(
            "noqa" in lines[i - 1]
            for i in range(lineno, min(end_lineno, len(lines)) + 1)
        ):
            continue
        if bound == "_":
            continue
        pat = re.compile(rf"\b{re.escape(bound)}\b")
        used = False
        for i, line in enumerate(lines, start=1):
            if lineno <= i <= end_lineno:
                continue
            if pat.search(line):
                used = True
                break
        if not used:
            findings.append(
                (lineno, bound,
                 f"F401 {display!r} imported but unused")
            )
    return findings


def check_tree(root: str, skip_init: bool = True) -> dict:
    """Lint every .py under ``root``; returns {relpath: findings}."""
    out = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [
            d for d in dirnames
            if d not in ("__pycache__", ".git", ".claude")
        ]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            if skip_init and fn == "__init__.py":
                continue
            path = os.path.join(dirpath, fn)
            with open(path, encoding="utf-8") as fh:
                findings = check_source(fh.read(), filename=path)
            if findings:
                out[os.path.relpath(path, root)] = findings
    return out


def main(argv=None) -> int:
    roots = argv if argv else [
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ]
    n = 0
    for root in roots:
        for rel, findings in sorted(check_tree(root).items()):
            for lineno, _name, msg in findings:
                print(f"{os.path.join(root, rel)}:{lineno}: {msg}")
                n += 1
    print(f"[importlint] {n} finding(s)")
    return 1 if n else 0


if __name__ == "__main__":
    import sys

    sys.exit(main(sys.argv[1:]))
