"""Seeded leaky mutants: the analyzer's positive controls.

Each mutant is a small traced program with ONE deliberate
access-pattern leak of a distinct class. The driver
(tools/check_oblivious.py) and tests/test_oblint.py run every mutant
through the SAME analyzer configuration as the production sweep —
production allowlist included — and require every one to FAIL. A mutant
that passes means the analyzer lost its teeth (or an allowlist entry
grew into a blanket permission), and the audit run itself errors out.

The six classes, per ISSUE 12: position-dependent branch, key-indexed
gather, data-dependent early exit, secret-shaped output, un-allowlisted
scatter, leaky debug print. A seventh (python-level branch) pins the
trace-abort path.
"""

from __future__ import annotations

from .oblint import analyze

#: every mutant: name -> (builder returning (fn, args, secrets),
#: expected violation kind)
_REGISTRY: dict = {}


def _mutant(name: str, kind: str):
    def deco(builder):
        _REGISTRY[name] = (builder, kind)
        return builder
    return deco


def _sds(*shape, dtype=None):
    import jax
    import numpy as np

    return jax.ShapeDtypeStruct(shape, dtype or np.uint32)


@_mutant("position_branch", "cond-predicate")
def _position_branch():
    """lax.cond on an ORAM position: the executed branch (and its
    device-time signature) reveals where the block lives."""
    import jax.numpy as jnp
    from jax import lax

    def fn(pos, table):
        return lax.cond(
            pos[0] > 7,
            lambda: jnp.sum(table),
            lambda: jnp.zeros((), table.dtype),
        )

    return fn, {"pos": _sds(4), "table": _sds(16)}, ("pos",)


@_mutant("key_indexed_gather", "gather-index")
def _key_indexed_gather():
    """A table read addressed by the recipient key — the classic
    access-pattern leak the whole ORAM exists to prevent."""
    def fn(key, table):
        return table[key % 16]  # vector index -> gather

    return fn, {"key": _sds(8), "table": _sds(16)}, ("key",)


@_mutant("data_dependent_early_exit", "while-predicate")
def _data_dependent_early_exit():
    """A while loop whose trip count depends on the secret: wall-clock
    (and transcript length) becomes a function of the data."""
    import jax.numpy as jnp
    from jax import lax

    def fn(secret):
        def cond(c):
            i, acc = c
            return i < secret[0]

        def body(c):
            i, acc = c
            return i + jnp.uint32(1), acc + i

        return lax.while_loop(
            cond, body, (jnp.uint32(0), jnp.uint32(0))
        )

    return fn, {"secret": _sds(4)}, ("secret",)


@_mutant("secret_shaped_output", "trace-dependence")
def _secret_shaped_output():
    """An output whose SHAPE is the secret (a result list sized by how
    many records matched). Cannot even trace — the analyzer converts
    the concretization abort into the finding."""
    import jax.numpy as jnp

    def fn(secret):
        n = int(secret[0])  # concretizes a traced value
        return jnp.zeros((n,), jnp.uint32)

    return fn, {"secret": _sds(4)}, ("secret",)


@_mutant("unallowlisted_scatter", "scatter-index")
def _unallowlisted_scatter():
    """A scatter targeted by a secret-derived index at a site no review
    ever admitted — the 'new private state without a proof' case the
    ROADMAP items 1-2 will create pressure for."""
    import jax.numpy as jnp

    def fn(secret, plane):
        return plane.at[secret[0] % 16].set(jnp.uint32(1))

    return fn, {"secret": _sds(4), "plane": _sds(16)}, ("secret",)


@_mutant("leaky_debug_print", "callback")
def _leaky_debug_print():
    """jax.debug.print of a secret: the host callback is an access
    pattern too — it reaches the operator's terminal and logs."""
    import jax

    def fn(secret, x):
        jax.debug.print("selected leaf {s}", s=secret[0])
        return x + 1

    return fn, {"secret": _sds(4), "x": _sds(8)}, ("secret",)


@_mutant("python_level_branch", "trace-dependence")
def _python_level_branch():
    """A host-Python `if` on a traced secret — different Python paths
    trace different programs; jax aborts, the analyzer reports."""
    import jax.numpy as jnp

    def fn(secret):
        if secret[0] > 3:  # TracerBoolConversionError
            return jnp.zeros((2,), jnp.uint32)
        return jnp.ones((2,), jnp.uint32)

    return fn, {"secret": _sds(4)}, ("secret",)


def mutant_names() -> tuple:
    return tuple(_REGISTRY)


def run_mutants(allowlist=()) -> dict:
    """Analyze every mutant under ``allowlist``; returns
    name -> (report, expected_kind, failed_as_expected)."""
    out = {}
    for name, (builder, kind) in _REGISTRY.items():
        fn, args, secrets = builder()
        rep = analyze(fn, args, secrets, allowlist=allowlist,
                      name=f"mutant/{name}")
        hit = any(v.kind == kind for v in rep.violations)
        out[name] = (rep, kind, hit)
    return out
