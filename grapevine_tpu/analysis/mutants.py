"""Seeded mutants: both analyzers' positive controls.

Each mutant is a small traced program with ONE deliberate defect of a
distinct class. The drivers (tools/check_oblivious.py,
tools/check_ranges.py) and the test suites run every mutant through the
SAME analyzer configuration as the production sweep — production
allowlists included — and require every one to FAIL. A mutant that
passes means an analyzer lost its teeth (or an allowlist entry grew
into a blanket permission), and the audit run itself errors out.

Obliviousness classes, per ISSUE 12: position-dependent branch,
key-indexed gather, data-dependent early exit, secret-shaped output,
un-allowlisted scatter, leaky debug print, python-level branch. An
eighth (flush-on-buffer-contents, ISSUE 15) pins the delayed-eviction
cadence — a flush gated on buffer occupancy instead of the round
counter must FAIL.

Overflow classes, per ISSUE 14 (``_RANGE_REGISTRY``, run through
analysis/rangelint.py): u32 leaf-arithmetic wrap, truncating cast,
off-by-one axis bound, unbounded scan counter, int32 byte-size
product — plus, per ISSUE 15, an eviction-buffer index overflow
(append cursor arithmetic that wraps past the buffer axis). One
shared runner (check_oblivious's mutant control) proves both
analyzers alive from a single tier-1 gate.
"""

from __future__ import annotations

from .oblint import analyze
from .rangelint import analyze_ranges

#: every mutant: name -> (builder returning (fn, args, secrets),
#: expected violation kind)
_REGISTRY: dict = {}


def _mutant(name: str, kind: str):
    def deco(builder):
        _REGISTRY[name] = (builder, kind)
        return builder
    return deco


def _sds(*shape, dtype=None):
    import jax
    import numpy as np

    return jax.ShapeDtypeStruct(shape, dtype or np.uint32)


@_mutant("position_branch", "cond-predicate")
def _position_branch():
    """lax.cond on an ORAM position: the executed branch (and its
    device-time signature) reveals where the block lives."""
    import jax.numpy as jnp
    from jax import lax

    def fn(pos, table):
        return lax.cond(
            pos[0] > 7,
            lambda: jnp.sum(table),
            lambda: jnp.zeros((), table.dtype),
        )

    return fn, {"pos": _sds(4), "table": _sds(16)}, ("pos",)


@_mutant("key_indexed_gather", "gather-index")
def _key_indexed_gather():
    """A table read addressed by the recipient key — the classic
    access-pattern leak the whole ORAM exists to prevent."""
    def fn(key, table):
        return table[key % 16]  # vector index -> gather

    return fn, {"key": _sds(8), "table": _sds(16)}, ("key",)


@_mutant("data_dependent_early_exit", "while-predicate")
def _data_dependent_early_exit():
    """A while loop whose trip count depends on the secret: wall-clock
    (and transcript length) becomes a function of the data."""
    import jax.numpy as jnp
    from jax import lax

    def fn(secret):
        def cond(c):
            i, acc = c
            return i < secret[0]

        def body(c):
            i, acc = c
            return i + jnp.uint32(1), acc + i

        return lax.while_loop(
            cond, body, (jnp.uint32(0), jnp.uint32(0))
        )

    return fn, {"secret": _sds(4)}, ("secret",)


@_mutant("secret_shaped_output", "trace-dependence")
def _secret_shaped_output():
    """An output whose SHAPE is the secret (a result list sized by how
    many records matched). Cannot even trace — the analyzer converts
    the concretization abort into the finding."""
    import jax.numpy as jnp

    def fn(secret):
        n = int(secret[0])  # concretizes a traced value
        return jnp.zeros((n,), jnp.uint32)

    return fn, {"secret": _sds(4)}, ("secret",)


@_mutant("unallowlisted_scatter", "scatter-index")
def _unallowlisted_scatter():
    """A scatter targeted by a secret-derived index at a site no review
    ever admitted — the 'new private state without a proof' case the
    ROADMAP items 1-2 will create pressure for."""
    import jax.numpy as jnp

    def fn(secret, plane):
        return plane.at[secret[0] % 16].set(jnp.uint32(1))

    return fn, {"secret": _sds(4), "plane": _sds(16)}, ("secret",)


@_mutant("leaky_debug_print", "callback")
def _leaky_debug_print():
    """jax.debug.print of a secret: the host callback is an access
    pattern too — it reaches the operator's terminal and logs."""
    import jax

    def fn(secret, x):
        jax.debug.print("selected leaf {s}", s=secret[0])
        return x + 1

    return fn, {"secret": _sds(4), "x": _sds(8)}, ("secret",)


@_mutant("flush_on_buffer_contents", "cond-predicate")
def _flush_on_buffer_contents():
    """The delayed-eviction failure mode (PR 15): a flush triggered by
    buffer *occupancy* instead of the round counter. Buffer contents are
    access-dependent (hot keys dedup to fewer live rows than cold
    scans), so an occupancy-gated write-back makes the flush *timing* a
    function of the workload — a recipient-dependent schedule. The
    production trigger is a pure round count (engine/batcher.py
    ``_flush_window_locked``); this mutant pins that an occupancy
    branch cannot slip in unflagged."""
    import jax.numpy as jnp
    from jax import lax

    def fn(ebuf_idx, tree):
        occupancy = jnp.sum(ebuf_idx != jnp.uint32(0xFFFFFFFF))
        return lax.cond(
            occupancy > 8,  # "buffer looks full: flush now"
            lambda: tree + jnp.uint32(1),  # the write-back
            lambda: tree,
        )

    return fn, {"ebuf_idx": _sds(16), "tree": _sds(32)}, ("ebuf_idx",)


@_mutant("adaptive_batch_from_contents", "cond-predicate")
def _adaptive_batch_from_contents():
    """The adaptive-batching failure mode (ISSUE 20): a collection
    window sized from queue *contents* instead of public aggregates.
    The production policy (server/adaptive.py) decides from the queue
    DEPTH, the arrival EWMA, and the SLO burn rates — counts and rates
    a passive /metrics observer already sees. This mutant threads the
    queued ops' payload bits into the window choice: op-mix-dependent
    round cadence, visible on the wire as a recipient-correlated
    dispatch schedule. Pins that a contents branch cannot slip into
    the window decision unflagged."""
    import jax.numpy as jnp
    from jax import lax

    def fn(payloads, wait):
        hot = jnp.sum(payloads & jnp.uint32(1))  # reads op contents
        return lax.cond(
            hot > 4,  # "queue looks pop-heavy: dispatch early"
            lambda: wait // jnp.uint32(2),
            lambda: wait,
        )

    return fn, {"payloads": _sds(16), "wait": _sds(1)}, ("payloads",)


@_mutant("python_level_branch", "trace-dependence")
def _python_level_branch():
    """A host-Python `if` on a traced secret — different Python paths
    trace different programs; jax aborts, the analyzer reports."""
    import jax.numpy as jnp

    def fn(secret):
        if secret[0] > 3:  # TracerBoolConversionError
            return jnp.zeros((2,), jnp.uint32)
        return jnp.ones((2,), jnp.uint32)

    return fn, {"secret": _sds(4)}, ("secret",)


# ----------------------------------------------------------------------
# overflow mutants (ISSUE 14): each one deliberate lane escape of a
# distinct class, analyzed by rangelint under the PRODUCTION range
# allowlist — none of whose mod-2^32 arguments may cover these sites
# ----------------------------------------------------------------------

#: name -> (builder returning (fn, args, bounds), expected finding kind)
_RANGE_REGISTRY: dict = {}


def _range_mutant(name: str, kind: str):
    def deco(builder):
        _RANGE_REGISTRY[name] = (builder, kind)
        return builder
    return deco


@_range_mutant("u32_leaf_arith_wrap", "overflow")
def _u32_leaf_arith_wrap():
    """Heap-bucket-id arithmetic one recursion level past the certified
    geometry: (2^31 - 1) + 4·leaf at 2^30 leaves silently wraps the u32
    lane — the exact class the 2^36 design point walks into."""
    import jax.numpy as jnp

    U32 = jnp.uint32

    def fn(leaf):
        return (U32(1) << U32(31)) - U32(1) + leaf * U32(4)

    return fn, {"leaf": _sds(8)}, {"leaf": (0, (1 << 30) - 1)}


@_range_mutant("truncating_cast", "trunc-cast")
def _truncating_cast():
    """An unbounded u32 value narrowed to the int32 index lane: every
    value >= 2^31 goes negative on the way into whatever it indexes."""
    import jax.numpy as jnp

    def fn(x):
        return x.astype(jnp.int32)

    return fn, {"x": _sds(8)}, {}


@_range_mutant("off_by_one_axis_bound", "oob-index")
def _off_by_one_axis_bound():
    """A gather whose declared index bound equals the axis extent — the
    classic <= vs < slip. XLA clamps the overrun onto the last row, so
    the program 'works' while reading the wrong data."""
    def fn(idx, table):
        return table[idx]

    return fn, {"idx": _sds(4), "table": _sds(16)}, {"idx": (0, 16)}


@_range_mutant("unbounded_scan_counter", "overflow")
def _unbounded_scan_counter():
    """A u32 accumulator gaining up to 2^16 per iteration over a 2^20-
    step scan: fine for any single step, 2^36 by the end of the run —
    only the carry fixpoint's trip-count extrapolation can see it."""
    import jax
    import jax.numpy as jnp

    U32 = jnp.uint32

    def fn(inc):
        def body(c, x):
            return c + inc[0], x

        return jax.lax.scan(body, U32(0), jnp.zeros((1 << 20,), U32))

    return fn, {"inc": _sds(2)}, {"inc": (0, 1 << 16)}


@_range_mutant("ebuf_index_overflow", "overflow")
def _ebuf_index_overflow():
    """The delayed-eviction (ISSUE 15) buffer-cursor failure mode: an
    append position computed as ``rounds · window_paths`` without the
    window-invariant reset — at a 2^20-slot ledger a u32 round counter
    that never resets walks the product past 2^32 and the append cursor
    wraps to the front of the buffer, silently overwriting live rows.
    The production program resets ``ebuf_rounds`` at every flush and
    declares its [0, W] budget (path_oram.RANGELINT_BOUNDS); this
    mutant drops the reset so rangelint must see the escape."""
    import jax.numpy as jnp

    U32 = jnp.uint32

    def fn(rounds, leaves):
        base = rounds[0] * U32(1 << 20)  # unreset counter × window rows
        return base + leaves

    return fn, {"rounds": _sds(2), "leaves": _sds(8)}, {
        # the counter bound a missing reset actually leaves you with:
        # monotone across the run, not the declared [0, W] window
        "rounds": (0, (1 << 32) - (1 << 16)),
        "leaves": (0, (1 << 14) - 1),
    }


@_range_mutant("int32_byte_size_product", "overflow")
def _int32_byte_size_product():
    """A byte-length product computed in int32: 2^20 rows of a 4 KiB
    bucket row is 2^32 bytes — positive sizes multiply into a negative
    length."""
    import jax.numpy as jnp

    def fn(rows):
        return rows.astype(jnp.int32) * jnp.int32(4096)

    return fn, {"rows": _sds(4)}, {"rows": (0, 1 << 20)}


def mutant_names() -> tuple:
    return tuple(_REGISTRY)


def range_mutant_names() -> tuple:
    return tuple(_RANGE_REGISTRY)


def run_range_mutants(allowlist=()) -> dict:
    """Analyze every overflow mutant under ``allowlist``; returns
    name -> (report, expected_kind, failed_as_expected)."""
    out = {}
    for name, (builder, kind) in _RANGE_REGISTRY.items():
        fn, args, bounds = builder()
        rep = analyze_ranges(fn, args, bounds, allowlist=allowlist,
                             name=f"range_mutant/{name}")
        hit = any(f.kind == kind for f in rep.findings)
        out[name] = (rep, kind, hit)
    return out


def control_failures(results: dict, flavor: str, log=print) -> list:
    """Shared mutant-control reporting for both drivers
    (tools/check_oblivious.py, tools/check_ranges.py): print one status
    line per mutant via ``log`` and return the not-caught failures.
    ``flavor`` labels the mutant class (e.g. "mutant", "range mutant");
    works over both report shapes (oblint ``violations``, rangelint
    ``findings``)."""
    failures = []
    for name, (rep, kind, hit) in results.items():
        status = "FAIL (expected)" if hit else "PASSED — NO TEETH"
        log(f"{flavor} {name}: {status}")
        if not hit:
            got = [
                v.kind for v in getattr(rep, "violations", None)
                or getattr(rep, "findings", [])
            ]
            failures.append(
                f"{flavor} {name!r} was NOT caught (expected a {kind}; "
                f"got {got})"
            )
    return failures


def run_mutants(allowlist=()) -> dict:
    """Analyze every mutant under ``allowlist``; returns
    name -> (report, expected_kind, failed_as_expected)."""
    out = {}
    for name, (builder, kind) in _REGISTRY.items():
        fn, args, secrets = builder()
        rep = analyze(fn, args, secrets, allowlist=allowlist,
                      name=f"mutant/{name}")
        hit = any(v.kind == kind for v in rep.violations)
        out[name] = (rep, kind, hit)
    return out
