"""Shared jaxpr-walking core for every obliviousness audit in the repo.

Before ISSUE 12 the equation walk, the primitive census, and the
HBM-plane row accounting each lived as private copies inside
tools/check_posmap_oblivious.py and tools/check_tree_cache_oblivious.py
(the PR-3/5/7/8 audit lineage). They are one implementation here so the
legacy gates and the taint analyzer (:mod:`.oblint`) see the identical
equation stream — a sub-jaxpr a census misses is a sub-jaxpr the taint
walk misses, and that class of drift is exactly what a unified analyzer
exists to kill.
"""

from __future__ import annotations

from collections import Counter

#: primitives that move data between HBM arrays — the access schedule
#: the transcript argument is about (superset of both legacy tools')
ACCESS_PRIMS = ("gather", "scatter", "scatter-add", "scatter-mul",
                "scatter-min", "scatter-max", "dynamic_slice",
                "dynamic_update_slice")
#: data-dependent control flow: forbidden anywhere in a traced round
CONTROL_PRIMS = ("cond", "while")


def _sub_jaxprs(eqn):
    """Every jaxpr-valued param of ``eqn`` (pjit bodies, scan/while/cond
    branches, custom-call wrappers), in a stable order."""
    for v in eqn.params.values():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for x in vs:
            if hasattr(x, "eqns") or hasattr(x, "jaxpr"):
                yield x


def walk_eqns(jaxpr):
    """Yield every equation, recursing into every sub-jaxpr."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in inner.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from walk_eqns(sub)


def census(jaxpr) -> Counter:
    """Primitive-name counts over a (closed) jaxpr, recursively."""
    return Counter(eqn.primitive.name for eqn in walk_eqns(jaxpr))


def site_of(eqn, pkg: str = "grapevine_tpu") -> str:
    """Stable source-site key for an equation: ``file.py:function`` of
    the innermost user frame (preferring frames inside ``pkg``).

    The allowlist (:mod:`.allowlist`) is keyed on these, so the key must
    survive line churn: function granularity, no line numbers. Returns
    ``"<unknown>"`` when the trace carries no usable frames (e.g. a
    jaxpr rebuilt without source info)."""
    tb = getattr(eqn.source_info, "traceback", None)
    frames = list(tb.frames) if tb is not None else []
    best = None
    for fr in frames:
        fn = fr.file_name.replace("\\", "/")
        if fn.endswith("analysis/oblint.py") or fn.endswith("analysis/rangelint.py"):
            continue  # an analyzer's own make_jaxpr frame, never a site
        if f"/{pkg}/" in fn or fn.startswith(f"{pkg}/"):
            tail = fn.split(f"{pkg}/")[-1]
            return f"{tail}:{fr.function_name}"
        if best is None and "site-packages" not in fn and "/jax/" not in fn \
                and not fn.endswith("/jax.py"):
            best = f"{fn.rsplit('/', 1)[-1]}:{fr.function_name}"
    return best or "<unknown>"


def plane_rows(jaxpr, planes: dict) -> dict:
    """Rows moved per named array plane by every gather/scatter in the
    traced program.

    ``planes`` maps name -> ``(shape, divisor)``: an operand whose aval
    shape equals ``shape`` is attributed to that plane; the moved leading
    dim is divided by ``divisor`` (flat slot planes report slots/Z). A
    gather's row count is its output leading dim; a scatter's is its
    updates leading dim — exactly the tree-cache tool's accounting,
    generalized so any audit can declare its own planes."""
    out: dict[str, list] = {k: [] for k in planes}
    for eqn in walk_eqns(jaxpr):
        name = eqn.primitive.name
        if not name.startswith("scatter") and name != "gather":
            continue
        op_shape = tuple(eqn.invars[0].aval.shape)
        moved = (
            eqn.outvars[0].aval.shape
            if name == "gather"
            else eqn.invars[2].aval.shape
        )
        for pname, (pshape, div) in planes.items():
            if op_shape == tuple(pshape):
                rows = (moved[0] if moved else 0) // div
                out[pname].append((name, rows))
    return out
