"""Protocol constants for the grapevine wire format.

These pin the byte-level contract of the reference implementation:

- record geometry: 1024 bytes = id 16 | sender 32 | recipient 32 |
  timestamp 8 | payload 936  (reference README.md:132-136, types/src/lib.rs:150)
- request/status enums (reference grapevine.proto:44-55,178-197 and
  types/src/lib.rs:16-22,123-137)
- the challenge-signature signing context (reference types/src/lib.rs:13)
- per-recipient in-flight cap of 62 messages (reference README.md:78-80) —
  a compile-time constant in the reference; here a module constant that the
  engine config must honor.
"""

# --- Record geometry (bytes) ---------------------------------------------
import os as _os

MSG_ID_SIZE = 16
PUBKEY_SIZE = 32  # compressed ristretto point
TIMESTAMP_SIZE = 8  # u64 LE seconds since unix epoch
#: the reference's compile-time record-size option: 1024 (default) or
#: 2048 bytes (reference README.md:138-139 — "a compile time option to
#: configure this to 2048"). Same mechanism here: a process-wide
#: constant fixed before import (env GRAPEVINE_RECORD_SIZE); every
#: layout below derives from it, and mixed-size processes are
#: impossible by construction, exactly like the reference's rebuild.
RECORD_SIZE = int(_os.environ.get("GRAPEVINE_RECORD_SIZE", "1024"))
if RECORD_SIZE not in (1024, 2048):
    raise ValueError(
        f"GRAPEVINE_RECORD_SIZE must be 1024 or 2048, got {RECORD_SIZE}"
    )
PAYLOAD_SIZE = RECORD_SIZE - (MSG_ID_SIZE + 2 * PUBKEY_SIZE + TIMESTAMP_SIZE)
assert PAYLOAD_SIZE in (936, 1960)

SIGNATURE_SIZE = 64  # ristretto Schnorr signature (reference types/src/lib.rs:44-52)
CHALLENGE_SIZE = 32  # bytes drawn from the challenge RNG per request
CHALLENGE_SEED_SIZE = 32  # ChaCha20 seed returned by Auth (grapevine.proto:20-25)

# --- Signing context (reference types/src/lib.rs:13) ---------------------
GRAPEVINE_CHALLENGE_SIGNING_CONTEXT = b"grapevine-challenge"

# --- RequestType enum (reference grapevine.proto:44-55) ------------------
REQUEST_TYPE_INVALID = 0  # unused; proto requires a zero value
REQUEST_TYPE_CREATE = 1
REQUEST_TYPE_READ = 2
REQUEST_TYPE_UPDATE = 3
REQUEST_TYPE_DELETE = 4

# --- StatusCode enum (reference grapevine.proto:178-197) -----------------
STATUS_CODE_INVALID = 0  # unused; proto requires a zero value
STATUS_CODE_SUCCESS = 1
STATUS_CODE_NOT_FOUND = 2
STATUS_CODE_MESSAGE_ID_ALREADY_IN_USE = 3
STATUS_CODE_INVALID_RECIPIENT = 4
STATUS_CODE_TOO_MANY_MESSAGES_FOR_RECIPIENT = 5
STATUS_CODE_TOO_MANY_RECIPIENTS = 6
STATUS_CODE_TOO_MANY_MESSAGES = 7
STATUS_CODE_INTERNAL_ERROR = 8

# --- Capacity invariants (reference README.md:78-80) ---------------------
MAILBOX_CAP = 62  # max in-flight messages per recipient

# --- Fixed-layout (non-protobuf) encoded sizes ---------------------------
# The inner, channel-encrypted codec used by this framework is a raw fixed
# layout (see wire/records.py). Sizes are constant by construction.
REQUEST_RECORD_WIRE_SIZE = MSG_ID_SIZE + PUBKEY_SIZE + PAYLOAD_SIZE  # 984 @1KB
QUERY_REQUEST_WIRE_SIZE = 4 + PUBKEY_SIZE + SIGNATURE_SIZE + REQUEST_RECORD_WIRE_SIZE  # 1084 @1KB
QUERY_RESPONSE_WIRE_SIZE = RECORD_SIZE + 4  # 1028 @1KB

ZERO_MSG_ID = b"\x00" * MSG_ID_SIZE
ZERO_PUBKEY = b"\x00" * PUBKEY_SIZE
