"""Minimal protobuf wire codec for the grapevine message set.

The reference keeps two parallel type stacks — prost structs
(types/src/lib.rs) and protobuf-codegen structs (api/ crate) — and tests
that they agree byte-for-byte (reference api/tests/grapevine_types.rs).
This module is our second stack: a hand-rolled encoder/decoder emitting
protobuf wire format with the reference's exact field numbers and types
(reference grapevine.proto:123-176), kept deliberately tiny so there is no
protoc build dependency. Conformance tests assert it round-trips against
the fixed-layout codec in :mod:`grapevine_tpu.wire.records` and that valid
messages encode at constant size.

Encoding follows prost emission rules:
- scalar fields are omitted when zero; bytes fields are omitted when empty
  (valid grapevine messages always carry full-length bytes and the engine
  guarantees a nonzero response timestamp, so sizes stay constant);
- ``request_type`` / ``status_code`` are fixed32, not varint enums — the
  reference does this explicitly "to avoid information leakage from
  protobuf compression" (reference grapevine.proto:40-43);
- ``timestamp`` is fixed64 for the same reason;
- fields are written in ascending field-number order.

Also defines the outer transport messages carried on the (unencrypted)
gRPC surface, mirroring the attest message shapes the reference imports
from mc-attest-api (reference grapevine.proto:8,10-36): ``AuthMessage``,
``Message`` (aad / channel_id / data) and ``AuthMessageWithChallengeSeed``.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from . import records as R

_WT_VARINT = 0
_WT_FIXED64 = 1
_WT_LEN = 2
_WT_FIXED32 = 5


def _varint(value: int) -> bytes:
    out = bytearray()
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _read_varint(data: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if shift >= 64:
            raise ValueError("varint too long")
        if pos >= len(data):
            raise ValueError("truncated varint")
        byte = data[pos]
        pos += 1
        result |= (byte & 0x7F) << shift
        if not byte & 0x80:
            if result >= 1 << 64:
                raise ValueError("varint exceeds u64")
            return result, pos
        shift += 7


def _tag(field_no: int, wire_type: int) -> bytes:
    return _varint((field_no << 3) | wire_type)


def _emit_bytes(field_no: int, value: bytes) -> bytes:
    if not value:
        return b""
    return _tag(field_no, _WT_LEN) + _varint(len(value)) + value


def _emit_fixed32(field_no: int, value: int) -> bytes:
    if value == 0:
        return b""
    return _tag(field_no, _WT_FIXED32) + int(value).to_bytes(4, "little")


def _emit_fixed64(field_no: int, value: int) -> bytes:
    if value == 0:
        return b""
    return _tag(field_no, _WT_FIXED64) + int(value).to_bytes(8, "little")


def _emit_message(field_no: int, payload: bytes) -> bytes:
    # required submessages are always emitted, even when empty
    return _tag(field_no, _WT_LEN) + _varint(len(payload)) + payload


def _parse_fields(data: bytes) -> dict[int, tuple[int, object]]:
    """Parse a message into {field_no: (wire_type, last value)}.

    Unknown field numbers are tolerated (skipped over but retained), matching
    prost; wire-type checking against the schema happens in the typed
    getters below, so a type-confused field is rejected rather than coerced.
    """
    fields: dict[int, tuple[int, object]] = {}
    pos = 0
    while pos < len(data):
        key, pos = _read_varint(data, pos)
        field_no, wire_type = key >> 3, key & 7
        if field_no == 0:
            raise ValueError("field number 0 is invalid")
        if wire_type == _WT_VARINT:
            value, pos = _read_varint(data, pos)
        elif wire_type == _WT_FIXED64:
            if pos + 8 > len(data):
                raise ValueError("truncated fixed64")
            value = int.from_bytes(data[pos : pos + 8], "little")
            pos += 8
        elif wire_type == _WT_FIXED32:
            if pos + 4 > len(data):
                raise ValueError("truncated fixed32")
            value = int.from_bytes(data[pos : pos + 4], "little")
            pos += 4
        elif wire_type == _WT_LEN:
            length, pos = _read_varint(data, pos)
            if pos + length > len(data):
                raise ValueError("truncated length-delimited field")
            value = data[pos : pos + length]
            pos += length
        else:
            raise ValueError(f"unsupported wire type {wire_type}")
        fields[field_no] = (wire_type, value)
    return fields


def _get_typed(
    fields: dict[int, tuple[int, object]], field_no: int, wire_type: int, default
):
    if field_no not in fields:
        return default
    got_type, value = fields[field_no]
    if got_type != wire_type:
        raise ValueError(
            f"field {field_no}: expected wire type {wire_type}, got {got_type}"
        )
    return value


def _get_bytes(fields, field_no: int) -> bytes:
    return bytes(_get_typed(fields, field_no, _WT_LEN, b""))


def _get_fixed32(fields, field_no: int) -> int:
    return int(_get_typed(fields, field_no, _WT_FIXED32, 0))


def _get_fixed64(fields, field_no: int) -> int:
    return int(_get_typed(fields, field_no, _WT_FIXED64, 0))


# --- grapevine.QueryRequest / RequestRecord / Record / QueryResponse -----


def encode_request_record(r: R.RequestRecord) -> bytes:
    r.validate()
    return (
        _emit_bytes(1, r.msg_id) + _emit_bytes(2, r.recipient) + _emit_bytes(3, r.payload)
    )


def decode_request_record(data: bytes) -> R.RequestRecord:
    f = _parse_fields(data)
    return R.RequestRecord(
        msg_id=_get_bytes(f, 1),
        recipient=_get_bytes(f, 2),
        payload=_get_bytes(f, 3),
    ).validate()


def encode_record(r: R.Record) -> bytes:
    r.validate()
    return (
        _emit_bytes(1, r.msg_id)
        + _emit_bytes(2, r.sender)
        + _emit_bytes(3, r.recipient)
        + _emit_fixed64(4, r.timestamp)
        + _emit_bytes(5, r.payload)
    )


def decode_record(data: bytes) -> R.Record:
    f = _parse_fields(data)
    return R.Record(
        msg_id=_get_bytes(f, 1),
        sender=_get_bytes(f, 2),
        recipient=_get_bytes(f, 3),
        timestamp=_get_fixed64(f, 4),
        payload=_get_bytes(f, 5),
    ).validate()


# Constant encoded sizes for fully-populated messages; enforced at encode
# time because ciphertext length leaks whatever plaintext length leaks
# (reference grapevine.proto:40-43). Derivation: every bytes field at full
# length + fixed scalars emitted (nonzero).
QUERY_REQUEST_PROTO_SIZE = 1099
QUERY_RESPONSE_PROTO_SIZE = 1042


def encode_query_request(q: R.QueryRequest) -> bytes:
    q.validate()
    if q.request_type == 0:
        raise ValueError("request_type must be nonzero (constant-size invariant)")
    out = (
        _emit_fixed32(1, q.request_type)
        + _emit_bytes(2, q.auth_identity)
        + _emit_bytes(3, q.auth_signature)
        + _emit_message(4, encode_request_record(q.record))
    )
    if len(out) != QUERY_REQUEST_PROTO_SIZE:
        raise AssertionError("QueryRequest proto encoding is not constant-size")
    return out


def decode_query_request(data: bytes) -> R.QueryRequest:
    f = _parse_fields(data)
    if 4 not in f:
        raise ValueError("QueryRequest.record is required")
    return R.QueryRequest(
        request_type=_get_fixed32(f, 1),
        auth_identity=_get_bytes(f, 2),
        auth_signature=_get_bytes(f, 3),
        record=decode_request_record(_get_bytes(f, 4)),
    ).validate()


def encode_query_response(q: R.QueryResponse) -> bytes:
    q.validate()
    if q.record.timestamp == 0:
        raise ValueError("response timestamp must be nonzero (constant-size invariant)")
    if q.status_code == 0:
        raise ValueError("status_code must be nonzero (constant-size invariant)")
    out = _emit_message(1, encode_record(q.record)) + _emit_fixed32(2, q.status_code)
    if len(out) != QUERY_RESPONSE_PROTO_SIZE:
        raise AssertionError("QueryResponse proto encoding is not constant-size")
    return out


def decode_query_response(data: bytes) -> R.QueryResponse:
    f = _parse_fields(data)
    if 1 not in f:
        raise ValueError("QueryResponse.record is required")
    return R.QueryResponse(
        record=decode_record(_get_bytes(f, 1)),
        status_code=_get_fixed32(f, 2),
    ).validate()


# --- outer transport messages (attest-shaped) ----------------------------


@dataclass
class AuthMessage:
    """Attested key-exchange handshake blob (shape of attest.AuthMessage)."""

    data: bytes = b""


@dataclass
class EnvelopeMessage:
    """An encrypted envelope on an established channel (shape of attest.Message)."""

    aad: bytes = b""
    channel_id: bytes = b""
    data: bytes = b""


@dataclass
class AuthMessageWithChallengeSeed:
    """Auth response: handshake blob + encrypted 32-byte challenge-RNG seed.

    Mirrors reference grapevine.proto:26-36; ``encrypted_challenge_seed`` is
    only the ciphertext (the channel id is implied by the connection and the
    aad is empty).
    """

    auth_message: AuthMessage = field(default_factory=AuthMessage)
    encrypted_challenge_seed: bytes = b""


def encode_auth_message(m: AuthMessage) -> bytes:
    return _emit_bytes(1, m.data)


def decode_auth_message(data: bytes) -> AuthMessage:
    f = _parse_fields(data)
    return AuthMessage(data=_get_bytes(f, 1))


def encode_envelope(m: EnvelopeMessage) -> bytes:
    return _emit_bytes(1, m.aad) + _emit_bytes(2, m.channel_id) + _emit_bytes(3, m.data)


def decode_envelope(data: bytes) -> EnvelopeMessage:
    f = _parse_fields(data)
    return EnvelopeMessage(
        aad=_get_bytes(f, 1),
        channel_id=_get_bytes(f, 2),
        data=_get_bytes(f, 3),
    )


def encode_auth_with_seed(m: AuthMessageWithChallengeSeed) -> bytes:
    return _emit_message(1, encode_auth_message(m.auth_message)) + _emit_bytes(
        2, m.encrypted_challenge_seed
    )


def decode_auth_with_seed(data: bytes) -> AuthMessageWithChallengeSeed:
    f = _parse_fields(data)
    return AuthMessageWithChallengeSeed(
        auth_message=decode_auth_message(_get_bytes(f, 1)),
        encrypted_challenge_seed=_get_bytes(f, 2),
    )
