"""Wire layer: protocol constants, typed records, and the two codecs.

Corresponds to the reference's ``api/`` + ``types/`` crates (reference
api/src/lib.rs, types/src/lib.rs). See :mod:`grapevine_tpu.wire.records`
for the fixed-layout channel codec and :mod:`grapevine_tpu.wire.protowire`
for the protobuf-wire conformance codec.
"""

from .constants import *  # noqa: F401,F403
from .records import QueryRequest, QueryResponse, Record, RequestRecord  # noqa: F401
