"""Wire-level request validation, shared by the engine batcher and the
hostpipe codec workers.

Lives in the wire package (not engine/batcher.py, its original home)
because hostpipe worker processes (server/hostpipe.py) validate
requests off-GIL and must not import the engine — engine/batcher.py
pulls in jax + a device backend, and a spawn-context worker paying a
device-runtime import per process would erase the point of the pool.
engine/batcher.py re-exports this for its existing callers.
"""

from __future__ import annotations

from ..testing.reference import HardProtocolError
from . import constants as C
from .records import QueryRequest


def validate_request(req: QueryRequest) -> None:
    """Fail-fast checks (reference grapevine.proto:57-64,95)."""
    req.validate()
    if req.auth_identity == C.ZERO_PUBKEY:
        raise HardProtocolError("auth identity must be nonzero")
    if not (1 <= req.request_type <= 4):
        raise HardProtocolError(f"invalid request type {req.request_type}")
    if req.request_type == C.REQUEST_TYPE_UPDATE and req.record.msg_id == C.ZERO_MSG_ID:
        raise HardProtocolError("UPDATE with zero msg_id")
