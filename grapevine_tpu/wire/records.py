"""Typed message records and the fixed-layout channel codec.

Mirrors the reference's enclave-compatible type stack
(``mc-grapevine-types``, reference types/src/lib.rs:27-120): ``QueryRequest``
carries an auth identity + challenge signature + a ``RequestRecord``;
``QueryResponse`` carries a full ``Record`` + status code. Every byte field
has a mandatory fixed length — a *constant wire size* is a security
requirement, because the encrypted channel otherwise leaks request/response
content through ciphertext length (reference grapevine.proto:40-43 and
api/tests/grapevine_types.rs:21-31).

Unlike the reference, which keeps protobuf (prost) encoding inside the
encrypted channel, this framework uses a raw fixed layout for the inner
codec (constant size by construction, and directly memcpy-able into the
device batch arrays). A protobuf-wire codec compatible with the reference's
field numbering lives in :mod:`grapevine_tpu.wire.protowire`; conformance
tests assert the two stacks round-trip and both encode at constant size,
the direct analog of the reference's two-type-stack tests.

Fixed layouts (little-endian scalars):

- ``RequestRecord``: msg_id(16) | recipient(32) | payload — sizes derive
  from wire/constants.py (984 bytes at the default 1024-byte record;
  GRAPEVINE_RECORD_SIZE=2048 selects the reference's 2 KB option)
- ``Record``:        msg_id(16) | sender(32) | recipient(32) |
  timestamp(8) | payload(C.PAYLOAD_SIZE)                 = C.RECORD_SIZE
  (field order matches the reference's table, README.md:132-136)
- ``QueryRequest``:  request_type(4) | auth_identity(32) |
  auth_signature(64) | record(984)                                      = 1084
- ``QueryResponse``: record(1024) | status_code(4)                      = 1028
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from . import constants as C


def _check_len(name: str, value: bytes, expected: int) -> bytes:
    if not isinstance(value, (bytes, bytearray, memoryview)):
        raise TypeError(f"{name} must be bytes, got {type(value).__name__}")
    value = bytes(value)
    if len(value) != expected:
        raise ValueError(f"{name} must be exactly {expected} bytes, got {len(value)}")
    return value


@dataclass
class RequestRecord:
    """The client-suppliable subset of a record (reference types/src/lib.rs:63-78).

    Sender and timestamp are always server-assigned, so they do not appear
    here. All fields must be fully populated (full length) even for request
    types that ignore them — constant wire size is mandatory.
    """

    msg_id: bytes = C.ZERO_MSG_ID
    recipient: bytes = C.ZERO_PUBKEY
    payload: bytes = b"\x00" * C.PAYLOAD_SIZE

    def validate(self) -> "RequestRecord":
        self.msg_id = _check_len("msg_id", self.msg_id, C.MSG_ID_SIZE)
        self.recipient = _check_len("recipient", self.recipient, C.PUBKEY_SIZE)
        self.payload = _check_len("payload", self.payload, C.PAYLOAD_SIZE)
        return self

    def pack(self) -> bytes:
        self.validate()
        return self.msg_id + self.recipient + self.payload

    @classmethod
    def unpack(cls, data: bytes) -> "RequestRecord":
        data = _check_len("RequestRecord", data, C.REQUEST_RECORD_WIRE_SIZE)
        return cls(
            msg_id=data[:16],
            recipient=data[16:48],
            payload=data[48:],
        ).validate()


@dataclass
class Record:
    """A message in the bus: the unit that moves in and out of ORAM.

    Exactly C.RECORD_SIZE bytes packed (reference README.md:132-139); the payload is
    opaque to the service (reference README.md:146-157).
    """

    msg_id: bytes = C.ZERO_MSG_ID
    sender: bytes = C.ZERO_PUBKEY
    recipient: bytes = C.ZERO_PUBKEY
    timestamp: int = 0
    payload: bytes = b"\x00" * C.PAYLOAD_SIZE

    def validate(self) -> "Record":
        self.msg_id = _check_len("msg_id", self.msg_id, C.MSG_ID_SIZE)
        self.sender = _check_len("sender", self.sender, C.PUBKEY_SIZE)
        self.recipient = _check_len("recipient", self.recipient, C.PUBKEY_SIZE)
        self.payload = _check_len("payload", self.payload, C.PAYLOAD_SIZE)
        if not (0 <= int(self.timestamp) < 1 << 64):
            raise ValueError("timestamp must fit in u64")
        self.timestamp = int(self.timestamp)
        return self

    def pack(self) -> bytes:
        self.validate()
        return (
            self.msg_id
            + self.sender
            + self.recipient
            + struct.pack("<Q", self.timestamp)
            + self.payload
        )

    @classmethod
    def unpack(cls, data: bytes) -> "Record":
        data = _check_len("Record", data, C.RECORD_SIZE)
        return cls(
            msg_id=data[:16],
            sender=data[16:48],
            recipient=data[48:80],
            timestamp=struct.unpack("<Q", data[80:88])[0],
            payload=data[88:],
        ).validate()


@dataclass
class QueryRequest:
    """An (inner, to-be-encrypted) CRUD request (reference types/src/lib.rs:27-59)."""

    request_type: int = C.REQUEST_TYPE_INVALID
    auth_identity: bytes = C.ZERO_PUBKEY
    auth_signature: bytes = b"\x00" * C.SIGNATURE_SIZE
    record: RequestRecord = field(default_factory=RequestRecord)

    def validate(self) -> "QueryRequest":
        if not (0 <= int(self.request_type) < 1 << 32):
            raise ValueError("request_type must fit in u32")
        self.request_type = int(self.request_type)
        self.auth_identity = _check_len("auth_identity", self.auth_identity, C.PUBKEY_SIZE)
        self.auth_signature = _check_len(
            "auth_signature", self.auth_signature, C.SIGNATURE_SIZE
        )
        self.record.validate()
        return self

    def pack(self) -> bytes:
        self.validate()
        return (
            struct.pack("<I", self.request_type)
            + self.auth_identity
            + self.auth_signature
            + self.record.pack()
        )

    @classmethod
    def unpack(cls, data: bytes) -> "QueryRequest":
        data = _check_len("QueryRequest", data, C.QUERY_REQUEST_WIRE_SIZE)
        return cls(
            request_type=struct.unpack("<I", data[:4])[0],
            auth_identity=data[4:36],
            auth_signature=data[36:100],
            record=RequestRecord.unpack(data[100:]),
        ).validate()


@dataclass
class QueryResponse:
    """An (inner, to-be-encrypted) response (reference types/src/lib.rs:111-120).

    Always carries one full Record + a status code regardless of the
    operation or its outcome (reference grapevine.proto:170-176); on
    failure the record is zero-filled but full length, and the engine still
    stamps a real timestamp so that even the protobuf-wire encoding stays
    constant-size (a zero fixed64 would be elided by prost rules).
    """

    record: Record = field(default_factory=Record)
    status_code: int = C.STATUS_CODE_INVALID

    def validate(self) -> "QueryResponse":
        if not (0 <= int(self.status_code) < 1 << 32):
            raise ValueError("status_code must fit in u32")
        self.status_code = int(self.status_code)
        self.record.validate()
        return self

    def pack(self) -> bytes:
        self.validate()
        return self.record.pack() + struct.pack("<I", self.status_code)

    @classmethod
    def unpack(cls, data: bytes) -> "QueryResponse":
        data = _check_len("QueryResponse", data, C.QUERY_RESPONSE_WIRE_SIZE)
        return cls(
            record=Record.unpack(data[: C.RECORD_SIZE]),
            status_code=struct.unpack("<I", data[C.RECORD_SIZE :])[0],
        ).validate()
