"""End-to-end commit-latency SLOs with multi-window burn-rate alerting.

The client-visible number a serving deployment actually promises is not
device-round time but **commit latency**: the wall clock from the moment
an op is enqueued in the scheduler to the moment its round settles and
the response is delivered (the "commit latency a client observes" note
in engine/batcher.py, where the measurement lives). This module turns
that into an operable SLO:

- a fixed-bucket histogram of per-round commit latencies (batch-level:
  one observation per round — the round's *oldest* op's enqueue→settle
  wait, i.e. the worst case inside the batch, which is what a latency
  objective is about);
- a configurable latency target (``--slo-commit-p99-ms``) with an error
  budget: the SLO is "at most ``error_budget`` of rounds may exceed the
  target";
- multi-window **burn rates** (the SRE-workbook alerting shape): the
  windowed breach fraction divided by the error budget, over a fast and
  a slow window. The verdict alerts only when BOTH windows burn above
  their thresholds — the fast window makes the alert responsive, the
  slow window keeps a transient spike from paging — and the verdict is
  folded into ``/healthz`` by the serving layers so a breached SLO
  stops routing like any other serving fault.

Leak stance (the PR-1/2 contract): everything here is round-level. The
observation is one scalar per round; the histogram's buckets are fixed
at registration; the exported series carry no labels. There is no
per-op, per-client, or per-type dimension anywhere — a latency SLO keyed
by op type would be exactly the timing side channel the engine exists
to close (obs/registry.py).

Thread-safety: one lock around the breach window; ``observe()`` runs on
the collector thread (PendingRound.resolve), ``verdict()`` on the
healthz probe thread.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque

from .registry import TelemetryRegistry

#: fixed commit-latency histogram boundaries (seconds): spans sub-ms
#: loopback rounds up to multi-second cold-compile and recovery rounds
SLO_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
    0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


@dataclasses.dataclass(frozen=True)
class SloConfig:
    """SLO target and burn-rate alerting shape (OPERATIONS.md §12)."""

    #: commit-latency objective: rounds settling slower than this breach
    commit_p99_ms: float = 250.0
    #: gate /healthz on the burn-rate alert. False = observe-only: the
    #: histograms, burn gauges, and ``grapevine_slo_alert`` still
    #: export, but ``verdict()["ok"]`` never goes False — the CLI
    #: default until the operator sets ``--slo-commit-p99-ms``
    #: explicitly, because a fleet upgraded with a target its honest
    #: latency cannot meet would otherwise flip EVERY replica to 503 at
    #: once (the breach is config-wide, not per-instance) with no flag
    #: to restore routing
    enforce: bool = True
    #: allowed breaching fraction of rounds (the error budget): 0.01 =
    #: "99% of rounds commit within the target"
    error_budget: float = 0.01
    #: burn-rate windows (seconds) and alert thresholds. The defaults
    #: are the SRE-workbook fast/slow pair: 14.4× over 5 min spends a
    #: 30-day budget in ~2 h; 6× over 1 h spends it in ~5 days.
    fast_window_s: float = 300.0
    slow_window_s: float = 3600.0
    fast_burn_threshold: float = 14.4
    slow_burn_threshold: float = 6.0
    #: minimum rounds in a window before it may alert — insufficient
    #: evidence is not an outage (the leakmon min-samples stance); keeps
    #: a cold engine's first compile-bearing rounds from paging
    min_rounds: int = 32
    #: hard cap on tracked rounds (bounds memory at high round rates; at
    #: the cap the slow window effectively covers the last N rounds)
    max_tracked_rounds: int = 65536


class SloTracker:
    """Round-level commit-latency SLO accounting + burn-rate verdict."""

    def __init__(
        self,
        cfg: SloConfig | None = None,
        registry: TelemetryRegistry | None = None,
        clock=time.monotonic,
    ):
        self.cfg = cfg or SloConfig()
        if self.cfg.error_budget <= 0 or self.cfg.error_budget >= 1:
            raise ValueError("error budget must be in (0, 1)")
        self._clock = clock
        self._lock = threading.Lock()
        #: (t_mono, breached) per observed round, oldest first
        self._window: deque = deque(maxlen=self.cfg.max_tracked_rounds)
        self._h_latency = None
        self._c_rounds = self._c_breaches = None
        self._g_fast = self._g_slow = self._g_alert = self._g_target = None
        if registry is not None:
            self._h_latency = registry.histogram(
                "grapevine_slo_commit_latency_seconds",
                "end-to-end commit latency per round: oldest-op enqueue "
                "to round settle (batch-level; one sample per round)",
                buckets=SLO_LATENCY_BUCKETS)
            self._c_rounds = registry.counter(
                "grapevine_slo_rounds_total",
                "rounds measured against the commit-latency SLO")
            self._c_breaches = registry.counter(
                "grapevine_slo_breaches_total",
                "rounds whose commit latency exceeded the SLO target")
            self._g_fast = registry.gauge(
                "grapevine_slo_burn_rate_fast",
                "fast-window error-budget burn rate (breach fraction / "
                "budget; 1.0 = spending exactly the budget)")
            self._g_slow = registry.gauge(
                "grapevine_slo_burn_rate_slow",
                "slow-window error-budget burn rate")
            self._g_alert = registry.gauge(
                "grapevine_slo_alert",
                "1 while the multi-window burn-rate alert is firing "
                "(folded into /healthz)")
            self._g_target = registry.gauge(
                "grapevine_slo_target_ms",
                "configured commit-latency SLO target (milliseconds)")
            self._g_target.set(self.cfg.commit_p99_ms)

    # -- recording (collector thread) -----------------------------------

    def observe(self, latency_s: float) -> None:
        """Record one round's commit latency (enqueue→settle seconds)."""
        latency_s = float(latency_s)
        breached = latency_s > self.cfg.commit_p99_ms / 1e3
        now = self._clock()
        with self._lock:
            self._window.append((now, breached))
            self._prune_locked(now)
        if self._h_latency is not None:
            self._h_latency.observe(latency_s)
            self._c_rounds.inc()
            if breached:
                self._c_breaches.inc()

    def _prune_locked(self, now: float) -> None:
        horizon = now - max(self.cfg.slow_window_s, self.cfg.fast_window_s)
        w = self._window
        while w and w[0][0] < horizon:
            w.popleft()

    # -- judging (healthz probe thread) ---------------------------------

    def _window_stats_locked(self, now: float, win_s: float):
        cutoff = now - win_s
        n = breaches = 0
        for t, b in reversed(self._window):
            if t < cutoff:
                break
            n += 1
            breaches += b
        return n, breaches

    def burn_rates(self) -> dict:
        """Windowed burn rates and sample counts (no verdict)."""
        now = self._clock()
        with self._lock:
            self._prune_locked(now)
            n_fast, b_fast = self._window_stats_locked(
                now, self.cfg.fast_window_s)
            n_slow, b_slow = self._window_stats_locked(
                now, self.cfg.slow_window_s)
        budget = self.cfg.error_budget
        return {
            "fast_burn_rate": round(
                (b_fast / n_fast) / budget if n_fast else 0.0, 4),
            "slow_burn_rate": round(
                (b_slow / n_slow) / budget if n_slow else 0.0, 4),
            "fast_rounds": n_fast,
            "slow_rounds": n_slow,
        }

    def verdict(self) -> dict:
        """Machine-readable SLO verdict; ``alerting`` is True while the
        multi-window burn-rate alert fires (both windows above their
        thresholds with enough evidence), and ``ok`` goes False only
        when the config also ``enforce``\\ s (the /healthz gate).
        Updates the burn gauges so /metrics and /healthz agree."""
        cfg = self.cfg
        rates = self.burn_rates()
        alerting = (
            rates["fast_rounds"] >= cfg.min_rounds
            and rates["slow_rounds"] >= cfg.min_rounds
            and rates["fast_burn_rate"] > cfg.fast_burn_threshold
            and rates["slow_burn_rate"] > cfg.slow_burn_threshold
        )
        if self._g_fast is not None:
            self._g_fast.set(rates["fast_burn_rate"])
            self._g_slow.set(rates["slow_burn_rate"])
            self._g_alert.set(1.0 if alerting else 0.0)
        return {
            "ok": not (alerting and cfg.enforce),
            "alerting": alerting,
            "enforced": cfg.enforce,
            "target_ms": cfg.commit_p99_ms,
            "error_budget": cfg.error_budget,
            "fast_window_s": cfg.fast_window_s,
            "slow_window_s": cfg.slow_window_s,
            **rates,
        }
