"""The /metrics + /healthz (+ /leakaudit, /flightrec, /trace,
/profile) endpoint: a stdlib http.server thread.

Deliberately not a gRPC method on the public service: scrapers and
load-balancer health checks speak plain HTTP, and the endpoint must stay
up (and truthful) when the engine wedges — so it runs on its own daemon
thread with no dependency on the gRPC executor or the collector loop.

Leak stance: the endpoint serves only the registry (already audited to
be batch-level) and a healthz verdict. It binds wherever the operator
points ``--metrics-port``; like the engine tier's Submit listener, keep
it on localhost or a private scrape network — batch-level metrics are
safe against the *clients*, but operational telemetry is still nobody
else's business.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .exporter import render_prometheus
from .registry import TelemetryRegistry

log = logging.getLogger("grapevine_tpu.obs")


class MetricsServer:
    """Serve ``/metrics`` (Prometheus text) and ``/healthz`` (JSON).

    ``health`` is a zero-arg callable returning ``(healthy: bool,
    detail: dict)``; unhealthy renders HTTP 503 so any LB/probe flips
    without parsing the body. The callable runs on the scrape thread —
    it must not take engine locks that a wedged round could hold.

    ``leakaudit`` is a zero-arg callable returning the leak monitor's
    machine-readable verdict dict (obs/leakmon.py) — served on
    ``/leakaudit`` as JSON, HTTP 200 on PASS and 503 on SUSPECT so a
    probe can alert without parsing. ``flightrec`` is a zero-arg
    callable returning the flight recorder dump dict (obs/flightrec.py)
    — served on ``/flightrec``. Both 404 when not configured.

    ``trace`` is a zero-arg callable returning Chrome trace-event JSON
    as a dict (obs/tracer.py RoundTracer.chrome_trace) — served on
    ``/trace``, loadable directly in Perfetto. ``profile`` is a
    one-arg callable ``(ms) -> dict`` running a live ``jax.profiler``
    capture (obs/profiler.py ProfilerGate.capture) — served on
    ``/profile?ms=N``; a second concurrent request gets 409. Both 404
    when not configured (``profile`` exists only behind
    ``--profile-enable``).
    """

    def __init__(
        self,
        registry: TelemetryRegistry,
        health=None,
        refresh=None,
        host: str = "127.0.0.1",
        port: int = 9464,
        leakaudit=None,
        flightrec=None,
        trace=None,
        profile=None,
        render=None,
    ):
        self.registry = registry
        #: optional zero-arg callable returning the /metrics exposition
        #: text — the fleet aggregator (obs/fleet.py) substitutes its
        #: merged member view; default is this registry's own exposition
        self.render = render
        self.health = health or (lambda: (True, {}))
        self.leakaudit = leakaudit
        self.flightrec = flightrec
        self.trace = trace
        self.profile = profile
        #: optional zero-arg pre-scrape hook: sample pull-style gauges
        #: (stash occupancy needs a device sync, which must happen at
        #: scrape cadence, not round cadence). Runs only for /metrics —
        #: /healthz must stay lock-free and answer while a round wedges.
        self.refresh = refresh
        self._host = host
        self._port = port
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    def start(self) -> int:
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # scrapes are not access-log news
                log.debug("metrics http: " + fmt, *args)

            def _reply(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?")[0]
                if path == "/metrics":
                    if outer.refresh is not None:
                        try:
                            outer.refresh()
                        except Exception:
                            log.exception("metrics refresh hook failed")
                    if outer.render is not None:
                        body = outer.render().encode()
                    else:
                        body = render_prometheus(outer.registry).encode()
                    self._reply(
                        200, body, "text/plain; version=0.0.4; charset=utf-8"
                    )
                elif path == "/healthz":
                    try:
                        healthy, detail = outer.health()
                    except Exception as exc:  # a broken probe is unhealthy
                        healthy, detail = False, {"error": repr(exc)}
                    body = json.dumps(
                        {"healthy": bool(healthy), **detail}
                    ).encode()
                    self._reply(
                        200 if healthy else 503, body, "application/json"
                    )
                elif path == "/leakaudit" and outer.leakaudit is not None:
                    try:
                        verdict = outer.leakaudit()
                    except Exception as exc:  # a broken audit is suspect
                        verdict = {"verdict": "SUSPECT",
                                   "error": repr(exc)}
                    body = json.dumps(verdict).encode()
                    self._reply(
                        200 if verdict.get("verdict") == "PASS" else 503,
                        body, "application/json",
                    )
                elif path == "/flightrec" and outer.flightrec is not None:
                    try:
                        dump = outer.flightrec()
                    except Exception as exc:
                        self._reply(500, repr(exc).encode(), "text/plain")
                        return
                    self._reply(
                        200, json.dumps(dump).encode(), "application/json"
                    )
                elif path == "/trace" and outer.trace is not None:
                    try:
                        trace = outer.trace()
                    except Exception as exc:
                        self._reply(500, repr(exc).encode(), "text/plain")
                        return
                    self._reply(
                        200, json.dumps(trace).encode(), "application/json"
                    )
                elif path == "/profile" and outer.profile is not None:
                    from urllib.parse import parse_qs, urlparse

                    from .profiler import ProfilerBusy

                    qs = parse_qs(urlparse(self.path).query)
                    try:
                        ms = int(qs.get("ms", ["1000"])[0])
                    except ValueError:
                        self._reply(400, b"ms must be an integer\n",
                                    "text/plain")
                        return
                    try:
                        # blocks this handler thread for ~ms while the
                        # engine keeps serving (ThreadingHTTPServer:
                        # scrapes stay live on their own threads)
                        result = outer.profile(ms)
                    except ProfilerBusy as exc:
                        self._reply(409, str(exc).encode(), "text/plain")
                        return
                    except Exception as exc:
                        self._reply(500, repr(exc).encode(), "text/plain")
                        return
                    self._reply(
                        200, json.dumps(result).encode(), "application/json"
                    )
                else:
                    self._reply(404, b"not found\n", "text/plain")

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="grapevine-metrics",
        )
        self._thread.start()
        port = self._httpd.server_address[1]
        log.info("metrics endpoint on %s:%d (/metrics, /healthz%s%s%s)",
                 self._host, port,
                 ", /leakaudit, /flightrec" if self.leakaudit else "",
                 ", /trace" if self.trace else "",
                 ", /profile" if self.profile else "")
        return port

    @property
    def port(self) -> int | None:
        return self._httpd.server_address[1] if self._httpd else None

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
