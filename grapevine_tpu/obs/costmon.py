"""Cost observatory: the static round-cost ledger on /metrics, plus
the runtime roofline residual.

Two halves, both riding :mod:`..analysis.costmodel` (the bit-exact
cross-validated model — see tools/check_cost_model.py for the gate):

- **startup info gauges** (``grapevine_cost_*``): the modeled per-phase
  HBM bytes / gather-scatter rows / cipher rows / sort key-volume and
  the flush-amortized steady-state round total, set once at attach
  time. Pure functions of public geometry × knobs — the same numbers
  any observer could derive from the config — so they are trivially
  leak-free (tools/check_telemetry_policy.py audits the namespace:
  ``phase`` is the only label key, and label *values* are the fixed
  phase names, never geometry).
- **roofline residual** (runtime): each resolved round pairs the
  tracer's host-observed device span against the modeled floor
  (steady-state bytes ÷ calibrated achieved bandwidth). The exported
  ratio ``measured / floor`` reads as "how far off the bandwidth
  roofline this round ran": residual DRIFT is the alert signal — a
  regressed knob, a silently grown geometry, or a mispredicting model
  all show up here at round cadence instead of in a post-hoc bench
  (OPERATIONS.md §21 carries the triage runbook).

Bandwidth constants: ``GRAPEVINE_COST_GBPS`` (the operator's
calibrated value — the ``cost_calibrate`` capture stage in
tools/tpu_capture.py fits it on real silicon) with conservative
per-backend placeholders until then. A placeholder constant shifts the
residual's LEVEL, not its drift: triage on change, not magnitude,
until calibration lands.
"""

from __future__ import annotations

import os

from ..analysis.costmodel import COST_PHASES, engine_cost_ledger

#: pre-calibration achieved-bandwidth placeholders (GB/s) per JAX
#: backend — deliberately conservative; cost_calibrate replaces them
DEFAULT_GBPS = {"cpu": 8.0, "gpu": 400.0, "tpu": 800.0}


def resolve_bandwidth_gbps(override: float | None = None) -> float:
    """Calibrated-constant resolution order: explicit override →
    ``GRAPEVINE_COST_GBPS`` → per-backend placeholder."""
    if override is not None:
        return float(override)
    env = os.environ.get("GRAPEVINE_COST_GBPS")
    if env:
        return float(env)
    try:
        import jax

        backend = jax.default_backend()
    except Exception:  # pragma: no cover - jax import/env failure
        backend = "cpu"
    return DEFAULT_GBPS.get(backend, DEFAULT_GBPS["cpu"])


class CostMonitor:
    """Exports the modeled cost ledger for one engine geometry and
    scores every resolved round against the roofline floor.

    Attached by :func:`..obs.attach_round_observability`; the engine
    hands each round's span ledger to :meth:`observe_round` off the
    jit path (engine/batcher.py ``PendingRound.resolve``, next to the
    tracer's ring append — a few float ops per ROUND)."""

    def __init__(self, ecfg, registry, *,
                 bandwidth_gbps: float | None = None):
        self.ledger = engine_cost_ledger(ecfg)
        self.bandwidth_gbps = resolve_bandwidth_gbps(bandwidth_gbps)
        self.floor_ms = self.ledger.floor_ms(self.bandwidth_gbps)

        phase_labels = {"phase": COST_PHASES}
        g_bytes = registry.gauge(
            "grapevine_cost_phase_hbm_bytes",
            "Modeled HBM bytes one execution of this phase moves "
            "(static geometry x knobs; flush/sweep are per flush/sweep "
            "call, not per round)",
            labels=phase_labels,
        )
        g_grows = registry.gauge(
            "grapevine_cost_phase_gather_rows",
            "Modeled HBM gather rows per execution of this phase",
            labels=phase_labels,
        )
        g_srows = registry.gauge(
            "grapevine_cost_phase_scatter_rows",
            "Modeled HBM scatter rows per execution of this phase",
            labels=phase_labels,
        )
        g_cipher = registry.gauge(
            "grapevine_cost_phase_cipher_rows",
            "Modeled bucket-cipher keystream rows per execution of "
            "this phase",
            labels=phase_labels,
        )
        g_sort = registry.gauge(
            "grapevine_cost_phase_sort_keys",
            "Modeled sort key-volume per execution of this phase",
            labels=phase_labels,
        )
        for phase in COST_PHASES:
            c = self.ledger.phases[phase]
            g_bytes.set(float(c.hbm_bytes), phase=phase)
            g_grows.set(float(c.gather_rows), phase=phase)
            g_srows.set(float(c.scatter_rows), phase=phase)
            g_cipher.set(float(c.cipher_rows), phase=phase)
            g_sort.set(float(c.sort_keys), phase=phase)

        registry.gauge(
            "grapevine_cost_steady_round_hbm_bytes",
            "Modeled flush-amortized HBM bytes per steady-state engine "
            "round (fetch + write-back + flush/evict_every; sweep "
            "excluded — operator-cadenced)",
        ).set(float(self.ledger.steady_round_bytes))
        registry.gauge(
            "grapevine_cost_bandwidth_gbps",
            "Achieved-bandwidth constant in use for the roofline floor "
            "(GRAPEVINE_COST_GBPS / cost_calibrate fit, else a "
            "per-backend placeholder)",
        ).set(self.bandwidth_gbps)
        registry.gauge(
            "grapevine_cost_roofline_floor_ms",
            "Modeled round-time floor: steady-state bytes / calibrated "
            "bandwidth",
        ).set(self.floor_ms)
        self._g_residual = registry.gauge(
            "grapevine_cost_roofline_residual",
            "Last round's host-observed device span / modeled roofline "
            "floor (drift, not level, is the alert signal)",
        )
        self._g_residual_max = registry.gauge(
            "grapevine_cost_roofline_residual_max",
            "Worst roofline residual observed since attach",
        )

    def observe_round(self, spans: dict) -> None:
        """Score one resolved round's device span against the floor.

        ``spans`` is the round's span ledger (name -> (start_s,
        dur_s)); the ``device`` span is the host-observed upper bound
        on device-busy time the tracer records."""
        dev = spans.get("device")
        if dev is None or self.floor_ms <= 0.0:
            return
        residual = (dev[1] * 1e3) / self.floor_ms
        self._g_residual.set(residual)
        self._g_residual_max.set_max(residual)
