"""Round-phase names, wall-clock phase timers, and device trace scopes.

Phase timing is the observability Path ORAM work actually runs on
(Palermo, arXiv:2411.05400, breaks rounds down by phase), and it is safe
here *only* at batch granularity: every phase covers the whole
fixed-size round, so its duration is a function of (capacity, batch
size), never of which ops or whose ops are inside (the timing leakage
stance of testing/leakcheck.py:timing_twosample_z).

Host-side phases (histograms + ``jax.profiler`` annotations):

- ``assembly``  — scheduler collection window (server/scheduler.py)
- ``verify``    — batched sr25519 signature verification
- ``dispatch``  — host pack + device round enqueue (engine/batcher.py)
- ``evict``     — device round completion wait: the ORAM fetch / apply /
                  evict / write-back program measured from the host
                  (per-stage device splits are in the profiler trace via
                  the ``jax.named_scope`` annotations, not in metrics —
                  the host cannot time inside one XLA program)
- ``demux``     — device→wire response unpacking
- ``sweep``     — expiry sweep (engine/expiry.py)
- ``journal``   — sealed batch-journal append + fsync (engine/journal.py)
- ``checkpoint``— sealed whole-state checkpoint write (engine/checkpoint.py)
- ``replay``    — startup journal replay (recovery; engine/batcher.py)
- ``sort``      — the round's bounded-key sort workload, measured by
                  calibration (GrapevineEngine.calibrate_sort_phase):
                  the host cannot time inside the fused round program,
                  but every sort in the round is shape-static and
                  data-independent (oblivious), so a standalone run of
                  the SAME jitted sort program at the round's geometry
                  IS the per-round sort cost — /metrics separates it
                  from the rest of the ``evict`` phase without touching
                  the hot path. Labelled batch-level by construction
                  (geometry only, never request data).
- ``posmap``    — per-round position-resolution cost, measured the same
                  calibration way (GrapevineEngine.calibrate_posmap_phase
                  runs the round's exact lookup_and_remap workload —
                  all three ORAM rounds' batch lookups — standalone at
                  the round geometry): under a recursive position map
                  (oram/posmap.py) this is the internal ORAM's rounds,
                  under a flat one the private gather/scatter pair, so
                  /trace and the flight recorder attribute position
                  handling separately from ``oram_evict``. Also a
                  device_phase scope inside the jit'd round for TPU
                  profiler captures.

Device-side scopes (``device_phase``): named_scope annotations compiled
into the jit'd round so TPU profiler captures (tools/tpu_capture.py
stage 6) attribute HLO time to fetch/apply/evict/writeback per tree.
"""

from __future__ import annotations

import contextlib
import time

#: canonical phase label values — the registry declares exactly these,
#: so a typo'd phase name raises instead of minting a new series
PHASES = ("assembly", "verify", "dispatch", "evict", "demux", "sweep",
          "journal", "checkpoint", "replay", "sort", "posmap", "flush")

#: fixed histogram boundaries for phase durations (seconds). Spans the
#: measured range: ~100 µs host phases at B=8 up to multi-second expiry
#: sweeps at 2^24 capacity (PERF.md / BIGRUN_r4.md).
PHASE_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

#: fixed boundaries for stash occupancy samples (entries; geometry-
#: independent absolutes — stash_size is 96 by default, configurable)
STASH_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 48.0, 64.0, 96.0, 128.0)


@contextlib.contextmanager
def phase_timer(histogram, phase: str, annotate: bool = True):
    """Time a host-side phase into ``histogram{phase=...}``.

    Also emits a ``jax.profiler.TraceAnnotation`` so host phases line up
    with device HLO spans in a TPU profiler capture. The annotation is a
    TraceMe — nanoseconds when no trace is active — and is batch-level
    by construction (the name is the static phase, never request data).
    """
    ann = None
    if annotate:
        try:
            import jax.profiler

            ann = jax.profiler.TraceAnnotation(f"grapevine/{phase}")
            ann.__enter__()
        except Exception:  # profiler unavailable: timing still works
            ann = None
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        if ann is not None:
            ann.__exit__(None, None, None)
        if histogram is not None:
            histogram.observe(dt, phase=phase)


def device_phase(name: str):
    """``jax.named_scope`` wrapper for phases *inside* jit'd programs.

    Pure trace-time metadata: names the HLO ops so profiler captures
    attribute device time per ORAM stage; compiles to nothing.
    """
    import jax

    return jax.named_scope(f"grapevine/{name}")
