"""Obliviousness-safe observability (the telemetry analog of
testing/leakcheck.py).

The engine's security claim constrains *telemetry*, not just storage:
per-op timing or op-type breakdowns would reopen exactly the side
channel the oblivious engine closes (reference grapevine.proto:120-122
— "access patterns and timings"). This package therefore enforces the
leak policy structurally rather than by convention:

- ``registry``: a central TelemetryRegistry (counters, gauges,
  histograms with fixed bucket boundaries) with a declarative allowlist
  of label keys and registration-time-declared label values — a metric
  keyed by client identity, msg id, or op type raises
  ``TelemetryLeakError`` at registration, and ``audit()`` asserts the
  whole registry is batch-level only;
- ``phases``: the canonical round-phase names, wall-clock phase timers
  feeding the registry, and ``jax`` trace annotations for TPU profiler
  runs;
- ``exporter``: Prometheus text exposition of a registry;
- ``httpd``: a stdlib ``http.server`` thread serving ``/metrics``,
  ``/healthz``, ``/leakaudit``, and ``/flightrec``;
- ``leakmon``: the streaming transcript leak monitor — the pytest
  detectors (testing/leakcheck.py) run continuously over a sliding
  window of production rounds, publishing aggregate-only statistics
  and a machine-readable PASS/SUSPECT verdict;
- ``flightrec``: a fixed-size ring of schema-checked per-round
  summaries, dumped on demand or on a PASS→SUSPECT transition;
- ``tracer``: the round-trace profiler — a fixed ring of per-round
  span ledgers exported as Chrome trace-event JSON (``/trace``,
  Perfetto-loadable) plus the derived host/device bubble-ratio gauge;
- ``slo``: end-to-end commit-latency SLOs (enqueue→settle, one sample
  per round) with multi-window burn-rate alerting folded into
  ``/healthz``;
- ``profiler``: gated programmatic ``jax.profiler`` capture of a live
  engine (``/profile?ms=N``, ``--profile-enable``);
- ``workload``: batch-level workload telemetry — fixed-bucket batch
  fill-fraction and queue-depth histograms at round cadence, an
  arrival-rate EWMA gauge, per-phase utilization from the tracer span
  ledgers, and saturation/backpressure counters (the signals the
  ``grapevine_tpu/load`` scenario harness measures against);
- ``fleet``: the multi-process observatory — a stdlib aggregator
  scraping N member processes on a fixed public cadence and serving
  merged shard-labeled /metrics, folded /healthz and /leakaudit, the
  cross-shard schedule-uniformity detectors
  (``leakmon.FleetUniformityMonitor``), and replication-lag gauges
  (ROADMAP items 1/2/4).
"""

from .registry import (  # noqa: F401
    ALLOWED_LABEL_KEYS,
    FORBIDDEN_LABEL_KEYS,
    Counter,
    Gauge,
    Histogram,
    TelemetryLeakError,
    TelemetryRegistry,
)
from .phases import PHASES, device_phase, phase_timer  # noqa: F401
from .exporter import render_prometheus  # noqa: F401
from .httpd import MetricsServer  # noqa: F401
from .flightrec import FlightRecorder  # noqa: F401
from .leakmon import (  # noqa: F401
    EngineLeakMonitor,
    FleetUniformityConfig,
    FleetUniformityMonitor,
    LeakMonitorConfig,
    TranscriptLeakMonitor,
)
from .fleet import FleetAggregator, FleetConfig, parse_exposition  # noqa: F401
from .tracer import RoundTracer  # noqa: F401
from .slo import SloConfig, SloTracker  # noqa: F401
from .profiler import ProfilerBusy, ProfilerGate  # noqa: F401
from .workload import WorkloadTelemetry  # noqa: F401
from .costmon import CostMonitor  # noqa: F401


def attach_round_observability(engine, registry, *, trace_ring_size=512,
                               slo=None, profile_enable=False):
    """Attach the round tracer + commit-latency SLO + workload
    telemetry (always on for the device owner — all three cost a few
    dict/histogram ops per ROUND, not per op) and the optional
    profiler gate to ``engine``; the ONE place the serving layers
    (server/service.py, server/tier.py) share the policy.

    No explicit SLO config = observe-only (the CLI-default contract,
    server/cli.py ``_slo_config``): latencies and burn rates export,
    but /healthz only gates once an operator-supplied config enforces
    a target. The jax.profiler capture gate stays opt-in
    (``--profile-enable``): a capture has real overhead and writes
    device traces to disk.

    Returns ``(tracer, slo_tracker, profiler_or_None)``.
    """
    tracer = RoundTracer(capacity=trace_ring_size, registry=registry)
    engine.attach_tracer(tracer)
    slo_tracker = SloTracker(
        slo if slo is not None else SloConfig(enforce=False),
        registry=registry,
    )
    engine.attach_slo(slo_tracker)
    # the workload observatory's serving-side half (obs/workload.py):
    # fill/depth at round cadence, arrival EWMA, phase utilization —
    # the queue-depth signal ROADMAP item 4's adaptive batcher needs
    # exists on every production engine, not only under the harness
    engine.attach_workload(
        WorkloadTelemetry(registry, batch_size=engine.ecfg.batch_size)
    )
    # the cost observatory (obs/costmon.py): the static grapevine_cost_*
    # ledger (pure geometry x knobs — the bit-exact model the
    # check_cost_model gate cross-validates) plus the per-round
    # roofline residual against the tracer's device span
    engine.attach_costmon(CostMonitor(engine.ecfg, registry))
    return tracer, slo_tracker, ProfilerGate() if profile_enable else None
