"""Obliviousness-safe observability (the telemetry analog of
testing/leakcheck.py).

The engine's security claim constrains *telemetry*, not just storage:
per-op timing or op-type breakdowns would reopen exactly the side
channel the oblivious engine closes (reference grapevine.proto:120-122
— "access patterns and timings"). This package therefore enforces the
leak policy structurally rather than by convention:

- ``registry``: a central TelemetryRegistry (counters, gauges,
  histograms with fixed bucket boundaries) with a declarative allowlist
  of label keys and registration-time-declared label values — a metric
  keyed by client identity, msg id, or op type raises
  ``TelemetryLeakError`` at registration, and ``audit()`` asserts the
  whole registry is batch-level only;
- ``phases``: the canonical round-phase names, wall-clock phase timers
  feeding the registry, and ``jax`` trace annotations for TPU profiler
  runs;
- ``exporter``: Prometheus text exposition of a registry;
- ``httpd``: a stdlib ``http.server`` thread serving ``/metrics``,
  ``/healthz``, ``/leakaudit``, and ``/flightrec``;
- ``leakmon``: the streaming transcript leak monitor — the pytest
  detectors (testing/leakcheck.py) run continuously over a sliding
  window of production rounds, publishing aggregate-only statistics
  and a machine-readable PASS/SUSPECT verdict;
- ``flightrec``: a fixed-size ring of schema-checked per-round
  summaries, dumped on demand or on a PASS→SUSPECT transition.
"""

from .registry import (  # noqa: F401
    ALLOWED_LABEL_KEYS,
    FORBIDDEN_LABEL_KEYS,
    Counter,
    Gauge,
    Histogram,
    TelemetryLeakError,
    TelemetryRegistry,
)
from .phases import PHASES, device_phase, phase_timer  # noqa: F401
from .exporter import render_prometheus  # noqa: F401
from .httpd import MetricsServer  # noqa: F401
from .flightrec import FlightRecorder  # noqa: F401
from .leakmon import (  # noqa: F401
    EngineLeakMonitor,
    LeakMonitorConfig,
    TranscriptLeakMonitor,
)
