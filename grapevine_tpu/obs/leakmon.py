"""Streaming transcript leak monitor — continuous obliviousness auditing.

The framework's whole value claim is that the public transcript of ORAM
leaf fetches is indistinguishable from independent uniform draws (Path
ORAM, arXiv:1202.5150). The reference repo gets that from SGX for free;
here it is an *empirical* property, and until now it was only checked
inside pytest (testing/leakcheck.py + tests/test_leak_canary.py). A
production bus serving millions of users needs the invariant watched
continuously — the way a race detector is observability for a lock
discipline — which is what this module does:

- :class:`TranscriptLeakMonitor` maintains sliding-window statistics
  for the three testable leak facets, reusing the pytest detectors
  (testing/leakcheck.py — the statistics are bit-identical, only the
  windowing is new):

  1. **same-key leaf collision rate** (within-round independence; a
     missing dedup makes same-key ops show equal leaves),
  2. **cross-round leaf repeat rate** (position-map freshness; a
     no-remap bug makes every re-access repeat the previous leaf),
  3. **chi-square marginal uniformity** of the pooled leaves (a
     constant or biased dummy leaf skews the histogram).

- :class:`EngineLeakMonitor` adapts the engine: it consumes the
  ``leaves`` transcript each ORAM round already returns
  (oram/round.py:oram_round) **off the jit path**, on its own daemon
  thread behind a bounded queue — a slow detector can never stall the
  round pipeline; overload drops rounds and counts the drops. Key
  grouping comes from the host-side mirror of the round's key selection
  (engine/round_step.py:transcript_key_groups).

Leak stance: the monitor *inspects* private data (which ops share keys
— the same standing the position map already has, host process memory)
but *publishes* only aggregates: windowed rates, z-scores, and sample
counts, through the PR-1 TelemetryRegistry under its label allowlist
(``tree`` is the only label). The flight recorder it feeds
(obs/flightrec.py) enforces the same property schema-structurally.

Verdict semantics: each detector reports its statistic, threshold, and
sample count; a detector with fewer than its minimum samples reports
PASS (insufficient evidence is not suspicion — thresholds and the
false-positive budget live in OPERATIONS.md). The overall verdict is
SUSPECT iff any detector trips; /leakaudit (obs/httpd.py) serves it
machine-readable and /healthz folds it into liveness.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import queue
import threading
import time
from collections import OrderedDict, deque

import numpy as np

from ..testing.leakcheck import (
    _leaf_hist,
    samekey_collision_counts,
    uniformity_z_from_counts,
)
from .flightrec import FlightRecorder
from .registry import TelemetryRegistry

log = logging.getLogger("grapevine_tpu.obs.leakmon")

PASS = "PASS"
SUSPECT = "SUSPECT"


@dataclasses.dataclass(frozen=True)
class LeakMonitorConfig:
    """Thresholds and window sizing (defaults justified in
    OPERATIONS.md §"continuous obliviousness auditing")."""

    #: sliding window length in observe() calls per stream. An engine
    #: round contributes TWO mailbox observations (rounds A and C) and
    #: one records observation, so a window of 256 covers ≥128 engine
    #: rounds on the mailbox stream and 256 on the records stream.
    window_rounds: int = 256
    #: histogram bins for the uniformity detector (clamped to the leaf
    #: count; bins always divide the power-of-two leaf range)
    uniformity_bins: int = 16
    #: |z| above this on the pooled window histogram → SUSPECT. Honest
    #: transcripts give |z| = O(1); the no-FP budget is ~1e-9 per
    #: verdict at 8.0 under the normal approximation (heavier chi-square
    #: tails still leave orders of magnitude of margin — the canary
    #: leaks push z past 50 within a few rounds).
    uniformity_z_threshold: float = 8.0
    #: rate floor for the same-key collision detector (honest rate is
    #: 1/leaves; a no-dedup leak drives it to 1.0). The *effective*
    #: threshold is max(floor, 1/leaves + rate_z_margin·σ) so small
    #: dev/test trees — where 1/leaves itself is a few percent — do not
    #: false-positive (the binomial-z form of the canary separation)
    collision_threshold: float = 0.02
    #: rate floor for the cross-round repeat detector (honest rate is
    #: 1/leaves; a no-remap leak drives it to 1.0); same effective-
    #: threshold rule as collision_threshold
    repeat_threshold: float = 0.05
    #: sampling-noise margin in binomial standard deviations for the two
    #: rate detectors' effective thresholds
    rate_z_margin: float = 8.0
    #: minimum evidence before a detector may trip (insufficient samples
    #: report PASS): same-key pairs / repeat opportunities / pooled
    #: leaves in the window
    min_pairs: int = 32
    min_opportunities: int = 32
    min_pooled_leaves: int = 256
    #: cross-round tracker capacity (LRU over stable key ids — private
    #: host memory, never exported)
    track_keys: int = 8192
    #: bounded hand-off queue between the round path and the monitor
    #: thread; a full queue drops the round (counted) instead of
    #: blocking the scheduler
    queue_depth: int = 64
    #: flight recorder ring size (engine rounds retained)
    flight_capacity: int = 512
    #: where a PASS→SUSPECT transition dumps the flight recorder
    #: (None = no automatic dump; /flightrec still serves it on demand)
    dump_path: str | None = None


class _Stream:
    """Sliding-window state for one leaf space (one ORAM tree)."""

    __slots__ = (
        "n_leaves", "bins", "window", "hist_sum", "collisions", "pairs",
        "repeats", "opportunities", "last_leaf", "_window_max", "_track",
    )

    def __init__(self, n_leaves: int, bins: int, window: int, track: int):
        if n_leaves & (n_leaves - 1):
            raise ValueError("leaf spaces are powers of two")
        self.n_leaves = n_leaves
        self.bins = min(bins, n_leaves)
        #: deque of (hist, collisions, pairs, repeats, opportunities)
        self.window: deque = deque(maxlen=None)
        self._window_max = window
        self.hist_sum = np.zeros((self.bins,), np.int64)
        self.collisions = 0
        self.pairs = 0
        self.repeats = 0
        self.opportunities = 0
        self.last_leaf: OrderedDict = OrderedDict()
        self._track = track


class TranscriptLeakMonitor:
    """Synchronous sliding-window core over named leaf streams.

    ``trees`` maps stream name → leaf-space size (e.g. ``{"rec": 2**20,
    "mb": 2**12}``). ``observe()`` feeds one round of one stream;
    ``verdict()`` evaluates the three detectors over every stream's
    current window. Thread-safe (one lock; observe and verdict may race
    from the monitor worker and the scrape thread).
    """

    def __init__(
        self,
        trees: dict[str, int],
        cfg: LeakMonitorConfig | None = None,
        registry: TelemetryRegistry | None = None,
    ):
        if not trees:
            raise ValueError("leak monitor needs at least one stream")
        self.cfg = cfg or LeakMonitorConfig()
        self._lock = threading.Lock()
        self._streams = {
            name: _Stream(
                n_leaves, self.cfg.uniformity_bins,
                self.cfg.window_rounds, self.cfg.track_keys,
            )
            for name, n_leaves in trees.items()
        }
        self._g_collision = self._g_repeat = self._g_unif = None
        self._g_pairs = self._g_opps = self._g_pool = None
        if registry is not None:
            labels = {"tree": tuple(trees)}
            self._g_collision = registry.gauge(
                "grapevine_leakmon_samekey_collision_rate",
                "windowed same-key transcript leaf collision rate "
                "(honest ≈ 1/leaves; no-dedup leak → 1)", labels=labels)
            self._g_repeat = registry.gauge(
                "grapevine_leakmon_cross_round_repeat_rate",
                "windowed cross-round same-key leaf repeat rate "
                "(honest ≈ 1/leaves; no-remap leak → 1)", labels=labels)
            self._g_unif = registry.gauge(
                "grapevine_leakmon_uniformity_z",
                "chi-square z of the windowed pooled transcript leaf "
                "histogram (honest |z| = O(1))", labels=labels)
            self._g_pairs = registry.gauge(
                "grapevine_leakmon_window_pairs",
                "same-key op pairs in the current window (collision "
                "detector sample size)", labels=labels)
            self._g_opps = registry.gauge(
                "grapevine_leakmon_window_repeat_opportunities",
                "cross-round re-accesses in the current window (repeat "
                "detector sample size)", labels=labels)
            self._g_pool = registry.gauge(
                "grapevine_leakmon_window_leaves",
                "pooled transcript leaves in the current window "
                "(uniformity detector sample size)", labels=labels)

    @property
    def streams(self) -> tuple:
        """Declared stream names (e.g. ("rec", "mb", "rec_pm", "mb_pm"))."""
        return tuple(self._streams)

    # -- feeding --------------------------------------------------------

    def observe(
        self,
        tree: str,
        keys: np.ndarray | None,
        leaves: np.ndarray,
        stable=None,
    ) -> None:
        """Feed one round of one stream.

        ``leaves``: the round's public transcript leaves (all of them —
        real, dummy, and padding fetches are all part of the public
        sequence). ``keys``: per-leaf within-round key group ids,
        ``-1`` = no key (padding / host-unresolvable); None disables the
        keyed detectors for this call. ``stable``: optional per-leaf
        cross-round-stable ids (hashable; e.g. recipient-key bytes) for
        the repeat tracker — defaults to the key group values, which is
        only correct when the caller's group ids are themselves stable
        across rounds (block indices in the oram-level tests)."""
        st = self._streams[tree]  # KeyError = undeclared stream, loudly
        leaves = np.asarray(leaves, np.int64).ravel()
        hist = _leaf_hist(leaves, st.n_leaves, st.bins)
        collisions = pairs = repeats = opportunities = 0
        if keys is not None:
            keys = np.asarray(keys, np.int64).ravel()
            if keys.shape != leaves.shape:
                raise ValueError("keys and leaves must align")
            collisions, pairs = samekey_collision_counts(keys, leaves)
        with self._lock:
            if keys is not None:
                repeats, opportunities = self._track_repeats(
                    st, keys, leaves, stable
                )
            st.window.append((hist, collisions, pairs, repeats, opportunities))
            st.hist_sum += hist
            st.collisions += collisions
            st.pairs += pairs
            st.repeats += repeats
            st.opportunities += opportunities
            while len(st.window) > st._window_max:
                h0, c0, p0, r0, o0 = st.window.popleft()
                st.hist_sum -= h0
                st.collisions -= c0
                st.pairs -= p0
                st.repeats -= r0
                st.opportunities -= o0
            self._export_locked(tree, st)

    def _track_repeats(self, st: _Stream, keys, leaves, stable):
        """Cross-round freshness: compare each key's authoritative
        (first-occurrence — the real path fetch; later occurrences are
        dummies) leaf against its previous round's. The tracker is an
        LRU over stable key ids — private host state, like the posmap;
        only the windowed rate leaves this module."""
        repeats = opportunities = 0
        real_idx = np.nonzero(keys >= 0)[0]
        if real_idx.size == 0:
            return 0, 0
        _, first = np.unique(keys[real_idx], return_index=True)
        for i in real_idx[first]:
            skey = stable[i] if stable is not None else int(keys[i])
            leaf = int(leaves[i])
            prev = st.last_leaf.pop(skey, None)
            if prev is not None:
                opportunities += 1
                if prev == leaf:
                    repeats += 1
            st.last_leaf[skey] = leaf
            while len(st.last_leaf) > st._track:
                st.last_leaf.popitem(last=False)
        return repeats, opportunities

    def _export_locked(self, tree: str, st: _Stream) -> None:
        if self._g_collision is None:
            return
        pooled = int(st.hist_sum.sum())
        self._g_collision.set(
            st.collisions / st.pairs if st.pairs else 0.0, tree=tree)
        self._g_repeat.set(
            st.repeats / st.opportunities if st.opportunities else 0.0,
            tree=tree)
        self._g_unif.set(
            uniformity_z_from_counts(st.hist_sum) if pooled else 0.0,
            tree=tree)
        self._g_pairs.set(st.pairs, tree=tree)
        self._g_opps.set(st.opportunities, tree=tree)
        self._g_pool.set(pooled, tree=tree)

    # -- judging --------------------------------------------------------

    def stats(self, tree: str) -> dict:
        """Windowed statistics for one stream (flight-recorder food)."""
        st = self._streams[tree]
        with self._lock:
            pooled = int(st.hist_sum.sum())
            return {
                "collision_rate": round(
                    st.collisions / st.pairs, 6) if st.pairs else 0.0,
                "collision_pairs": st.pairs,
                "repeat_rate": round(
                    st.repeats / st.opportunities, 6
                ) if st.opportunities else 0.0,
                "repeat_opportunities": st.opportunities,
                "uniformity_z": float(round(
                    uniformity_z_from_counts(st.hist_sum), 3
                )) if pooled else 0.0,
                "pooled_leaves": pooled,
            }

    def _rate_threshold(self, floor: float, n_leaves: int, n: int) -> float:
        """Effective threshold for a rate detector: the configured floor
        OR the honest expectation (1/leaves) plus ``rate_z_margin``
        binomial standard deviations of sampling noise, whichever is
        larger — scale-free across tree geometries (a 2^4-leaf dev tree
        has an honest repeat rate of 6%; a 2^20-leaf production tree,
        1e-6; a leak drives either to ~1)."""
        p = 1.0 / n_leaves
        if n <= 0:
            return max(floor, p)
        return max(floor, p + self.cfg.rate_z_margin
                   * math.sqrt(p * (1.0 - p) / n))

    def verdict(self) -> dict:
        """Machine-readable verdict: per-detector statistic, threshold,
        sample count, and PASS/SUSPECT, per stream (the /leakaudit
        body). Overall SUSPECT iff any detector trips."""
        cfg = self.cfg
        detectors = []
        for tree in self._streams:
            s = self.stats(tree)
            n_leaves = self._streams[tree].n_leaves
            coll_thr = self._rate_threshold(
                cfg.collision_threshold, n_leaves, s["collision_pairs"])
            detectors.append({
                "name": "samekey_collision",
                "tree": tree,
                "statistic": s["collision_rate"],
                "threshold": round(coll_thr, 6),
                "samples": s["collision_pairs"],
                "min_samples": cfg.min_pairs,
                "verdict": SUSPECT if (
                    s["collision_pairs"] >= cfg.min_pairs
                    and s["collision_rate"] > coll_thr
                ) else PASS,
            })
            rep_thr = self._rate_threshold(
                cfg.repeat_threshold, n_leaves, s["repeat_opportunities"])
            detectors.append({
                "name": "cross_round_repeat",
                "tree": tree,
                "statistic": s["repeat_rate"],
                "threshold": round(rep_thr, 6),
                "samples": s["repeat_opportunities"],
                "min_samples": cfg.min_opportunities,
                "verdict": SUSPECT if (
                    s["repeat_opportunities"] >= cfg.min_opportunities
                    and s["repeat_rate"] > rep_thr
                ) else PASS,
            })
            detectors.append({
                "name": "uniformity",
                "tree": tree,
                "statistic": s["uniformity_z"],
                "threshold": cfg.uniformity_z_threshold,
                "samples": s["pooled_leaves"],
                "min_samples": cfg.min_pooled_leaves,
                "verdict": SUSPECT if (
                    s["pooled_leaves"] >= cfg.min_pooled_leaves
                    and abs(s["uniformity_z"]) > cfg.uniformity_z_threshold
                ) else PASS,
            })
        overall = SUSPECT if any(
            d["verdict"] == SUSPECT for d in detectors) else PASS
        return {
            "verdict": overall,
            "window_rounds": cfg.window_rounds,
            "detectors": detectors,
        }


class EngineLeakMonitor:
    """Async engine adapter: transcript hand-off queue + worker thread
    + flight recorder + verdict cache.

    The round path (PendingRound.resolve, engine/batcher.py) calls
    ``submit_round`` — one non-blocking queue put. Everything heavy
    (device→host transcript copy, key grouping, detector updates,
    verdict evaluation, flight recording) happens on the daemon worker,
    so enabling the monitor costs the round pipeline nothing but the
    enqueue (the <3% loopback-p99 budget in ISSUE acceptance).
    """

    def __init__(
        self,
        mb_leaves: int,
        rec_leaves: int,
        mb_choices: int,
        cfg: LeakMonitorConfig | None = None,
        registry: TelemetryRegistry | None = None,
        recorder: FlightRecorder | None = None,
        mb_pm_leaves: int | None = None,
        rec_pm_leaves: int | None = None,
        flush_every: int | None = None,
    ):
        self.cfg = cfg or LeakMonitorConfig()
        self.mb_choices = mb_choices
        trees = {"rec": rec_leaves, "mb": mb_leaves}
        # recursive position map (oram/posmap.py): the internal ORAM's
        # accesses ride the transcript as appended columns — they get
        # their own detector streams sized to the *internal* leaf space
        self._has_pm = mb_pm_leaves is not None and rec_pm_leaves is not None
        if self._has_pm:
            trees["rec_pm"] = rec_pm_leaves
            trees["mb_pm"] = mb_pm_leaves
        self.monitor = TranscriptLeakMonitor(trees, self.cfg, registry)
        self.recorder = recorder or FlightRecorder(self.cfg.flight_capacity)
        self._c_rounds = self._c_dropped = self._c_transitions = None
        self._g_suspect = None
        if registry is not None:
            self._c_rounds = registry.counter(
                "grapevine_leakmon_rounds_total",
                "engine rounds whose transcripts the leak monitor audited")
            self._c_dropped = registry.counter(
                "grapevine_leakmon_rounds_dropped_total",
                "engine rounds dropped at the monitor hand-off queue "
                "(monitor slower than the round rate)")
            self._c_transitions = registry.counter(
                "grapevine_leakmon_suspect_transitions_total",
                "PASS→SUSPECT verdict transitions")
            self._g_suspect = registry.gauge(
                "grapevine_leakmon_suspect",
                "1 while the leak audit verdict is SUSPECT")
        self._q: queue.Queue = queue.Queue(maxsize=self.cfg.queue_depth)
        self._submitted = 0
        self._processed = 0
        self._seq = 0
        self._suspect = False
        self._last_verdict: dict | None = None
        #: replication cadence books (engine/replication.py): when a
        #: JournalShipper is attached, its byte-cadence stats join the
        #: verdict schema as a ``ship_cadence`` detector — shipping
        #: traffic must be a pure function of the round counter
        #: (constant frame sizes, constant framing), so any
        #: content-sized byte on the wire is a SUSPECT exactly like an
        #: access-pattern detector tripping
        self._shipper = None
        #: delayed-eviction flush cadence books (engine/batcher.py
        #: _flush_window_locked): the schedule-independence claim says
        #: the automatic flush fires strictly every ``flush_every``
        #: dispatched rounds — a pure function of the round counter.
        #: The engine reports each scheduled flush's observed interval
        #: via note_flush(); any interval that deviates from the
        #: declared cadence is content-modulated scheduling (the
        #: flush_on_buffer_contents mutant's signature) and trips the
        #: ``flush_cadence`` detector exactly like an access-pattern
        #: detector. None = immediate eviction, detector absent.
        self._flush_every = flush_every
        self._flush_samples = 0
        self._flush_illegal = 0
        self._worker = threading.Thread(
            target=self._run, daemon=True, name="grapevine-leakmon"
        )
        self._worker.start()

    @classmethod
    def for_engine(cls, engine, cfg: LeakMonitorConfig | None = None):
        """Build a monitor sized to an engine's ORAM geometry, publishing
        into the engine's own telemetry registry (one merged /metrics)."""
        ecfg = engine.ecfg
        recursive = ecfg.rec.posmap is not None
        delayed = getattr(engine, "_flush_step", None) is not None
        return cls(
            mb_leaves=ecfg.mb.leaves,
            rec_leaves=ecfg.rec.leaves,
            mb_choices=ecfg.mb_choices,
            cfg=cfg,
            registry=engine.metrics.registry,
            mb_pm_leaves=ecfg.mb.posmap.inner_leaves if recursive else None,
            rec_pm_leaves=ecfg.rec.posmap.inner_leaves if recursive else None,
            flush_every=engine.evict_every if delayed else None,
        )

    # -- round-path API (must stay O(1) and non-blocking) ---------------

    def submit_round(
        self, batch: dict, transcript, n_real: int, batch_size: int,
        phases: dict | None = None, queue_depth: int | None = None,
    ) -> bool:
        """Enqueue one round's transcript; False = dropped (queue full)."""
        try:
            self._q.put_nowait((batch, transcript, n_real, batch_size,
                                dict(phases) if phases else {},
                                queue_depth))
        except queue.Full:
            if self._c_dropped is not None:
                self._c_dropped.inc()
            return False
        self._submitted += 1
        return True

    def note_flush(self, interval_rounds: int, scheduled: bool = True) -> None:
        """Record one delayed-eviction flush's observed interval (rounds
        since the previous flush; engine/batcher.py calls this under the
        engine lock just before the cadence counter resets). Only
        ``scheduled`` flushes are audited — flush_now() and recovery
        completion are operator/restart actions outside the steady-state
        cadence claim. O(1), two int bumps."""
        if not scheduled or self._flush_every is None:
            return
        self._flush_samples += 1
        if int(interval_rounds) != int(self._flush_every):
            self._flush_illegal += 1

    # -- verdict views --------------------------------------------------

    def attach_shipper(self, shipper) -> None:
        """Fold a JournalShipper's cadence books into the verdict
        schema (see the ``_shipper`` field note). Pass None to detach."""
        self._shipper = shipper

    def verdict(self) -> dict:
        """Fresh verdict over the current windows (the /leakaudit body)."""
        v = self.monitor.verdict()
        v["rounds_observed"] = self._processed
        v["rounds_dropped"] = int(
            self._c_dropped.get()) if self._c_dropped else 0
        if self._shipper is not None:
            rep = self._shipper.stats()
            v["replication"] = rep
            v["detectors"].append({
                "name": "ship_cadence",
                "tree": "journal",
                "statistic": float(rep["illegal_frames"]),
                "threshold": 0.0,
                "samples": int(rep["frames_shipped"]),
                "min_samples": 1,
                "verdict": PASS if rep["cadence_ok"] else SUSPECT,
            })
            if not rep["cadence_ok"]:
                v["verdict"] = SUSPECT
        if self._flush_every is not None:
            illegal = self._flush_illegal
            v["detectors"].append({
                "name": "flush_cadence",
                "tree": "evict",
                "statistic": float(illegal),
                "threshold": 0.0,
                "samples": int(self._flush_samples),
                "min_samples": 1,
                "verdict": SUSPECT if illegal else PASS,
            })
            if illegal:
                v["verdict"] = SUSPECT
        return v

    def last_verdict(self) -> dict:
        """The worker's cached verdict — lock-free for /healthz, which
        must answer while a wedged round holds other locks."""
        return self._last_verdict or self.verdict()

    # -- worker ---------------------------------------------------------

    def _run(self):
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            try:
                self._process(*item)
            except Exception:
                log.exception("leak monitor failed on a round "
                              "(monitoring continues)")
            finally:
                self._processed += 1
                self._q.task_done()

    def _process(self, batch, transcript, n_real, batch_size, phases,
                 queue_depth=None):
        # lazy import: obs must stay importable without the engine
        # package (and this breaks the obs ↔ engine import cycle)
        from ..engine.round_step import transcript_key_groups

        tr = np.asarray(transcript)  # device→host copy, off the jit path
        # columns are [a_0..a_{D-1}, b, c_0..c_{D-1}] for the phase-major
        # engine (D = configured mb_choices) and [a, b, c] for the
        # op-major one (always one fetch per mailbox round); a recursive
        # position map appends the internal ORAM's columns in the same
        # layout, doubling the width (engine/round_step.py) — fall back
        # to the width-derived D when the configured one doesn't match
        d = self.mb_choices
        pm_tr = None
        if self._has_pm and tr.shape[1] == 2 * (2 * d + 1):
            pm_tr = tr[:, 2 * d + 1:]
            tr = tr[:, : 2 * d + 1]
        elif tr.shape[1] != 2 * d + 1:
            d = max(1, (tr.shape[1] - 1) // 2)
        (mb_keys, mb_stable), (rec_keys, rec_stable) = transcript_key_groups(
            batch, d
        )
        # transcript columns: [a_0..a_{D-1}, b, c_0..c_{D-1}]
        # (engine/round_step.py); mailbox rounds A and C are successive
        # observations of the mb stream — same keys, independent leaves
        self.monitor.observe("mb", mb_keys, tr[:, :d].ravel(), mb_stable)
        self.monitor.observe("rec", rec_keys, tr[:, d], rec_stable)
        self.monitor.observe("mb", mb_keys, tr[:, d + 1:].ravel(), mb_stable)
        if pm_tr is not None:
            # internal posmap accesses: grouped by the same host-visible
            # keys as their outer rounds (two ops sharing an outer key
            # share an internal block; distinct keys *may* also share a
            # block — an undercount of same-key pairs, never a false
            # SUSPECT — the transcript_key_groups stance). The internal
            # round's own dedup makes every entry an independent uniform
            # internal leaf, which these streams verify continuously.
            self.monitor.observe(
                "mb_pm", mb_keys, pm_tr[:, :d].ravel(), mb_stable
            )
            self.monitor.observe("rec_pm", rec_keys, pm_tr[:, d], rec_stable)
            self.monitor.observe(
                "mb_pm", mb_keys, pm_tr[:, d + 1:].ravel(), mb_stable
            )
        if self._c_rounds is not None:
            self._c_rounds.inc()
        self._seq += 1

        v = self.monitor.verdict()
        self._last_verdict = v
        suspect = v["verdict"] == SUSPECT
        if suspect and not self._suspect:
            if self._c_transitions is not None:
                self._c_transitions.inc()
            tripped = [
                f"{x['name']}/{x['tree']}={x['statistic']}"
                for x in v["detectors"] if x["verdict"] == SUSPECT
            ]
            log.warning(
                "leak audit verdict PASS->SUSPECT (%s) — see /leakaudit "
                "and the OPERATIONS.md runbook", ", ".join(tripped)
            )
            if self.cfg.dump_path:
                try:
                    self.recorder.dump_to(self.cfg.dump_path)
                    log.warning("flight recorder dumped to %s",
                                self.cfg.dump_path)
                except OSError:
                    log.exception("flight recorder dump failed")
        elif not suspect and self._suspect:
            log.warning("leak audit verdict SUSPECT->PASS (window drained)")
        self._suspect = suspect
        if self._g_suspect is not None:
            self._g_suspect.set(1.0 if suspect else 0.0)

        # phases arrive exact-paired on the round's own span ledger
        # (engine/batcher.py PendingRound) — assembly/verify included
        self.recorder.record({
            "seq": self._seq,
            "t_mono_s": round(time.monotonic(), 3),
            "batch_size": int(batch_size),
            "n_real": int(n_real),
            "fill": round(n_real / batch_size, 4) if batch_size else 0.0,
            "queue_depth": int(queue_depth) if queue_depth is not None else 0,
            "phase_s": {k: round(float(x), 6) for k, x in phases.items()},
            "stats": {t: self.monitor.stats(t)
                      for t in self.monitor.streams},
            "verdict": v["verdict"],
        })

    # -- lifecycle ------------------------------------------------------

    def flush(self, timeout: float = 30.0) -> bool:
        """Block until every submitted round has been processed (tests
        and orderly shutdown); False on timeout."""
        deadline = time.monotonic() + timeout
        while self._processed < self._submitted:
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.002)
        return True

    def close(self, timeout: float = 5.0) -> None:
        if not self._worker.is_alive():
            return
        self._q.put(None)
        self._worker.join(timeout=timeout)


# ----------------------------------------------------------------------
# cross-shard schedule uniformity (the fleet observatory's detector leg)
# ----------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class FleetUniformityConfig:
    """Thresholds and window sizing for the cross-shard detectors
    (defaults justified in OPERATIONS.md §20)."""

    #: sliding window length in aligned fleet ticks (one tick = one
    #: same-instant observation of every shard — a scrape cycle in
    #: production, a dispatch tick in the load drill)
    window_ticks: int = 128
    #: minimum aligned ticks before the correlation detector may trip
    min_ticks: int = 24
    #: minimum per-shard rounds in the window before the cadence and
    #: flush detectors may trip (insufficient evidence reports PASS —
    #: the PR-2 min-samples stance)
    min_rounds: int = 16
    #: |log cadence ratio| floor for the pairwise cadence detector: an
    #: honest uniformly-scheduled fleet keeps every pair's windowed
    #: round-count ratio near 1 (drift |log r| = O(sqrt(1/R))); 0.35
    #: tolerates a 1.4x transient imbalance before suspicion
    cadence_ratio_floor: float = 0.35
    #: Fisher-z threshold for the dispatch-vs-offered-load correlation
    #: detector (honest uniform scheduling dispatches unconditionally,
    #: so the correlation is sampling noise: |z| = O(1))
    corr_z_threshold: float = 6.0
    #: pairwise flush-per-round rate drift floor (honest shards all
    #: flush at the declared 1/evict_every cadence)
    flush_rate_floor: float = 0.1
    #: sampling-noise margin in standard deviations for the cadence and
    #: flush thresholds (the leakmon rate_z_margin analog)
    rate_z_margin: float = 8.0


class FleetUniformityMonitor:
    """Cross-shard schedule-uniformity detectors over PUBLIC series.

    The single-process monitors above judge one engine's transcript.
    A recipient-sharded fleet has a second obliviousness obligation the
    ROADMAP (item 1) names explicitly: per-shard round cadence and
    batch shape must stay recipient-independent — a scheduler that
    dispatches shard s's round only when s's own queue is hot encodes
    *which shard's recipients are busy* into the public round schedule,
    exactly the signal BOLT's fleet-level adversary reads. This monitor
    consumes only per-shard batch-level time series (round cadence,
    batch fill, flush cadence, queue depth at round/scrape grain — all
    already public on each member's /metrics) and flags
    recipient-dependent skew:

    1. **pairwise cadence-ratio drift** — windowed round-count ratios
       between shards must stay near 1 (uniform scheduling dispatches
       every shard on the same public cadence);
    2. **dispatch/fill correlation with offered shard load** — a
       shard's round activity must not correlate with its own queue
       depth beyond the declared partition (honest scheduling is
       unconditional; only a load-gated scheduler correlates);
    3. **flush-phase alignment** — delayed-eviction flush-per-round
       rates must match the declared cadence on every shard alike.

    Feeding: ``observe_tick(samples)`` with one aligned sample per
    shard. A tick with any shard missing (scrape failure) updates the
    cumulative baselines but contributes no evidence — a degraded
    fleet accumulates verdicts more slowly instead of falsely.

    Verdict semantics mirror :class:`TranscriptLeakMonitor`: each
    detector reports statistic, threshold, and sample count; below
    min-samples reports PASS; overall SUSPECT iff any detector trips.
    Exports are statistic/threshold/verdict/sample-count only, under
    the ``grapevine_fleet_*`` namespace with ``shard`` (declared
    integer indices) as the only label — audited by
    tools/check_telemetry_policy.py.
    """

    def __init__(
        self,
        n_shards: int,
        cfg: FleetUniformityConfig | None = None,
        registry: TelemetryRegistry | None = None,
    ):
        if n_shards < 2:
            raise ValueError("fleet uniformity needs at least 2 shards")
        self.n_shards = int(n_shards)
        self.cfg = cfg or FleetUniformityConfig()
        self._lock = threading.Lock()
        #: last cumulative (rounds, fill_sum, fill_count, flushes) per
        #: shard, None until first observed
        self._base: list = [None] * self.n_shards
        #: aligned tick window: each entry is (d_rounds, fill_mean,
        #: d_flushes, queue_depth) arrays over shards
        self._window: deque = deque(maxlen=self.cfg.window_ticks)
        self._g_stat = self._g_thr = self._g_suspect = None
        self._g_rounds = self._g_ticks = None
        if registry is not None:
            shards = tuple(str(i) for i in range(self.n_shards))
            # one unlabeled statistic/threshold pair per detector: the
            # grapevine_fleet_* namespace permits ONLY the shard label
            # (tools/check_telemetry_policy.py audit_fleet_registry),
            # so detector identity lives in the metric name
            self._g_stat = {}
            self._g_thr = {}
            for det, what in (
                ("cadence_ratio", "pairwise windowed round-count "
                 "|log ratio| (honest uniform scheduling ~ 0)"),
                ("fill_load_correlation", "max per-shard Fisher |z| of "
                 "corr(round activity, own queue depth) — honest "
                 "unconditional dispatch gives sampling noise"),
                ("flush_phase", "pairwise flush-per-round rate drift "
                 "(honest shards all flush at the declared cadence)"),
            ):
                self._g_stat[det] = registry.gauge(
                    f"grapevine_fleet_uniformity_{det}_statistic",
                    f"cross-shard uniformity detector statistic: {what}")
                self._g_thr[det] = registry.gauge(
                    f"grapevine_fleet_uniformity_{det}_threshold",
                    "effective (scale-aware) threshold for the "
                    f"{det} detector")
            self._g_suspect = registry.gauge(
                "grapevine_fleet_uniformity_suspect",
                "1 while any cross-shard uniformity detector trips")
            self._g_rounds = registry.gauge(
                "grapevine_fleet_uniformity_window_rounds",
                "per-shard rounds in the current uniformity window "
                "(cadence/flush detector sample size)",
                labels={"shard": shards})
            self._g_ticks = registry.gauge(
                "grapevine_fleet_uniformity_window_ticks",
                "aligned fleet ticks in the current uniformity window "
                "(correlation detector sample size)")

    # -- feeding --------------------------------------------------------

    def observe_tick(self, samples) -> None:
        """Feed one aligned fleet tick.

        ``samples``: sequence of length ``n_shards``; each element is a
        dict with cumulative ``rounds_total``, ``flushes_total``,
        optional cumulative ``fill_sum``/``fill_count``, and
        instantaneous ``queue_depth`` — or None for a shard whose
        scrape failed this tick."""
        if len(samples) != self.n_shards:
            raise ValueError(
                f"tick has {len(samples)} samples for {self.n_shards} shards"
            )
        with self._lock:
            complete = all(s is not None for s in samples)
            d_rounds = np.zeros(self.n_shards)
            fill_mean = np.zeros(self.n_shards)
            d_flush = np.zeros(self.n_shards)
            qdepth = np.zeros(self.n_shards)
            for i, s in enumerate(samples):
                if s is None:
                    continue
                cur = (
                    float(s["rounds_total"]),
                    float(s.get("fill_sum", 0.0)),
                    float(s.get("fill_count", 0.0)),
                    float(s.get("flushes_total", 0.0)),
                )
                base = self._base[i]
                self._base[i] = cur
                if base is None:
                    complete = False  # first sight: no delta yet
                    continue
                # counters only go up; a reset (member restart) would
                # produce a negative delta — clamp and treat the tick
                # as evidence-free for that shard
                dr = cur[0] - base[0]
                if dr < 0:
                    complete = False
                    continue
                d_rounds[i] = dr
                dfc = cur[2] - base[2]
                fill_mean[i] = (
                    (cur[1] - base[1]) / dfc if dfc > 0 else 0.0
                )
                d_flush[i] = max(0.0, cur[3] - base[3])
                qdepth[i] = float(s.get("queue_depth", 0.0))
            if complete:
                self._window.append((d_rounds, fill_mean, d_flush, qdepth))
            self._export_locked()

    def _export_locked(self) -> None:
        if self._g_rounds is None:
            return
        rounds = self._rounds_locked()
        for i in range(self.n_shards):
            self._g_rounds.set(float(rounds[i]), shard=str(i))
        self._g_ticks.set(float(len(self._window)))

    def _rounds_locked(self) -> np.ndarray:
        if not self._window:
            return np.zeros(self.n_shards)
        return np.sum([w[0] for w in self._window], axis=0)

    # -- judging --------------------------------------------------------

    def verdict(self) -> dict:
        """Machine-readable fleet uniformity verdict, in the
        TranscriptLeakMonitor detector-dict shape (folded into the
        fleet /leakaudit body by obs/fleet.py)."""
        cfg = self.cfg
        with self._lock:
            ticks = len(self._window)
            if ticks:
                d_rounds = np.stack([w[0] for w in self._window])
                d_flush = np.stack([w[2] for w in self._window])
                qdepth = np.stack([w[3] for w in self._window])
            else:
                d_rounds = d_flush = qdepth = np.zeros((0, self.n_shards))
        R = d_rounds.sum(axis=0)  # per-shard rounds in window
        F = d_flush.sum(axis=0)
        detectors = []

        # 1. pairwise cadence-ratio drift (max over pairs)
        worst = (0, 1, 0.0, cfg.cadence_ratio_floor)
        for a in range(self.n_shards):
            for b in range(a + 1, self.n_shards):
                stat = abs(math.log((R[a] + 0.5) / (R[b] + 0.5)))
                thr = max(
                    cfg.cadence_ratio_floor,
                    cfg.rate_z_margin * math.sqrt(
                        1.0 / (R[a] + 0.5) + 1.0 / (R[b] + 0.5)),
                )
                # rank pairs by threshold exceedance, not raw drift — a
                # low-evidence pair with a big ratio must not outrank a
                # well-evidenced drifting pair
                if stat - thr > worst[2] - worst[3]:
                    worst = (a, b, stat, thr)
        a, b, stat, thr = worst
        samples = int(min(R[a], R[b])) if ticks else 0
        detectors.append({
            "name": "cadence_ratio",
            "pair": [a, b],
            "statistic": round(stat, 4),
            "threshold": round(thr, 4),
            "samples": samples,
            "min_samples": cfg.min_rounds,
            "verdict": SUSPECT if (
                samples >= cfg.min_rounds and stat > thr
            ) else PASS,
        })

        # 2. per-shard dispatch/load correlation (max Fisher |z|)
        worst_s, worst_z = 0, 0.0
        for s in range(self.n_shards):
            z = self._fisher_z(d_rounds[:, s], qdepth[:, s])
            if z > worst_z:
                worst_s, worst_z = s, z
        detectors.append({
            "name": "fill_load_correlation",
            "shard": worst_s,
            "statistic": round(worst_z, 3),
            "threshold": cfg.corr_z_threshold,
            "samples": ticks,
            "min_samples": cfg.min_ticks,
            "verdict": SUSPECT if (
                ticks >= cfg.min_ticks and worst_z > cfg.corr_z_threshold
            ) else PASS,
        })

        # 3. pairwise flush-per-round rate drift
        f = (F + 0.5) / (R + 1.0)
        fa, fb = (int(np.argmax(f)), int(np.argmin(f)))
        stat = float(f[fa] - f[fb])
        fbar = min(max(float(np.mean(f)), 1e-6), 1.0 - 1e-6)
        samples = int(min(R[fa], R[fb])) if ticks else 0
        thr = max(
            cfg.flush_rate_floor,
            cfg.rate_z_margin * math.sqrt(
                fbar * (1.0 - fbar)
                * (1.0 / (R[fa] + 1.0) + 1.0 / (R[fb] + 1.0))),
        )
        detectors.append({
            "name": "flush_phase",
            "pair": [fa, fb],
            "statistic": round(stat, 4),
            "threshold": round(thr, 4),
            "samples": samples,
            "min_samples": cfg.min_rounds,
            "verdict": SUSPECT if (
                samples >= cfg.min_rounds and stat > thr
            ) else PASS,
        })

        overall = SUSPECT if any(
            d["verdict"] == SUSPECT for d in detectors) else PASS
        if self._g_stat is not None:
            for d in detectors:
                self._g_stat[d["name"]].set(float(d["statistic"]))
                self._g_thr[d["name"]].set(float(d["threshold"]))
            self._g_suspect.set(1.0 if overall == SUSPECT else 0.0)
        return {
            "verdict": overall,
            "n_shards": self.n_shards,
            "window_ticks": ticks,
            "detectors": detectors,
        }

    @staticmethod
    def _fisher_z(x: np.ndarray, y: np.ndarray) -> float:
        """|Fisher z| of the Pearson correlation; 0 when either series
        is constant (an unconditionally-dispatching shard has zero
        round-count variance — the honest case, by construction)."""
        n = len(x)
        if n < 4 or float(np.std(x)) == 0.0 or float(np.std(y)) == 0.0:
            return 0.0
        r = float(np.corrcoef(x, y)[0, 1])
        if not math.isfinite(r):
            return 0.0
        r = max(-0.999999, min(0.999999, r))
        return abs(math.atanh(r)) * math.sqrt(n - 3)
