"""Prometheus text exposition (format version 0.0.4) of a registry.

Stdlib-only on purpose: the container policy bakes no prometheus_client,
and the text format is small enough that owning it is cheaper than
gating a dependency. Histograms render cumulative ``_bucket`` series
with ``le`` edges fixed at registration, plus ``_sum``/``_count``.
"""

from __future__ import annotations

import math

from .registry import TelemetryRegistry


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) else repr(f)


def _escape_help(text: str) -> str:
    """# HELP escaping per the 0.0.4 text format: backslash and line
    feed (a raw newline would terminate the comment mid-text and turn
    the remainder into an unparseable sample line)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label_value(value: str) -> str:
    """Label-value escaping per the 0.0.4 text format: backslash,
    double-quote, and line feed. Label values are registration-declared
    (obs/registry.py), so this is belt-and-braces — but a declared value
    containing a quote must still scrape clean, not corrupt the series
    name for every metric after it."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _labelstr(keys, vals, extra=()) -> str:
    pairs = [f'{k}="{_escape_label_value(v)}"' for k, v in zip(keys, vals)]
    pairs += [f'{k}="{_escape_label_value(v)}"' for k, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def render_prometheus(registry: TelemetryRegistry) -> str:
    lines: list[str] = []
    for m in registry.collect():
        lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        for vals, child in m.series():
            if m.kind == "histogram":
                # one locked state() read: cumulative buckets, +Inf, sum
                # and count must come from the same instant or a racing
                # observe() renders a torn histogram
                counts, total, count = child.state()
                acc = 0
                for edge, c in zip(m.buckets, counts):
                    acc += c
                    ls = _labelstr(
                        m.label_keys, vals, [("le", _fmt_value(edge))]
                    )
                    lines.append(f"{m.name}_bucket{ls} {acc}")
                ls = _labelstr(m.label_keys, vals, [("le", "+Inf")])
                lines.append(f"{m.name}_bucket{ls} {count}")
                ls = _labelstr(m.label_keys, vals)
                lines.append(f"{m.name}_sum{ls} {_fmt_value(total)}")
                lines.append(f"{m.name}_count{ls} {count}")
            else:
                ls = _labelstr(m.label_keys, vals)
                lines.append(f"{m.name}{ls} {_fmt_value(child.value)}")
    return "\n".join(lines) + "\n"
